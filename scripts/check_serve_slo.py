"""Serving SLO gate: judge a load-generator run's ``serve_bench`` row
against explicit SLO thresholds — the serving-tier counterpart of
scripts/check_bench_regress.py.

The load generator (serve/loadgen.py, ``python -m xflow_tpu.serve
loadgen``) is OPEN-loop: offered traffic arrives on its own clock, so
a tier past capacity shows up as shed fraction and tail latency, not
as a quietly lower throughput number.  This script turns that row into
a verdict:

* ``errors`` must not exceed ``--max-error-frac`` of offered traffic
  (default 0: a failed request is never an SLO trade);
* ``shed_frac`` must stay under ``--max-shed-frac`` (shedding is the
  tier *defending* the deadline budget — some is policy, a storm is a
  capacity failure);
* client-observed ``e2e_p99`` must stay under ``--max-p99-ms`` when
  given (0 disables: absolute latency on a degraded CI container
  measures the box, not the code — pass a bar only where the numbers
  are trustworthy, exactly the check_bench_regress discipline);
* ``achieved_qps / offered_qps_actual`` must reach
  ``--min-achieved-frac`` when given;
* ``outstanding`` (admitted requests the tier never resolved before
  the loadgen drain timeout) must not exceed ``--max-outstanding``
  (default 0: a black-holed request is neither an error nor a shed
  and must not pass silently);
* ``--compare-transports`` switches to two-leg mode: the newest
  ``transport == "binary"`` row is judged against every gate above AND
  must beat the newest ``transport == "http"`` row's achieved QPS by
  ``--min-transport-ratio`` with a p99 no worse — a binary transport
  that is not faster than HTTP on the same fleet is a regression, not
  a feature;
* ``--qos-ordering`` asserts the admission-control shed ORDER on the
  judged row: ``bidding`` must shed nothing, and any shedding at all
  must include ``best_effort`` — overload is supposed to land on the
  class that bid for it.

The metrics file must pass obs/schema.py validation first — a gate
that reads torn rows gates nothing.  The NEWEST ``serve_bench`` row is
judged (a file may accumulate runs).

Run from the repo root:

    python scripts/check_serve_slo.py serve_metrics.jsonl \
        --max-shed-frac 0.05 --max-p99-ms 250

Wired into tier-1 via tests/test_serve.py::test_check_serve_slo_gate
(a healthy loadgen run passes; an injected latency regression exits
non-zero).
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("metrics", help="JSONL file with serve_bench row(s)")
    p.add_argument(
        "--max-shed-frac", type=float, default=0.05,
        help="max admission-control shed fraction (default 0.05)",
    )
    p.add_argument(
        "--max-error-frac", type=float, default=0.0,
        help="max failed-request fraction of offered traffic "
        "(default 0.0 — errors are never an SLO trade)",
    )
    p.add_argument(
        "--max-p99-ms", type=float, default=0.0,
        help="max client-observed e2e p99 in ms (0 = disabled; "
        "absolute latency on degraded CI boxes measures the box)",
    )
    p.add_argument(
        "--min-achieved-frac", type=float, default=0.0,
        help="min achieved_qps / offered_qps_actual (0 = disabled)",
    )
    p.add_argument(
        "--max-outstanding", type=int, default=0,
        help="max requests still unresolved when the loadgen drain "
        "timed out (default 0: a black-holed request is neither an "
        "error nor a shed and must not pass silently)",
    )
    p.add_argument(
        "--compare-transports", action="store_true",
        help="two-leg mode: judge the newest transport=binary row "
        "(all standard gates) and require it to beat the newest "
        "transport=http row on achieved QPS with a p99 no worse",
    )
    p.add_argument(
        "--min-transport-ratio", type=float, default=1.0,
        help="with --compare-transports: min binary/http achieved-QPS "
        "ratio (default 1.0 — binary must at least match HTTP)",
    )
    p.add_argument(
        "--qos-ordering", action="store_true",
        help="assert shed order on the judged row: bidding sheds "
        "nothing and any shedding includes best_effort (row must "
        "carry qos_shed — run loadgen with --qos-mix)",
    )
    args = p.parse_args(argv)

    from xflow_tpu.obs.schema import load_jsonl, validate_rows

    try:
        rows = load_jsonl(args.metrics)
    except OSError as e:
        print(f"FAIL: cannot read {args.metrics}: {e}", file=sys.stderr)
        return 2
    errors = validate_rows(rows)
    if errors:
        for e in errors:
            print(f"FAIL: schema violation: {e}", file=sys.stderr)
        return 2
    bench = [r for r in rows if r.get("kind") == "serve_bench"]
    if not bench:
        print(
            f"FAIL: {args.metrics} has no serve_bench row — run "
            "`python -m xflow_tpu.serve loadgen ... --metrics-out` "
            "first",
            file=sys.stderr,
        )
        return 2
    http_row = None
    if args.compare_transports:
        by = {"binary": None, "http": None}
        for r in bench:  # newest of each transport wins
            t = r.get("transport")
            if t in by:
                by[t] = r
        missing = [t for t, r in by.items() if r is None]
        if missing:
            print(
                "FAIL: --compare-transports needs one serve_bench row "
                f"per transport; missing {missing} in {args.metrics} "
                "(run loadgen once with --binary-addr and once with "
                "--url against the same server)",
                file=sys.stderr,
            )
            return 2
        row, http_row = by["binary"], by["http"]
    else:
        row = bench[-1]
    if "offered_qps_actual" not in row:
        print(
            "FAIL: newest serve_bench row carries no offered_qps_actual "
            "— that is a closed-loop `bench` row, not a loadgen run; "
            "every gate below would compare defaults against defaults "
            "and pass vacuously.  Run `python -m xflow_tpu.serve "
            "loadgen ... --metrics-out` and gate that file.",
            file=sys.stderr,
        )
        return 2

    offered = float(row.get("offered_qps_actual", 0.0)) or float(
        row.get("offered_qps", 0.0)
    )
    submitted = max(
        1.0, offered * float(row.get("seconds", 0.0))
    )
    p99_ms = 1e3 * float(row.get("e2e_p99", 0.0))
    shed_frac = float(row.get("shed_frac", 0.0))
    error_frac = float(row.get("errors", 0)) / submitted
    outstanding = int(row.get("outstanding", 0))
    achieved_frac = (
        float(row.get("achieved_qps", 0.0)) / offered if offered else 0.0
    )

    checks: list[tuple[str, bool, str]] = [
        (
            "error_frac",
            error_frac <= args.max_error_frac,
            f"{error_frac:.4f} (max {args.max_error_frac}, "
            f"{row.get('errors', 0)} error(s))",
        ),
        (
            "shed_frac",
            shed_frac <= args.max_shed_frac,
            f"{shed_frac:.4f} (max {args.max_shed_frac}, by cause "
            f"{row.get('shed_by_cause', {})})",
        ),
        (
            "outstanding",
            outstanding <= args.max_outstanding,
            f"{outstanding} unresolved at drain timeout "
            f"(max {args.max_outstanding})",
        ),
    ]
    if args.max_p99_ms > 0:
        checks.append((
            "e2e_p99",
            p99_ms <= args.max_p99_ms,
            f"{p99_ms:.1f}ms (max {args.max_p99_ms}ms)",
        ))
    if args.min_achieved_frac > 0:
        checks.append((
            "achieved/offered",
            achieved_frac >= args.min_achieved_frac,
            f"{achieved_frac:.3f} (min {args.min_achieved_frac}, "
            f"{row.get('achieved_qps')} of {offered} qps)",
        ))
    if http_row is not None:
        bin_qps = float(row.get("achieved_qps", 0.0))
        http_qps = float(http_row.get("achieved_qps", 0.0))
        ratio = bin_qps / http_qps if http_qps else float("inf")
        checks.append((
            "transport_qps",
            ratio >= args.min_transport_ratio,
            f"binary {bin_qps} vs http {http_qps} qps achieved "
            f"({ratio:.2f}x, min {args.min_transport_ratio}x)",
        ))
        bin_p99 = 1e3 * float(row.get("e2e_p99", 0.0))
        http_p99 = 1e3 * float(http_row.get("e2e_p99", 0.0))
        checks.append((
            "transport_p99",
            bin_p99 <= http_p99,
            f"binary {bin_p99:.1f}ms vs http {http_p99:.1f}ms "
            "(binary must be no worse)",
        ))
    if args.qos_ordering:
        qshed = row.get("qos_shed")
        if not isinstance(qshed, dict):
            print(
                "FAIL: --qos-ordering needs a qos_shed map on the "
                "judged serve_bench row — run loadgen with --qos-mix",
                file=sys.stderr,
            )
            return 2
        bidding = int(qshed.get("bidding", 0))
        best_effort = int(qshed.get("best_effort", 0))
        total = sum(int(v) for v in qshed.values())
        checks.append((
            "qos_bidding_shed",
            bidding == 0,
            f"{bidding} bidding request(s) shed (must be 0: the top "
            "class is the last to go)",
        ))
        checks.append((
            "qos_shed_order",
            total == 0 or best_effort > 0,
            f"{total} total shed, {best_effort} from best_effort "
            "(any shedding must include the lowest class)",
        ))

    failed = 0
    for name, ok, detail in checks:
        print(f"{'ok  ' if ok else 'FAIL'} {name}: {detail}")
        failed += 0 if ok else 1
    if failed:
        print(
            f"FAIL: {failed} SLO gate(s) breached by the newest "
            f"serve_bench row in {args.metrics}",
            file=sys.stderr,
        )
        return 1
    print(
        f"OK: serve SLO gates passed ({row.get('requests')} requests "
        f"at {row.get('achieved_qps')} qps achieved / "
        f"{offered} offered, p99 {p99_ms:.1f}ms, shed "
        f"{100 * shed_frac:.1f}%)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
