"""Op-level probe: is cold-key consolidation worth its argsort?

Measures, per (D, dup_frac) on real-ish zipf key sets:
  a) plain scatter-add of [M, D] occurrence grads (the dense-mode path)
  b) argsort + segment-sum + scatter of [M, D] consolidated grads
     (Config.cold_consolidate) — same M slots, duplicates collapsed
     into sentinel-key slots that XLA scatter mode="drop" discards
  c) the argsort alone (the price), and segment_sum alone

Prints one JSON line per config, flush=True (tunnel can die mid-run —
partial results must survive).  Run on the real chip:

    python scripts/probe_consolidate.py
"""

import json
import sys
import time

sys.path.insert(0, ".")

import numpy as np


def sync(x):
    import jax

    jax.block_until_ready(x)
    # platform gotcha: block_until_ready can return early on the
    # tunneled backend; device_get of a slice forces completion
    jax.device_get(x.ravel()[:1] if hasattr(x, "ravel") else x)


def timeit(fn, *args, iters=8, warmup=2):
    for _ in range(warmup):
        sync(fn(*args))
    t0 = time.time()
    for _ in range(iters):
        out = fn(*args)
    sync(out)
    return (time.time() - t0) / iters


def main():
    if "--cpu" in sys.argv:  # smoke-test mode off the tunnel
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp

    from xflow_tpu.ops.sparse import consolidate_apply, consolidate_plan

    t_log2 = 24
    t = 1 << t_log2
    rng = np.random.default_rng(0)
    for m_log2 in (20, 21):
        m = 1 << m_log2
        # zipf(1.2) keys over a 3.9M vocab reduced mod 2^24 — the bench
        # dataset's distribution (gen_synth), which sets the real
        # duplicate rate
        raw = rng.zipf(1.2, size=2 * m)
        keys_np = (raw[raw < 3_900_000][:m] % t).astype(np.int32)
        dup = 1.0 - len(np.unique(keys_np)) / m
        keys = jnp.asarray(keys_np)
        for d in (1, 4, 8, 10):
            grads = jnp.asarray(
                rng.standard_normal((m, d)).astype(np.float32)
            )
            gbuf = jnp.zeros((t, d), jnp.float32)

            plain = jax.jit(
                lambda gb, k, g: gb.at[k].add(g, mode="drop")
            )

            def cons_fn(gb, k, g):
                order, seg, ukeys = consolidate_plan(k, t)
                return gb.at[ukeys].add(
                    consolidate_apply(g, order, seg), mode="drop"
                )

            cons = jax.jit(cons_fn)
            sort_only = jax.jit(lambda k: jnp.argsort(k))

            # does a dropped (sentinel) slice cost like a live one?  If
            # drops are ~free, consolidation saves the full duplicate
            # fraction of scatter time; if not, only the segment-sum's
            # bandwidth matters.
            all_sentinel = jnp.full_like(keys, t)
            row = {
                "m_log2": m_log2,
                "d": d,
                "dup_frac": round(dup, 3),
                "plain_ms": round(timeit(plain, gbuf, keys, grads) * 1e3, 3),
                "consolidated_ms": round(
                    timeit(cons, gbuf, keys, grads) * 1e3, 3
                ),
                "argsort_ms": round(timeit(sort_only, keys) * 1e3, 3),
                "all_dropped_ms": round(
                    timeit(plain, gbuf, all_sentinel, grads) * 1e3, 3
                ),
                "backend": jax.devices()[0].platform,
            }
            row["plain_ns_per_slice"] = round(
                row["plain_ms"] * 1e6 / m, 2
            )
            print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
