"""Sharding & memory gate: the XF010–XF014 static pass plus the
transient-HBM budget report, gating the shape/dtype/sharding/memory
invariants before the pod-scale sharding work (ROADMAP item 2)
multiplies the surface.

Run from the repo root:

    python scripts/check_memory.py
    python scripts/check_memory.py --write-budget   # regenerate candidates

Three parts, all must pass:

1. **Static** — ``xflow_tpu.analysis`` with the five memory rules
   (XF010 full-table transients, XF011 dtype discipline, XF012
   sharding coverage, XF013 donation safety, XF014 transient budget —
   docs/ANALYSIS.md) over the whole package against the committed
   baseline, same contract as scripts/check_analysis.py.
2. **Budget presence** — ``memory-budget.json`` must exist at the repo
   root: XF014 is deliberately silent when no budget file is in scope
   (fixture scans), so the gate — not the rule — refuses a deleted
   budget.
3. **Report** — the per-jit transient estimate at the north-star
   geometry (T=2^28, flagship D per model family) is printed for every
   jit entry, with its budget and the largest contributing site — the
   number ROADMAP item 2's sharding work budgets against.

``--write-budget`` rewrites the ``budgets`` section from the current
estimates (+10% headroom, rounded), carrying comment fields — review
the diff before committing; raising a budget is a design decision
(docs/ANALYSIS.md XF014 policy).

Wired into tier-1 via tests/test_memory_analysis.py, next to
check_analysis.py / check_concurrency.py.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

MEMORY_RULES = ["XF010", "XF011", "XF012", "XF013", "XF014"]


def check_static(index, baseline_path: str) -> int:
    from xflow_tpu.analysis import (
        load_baseline,
        render_text,
        run_analysis,
        split_baselined,
    )

    # the shared index carries the cached shapeflow MemoryContext, so
    # the static pass reuses report_estimates' interpretation run
    findings, pragma_suppressed = run_analysis(
        index, select=MEMORY_RULES
    )
    entries = [
        e for e in load_baseline(baseline_path) if e["rule"] in MEMORY_RULES
    ]
    new, grandfathered, stale = split_baselined(findings, entries)
    print(render_text(new, grandfathered, pragma_suppressed, stale))
    if new:
        return 1
    if stale:
        print(
            "FAIL: stale baseline entries (prune analysis-baseline.json)",
            file=sys.stderr,
        )
        return 1
    return 0


def _gib(n: int) -> str:
    return f"{n / 2**30:.3f} GiB" if n >= 1 << 20 else f"{n} B"


def report_estimates(index, budget_path: str,
                     write: bool = False) -> int:
    from xflow_tpu.analysis import estimate_transients, load_budget

    doc = load_budget(budget_path)
    estimates = estimate_transients(index, doc)
    if not estimates:
        print("FAIL: no jit entries discovered — shapeflow regression?")
        return 1
    rc = 0
    budgets = doc["budgets"]
    print("per-jit transient estimates at the north-star geometry "
          f"(T=2^{doc['geometry']['T'].bit_length() - 1}):")
    for key, fams in sorted(estimates.items()):
        entry = budgets.get(key, {})
        for family, est in sorted(fams.items()):
            allowed = entry.get(family)
            ok = allowed is not None and est["bytes"] <= int(allowed)
            top = est["sites"][0] if est["sites"] else None
            where = (
                f"  largest: {top['shape']} {top['kind']} "
                f"{top['path']}:{top['line']}"
                if top
                else ""
            )
            status = "ok" if ok else "FAIL"
            budget_s = _gib(int(allowed)) if allowed is not None else "NONE"
            print(
                f"  {status:4s} {key} [{family}] "
                f"{_gib(est['bytes'])} / budget {budget_s}{where}"
            )
            if not ok:
                rc = 1
            if est["unsized"]:
                print(
                    f"       note: {est['unsized']} transient(s) the "
                    "flow could not size (not counted)"
                )
    if write:
        for key, fams in sorted(estimates.items()):
            old = budgets.get(key, {})
            # rebuild families from the live estimates (stale family
            # values would silently re-arm if the name ever returned);
            # carry non-numeric fields (comments) across
            entry = {
                k: v for k, v in old.items()
                if not isinstance(v, (int, float))
            }
            for family, est in fams.items():
                entry[family] = int(est["bytes"] * 1.1)
            budgets[key] = entry
        stale = [k for k in budgets if k not in estimates]
        for k in stale:
            del budgets[k]
        with open(budget_path, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        print(f"wrote budget candidates (+10%) to {budget_path} — "
              "review the diff before committing")
        return 0
    return rc


def main(argv: list[str] | None = None) -> int:
    from xflow_tpu.analysis.core import PackageIndex

    write = "--write-budget" in (argv if argv is not None else sys.argv[1:])
    package = os.path.join(REPO, "xflow_tpu")
    baseline = os.path.join(REPO, "analysis-baseline.json")
    budget = os.path.join(REPO, "memory-budget.json")
    if not os.path.exists(budget):
        # XF014 is silent without a budget in scope — the gate is what
        # makes deleting the committed file a failure, not a pass
        print(f"FAIL: {budget} missing — the XF014 transient budget "
              "must stay committed", file=sys.stderr)
        return 1
    index = PackageIndex([package])  # one parse + interpretation, shared
    rc = report_estimates(index, budget, write=write)
    if write:
        return rc
    rc = check_static(index, baseline) or rc
    return rc


if __name__ == "__main__":
    sys.exit(main())
