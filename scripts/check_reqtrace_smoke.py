"""Request-trace smoke lint: train a toy ranker, serve it on a
2-replica fleet with request-scoped tracing armed, drive zipf traffic,
and validate everything the tracing spine promises
(docs/OBSERVABILITY.md "Tracing a request"):

* **0 errors, 0 recompiles** — tracing adds span stamps, never
  compiles or failures: the loadgen run answers everything and the
  fleet's compile count is unchanged from warm;
* **complete span trees** — every sampled request's ``reqtrace`` row
  has the full phase vocabulary, its phases sum to its e2e exactly
  (chain-fill), and its batch reference resolves to a batch span that
  fans the trace id in;
* **client/server agreement** — the ``serve_bench`` row's
  ``slowest_exemplars`` carry server-side phase breakdowns whose sum
  is within 10% (plus a 2 ms scheduler-noise floor) of the
  client-observed e2e;
* **tail sampling contract** — at ``sample=0.0`` a window still keeps
  the slowest-k exemplars, and error/shed spans are always kept;
* **front-door propagation** — a trace id sent on the XFS2 packed
  wire and as an ``X-XFlow-Trace`` header comes back on the response;
* **doctor attribution** — ``obs doctor`` stays clean on the healthy
  stream and raises ``reqtrace_tail`` naming the **device** phase on a
  run with an injected device-side slowdown.  The slowdown is injected
  by wrapping ``predict_prepared`` with a sleeping delegator rather
  than the ``serve.replica_score`` failpoint: the chaos fabric's
  failpoints RAISE (error path — covered by the sampling contract
  above), and tail attribution needs slow-but-successful requests.
* **schema** — both metrics streams (``reqtrace`` rows included) pass
  obs/schema.py strictly.

Run from the repo root:

    JAX_PLATFORMS=cpu python scripts/check_reqtrace_smoke.py

Wired into tier-1 via tests/test_reqtrace.py::test_check_reqtrace_smoke_script,
like check_serve_smoke.py / check_cascade_smoke.py.
"""

from __future__ import annotations

import itertools
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

BUCKETS = (8, 64)
SLOW_SLEEP_S = 0.08  # injected device-side stall, every 8th batch
PHASE_SUM_TOL = 1e-4  # rounding slack: phases round to 1e-6 s each


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import http.client

    import numpy as np

    from tests.gen_data import generate_dataset
    from xflow_tpu.config import Config
    from xflow_tpu.obs.doctor import diagnose
    from xflow_tpu.obs.reqtrace import PHASES, ReqTraceSink
    from xflow_tpu.obs.schema import load_jsonl, validate_rows
    from xflow_tpu.serve.artifact import export_artifact
    from xflow_tpu.serve.fleet import ReplicaFleet
    from xflow_tpu.serve.loadgen import run_loadgen, zipf_rows
    from xflow_tpu.serve.server import (
        ServeTier,
        decode_packed_response,
        encode_packed_request,
    )
    from xflow_tpu.trainer import Trainer
    from xflow_tpu.utils.logging import MetricsLogger

    errors: list[str] = []
    with tempfile.TemporaryDirectory() as root:
        ds = generate_dataset(
            os.path.join(root, "data"),
            num_train_shards=2,
            lines_per_shard=150,
            num_fields=10,
            vocab_per_field=8,
            seed=11,
            scale=3.0,
        )
        cfg = Config(
            model="dcn",
            train_path=ds.train_prefix,
            test_path=ds.test_prefix,
            epochs=1,
            batch_size=64,
            table_size_log2=14,
            max_nnz=24,
            max_fields=10,
            num_devices=1,
        )
        tr = Trainer(cfg)
        tr.train()
        art = export_artifact(tr, os.path.join(root, "artifact"))

        # generous admission budgets: CPU toy device calls are tens of
        # ms, so production deadlines would shed healthy traffic — the
        # smoke asserts full service; shed-path sampling is exercised
        # at the sink level below
        admission = dict(deadline_budget_ms=5000.0, depth_budget=1024)

        def request_rows(rows):
            return [
                r for r in rows
                if r.get("kind") == "reqtrace" and r.get("span") == "request"
            ]

        def check_trees(rows, where):
            """Every request span: full phase vocabulary, phases sum
            to e2e, batch reference resolves and fans the id in."""
            batches = {
                r["batch"]: r for r in rows
                if r.get("kind") == "reqtrace" and r.get("span") == "batch"
            }
            reqs = request_rows(rows)
            if not reqs:
                errors.append(f"{where}: no reqtrace request rows")
                return
            for r in reqs:
                if tuple(sorted(r["phases"])) != tuple(sorted(PHASES)):
                    errors.append(
                        f"{where}: trace {r.get('trace_id')} phase keys "
                        f"{sorted(r['phases'])} != {sorted(PHASES)}"
                    )
                    continue
                gap = abs(sum(r["phases"].values()) - r["e2e"])
                if gap > PHASE_SUM_TOL:
                    errors.append(
                        f"{where}: trace {r.get('trace_id')} phases sum "
                        f"off e2e by {gap:.6f}s"
                    )
                if r.get("status") == "ok":
                    b = batches.get(r.get("batch"))
                    if b is None:
                        errors.append(
                            f"{where}: trace {r.get('trace_id')} batch "
                            f"{r.get('batch')!r} has no batch span"
                        )
                    elif r["trace_id"] not in b["trace_ids"]:
                        errors.append(
                            f"{where}: batch {r.get('batch')!r} does not "
                            f"fan in trace {r['trace_id']}"
                        )
            for b in batches.values():
                if len({b["digest"]}) != 1 or not b["digest"]:
                    errors.append(f"{where}: batch {b['batch']} digest odd")

        # ---- healthy leg: loadgen, sample=1.0 (every tree emitted) ----
        healthy = os.path.join(root, "healthy.jsonl")
        logger = MetricsLogger(healthy, run_header={
            "run_id": "reqtrace-smoke",
            "config_digest": "smoke",
            "rank": 0,
            "num_hosts": 1,
        })
        fleet = ReplicaFleet.load(
            art, replicas=2, buckets=BUCKETS, metrics_logger=logger,
            **admission,
        )
        fleet.reqtrace = ReqTraceSink(metrics_logger=logger, sample=1.0)
        fleet.log_load(art)
        compiles_warm = fleet.engines[0].compile_count
        summary = run_loadgen(
            fleet,
            offered_qps=60.0,
            duration_s=2.0,
            concurrency=4,
            nnz=8,
            zipf_a=1.3,
            seed=5,
            metrics_logger=logger,
        )
        if summary["errors"]:
            errors.append(f"healthy loadgen errors: {summary['errors']}")
        if summary["requests"] < 20:
            errors.append(
                f"healthy loadgen answered only {summary['requests']} "
                "requests — too few to judge anything"
            )
        if fleet.engines[0].compile_count != compiles_warm:
            errors.append(
                "tracing recompiled the fleet: "
                f"{compiles_warm} -> {fleet.engines[0].compile_count}"
            )
        exemplars = summary.get("slowest_exemplars") or []
        if not exemplars:
            errors.append("serve_bench summary has no slowest_exemplars")
        with_phases = [e for e in exemplars if "phases_ms" in e]
        if not with_phases:
            errors.append(
                "no slowest exemplar resolved a server-side phase "
                f"breakdown: {exemplars}"
            )
        for e in with_phases:
            client = e["e2e_ms"]
            server = sum(e["phases_ms"].values())
            if abs(client - server) > max(0.10 * client, 2.0):
                errors.append(
                    f"exemplar {e['trace_id']}: server phase sum "
                    f"{server:.3f}ms vs client e2e {client:.3f}ms "
                    "(>10% + 2ms apart)"
                )

        # ---- front door: trace id rides wire + header and echoes ------
        tier = ServeTier(fleet, port=0).start()
        ctx = fleet.reqtrace.mint()
        row = zipf_rows(
            np.random.default_rng(9), 1, table_size=cfg.table_size,
            nnz=8, max_fields=cfg.max_fields,
        )[0]
        conn = http.client.HTTPConnection("127.0.0.1", tier.port,
                                          timeout=30)
        conn.request(
            "POST", "/v1/score_packed",
            body=encode_packed_request([row], trace=ctx),
            headers={"Content-Type": "application/octet-stream"},
        )
        resp = conn.getresponse()
        payload = resp.read()
        echoed = resp.getheader("X-XFlow-Trace") or ""
        if resp.status != 200:
            errors.append(f"packed trace request HTTP {resp.status}")
        else:
            decode_packed_response(payload)
        if not echoed.startswith(f"{ctx.trace_id:016x}-"):
            errors.append(
                f"packed wire trace not echoed: {echoed!r} vs "
                f"{ctx.trace_id:016x}"
            )
        ctx2 = fleet.reqtrace.mint()
        conn.request(
            "POST", "/v1/score",
            body=json.dumps({
                "keys": [int(k) for k in row[0]],
                "slots": [int(s) for s in row[1]],
            }).encode(),
            headers={
                "Content-Type": "application/json",
                "X-XFlow-Trace":
                    f"{ctx2.trace_id:016x}-0000000000000000-1",
            },
        )
        resp = conn.getresponse()
        resp.read()
        echoed = resp.getheader("X-XFlow-Trace") or ""
        if not echoed.startswith(f"{ctx2.trace_id:016x}-"):
            errors.append(
                f"header trace not echoed: {echoed!r} vs "
                f"{ctx2.trace_id:016x}"
            )
        conn.close()
        fleet.emit_stats()  # flush the front-door spans into the stream

        # ---- sampling contract: sample=0 keeps slowest-k + errors -----
        sink0 = ReqTraceSink(sample=0.0, slow_k=3)
        fleet.reqtrace = sink0
        rows30 = zipf_rows(
            np.random.default_rng(13), 30, table_size=cfg.table_size,
            nnz=8, max_fields=cfg.max_fields,
        )
        for r in rows30:
            fleet.submit(*r).result(timeout=60)
        err_span = sink0.start(None, "score")
        sink0.complete(err_span, "error", detail="injected")
        shed_span = sink0.start(None, "score")
        sink0.complete(shed_span, "shed", detail="deadline_budget")
        kept = sink0.flush()
        kept_reqs = [r for r in kept if r["span"] == "request"]
        by_keep: dict[str, int] = {}
        for r in kept_reqs:
            by_keep[r["keep"]] = by_keep.get(r["keep"], 0) + 1
        if by_keep.get("slow", 0) != 3:
            errors.append(
                f"sample=0 window kept {by_keep.get('slow', 0)} slow "
                f"exemplars, want 3 (keeps: {by_keep})"
            )
        if by_keep.get("error", 0) != 1 or by_keep.get("shed", 0) != 1:
            errors.append(
                f"sample=0 window dropped error/shed spans: {by_keep}"
            )
        if by_keep.get("head", 0):
            errors.append(f"sample=0 window head-kept spans: {by_keep}")

        # ---- healthy stream: schema + trees + doctor stays clean ------
        tier.close()  # drains and closes the fleet
        logger.close()
        hrows = load_jsonl(healthy)
        errors.extend(f"healthy schema: {e}" for e in validate_rows(hrows))
        check_trees(hrows, "healthy")
        tail = [d for d in diagnose(hrows) if d.code == "reqtrace_tail"]
        if tail:
            errors.append(
                f"doctor tail-attribution fired on the healthy run: "
                f"{tail[0].message[:160]}"
            )

        # ---- slow leg: injected device stall -> doctor names device ---
        slow = os.path.join(root, "slow.jsonl")
        slogger = MetricsLogger(slow, run_header={
            "run_id": "reqtrace-smoke-slow",
            "config_digest": "smoke",
            "rank": 0,
            "num_hosts": 1,
        })
        fleet2 = ReplicaFleet.load(
            art, replicas=2, buckets=BUCKETS, metrics_logger=slogger,
            **admission,
        )
        fleet2.reqtrace = ReqTraceSink(metrics_logger=slogger, sample=1.0)
        calls = itertools.count()
        for eng in fleet2.engines:
            orig = eng.predict_prepared

            def slow_call(batch, _orig=orig):
                if next(calls) % 8 == 0:
                    time.sleep(SLOW_SLEEP_S)
                return _orig(batch)

            eng.predict_prepared = slow_call
        rows40 = zipf_rows(
            np.random.default_rng(17), 40, table_size=cfg.table_size,
            nnz=8, max_fields=cfg.max_fields,
        )
        for r in rows40:  # sequential: one batch per request
            fleet2.submit(*r).result(timeout=60)
        fleet2.emit_stats()
        fleet2.close()
        slogger.close()
        srows = load_jsonl(slow)
        errors.extend(f"slow schema: {e}" for e in validate_rows(srows))
        check_trees(srows, "slow")
        stail = [d for d in diagnose(srows) if d.code == "reqtrace_tail"]
        if not stail:
            errors.append(
                "doctor missed the injected device stall: no "
                "reqtrace_tail finding on the slow stream"
            )
        elif "device phase" not in stail[0].message:
            errors.append(
                "doctor misattributed the injected device stall: "
                f"{stail[0].message[:200]}"
            )

    if errors:
        print("check_reqtrace_smoke: FAIL", file=sys.stderr)
        for e in errors:
            print(f"  - {e}", file=sys.stderr)
        return 1
    print(
        "check_reqtrace_smoke: OK (0 errors, 0 recompiles with tracing "
        "on, complete span trees with phase sums matching e2e, "
        "client/server exemplar agreement, slowest-k + error/shed kept "
        "at sample=0, wire+header trace echo, doctor clean on healthy "
        "and device-attributed on the injected stall)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
