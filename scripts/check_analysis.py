"""Static-analysis gate: run the xflow_tpu.analysis rule pass (XF001
recompile hazards, XF002 hidden host syncs, XF003 lock discipline,
XF004 schema drift, XF005 C-ABI parity, and the XF006–XF009
concurrency rules — docs/ANALYSIS.md) over the whole package against
the committed baseline.  scripts/check_concurrency.py re-runs the
concurrency subset plus the runtime lock-order sanitizer cross-check.

Run from the repo root:

    python scripts/check_analysis.py

Wired into tier-1 next to check_metrics_schema.py/check_serve_smoke.py
(tests/test_analysis.py::test_check_analysis_script), so a careless
edit that reintroduces a per-shape recompile, an unbooked host sync, an
unlocked mutation of loader/batcher state, an undeclared JSONL kind, or
a one-sided ABI change fails CI instead of surfacing in production.

Unlike the two runtime lints this one never executes the pipeline — it
is pure AST over the source tree, so it stays fast and works in images
without a functional accelerator backend.
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main() -> int:
    from xflow_tpu.analysis import (
        load_baseline,
        render_text,
        run_analysis,
        split_baselined,
    )

    package = os.path.join(REPO, "xflow_tpu")
    baseline = os.path.join(REPO, "analysis-baseline.json")
    findings, pragma_suppressed = run_analysis([package])
    new, grandfathered, stale = split_baselined(
        findings, load_baseline(baseline)
    )
    print(render_text(new, grandfathered, pragma_suppressed, stale))
    if new:
        return 1
    if stale:
        # a stale entry means a grandfathered finding got fixed — the
        # baseline must shrink with it, or it will silently grandfather
        # a future regression with the same message
        print(
            "FAIL: stale baseline entries (prune analysis-baseline.json)",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
