"""Convergence baseline (SURVEY §6: "a first task of the new repo"):
train any model family (lr / fm / mvm / ffm / wide_deep) to
convergence with the reference's exact FTRL hyperparameters
(/root/reference/src/optimizer/ftrl.h:17-20 — α=5e-2, β=1, λ1=5e-5,
λ2=10, v_dim=10) on a Criteo-shaped synthetic dataset with planted
logistic signal (scripts/gen_synth.py; real Criteo is not available in
this environment — documented proxy), and record per-epoch test
logloss/AUC curves against the generator's Bayes-optimal floor.

The recorded docs/CONVERGENCE.md rows used: `--models lr --epochs 6`,
`--models fm mvm --epochs 6`, `--models wide_deep --epochs 6`, and
`--models ffm --epochs 2` (FFM's CPU step is ~10× the others').

Dataset: 10M train / 1M test, 39 fields, zipf(1.2) ids, vocab 3.9M —
generate with:
    python scripts/gen_synth.py /tmp/xflow_conv/c10m 10000000 \
        --num-test 1000000 --train-shards 4
    python -m xflow_tpu.io.binary --train /tmp/xflow_conv/c10m.train \
        --out /tmp/xflow_conv/bin.train --block-mib 8   (and .test)

Run: python scripts/convergence_baseline.py [--models lr fm mvm]
Writes /tmp/xflow_conv/convergence.json and prints per-epoch JSON lines
— paste the summary into BASELINE.md.
"""

import argparse
import json
import sys
import time

sys.path.insert(0, ".")

from xflow_tpu.config import Config
from xflow_tpu.trainer import Trainer

TRAIN = "/tmp/xflow_conv/bin.train"
TEST = "/tmp/xflow_conv/bin.test"
BAYES_LOGLOSS = 0.5106  # gen_synth.bayes_optimal_logloss(seed=7)
BAYES_AUC = 0.7883


def run_model(
    model: str, epochs: int, batch_size: int, table_size_log2: int = 24
) -> dict:
    cfg = Config(
        model=model,
        train_path=TRAIN,
        test_path=TEST,
        epochs=epochs,
        batch_size=batch_size,
        table_size_log2=table_size_log2,
        max_nnz=40,
        max_fields=39,
        num_devices=1,
        # Gradients are mean-over-batch (reference lr_worker.cc:116-118
        # parity), so the batch size IS an optimizer hyperparameter:
        # per-key updates scale as 1/B.  The reference's effective batch
        # is a per-thread slice of a 2 MiB block — a few hundred rows —
        # so convergence runs use a comparable small batch (measured:
        # B=8192 reaches AUC 0.53 where B=512 reaches 0.65 on the same
        # 500k examples).  Sparse update mode keeps small-batch steps
        # O(B*nnz) instead of O(table).
        update_mode="sparse",
        # optimizer defaults ARE the reference's ftrl.h:17-20 values
    )
    t = Trainer(cfg)
    curve = []
    for epoch in range(epochs):
        t.epoch = epoch
        stats = t.train_epoch()
        ev = t.evaluate()
        row = {
            "model": model,
            "epoch": epoch,
            "train_logloss": round(stats["train_logloss"], 6),
            "test_logloss": round(ev["logloss"], 6),
            "test_auc": round(ev["auc"], 6),
            "examples_per_sec": round(stats["examples_per_sec"], 0),
        }
        curve.append(row)
        print(json.dumps(row), flush=True)
    return {
        "model": model,
        "epochs": epochs,
        "batch_size": batch_size,
        "table_size_log2": table_size_log2,
        "final_test_logloss": curve[-1]["test_logloss"],
        "final_test_auc": curve[-1]["test_auc"],
        "curve": curve,
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--models", nargs="*", default=["lr", "fm", "mvm"])
    p.add_argument("--epochs", type=int, default=6)
    p.add_argument("--batch-size", type=int, default=512)
    p.add_argument(
        "--table-size-log2", type=int, default=24,
        help="2^24 carries ~12%% occurrence collisions on this dataset, "
        "2^28 ~1%% (docs/PERF.md) — vary to quantify the collision cost "
        "the reference's exact-key store doesn't pay",
    )
    p.add_argument("--out", default="/tmp/xflow_conv/convergence.json")
    p.add_argument(
        "--platform",
        help="force the JAX backend (e.g. cpu — convergence results are "
        "device-independent; pin before any backend query or the "
        "accelerator plugin hijacks selection)",
    )
    args = p.parse_args()
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    results = {
        "dataset": "synthetic Criteo-shaped, 10M train / 1M test, "
        "39 fields, zipf(1.2), planted logistic signal (gen_synth "
        "seed=7)",
        "ftrl": "alpha=5e-2 beta=1 lambda1=5e-5 lambda2=10 (ftrl.h:17-20)",
        "bayes_optimal": {"logloss": BAYES_LOGLOSS, "auc": BAYES_AUC},
        "models": [],
    }
    for m in args.models:
        t0 = time.time()
        r = run_model(
            m, args.epochs, args.batch_size, args.table_size_log2
        )
        r["wall_secs"] = round(time.time() - t0, 1)
        results["models"].append(r)
        with open(args.out, "w") as f:
            json.dump(results, f, indent=2)
    print(json.dumps({k: v for k, v in results.items() if k != "models"}))
    for r in results["models"]:
        print(
            json.dumps(
                {
                    "model": r["model"],
                    "final_test_logloss": r["final_test_logloss"],
                    "final_test_auc": r["final_test_auc"],
                    "wall_secs": r["wall_secs"],
                }
            )
        )


if __name__ == "__main__":
    main()
