"""Live-observability gate (tier-1): the telemetry plane of ISSUE 19
must tell the truth while the tier is running, not just post-hoc.

Four legs over one toy artifact:

* **Healthy tier under load** — a 2-replica ServeTier takes open-loop
  loadgen traffic while a scraper thread hammers ``GET /metrics``;
  every scrape must parse and counters must be monotonic (the snapshot
  IS the lock-safety — a torn read would show a counter going
  backwards).  After traffic quiesces, one scrape must agree exactly
  with the same-instant ``fleet.stats()`` registry snapshot, ``GET
  /v1/stats`` must carry the watchdog's health state and the alert
  summary, and the SLO evaluator must stay silent: a healthy leg that
  pages is as broken as a sick leg that doesn't.
* **Chaos leg** — ``serve.replica_score`` faults error a scoring
  window; the ``serve_error_frac`` rule (and ONLY that rule) must fire
  on the bad window and resolve on the next clean one, with both
  ``alert`` rows landing schema-valid in the metrics stream.
* **Exporter/sampler lifecycle** — the standalone ``MetricsExporter``
  must serve over a real socket exactly what ``render_exposition``
  says, and the threaded ``ResourceSampler`` must emit schema-valid
  ``resource`` rows; both must leave ZERO threads behind after
  ``close()``.
* **Live-vs-post-hoc parity** — ``obs live --once`` on a finished (or
  torn, still-growing) file must reach the same diagnosis codes and
  exit verdict ``obs doctor`` reaches post-hoc.

Run from the repo root:

    JAX_PLATFORMS=cpu python scripts/check_live_obs.py

Wired into tier-1 via tests/test_live_obs.py::test_check_live_obs_script.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# thread-name prefixes the fabrics under test own — none may survive
_THREAD_PREFIXES = (
    "xflow-serve", "xflow-replica-revive", "xflow-loadgen",
    "xflow-obs-watchdog", "resource-sampler", "metrics-exporter",
)


def _leaked_threads() -> list[str]:
    return sorted(
        t.name for t in threading.enumerate()
        if t.is_alive() and t.name.startswith(_THREAD_PREFIXES)
    )


def _get(url: str, timeout: float = 10.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read()


def _live_codes(path: str) -> tuple[set, int, list[str]]:
    """(diagnosis codes, exit code, raw lines) from `obs live --once`."""
    from xflow_tpu.obs.live import run_live

    lines: list[str] = []
    rc = run_live([path], once=True, out=lines.append)
    codes = set()
    for line in lines:
        if line.startswith("[") and "] " in line:
            head = line.split("] ", 1)[1]
            codes.add(head.split(":", 1)[0])
    return codes, rc, lines


def _doctor_codes(path: str) -> tuple[set, int]:
    """(diagnosis codes, exit code) the post-hoc doctor reaches."""
    from xflow_tpu.obs.doctor import diagnose, merge_rows

    findings = diagnose(merge_rows([path]))
    rc = 1 if any(d.severity in ("crit", "warn") for d in findings) else 0
    return {d.code for d in findings}, rc


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from tests.gen_data import generate_dataset
    from xflow_tpu import chaos
    from xflow_tpu.config import Config
    from xflow_tpu.obs.export import (
        MetricsExporter,
        ResourceSampler,
        parse_exposition,
        render_exposition,
    )
    from xflow_tpu.obs.flight import FlightRecorder
    from xflow_tpu.obs.live import AlertEvaluator
    from xflow_tpu.obs.registry import MetricsRegistry
    from xflow_tpu.obs.schema import load_jsonl, validate_rows
    from xflow_tpu.obs.watchdog import Watchdog
    from xflow_tpu.serve.artifact import export_artifact
    from xflow_tpu.serve.fleet import ReplicaFleet
    from xflow_tpu.serve.loadgen import run_loadgen
    from xflow_tpu.serve.server import ServeTier
    from xflow_tpu.trainer import Trainer
    from xflow_tpu.utils.logging import MetricsLogger

    errors: list[str] = []
    with tempfile.TemporaryDirectory() as root:
        ds = generate_dataset(
            os.path.join(root, "data"),
            num_train_shards=2,
            lines_per_shard=200,
            num_fields=10,
            vocab_per_field=8,
            seed=19,
            scale=3.0,
        )
        cfg = Config(
            train_path=ds.train_prefix,
            test_path=ds.test_prefix,
            model="lr",
            epochs=1,
            batch_size=64,
            table_size_log2=14,
            max_nnz=24,
            num_devices=1,
        )
        trainer = Trainer(cfg)
        trainer.train()
        artifact = export_artifact(trainer, os.path.join(root, "artifact"))
        trainer.close()

        # -- leg A: healthy tier under load, scraped live ------------------
        metrics_a = os.path.join(root, "serve_healthy.jsonl")
        logger = MetricsLogger(metrics_a, run_header={
            "run_id": "live-obs-healthy",
            "config_digest": "gate",
            "rank": 0,
            "num_hosts": 1,
            "model": "lr",
        })
        flight = FlightRecorder()
        fleet = ReplicaFleet.load(
            artifact, replicas=2, buckets=(1, 8), warm=False,
            metrics_logger=logger, flight=flight,
        )
        tier = ServeTier(fleet, port=0, flight=flight)
        wd = Watchdog(flight, serve_s=30.0, metrics_logger=logger)
        wd.set_pending("serve", fleet.pending)
        wd.set_pending("http", lambda: tier.running)
        alerts = AlertEvaluator(metrics_logger=logger)
        sampler = ResourceSampler(
            metrics_logger=logger, registry=fleet.registry
        )
        tier.watchdog = wd
        tier.alerts = alerts
        tier.start()
        wd.start()

        scrape_errors: list[str] = []
        scrapes = [0]
        stop_scraping = threading.Event()

        def _scrape_loop() -> None:
            last: dict[str, float] = {}
            while not stop_scraping.is_set():
                try:
                    text = _get(f"{tier.address}/metrics").decode()
                    parsed = parse_exposition(text)
                except Exception as e:  # noqa: BLE001 — report, don't die
                    scrape_errors.append(f"{type(e).__name__}: {e}")
                    return
                scrapes[0] += 1
                for name, v in parsed["counter"].items():
                    if v < last.get(name, 0.0):
                        scrape_errors.append(
                            f"counter {name} went backwards: "
                            f"{last[name]} -> {v} (torn read)"
                        )
                        return
                    last[name] = v
                time.sleep(0.01)

        scraper = threading.Thread(
            target=_scrape_loop, name="live-obs-scraper"
        )
        scraper.start()
        summary = run_loadgen(
            fleet,
            offered_qps=80.0,
            duration_s=1.5,
            concurrency=4,
            nnz=8,
            zipf_a=1.3,
            seed=0,
            metrics_logger=logger,
        )
        sampler.sample()
        stop_scraping.set()
        scraper.join(timeout=10.0)
        errors.extend(f"scrape: {e}" for e in scrape_errors)
        if scrapes[0] < 3:
            errors.append(
                f"only {scrapes[0]} successful scrape(s) during load — "
                "the concurrent-scrape leg never really ran"
            )

        # scrape-vs-snapshot parity: traffic has quiesced (loadgen
        # drained), both reads are non-destructive → exact agreement
        scraped = parse_exposition(_get(f"{tier.address}/metrics").decode())
        stats = fleet.stats()["stats"]
        pairs = [
            ("requests", scraped["counter"].get("xflow_serve_requests", 0.0)),
            ("batches", scraped["counter"].get("xflow_serve_batches", 0.0)),
            ("shed_total",
             scraped["counter"].get("xflow_serve_shed_total", 0.0)),
        ]
        for field, got in pairs:
            if int(got) != int(stats[field]):
                errors.append(
                    f"scrape/snapshot parity: {field} scraped {got} != "
                    f"stats {stats[field]}"
                )
        q = scraped["summary"].get("xflow_serve_queue_seconds", {})
        for label, field in (("0.5", "queue_p50"), ("0.99", "queue_p99")):
            if round(q.get(label, 0.0), 6) != stats[field]:
                errors.append(
                    f"scrape/snapshot parity: queue {label} scraped "
                    f"{q.get(label)} != stats {field} {stats[field]}"
                )

        # /v1/stats carries the watchdog state + alert summary
        doc = json.loads(_get(f"{tier.address}/v1/stats"))
        if "watchdog" not in doc or doc["watchdog"].get("healthy") is not True:
            errors.append(
                f"/v1/stats watchdog state missing or unhealthy on a "
                f"healthy tier: {doc.get('watchdog')}"
            )
        if "alerts" not in doc or doc["alerts"].get("fired_total") != 0:
            errors.append(
                f"/v1/stats alert summary missing or non-silent on a "
                f"healthy leg: {doc.get('alerts')}"
            )

        # the healthy leg must be alert-silent through the evaluator too
        out = fleet.emit_stats()
        fired = alerts.observe_rows([
            dict(out["stats"], kind="serve_stats"),
            dict(out["shed"], kind="serve_shed"),
        ])
        if fired:
            errors.append(
                f"healthy leg fired alert(s): "
                f"{[(a['rule'], a['state']) for a in fired]}"
            )
        wd.stop()
        tier.close()
        logger.close()

        rows_a = load_jsonl(metrics_a)
        errors.extend(f"healthy leg: {e}" for e in validate_rows(rows_a))
        if not any(r.get("kind") == "resource" for r in rows_a):
            errors.append("healthy leg emitted no resource row")
        if any(r.get("kind") == "alert" for r in rows_a):
            errors.append("healthy leg logged alert row(s)")

        # -- leg B: chaos fires the matching alert, then resolves ----------
        metrics_b = os.path.join(root, "serve_chaos.jsonl")
        logger_b = MetricsLogger(metrics_b, run_header={
            "run_id": "live-obs-chaos",
            "config_digest": "gate",
            "rank": 0,
            "num_hosts": 1,
            "model": "lr",
        })
        reg = chaos.arm("seed=5;serve.replica_score:p=1,times=2")
        chaos.attach_logger(logger_b)
        # evictions off (high streak bar): this leg is about the alert
        # plane, not the self-healing plane check_chaos.py already pins
        fleet_b = ReplicaFleet.load(
            artifact, replicas=2, buckets=(1, 8), warm=False,
            metrics_logger=logger_b, evict_after_errors=100,
        )
        eval_b = AlertEvaluator(metrics_logger=logger_b)
        rng = np.random.default_rng(0)
        probes = [
            rng.integers(0, cfg.table_size, size=8) for _ in range(6)
        ]
        faulted = 0
        for keys in probes:
            try:
                fleet_b.score(keys)
            except Exception:  # noqa: BLE001 — the injected fault
                faulted += 1
        if faulted < 1:
            errors.append("chaos leg: serve.replica_score never surfaced")
        t0 = 1_000_000.0
        out_bad = fleet_b.emit_stats()
        trans_bad = eval_b.observe_rows([
            dict(out_bad["stats"], kind="serve_stats"),
            dict(out_bad["shed"], kind="serve_shed"),
        ], now=t0)
        if [(a["rule"], a["state"]) for a in trans_bad] != [
            ("serve_error_frac", "firing")
        ]:
            errors.append(
                f"chaos window expected exactly serve_error_frac to "
                f"fire, got {[(a['rule'], a['state']) for a in trans_bad]} "
                f"(window {out_bad['shed']})"
            )
        chaos.disarm()
        # clean window, 2 minutes later: the bad sample ages out of the
        # short window, the rule resolves
        for keys in probes:
            fleet_b.score(keys)
        out_ok = fleet_b.emit_stats()
        trans_ok = eval_b.observe_rows([
            dict(out_ok["stats"], kind="serve_stats"),
            dict(out_ok["shed"], kind="serve_shed"),
        ], now=t0 + 120.0)
        if [(a["rule"], a["state"]) for a in trans_ok] != [
            ("serve_error_frac", "resolved")
        ]:
            errors.append(
                f"clean window expected serve_error_frac to resolve, "
                f"got {[(a['rule'], a['state']) for a in trans_ok]}"
            )
        if eval_b.summary()["firing"]:
            errors.append(
                f"chaos leg left rules firing: {eval_b.summary()['firing']}"
            )
        fires = reg.fired().get("serve.replica_score", 0)
        if fires < 1:
            errors.append("chaos registry recorded no fires")
        fleet_b.close()
        chaos.detach_logger(logger_b)
        chaos.disarm()
        logger_b.close()

        rows_b = load_jsonl(metrics_b)
        errors.extend(f"chaos leg: {e}" for e in validate_rows(rows_b))
        alert_states = [
            (r["rule"], r["state"]) for r in rows_b
            if r.get("kind") == "alert"
        ]
        if alert_states != [
            ("serve_error_frac", "firing"),
            ("serve_error_frac", "resolved"),
        ]:
            errors.append(
                f"chaos leg alert rows: {alert_states} (want exactly "
                "firing then resolved for serve_error_frac)"
            )

        # -- leg C: standalone exporter + threaded sampler lifecycle -------
        reg_c = MetricsRegistry()
        reg_c.counter_add("train.steps", 123)
        reg_c.gauge_set("loader.depth", 4)
        for v in (0.01, 0.02, 0.04):
            reg_c.observe("step.seconds", v)
        exporter = MetricsExporter(reg_c, port=0).start()
        wire = _get(f"{exporter.address}/metrics").decode()
        if wire != render_exposition(reg_c.snapshot(reset=False)):
            errors.append(
                "exporter served something other than the registry's "
                "own exposition"
            )
        if json.loads(_get(f"{exporter.address}/healthz")).get(
            "status"
        ) != "exporting":
            errors.append("exporter /healthz is not exporting")
        metrics_c = os.path.join(root, "sampler.jsonl")
        logger_c = MetricsLogger(metrics_c, run_header={
            "run_id": "live-obs-sampler",
            "config_digest": "gate",
            "rank": 0,
            "num_hosts": 1,
            "model": "lr",
        })
        sampler_c = ResourceSampler(
            metrics_logger=logger_c, registry=reg_c, interval_s=0.05
        ).start()
        time.sleep(0.2)
        sampler_c.close()
        exporter.close()
        logger_c.close()
        rows_c = load_jsonl(metrics_c)
        errors.extend(f"sampler leg: {e}" for e in validate_rows(rows_c))
        n_resource = sum(1 for r in rows_c if r.get("kind") == "resource")
        if n_resource < 2:
            errors.append(
                f"threaded sampler emitted {n_resource} resource row(s), "
                "want >= 2 (start + close at minimum)"
            )
        if "obs.resource.rss_bytes" not in reg_c.snapshot().gauges:
            errors.append("sampler never mirrored gauges into the registry")

        # -- leg D: obs live --once parity with post-hoc doctor ------------
        live_codes, live_rc, _ = _live_codes(metrics_a)
        doc_codes, doc_rc = _doctor_codes(metrics_a)
        if live_codes != doc_codes or live_rc != doc_rc:
            errors.append(
                f"healthy-file parity: live {sorted(live_codes)} rc "
                f"{live_rc} != doctor {sorted(doc_codes)} rc {doc_rc}"
            )
        # a sick, still-growing file: a watchdog trip plus a torn tail
        sick = os.path.join(root, "sick.jsonl")
        from xflow_tpu.obs.schema import health_row

        with open(sick, "w") as f:
            f.write(json.dumps({
                "t": 0.0, "kind": "run_start", "run_id": "sick",
                "time_unix": 100.0, "hostname": "h", "pid": 1,
                "config_digest": "gate", "rank": 0, "num_hosts": 1,
                "model": "lr",
            }) + "\n")
            f.write(json.dumps(dict(health_row(
                cause="input_stall", channel="train",
                silence_seconds=45.0, threshold_seconds=30.0,
                detail="input_stall",
            ), t=5.0, kind="health")) + "\n")
            f.write('{"t": 9.0, "kind": "train_ep')  # torn, mid-append
        live_codes, live_rc, live_lines = _live_codes(sick)
        doc_codes, doc_rc = _doctor_codes(sick)
        if live_codes != doc_codes or live_rc != doc_rc:
            errors.append(
                f"sick-file parity: live {sorted(live_codes)} rc "
                f"{live_rc} != doctor {sorted(doc_codes)} rc {doc_rc} "
                f"(live said: {live_lines})"
            )
        if live_rc != 1:
            errors.append(
                f"sick file (watchdog trip) exited {live_rc}, want 1"
            )

        leaked = _leaked_threads()
        if leaked:
            errors.append(f"leaked thread(s) survived the legs: {leaked}")

    for e in errors:
        print(f"FAIL: {e}", file=sys.stderr)
    if errors:
        return 1
    print(
        f"OK: {scrapes[0]} concurrent scrapes clean under "
        f"{summary['requests']} loadgen requests; scrape==snapshot; "
        f"healthy leg alert-silent; chaos leg fired+resolved "
        f"serve_error_frac ({fires} injected fault(s)); exporter wire "
        f"parity; {n_resource} threaded resource rows; live==doctor on "
        "finished and torn files; no leaked threads"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
