"""Per-model training-step throughput (docs/PERF.md model-zoo table).

Runs the fused train step for every model family at bench-scale shapes
and prints one JSON line per model:
    {"model": ..., "examples_per_sec": N, "batch_size": B, ...}

Usage:  python scripts/bench_models.py [--cpu] [--batch-log2 N]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, ".")  # repo root

from bench import (  # noqa: E402
    build,
    make_batches,
    prepare_real_data,
    probe_accelerator,
    real_batches,
)


def model_cfgs(base_b: int, accel: bool):
    """(name, Config) per family, enumerated from the MODEL REGISTRY
    (models/__init__.py) — every registered family MUST have a bench
    geometry below, so a new family is throughput-tracked (and gated
    by check_bench_regress.py) from the day it registers, or this
    script fails loudly instead of silently skipping it.

    FM/MVM: v_dim=10 (ftrl.h:16).  FFM: per-field latent D=4.
    max_fields=39 everywhere — the bench data is Criteo-shaped with
    fgids 0..38 (gen_synth.FIELDS); a smaller cap would silently mask
    fields out of the field-aware models.  Sizes shrink on the CPU
    fallback to keep runtime bounded.

    Hot geometries are the measured per-model optima (docs/PERF.md
    round-4 sweeps).  The wide-row models (FM/MVM, D=10) profit from a
    LARGER head than LR: their cold scatter costs ~106 ns/slice (any
    D>1 hits XLA's slow multi-lane scatter path, scripts/probe_fm2.py)
    vs ~15 ns for LR's scalars, so hiding more mass behind the MXU hot
    path is worth the extra one-hot traffic.

    FFM's table rows are max_fields*v_dim = 156 floats wide — at
    T=2^24 the (param, n, z) triple would be ~31 GB; its natural
    single-chip scale is T=2^21 (3.9 GB).  No hot table: h2*D = 9984
    lanes would force tiny scan chunks through ops/hot.py.

    two_tower/dcn (the cascade families, docs/SERVING.md): the same
    embedding-tower geometry as wide_deep (E=8 over 39 fields) so
    their rows read against its trajectory; two_tower splits the 39
    fields 20 user / 19 item."""
    from xflow_tpu.config import Config
    from xflow_tpu.models import model_names

    t = 24 if accel else 20
    b = base_b if accel else min(base_b, 16384)
    common = dict(
        optimizer="ftrl", table_size_log2=t, batch_size=b, num_devices=1,
        max_fields=39,
    )
    hot = dict(max_nnz=12, hot_size_log2=14, hot_nnz=32)
    geometries = {
        # flagship geometry (docs/PERF.md round-4 sweep)
        "lr": [
            ("lr", Config(model="lr", max_nnz=16, hot_size_log2=12,
                          hot_nnz=32, **common)),
            ("lr_nohot", Config(model="lr", max_nnz=40, **common)),
        ],
        "fm": [
            ("fm", Config(model="fm", v_dim=10, **hot, **common)),
            ("fm_nohot", Config(model="fm", max_nnz=40, v_dim=10,
                                **common)),
        ],
        "mvm": [
            ("mvm", Config(model="mvm", v_dim=10, **hot, **common)),
            ("mvm_nohot", Config(model="mvm", max_nnz=40, v_dim=10,
                                 **common)),
        ],
        # microbatch=4: FFM's [B/s, K, F*D] pair tensors are the live
        # memory; gradient accumulation runs full-size batches at 1/4
        # the intermediates (and measures FASTER than B=32768 whole)
        "ffm": [
            ("ffm", Config(model="ffm", max_nnz=40, ffm_v_dim=4,
                           microbatch=4,
                           **{**common,
                              "table_size_log2": 21 if accel else 18})),
        ],
        "wide_deep": [
            ("wide_deep", Config(model="wide_deep", emb_dim=8,
                                 hidden_dim=64, **hot, **common)),
            ("wide_deep_nohot", Config(model="wide_deep", max_nnz=40,
                                       emb_dim=8, hidden_dim=64,
                                       **common)),
        ],
        "two_tower": [
            ("two_tower", Config(model="two_tower", max_nnz=40, emb_dim=8,
                                 hidden_dim=64, tower_dim=16,
                                 tower_split_field=20, **common)),
        ],
        "dcn": [
            ("dcn", Config(model="dcn", max_nnz=40, emb_dim=8,
                           hidden_dim=64, cross_layers=2, **common)),
        ],
    }
    missing = [n for n in model_names() if n not in geometries]
    if missing:
        raise SystemExit(
            f"bench_models: registered famil{'ies' if len(missing) > 1 else 'y'} "
            f"{missing} have no bench geometry — add one above so "
            "check_bench_regress.py tracks them from day one"
        )
    stale = [n for n in geometries if n not in model_names()]
    if stale:
        # the reverse direction: a geometry whose family was renamed
        # or removed must fail as loudly as a missing one, not rot as
        # silently-unbenched dead code
        raise SystemExit(
            f"bench_models: geometry entr{'ies' if len(stale) > 1 else 'y'} "
            f"{stale} match no registered family — rename or delete"
        )
    return [row for name in model_names() for row in geometries[name]]


def run_one(name: str, args) -> None:
    """Bench a single model in THIS process (child mode)."""
    backend = None if args.cpu else probe_accelerator()
    import jax

    if backend is None:
        jax.config.update("jax_platforms", "cpu")
        devices = jax.devices("cpu")
    else:
        devices = [d for d in jax.devices() if d.platform != "cpu"]
    accel = backend is not None
    iters = args.iters if accel else max(2, args.iters // 3)

    cfg = dict(model_cfgs(1 << args.batch_log2, accel))[name]
    # geometry overrides for hot-head scaling sweeps (VERDICT r4 #4:
    # find each D>1 model's mass-vs-h2*D-traffic optimum)
    over = {}
    if args.hot_log2 is not None:
        over["hot_size_log2"] = args.hot_log2
    if args.hot_nnz is not None:
        over["hot_nnz"] = args.hot_nnz
    if args.cold_nnz is not None:
        over["max_nnz"] = args.cold_nnz
    if args.hot_dtype is not None:
        over["hot_dtype"] = args.hot_dtype
    if args.microbatch is not None:
        over["microbatch"] = args.microbatch
    if args.cold_consolidate:
        over["cold_consolidate"] = True
    if over:
        cfg = cfg.replace(**over)
    csr = remap = None
    if not args.synthetic:
        try:
            _, csr, remap, _ = prepare_real_data(
                cfg, 2_000_000 if accel else 200_000
            )
        except Exception as e:
            print(
                json.dumps({"real_data_error": f"{type(e).__name__}: {e}"}),
                flush=True,
            )
    try:
        from bench import run

        step, state = build(devices, cfg)
        source = "synthetic"
        batches = None
        batch_err = None
        if csr is not None:
            try:
                batches, _ = real_batches(
                    cfg, csr, remap if cfg.hot_size else None, 2
                )
                source = "zipf-cache"
            except Exception as e:  # e.g. batch too large for cache
                batch_err = f"{type(e).__name__}: {e}"
        if batches is None:
            batches, _ = make_batches(cfg, 2)
        t0 = time.time()
        _, eps = run(step, state, batches, iters=iters, warmup=2)
        row = {
            "model": name,
            "examples_per_sec": round(eps, 1),
            "batch_size": cfg.batch_size,
            "table_size_log2": cfg.table_size_log2,
            "hot": f"2^{cfg.hot_size_log2}x{cfg.hot_nnz}+cold{cfg.max_nnz}"
            if cfg.hot_size else "off",
            "cold_consolidate": cfg.cold_consolidate,
            "hot_dtype": cfg.hot_dtype,
            "backend": backend or "cpu",
            "batch_source": source,
            "wall_s": round(time.time() - t0, 1),
        }
        if batch_err is not None:
            row["real_batch_error"] = batch_err
        print(json.dumps(row), flush=True)
    except Exception as e:
        print(
            json.dumps({"model": name, "error": f"{type(e).__name__}: {e}"}),
            flush=True,
        )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--batch-log2", type=int, default=16)  # 65536
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument(
        "--synthetic", action="store_true",
        help="use synthetic batches instead of the zipf CSR cache",
    )
    ap.add_argument(
        "--model", default=None,
        help="bench ONE model inline (child mode); default: all models, "
        "each in its own subprocess",
    )
    ap.add_argument("--hot-log2", type=int, default=None,
                    help="override hot_size_log2 (0 = hot off)")
    ap.add_argument("--hot-nnz", type=int, default=None)
    ap.add_argument("--cold-nnz", type=int, default=None,
                    help="override max_nnz (cold capacity)")
    ap.add_argument("--hot-dtype", default=None,
                    choices=["float32", "bfloat16"])
    ap.add_argument("--microbatch", type=int, default=None)
    ap.add_argument("--cold-consolidate", action="store_true",
                    dest="cold_consolidate")
    args = ap.parse_args()

    if args.model is not None:
        run_one(args.model, args)
        return

    if args.cold_consolidate or any(
        v is not None
        for v in (args.hot_log2, args.hot_nnz, args.cold_nnz,
                  args.hot_dtype, args.microbatch)
    ):
        # geometry overrides are per-model sweep knobs; applied fleet-
        # wide they'd also rewrite the *_nohot control rows (making the
        # hot-vs-nohot comparison hot-vs-hot) and hand FFM a hot table
        # its 156-wide rows can't ride (model_cfgs docstring)
        ap.error("geometry overrides require --model (child mode)")

    # Parent mode: one subprocess per model.  Isolation matters — a
    # model whose tables cannot fit (or that trips an OOM) must not
    # poison the device heap/jit caches of the models after it, which
    # is exactly what happened when all models shared one process
    # (round-4 log: FFM's 31 GB table OOM'd, then wide_deep — fine in
    # isolation — reported RESOURCE_EXHAUSTED too).
    import subprocess

    names = [n for n, _ in model_cfgs(1 << args.batch_log2, True)]
    passthrough = []
    if args.cpu:
        passthrough.append("--cpu")
    if args.synthetic:
        passthrough.append("--synthetic")
    passthrough += ["--batch-log2", str(args.batch_log2),
                    "--iters", str(args.iters)]
    for name in names:
        proc = subprocess.run(
            [sys.executable, __file__, "--model", name, *passthrough],
            stdout=subprocess.PIPE, text=True,
        )
        out = proc.stdout.strip()
        if out:
            print(out, flush=True)
        if proc.returncode != 0:
            print(
                json.dumps({"model": name, "error": f"exit {proc.returncode}"}),
                flush=True,
            )


if __name__ == "__main__":
    main()
