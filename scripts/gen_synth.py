"""Synthetic zipf-feature CTR dataset generator (libffm text format).

Produces data shaped like the reference's bundled files
(/root/reference/data/small_train-00000:1 — ``label<TAB>fgid:fid:val``
lines, shard naming ``prefix-%05d`` per lr_worker.cc:210) but at
arbitrary scale, with:

* **zipf-distributed feature ids** per field — CTR traffic is zipfian,
  which is what makes frequency-hot tables and gradient consolidation
  worth benchmarking;
* **a planted logistic signal**: each (field, id) carries a hidden
  weight w ~ N(0, w_scale); label ~ Bernoulli(sigmoid(bias + Σw)).  A
  correct trainer must converge to logloss/AUC measurably better than
  chance, giving the convergence baseline VERDICT round 1 asked for.

Generation is fully vectorized fixed-width byte assembly (no per-line
Python), sustaining >100 MB/s on one core: every token is exactly
``FF:XXXXXXX:1 `` (2-digit field, 7-digit global id, binary value — the
hash-mode loader discards values anyway, load_data_from_disk.cc:151).
"""

from __future__ import annotations

import argparse
import math
import os

import numpy as np

# Bump when output bytes change for the same params — v2: multi-shard
# datasets share ONE planted model (model_seed); single-shard bytes are
# unchanged but the version stamp invalidates any cached multi-shard or
# test split written by the broken v1 (no other staleness signal
# exists for a dataset already on disk).
GEN_VERSION = 2

FIELDS = 39  # Criteo-style: 13 numeric + 26 categorical
VOCAB = 100_000  # ids per field; global id = field * VOCAB + local
TOKEN_W = 13  # b"FF:XXXXXXX:1 "
LINE_W = 2 + FIELDS * TOKEN_W  # label + tab + tokens (last byte -> \n)


def hidden_weights(seed: int, w_scale: float = 0.22) -> np.ndarray:
    """The planted model: float32 [FIELDS, VOCAB], deterministic in seed."""
    rng = np.random.default_rng(seed ^ 0x5EED)
    return rng.normal(0.0, w_scale, (FIELDS, VOCAB)).astype(np.float32)


_ALIAS_CACHE: dict[float, tuple[np.ndarray, np.ndarray]] = {}
_HI_DIGITS = None  # [10000, 4] uint8 ascii digits
_LO_DIGITS = None  # [1000, 3]


def _zipf_alias(a: float) -> tuple[np.ndarray, np.ndarray]:
    """Walker alias tables for the bounded zipf over ranks [0, VOCAB)
    (P(r) ∝ (r+1)^-a): exact sampling in O(1) per draw — two uniforms +
    two table gathers — ~5x faster than inverse-CDF binary search and
    ~10x faster than numpy's unbounded rejection sampler."""
    tabs = _ALIAS_CACHE.get(a)
    if tabs is None:
        pmf = np.arange(1, VOCAB + 1, dtype=np.float64) ** -a
        pmf /= pmf.sum()
        scaled = pmf * VOCAB
        prob = np.ones(VOCAB)
        alias = np.arange(VOCAB, dtype=np.int32)
        small = [i for i in range(VOCAB) if scaled[i] < 1.0]
        large = [i for i in range(VOCAB) if scaled[i] >= 1.0]
        scaled = scaled.copy()
        while small and large:
            s, l = small.pop(), large.pop()
            prob[s] = scaled[s]
            alias[s] = l
            scaled[l] += scaled[s] - 1.0
            (small if scaled[l] < 1.0 else large).append(l)
        tabs = (prob, alias)
        _ALIAS_CACHE[a] = tabs
    return tabs


def _zipf_draw(
    rng: np.random.Generator, shape: tuple[int, ...], a: float
) -> np.ndarray:
    prob, alias = _zipf_alias(a)
    k = (rng.random(shape) * VOCAB).astype(np.int32)
    return np.where(rng.random(shape) < prob[k], k, alias[k]).astype(np.int32)


def _digit_tables():
    global _HI_DIGITS, _LO_DIGITS
    if _HI_DIGITS is None:
        hi = np.arange(10000, dtype=np.int32)
        _HI_DIGITS = np.stack(
            [48 + (hi // 10 ** (3 - d)) % 10 for d in range(4)], axis=1
        ).astype(np.uint8)
        _LO_DIGITS = _HI_DIGITS[:1000, 1:].copy()
    return _HI_DIGITS, _LO_DIGITS


def _chunk_bytes(
    rng: np.random.Generator,
    n: int,
    w: np.ndarray,
    bias: float,
    zipf_a: float,
) -> bytes:
    ids = _zipf_draw(rng, (n, FIELDS), zipf_a)
    logit = w[np.arange(FIELDS)[None, :], ids].sum(axis=1) + bias
    p = 1.0 / (1.0 + np.exp(-logit))
    labels = (rng.random(n) < p).astype(np.uint8)

    hi_d, lo_d = _digit_tables()
    buf = np.empty((n, LINE_W), dtype=np.uint8)
    buf[:, 0] = 48 + labels
    buf[:, 1] = 9  # tab
    tok = buf[:, 2:].reshape(n, FIELDS, TOKEN_W)
    fgid = np.arange(FIELDS, dtype=np.int32)[None, :]
    tok[:, :, 0] = 48 + fgid // 10
    tok[:, :, 1] = 48 + fgid % 10
    tok[:, :, 2] = 58  # ':'
    gid = fgid * VOCAB + ids  # 7 digits: 4 high + 3 low via lookup
    tok[:, :, 3:7] = hi_d[gid // 1000]
    tok[:, :, 7:10] = lo_d[gid % 1000]
    tok[:, :, 10] = 58  # ':'
    tok[:, :, 11] = 49  # '1'
    tok[:, :, 12] = 32  # ' '
    buf[:, -1] = 10  # '\n'
    return buf.tobytes()


def generate_shard(
    path: str,
    num_examples: int,
    seed: int = 7,
    bias: float = -1.0,
    zipf_a: float = 1.2,
    chunk: int = 131072,
    model_seed: int | None = None,
) -> dict:
    """Write one shard; returns {"bytes": ..., "examples": ...}.

    ``model_seed`` selects the PLANTED MODEL (hidden_weights); ``seed``
    selects the example stream.  They must be distinguished whenever a
    dataset spans multiple shards: with the old behavior (model tied to
    the per-shard stream seed) every shard carried a DIFFERENT planted
    model and the dataset as a whole had no learnable signal — measured
    as test AUC ~0.49 on a 4-shard train + test split (round 4).
    Defaults to ``seed`` so single-shard datasets are byte-identical to
    older versions (the bench cache stays valid)."""
    w = hidden_weights(seed if model_seed is None else model_seed)
    rng = np.random.default_rng(seed)
    written = 0
    with open(path, "wb", buffering=1 << 22) as f:
        while written < num_examples:
            n = min(chunk, num_examples - written)
            f.write(_chunk_bytes(rng, n, w, bias, zipf_a))
            written += n
    return {"bytes": os.path.getsize(path), "examples": num_examples}


def generate_dataset(
    prefix: str,
    num_train: int,
    num_test: int = 0,
    train_shards: int = 1,
    seed: int = 7,
    **kw,
) -> dict:
    """Write ``<prefix>.train-%05d`` shards (+ ``<prefix>.test-00000``).
    Train and test draw from the same planted model (different streams).
    """
    os.makedirs(os.path.dirname(prefix) or ".", exist_ok=True)
    per = math.ceil(num_train / train_shards)
    info: dict = {"train": [], "test": None}
    done = 0
    for s in range(train_shards):
        n = min(per, num_train - done)
        info["train"].append(
            generate_shard(
                f"{prefix}.train-{s:05d}", n, seed=seed + s,
                model_seed=seed, **kw,
            )
        )
        done += n
    if num_test:
        info["test"] = generate_shard(
            f"{prefix}.test-00000", num_test, seed=seed + 10_000,
            model_seed=seed, **kw,
        )
    return info


def bayes_optimal_logloss(
    seed: int = 7, bias: float = -1.0, zipf_a: float = 1.2, n: int = 500_000
) -> float:
    """Monte-Carlo estimate of the generator's irreducible logloss (the
    planted model scored against its own labels) — the convergence floor
    a perfect trainer approaches."""
    w = hidden_weights(seed)
    rng = np.random.default_rng(seed ^ 0xF100)
    ids = _zipf_draw(rng, (n, FIELDS), zipf_a)
    logit = w[np.arange(FIELDS)[None, :], ids].sum(axis=1) + bias
    p = 1.0 / (1.0 + np.exp(-logit))
    return float(np.mean(-(p * np.log(p) + (1 - p) * np.log1p(-p))))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("prefix", help="output path prefix")
    ap.add_argument("num_train", type=int)
    ap.add_argument("--num-test", type=int, default=0)
    ap.add_argument("--train-shards", type=int, default=1)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--zipf-a", type=float, default=1.2)
    args = ap.parse_args()
    info = generate_dataset(
        args.prefix,
        args.num_train,
        args.num_test,
        args.train_shards,
        seed=args.seed,
        zipf_a=args.zipf_a,
    )
    print(info)


if __name__ == "__main__":
    main()
