#!/bin/bash
# Round-5 TPU measurement queue — run when the tunnel is healthy:
#     bash scripts/tpu_session.sh [outdir]
#
# Runs the full evidence list in priority order, flushing each result
# to its own file the moment it lands (the tunnel dies without
# warning — docs/PERF.md).  NO timeouts around TPU-bound processes:
# killing one wedges the chip lease for every later client (verify
# skill notes).  Priorities:
#   1. bench.py             -> flagship artifact (BENCH + docs/artifacts)
#   2. time_to_auc lr       -> the north-star >=5x wall-clock-to-AUC
#   3. time_to_auc flagship -> full-protocol path-parity overlay
#   4. probe_consolidate    -> is the argsort worth the saved slices?
#   5. bench_models sweeps  -> D>1 hot-head scaling + cold_consolidate
#   6. time_to_auc t28      -> B_eff=512 at the north-star table
set -u
cd "$(dirname "$0")/.."
OUT="${1:-/tmp/tpu_r5}"
mkdir -p "$OUT"
log() { echo "[$(date -u +%H:%M:%S)] $*"; }

log "1/6 bench.py (flagship)"
python bench.py >"$OUT/bench.json" 2>"$OUT/bench.err"
tail -c 400 "$OUT/bench.json"

log "2/6 time_to_auc lr (plain path, the north-star artifact)"
python scripts/time_to_auc.py --model lr \
    >"$OUT/ttauc_lr.out" 2>"$OUT/ttauc_lr.err"
tail -2 "$OUT/ttauc_lr.out"

log "3/6 time_to_auc lr flagship path (full-protocol overlay)"
python scripts/time_to_auc.py --model lr \
    --hot-size-log2 12 --hot-nnz 32 --max-nnz 16 \
    --out docs/artifacts/time_to_auc_lr_flagship.json \
    >"$OUT/ttauc_lr_flag.out" 2>"$OUT/ttauc_lr_flag.err"
tail -2 "$OUT/ttauc_lr_flag.out"

log "4/6 probe_consolidate"
python scripts/probe_consolidate.py \
    >"$OUT/probe_consolidate.out" 2>"$OUT/probe_consolidate.err"
cat "$OUT/probe_consolidate.out"

log "5/6 bench_models: baseline + D>1 sweeps"
python scripts/bench_models.py --batch-log2 17 \
    >"$OUT/models_base.out" 2>"$OUT/models_base.err"
for m in fm mvm wide_deep; do
  for h in 14 15 16; do
    python scripts/bench_models.py --model "$m" --batch-log2 17 \
        --hot-log2 "$h" \
        >>"$OUT/models_sweep.out" 2>>"$OUT/models_sweep.err"
    python scripts/bench_models.py --model "$m" --batch-log2 17 \
        --hot-log2 "$h" --cold-consolidate \
        >>"$OUT/models_sweep.out" 2>>"$OUT/models_sweep.err"
  done
  python scripts/bench_models.py --model "$m" --batch-log2 17 \
      --hot-log2 14 --hot-dtype bfloat16 \
      >>"$OUT/models_sweep.out" 2>>"$OUT/models_sweep.err"
done
# FFM: no hot geometry fits its 156-wide rows; measure consolidation
python scripts/bench_models.py --model ffm --batch-log2 17 \
    --cold-consolidate \
    >>"$OUT/models_sweep.out" 2>>"$OUT/models_sweep.err"
# LR flagship neighbors: resolve round-4's interpolated flagship row
# with direct measurements (cold 12 — cold 16 IS the step-5 baseline
# lr row — and bf16 hot)
python scripts/bench_models.py --model lr --batch-log2 17 \
    --hot-log2 12 --cold-nnz 12 \
    >>"$OUT/models_sweep.out" 2>>"$OUT/models_sweep.err"
python scripts/bench_models.py --model lr --batch-log2 17 \
    --hot-log2 12 --hot-dtype bfloat16 \
    >>"$OUT/models_sweep.out" 2>>"$OUT/models_sweep.err"
tail -8 "$OUT/models_sweep.out"

log "6/6 time_to_auc t28 sparse inner (north-star table)"
python scripts/time_to_auc.py --model lr --table-size-log2 28 \
    --sequential-inner sparse --max-epochs 2 --target-auc 0.99 \
    --out docs/artifacts/time_to_auc_lr_t28.json \
    >"$OUT/ttauc_t28.out" 2>"$OUT/ttauc_t28.err"
tail -2 "$OUT/ttauc_t28.out"

log "queue complete — results in $OUT and docs/artifacts/"
