#!/bin/bash
# TPU measurement queue — ALL the chip-session probe queues in one
# parameterized script (formerly tpu_session.sh + tpu_session{2,3,4}.sh,
# one file per round-5 re-plan; each former variant is a part here):
#
#     bash scripts/tpu_session.sh PART [outdir]
#
#   PART = r5     round-5 evidence list: flagship bench, wall-to-AUC,
#                 probe_consolidate, D>1 hot sweeps, t28 sparse probe
#          r5b    post-tunnel-drop re-plan: sparse-inner headline,
#                 reference-shaped e2e ckpt/resume, D>1 sweeps, fm/mvm
#                 wall-to-AUC (sparse inner)
#          r5c    hot-fine/cold-coarse inner (sequential_inner='hot'):
#                 headline crossings, half-window, t28 rate probe,
#                 fm/mvm on the hot inner
#          r5d    remainder of r5b after the 2026-07-31 drop: e2e
#                 ckpt/resume, lr flagship neighbors, D>1 sweeps, ffm
#                 per-table hot
#          store  tiered-store (store_mode='tiered', docs/STORE.md):
#                 D>1 families at the 2^28 north star + zipf hit-rate
#                 and store-row evidence
#
# Run when the tunnel is healthy.  Results flush to their own files the
# moment they land (the tunnel dies without warning — docs/PERF.md).
# NO timeouts around TPU-bound processes: killing one wedges the chip
# lease for every later client (verify skill notes).
set -u
cd "$(dirname "$0")/.."
PART="${1:?usage: tpu_session.sh {r5|r5b|r5c|r5d|store} [outdir]}"
OUT="${2:-/tmp/tpu_${PART}}"
mkdir -p "$OUT"
log() { echo "[$(date -u +%H:%M:%S)] $*"; }

e2e_ckpt_resume() {
  log "reference-shaped e2e on TPU: CLI train over the binary cache + ckpt + resume"
  rm -rf /tmp/ck_tpu /tmp/pred_tpu.txt
  python -m xflow_tpu.train --model lr \
      --train /tmp/xflow_conv/bin.train --test /tmp/xflow_conv/bin.test \
      --epochs 2 --batch-size 131072 --table-size-log2 24 --max-nnz 40 \
      --hot-size-log2 12 --hot-nnz 32 --num-devices 1 \
      --checkpoint-dir /tmp/ck_tpu --metrics-out "$OUT/e2e_train_metrics.jsonl" \
      >"$OUT/e2e_train.out" 2>"$OUT/e2e_train.err"
  tail -3 "$OUT/e2e_train.out"
  python -m xflow_tpu.train --model lr \
      --train /tmp/xflow_conv/bin.train --test /tmp/xflow_conv/bin.test \
      --epochs 3 --batch-size 131072 --table-size-log2 24 --max-nnz 40 \
      --hot-size-log2 12 --hot-nnz 32 --num-devices 1 \
      --checkpoint-dir /tmp/ck_tpu --resume \
      >"$OUT/e2e_resume.out" 2>"$OUT/e2e_resume.err"
  tail -3 "$OUT/e2e_resume.out"
}

lr_flagship_neighbors() {
  log "lr flagship neighbors (cold-nnz 12, bf16 hot)"
  python scripts/bench_models.py --model lr --batch-log2 17 \
      --hot-log2 12 --cold-nnz 12 \
      >>"$OUT/lr_neighbors.out" 2>>"$OUT/lr_neighbors.err"
  python scripts/bench_models.py --model lr --batch-log2 17 \
      --hot-log2 12 --hot-dtype bfloat16 \
      >>"$OUT/lr_neighbors.out" 2>>"$OUT/lr_neighbors.err"
  tail -2 "$OUT/lr_neighbors.out"
}

d1_hot_sweeps() {  # fm/mvm/wide_deep hot {15,16} + bf16
  log "D>1 hot-head scaling: fm/mvm/wide_deep hot {15,16} + bf16"
  for m in fm mvm wide_deep; do
    for h in 15 16; do
      python scripts/bench_models.py --model "$m" --batch-log2 17 \
          --hot-log2 "$h" \
          >>"$OUT/models_sweep.out" 2>>"$OUT/models_sweep.err"
    done
    python scripts/bench_models.py --model "$m" --batch-log2 17 \
        --hot-log2 14 --hot-dtype bfloat16 \
        >>"$OUT/models_sweep.out" 2>>"$OUT/models_sweep.err"
  done
  tail -9 "$OUT/models_sweep.out"
}

ttauc_t28_sparse() {
  log "time_to_auc t28 sparse inner (north-star table)"
  python scripts/time_to_auc.py --model lr --table-size-log2 28 \
      --sequential-inner sparse --max-epochs 2 --target-auc 0.99 \
      --out docs/artifacts/time_to_auc_lr_t28.json \
      >"$OUT/ttauc_t28.out" 2>"$OUT/ttauc_t28.err"
  tail -2 "$OUT/ttauc_t28.out"
}

part_r5() {
  log "1/6 bench.py (flagship)"
  python bench.py >"$OUT/bench.json" 2>"$OUT/bench.err"
  tail -c 400 "$OUT/bench.json"

  log "2/6 time_to_auc lr (plain path, the north-star artifact)"
  python scripts/time_to_auc.py --model lr \
      >"$OUT/ttauc_lr.out" 2>"$OUT/ttauc_lr.err"
  tail -2 "$OUT/ttauc_lr.out"

  log "3/6 time_to_auc lr flagship path (full-protocol overlay)"
  python scripts/time_to_auc.py --model lr \
      --hot-size-log2 12 --hot-nnz 32 --max-nnz 16 \
      --out docs/artifacts/time_to_auc_lr_flagship.json \
      >"$OUT/ttauc_lr_flag.out" 2>"$OUT/ttauc_lr_flag.err"
  tail -2 "$OUT/ttauc_lr_flag.out"

  log "4/6 probe_consolidate"
  python scripts/probe_consolidate.py \
      >"$OUT/probe_consolidate.out" 2>"$OUT/probe_consolidate.err"
  cat "$OUT/probe_consolidate.out"

  log "5/6 bench_models: baseline + D>1 sweeps"
  python scripts/bench_models.py --batch-log2 17 \
      >"$OUT/models_base.out" 2>"$OUT/models_base.err"
  for m in fm mvm wide_deep; do
    for h in 14 15 16; do
      python scripts/bench_models.py --model "$m" --batch-log2 17 \
          --hot-log2 "$h" \
          >>"$OUT/models_sweep.out" 2>>"$OUT/models_sweep.err"
      python scripts/bench_models.py --model "$m" --batch-log2 17 \
          --hot-log2 "$h" --cold-consolidate \
          >>"$OUT/models_sweep.out" 2>>"$OUT/models_sweep.err"
    done
    python scripts/bench_models.py --model "$m" --batch-log2 17 \
        --hot-log2 14 --hot-dtype bfloat16 \
        >>"$OUT/models_sweep.out" 2>>"$OUT/models_sweep.err"
  done
  # FFM: no hot geometry fits its 156-wide rows; measure consolidation
  python scripts/bench_models.py --model ffm --batch-log2 17 \
      --cold-consolidate \
      >>"$OUT/models_sweep.out" 2>>"$OUT/models_sweep.err"
  lr_flagship_neighbors
  tail -8 "$OUT/models_sweep.out"

  log "6/6 t28"
  ttauc_t28_sparse
}

part_r5b() {
  log "1/6 time_to_auc lr, sparse inner (headline north-star attempt)"
  python scripts/time_to_auc.py --model lr --sequential-inner sparse \
      --out docs/artifacts/time_to_auc_lr_sparse.json \
      >"$OUT/ttauc_sparse.out" 2>"$OUT/ttauc_sparse.err"
  tail -2 "$OUT/ttauc_sparse.out"

  log "1b/6 time_to_auc lr, HYBRID sparse inner + flagship hot geometry"
  python scripts/time_to_auc.py --model lr --sequential-inner sparse \
      --hot-size-log2 12 --hot-nnz 32 --max-nnz 16 \
      --out docs/artifacts/time_to_auc_lr_sparse_flagship.json \
      >"$OUT/ttauc_sparse_flag.out" 2>"$OUT/ttauc_sparse_flag.err"
  tail -2 "$OUT/ttauc_sparse_flag.out"

  log "2/6"; e2e_ckpt_resume
  log "3/6"; lr_flagship_neighbors
  log "4/6"; ttauc_t28_sparse
  log "5/6"; d1_hot_sweeps

  log "6/6 wall-to-AUC for the D>1 families, sparse inner (fm, mvm)"
  python scripts/time_to_auc.py --model fm --sequential-inner sparse --max-epochs 10 \
      --out docs/artifacts/time_to_auc_fm_sparse.json \
      >"$OUT/ttauc_fm.out" 2>"$OUT/ttauc_fm.err"
  tail -1 "$OUT/ttauc_fm.out"
  python scripts/time_to_auc.py --model mvm --sequential-inner sparse --max-epochs 10 \
      --out docs/artifacts/time_to_auc_mvm_sparse.json \
      >"$OUT/ttauc_mvm.out" 2>"$OUT/ttauc_mvm.err"
  tail -1 "$OUT/ttauc_mvm.out"
}

part_r5c() {
  log "1/4 HEADLINE: time_to_auc lr, hot inner, 2^14 head"
  python scripts/time_to_auc.py --model lr --sequential-inner hot --max-epochs 9 \
      --hot-size-log2 14 --hot-nnz 32 --max-nnz 16 \
      --out docs/artifacts/time_to_auc_lr_hot14.json \
      >"$OUT/ttauc_hot14.out" 2>"$OUT/ttauc_hot14.err"
  tail -2 "$OUT/ttauc_hot14.out"

  log "2/4 hot inner, flagship geometry (2^12 head)"
  python scripts/time_to_auc.py --model lr --sequential-inner hot --max-epochs 9 \
      --hot-size-log2 12 --hot-nnz 32 --max-nnz 16 \
      --out docs/artifacts/time_to_auc_lr_hot_flagship.json \
      >"$OUT/ttauc_hot_flag.out" 2>"$OUT/ttauc_hot_flag.err"
  tail -2 "$OUT/ttauc_hot_flag.out"

  log "2b/4 hot inner, half window (B=65536): halves cold staleness"
  python scripts/time_to_auc.py --model lr --sequential-inner hot --max-epochs 9 \
      --batch-size 65536 --hot-size-log2 12 --hot-nnz 32 --max-nnz 16 \
      --out docs/artifacts/time_to_auc_lr_hot_b64k.json \
      >"$OUT/ttauc_hot_b64k.out" 2>"$OUT/ttauc_hot_b64k.err"
  tail -2 "$OUT/ttauc_hot_b64k.out"

  log "3/4 north-star table: hot inner at T=2^28 (2 epochs, rate probe)"
  python scripts/time_to_auc.py --model lr --table-size-log2 28 \
      --sequential-inner hot --hot-size-log2 14 --hot-nnz 32 --max-nnz 16 \
      --max-epochs 2 --target-auc 0.99 \
      --out docs/artifacts/time_to_auc_lr_hot_t28.json \
      >"$OUT/ttauc_hot_t28.out" 2>"$OUT/ttauc_hot_t28.err"
  tail -2 "$OUT/ttauc_hot_t28.out"

  log "4/4 D>1 families on the hot inner: fm, mvm wall-to-AUC"
  python scripts/time_to_auc.py --model fm --sequential-inner hot \
      --hot-size-log2 14 --hot-nnz 32 --max-nnz 16 --max-epochs 10 \
      --out docs/artifacts/time_to_auc_fm_hot.json \
      >"$OUT/ttauc_fm_hot.out" 2>"$OUT/ttauc_fm_hot.err"
  tail -1 "$OUT/ttauc_fm_hot.out"
  python scripts/time_to_auc.py --model mvm --sequential-inner hot \
      --hot-size-log2 14 --hot-nnz 32 --max-nnz 16 --max-epochs 10 \
      --out docs/artifacts/time_to_auc_mvm_hot.json \
      >"$OUT/ttauc_mvm_hot.out" 2>"$OUT/ttauc_mvm_hot.err"
  tail -1 "$OUT/ttauc_mvm_hot.out"
}

part_r5d() {
  log "1/3"; e2e_ckpt_resume
  log "2/3"; lr_flagship_neighbors
  log "3/3"; d1_hot_sweeps

  log "3b/3 ffm per-table hot (w on MXU, v on DMA)"
  for h in 12 14 15; do
    python scripts/bench_models.py --model ffm --batch-log2 17 \
        --hot-log2 "$h" \
        >>"$OUT/ffm_hot.out" 2>>"$OUT/ffm_hot.err"
  done
  tail -3 "$OUT/ffm_hot.out"
}

part_store() {
  # Tiered-store evidence (docs/STORE.md): D>1 at the 2^28 north star
  # — only trainable through store_mode='tiered' — plus zipf hit-rate
  # rows for the promotion policy.  Uses the synth zipf generator.
  log "0/2 synth zipf data"
  python scripts/gen_synth.py /tmp/xflow_store/zipf 2000000 --num-test 200000 \
      --zipf-a 1.2 >"$OUT/gen.out" 2>"$OUT/gen.err"

  log "1/2 fm at 2^28, tiered (the PR 8 acceptance geometry at scale)"
  python -m xflow_tpu.train --model fm \
      --train /tmp/xflow_store/zipf.train --test /tmp/xflow_store/zipf.test \
      --epochs 2 --batch-size 8192 --table-size-log2 28 --max-nnz 48 \
      --store-mode tiered --hot-capacity-log2 18 --num-devices 1 \
      --metrics-out "$OUT/store_fm28.jsonl" \
      >"$OUT/store_fm28.out" 2>"$OUT/store_fm28.err"
  tail -3 "$OUT/store_fm28.out"
  grep '"kind": "store"' "$OUT/store_fm28.jsonl" | tail -2

  log "2/2 lr tiered vs dense throughput at 2^24 (tiering overhead)"
  for mode in dense tiered; do
    extra=""
    [ "$mode" = tiered ] && extra="--hot-capacity-log2 18"
    python -m xflow_tpu.train --model lr \
        --train /tmp/xflow_store/zipf.train --epochs 2 \
        --batch-size 8192 --table-size-log2 24 --max-nnz 48 \
        --store-mode "$mode" $extra --num-devices 1 --skip-eval \
        --metrics-out "$OUT/store_lr_${mode}.jsonl" \
        >"$OUT/store_lr_${mode}.out" 2>"$OUT/store_lr_${mode}.err"
    tail -2 "$OUT/store_lr_${mode}.out"
  done
}

case "$PART" in
  r5) part_r5 ;;
  r5b) part_r5b ;;
  r5c) part_r5c ;;
  r5d) part_r5d ;;
  store) part_store ;;
  *) echo "unknown part $PART (r5|r5b|r5c|r5d|store)" >&2; exit 2 ;;
esac
log "queue complete — results in $OUT and docs/artifacts/"
