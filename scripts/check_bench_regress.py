"""Bench-trajectory regress check: compare the newest committed
``BENCH_r*.json`` against the best prior run via ``python -m
xflow_tpu.obs compare --fail-on-regress``.

Two metrics gate:

* the train metric (``value``) against the best non-degraded prior;
* ``e2e_packed_examples_per_sec`` — the packed input-path throughput
  the fan-out work (ISSUE 14 / ROADMAP 1) optimizes — against the best
  non-degraded prior that MEASURES it (older artifacts predate the
  metric; a degraded round never becomes either bar).

The committed bench artifacts accumulated for five PRs without ever
gating anything; this script turns the trajectory into a signal.  It
is WARN-ONLY by default (exit 0 with a loud message): the containers
the tier-1 suite runs in are routinely degraded (CPU backend,
``degraded: true`` in the artifact) and wildly different in core
count, so a hard gate would fail on environment, not on code.
``--strict`` makes a regression (or a missing baseline) exit non-zero
for environments where the numbers are trustworthy.

Run from the repo root:

    python scripts/check_bench_regress.py [--frac 0.10] [--strict]

Wired into tier-1 (warn-only) via tests/test_observability.py::
test_check_bench_regress_script.
"""

from __future__ import annotations

import argparse
import glob
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def find_bench_artifacts(root: str) -> list[str]:
    return sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument(
        "--frac", type=float, default=0.10,
        help="fail threshold: fraction below the best prior run "
        "(default 0.10 = 10%%)",
    )
    p.add_argument(
        "--strict", action="store_true",
        help="exit non-zero on regression (default: warn only — "
        "tier-1 containers produce degraded numbers)",
    )
    p.add_argument("--root", default=REPO, help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    from xflow_tpu.obs.__main__ import main as obs_main
    from xflow_tpu.obs.summary import load_bench_result

    # one read per artifact: every later filter/lookup goes through
    # this memo (an artifact rewritten mid-run can't be seen in two
    # different states).  load_bench_result only swallows parse
    # errors; an artifact that can't be READ (racing delete, bad
    # perms) must degrade to "not usable", not crash the gate.
    results: dict[str, dict | None] = {}
    unreadable = []
    for p_ in find_bench_artifacts(args.root):
        try:
            results[p_] = load_bench_result(p_)
        except OSError as e:
            results[p_] = None
            unreadable.append(f"{p_} ({e.strerror or e})")
    usable = [p_ for p_, r in results.items() if r is not None]
    if len(usable) < 2:
        detail = (
            "; unreadable: " + ", ".join(unreadable) if unreadable else ""
        )
        print(
            f"SKIP: {len(usable)} usable bench artifact(s) under "
            f"{args.root} — need a latest and at least one prior"
            f"{detail}"
        )
        return 1 if args.strict else 0
    latest = usable[-1]
    # A degraded run (CPU fallback where an accelerator was expected —
    # bench.py _finalize_artifact) must never become the bar: its
    # "value" measures the container, not the code.  Baseline
    # candidates are the non-degraded priors; when every prior is
    # degraded (a whole stretch of broken tunnels) fall back to all of
    # them rather than skipping the check entirely.
    priors = [
        p_ for p_ in usable[:-1] if not results[p_].get("degraded")
    ]
    if not priors:
        print(
            "WARNING: every prior bench artifact is degraded — "
            "comparing against degraded baselines"
        )
        priors = usable[:-1]
    best_prior = max(priors, key=lambda p_: float(results[p_]["value"]))
    print(f"comparing latest {latest} against best prior {best_prior}:")
    rc = obs_main([
        "compare", "--fail-on-regress", str(args.frac), best_prior, latest,
    ])
    regressions = []
    if rc == 3:
        regressions.append(
            f"bench regression: {latest} fell more than "
            f"{100 * args.frac:.0f}% below {best_prior}"
        )
    elif rc != 0:
        print(f"FAIL: obs compare exited {rc}", file=sys.stderr)
        return rc
    else:
        print(f"OK: {latest} within {100 * args.frac:.0f}% of {best_prior}")

    # secondary gate: the packed input-path metric.  Its baseline is
    # chosen among priors that HAVE it (it postdates the early rounds),
    # still skipping degraded ones.
    e2e = "e2e_packed_examples_per_sec"
    latest_e2e = results[latest].get(e2e)
    e2e_priors = [p_ for p_ in priors if results[p_].get(e2e)]
    if latest_e2e and e2e_priors:
        best_e2e = max(e2e_priors, key=lambda p_: float(results[p_][e2e]))
        a = float(results[best_e2e][e2e])
        b = float(latest_e2e)
        drop = (a - b) / a if a > 0 else 0.0
        if drop > args.frac:
            regressions.append(
                f"input-path regression: {latest} {e2e}={b:.0f} is "
                f"{100 * drop:.1f}% below {best_e2e} ({a:.0f})"
            )
        else:
            print(
                f"OK: {e2e} {b:.0f} within {100 * args.frac:.0f}% of "
                f"best prior {best_e2e} ({a:.0f})"
            )
    elif not latest_e2e and e2e_priors:
        # priors measure the metric but the latest doesn't: the e2e
        # bench leg broke or was skipped — the gate must not silently
        # stop measuring the very metric it exists to protect
        regressions.append(
            f"missing metric: latest artifact {latest} has no {e2e} "
            "while prior artifacts measure it — the e2e packed bench "
            "leg did not run"
        )
    for msg in regressions:
        if args.strict:
            print(f"FAIL: {msg}", file=sys.stderr)
        else:
            print(f"WARN (non-gating): {msg}", file=sys.stderr)
    return 1 if (regressions and args.strict) else 0


if __name__ == "__main__":
    sys.exit(main())
