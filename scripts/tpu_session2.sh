#!/bin/bash
# Round-5 TPU measurement queue, part 2 — the first session captured
# the flagship bench artifact (3.09 M ex/s, 7.64x, bench_tpu_*.json),
# the plain-path wall-to-AUC (232.8 s train+eval to 0.7401) and the
# flagship-path parity overlay, then the tunnel died during the D>1
# sweeps.  This queue holds what remains, re-prioritized:
#   - cold-consolidate sweeps are DROPPED: probe_consolidate measured
#     the consolidated scatter 2x SLOWER than plain on TPU (497 ms vs
#     239 ms at dup_frac 0.92) — negative result recorded in PERF.md.
#   - the headline attempt is now sequential_inner=sparse, measured
#     17x faster per window than the dense inner on CPU (the dense
#     inner streams the full 2^24 table per 512-example slice).
# Run when the tunnel is healthy: bash scripts/tpu_session2.sh [outdir]
# NO timeouts around TPU-bound processes (verify skill: killing one
# wedges the chip lease).
set -u
cd "$(dirname "$0")/.."
OUT="${1:-/tmp/tpu_r5b}"
mkdir -p "$OUT"
log() { echo "[$(date -u +%H:%M:%S)] $*"; }

log "1/6 time_to_auc lr, sparse inner (headline north-star attempt)"
python scripts/time_to_auc.py --model lr --sequential-inner sparse \
    --out docs/artifacts/time_to_auc_lr_sparse.json \
    >"$OUT/ttauc_sparse.out" 2>"$OUT/ttauc_sparse.err"
tail -2 "$OUT/ttauc_sparse.out"

log "1b/6 time_to_auc lr, HYBRID sparse inner + flagship hot geometry"
python scripts/time_to_auc.py --model lr --sequential-inner sparse \
    --hot-size-log2 12 --hot-nnz 32 --max-nnz 16 \
    --out docs/artifacts/time_to_auc_lr_sparse_flagship.json \
    >"$OUT/ttauc_sparse_flag.out" 2>"$OUT/ttauc_sparse_flag.err"
tail -2 "$OUT/ttauc_sparse_flag.out"

log "2/6 reference-shaped e2e on TPU: CLI train over the binary cache + ckpt + resume"
rm -rf /tmp/ck_tpu /tmp/pred_tpu.txt
python -m xflow_tpu.train --model lr \
    --train /tmp/xflow_conv/bin.train --test /tmp/xflow_conv/bin.test \
    --epochs 2 --batch-size 131072 --table-size-log2 24 --max-nnz 40 \
    --hot-size-log2 12 --hot-nnz 32 --num-devices 1 \
    --checkpoint-dir /tmp/ck_tpu --metrics-out "$OUT/e2e_train_metrics.jsonl" \
    >"$OUT/e2e_train.out" 2>"$OUT/e2e_train.err"
tail -3 "$OUT/e2e_train.out"
python -m xflow_tpu.train --model lr \
    --train /tmp/xflow_conv/bin.train --test /tmp/xflow_conv/bin.test \
    --epochs 3 --batch-size 131072 --table-size-log2 24 --max-nnz 40 \
    --hot-size-log2 12 --hot-nnz 32 --num-devices 1 \
    --checkpoint-dir /tmp/ck_tpu --resume \
    >"$OUT/e2e_resume.out" 2>"$OUT/e2e_resume.err"
tail -3 "$OUT/e2e_resume.out"

log "3/6 lr flagship neighbors (resolve the interpolated flagship row)"
python scripts/bench_models.py --model lr --batch-log2 17 \
    --hot-log2 12 --cold-nnz 12 \
    >>"$OUT/lr_neighbors.out" 2>>"$OUT/lr_neighbors.err"
python scripts/bench_models.py --model lr --batch-log2 17 \
    --hot-log2 12 --hot-dtype bfloat16 \
    >>"$OUT/lr_neighbors.out" 2>>"$OUT/lr_neighbors.err"
tail -2 "$OUT/lr_neighbors.out"

log "4/6 time_to_auc t28 sparse inner (north-star table)"
python scripts/time_to_auc.py --model lr --table-size-log2 28 \
    --sequential-inner sparse --max-epochs 2 --target-auc 0.99 \
    --out docs/artifacts/time_to_auc_lr_t28.json \
    >"$OUT/ttauc_t28.out" 2>"$OUT/ttauc_t28.err"
tail -2 "$OUT/ttauc_t28.out"

log "5/6 D>1 hot-head scaling: fm/mvm/wide_deep hot {15,16} + bf16"
for m in fm mvm wide_deep; do
  for h in 15 16; do
    python scripts/bench_models.py --model "$m" --batch-log2 17 \
        --hot-log2 "$h" \
        >>"$OUT/models_sweep.out" 2>>"$OUT/models_sweep.err"
  done
  python scripts/bench_models.py --model "$m" --batch-log2 17 \
      --hot-log2 14 --hot-dtype bfloat16 \
      >>"$OUT/models_sweep.out" 2>>"$OUT/models_sweep.err"
done
tail -9 "$OUT/models_sweep.out"

log "6/6 wall-to-AUC for the D>1 families, sparse inner (fm, mvm)"
python scripts/time_to_auc.py --model fm --sequential-inner sparse --max-epochs 10 \
    --out docs/artifacts/time_to_auc_fm_sparse.json \
    >"$OUT/ttauc_fm.out" 2>"$OUT/ttauc_fm.err"
tail -1 "$OUT/ttauc_fm.out"
python scripts/time_to_auc.py --model mvm --sequential-inner sparse --max-epochs 10 \
    --out docs/artifacts/time_to_auc_mvm_sparse.json \
    >"$OUT/ttauc_mvm.out" 2>"$OUT/ttauc_mvm.err"
tail -1 "$OUT/ttauc_mvm.out"

log "queue complete — results in $OUT and docs/artifacts/"
