"""Cascade smoke lint: train both stages on toy data, export, serve
the retrieval→ranking cascade over HTTP, drive a zipf mix of
single-row and top-k traffic, and validate everything the cascade tier
promises (docs/SERVING.md "Retrieval→ranking cascade"):

* **top-k parity** — the engine's AOT dot-scan + device top-k matches
  a numpy full-scan argsort over the same user embeddings at 1e-6;
* **zero fleet-wide recompiles** — after warm, mixed single-row
  (/v1/score_packed on the ranking fleet) and top-k (/v1/recommend
  through the cascade) traffic adds no compiled executables on either
  stage;
* **0 errors** — every offered request answers 200 with a full
  k-candidate slate (no starvation on a k <= index-size setup);
* **independent staged rollout** — the ranking stage canaries and
  commits a rollout through the existing gate while the retrieval
  stage serves untouched;
* **schema** — the emitted metrics JSONL (run_start / serve_load /
  cascade / serve_stats / serve_shed / rollout) passes obs/schema.py
  strictly, and `obs doctor` raises no cascade warn on the healthy
  stream.

Run from the repo root:

    JAX_PLATFORMS=cpu python scripts/check_cascade_smoke.py

Wired into tier-1 via tests/test_cascade.py::test_check_cascade_smoke_script,
like check_serve_smoke.py.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

K = 5  # candidates per cascade request
TOPK_K = 8  # compiled top-k width
BUCKETS = (8, 64)


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import http.client

    import numpy as np

    from tests.gen_data import generate_dataset
    from xflow_tpu.config import Config
    from xflow_tpu.io.batch import pad_batch_rows
    from xflow_tpu.io.loader import make_parse_fn
    from xflow_tpu.obs.doctor import diagnose
    from xflow_tpu.obs.schema import load_jsonl, validate_rows
    from xflow_tpu.serve.artifact import export_artifact, export_item_index
    from xflow_tpu.serve.cascade import CascadeEngine
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.serve.fleet import ReplicaFleet
    from xflow_tpu.serve.loadgen import zipf_rows
    from xflow_tpu.serve.server import (
        ServeTier,
        decode_packed_response,
        encode_packed_request,
    )
    from xflow_tpu.trainer import Trainer
    from xflow_tpu.utils.logging import MetricsLogger

    errors: list[str] = []
    with tempfile.TemporaryDirectory() as root:
        ds = generate_dataset(
            os.path.join(root, "data"),
            num_train_shards=2,
            lines_per_shard=150,
            num_fields=10,
            vocab_per_field=8,
            seed=7,
            scale=3.0,
        )
        common = dict(
            train_path=ds.train_prefix,
            test_path=ds.test_prefix,
            epochs=1,
            batch_size=64,
            table_size_log2=14,
            max_nnz=24,
            max_fields=10,
            num_devices=1,
        )
        # -- stage 1: two-tower retrieval + item index ------------------
        rcfg = Config(
            model="two_tower", tower_split_field=5, tower_dim=8, **common
        )
        rtr = Trainer(rcfg)
        rtr.train()
        rart = export_artifact(rtr, os.path.join(root, "retrieval"))
        # item catalog + user rows from parsed test lines: item-side
        # features are slots >= split, user-side slots < split — the
        # same hashed key space training used.  The catalog comes from
        # the SHARED identity rule (serve/artifact.py::
        # item_catalog_from_block — also the `serve index` CLI's), so
        # this gate exercises exactly what the shipped tool builds.
        from xflow_tpu.serve.artifact import item_catalog_from_block

        parse = make_parse_fn(
            rcfg.table_size, rcfg.hash_mode, rcfg.seed, prefer_native=False
        )
        with open(ds.test_prefix + "-00000", "rb") as f:
            block = parse(f.read())
        items = item_catalog_from_block(block, rcfg.tower_split_field)
        user_rows = []
        for i in range(min(8, block.num_samples)):
            lo, hi = int(block.row_ptr[i]), int(block.row_ptr[i + 1])
            ks = block.keys[lo:hi].astype(np.int64)
            ss = block.slots[lo:hi].astype(np.int32)
            sel = ss < rcfg.tower_split_field
            user_rows.append((ks[sel], ss[sel], None))
        export_item_index(
            PredictEngine.load(rart, warm=False, buckets=BUCKETS),
            rart,
            items,
        )
        # -- top-k parity: device scan vs numpy full-scan argsort -------
        eng = PredictEngine.load(
            rart, warm=True, buckets=BUCKETS, topk_k=TOPK_K
        )
        n = len(user_rows)
        prepared = pad_batch_rows(
            eng._prepare(eng.featurize_raw(user_rows)), eng.bucket_for(n)
        )
        ids, scores, u = eng.topk_prepared(prepared)
        ids, scores, u = ids[:n], scores[:n], u[:n]
        full = u @ eng.item_index["item_index"].T  # numpy full scan
        ref_order = np.argsort(-full, axis=1, kind="stable")[:, :TOPK_K]
        ref_ids = eng.item_index["item_ids"][ref_order]
        ref_scores = np.take_along_axis(full, ref_order, axis=1)
        if np.abs(ref_scores - scores).max() > 1e-6:
            errors.append(
                "top-k parity: device scores differ from the numpy "
                f"full scan by {np.abs(ref_scores - scores).max()}"
            )
        # id sets must match per row (ties may order differently, so
        # compare as sets where scores tie, exact where they don't)
        for r in range(n):
            if set(ids[r]) != set(ref_ids[r]) and not np.allclose(
                scores[r], ref_scores[r], atol=1e-6
            ):
                errors.append(f"top-k parity: row {r} id set mismatch")
        # -- stage 2: dcn ranker ----------------------------------------
        kcfg = Config(model="dcn", **common)
        ktr = Trainer(kcfg)
        ktr.train()
        kart = export_artifact(ktr, os.path.join(root, "ranking"))

        # -- C-ABI surface: the new families point-score through
        # capi_impl (registry-routed — an unknown family would refuse
        # with the registered-families list); top-k stays RPC-only
        from xflow_tpu import capi_impl

        with open(ds.test_prefix + "-00000") as f:
            line = f.readline().strip()
        for art in (rart, kart):
            capi_engine = capi_impl.engine_create(art)
            p = capi_impl.engine_score_line(capi_engine, line)
            if not 0.0 <= p <= 1.0:
                errors.append(f"capi engine_score_line({art}) gave {p}")

        # -- cascade tier over HTTP -------------------------------------
        metrics = os.path.join(root, "cascade.jsonl")
        logger = MetricsLogger(metrics, run_header={
            "run_id": "cascade-smoke",
            "config_digest": "smoke",
            "rank": 0,
            "num_hosts": 1,
        })
        # generous admission budgets: a CPU toy device call is tens of
        # ms, so production-default deadline budgets would shed this
        # healthy traffic — the smoke asserts FULL service, and the
        # shed path has its own coverage (tests/test_serve.py)
        admission = dict(deadline_budget_ms=5000.0, depth_budget=1024)
        retrieval = ReplicaFleet.load(
            rart, replicas=2, buckets=BUCKETS, topk=True, topk_k=TOPK_K,
            metrics_logger=logger, **admission,
        )
        ranking = ReplicaFleet.load(
            kart, replicas=2, buckets=BUCKETS, metrics_logger=logger,
            **admission,
        )
        retrieval.log_load(rart)
        ranking.log_load(kart)
        cascade = CascadeEngine(
            retrieval, ranking, k=K, metrics_logger=logger
        )
        tier = ServeTier(ranking, port=0, cascade=cascade).start()
        host, port = "127.0.0.1", tier.port

        def fleet_compiles() -> int:
            return (
                retrieval.engines[0].compile_count
                + ranking.engines[0].compile_count
            )

        compiles_warm = fleet_compiles()

        # -- mixed zipf traffic: single-row scores + cascade top-k ------
        rng = np.random.default_rng(3)
        score_rows = zipf_rows(
            rng, 40, table_size=kcfg.table_size, nnz=8,
            max_fields=kcfg.max_fields,
        )
        rec_rows = [user_rows[i % len(user_rows)] for i in range(20)]
        fails: list[str] = []
        lock = threading.Lock()
        k_returned: list[int] = []

        def post(conn, path, body, ctype):
            conn.request("POST", path, body=body,
                         headers={"Content-Type": ctype})
            r = conn.getresponse()
            return r.status, r.read()

        def score_worker(rows) -> None:
            conn = http.client.HTTPConnection(host, port, timeout=30)
            try:
                for row in rows:
                    st, payload = post(
                        conn, "/v1/score_packed",
                        encode_packed_request([row]),
                        "application/octet-stream",
                    )
                    if st != 200:
                        with lock:
                            fails.append(f"score HTTP {st}: {payload[:120]!r}")
                        continue
                    decode_packed_response(payload)
            except Exception as e:
                with lock:
                    fails.append(f"score worker: {type(e).__name__}: {e}")
            finally:
                conn.close()

        def rec_worker(rows) -> None:
            conn = http.client.HTTPConnection(host, port, timeout=30)
            try:
                for keys, slots, _ in rows:
                    st, payload = post(
                        conn, "/v1/recommend",
                        json.dumps({
                            "keys": [int(x) for x in keys],
                            "slots": [int(x) for x in slots],
                            "k": K,
                        }).encode(),
                        "application/json",
                    )
                    if st != 200:
                        with lock:
                            fails.append(f"recommend HTTP {st}: {payload[:120]!r}")
                        continue
                    doc = json.loads(payload.decode())
                    with lock:
                        k_returned.append(len(doc["items"]))
            except Exception as e:
                with lock:
                    fails.append(f"recommend worker: {type(e).__name__}: {e}")
            finally:
                conn.close()

        threads = [
            threading.Thread(target=score_worker, args=(score_rows[0::2],)),
            threading.Thread(target=score_worker, args=(score_rows[1::2],)),
            threading.Thread(target=rec_worker, args=(rec_rows[0::2],)),
            threading.Thread(target=rec_worker, args=(rec_rows[1::2],)),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        errors.extend(fails)
        if len(k_returned) != len(rec_rows):
            errors.append(
                f"only {len(k_returned)}/{len(rec_rows)} recommend "
                "responses arrived"
            )
        if any(n != K for n in k_returned):
            errors.append(
                f"candidate starvation: k_returned {sorted(set(k_returned))} "
                f"!= requested {K}"
            )
        if fleet_compiles() != compiles_warm:
            errors.append(
                f"fleet-wide recompile under mixed traffic: "
                f"{compiles_warm} -> {fleet_compiles()}"
            )

        # -- independent staged rollout of the ranking stage ------------
        ro = ranking.begin_rollout(kart, canary_frac=0.5,
                                   min_canary_requests=4)
        del ro
        for keys, slots, _ in rec_rows[:8]:
            cascade.recommend(np.asarray(keys), slots)
        ranking.commit_rollout()
        if retrieval.rollout_state() is not None:
            errors.append("retrieval stage saw the ranking rollout")
        if fleet_compiles() != compiles_warm:
            errors.append("rollout of a same-digest artifact recompiled")

        cascade.emit_stats()
        tier.close()
        logger.close()

        rows = load_jsonl(metrics)
        schema_errors = validate_rows(rows)
        errors.extend(f"schema: {e}" for e in schema_errors)
        kinds = {r.get("kind") for r in rows}
        for want in ("cascade", "serve_load", "rollout"):
            if want not in kinds:
                errors.append(f"metrics stream missing kind {want!r}")
        crows = [r for r in rows if r.get("kind") == "cascade"]
        if not any(int(r.get("requests", 0)) > 0 for r in crows):
            errors.append("no cascade row with requests > 0")
        if any(int(r.get("starved", 0)) for r in crows):
            errors.append("cascade rows report starvation on k <= index")
        if any(int(r.get("errors", 0)) for r in crows):
            errors.append("cascade rows report stage errors")
        bad = [
            d for d in diagnose(rows)
            if d.severity in ("crit", "warn")
            and d.code in ("candidate_starvation", "cascade_errors")
        ]
        errors.extend(
            f"doctor: [{d.severity}] {d.code}: {d.message}" for d in bad
        )

    if errors:
        print("check_cascade_smoke: FAIL", file=sys.stderr)
        for e in errors:
            print(f"  - {e}", file=sys.stderr)
        return 1
    print(
        "check_cascade_smoke: OK (top-k parity 1e-6, 0 errors, "
        f"0 recompiles under mixed traffic, {K}-candidate slates, "
        "ranking rollout committed independently, cascade rows "
        "schema-valid)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
