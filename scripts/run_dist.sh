#!/usr/bin/env bash
# Multi-host pod launch — the counterpart of the reference's
# run_ps_dist.sh / scripts/start_{scheduler,server,worker}.sh manual
# role bootstrap.  Run this same script on EVERY host of the pod; the
# scheduler's job (rendezvous) is done by JAX's coordinator.
#
# Required env:
#   XF_COORDINATOR   host:port of process 0 (any reachable port there)
#   XF_NUM_PROCESSES total number of hosts
#   XF_PROCESS_ID    this host's index, 0-based
#
# Each host reads the shard subset {i : i % NUM_PROCESSES == PROCESS_ID}
# of TRAIN_PREFIX-%05d — the same shard-per-worker layout as the
# reference (lr_worker.cc:210).
#
# Usage: scripts/run_dist.sh TRAIN_PREFIX TEST_PREFIX [MODEL] [EPOCHS]
set -euo pipefail
cd "$(dirname "$0")/.."

TRAIN=${1:?train shard prefix required}
TEST=${2:?test shard prefix required}
MODEL=${3:-lr}
EPOCHS=${4:-60}

exec python -m xflow_tpu.train \
  --model "$MODEL" \
  --train "$TRAIN" \
  --test "$TEST" \
  --epochs "$EPOCHS" \
  --coordinator "${XF_COORDINATOR:?}" \
  --num-processes "${XF_NUM_PROCESSES:?}" \
  --process-id "${XF_PROCESS_ID:?}" \
  "${@:5}"
