#!/bin/bash
# Round-5 TPU measurement queue, part 4 — what remains of part 2 after
# the 04:06 UTC 2026-07-31 tunnel drop left tpu_session2.sh hung inside
# step 1b (hybrid sparse+hot).  Parts 4 of part 2 (t28) and 6 (fm/mvm
# wall-to-AUC) are superseded by tpu_session3.sh's hot-inner runs; this
# script holds the rest.  Run AFTER tpu_session3.sh.
# NO timeouts around TPU-bound processes (verify skill).
set -u
cd "$(dirname "$0")/.."
OUT="${1:-/tmp/tpu_r5d}"
mkdir -p "$OUT"
log() { echo "[$(date -u +%H:%M:%S)] $*"; }

log "1/3 reference-shaped e2e on TPU: CLI train over the binary cache + ckpt + resume"
rm -rf /tmp/ck_tpu /tmp/pred_tpu.txt
python -m xflow_tpu.train --model lr \
    --train /tmp/xflow_conv/bin.train --test /tmp/xflow_conv/bin.test \
    --epochs 2 --batch-size 131072 --table-size-log2 24 --max-nnz 40 \
    --hot-size-log2 12 --hot-nnz 32 --num-devices 1 \
    --checkpoint-dir /tmp/ck_tpu --metrics-out "$OUT/e2e_train_metrics.jsonl" \
    >"$OUT/e2e_train.out" 2>"$OUT/e2e_train.err"
tail -3 "$OUT/e2e_train.out"
python -m xflow_tpu.train --model lr \
    --train /tmp/xflow_conv/bin.train --test /tmp/xflow_conv/bin.test \
    --epochs 3 --batch-size 131072 --table-size-log2 24 --max-nnz 40 \
    --hot-size-log2 12 --hot-nnz 32 --num-devices 1 \
    --checkpoint-dir /tmp/ck_tpu --resume \
    >"$OUT/e2e_resume.out" 2>"$OUT/e2e_resume.err"
tail -3 "$OUT/e2e_resume.out"

log "2/3 lr flagship neighbors (cold-nnz 12, bf16 hot)"
python scripts/bench_models.py --model lr --batch-log2 17 \
    --hot-log2 12 --cold-nnz 12 \
    >>"$OUT/lr_neighbors.out" 2>>"$OUT/lr_neighbors.err"
python scripts/bench_models.py --model lr --batch-log2 17 \
    --hot-log2 12 --hot-dtype bfloat16 \
    >>"$OUT/lr_neighbors.out" 2>>"$OUT/lr_neighbors.err"
tail -2 "$OUT/lr_neighbors.out"

log "3/3 D>1 hot-head scaling: fm/mvm/wide_deep hot {15,16} + bf16"
for m in fm mvm wide_deep; do
  for h in 15 16; do
    python scripts/bench_models.py --model "$m" --batch-log2 17 \
        --hot-log2 "$h" \
        >>"$OUT/models_sweep.out" 2>>"$OUT/models_sweep.err"
  done
  python scripts/bench_models.py --model "$m" --batch-log2 17 \
      --hot-log2 14 --hot-dtype bfloat16 \
      >>"$OUT/models_sweep.out" 2>>"$OUT/models_sweep.err"
done
tail -9 "$OUT/models_sweep.out"

log "3b/3 ffm per-table hot (w on MXU, v on DMA — first hot geometry)"
for h in 12 14 15; do
  python scripts/bench_models.py --model ffm --batch-log2 17 \
      --hot-log2 "$h" \
      >>"$OUT/ffm_hot.out" 2>>"$OUT/ffm_hot.err"
done
tail -3 "$OUT/ffm_hot.out"

log "queue complete — results in $OUT"
