"""Chaos gate (tier-1): the self-healing fabric under a SEEDED fault
schedule must be indistinguishable — in model outputs — from the
fault-free run, with every injected fault accounted for
(docs/ROBUSTNESS.md).

Part 1 — train → checkpoint → kill → auto-resume → export:

* **ref**: lr, 2 epochs, no chaos → artifact → P_ref.
* **run A**: checkpointing on, ``ckpt.finalize:nth=2`` armed — the
  epoch-0 generation commits (hit 1), the epoch-1 save is KILLED
  mid-commit (hit 2: manifest written, rename never runs).  The run
  dies on the injected fault (``checkpoint_save_failed`` health row,
  flight dump, crash-path close); only a ``.tmp-ckpt-*`` is left and
  the epoch-0 generation stays the newest complete one.
* **corruption**: a manifest-less ``ckpt-9999999999`` dir simulates an
  externally truncated generation (the one failure the commit protocol
  itself can never produce).
* **run B**: a fresh trainer, ``restore(auto=True)`` — skips the
  corrupt generation (``checkpoint_fallback`` health row), restores
  the epoch-0 generation, retrains epoch 1 with
  ``loader.read_block:nth=1`` armed (transient read, healed by the
  bounded retry — ``recovered:io_retry`` health row), exports.
* **gate**: P_chaos within 1e-6 of P_ref; every registry fire has a
  matching ``chaos`` JSONL row; every armed site has its healing
  ``health`` row; zero leaked threads.

Part 2 — loadgen-driven fleet under scoring faults:

* a 2-replica fleet scores a fixed probe set fault-free → S_ref;
* a second fleet (``evict_after_errors=1``) runs open-loop zipf load
  with ``serve.replica_score:p=1,times=2`` armed: the poisoned batches
  error, the owning replicas are EVICTED from routing
  (``replica_evicted``), background revives re-clone them from the
  shared artifact (``replica_revived``);
* **gate**: the fleet returns to full health, the probe set scores
  within 1e-6 of S_ref, evictions == revivals, chaos rows match
  registry fires, zero leaked threads.

Run from the repo root:

    JAX_PLATFORMS=cpu python scripts/check_chaos.py

Wired into tier-1 via tests/test_chaos.py::test_check_chaos_script.
"""

from __future__ import annotations

import os
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

PARITY_ATOL = 1e-6
# thread-name prefixes this repo's fabrics own — none may survive
_THREAD_PREFIXES = (
    "store-promote", "xflow-serve", "xflow-replica-revive",
    "xflow-loadgen", "xflow-obs-watchdog",
)


def _leaked_threads() -> list[str]:
    return sorted(
        t.name for t in threading.enumerate()
        if t.is_alive() and t.name.startswith(_THREAD_PREFIXES)
    )


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from tests.gen_data import generate_dataset
    from xflow_tpu import chaos
    from xflow_tpu.config import Config
    from xflow_tpu.obs.schema import load_jsonl, validate_rows
    from xflow_tpu.serve.artifact import export_artifact
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.serve.fleet import ReplicaFleet
    from xflow_tpu.serve.loadgen import run_loadgen
    from xflow_tpu.trainer import Trainer
    from xflow_tpu.utils.logging import MetricsLogger

    errors: list[str] = []
    expected_fires: dict[str, int] = {}

    with tempfile.TemporaryDirectory() as root:
        ds = generate_dataset(
            os.path.join(root, "data"),
            num_train_shards=2,
            lines_per_shard=200,
            num_fields=10,
            vocab_per_field=8,
            seed=11,
            scale=3.0,
        )
        base = dict(
            train_path=ds.train_prefix,
            test_path=ds.test_prefix,
            model="lr",
            epochs=2,
            batch_size=64,
            table_size_log2=16,
            max_nnz=24,
            num_devices=1,
            parse_workers=1,  # deterministic failpoint hit order
        )
        rng = np.random.default_rng(0)
        probes = [
            rng.integers(0, 1 << 16, size=int(rng.integers(1, 12)))
            for _ in range(64)
        ]

        # -- part 1: train / checkpoint / kill / auto-resume ---------------
        chaos.disarm()
        ref = Trainer(Config(**base))
        ref.train()
        art_ref = export_artifact(ref, os.path.join(root, "art_ref"))
        ref.close()
        eng_ref = PredictEngine.load(art_ref, buckets=(64,), warm=False)
        p_ref = eng_ref.predict(eng_ref.featurize_raw(probes))

        ck = os.path.join(root, "ck")
        metrics = os.path.join(root, "train.jsonl")
        cfg_a = Config(
            checkpoint_dir=ck,
            metrics_out=metrics,
            chaos_spec="seed=3;ckpt.finalize:nth=2",
            **base,
        )
        trainer_a = Trainer(cfg_a)
        reg_a = chaos.armed()  # close() disarms config-armed schedules
        died = None
        try:
            trainer_a.train()
        except chaos.ChaosError as e:
            died = e
        finally:
            trainer_a.close()
        if died is None:
            errors.append(
                "run A survived the ckpt.finalize kill — the failpoint "
                "never fired or the save swallowed it"
            )
        for site, n in reg_a.fired().items():
            expected_fires[site] = expected_fires.get(site, 0) + n
        from xflow_tpu.utils.checkpoint import latest_complete

        gens = [d for d in os.listdir(ck) if d.startswith("ckpt-")]
        if len(gens) != 1:
            errors.append(
                f"expected exactly the epoch-0 generation after the "
                f"kill mid-commit (the epoch-1 save must never have "
                f"become visible), found {sorted(gens)}"
            )
        gen_a = latest_complete(ck)
        if gen_a is None:
            errors.append(
                "latest_complete found nothing after the kill — the "
                "epoch-0 generation should have survived"
            )

        # externally truncated generation: a committed-looking dir with
        # no manifest (the commit protocol can never produce this)
        os.makedirs(os.path.join(ck, "ckpt-9999999999"))

        cfg_b = cfg_a.replace(
            chaos_spec="seed=3;loader.read_block:nth=1"
        )
        trainer_b = Trainer(cfg_b)
        reg_b = chaos.armed()
        cursor = trainer_b.restore(auto=True)
        if cursor is None or int(cursor.get("epoch", -1)) != 1:
            errors.append(
                f"--resume auto restored cursor {cursor}, expected the "
                "complete epoch-0 generation (epoch 1 start)"
            )
        trainer_b.train()
        art_b = export_artifact(trainer_b, os.path.join(root, "art_b"))
        trainer_b.close()
        for site, n in reg_b.fired().items():
            expected_fires[site] = expected_fires.get(site, 0) + n
        chaos.disarm()

        eng_b = PredictEngine.load(art_b, buckets=(64,), warm=False)
        p_b = eng_b.predict(eng_b.featurize_raw(probes))
        worst_train = float(np.abs(p_b - p_ref).max())
        if not np.allclose(p_b, p_ref, atol=PARITY_ATOL):
            errors.append(
                f"kill→auto-resume→export diverged from the fault-free "
                f"run (max |diff| {worst_train:.2e} > {PARITY_ATOL})"
            )

        rows = load_jsonl(metrics)
        errors.extend(validate_rows(rows))
        by_site: dict[str, int] = {}
        for r in rows:
            if r.get("kind") == "chaos":
                by_site[r["site"]] = by_site.get(r["site"], 0) + 1
        causes: dict[str, int] = {}
        for r in rows:
            if r.get("kind") == "health":
                causes[r["cause"]] = causes.get(r["cause"], 0) + 1
        dropped = reg_a.dropped_rows() + reg_b.dropped_rows()
        for site, n in expected_fires.items():
            if by_site.get(site, 0) != n:
                errors.append(
                    f"fault accounting: site {site} fired {n}x but "
                    f"{by_site.get(site, 0)} chaos row(s) logged "
                    f"({dropped} row(s) dropped at logging)"
                )
        # every injected fault pairs with the row of the layer that
        # healed (or loudly reported) it
        pairs = {
            "ckpt.finalize": "checkpoint_save_failed",
            "loader.read_block": "recovered:io_retry",
        }
        for site, cause in pairs.items():
            if expected_fires.get(site) and not causes.get(cause):
                errors.append(
                    f"fault at {site} has no matching {cause!r} health "
                    "row — the heal was silent"
                )
        if not causes.get("checkpoint_fallback"):
            errors.append(
                "restore auto skipped the corrupt generation without a "
                "checkpoint_fallback health row"
            )
        n_train_rows = len(rows)

        # -- part 2: fleet under scoring faults ----------------------------
        fleet_ref = ReplicaFleet.load(
            art_ref, replicas=2, buckets=(1, 8), warm=False
        )
        s_ref = np.asarray([fleet_ref.score(k) for k in probes])
        fleet_ref.close()

        serve_metrics = os.path.join(root, "serve.jsonl")
        logger = MetricsLogger(serve_metrics, run_header={
            "run_id": "chaos-gate-serve",
            "config_digest": fleet_ref.digest,
            "rank": 0,
            "num_hosts": 1,
            "model": "lr",
        })
        reg = chaos.arm("seed=5;serve.replica_score:p=1,times=2")
        chaos.attach_logger(logger)
        fleet = ReplicaFleet.load(
            art_ref, replicas=2, buckets=(1, 8), warm=False,
            metrics_logger=logger, evict_after_errors=1,
        )
        summary = run_loadgen(
            fleet,
            offered_qps=100.0,
            duration_s=1.0,
            concurrency=4,
            nnz=8,
            zipf_a=1.3,
            seed=0,
            metrics_logger=logger,
        )
        # wait for the background revives to land
        deadline = time.perf_counter() + 20.0
        while time.perf_counter() < deadline:
            health = fleet.health()
            if not health["unhealthy"] and (
                health["revivals"] >= health["evictions"]
            ):
                break
            time.sleep(0.05)
        health = fleet.health()
        fires = reg.fired().get("serve.replica_score", 0)
        if fires < 1:
            errors.append("serve.replica_score never fired under load")
        if summary["errors"] < 1:
            errors.append(
                "injected scoring faults produced no client-visible "
                "errors — they were silently swallowed somewhere"
            )
        if health["evictions"] < 1:
            errors.append(
                f"no replica eviction despite {fires} scoring fault(s) "
                f"at evict_after_errors=1 (health {health})"
            )
        if health["unhealthy"] or health["revivals"] < health["evictions"]:
            errors.append(
                f"fleet did not return to full health: {health}"
            )
        s_chaos = np.asarray([fleet.score(k) for k in probes])
        worst_serve = float(np.abs(s_chaos - s_ref).max())
        if not np.allclose(s_chaos, s_ref, atol=PARITY_ATOL):
            errors.append(
                f"post-revive fleet scores diverge from the fault-free "
                f"fleet (max |diff| {worst_serve:.2e} > {PARITY_ATOL})"
            )
        fleet.close()
        chaos.detach_logger(logger)
        chaos.disarm()
        logger.close()

        srows = load_jsonl(serve_metrics)
        errors.extend(validate_rows(srows))
        n_chaos_rows = sum(
            1 for r in srows
            if r.get("kind") == "chaos"
            and r.get("site") == "serve.replica_score"
        )
        if n_chaos_rows != fires:
            errors.append(
                f"serve fault accounting: {fires} fires vs "
                f"{n_chaos_rows} chaos row(s) "
                f"({reg.dropped_rows()} dropped at logging)"
            )
        scauses: dict[str, int] = {}
        for r in srows:
            if r.get("kind") == "health":
                scauses[r["cause"]] = scauses.get(r["cause"], 0) + 1
        if scauses.get("replica_evicted", 0) != health["evictions"]:
            errors.append(
                f"{health['evictions']} eviction(s) vs "
                f"{scauses.get('replica_evicted', 0)} replica_evicted "
                "health row(s)"
            )
        if scauses.get("replica_revived", 0) != health["revivals"]:
            errors.append(
                f"{health['revivals']} revival(s) vs "
                f"{scauses.get('replica_revived', 0)} replica_revived "
                "health row(s)"
            )

        leaked = _leaked_threads()
        if leaked:
            errors.append(f"leaked thread(s) survived the runs: {leaked}")

    for e in errors:
        print(f"FAIL: {e}", file=sys.stderr)
    if errors:
        return 1
    print(
        f"OK: kill→auto-resume parity max|diff|={worst_train:.1e}; "
        f"fleet evict/revive parity max|diff|={worst_serve:.1e} "
        f"({health['evictions']} evicted, {health['revivals']} revived, "
        f"{summary['errors']} client error(s) under load); "
        f"{sum(expected_fires.values()) + fires} injected fault(s) all "
        f"accounted for; {n_train_rows}+{len(srows)} metrics rows "
        "validated; no leaked threads"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
