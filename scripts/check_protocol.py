"""Wire-protocol gate: the XF016–XF020 static pass PLUS the seeded
decoder fuzzer, pre-gating the pod-scale store (ROADMAP item 2) and
the persistent binary serve transport (ROADMAP item 5) before those
formats cross real sockets and failure domains.

Run from the repo root:

    python scripts/check_protocol.py
    python scripts/check_protocol.py --write-registry   # after a
        deliberate wire-format change (version/magic bump)

Two halves, both must pass:

1. **Static** — ``xflow_tpu.analysis`` with the five protocol rules
   (XF016 codec parity + registry fingerprints, XF017 blocking-I/O
   timeouts, XF018 failpoint coverage, XF019 determinism taint, XF020
   explicit endianness — docs/ANALYSIS.md) over the whole package
   against the committed baseline, same contract as
   scripts/check_analysis.py.  The wire fingerprints (magic
   constants, format-version constants, struct format strings per
   module) are pinned by ``protocol-registry.json``: an unregistered
   format change fails here, and ``--write-registry`` is the explicit
   "yes, I bumped the version" acknowledgement that refreshes it.
2. **Runtime** — analysis/wirefuzz.py drives every wire decoder
   (XFS1, XFS2, packed-v2, binary CSR, delta manifest) through
   ``FUZZ_ROUNDS`` seeded structure-aware mutations each; any untyped
   exception, over-budget case, or accepted-but-rewritten payload
   fails the gate.

Wired into tier-1 via tests/test_analysis.py, next to
check_analysis.py / check_concurrency.py / check_memory.py.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

PROTOCOL_RULES = ["XF016", "XF017", "XF018", "XF019", "XF020"]

# fixed gate seed + per-decoder mutation count (acceptance bar: >= 200
# mutations per decoder; keep a margin over it)
FUZZ_SEED = 0xC0FFEE
FUZZ_ROUNDS = 220


def write_registry(package: str, registry_path: str) -> int:
    from xflow_tpu.analysis.core import PackageIndex
    from xflow_tpu.analysis.rules_protocol import build_registry

    modules = build_registry(PackageIndex([package]))
    doc = {
        "comment": (
            "Wire-format fingerprints per module (magic constants, "
            "format-version constants, struct format strings) — the "
            "XF016 registry.  A format change MUST come with a "
            "version/magic bump and a refresh via "
            "`python scripts/check_protocol.py --write-registry`; "
            "an unregistered drift fails scripts/check_protocol.py."
        ),
        "modules": modules,
    }
    with open(registry_path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    print(
        f"wrote {os.path.relpath(registry_path, REPO)}: "
        f"{len(modules)} wire module(s)"
    )
    return 0


def check_static(package: str, baseline_path: str) -> int:
    from xflow_tpu.analysis import (
        load_baseline,
        render_text,
        run_analysis,
        split_baselined,
    )

    findings, pragma_suppressed = run_analysis(
        [package], select=PROTOCOL_RULES
    )
    entries = [
        e
        for e in load_baseline(baseline_path)
        if e["rule"] in PROTOCOL_RULES
    ]
    new, grandfathered, stale = split_baselined(findings, entries)
    print(render_text(new, grandfathered, pragma_suppressed, stale))
    if new:
        return 1
    if stale:
        print(
            "FAIL: stale baseline entries (prune analysis-baseline.json)",
            file=sys.stderr,
        )
        return 1
    return 0


def check_runtime() -> int:
    """Every wire decoder under the seeded fuzzer: typed errors only,
    no hang, no silently-rewritten accepted payload."""
    from xflow_tpu.analysis.wirefuzz import render_report, run_wirefuzz

    report = run_wirefuzz(seed=FUZZ_SEED, rounds=FUZZ_ROUNDS)
    print(render_report(report))
    if not report["ok"]:
        print(
            "FAIL: a wire decoder raised an untyped error, blew the "
            "per-case budget, or silently accepted a rewritten "
            "payload (see failures above)",
            file=sys.stderr,
        )
        return 1
    print(
        f"OK: {len(report['targets'])} decoder(s) x {FUZZ_ROUNDS} "
        "mutation(s) — typed refusals only"
    )
    return 0


def main(argv: list[str]) -> int:
    package = os.path.join(REPO, "xflow_tpu")
    baseline = os.path.join(REPO, "analysis-baseline.json")
    registry = os.path.join(REPO, "protocol-registry.json")
    if "--write-registry" in argv:
        return write_registry(package, registry)
    rc = check_static(package, baseline)
    rc = check_runtime() or rc
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
