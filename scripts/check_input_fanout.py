"""Input fan-out gate: the N-stream sharded reader (io/fanout.py) must
be invisible to training except for speed.

Four invariants, all on a toy packed-v2 corpus (ISSUE 14 / ROADMAP 1):

1. **Bitwise stream identity** — the batch sequence a 4-stream pool
   merges (order, resume offsets, every compact plane) is identical to
   the 1-stream pool's, which is identical to the serial loaders'.
2. **Bitwise train identity** — a Trainer at ``input_streams=4`` (deep
   staging ring) ends an epoch with exactly the state of the serial
   trainer, and emits schema-valid per-stream ``stream`` rows.
3. **Zero thread leaks (XF006)** — every stream producer and ring
   worker is joined by the time the pool/trainer closes.
4. **Lock-order sanity (XF007 runtime)** — the fan-out trainer runs
   with the lock-order sanitizer armed; observed acquisition orders
   must not contradict the static lock graph.

Run from the repo root:

    JAX_PLATFORMS=cpu python scripts/check_input_fanout.py

Wired into tier-1 via tests/test_fanout.py::test_check_input_fanout_script.
"""

from __future__ import annotations

import os
import sys
import tempfile
import threading

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

PLANES = (
    "cu", "ci", "ct", "cf", "cc", "h8", "hx", "hxh", "hf", "hc",
    "lb", "wb", "cs", "hs",
)
COUNTS = (
    "n_real", "n_cold", "n_dict", "n_dict_occ", "n_hot", "n_h8",
    "slots_code",
)


def build_corpus(root: str) -> list[str]:
    """Toy packed-v2 corpus: 6 text shards converted shard-for-shard."""
    from tests.gen_data import generate_dataset
    from xflow_tpu.io import packed

    ds = generate_dataset(
        os.path.join(root, "data"),
        num_train_shards=6,
        lines_per_shard=180,
        num_fields=10,
        vocab_per_field=8,
        seed=13,
        scale=3.0,
    )
    paths = []
    for i in range(6):
        src = f"{ds.train_prefix}-{i:05d}"
        dst = os.path.join(root, f"corpus.pk-{i:05d}")
        packed.convert_shard(
            src, dst, fmt="v2", batch_size=64, max_nnz=24,
            table_size=1 << 14,
        )
        paths.append(dst)
    return paths


def _loader(path: str):
    from xflow_tpu.io.loader import ShardLoader

    return ShardLoader(
        path, batch_size=64, max_nnz=24, table_size=1 << 14,
        emit_compact=True,
    )


def _collect(shards: list[str], num_streams: int) -> list[tuple]:
    from xflow_tpu.io.fanout import ShardStreamPool

    pool = ShardStreamPool(shards, _loader, num_streams=num_streams, depth=2)
    try:
        return [(si, resume, cb) for cb, si, resume in pool]
    finally:
        pool.close()


def check_stream_identity(shards: list[str]) -> list[str]:
    errors = []
    serial = []
    for si, path in enumerate(shards):
        for cb, resume in _loader(path).iter_batches():
            serial.append((si, resume, cb))
    for n in (1, 4):
        got = _collect(shards, n)
        if len(got) != len(serial):
            errors.append(
                f"N={n}: {len(got)} batches vs {len(serial)} serial"
            )
            continue
        for i, ((sa, ra, ca), (sb, rb, cb)) in enumerate(zip(serial, got)):
            if (sa, ra) != (sb, rb):
                errors.append(
                    f"N={n} batch {i}: (shard, resume) ({sb}, {rb}) != "
                    f"serial ({sa}, {ra})"
                )
                break
            for fld in COUNTS:
                if getattr(ca, fld) != getattr(cb, fld):
                    errors.append(f"N={n} batch {i}: count {fld} differs")
            for pl in PLANES:
                if not np.array_equal(getattr(ca, pl), getattr(cb, pl)):
                    errors.append(f"N={n} batch {i}: plane {pl} differs")
    return errors


def _train(root: str, train_prefix: str, streams: int, metrics: str = ""):
    import jax

    from xflow_tpu.config import Config
    from xflow_tpu.trainer import Trainer

    cfg = Config(
        model="lr", train_path=train_prefix, epochs=1, batch_size=32,
        table_size_log2=14, max_nnz=24, num_devices=1,
        input_streams=streams, transfer_ahead_depth=3,
        metrics_out=metrics, obs_lock_sanitizer=bool(metrics),
    )
    with Trainer(cfg) as t:
        t.train_epoch()
        return jax.device_get(t.state)


def check_train_identity(root: str) -> list[str]:
    """Serial vs 4-stream Trainer: bitwise state, schema-valid stream
    rows, sanitizer-clean lock orders."""
    import jax.tree_util as tu

    from xflow_tpu.analysis import static_lock_order
    from xflow_tpu.analysis.sanitizer import global_sanitizer
    from xflow_tpu.obs.schema import load_jsonl, validate_rows

    errors = []
    prefix = os.path.join(root, "data", "toy_train")
    metrics = os.path.join(root, "fanout-metrics.jsonl")
    state1 = _train(root, prefix, streams=1)
    state4 = _train(root, prefix, streams=4, metrics=metrics)
    for i, (a, b) in enumerate(
        zip(tu.tree_leaves(state1), tu.tree_leaves(state4))
    ):
        if not np.array_equal(np.asarray(a), np.asarray(b)):
            errors.append(
                f"state leaf {i}: input_streams=4 differs from serial"
            )
    rows = load_jsonl(metrics)
    errors += validate_rows(rows)
    stream_rows = [r for r in rows if r.get("kind") == "stream"]
    if len(stream_rows) < 2:
        errors.append(
            f"expected >= 2 per-stream rows, got {len(stream_rows)}"
        )
    if sum(r.get("batches", 0) for r in stream_rows) <= 0:
        errors.append("stream rows carry no batches")
    if sum(r.get("shards", 0) for r in stream_rows) != 6:
        errors.append("stream rows do not cover the 6-shard corpus")
    san = global_sanitizer()
    contradictions = san.contradictions(static_lock_order(["xflow_tpu"]))
    for c in contradictions:
        errors.append(f"observed lock order contradicts XF007: {c}")
    return errors


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    before = {
        th.ident for th in threading.enumerate() if th.is_alive()
    }
    with tempfile.TemporaryDirectory() as root:
        shards = build_corpus(root)
        errors = check_stream_identity(shards)
        errors += check_train_identity(root)
    import time

    deadline = time.time() + 15.0
    while time.time() < deadline:
        leaked = [
            th
            for th in threading.enumerate()
            if th.is_alive() and th.ident not in before
        ]
        if not leaked:
            break
        time.sleep(0.05)
    else:
        errors.append(
            f"thread leak (XF006): {[th.name for th in leaked]} "
            "outlived the pools/trainers"
        )
    for e in errors:
        print(f"FAIL: {e}", file=sys.stderr)
    if errors:
        return 1
    print(
        "OK: 4-stream fan-out bitwise-identical to serial (pool + "
        "trainer), stream rows schema-valid, zero leaked threads, "
        "lock orders sanitizer-clean"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
