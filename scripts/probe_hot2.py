"""Perf probe 2: two-level one-hot MXU gather/scatter for the hot table.

key = hi*h2 + lo.  Gather: ((oh_hi @ W) * oh_lo).sum(-1) where W is
[h1, h2] (D=1 case) — traffic is M*(h1+h2) instead of M*H.
Scatter: W += oh_hi^T @ (g[:,None] * oh_lo)  — one [h1,M]@[M,h2] matmul.

Run: python scripts/probe_hot2.py
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

M = 131072 * 40
HOT_FRAC = 0.3
MH = int(M * HOT_FRAC)


def timed(fn, *args, iters=10, warmup=3):
    for _ in range(warmup):
        out = fn(*args)
    jax.device_get(jax.tree.leaves(out)[0].ravel()[:1])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.device_get(jax.tree.leaves(out)[0].ravel()[:1])
    return (time.perf_counter() - t0) / iters * 1e3


def run(h1, h2):
    H = h1 * h2
    dev = [d for d in jax.devices() if d.platform != "cpu"][0]
    rng = np.random.default_rng(0)
    keys = jax.device_put(jnp.asarray(rng.integers(0, H, MH).astype(np.int32)), dev)
    g = jax.device_put(jnp.ones((MH,), jnp.float32), dev)
    W = jax.device_put(jnp.asarray(rng.normal(size=(h1, h2)).astype(np.float32)), dev)

    @jax.jit
    def gather2(W, k):
        hi = k // h2
        lo = k % h2
        oh_hi = (hi[:, None] == jnp.arange(h1, dtype=jnp.int32)[None, :]).astype(jnp.bfloat16)
        oh_lo = (lo[:, None] == jnp.arange(h2, dtype=jnp.int32)[None, :]).astype(jnp.bfloat16)
        rows = jnp.dot(oh_hi, W.astype(jnp.bfloat16),
                       preferred_element_type=jnp.float32)  # [M, h2]
        return (rows * oh_lo).sum(-1).sum()

    @jax.jit
    def scatter2(k, g):
        hi = k // h2
        lo = k % h2
        oh_hi = (hi[:, None] == jnp.arange(h1, dtype=jnp.int32)[None, :]).astype(jnp.bfloat16)
        oh_lo = (lo[:, None] == jnp.arange(h2, dtype=jnp.int32)[None, :]).astype(jnp.float32)
        glo = (g[:, None] * oh_lo).astype(jnp.bfloat16)  # [M, h2]
        return jnp.dot(oh_hi.T, glo, preferred_element_type=jnp.float32)

    @jax.jit
    def gather_dma(W, k):
        return W.reshape(-1, 1).at[k].get(mode="clip").sum()

    @jax.jit
    def scatter_dma(W, k, g):
        return jnp.zeros((H, 1), jnp.float32).at[k].add(g[:, None], mode="drop")

    print(f"H={H} ({h1}x{h2}), MH={MH}")
    print(f"  gather  2-level MXU: {timed(gather2, W, keys):7.2f} ms   DMA: {timed(gather_dma, W, keys):7.2f} ms")
    print(f"  scatter 2-level MXU: {timed(scatter2, keys, g):7.2f} ms   DMA: {timed(scatter_dma, W, keys, g):7.2f} ms")


if __name__ == "__main__":
    run(64, 64)
    run(128, 128)
    run(128, 512)
