"""Continuous-training gate (tier-1): the event-to-servable loop must
close END TO END on a toy stream — shards appended DURING training
reach a serving fleet via incremental delta export + staged rollout —
with measured freshness, zero failed requests, chain-verified swaps,
one injected fault absorbed, and zero leaked threads
(docs/CONTINUOUS.md; ISSUE 12).

What runs:

* a writer thread converts toy text shards into packed-v2 shards and
  drops them into the stream directory on a delay — the follower must
  pick them up mid-run (tail mode, not a pre-listed epoch);
* the StreamDriver trains continuously, cutting a base then deltas
  every few steps, each rolled onto a 2-replica fleet through the
  canary health gate with inline probe traffic;
* ``stream.poll:nth=2`` is armed: the second directory poll fails with
  an injected ChaosError and must heal through the bounded retry
  (``recovered:io_retry`` health row + ``chaos`` audit row);
* at every commit the gate exports a from-scratch FULL artifact at the
  same step and scores a fixed probe set through BOTH paths — the
  hot-swapped servable must match at 1e-6 (it is bitwise-equal tables,
  so the tolerance is slack for the scoring pipeline).

Gate conditions: >= 2 rollouts COMMITTED through the canary gate,
freshness rows schema-valid with every commit age under the SLO, the
delta exports measurably incremental (delta bytes < 25% of the base),
zero request errors, fault accounting reconciled, doctor verdict
clean, zero leaked threads.

Run from the repo root:

    JAX_PLATFORMS=cpu python scripts/check_continuous.py

Wired into tier-1 via tests/test_stream.py::test_check_continuous_script.
"""

from __future__ import annotations

import os
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

PARITY_ATOL = 1e-6
FRESHNESS_SLO_S = 30.0
_THREAD_PREFIXES = (
    "store-promote", "xflow-serve", "xflow-replica-revive",
    "xflow-loadgen", "xflow-obs-watchdog",
)


def _leaked_threads() -> list[str]:
    return sorted(
        t.name for t in threading.enumerate()
        if t.is_alive() and t.name.startswith(_THREAD_PREFIXES)
    )


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from tests.gen_data import generate_dataset
    from xflow_tpu import chaos
    from xflow_tpu.config import Config
    from xflow_tpu.io import packed
    from xflow_tpu.obs.doctor import diagnose
    from xflow_tpu.obs.schema import load_jsonl, validate_rows
    from xflow_tpu.serve.artifact import export_artifact
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.stream.driver import StreamDriver

    errors: list[str] = []

    with tempfile.TemporaryDirectory() as root:
        ds = generate_dataset(
            os.path.join(root, "data"),
            num_train_shards=5,
            lines_per_shard=200,
            num_fields=10,
            vocab_per_field=8,
            seed=11,
            scale=3.0,
        )
        stream_dir = os.path.join(root, "stream")
        os.makedirs(stream_dir)

        def pack(i: int) -> None:
            packed.convert_shard(
                f"{ds.train_prefix}-{i:05d}",
                os.path.join(stream_dir, f"shard-{i:05d}.pk"),
                batch_size=64,
                max_nnz=24,
                table_size=1 << 16,
                hash_mode=True,
                hash_seed=0,
                fmt="v2",
            )

        # two shards up front (base + first delta), three appended
        # MID-RUN — the part an epoch loader cannot do
        pack(0)
        pack(1)

        def writer() -> None:
            for i in (2, 3, 4):
                time.sleep(0.9)
                pack(i)

        w = threading.Thread(target=writer, name="gate-shard-writer")

        metrics = os.path.join(root, "run.jsonl")
        cfg = Config(
            model="lr",
            epochs=1,
            batch_size=64,
            table_size_log2=16,
            max_nnz=24,
            num_devices=1,
            parse_workers=1,
            metrics_out=metrics,
            chaos_spec="seed=3;stream.poll:nth=2",
        )
        rng = np.random.default_rng(0)
        probes = [
            rng.integers(0, 1 << 16, size=int(rng.integers(1, 12)))
            for _ in range(32)
        ]
        parity: list[tuple[int, float]] = []

        def on_commit(driver: StreamDriver, info: dict) -> None:
            # the trainer still sits at the committed step: a
            # from-scratch full export here IS "the same step"
            ref_dir = os.path.join(root, f"ref-{info['step']}")
            export_artifact(driver.trainer, ref_dir)
            ref = PredictEngine.load(ref_dir, buckets=(32,), warm=False)
            p_ref = ref.predict(ref.featurize_raw(probes))
            p_fleet = np.asarray(
                [driver.fleet.score(k) for k in probes]
            )
            parity.append(
                (info["step"], float(np.abs(p_fleet - p_ref).max()))
            )

        driver = StreamDriver(
            cfg,
            stream_dir,
            os.path.join(root, "work"),
            replicas=2,
            export_every_steps=4,
            compact_every=3,
            canary_frac=0.5,
            min_canary_requests=6,
            max_error_frac=0.0,
            freshness_slo_s=FRESHNESS_SLO_S,
            rollout_timeout_s=60.0,
            poll_interval_s=0.2,
            idle_stop_s=3.0,
            buckets=(1, 8, 32),
            log=lambda s: print(f"  driver: {s}"),
        )
        driver.on_commit = on_commit
        reg = chaos.armed()
        w.start()
        try:
            summary = driver.run()
        finally:
            w.join(timeout=30)

        # -- loop-level conditions -----------------------------------------
        if summary["commits"] < 2:
            errors.append(
                f"only {summary['commits']} rollout(s) committed "
                "through the canary gate (need >= 2)"
            )
        if summary["shards_ingested"] < 5:
            errors.append(
                f"only {summary['shards_ingested']} of 5 shards "
                "ingested — the follower missed appended files"
            )
        if summary["probe_errors"]:
            errors.append(
                f"{summary['probe_errors']} probe request(s) FAILED"
            )
        fleet_stats = summary.get("fleet") or {}
        shed = fleet_stats.get("shed", {})
        if shed.get("errors"):
            errors.append(
                f"fleet scored {shed['errors']} request error(s) — "
                "the zero-failed-requests condition"
            )

        # -- parity: every swapped servable vs a full export ---------------
        if not parity:
            errors.append("no commit ever reached the parity check")
        for step, worst in parity:
            if worst > PARITY_ATOL:
                errors.append(
                    f"servable at step {step} diverged from the "
                    f"from-scratch full export (max |diff| "
                    f"{worst:.2e} > {PARITY_ATOL})"
                )

        # -- metrics stream: schema, freshness, fault accounting -----------
        rows = load_jsonl(metrics)
        errors.extend(validate_rows(rows))
        fresh = [r for r in rows if r.get("kind") == "freshness"]
        commits = [r for r in fresh if r.get("event") == "commit"]
        if len(commits) < 2:
            errors.append(
                f"{len(commits)} freshness commit row(s) (need >= 2)"
            )
        ages = sorted(
            float(r["newest_event_age_s"]) for r in commits
        )
        over = [a for a in ages if a > FRESHNESS_SLO_S]
        if over:
            errors.append(
                f"{len(over)} commit(s) over the {FRESHNESS_SLO_S}s "
                f"freshness SLO: {over}"
            )
        if ages:
            p50 = ages[len(ages) // 2]
            p99 = ages[min(len(ages) - 1, int(0.99 * len(ages)))]
            print(
                f"  freshness: {len(ages)} commit(s), newest-event-age"
                f" p50={p50:.2f}s p99={p99:.2f}s (SLO {FRESHNESS_SLO_S}s)"
            )
        deltas = [
            r for r in fresh
            if r["export_kind"] == "delta" and r["event"] == "export"
        ]
        bases = [
            r for r in fresh
            if r["export_kind"] == "base" and r["event"] == "export"
        ]
        if deltas and bases:
            ratio = deltas[-1]["delta_bytes"] / bases[-1]["delta_bytes"]
            print(
                f"  delta bytes: {deltas[-1]['delta_bytes']} vs base "
                f"{bases[-1]['delta_bytes']} ({ratio:.1%})"
            )
            if ratio >= 0.25:
                errors.append(
                    f"delta export is {ratio:.1%} of the base — not "
                    "incremental (need < 25%)"
                )
        elif not deltas:
            errors.append("no delta export was ever cut")

        fires = reg.fired() if reg is not None else {}
        if fires.get("stream.poll", 0) < 1:
            errors.append(
                "the stream.poll failpoint never fired — the chaos "
                "schedule did not reach the follower"
            )
        chaos_rows = [
            r for r in rows
            if r.get("kind") == "chaos" and r.get("site") == "stream.poll"
        ]
        if len(chaos_rows) != fires.get("stream.poll", 0):
            errors.append(
                f"stream.poll fired {fires.get('stream.poll', 0)}x "
                f"but {len(chaos_rows)} chaos row(s) logged"
            )
        healed = [
            r for r in rows
            if r.get("kind") == "health"
            and r.get("cause") == "recovered:io_retry"
            and r.get("channel") == "stream"
        ]
        if fires.get("stream.poll") and not healed:
            errors.append(
                "the injected stream.poll fault has no "
                "recovered:io_retry health row — the heal was silent"
            )

        # -- doctor verdict -------------------------------------------------
        findings = diagnose(rows)
        bad = [
            f"{d.code}: {d.message}" for d in findings
            if d.severity in ("crit", "warn")
        ]
        if bad:
            errors.append(
                f"obs doctor is not clean on the stream run: {bad}"
            )

    chaos.disarm()
    time.sleep(0.2)  # let daemon teardown finish before the census
    leaked = _leaked_threads()
    if leaked:
        errors.append(f"leaked threads after close: {leaked}")

    if errors:
        print("check_continuous: FAIL")
        for e in errors:
            print(f"  - {e}")
        return 1
    print(
        "check_continuous: OK — streaming ingestion -> delta export "
        "-> canary-gated hot-swap closed end to end"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
