#!/bin/bash
# Round-5 TPU measurement queue, part 3 — the hot-fine/cold-coarse
# sequential inner (sequential_inner='hot', step.py::_train_sequential_hot),
# built after part 2's first results showed BOTH existing inners miss
# the >=5x north star on wall-clock:
#   dense inner  36.8 s/epoch -> 232.8 s to AUC 0.7401 (3.4x total)
#   sparse inner ~50 s/epoch  -> 395.9 s               (2.33x total)
# The hot inner removes per-slice DMA and full-table streams from the
# scan entirely; the TPU run is ALSO the quality experiment — crossing
# 0.7401 proves the cold-coarsening/staleness cost is absorbed.
# Run when the tunnel is healthy: bash scripts/tpu_session3.sh [outdir]
# NO timeouts around TPU-bound processes (verify skill).
set -u
cd "$(dirname "$0")/.."
OUT="${1:-/tmp/tpu_r5c}"
mkdir -p "$OUT"
log() { echo "[$(date -u +%H:%M:%S)] $*"; }

log "1/4 HEADLINE: time_to_auc lr, hot inner, 2^14 head (CPU rehearsal crossed at epoch 5 — the strongest candidate runs first in case the tunnel is short-lived)"
python scripts/time_to_auc.py --model lr --sequential-inner hot --max-epochs 9 \
    --hot-size-log2 14 --hot-nnz 32 --max-nnz 16 \
    --out docs/artifacts/time_to_auc_lr_hot14.json \
    >"$OUT/ttauc_hot14.out" 2>"$OUT/ttauc_hot14.err"
tail -2 "$OUT/ttauc_hot14.out"

log "2/4 hot inner, flagship geometry (2^12 head; rehearsal says crossing at epoch ~6)"
python scripts/time_to_auc.py --model lr --sequential-inner hot --max-epochs 9 \
    --hot-size-log2 12 --hot-nnz 32 --max-nnz 16 \
    --out docs/artifacts/time_to_auc_lr_hot_flagship.json \
    >"$OUT/ttauc_hot_flag.out" 2>"$OUT/ttauc_hot_flag.err"
tail -2 "$OUT/ttauc_hot_flag.out"

log "2b/4 hot inner, half window (B=65536): halves cold staleness/coarsening"
python scripts/time_to_auc.py --model lr --sequential-inner hot --max-epochs 9 \
    --batch-size 65536 --hot-size-log2 12 --hot-nnz 32 --max-nnz 16 \
    --out docs/artifacts/time_to_auc_lr_hot_b64k.json \
    >"$OUT/ttauc_hot_b64k.out" 2>"$OUT/ttauc_hot_b64k.err"
tail -2 "$OUT/ttauc_hot_b64k.out"

log "3/4 north-star table: hot inner at T=2^28 (2 epochs, rate probe)"
python scripts/time_to_auc.py --model lr --table-size-log2 28 \
    --sequential-inner hot --hot-size-log2 14 --hot-nnz 32 --max-nnz 16 \
    --max-epochs 2 --target-auc 0.99 \
    --out docs/artifacts/time_to_auc_lr_hot_t28.json \
    >"$OUT/ttauc_hot_t28.out" 2>"$OUT/ttauc_hot_t28.err"
tail -2 "$OUT/ttauc_hot_t28.out"

log "4/4 D>1 families on the hot inner: fm, mvm wall-to-AUC"
python scripts/time_to_auc.py --model fm --sequential-inner hot \
    --hot-size-log2 14 --hot-nnz 32 --max-nnz 16 --max-epochs 10 \
    --out docs/artifacts/time_to_auc_fm_hot.json \
    >"$OUT/ttauc_fm_hot.out" 2>"$OUT/ttauc_fm_hot.err"
tail -1 "$OUT/ttauc_fm_hot.out"
python scripts/time_to_auc.py --model mvm --sequential-inner hot \
    --hot-size-log2 14 --hot-nnz 32 --max-nnz 16 --max-epochs 10 \
    --out docs/artifacts/time_to_auc_mvm_hot.json \
    >"$OUT/ttauc_mvm_hot.out" 2>"$OUT/ttauc_mvm_hot.err"
tail -1 "$OUT/ttauc_mvm_hot.out"

log "queue complete — results in $OUT and docs/artifacts/"
