"""Metrics-schema lint: run the toy 2-epoch pipeline end to end and
validate every emitted JSONL row against the schema (obs/schema.py),
plus the phase-accounting invariant the summarize tool relies on —
main-thread phases must account for >= 90% of the run's wall-clock.

Run from the repo root:

    JAX_PLATFORMS=cpu python scripts/check_metrics_schema.py

Wired into tier-1 as a fast test (tests/test_observability.py::
test_check_metrics_schema_script), so a schema drift — a new field
missing from SCHEMA, a renamed kind, a broken phase counter — fails CI
instead of surfacing later as an unreadable metrics file.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def run_toy_pipeline(root: str) -> str:
    """2-epoch toy train + eval with metrics on; returns the JSONL path."""
    from tests.gen_data import generate_dataset
    from xflow_tpu.config import Config
    from xflow_tpu.trainer import Trainer

    ds = generate_dataset(
        os.path.join(root, "data"),
        num_train_shards=2,
        lines_per_shard=200,
        num_fields=10,
        vocab_per_field=8,
        seed=7,
        scale=3.0,
    )
    out = os.path.join(root, "metrics.jsonl")
    cfg = Config(
        train_path=ds.train_prefix,
        test_path=ds.test_prefix,
        model="lr",
        epochs=2,
        batch_size=64,
        table_size_log2=14,
        max_nnz=24,
        num_devices=1,
        metrics_out=out,
        # resource telemetry on (obs/export.py): the sampler thread
        # emits at least the start + close `resource` rows here, so
        # the schema lint covers the live-telemetry kinds too
        obs_resource_every_s=0.2,
    )
    with Trainer(cfg) as t:
        t.train()
        t.evaluate()
    return out


def check(path: str) -> list[str]:
    from xflow_tpu.obs.schema import SCHEMA, load_jsonl, validate_rows
    from xflow_tpu.obs.summary import split_runs

    rows = load_jsonl(path)
    errors = validate_rows(rows)

    kinds = {r.get("kind") for r in rows}
    for expected in ("run_start", "train_epoch", "eval", "shard",
                     "resource"):
        if expected not in kinds:
            errors.append(f"toy pipeline emitted no {expected!r} row")
    unknown = kinds - set(SCHEMA)
    if unknown:
        errors.append(f"kinds missing from SCHEMA: {sorted(unknown)}")

    # the live-telemetry row constructors must themselves produce
    # schema-valid rows — alert rows come from the SLO evaluator
    # (obs/live.py), not the toy pipeline, so mint one directly
    from xflow_tpu.obs.schema import alert_row, resource_row

    synthetic = [
        dict(alert_row(
            rule="serve_error_frac", state="firing", value=0.5,
            threshold=0.05, short_s=60.0, long_s=300.0, samples=3,
            detail="lint",
        ), t=0.0, kind="alert"),
        dict(resource_row(
            rss_bytes=1, cpu_seconds=0.1, threads=1, open_fds=1,
            gc_collections=0,
        ), t=0.0, kind="resource"),
    ]
    errors.extend(
        f"constructor row: {e}" for e in validate_rows(synthetic)
    )

    # the summarize accounting contract: exclusive phases cover the
    # run's wall-clock (ISSUE 1 acceptance: >= 90%)
    for run in split_runs(rows):
        wall = run.wall_seconds()
        if not wall:
            continue
        accounted = sum(run.phase_totals()[0].values())
        if accounted / wall < 0.90:
            errors.append(
                f"phases account for only {accounted / wall:.1%} of "
                f"wall-clock (need >= 90%): phases "
                f"{json.dumps(run.phase_totals()[0])}, wall {wall:.3f}s"
            )
    return errors


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    with tempfile.TemporaryDirectory() as root:
        path = run_toy_pipeline(root)
        errors = check(path)
        n = sum(1 for _ in open(path))
    for e in errors:
        print(f"FAIL: {e}", file=sys.stderr)
    if errors:
        return 1
    print(f"OK: {n} rows validated against obs/schema.py")
    return 0


if __name__ == "__main__":
    sys.exit(main())
