"""Sweep hot-table size and dtype on the flagship bench workload.

Run: python scripts/probe_hot_sweep.py
"""

import sys

sys.path.insert(0, ".")

import jax

from bench import build, make_batches, run
from xflow_tpu.config import Config


def main():
    accel = [d for d in jax.devices() if d.platform != "cpu"]
    base = dict(
        model="lr",
        optimizer="ftrl",
        table_size_log2=24,
        batch_size=131072,
        max_nnz=32,
        hot_nnz=16,
        num_devices=1,
    )
    configs = [("off", Config(**{**base, "max_nnz": 40, "hot_nnz": 24}))]
    for log2, dt in (
        (12, "float32"),
        (12, "bfloat16"),
        (14, "float32"),
        (14, "bfloat16"),
    ):
        configs.append(
            (
                f"H=2^{log2} {dt}",
                Config(**{**base, "hot_size_log2": log2, "hot_dtype": dt}),
            )
        )
    for name, cfg in configs:
        step, state = build(accel, cfg)
        batches, _ = make_batches(cfg, 2)
        _, eps = run(step, state, batches, iters=10, warmup=2)
        print(f"{name:18s} {eps/1e6:6.3f} M ex/s", flush=True)


if __name__ == "__main__":
    main()
