"""Sweep hot-table geometry (H, hot_nnz, cold_nnz) and hot dtype on the
flagship LR+FTRL workload using REAL zipf-distributed batches from the
bench dataset (not synthetic uniform keys): batches come off the CSR
binary cache through the production ShardLoader with a measured
frequency remap, exactly like training.

Run: python scripts/probe_hot_sweep.py [--iters N]
Writes one JSON line per config; paste the table into docs/PERF.md.
"""

import argparse
import itertools
import json
import os
import sys
import time

sys.path.insert(0, ".")

import bench
from xflow_tpu.config import Config
from xflow_tpu.io import freq

T_LOG2 = 24
BATCH = 131072
NBATCH = 4


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=20)
    args = p.parse_args()

    import jax

    accel = [d for d in jax.devices() if d.platform != "cpu"]
    data = bench.ensure_synth_data(
        os.path.join("/tmp/xflow_bench", "zipf-2000000.ffm"), 2_000_000
    )
    csr = data + ".xfbc"
    if not os.path.exists(csr):
        from xflow_tpu.io import binary

        binary.convert_shard(data, csr, block_mib=8)

    # frequency stats once; per-H remaps derive from the same counts
    counts = freq.count_keys([csr], None, 1 << T_LOG2, 64 << 20, 8 << 20)

    base = dict(
        model="lr",
        optimizer="ftrl",
        table_size_log2=T_LOG2,
        batch_size=BATCH,
        num_devices=1,
    )
    sweeps = [("off", dict(max_nnz=40), None)]
    for h_log2, (hot_nnz, cold), dt in itertools.product(
        (12, 13, 14, 15, 16),
        ((16, 32), (24, 16), (32, 12)),
        ("float32", "bfloat16"),
    ):
        sweeps.append(
            (
                f"H=2^{h_log2} kh={hot_nnz} kc={cold} {dt}",
                dict(
                    max_nnz=cold,
                    hot_size_log2=h_log2,
                    hot_nnz=hot_nnz,
                    hot_dtype=dt,
                ),
                h_log2,
            )
        )

    remaps = {}
    for name, kw, h_log2 in sweeps:
        cfg = Config(**{**base, **kw})
        remap = None
        mass = None
        if h_log2:
            if h_log2 not in remaps:
                remaps[h_log2] = freq.build_remap(counts, 1 << h_log2)
            remap = remaps[h_log2]
            mass = freq.hot_mass(counts, remap, 1 << h_log2)
        batches, trunc = bench.real_batches(cfg, csr, remap, NBATCH)
        step, state = bench.build(accel, cfg)
        t0 = time.time()
        _, eps = bench.run(step, state, batches, iters=args.iters)
        print(
            json.dumps(
                {
                    "config": name,
                    "examples_per_sec": round(eps, 0),
                    "truncated_frac": round(trunc, 5),
                    "hot_mass": None if mass is None else round(mass, 4),
                    "compile_plus_run_secs": round(time.time() - t0, 1),
                }
            ),
            flush=True,
        )


if __name__ == "__main__":
    main()
