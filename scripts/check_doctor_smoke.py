"""Doctor smoke lint: run the toy pipeline WITH the stall watchdog and
flight recorder armed, then assert the diagnosis toolchain's healthy
path end to end:

* the run emits no `health` rows and writes no flight dump (a healthy
  toy run must not trip the watchdog — a false positive here means the
  thresholds or the idle-phase handling regressed);
* the emitted metrics file (including the new run_start hostname/pid
  fields) still passes `obs validate` strictly;
* `python -m xflow_tpu.obs doctor` exits 0 and prints a clean
  diagnosis — the first-responder command keeps working on the boring
  case, so it can be trusted on the interesting one.

Run from the repo root:

    JAX_PLATFORMS=cpu python scripts/check_doctor_smoke.py

Wired into tier-1 via tests/test_observability.py::
test_check_doctor_smoke_script, like the schema and serve smokes.
"""

from __future__ import annotations

import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from tests.gen_data import generate_dataset
    from xflow_tpu.config import Config
    from xflow_tpu.obs.__main__ import main as obs_main
    from xflow_tpu.obs.schema import load_jsonl, validate_rows
    from xflow_tpu.trainer import Trainer

    errors: list[str] = []
    with tempfile.TemporaryDirectory() as root:
        ds = generate_dataset(
            os.path.join(root, "data"),
            num_train_shards=2,
            lines_per_shard=200,
            num_fields=10,
            vocab_per_field=8,
            seed=7,
            scale=3.0,
        )
        metrics = os.path.join(root, "metrics.jsonl")
        flight = os.path.join(root, "flight.json")
        cfg = Config(
            train_path=ds.train_prefix,
            test_path=ds.test_prefix,
            model="lr",
            epochs=2,
            batch_size=64,
            table_size_log2=14,
            max_nnz=24,
            num_devices=1,
            metrics_out=metrics,
            obs_flight_out=flight,
            obs_watchdog=True,  # default thresholds: must NOT trip
        )
        with Trainer(cfg) as t:
            t.train()
            t.evaluate()
            wd = t._watchdog
            if wd is None:
                errors.append("obs_watchdog=True built no watchdog")
            elif wd.trip_count:
                errors.append(
                    f"healthy toy run tripped the watchdog "
                    f"{wd.trip_count}x — thresholds or idle handling "
                    "regressed"
                )
        rows = load_jsonl(metrics)
        errors.extend(validate_rows(rows))
        if any(r.get("kind") == "health" for r in rows):
            errors.append("healthy run emitted `health` rows")
        if os.path.exists(flight):
            errors.append(
                "healthy run wrote a flight dump (nothing crashed, "
                "nothing stalled)"
            )

        rc = obs_main(["doctor", metrics])
        if rc != 0:
            errors.append(
                f"`obs doctor` exited {rc} on a healthy run (expected "
                "0 / clean diagnosis)"
            )
        n = len(rows)

    for e in errors:
        print(f"FAIL: {e}", file=sys.stderr)
    if errors:
        return 1
    print(
        f"OK: watchdog armed, 0 trips; {n} metrics rows validated; "
        "obs doctor reports clean"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
