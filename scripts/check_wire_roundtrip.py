"""Wire/compaction round-trip lint: the packed-v2 cache and the host
compaction stage (io/compact.py) must be lossless end to end.

Builds a deterministic toy shard, packs it into a v2 cache
(write -> read), and asserts:

* every record read back EXPANDS byte-exact to the batch the text
  loader assembles at the same config (write -> read -> expand);
* re-compacting the expanded batch reproduces the record's planes
  exactly (read -> compact fixed point — the dedup kernel and plane
  capacities are deterministic);
* the dict wire's metrics rows validate against obs/schema.py — a
  toy training run emits a ``wire`` row and ``obs validate`` accepts
  the file (the XF004 schema-drift gate covers the emitting call site
  statically; this covers the emitted values).

Run from the repo root:

    JAX_PLATFORMS=cpu python scripts/check_wire_roundtrip.py

Wired into tier-1 via tests/test_compact.py::
test_check_wire_roundtrip_script.
"""

from __future__ import annotations

import os
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

PLANES = (
    "cu", "ci", "ct", "cf", "cc", "h8", "hx", "hxh", "hf", "hc",
    "lb", "wb", "cs", "hs",
)


def check_roundtrip(root: str) -> list[str]:
    from tests.gen_data import generate_dataset
    from xflow_tpu.io import packed
    from xflow_tpu.io.compact import compact_batch
    from xflow_tpu.io.loader import ShardLoader

    errors: list[str] = []
    ds = generate_dataset(
        os.path.join(root, "data"),
        num_train_shards=1,
        lines_per_shard=300,
        num_fields=10,
        vocab_per_field=8,
        seed=11,
        scale=3.0,
    )
    src = ds.train_prefix + "-00000"
    dst = os.path.join(root, "golden-v2")
    table = 1 << 14
    hot_size, hot_nnz = 256, 6
    rng = np.random.default_rng(5)
    remap = rng.permutation(table).astype(np.int32)
    kw = dict(
        batch_size=64, max_nnz=24, table_size=table,
        hot_size=hot_size, hot_nnz=hot_nnz, remap=remap,
    )
    meta = packed.convert_shard(src, dst, fmt="v2", block_mib=0.01, **kw)
    text = list(ShardLoader(src, block_mib=1, **kw).iter_batches())
    with open(dst, "rb") as f:
        records = list(packed.iter_compact_batches(f))
    if len(records) != len(text) or meta["batches"] != len(text):
        return [
            f"record count mismatch: {len(records)} records vs "
            f"{len(text)} text batches"
        ]
    fields = (
        "keys", "slots", "vals", "mask", "labels", "weights",
        "hot_keys", "hot_slots", "hot_vals", "hot_mask",
    )
    for i, ((tb, _), (cb, _, _)) in enumerate(zip(text, records)):
        eb = cb.expand()
        for fld in fields:
            a, b = getattr(tb, fld), getattr(eb, fld)
            if a.dtype != b.dtype or not np.array_equal(a, b):
                errors.append(
                    f"record {i}: expand()[{fld}] != text loader batch"
                )
        cb2 = compact_batch(eb, table, hot_size)
        for pl in PLANES:
            if not np.array_equal(getattr(cb, pl), getattr(cb2, pl)):
                errors.append(
                    f"record {i}: re-compacted plane {pl} != stored "
                    "record (compaction not a fixed point)"
                )
    return errors


def check_wire_metrics(root: str) -> list[str]:
    """Toy train with metrics on: the dict wire must emit a ``wire``
    row that ``obs validate`` (the schema) accepts."""
    from tests.gen_data import generate_dataset
    from xflow_tpu.config import Config
    from xflow_tpu.obs.schema import load_jsonl, validate_rows
    from xflow_tpu.trainer import Trainer

    ds = generate_dataset(
        os.path.join(root, "wdata"),
        num_train_shards=1,
        lines_per_shard=200,
        num_fields=10,
        vocab_per_field=8,
        seed=3,
        scale=3.0,
    )
    out = os.path.join(root, "metrics.jsonl")
    cfg = Config(
        train_path=ds.train_prefix, model="lr", epochs=1,
        batch_size=64, table_size_log2=14, max_nnz=24, num_devices=1,
        metrics_out=out,
    )
    with Trainer(cfg) as t:
        assert t.step.dict_wire, "toy config should be dict-eligible"
        t.train()
    rows = load_jsonl(out)
    errors = validate_rows(rows)
    wire = [r for r in rows if r.get("kind") == "wire"]
    if not wire:
        errors.append("toy run emitted no 'wire' metrics row")
    for r in wire:
        if r.get("format") != "dict":
            errors.append(f"wire row format {r.get('format')!r} != 'dict'")
        if not r.get("wire_bytes_per_example", 0) > 0:
            errors.append("wire row has no positive wire_bytes_per_example")
        if not r.get("compaction_ratio", 0) >= 1.0:
            errors.append("wire row compaction_ratio < 1")
    return errors


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    with tempfile.TemporaryDirectory() as root:
        errors = check_roundtrip(root)
        errors += check_wire_metrics(root)
    for e in errors:
        print(f"FAIL: {e}", file=sys.stderr)
    if errors:
        return 1
    print(
        "OK: packed-v2 write->read->expand byte-exact, "
        "read->compact fixed point, wire metrics schema-valid"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
