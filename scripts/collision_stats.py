"""Hash-collision accounting for the dense-table design.

The reference stores full 64-bit hashed keys collision-free in an
unordered_map (/root/reference/src/optimizer/ftrl.h:84,151); this
framework reduces keys mod table_size into a dense array, so distinct
features can share a row.  This script measures what that costs on the
bench dataset: for each table size, the fraction of distinct features
— and of feature OCCURRENCES (what training actually sees) — that
share a row with a different feature.

Uses the CSR binary cache's full 64-bit keys (io/binary.py stores them
unreduced precisely so this measurement and any future table size need
no re-parse).

Run: python scripts/collision_stats.py [--data PATH] ; one JSON line
per table size — paste into docs/PERF.md.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, ".")

import numpy as np


def full_key_counts(csr_path: str) -> tuple[np.ndarray, np.ndarray]:
    """(unique full keys, occurrence counts) over the whole shard."""
    from xflow_tpu.io import binary

    chunks = []
    with open(csr_path, "rb") as f:
        binary.read_header(f)
        while True:
            block = binary.read_record(f)
            if block is None:
                break
            chunks.append(block.keys)
    keys = np.concatenate(chunks)
    return np.unique(keys, return_counts=True)


def collision_stats(ukeys: np.ndarray, counts: np.ndarray, t: int) -> dict:
    rows = ukeys.view(np.uint64) % np.uint64(t)
    order = np.argsort(rows)
    rows_sorted = rows[order]
    counts_sorted = counts[order]
    # a key collides iff its row equals a neighbor's in sorted order
    same_prev = np.empty(len(rows_sorted), bool)
    same_prev[0] = False
    same_prev[1:] = rows_sorted[1:] == rows_sorted[:-1]
    collides = same_prev.copy()
    collides[:-1] |= same_prev[1:]
    d = len(ukeys)
    occ = counts.sum()
    return {
        "table_size_log2": int(np.log2(t)),
        "distinct_keys": int(d),
        "colliding_keys_frac": round(float(collides.sum()) / d, 6),
        "colliding_occurrence_frac": round(
            float(counts_sorted[collides].sum()) / float(occ), 6
        ),
        "occupied_rows": int(len(np.unique(rows))),
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument(
        "--data",
        default="/tmp/xflow_bench/zipf-2000000-g1-s7-f39-v100000.ffm",
    )
    p.add_argument("--table-size-log2", type=int, nargs="*",
                   default=[22, 24, 28])
    args = p.parse_args()

    csr = args.data + ".xfbc"
    if not os.path.exists(csr):
        from xflow_tpu.io import binary

        binary.convert_shard(args.data, csr, block_mib=8)
    ukeys, counts = full_key_counts(csr)
    for log2 in args.table_size_log2:
        print(json.dumps(collision_stats(ukeys, counts, 1 << log2)), flush=True)


if __name__ == "__main__":
    main()
