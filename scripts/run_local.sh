#!/usr/bin/env bash
# Single-host launch — the counterpart of the reference's
# run_ps_local.sh + scripts/local.sh (which forked a scheduler, S
# servers, and W workers with DMLC_* env).  On TPU there are no roles:
# one process drives every local device via SPMD.
#
# Usage: scripts/run_local.sh TRAIN_PREFIX TEST_PREFIX [MODEL] [EPOCHS]
#   MODEL: lr|fm|mvm or 0|1|2 (reference argv aliases)
set -euo pipefail
cd "$(dirname "$0")/.."

TRAIN=${1:?train shard prefix required}
TEST=${2:?test shard prefix required}
MODEL=${3:-lr}
EPOCHS=${4:-60}

exec python -m xflow_tpu.train \
  --model "$MODEL" \
  --train "$TRAIN" \
  --test "$TEST" \
  --epochs "$EPOCHS" \
  "${@:5}"
