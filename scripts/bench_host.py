"""Host ingestion ladder: what the CPU side can feed per second, format
by format, and how text parsing scales with parse_workers.

This is the evidence for the host-feed story (VERDICT round 3 weak
point 2): the reference re-parses text every epoch
(/root/reference/src/io/load_data_from_disk.cc:103-210), so its feed
rate is the parse rate; this framework's CSR cache removes parsing and
the packed cache removes batch assembly, leaving memory-speed reads.

Run on an idle host: python scripts/bench_host.py [--workers 1 2 4]
One JSON line per measurement; paste into docs/PERF.md.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, ".")


def measure(loader, parse_workers=0, label="", wire_prep=False):
    """wire_prep: run the compact wire's numpy half per batch — required
    for mmap-backed packed caches, where untouched fields never page in
    and a bare num_real() loop measures only header reads."""
    from xflow_tpu.parallel.step import compact_wire_np

    t0 = time.perf_counter()
    n = 0
    for batch, _ in loader.iter_batches(parse_workers=parse_workers):
        if wire_prep:
            n += int(compact_wire_np(batch)["weights_u8"].sum())
        else:
            n += batch.num_real()
    dt = time.perf_counter() - t0
    size = os.path.getsize(loader.path)
    print(
        json.dumps(
            {
                "path": label,
                "parse_workers": parse_workers,
                "examples_per_sec": round(n / dt, 0),
                "mb_per_sec": round(size / dt / 2**20, 1),
                "seconds": round(dt, 2),
            }
        ),
        flush=True,
    )
    return n / dt


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")

    import bench
    from xflow_tpu.config import Config
    from xflow_tpu.io import binary, packed
    from xflow_tpu.io.loader import ShardLoader, make_parse_fn

    p = argparse.ArgumentParser()
    p.add_argument("--workers", type=int, nargs="*", default=[1, 2, 4])
    p.add_argument("--examples", type=int, default=2_000_000)
    args = p.parse_args()

    cfg = Config(
        model="lr",
        table_size_log2=24,
        batch_size=131072,
        max_nnz=40,
        num_devices=1,
    )
    text = bench.ensure_synth_data(
        os.path.join("/tmp/xflow_bench", f"zipf-{args.examples}.ffm"),
        args.examples,
    )
    csr = text + ".xfbc"
    if not os.path.exists(csr):
        binary.convert_shard(text, csr, block_mib=8)
    pk = text + ".hostbench.pk"
    if not os.path.exists(pk):
        packed.convert_shard(
            text,
            pk,
            batch_size=cfg.batch_size,
            max_nnz=cfg.max_nnz,
            table_size=cfg.table_size,
            block_mib=8,
        )

    def loader(path):
        return ShardLoader(
            path,
            batch_size=cfg.batch_size,
            max_nnz=cfg.max_nnz,
            table_size=cfg.table_size,
            block_mib=8,
            # native parser (falls back to Python when unbuilt) — the
            # production default; the Python parser is ~8x slower
            parse_fn=make_parse_fn(cfg.table_size, True, cfg.seed),
        )

    # text parse+pack, worker scaling curve
    for w in args.workers:
        measure(loader(text), parse_workers=w, label=f"text[{w}w]")
    # CSR cache: no parse, native pack remains — at BOTH pack
    # geometries of the ladder (40-wide hot-off vs the flagship
    # 16-cold + 32-hot split, whose per-entry pack cost is lower)
    measure(loader(csr), label="csr-cache[cold40]")
    from xflow_tpu.io import freq

    remap = freq.build_remap(
        bench.cached_counts(csr, cfg.table_size_log2), 1 << 12
    )
    measure(
        ShardLoader(
            csr,
            batch_size=cfg.batch_size,
            max_nnz=16,
            table_size=cfg.table_size,
            block_mib=8,
            parse_fn=make_parse_fn(cfg.table_size, True, cfg.seed),
            hash_seed=cfg.seed,
            remap=remap,
            hot_size=1 << 12,
            hot_nnz=32,
        ),
        label="csr-cache[hot 2^12x32 + cold16]",
    )
    # packed cache: mmap record views + wire prep, twice (page-cache
    # steady state)
    measure(loader(pk), label="packed-cache", wire_prep=True)
    measure(loader(pk), label="packed-cache(warm)", wire_prep=True)


if __name__ == "__main__":
    main()
