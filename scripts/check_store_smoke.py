"""Tiered-store smoke lint: a tiny tiered run (T=2^16, hot 2^10) must
be indistinguishable from the dense run it replaces — and leave no
threads behind (docs/STORE.md):

* **parity** — train fm (D>1, the family the store exists for) tiered
  and dense from the SAME logical init, export both, score both
  through PredictEngine: predictions agree to 1e-6 (the acceptance
  bar; in practice bitwise on CPU).  The dense run's tables are seeded
  from the store's per-row init (store/cold.py::row_init_values) so
  the comparison isolates the TIERING, not the init scheme.
* **schema** — the run's ``store`` JSONL rows validate strictly
  against obs/schema.py and the epoch-2 hot_hit_rate is sane (> 0 —
  the toy working set fits 2^10 slots, so warm epochs should hit).
* **thread hygiene** — after Trainer.close() no ``store-promote``
  worker survives (the XF006 bounded-join contract, checked live).

Run from the repo root:

    JAX_PLATFORMS=cpu python scripts/check_store_smoke.py

Wired into tier-1 like check_serve_smoke.py
(tests/test_store.py::test_check_store_smoke_script).
"""

from __future__ import annotations

import os
import sys
import tempfile
import threading

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    import jax

    from tests.gen_data import generate_dataset
    from xflow_tpu.config import Config
    from xflow_tpu.obs.schema import load_jsonl, validate_rows
    from xflow_tpu.parallel.mesh import table_sharding
    from xflow_tpu.serve.artifact import export_artifact
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.store.cold import row_init_values
    from xflow_tpu.trainer import Trainer

    errors: list[str] = []
    with tempfile.TemporaryDirectory() as root:
        ds = generate_dataset(
            os.path.join(root, "data"),
            num_train_shards=2,
            lines_per_shard=200,
            num_fields=10,
            vocab_per_field=8,
            seed=7,
            scale=3.0,
        )
        base = dict(
            train_path=ds.train_prefix,
            test_path=ds.test_prefix,
            model="fm",
            epochs=2,
            batch_size=64,
            table_size_log2=16,
            max_nnz=24,
            num_devices=1,
        )
        metrics = os.path.join(root, "store.jsonl")
        cfg_t = Config(
            store_mode="tiered",
            hot_capacity_log2=10,
            metrics_out=metrics,
            **base,
        )
        cfg_d = Config(**base)

        tiered = Trainer(cfg_t)
        dense = Trainer(cfg_d)
        # same logical starting table: seed the dense run's params from
        # the store's deterministic per-row init
        sharding = table_sharding(dense.mesh)
        for spec in dense.model.tables():
            init = row_init_values(
                cfg_d.seed,
                spec.name,
                "param",
                np.arange(cfg_d.table_size, dtype=np.int64),
                spec.dim,
                spec.init_kind,
                spec.init_scale,
            )
            dense.state["tables"][spec.name]["param"] = jax.device_put(
                init, sharding
            )
        tiered.train()
        dense.train()

        art_t = export_artifact(tiered, os.path.join(root, "art_tiered"))
        art_d = export_artifact(dense, os.path.join(root, "art_dense"))
        eng_t = PredictEngine.load(art_t, buckets=(64,), warm=False)
        eng_d = PredictEngine.load(art_d, buckets=(64,), warm=False)
        rng = np.random.default_rng(0)
        rows = [
            rng.integers(0, cfg_d.table_size, size=int(rng.integers(1, 12)))
            for _ in range(128)
        ]
        p_t = eng_t.predict(eng_t.featurize_raw(rows))
        p_d = eng_d.predict(eng_d.featurize_raw(rows))
        worst = float(np.abs(p_t - p_d).max())
        if not np.allclose(p_t, p_d, atol=1e-6):
            errors.append(
                f"tiered vs dense predictions diverge (max |diff| "
                f"{worst:.2e} > 1e-6) — the tiering changed the model"
            )

        tiered.close()
        dense.close()
        leaked = [
            t.name for t in threading.enumerate()
            if t.name.startswith("store-promote") and t.is_alive()
        ]
        if leaked:
            errors.append(
                f"promotion worker thread(s) survived close(): {leaked}"
            )

        rows_jsonl = load_jsonl(metrics)
        errors.extend(validate_rows(rows_jsonl))
        store_rows = [r for r in rows_jsonl if r.get("kind") == "store"]
        if len(store_rows) < 2:
            errors.append(
                f"tiered run emitted {len(store_rows)} store row(s), "
                "expected one per epoch"
            )
        else:
            warm = store_rows[-1]
            if warm["hot_hit_rate"] <= 0.0:
                errors.append(
                    f"warm-epoch hot_hit_rate {warm['hot_hit_rate']} "
                    "is not positive — promotion never filled the tier"
                )
        n = len(rows_jsonl)

    for e in errors:
        print(f"FAIL: {e}", file=sys.stderr)
    if errors:
        return 1
    print(
        f"OK: tiered/dense parity max|diff|={worst:.1e}; "
        f"{n} metrics rows validated; warm hot_hit_rate="
        f"{store_rows[-1]['hot_hit_rate']}; no promotion-worker leaks"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
