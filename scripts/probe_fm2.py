"""Scatter/gather cost vs row width D on [T, D] tables — find the
alignment geometry that fixes FM/MVM's 106 ns/slice scatter.

Run: python scripts/probe_fm2.py
"""

import json
import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np

B, K = 131072, 40
T = 1 << 24
M = B * K
ITERS = 5


def timeit(name, fn, *args, extra=None):
    fn_j = jax.jit(fn)
    out = fn_j(*args)
    jax.device_get(jax.tree.leaves(out)[0].ravel()[:1])
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = fn_j(*args)
    jax.device_get(jax.tree.leaves(out)[0].ravel()[:1])
    dt = (time.perf_counter() - t0) / ITERS
    row = {"op": name, "ms": round(dt * 1e3, 2),
           "ns_per_slice": round(dt / M * 1e9, 1)}
    if extra:
        row.update(extra)
    print(json.dumps(row), flush=True)
    for leaf in jax.tree.leaves(out):
        leaf.delete()
    return None


def main():
    accel = [d for d in jax.devices() if d.platform != "cpu"]
    dev = accel[0]
    rng = np.random.default_rng(0)
    keys = jax.device_put(rng.integers(0, T, M).astype(np.int32), dev)

    for d in (1, 2, 4, 8, 10, 16, 32):
        tbl = jax.device_put(jnp.zeros((T, d), jnp.float32), dev)
        g = jax.device_put(jnp.ones((M, d), jnp.float32), dev)
        timeit(f"gather D={d}", lambda t, k: t[k], tbl, keys,
               extra={"d": d})
        timeit(
            f"scatter-add D={d}",
            lambda t, k, gg: jnp.zeros_like(t).at[k].add(gg, mode="drop"),
            tbl, keys, g, extra={"d": d},
        )
        tbl.delete()
        g.delete()

    # NOTE: a "donated table" variant was removed — timeit re-jits its
    # fn (nested jit ignores donation) and true donation would kill the
    # buffer after the first of the repeated timing calls, so the probe
    # cannot measure in-place scatter this way.
    d = 10
    tbl = jax.device_put(jnp.zeros((T, d), jnp.float32), dev)
    g = jax.device_put(jnp.ones((M, d), jnp.float32), dev)

    # sort + segment-sum consolidation then row scatter: the sparse-mode
    # shape. unique keys ~ U << M on zipf, but here uniform worst case.
    def consolidated(t, k, gg):
        order = jnp.argsort(k)
        ks = k[order]
        gs = gg[order]
        seg_start = jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), ks[1:] != ks[:-1]]
        )
        seg_id = jnp.cumsum(seg_start.astype(jnp.int32)) - 1
        gsum = jax.ops.segment_sum(gs, seg_id, num_segments=M)
        rep = jnp.where(seg_start, ks, T)
        return jnp.zeros_like(t).at[rep].add(gsum[: rep.shape[0]], mode="drop")

    timeit("sort+segsum+scatter D=10", consolidated, tbl, keys, g)
    tbl.delete(); g.delete()

    # flattened layout: [T*D] scalar rows, key -> base row, D scatters of
    # [M] each? no — single scatter of M*D scalar slices
    d = 10
    tblf = jax.device_put(jnp.zeros((T * d,), jnp.float32), dev)
    gf = jax.device_put(jnp.ones((M, d), jnp.float32), dev)

    def flat_scatter(t, k, gg):
        rows = (k[:, None] * d + jnp.arange(d)[None, :]).reshape(-1)
        return jnp.zeros_like(t).at[rows].add(gg.reshape(-1), mode="drop")

    timeit("flat [T*10] scalar scatter (M*D slices)", flat_scatter,
           tblf, keys, gf)
    tblf.delete(); gf.delete()


if __name__ == "__main__":
    main()
