"""North-star shape bench: T=2^28 LR+FTRL on one chip (BASELINE.md
targets table: "hashed 2^28 features").

Proves HBM fit of the full-size table (w,n,z = 3 x [2^28,1] f32 =
3 GiB) and records examples/sec for update_mode in {dense, sparse} and
for the flagship hot/cold geometry, on REAL zipf batches off the CSR
binary cache (full 64-bit keys stored, so the same cache re-keys at any
table size without re-parsing — docs/PERF.md collision section).

At T=2^28 the dense mode's full-table FTRL elementwise pass touches
3 GiB/step; the sparse mode consolidates to unique keys and updates
only touched rows — this is the shape where the two modes genuinely
diverge, which is why BASELINE.md wants both numbers.

Run: python scripts/bench_northstar.py [--iters N]
One JSON line per config; paste into docs/PERF.md.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, ".")

import bench
from xflow_tpu.config import Config
from xflow_tpu.io import freq

T_LOG2 = 28
BATCH = 131072
NBATCH = 4


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=20)
    args = p.parse_args()

    import jax

    accel = [d for d in jax.devices() if d.platform != "cpu"]
    if not accel:
        print(json.dumps({"error": "no accelerator"}))
        return

    # shared data prep (synth shard + CSR cache, both disk-cached)
    probe_cfg = Config(
        model="lr", optimizer="ftrl", table_size_log2=T_LOG2,
        batch_size=BATCH, max_nnz=40, num_devices=1,
    )
    _, csr, _, _ = bench.prepare_real_data(probe_cfg, 2_000_000)

    base = dict(
        model="lr",
        optimizer="ftrl",
        table_size_log2=T_LOG2,
        batch_size=BATCH,
        num_devices=1,
    )
    # dense vs sparse hot-off (the mode comparison), plus the flagship
    # hot geometry at 2^28 (hot path is table-size independent; the
    # cold section re-keys at 2^28)
    sweeps = [
        ("dense, hot off", dict(max_nnz=40, update_mode="dense"), False),
        ("sparse, hot off", dict(max_nnz=40, update_mode="sparse"), False),
        (
            "dense, hot 2^12x32 cold 16 (flagship)",
            dict(max_nnz=16, hot_size_log2=12, hot_nnz=32,
                 update_mode="dense"),
            True,
        ),
    ]

    counts = remap = None
    for name, kw, want_hot in sweeps:
        cfg = Config(**{**base, **kw})
        mass = None
        r = None
        if want_hot:
            if counts is None:
                counts = bench.cached_counts(csr, T_LOG2)
                remap = freq.build_remap(counts, cfg.hot_size)
            r = remap
            mass = freq.hot_mass(counts, r, cfg.hot_size)
        try:
            batches, trunc = bench.real_batches(cfg, csr, r, NBATCH)
            step, state = bench.build(accel, cfg)
            t0 = time.time()
            _, eps = bench.run(step, state, batches, iters=args.iters)
            row = {
                "config": name,
                "table_size_log2": T_LOG2,
                "examples_per_sec": round(eps, 0),
                "truncated_frac": round(trunc, 5),
                "hot_mass": None if mass is None else round(mass, 4),
                "compile_plus_run_secs": round(time.time() - t0, 1),
            }
        except Exception as e:
            row = {"config": name, "error": f"{type(e).__name__}: {e}"}
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
