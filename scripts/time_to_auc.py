"""Wall-clock-to-AUC: the north-star measurement (BASELINE.md "≥5×
wall-clock to convergence vs the CPU baseline").

Composes the two halves the repo previously measured separately:

* QUALITY — the proven B=512 FTRL convergence protocol
  (docs/CONVERGENCE.md: LR reaches test AUC 0.7401 in 6 epochs,
  1071 s on the 1-core CPU host).  Batch size is an optimizer
  hyperparameter under the reference's mean-over-batch gradients
  (lr_worker.cc:116-118), so the demo must keep the EFFECTIVE batch
  at 512.
* THROUGHPUT — device-rate dispatch.  update_mode="sequential"
  (parallel/step.py::_train_sequential) applies the optimizer once per
  512-example slice inside a scanned dispatch of `--batch-size`
  examples: B_eff stays 512 while the host dispatches B=131072.

The dataset is staged into device HBM ONCE as compact-wire planes
(~1.6 GB for 10 M examples at 40 keys/row — int32 keys + u8
labels/weights), so the training loop reads device-resident windows
instead of paying the tunneled host↔device link every step.  The
clock starts BEFORE staging: uploads are enqueued as per-window async
transfers and epoch-0 compute overlaps the transfer stream, so
wall-to-target (secs_to_target_auc) pays the upload honestly without
serializing on it.  Compile time is reported separately AND added
into total_wall_secs / the headline speedup (a persistent XLA
compilation cache makes it ~1 s on repeat runs of a geometry).

Usage (full protocol, after gen_synth + binary conversion — see
scripts/convergence_baseline.py header for the dataset recipe):

    python scripts/time_to_auc.py                      # LR, 6 epochs
    python scripts/time_to_auc.py --platform cpu \
        --examples 200000 --test-examples 50000        # smoke test

Writes docs/artifacts/time_to_auc_<model>.json with per-epoch rows and
the wall-clock at which the target AUC was crossed.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TRAIN = "/tmp/xflow_conv/bin.train"
TEST = "/tmp/xflow_conv/bin.test"
CPU_BASELINE = {  # docs/CONVERGENCE.md wall column (1-core CPU host)
    "lr": 1071.0,
    "fm": 1673.0,
    "mvm": 1719.0,
    "wide_deep": 1876.0,
}
TARGET_AUC = {  # each model's OWN final test AUC (docs/CONVERGENCE.md)
    "lr": 0.7401,
    "fm": 0.7530,
    "mvm": 0.7596,
    "wide_deep": 0.7414,
}


def stage_planes(trainer, path, cache_tag, limit=0):
    """Parse the shard(s) once through the production ShardLoader —
    using the TRAINER's loader so the hot remap (when on) is the one
    sampled from the training data, shared by both splits — into
    concatenated compact-wire planes, memoized to .npz beside the
    data."""
    from xflow_tpu.parallel.step import compact_wire_np
    from xflow_tpu.trainer import find_shards

    cache = f"{path}.{cache_tag}{'-n%d' % limit if limit else ''}.npz"
    if os.path.exists(cache):
        with np.load(cache) as z:
            return {k: z[k] for k in z.files}
    planes: dict[str, list] = {}
    seen = 0
    for shard in find_shards(path):
        for batch, _ in trainer._loader(shard).iter_batches():
            wire = compact_wire_np(
                batch,
                ship_slots=trainer.step._ship_slots,
                hot_u16=trainer.step._hot_u16,
            )
            for k, v in wire.items():
                planes.setdefault(k, []).append(v)
            seen += int(batch.weights.sum())
            if limit and seen >= limit:
                break
        if limit and seen >= limit:
            break
    out = {k: np.concatenate(v) for k, v in planes.items()}
    np.savez(cache, **out)
    return out


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="lr")
    p.add_argument("--train", default=TRAIN)
    p.add_argument("--test", default=TEST)
    p.add_argument(
        "--target-auc", type=float, default=None,
        help="default: the model's OWN docs/CONVERGENCE.md final AUC — "
        "the CPU baseline's wall time is to that target, so comparing "
        "against an easier one would inflate the speedup",
    )
    p.add_argument("--max-epochs", type=int, default=6)
    p.add_argument("--batch-size", type=int, default=131072,
                   help="dispatch window (examples per device call)")
    p.add_argument("--eff-batch", type=int, default=512,
                   help="effective optimizer batch (slice size)")
    p.add_argument("--table-size-log2", type=int, default=24)
    p.add_argument("--max-nnz", type=int, default=40)
    p.add_argument("--hot-size-log2", type=int, default=0)
    p.add_argument("--hot-nnz", type=int, default=32)
    p.add_argument(
        "--sequential-inner", default="dense",
        choices=["dense", "sparse", "hot"],
        help="sparse = touched-rows-only per slice (T=2^28 scale); "
        "hot = hot-fine/cold-coarse (needs --hot-size-log2)",
    )
    p.add_argument("--examples", type=int, default=0,
                   help="cap train examples (0 = all; smoke tests)")
    p.add_argument("--test-examples", type=int, default=0)
    p.add_argument("--platform", help="force JAX backend (cpu for smoke)")
    p.add_argument("--out", default="")
    p.add_argument(
        "--stage-only", action="store_true",
        help="build/refresh the .npz plane caches and exit (lets a CPU "
        "session pre-pay host prep so the TPU session starts hot)",
    )
    args = p.parse_args()
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    import jax
    import jax.numpy as jnp

    # persistent XLA compilation cache: repeat runs of the same
    # geometry skip the ~14 s trace+compile (reported separately
    # either way, so the artifact shows which case it was)
    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get("XFLOW_JAX_CACHE", "/tmp/xflow_jaxcache"),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from xflow_tpu.config import Config
    from xflow_tpu.trainer import Trainer
    from xflow_tpu.utils.metrics import AucAccumulator

    assert args.batch_size % args.eff_batch == 0
    cfg = Config(
        model=args.model,
        train_path=args.train,
        test_path=args.test,
        batch_size=args.batch_size,
        table_size_log2=args.table_size_log2,
        max_nnz=args.max_nnz,
        max_fields=39,
        num_devices=1,
        update_mode="sequential",
        sequential_inner=args.sequential_inner,
        microbatch=args.batch_size // args.eff_batch,
        hot_size_log2=args.hot_size_log2,
        hot_nnz=args.hot_nnz,
        # the remap (when hot is on) samples key frequencies from the
        # training data exactly as production does
        freq_sample_mib=64,
        checkpoint_dir="",
    )
    if args.target_auc is None:
        if args.model not in TARGET_AUC:
            p.error(f"--target-auc required for model {args.model!r}")
        args.target_auc = TARGET_AUC[args.model]
    trainer = Trainer(cfg, log=lambda s: print(s, file=sys.stderr))
    # the cache key carries everything that shapes the planes: table
    # size, hot geometry, cold capacity, batch padding, and whether a
    # slots plane is shipped (slot models on a slot-free cache would
    # silently train every feature in field 0)
    tag = "ttauc-t{}-h{}-hn{}-c{}-b{}-s{}{}".format(
        args.table_size_log2,
        args.hot_size_log2 if args.hot_size_log2 else 0,
        args.hot_nnz if args.hot_size_log2 else 0,
        args.max_nnz,
        args.batch_size,
        int(trainer.step._ship_slots),
        "-w2" if trainer.step._hot_u16 else "",
    )
    t_setup0 = time.time()
    train_planes = stage_planes(trainer, args.train, tag, args.examples)
    test_planes = stage_planes(trainer, args.test, tag, args.test_examples)
    host_prep_secs = time.time() - t_setup0
    if args.stage_only:
        print(
            json.dumps(
                {
                    "staged": True,
                    "n_train": len(train_planes["labels_u8"]),
                    "n_test": len(test_planes["labels_u8"]),
                    "host_prep_secs": round(host_prep_secs, 2),
                }
            )
        )
        return

    B = args.batch_size

    def pad_planes(planes, multiple):
        n = len(planes["labels_u8"])
        pad = (-n) % multiple
        if pad == 0:
            return planes, n
        out = {}
        for k, v in planes.items():
            if k.endswith("ckeys_u16"):
                fill_val = 0xFFFF  # the u16 pad sentinel
            elif k.endswith("ckeys"):
                fill_val = -1
            else:
                fill_val = 0
            fill = np.full((pad,) + v.shape[1:], fill_val, v.dtype)
            out[k] = np.concatenate([v, fill])
        # padding examples carry weight 0 -> no gradient, no metric
        return out, n

    train_planes, n_train = pad_planes(train_planes, B)
    test_planes, n_test = pad_planes(test_planes, B)
    n_padded = len(train_planes["labels_u8"])
    n_windows = n_padded // B
    bytes_staged = sum(
        v.nbytes for v in list(train_planes.values()) + list(test_planes.values())
    )

    step = trainer.step

    run_chunk = jax.jit(
        lambda state, window: step._train_impl(state, window),
        donate_argnums=0,
    )
    predict_chunk = jax.jit(
        lambda state, window: step._predict_impl(state, window)
    )

    def window_of(planes, i):
        return {k: v[i * B : (i + 1) * B] for k, v in planes.items()}

    def evaluate(state, test_dev):
        acc = AucAccumulator()
        for i, win in enumerate(test_dev):
            pctr = np.asarray(jax.device_get(predict_chunk(state, win)))
            sl = slice(i * B, (i + 1) * B)
            acc.add(
                test_planes["labels_u8"][sl].astype(np.float32),
                pctr,
                test_planes["weights_u8"][sl].astype(np.float32),
            )
        ll, auc = acc.compute()
        return ll, auc

    # compile on a zero-filled dummy window BEFORE any real data is
    # staged (one-time, reported separately; persistent-cache hits
    # make this ~1 s on repeat runs of the same geometry)
    t_c0 = time.time()
    dummy = {
        k: jnp.zeros((B,) + v.shape[1:], v.dtype)
        for k, v in train_planes.items()
    }
    state = trainer.state
    state, m = run_chunk(state, dummy)
    jax.device_get(m["logloss"])
    jax.device_get(predict_chunk(state, dummy)[:1])
    compile_secs = time.time() - t_c0
    # rebuild pristine state (the compile probe trained one window)
    from xflow_tpu.parallel.step import init_state

    state = init_state(trainer.model, trainer.optimizer, cfg, trainer.mesh)

    result = {
        "model": args.model,
        # v2 = overlapped staging inside the timed region, headline
        # speedup = baseline / (secs_to_target + compile); v1
        # artifacts (no accounting key) timed staging separately and
        # divided by total+stage+compile
        "accounting": "v2-overlapped-staging",
        "protocol": "docs/CONVERGENCE.md (B_eff=%d, ftrl.h:17-20 "
        "hyperparameters, T=2^%d)" % (args.eff_batch, args.table_size_log2),
        "backend": jax.devices()[0].platform,
        "batch_size": B,
        "eff_batch": args.eff_batch,
        "microbatch": cfg.microbatch,
        "sequential_inner": cfg.sequential_inner,
        "hot_size_log2": args.hot_size_log2,
        "n_train": n_train,
        "n_test": n_test,
        "host_prep_secs": round(host_prep_secs, 2),
        "bytes_staged": bytes_staged,
        "compile_secs": round(compile_secs, 2),
        "target_auc": args.target_auc,
        "cpu_baseline_secs": CPU_BASELINE.get(args.model),
        "epochs": [],
    }

    # The clock starts BEFORE device staging: wall-to-target pays the
    # full host→device upload honestly.  Uploads are enqueued as
    # per-window async transfers (jnp.asarray returns before the copy
    # lands), so epoch-0 compute overlaps the tail of the transfer
    # stream instead of waiting for all of it.  Staging is therefore
    # NOT a separable wall-clock term: upload_enqueue_secs is the host
    # dispatch cost alone; uploads_verified_by_wall_secs the wall
    # offset by which every transfer was VERIFIED landed (upper bound
    # — the check runs after epoch-0 compute).
    t0 = time.time()
    train_dev = [
        {k: jnp.asarray(v) for k, v in window_of(train_planes, i).items()}
        for i in range(n_windows)
    ]
    test_dev = [
        {k: jnp.asarray(v) for k, v in window_of(test_planes, i).items()}
        for i in range(len(test_planes["labels_u8"]) // B)
    ]
    result["upload_enqueue_secs"] = round(time.time() - t0, 2)
    reached = None
    for epoch in range(args.max_epochs):
        t_ep = time.time()
        ll_sum = cnt = 0.0
        metrics = []
        for win in train_dev:
            state, m = run_chunk(state, win)
            metrics.append(m)
        for m in jax.device_get(metrics):
            ll_sum += float(m["logloss"]) * float(m["count"])
            cnt += float(m["count"])
        train_secs = time.time() - t_ep
        if epoch == 0:
            # verify every transfer landed — device_get, NOT
            # block_until_ready, which returns early on this tunneled
            # platform (verify-skill gotcha); transfers were enqueued
            # in order on one stream, but touch one element of every
            # test window rather than assume ordering.  UPPER BOUND:
            # checked after epoch-0 compute, so this records "landed
            # by here", not the landing instant.
            for w in test_dev:
                jax.device_get(w["labels_u8"][:1])
            result["uploads_verified_by_wall_secs"] = round(
                time.time() - t0, 2
            )
        ev_ll, ev_auc = evaluate(state, test_dev)
        wall = time.time() - t0
        row = {
            "epoch": epoch,
            "train_logloss": round(ll_sum / max(cnt, 1.0), 6),
            "test_logloss": round(ev_ll, 6),
            "test_auc": round(ev_auc, 6),
            "epoch_train_secs": round(train_secs, 2),
            "wall_secs": round(wall, 2),
            "examples_per_sec": round(cnt / max(train_secs, 1e-9), 0),
        }
        result["epochs"].append(row)
        print(json.dumps(row), flush=True)
        if reached is None and ev_auc >= args.target_auc:
            reached = wall
            result["secs_to_target_auc"] = round(wall, 2)
            break

    total = time.time() - t0
    # timed region = staging + train + eval (staging overlaps epoch 0
    # and is not separable); compile is added back for the headline
    result["stage_train_eval_wall_secs"] = round(total, 2)
    result["total_wall_secs"] = round(total + compile_secs, 2)
    if reached is not None and result["cpu_baseline_secs"]:
        result["speedup_vs_cpu_baseline"] = round(
            result["cpu_baseline_secs"] / (reached + compile_secs), 2
        )
        result["speedup_excl_compile"] = round(
            result["cpu_baseline_secs"] / reached, 2
        )
    out = args.out or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "docs",
        "artifacts",
        f"time_to_auc_{args.model}.json",
    )
    with open(out, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(json.dumps({k: v for k, v in result.items() if k != "epochs"}))


if __name__ == "__main__":
    main()
