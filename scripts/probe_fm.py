"""Piecewise timing of the FM train step at bench shapes — find where
the measured 762 ms/step (171 k ex/s at B=131072) goes.

Run: python scripts/probe_fm.py
"""

import json
import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np

B, K, D = 131072, 40, 10
T = 1 << 24
ITERS = 10


def timeit(name, fn, *args):
    fn_j = jax.jit(fn)
    out = fn_j(*args)
    jax.tree.map(lambda a: a.block_until_ready(), out)
    # device_get sync per docs/PERF.md (block_until_ready unreliable here)
    leaf = jax.tree.leaves(out)[0]
    jax.device_get(leaf.ravel()[:1])
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = fn_j(*args)
    leaf = jax.tree.leaves(out)[0]
    jax.device_get(leaf.ravel()[:1])
    dt = (time.perf_counter() - t0) / ITERS
    print(json.dumps({"op": name, "ms": round(dt * 1e3, 2)}), flush=True)
    return out


def main():
    accel = [d for d in jax.devices() if d.platform != "cpu"]
    dev = accel[0]
    rng = np.random.default_rng(0)
    keys = jax.device_put(
        rng.integers(0, T, (B, K)).astype(np.int32), dev
    )
    w = jax.device_put(jnp.zeros((T, 1), jnp.float32), dev)
    v = jax.device_put(jnp.zeros((T, D), jnp.float32), dev)
    n_v = jnp.zeros_like(v)
    z_v = jnp.zeros_like(v)
    gv = jax.device_put(
        rng.standard_normal((B, K, D)).astype(np.float32), dev
    )
    gw = jax.device_put(
        rng.standard_normal((B, K, 1)).astype(np.float32), dev
    )

    timeit("gather w [B,K,1]", lambda t, k: t[k], w, keys)
    timeit("gather v [B,K,10]", lambda t, k: t[k], v, keys)
    timeit(
        "scatter-add w",
        lambda t, k, g: jnp.zeros_like(t).at[k.reshape(-1)].add(
            g.reshape(-1, 1), mode="drop"
        ),
        w, keys, gw,
    )
    timeit(
        "scatter-add v [T,10]",
        lambda t, k, g: jnp.zeros_like(t).at[k.reshape(-1)].add(
            g.reshape(-1, D), mode="drop"
        ),
        v, keys, gv,
    )

    def ftrl_elem(w_, n_, z_, g_):
        n2 = n_ + g_ * g_
        sigma = (jnp.sqrt(n2) - jnp.sqrt(n_)) / 5e-2
        z2 = z_ + g_ - sigma * w_
        shrink = (jnp.sign(z2) * 5e-5 - z2) / ((1.0 + jnp.sqrt(n2)) / 5e-2 + 10.0)
        w2 = jnp.where(jnp.abs(z2) <= 5e-5, 0.0, shrink)
        return jnp.where(n2 == 0.0, w_, w2), n2, z2

    gfull = jax.device_put(jnp.ones((T, D), jnp.float32), dev)
    timeit("ftrl elementwise [T,10]", ftrl_elem, v, n_v, z_v, gfull)

    def scatter_then_ftrl(v_, n_, z_, k, g):
        gbuf = jnp.zeros_like(v_).at[k.reshape(-1)].add(
            g.reshape(-1, D), mode="drop"
        )
        return ftrl_elem(v_, n_, z_, gbuf)

    timeit("scatter+ftrl v (fused)", scatter_then_ftrl, v, n_v, z_v, keys, gv)

    # full production FM step for cross-check
    from bench import build, make_batches
    from xflow_tpu.config import Config

    cfg = Config(
        model="fm", optimizer="ftrl", table_size_log2=24,
        batch_size=B, max_nnz=K, v_dim=D, num_devices=1, max_fields=39,
    )
    step, state = build(accel, cfg)
    batches, _ = make_batches(cfg, 2)
    from bench import run

    _, eps = run(step, state, batches, iters=ITERS, warmup=2)
    print(json.dumps({"op": "full fm step", "examples_per_sec": round(eps)}),
          flush=True)


if __name__ == "__main__":
    main()
