"""Perf probe: MXU one-hot matmul as the gather/scatter path for a small
"hot" table (frequency-partitioned embedding).

If XLA fuses the one-hot (iota==key compare) into the matmul operand
without materializing [M, H], then hot-key gather ~= A @ w_hot and
hot-key scatter ~= A^T @ g run at MXU speed, removing those occurrences
from the per-slice DMA budget entirely.

Run: python scripts/probe_hot.py
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

H = 4096           # hot table rows
M = 131072 * 40    # total occurrences per step
HOT_FRAC = 0.3
MH = int(M * HOT_FRAC)


def timed(fn, *args, iters=10, warmup=3):
    for _ in range(warmup):
        out = fn(*args)
    jax.device_get(jax.tree.leaves(out)[0].ravel()[:1])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.device_get(jax.tree.leaves(out)[0].ravel()[:1])
    return (time.perf_counter() - t0) / iters * 1e3


def main():
    dev = [d for d in jax.devices() if d.platform != "cpu"][0]
    rng = np.random.default_rng(0)
    keys = jax.device_put(jnp.asarray(rng.integers(0, H, MH).astype(np.int32)), dev)
    g = jax.device_put(jnp.ones((MH,), jnp.float32), dev)
    w = jax.device_put(jnp.asarray(rng.normal(size=(H, 1)).astype(np.float32)), dev)
    wv = jax.device_put(jnp.asarray(rng.normal(size=(H, 16)).astype(np.float32)), dev)

    CH = 32768  # chunk rows per one-hot block

    @jax.jit
    def gather_dma(w, k):
        return w.at[k].get(mode="clip").sum()

    @jax.jit
    def gather_mxu(w, k):
        # chunked one-hot @ w; rely on XLA fusing the iota-compare operand
        def body(c, kc):
            oh = (kc[:, None] == jnp.arange(H, dtype=jnp.int32)[None, :])
            return c, (oh.astype(jnp.bfloat16) @ w.astype(jnp.bfloat16))
        _, out = jax.lax.scan(body, 0, k.reshape(-1, CH))
        return out.sum()

    @jax.jit
    def scatter_dma(w, k, g):
        return jnp.zeros_like(w).at[k].add(g[:, None], mode="drop")

    @jax.jit
    def scatter_mxu(w, k, g):
        def body(acc, xs):
            kc, gc = xs
            oh = (kc[:, None] == jnp.arange(H, dtype=jnp.int32)[None, :])
            return acc + (oh.astype(jnp.bfloat16).T @ gc[:, None].astype(jnp.bfloat16)).astype(jnp.float32), None
        acc, _ = jax.lax.scan(
            body, jnp.zeros((H, 1), jnp.float32),
            (k.reshape(-1, CH), g.reshape(-1, CH)),
        )
        return acc

    print(f"MH={MH} hot occurrences, H={H} rows, chunk={CH}")
    print(f"gather  DMA: {timed(gather_dma, w, keys):7.2f} ms")
    print(f"gather  MXU: {timed(gather_mxu, w, keys):7.2f} ms")
    print(f"scatter DMA: {timed(scatter_dma, w, keys, g):7.2f} ms")
    print(f"scatter MXU: {timed(scatter_mxu, w, keys, g):7.2f} ms")

    # wider rows (FM v table, D=16): matmul gets D columns for free-ish
    @jax.jit
    def gather_mxu_wide(w, k):
        def body(c, kc):
            oh = (kc[:, None] == jnp.arange(H, dtype=jnp.int32)[None, :])
            return c, (oh.astype(jnp.bfloat16) @ w.astype(jnp.bfloat16))
        _, out = jax.lax.scan(body, 0, k.reshape(-1, CH))
        return out.sum()

    @jax.jit
    def gather_dma_wide(w, k):
        return w.at[k].get(mode="clip").sum()

    print(f"gather  DMA D=16: {timed(gather_dma_wide, wv, keys):7.2f} ms")
    print(f"gather  MXU D=16: {timed(gather_mxu_wide, wv, keys):7.2f} ms")


if __name__ == "__main__":
    main()
