"""Serving smoke lint: train the toy pipeline, export an artifact,
score through the MicroBatcher, and validate everything the serving
tier promises (docs/SERVING.md):

* the emitted serve-mode metrics JSONL rows (run_start / serve_load /
  serve_stats) pass obs/schema.py strictly;
* the engine compiled exactly once per warmed bucket and stayed there
  under mixed-size traffic (the no-recompile guarantee);
* batcher scores match direct engine predictions (coalescing changes
  latency, never values);
* the hot-table remap folds into the artifact (the toy model here
  trains WITH a hot table so the remap path is exercised).

Run from the repo root:

    JAX_PLATFORMS=cpu python scripts/check_serve_smoke.py

Wired into tier-1 like check_metrics_schema.py (tests/test_serve.py::
test_check_serve_smoke_script), so a serving-schema drift or a
recompile regression fails CI instead of surfacing as a latency cliff.
"""

from __future__ import annotations

import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from tests.gen_data import generate_dataset
    from xflow_tpu.config import Config
    from xflow_tpu.obs.schema import SCHEMA, load_jsonl, validate_rows
    from xflow_tpu.serve.artifact import export_artifact
    from xflow_tpu.serve.batcher import MicroBatcher
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.trainer import Trainer
    from xflow_tpu.utils.logging import MetricsLogger

    errors: list[str] = []
    with tempfile.TemporaryDirectory() as root:
        ds = generate_dataset(
            os.path.join(root, "data"),
            num_train_shards=2,
            lines_per_shard=200,
            num_fields=10,
            vocab_per_field=8,
            seed=7,
            scale=3.0,
        )
        cfg = Config(
            train_path=ds.train_prefix,
            test_path=ds.test_prefix,
            model="lr",
            epochs=1,
            batch_size=64,
            table_size_log2=14,
            max_nnz=24,
            num_devices=1,
            # hot table ON so the artifact carries (and the engine
            # folds in) the frequency remap
            hot_size_log2=6,
            hot_nnz=8,
            freq_sample_mib=1,
        )
        trainer = Trainer(cfg)
        trainer.train()
        artifact = export_artifact(trainer, os.path.join(root, "artifact"))
        if not os.path.exists(os.path.join(artifact, "remap.npy")):
            errors.append("hot-table artifact is missing remap.npy")

        buckets = (8, 64)
        engine = PredictEngine.load(artifact, buckets=buckets, warm=True)
        if engine.compile_count != len(buckets):
            errors.append(
                f"warm() compiled {engine.compile_count} executables "
                f"for {len(buckets)} buckets"
            )

        metrics = os.path.join(root, "serve.jsonl")
        logger = MetricsLogger(metrics, run_header={
            "run_id": f"{int(time.time() * 1000):x}-smoke",
            "config_digest": engine.digest,
            "rank": 0,
            "num_hosts": 1,
            "model": cfg.model,
        })
        logger.log("serve_load", {
            "artifact": artifact,
            "config_digest": engine.digest,
            "model": cfg.model,
            "buckets": list(engine.buckets),
            "warm_seconds": round(engine.warm_seconds, 6),
            "compiles": engine.compile_count,
        })

        rng = np.random.default_rng(0)
        rows = [
            rng.integers(0, cfg.table_size, size=int(rng.integers(1, 12)))
            for _ in range(100)
        ]
        batcher = MicroBatcher(
            engine, max_wait_ms=5.0, metrics_logger=logger
        )
        futs = [batcher.submit(r) for r in rows]
        got = np.asarray([f.result() for f in futs])
        stats = batcher.close()
        logger.close()

        direct = engine.predict(engine.featurize_raw(list(rows)))
        if not np.allclose(got, direct, atol=1e-6):
            errors.append("batcher scores diverge from direct engine predict")
        if engine.compile_count != len(buckets):
            errors.append(
                f"mixed-size traffic grew compile_count to "
                f"{engine.compile_count} (buckets: {len(buckets)}) — "
                "the no-recompile guarantee is broken"
            )
        if stats["requests"] != len(rows):
            errors.append(
                f"serve_stats requests {stats['requests']} != {len(rows)}"
            )
        for field in ("queue_p99", "featurize_p99", "device_p99"):
            if stats[field] <= 0.0:
                errors.append(f"serve_stats {field} is not positive")

        rows_jsonl = load_jsonl(metrics)
        errors.extend(validate_rows(rows_jsonl))
        kinds = {r.get("kind") for r in rows_jsonl}
        for expected in ("run_start", "serve_load", "serve_stats"):
            if expected not in kinds:
                errors.append(f"serve pipeline emitted no {expected!r} row")
        unknown = kinds - set(SCHEMA)
        if unknown:
            errors.append(f"kinds missing from SCHEMA: {sorted(unknown)}")
        n = len(rows_jsonl)

    for e in errors:
        print(f"FAIL: {e}", file=sys.stderr)
    if errors:
        return 1
    print(
        f"OK: {n} serve metrics rows validated; "
        f"{len(rows)} requests in {stats['batches']} coalesced batches; "
        f"{engine.compile_count} compiles for {len(buckets)} buckets"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
