"""Perf probe: does XLA TPU overlap an independent gather and scatter
(different DMA directions) inside one program?

If yes, a delayed-gradient update mode (apply step i-1's gradients while
computing step i's forward from the pre-update table) breaks the serial
gather->scatter dependency and can approach 2x on the slice-bound step.

Run on the real chip: python scripts/probe_overlap.py
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

T = 1 << 24
M = 131072 * 40  # B=131k, nnz=40


def timed(fn, *args, iters=10, warmup=3):
    for _ in range(warmup):
        out = fn(*args)
    jax.device_get(jax.tree.leaves(out)[0].ravel()[:1])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.device_get(jax.tree.leaves(out)[0].ravel()[:1])
    return (time.perf_counter() - t0) / iters * 1e3


def main():
    dev = [d for d in jax.devices() if d.platform != "cpu"][0]
    rng = np.random.default_rng(0)
    w = jax.device_put(jnp.zeros((T, 1), jnp.float32), dev)
    gbuf = jax.device_put(jnp.zeros((T, 1), jnp.float32), dev)
    keys_a = jax.device_put(
        jnp.asarray(rng.integers(0, T, M).astype(np.int32)), dev
    )
    keys_b = jax.device_put(
        jnp.asarray(rng.integers(0, T, M).astype(np.int32)), dev
    )
    g = jax.device_put(jnp.ones((M, 1), jnp.float32), dev)

    @jax.jit
    def gather_only(w, k):
        return w.at[k].get(mode="clip").sum()

    @jax.jit
    def scatter_only(buf, k, g):
        return buf.at[k].add(g, mode="drop")

    @jax.jit
    def both_dependent(w, k, g):
        rows = w.at[k].get(mode="clip")
        return w.at[k].add(rows + g, mode="drop")

    @jax.jit
    def both_independent(w, buf, ka, kb, g):
        # gather from w with ka, scatter into buf with kb: no data dep
        rows = w.at[ka].get(mode="clip")
        buf2 = buf.at[kb].add(g, mode="drop")
        return rows.sum(), buf2

    tg = timed(gather_only, w, keys_a)
    ts = timed(scatter_only, gbuf, keys_b, g)
    td = timed(both_dependent, w, keys_a, g)
    ti = timed(both_independent, w, gbuf, keys_a, keys_b, g)
    print(f"gather only:        {tg:7.2f} ms")
    print(f"scatter-add only:   {ts:7.2f} ms")
    print(f"dependent g+s:      {td:7.2f} ms (expect ~= g+s sum)")
    print(f"independent g+s:    {ti:7.2f} ms (overlap if < sum={tg+ts:.2f})")

    # sorted/unique hints on the consolidated path
    uk = jnp.asarray(np.sort(rng.choice(T, M // 2, replace=False)).astype(np.int32))
    uk = jax.device_put(uk, dev)
    gu = jax.device_put(jnp.ones((M // 2, 1), jnp.float32), dev)

    @jax.jit
    def scatter_hints(w, k, rows):
        return w.at[k].set(rows, mode="drop", unique_indices=True,
                           indices_are_sorted=True)

    @jax.jit
    def scatter_nohints(w, k, rows):
        return w.at[k].set(rows, mode="drop")

    th = timed(scatter_hints, w, uk, gu)
    tn = timed(scatter_nohints, w, uk, gu)
    print(f"scatter M/2 sorted+unique hints: {th:7.2f} ms vs no hints {tn:7.2f} ms")

    @jax.jit
    def gather_hints(w, k):
        return jax.lax.gather(
            w,
            k[:, None],
            jax.lax.GatherDimensionNumbers(
                offset_dims=(1,), collapsed_slice_dims=(0,),
                start_index_map=(0,),
            ),
            (1, 1),
            indices_are_sorted=True,
            unique_indices=True,
            mode=jax.lax.GatherScatterMode.CLIP,
        ).sum()

    tgh = timed(gather_hints, w, uk)
    tgn = timed(gather_only, w, uk)
    print(f"gather M/2 sorted+unique hints:  {tgh:7.2f} ms vs no hints {tgn:7.2f} ms")


if __name__ == "__main__":
    main()
