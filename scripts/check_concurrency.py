"""Concurrency gate: the XF006–XF009 static pass PLUS a runtime
lock-order sanitizer smoke, gating the thread fabric before the
N-stream input fan-out (ROADMAP item 1) multiplies it.

Run from the repo root:

    python scripts/check_concurrency.py

Two halves, both must pass:

1. **Static** — ``xflow_tpu.analysis`` with the four concurrency rules
   (XF006 thread lifecycle, XF007 lock order, XF008 shared-state
   discipline, XF009 heartbeat coverage — docs/ANALYSIS.md) over the
   whole package against the committed baseline, same contract as
   scripts/check_analysis.py.
2. **Runtime** — arm the lock-order sanitizer
   (analysis/sanitizer.py) over a live MicroBatcher + MetricsLogger +
   MetricsRegistry, push concurrent traffic through them, and
   cross-check every OBSERVED lock-acquisition order against the
   static XF007 graph.  An observed order that contradicts the static
   model (a cycle in the combined graph) fails the gate: the code
   takes locks in an order the analysis says can deadlock.

Wired into tier-1 via tests/test_analysis.py, next to
check_analysis.py / check_metrics_schema.py / check_serve_smoke.py.
"""

from __future__ import annotations

import os
import sys
import tempfile
import threading

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

CONCURRENCY_RULES = ["XF006", "XF007", "XF008", "XF009"]


def check_static(package: str, baseline_path: str) -> int:
    from xflow_tpu.analysis import (
        load_baseline,
        render_text,
        run_analysis,
        split_baselined,
    )

    findings, pragma_suppressed = run_analysis(
        [package], select=CONCURRENCY_RULES
    )
    entries = [
        e
        for e in load_baseline(baseline_path)
        if e["rule"] in CONCURRENCY_RULES
    ]
    new, grandfathered, stale = split_baselined(findings, entries)
    print(render_text(new, grandfathered, pragma_suppressed, stale))
    if new:
        return 1
    if stale:
        print(
            "FAIL: stale baseline entries (prune analysis-baseline.json)",
            file=sys.stderr,
        )
        return 1
    return 0


class _EchoEngine:
    """Engine stub for the smoke (no jax): pctr == the request's key."""

    buckets = (1, 8)
    digest = "smoke000"

    def featurize(self, rows):
        return [keys for keys, _, _ in rows]

    def predict_prepared(self, batch):
        return [float(k[0]) for k in batch]


def check_runtime(package: str) -> int:
    """Exercise the real lock users under the sanitizer and cross-check
    observed acquisition orders against the static XF007 graph."""
    from xflow_tpu.analysis import LockOrderSanitizer, static_lock_order
    from xflow_tpu.obs.registry import MetricsRegistry
    from xflow_tpu.serve.batcher import MicroBatcher
    from xflow_tpu.utils.logging import MetricsLogger

    static = static_lock_order([package])
    san = LockOrderSanitizer()
    with tempfile.TemporaryDirectory() as tmp:
        logger = MetricsLogger(os.path.join(tmp, "smoke.jsonl"))
        registry = MetricsRegistry()
        batcher = MicroBatcher(
            _EchoEngine(),
            max_wait_ms=0.5,
            registry=registry,
            metrics_logger=logger,
        )
        san.instrument(logger, "_lock", "MetricsLogger._lock")
        san.instrument(registry, "_lock", "MetricsRegistry._lock")
        san.instrument(batcher, "_swap_lock", "MicroBatcher._swap_lock")
        san.instrument(
            batcher, "_submit_lock", "MicroBatcher._submit_lock"
        )
        n_threads, per_thread = 4, 32
        barrier = threading.Barrier(n_threads)
        errors: list[BaseException] = []

        def worker(tid: int) -> None:
            try:
                barrier.wait()
                futs = [
                    batcher.submit([float(tid * per_thread + i)])
                    for i in range(per_thread)
                ]
                for f in futs:
                    f.result(timeout=30)
                from xflow_tpu.obs.schema import health_row

                logger.log("health", health_row(
                    cause="smoke", channel="serve",
                    silence_seconds=0.0, threshold_seconds=0.0,
                    detail="sanitizer smoke",
                ))
            except BaseException as e:  # pragma: no cover - failure path
                errors.append(e)

        threads = [
            threading.Thread(target=worker, args=(t,))
            for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        batcher.close()
        logger.close()
        if errors:
            print(f"FAIL: sanitizer smoke errored: {errors[0]!r}")
            return 1
    observed = san.edges()
    contradictions = san.contradictions(static)
    n_obs = sum(len(v) for v in observed.values())
    n_static = sum(len(v) for v in static.values())
    print(
        f"sanitizer smoke: {n_obs} observed lock-order edge(s) vs "
        f"{n_static} static edge(s)"
    )
    if contradictions:
        for c in contradictions:
            print(f"FAIL: observed lock order contradicts XF007: {c}")
        return 1
    print("OK: observed lock orders consistent with the static graph")
    return 0


def main() -> int:
    package = os.path.join(REPO, "xflow_tpu")
    baseline = os.path.join(REPO, "analysis-baseline.json")
    rc = check_static(package, baseline)
    rc = check_runtime(package) or rc
    return rc


if __name__ == "__main__":
    sys.exit(main())
