"""Checkpoint/resume (capability gap filled — reference has none,
SURVEY §5): save → restore roundtrip, resume-continues-identically, and
cross-mesh restore."""

import numpy as np
import jax

from xflow_tpu.config import Config
from xflow_tpu.trainer import Trainer
from xflow_tpu.utils.checkpoint import latest_checkpoint


def cfg_for(ds, tmp, ndev=1, **kw):
    base = dict(
        train_path=ds.train_prefix,
        test_path=ds.test_prefix,
        epochs=2,
        batch_size=64,
        table_size_log2=14,
        max_nnz=24,
        num_devices=ndev,
        checkpoint_dir=str(tmp),
    )
    base.update(kw)
    return Config(model="lr", **base)


def host_tables(trainer):
    return jax.tree.map(
        lambda a: np.asarray(jax.device_get(a)), trainer.state["tables"]
    )


def test_roundtrip(toy_dataset, tmp_path):
    t = Trainer(cfg_for(toy_dataset, tmp_path))
    t.train()
    before = host_tables(t)
    step_before = int(jax.device_get(t.state["step"]))

    t2 = Trainer(cfg_for(toy_dataset, tmp_path))
    cursor = t2.restore()
    assert cursor is not None and cursor["epoch"] == 2
    after = host_tables(t2)
    jax.tree.map(np.testing.assert_array_equal, before, after)
    assert int(jax.device_get(t2.state["step"])) == step_before


def test_resume_training_continues(toy_dataset, tmp_path):
    # train 4 epochs straight through
    cfg_full = cfg_for(toy_dataset, tmp_path / "a", epochs=4)
    tfull = Trainer(cfg_full)
    tfull.train()

    # train 2, checkpoint, new trainer resumes for 2 more
    cfg_half = cfg_for(toy_dataset, tmp_path / "b", epochs=2)
    thalf = Trainer(cfg_half)
    thalf.train()
    cfg_rest = cfg_for(toy_dataset, tmp_path / "b", epochs=4)
    trest = Trainer(cfg_rest)
    trest.restore()
    assert trest.epoch == 2
    trest.train()

    np.testing.assert_allclose(
        host_tables(tfull)["w"]["param"],
        host_tables(trest)["w"]["param"],
        rtol=1e-6,
        atol=1e-8,
    )


def test_restore_onto_different_mesh(toy_dataset, tmp_path):
    t1 = Trainer(cfg_for(toy_dataset, tmp_path, ndev=1))
    t1.train()
    t8 = Trainer(cfg_for(toy_dataset, tmp_path, ndev=8))
    t8.restore()
    np.testing.assert_array_equal(
        host_tables(t1)["w"]["param"], host_tables(t8)["w"]["param"]
    )
    assert len(t8.state["tables"]["w"]["param"].sharding.device_set) == 8


def test_latest_checkpoint_empty(tmp_path):
    assert latest_checkpoint(str(tmp_path)) is None


def test_checkpoint_keep_last_k(toy_dataset, tmp_path):
    """checkpoint_keep=2: only the 2 newest ckpt-* dirs survive a run
    that writes one checkpoint per epoch (unbounded accumulation at
    2^28-row FM scale is ~13 GB per checkpoint)."""
    import glob
    import os

    t = Trainer(cfg_for(toy_dataset, tmp_path, epochs=4, checkpoint_keep=2))
    t.train()
    ckpts = sorted(glob.glob(str(tmp_path / "ckpt-*")))
    assert len(ckpts) == 2
    # the survivors are the NEWEST two, and LATEST points at the newest
    steps = [int(os.path.basename(c).split("-")[1]) for c in ckpts]
    assert steps == sorted(steps)
    with open(tmp_path / "LATEST") as f:
        assert f.read().strip() == os.path.basename(ckpts[-1])
    # restore still works from the retained set
    t2 = Trainer(cfg_for(toy_dataset, tmp_path, epochs=4, checkpoint_keep=2))
    cursor = t2.restore()
    assert cursor is not None and cursor["epoch"] == 4


def test_save_failure_raises_not_hangs(toy_dataset, tmp_path):
    """A checkpoint-dir that cannot be created must surface as an
    exception from save() (single-host analogue of the multi-host
    pre-barrier protocol test in test_distributed.py)."""
    import pytest

    blocker = tmp_path / "blocker"
    blocker.write_text("a regular file where the ckpt dir should go")
    cfg = cfg_for(toy_dataset, tmp_path, epochs=1)
    cfg = cfg.replace(checkpoint_dir=str(blocker / "ck"))
    t = Trainer(cfg)
    with pytest.raises(OSError):
        t.save()


def test_mid_epoch_cursor_used_on_resume(toy_dataset, tmp_path, monkeypatch):
    """A mid-epoch checkpoint's (shard, offset) cursor must flow into the
    first train_epoch after restore (not restart the epoch from zero)."""
    t = Trainer(cfg_for(toy_dataset, tmp_path))
    # simulate a mid-epoch save: one block into shard 1
    saved = t.save(shard_idx=1, offset=4096)
    assert saved is not None

    t2 = Trainer(cfg_for(toy_dataset, tmp_path))
    cursor = t2.restore()
    assert (cursor["shard"], cursor["offset"]) == (1, 4096)

    calls = []
    real = t2.train_epoch

    def spy(start_shard=0, start_offset=0):
        calls.append((start_shard, start_offset))
        return real(start_shard=0, start_offset=0)  # toy offsets exceed file

    monkeypatch.setattr(t2, "train_epoch", spy)
    t2.train()
    assert calls[0] == (1, 4096)
    # subsequent epochs start clean
    assert all(c == (0, 0) for c in calls[1:])


def test_sharded_files_no_allgather(toy_dataset, tmp_path):
    """Each device's row range lands in its own .r<start>-<stop>.npy file
    (the round-2 sharded format: no allgather on save), and a checkpoint
    written on an 8-device mesh restores bit-identically onto 1 device."""
    import glob
    import os

    t8 = Trainer(cfg_for(toy_dataset, tmp_path, ndev=8, epochs=1))
    t8.train()
    ck = latest_checkpoint(str(tmp_path))
    files = glob.glob(os.path.join(ck, "w.param.r*.npy"))
    assert len(files) == 8  # one row-range file per device shard
    rows = 1 << 14
    sizes = [np.load(f, mmap_mode="r").shape[0] for f in files]
    assert sorted(sizes) == [rows // 8] * 8

    before = host_tables(t8)
    t1 = Trainer(cfg_for(toy_dataset, tmp_path, ndev=1, epochs=1))
    t1.restore()
    jax.tree.map(np.testing.assert_array_equal, before, host_tables(t1))
