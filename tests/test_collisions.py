"""Collision accounting (scripts/collision_stats.py): the dense table
reduces 64-bit keys mod table_size, unlike the reference's collision-
free unordered_map store (ftrl.h:84) — the measured collision rate is
part of any quality comparison (VERDICT round 3 item 7)."""

import numpy as np

import scripts.collision_stats as mod


def test_collision_stats_crafted():
    t = 8
    # keys 1 and 9 share row 1; keys 2, 10, 18 share row 2; 5 is alone
    ukeys = np.asarray([1, 9, 2, 10, 18, 5], np.int64)
    counts = np.asarray([4, 1, 2, 2, 2, 7], np.int64)
    s = mod.collision_stats(ukeys, counts, t)
    assert s["distinct_keys"] == 6
    assert s["occupied_rows"] == 3
    # script rounds to 6 decimals
    np.testing.assert_allclose(s["colliding_keys_frac"], 5 / 6, rtol=1e-5)
    np.testing.assert_allclose(
        s["colliding_occurrence_frac"], 11 / 18, rtol=1e-6
    )


def test_collision_stats_full_key_negative_int64():
    """Full murmur hashes stored as two's-complement int64 must reduce
    through uint64 arithmetic (row of a 'negative' key is still its
    unsigned hash mod T)."""
    t = 16
    h = np.uint64(2**64 - 3)  # int64 view: -3; row must be (2^64-3) % 16
    ukeys = np.asarray([h], np.uint64).view(np.int64)
    counts = np.asarray([1], np.int64)
    s = mod.collision_stats(ukeys, counts, t)
    assert s["occupied_rows"] == 1
    assert s["colliding_keys_frac"] == 0.0
