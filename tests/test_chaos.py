"""Chaos fabric (xflow_tpu/chaos/; docs/ROBUSTNESS.md): seeded
deterministic failpoints, per-site self-healing fixtures, doctor
diagnosis, and the tier-1 gate wiring.

Per-site coverage:

* registry — spec grammar, deterministic fire schedules (nth/every/
  p/times), zero-overhead disarmed path, chaos-row audit trail;
* loader — transient read healed by bounded retry (identical batches),
  persistent corruption quarantined (skip + health row), quarantine
  budget abort;
* checkpoint — latest_complete / manifest-less refusal, kill
  mid-commit leaves the previous generation restorable, restore-auto
  fallback walks past broken generations;
* store — promotion-worker death detected between steps and restarted
  once (second death freezes placement, training stays correct),
  transient cold-fetch healed by retry;
* serve — replica eviction + background revive from the shared
  artifact, accept-loop failpoint survived;
* doctor — quarantine-budget blamed as corruption (not input stall),
  evict/revive ranked as absorbed vs reduced-capacity.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from xflow_tpu import chaos
from xflow_tpu.config import Config
from xflow_tpu.trainer import Trainer


@pytest.fixture(autouse=True)
def _disarmed():
    """Every test starts and ends with the global registry disarmed —
    an armed leftover would inject faults into unrelated tests."""
    chaos.disarm()
    yield
    chaos.disarm()


@pytest.fixture(scope="module")
def toy_dataset(tmp_path_factory):
    from tests.gen_data import generate_dataset

    root = tmp_path_factory.mktemp("chaos_data")
    return generate_dataset(
        str(root),
        num_train_shards=2,
        lines_per_shard=200,
        num_fields=10,
        vocab_per_field=8,
        seed=5,
        scale=3.0,
    )


def _cfg(ds, **kw):
    base = dict(
        train_path=ds.train_prefix,
        test_path=ds.test_prefix,
        model="lr",
        epochs=1,
        batch_size=64,
        table_size_log2=14,
        max_nnz=16,
        num_devices=1,
        parse_workers=1,
    )
    base.update(kw)
    return Config(**base)


class _FakeLogger:
    def __init__(self):
        self.rows = []
        self.closed = False

    def log(self, kind, record):
        row = {"t": 0.0, "kind": kind}
        row.update(record)
        self.rows.append(row)


# -- registry ---------------------------------------------------------------


def test_parse_spec_grammar():
    seed, rules = chaos.parse_spec(
        "seed=9; loader.read_block:nth=2 ; serve.replica_score:p=0.5,times=3"
    )
    assert seed == 9
    assert rules["loader.read_block"].nth == 2
    assert rules["serve.replica_score"].p == 0.5
    assert rules["serve.replica_score"].times == 3


@pytest.mark.parametrize("bad", [
    "",
    "seed=1",
    "site-with-caps!:p=1",
    "a.b",
    "a.b:frob=1",
    "a.b:p=2",
    "a.b:nth=0",
    "a.b:nth=1;a.b:nth=2",
    "a.b:p=0.5,nth=3",
])
def test_parse_spec_rejects(bad):
    with pytest.raises(ValueError):
        chaos.parse_spec(bad)


def test_config_validates_chaos_spec():
    with pytest.raises(ValueError):
        Config(chaos_spec="not a spec")
    assert Config(chaos_spec="a.b:nth=1").chaos_spec == "a.b:nth=1"


def test_arm_from_env(monkeypatch):
    """XFLOW_CHAOS reaches every entry point (Trainer and the serve
    CLI both arm through this helper); unset = no-op, keeping
    whatever is armed."""
    monkeypatch.delenv("XFLOW_CHAOS", raising=False)
    assert chaos.arm_from_env() is None
    chaos.arm("x.y:nth=1")
    assert chaos.arm_from_env() is None  # unset must not disarm
    assert chaos.armed() is not None
    monkeypatch.setenv("XFLOW_CHAOS", "a.b:nth=2")
    reg = chaos.arm_from_env()
    assert reg is chaos.armed() and "a.b" in reg.rules


def test_disarmed_failpoint_is_noop():
    assert chaos.armed() is None
    chaos.failpoint("anything.at.all")  # no raise, no state, no logger
    assert chaos.fired() == {}


def test_deterministic_fire_schedule():
    """Same seed + same hit sequence → identical fire pattern, on two
    independent registries (the reproducibility the gate rides on)."""

    def pattern(spec):
        reg = chaos.arm(spec)
        fired = []
        for i in range(64):
            try:
                chaos.failpoint("x.y")
                fired.append(False)
            except chaos.ChaosError:
                fired.append(True)
        chaos.disarm()
        return fired, reg.fired()

    a, fa = pattern("seed=4;x.y:p=0.25")
    b, fb = pattern("seed=4;x.y:p=0.25")
    c, _ = pattern("seed=5;x.y:p=0.25")
    assert a == b and fa == fb
    assert any(a) and not all(a)
    assert c != a  # a different seed moves the schedule


def test_nth_every_times_semantics():
    chaos.arm("x.y:every=3,times=2")
    hits = []
    for i in range(1, 13):
        try:
            chaos.failpoint("x.y")
        except chaos.ChaosError as e:
            hits.append(e.hit)
    assert hits == [3, 6]  # every=3 capped at times=2


def test_chaos_rows_logged_and_schema_valid():
    from xflow_tpu.obs.schema import validate_rows

    log = _FakeLogger()
    chaos.arm("x.y:nth=1")
    chaos.attach_logger(log)
    with pytest.raises(chaos.ChaosError):
        chaos.failpoint("x.y")
    assert [r["kind"] for r in log.rows] == ["chaos"]
    assert log.rows[0]["site"] == "x.y"
    assert validate_rows(log.rows) == []
    # detach of a DIFFERENT logger must not steal the attachment
    chaos.detach_logger(object())
    assert chaos.armed()._logger is log


# -- loader -----------------------------------------------------------------


def _collect_batches(ds, cfg):
    trainer = Trainer(cfg)
    loader = trainer._loader(
        ds.train_prefix + "-00000"
    )
    out = [b for b, _ in loader.iter_batches()]
    trainer.close()
    return out, loader


def test_loader_transient_read_heals_with_identical_batches(toy_dataset):
    clean, _ = _collect_batches(toy_dataset, _cfg(toy_dataset))
    chaos.arm("loader.read_block:nth=1")
    healed, loader = _collect_batches(toy_dataset, _cfg(toy_dataset))
    assert chaos.fired() == {"loader.read_block": 1}
    assert len(healed) == len(clean)
    for a, b in zip(clean, healed):
        np.testing.assert_array_equal(a.keys, b.keys)
        np.testing.assert_array_equal(a.labels, b.labels)
    assert loader._quarantined == 0


def test_loader_persistent_corruption_quarantines(toy_dataset):
    """A block that fails past the retry budget is SKIPPED (health row
    + counter), not fatal — and the stream keeps going."""
    clean, _ = _collect_batches(toy_dataset, _cfg(toy_dataset))
    # nth=1 keeps firing only on hit 1..  every retry re-hits, so use
    # p=1,times=N with N > io_retries to exhaust one block's budget
    chaos.arm("loader.parse_record:p=1,times=3")
    cfg = _cfg(toy_dataset, io_retries=2, io_retry_backoff_s=0.0)
    healed, loader = _collect_batches(toy_dataset, cfg)
    assert loader._quarantined == 1
    assert len(healed) < len(clean)  # the block's samples are gone


def test_loader_quarantine_budget_aborts(toy_dataset):
    from xflow_tpu.io.loader import QuarantineExceeded

    chaos.arm("loader.parse_record:p=1")  # every block, forever
    cfg = _cfg(toy_dataset, io_retries=0, max_quarantined_frac=0.05)
    trainer = Trainer(cfg)
    loader = trainer._loader(toy_dataset.train_prefix + "-00000")
    # toy shards are one block each: force more blocks per shard
    loader.block_bytes = 1 << 10
    with pytest.raises(QuarantineExceeded):
        for _ in loader.iter_batches():
            pass
    trainer.close()


def test_loader_health_rows_flow_without_flight_recorder(
    toy_dataset, tmp_path
):
    """The heal is loud whenever a metrics stream exists — the flight
    recorder being off must not silence recovered:io_retry."""
    metrics = tmp_path / "m.jsonl"
    chaos.arm("loader.read_block:nth=1")
    cfg = _cfg(toy_dataset, metrics_out=str(metrics))
    trainer = Trainer(cfg)
    trainer.train()
    trainer.close()
    rows = [json.loads(l) for l in metrics.read_text().splitlines()]
    causes = [r["cause"] for r in rows if r["kind"] == "health"]
    assert "recovered:io_retry" in causes
    assert [r["site"] for r in rows if r["kind"] == "chaos"] == [
        "loader.read_block"
    ]


# -- checkpoint -------------------------------------------------------------


def test_latest_complete_and_missing_manifest_refusal(tmp_path):
    from xflow_tpu.utils.checkpoint import (
        IncompatibleCheckpoint,
        checkpoint_candidates,
        latest_complete,
        load_checkpoint,
    )

    ck = tmp_path / "ck"
    (ck / "ckpt-0000000005").mkdir(parents=True)
    (ck / "ckpt-0000000005" / "manifest.json").write_text("{}")
    (ck / "ckpt-0000000009").mkdir()  # newer, no manifest
    (ck / ".tmp-ckpt-0000000011").mkdir()  # never a candidate
    assert checkpoint_candidates(str(ck)) == [
        str(ck / "ckpt-0000000009"), str(ck / "ckpt-0000000005"),
    ]
    assert latest_complete(str(ck)) == str(ck / "ckpt-0000000005")
    with pytest.raises(IncompatibleCheckpoint, match="manifest"):
        load_checkpoint(str(ck / "ckpt-0000000009"), {"tables": {}})


def test_gc_counts_only_complete_generations(tmp_path):
    """An externally corrupted manifest-less dir must neither occupy a
    keep slot (leaving < keep restorable generations) nor be deleted
    (it is evidence)."""
    from xflow_tpu.utils.checkpoint import gc_checkpoints

    ck = tmp_path / "ck"
    for step, complete in [(1, True), (2, True), (3, True), (9, False)]:
        d = ck / f"ckpt-{step:010d}"
        d.mkdir(parents=True)
        if complete:
            (d / "manifest.json").write_text("{}")
    removed = gc_checkpoints(str(ck), keep=2)
    left = sorted(p.name for p in ck.iterdir())
    # oldest complete gen pruned; BOTH newer complete gens survive the
    # budget despite the newest-sorting corrupt dir, which stays put
    assert [os.path.basename(r) for r in removed] == ["ckpt-0000000001"]
    assert left == [
        "ckpt-0000000002", "ckpt-0000000003", "ckpt-0000000009",
    ]


def test_dropped_chaos_rows_are_countable():
    class _Raising:
        def log(self, kind, record):
            raise OSError("logger died")

    reg = chaos.arm("x.y:nth=1")
    chaos.attach_logger(_Raising())
    with pytest.raises(chaos.ChaosError):
        chaos.failpoint("x.y")  # the drop must not mask the fault
    assert reg.dropped_rows() == 1
    assert reg.fired() == {"x.y": 1}


def test_writeback_heal_on_checkpoint_path_is_loud(toy_dataset, tmp_path):
    """A store.writeback transient healed during the PRE-CHECKPOINT
    flush (a no-per-call-obs path) still emits its recovery row —
    'recovery is never silent' holds on every call path."""
    metrics = tmp_path / "m.jsonl"
    ck = tmp_path / "ck"
    cfg = _tiered_cfg(
        toy_dataset, metrics_out=str(metrics), checkpoint_dir=str(ck)
    )
    t = Trainer(cfg)
    try:
        # fresh store: the first batch's keys all MISS, so dispatch
        # leaves a non-empty pending write-back for save to flush
        loader = t._loader(toy_dataset.train_prefix + "-00000")
        batch = next(loader.iter_batches())[0]
        arrays = t.step.put_batch(batch)
        t.state, _ = t.step.dispatch_train(t.state, arrays)
        reg = chaos.arm("store.writeback:nth=1")
        t.save(0, 0)
        assert reg.fired() == {"store.writeback": 1}
    finally:
        t.close()
    rows = [json.loads(l) for l in metrics.read_text().splitlines()]
    causes = [r["cause"] for r in rows if r["kind"] == "health"]
    assert "recovered:io_retry" in causes


def test_checkpoint_keep_default_prunes(toy_dataset, tmp_path):
    """keep-last-N GC (default 2): a run that checkpoints every few
    steps ends with at most 2 committed generations."""
    ck = tmp_path / "ck"
    cfg = _cfg(
        toy_dataset, checkpoint_dir=str(ck), checkpoint_every_steps=2
    )
    assert cfg.checkpoint_keep == 2
    t = Trainer(cfg)
    t.train()
    t.close()
    gens = [d for d in os.listdir(ck) if d.startswith("ckpt-")]
    assert 1 <= len(gens) <= 2


def test_kill_mid_commit_then_resume_auto_parity(toy_dataset, tmp_path):
    """The tentpole invariant in miniature: epoch-0 generation commits,
    the epoch-1 save is killed mid-commit, resume auto restores the
    complete generation and retraining converges to the fault-free
    weights exactly."""
    ref = Trainer(_cfg(toy_dataset, epochs=2))
    ref.train()
    w_ref = np.asarray(ref.state["tables"]["w"]["param"])
    ref.close()

    ck = tmp_path / "ck"
    cfg = _cfg(toy_dataset, epochs=2, checkpoint_dir=str(ck))
    chaos.arm("ckpt.finalize:nth=2")
    t1 = Trainer(cfg)
    with pytest.raises(chaos.ChaosError):
        t1.train()
    t1.close()
    chaos.disarm()

    t2 = Trainer(cfg)
    cursor = t2.restore(auto=True)
    assert cursor is not None and cursor["epoch"] == 1
    t2.train()
    w2 = np.asarray(t2.state["tables"]["w"]["param"])
    t2.close()
    np.testing.assert_allclose(w2, w_ref, atol=1e-6)


def test_restore_auto_falls_back_past_failing_candidate(
    toy_dataset, tmp_path
):
    """ckpt.restore firing on the newest generation (transient restore
    error) makes auto mode fall back to the next one; plain mode
    propagates."""
    ck = tmp_path / "ck"
    # checkpoint_every_steps yields several distinct generations;
    # keep-last-N (default 2) retains two
    cfg = _cfg(toy_dataset, checkpoint_dir=str(ck), checkpoint_every_steps=3)
    t = Trainer(cfg)
    t.train()
    t.close()
    from xflow_tpu.utils.checkpoint import checkpoint_candidates

    assert len(checkpoint_candidates(str(ck))) == 2

    chaos.arm("ckpt.restore:nth=1")
    t2 = Trainer(cfg)
    cursor = t2.restore(auto=True)
    assert cursor is not None  # healed by falling back
    t2.close()

    chaos.arm("ckpt.restore:nth=1")
    t3 = Trainer(cfg)
    with pytest.raises(chaos.ChaosError):
        t3.restore()  # plain mode: the error propagates
    t3.close()


# -- store ------------------------------------------------------------------


def _tiered_cfg(ds, **kw):
    return _cfg(
        ds,
        model="fm",
        table_size_log2=16,
        store_mode="tiered",
        hot_capacity_log2=10,
        **kw,
    )


def test_promote_worker_death_restarted_once(toy_dataset, tmp_path):
    metrics = tmp_path / "m.jsonl"
    chaos.arm("store.promote_worker:nth=1")
    t = Trainer(_tiered_cfg(toy_dataset, metrics_out=str(metrics)))
    t.train()
    store = t.step.store
    assert store._promoter_restarts == 1
    assert not store._promoter_dead
    assert store.promoter.alive()  # the restarted worker is live
    t.close()
    rows = [json.loads(l) for l in metrics.read_text().splitlines()]
    causes = [r["cause"] for r in rows if r["kind"] == "health"]
    assert "store_promote_restarted" in causes


def test_promote_worker_second_death_freezes_placement(
    toy_dataset, tmp_path
):
    metrics = tmp_path / "m.jsonl"
    chaos.arm("store.promote_worker:every=1,times=2")
    t = Trainer(
        _tiered_cfg(toy_dataset, epochs=2, metrics_out=str(metrics))
    )
    t.train()  # must COMPLETE: placement frozen, training correct
    store = t.step.store
    assert store._promoter_dead
    t.close()
    rows = [json.loads(l) for l in metrics.read_text().splitlines()]
    causes = [r["cause"] for r in rows if r["kind"] == "health"]
    assert "store_promote_dead" in causes
    leaked = [
        th.name for th in threading.enumerate()
        if th.name.startswith("store-promote") and th.is_alive()
    ]
    assert leaked == []


def test_cold_fetch_transient_healed(toy_dataset, tmp_path):
    metrics = tmp_path / "m.jsonl"
    chaos.arm("store.cold_fetch:nth=2")
    t = Trainer(_tiered_cfg(toy_dataset, metrics_out=str(metrics)))
    t.train()
    t.close()
    assert chaos.fired() == {"store.cold_fetch": 1}
    rows = [json.loads(l) for l in metrics.read_text().splitlines()]
    causes = [r["cause"] for r in rows if r["kind"] == "health"]
    assert "recovered:io_retry" in causes


# -- serve ------------------------------------------------------------------


@pytest.fixture(scope="module")
def lr_artifact(toy_dataset, tmp_path_factory):
    from xflow_tpu.serve.artifact import export_artifact

    chaos.disarm()  # module fixture builds before the autouse fixture
    trainer = Trainer(_cfg(toy_dataset))
    trainer.train()
    art = str(tmp_path_factory.mktemp("chaos_serve") / "artifact")
    export_artifact(trainer, art)
    trainer.close()
    return art


def test_fleet_evicts_and_revives(lr_artifact):
    from xflow_tpu.serve.fleet import ReplicaFleet

    log = _FakeLogger()
    fleet = ReplicaFleet.load(
        lr_artifact, replicas=2, buckets=(1, 4), warm=False,
        metrics_logger=log, evict_after_errors=1,
    )
    ref = fleet.score(np.array([3, 5, 7]))
    chaos.arm("serve.replica_score:p=1,times=1")
    chaos.attach_logger(log)
    with pytest.raises(chaos.ChaosError):
        fleet.score(np.array([3, 5, 7]))
    deadline = time.perf_counter() + 15.0
    while time.perf_counter() < deadline:
        h = fleet.health()
        if not h["unhealthy"] and h["revivals"] >= 1:
            break
        time.sleep(0.02)
    h = fleet.health()
    assert h["evictions"] == 1 and h["revivals"] == 1
    assert h["unhealthy"] == []
    # the revived clone serves the same artifact state
    assert fleet.score(np.array([3, 5, 7])) == pytest.approx(
        ref, abs=1e-6
    )
    assert fleet.stats()["health"]["revivals"] == 1
    fleet.close()
    causes = [r["cause"] for r in log.rows if r["kind"] == "health"]
    assert causes.count("replica_evicted") == 1
    assert causes.count("replica_revived") == 1


def test_all_replicas_evicted_sheds_typed(lr_artifact):
    from xflow_tpu.serve.fleet import ReplicaFleet, ShedError

    fleet = ReplicaFleet.load(
        lr_artifact, replicas=1, buckets=(1, 4), warm=False,
        evict_after_errors=1, revive=False,
    )
    chaos.arm("serve.replica_score:p=1,times=1")
    with pytest.raises(chaos.ChaosError):
        fleet.score(np.array([1]))
    deadline = time.perf_counter() + 5.0
    while time.perf_counter() < deadline and not fleet.health()["evictions"]:
        time.sleep(0.01)
    with pytest.raises(ShedError) as ei:
        fleet.submit(np.array([1]))
    assert ei.value.cause == "replica_unavailable"
    shed = fleet.close()["shed"]
    assert shed["by_cause"].get("replica_unavailable", 0) >= 1


def test_serve_accept_failpoint_survives(lr_artifact):
    """An injected accept-loop fault must not kill serve_forever: the
    tier keeps answering after the fires."""
    import urllib.request

    from xflow_tpu.serve.fleet import ReplicaFleet
    from xflow_tpu.serve.server import ServeTier

    fleet = ReplicaFleet.load(
        lr_artifact, replicas=1, buckets=(1, 4), warm=False
    )
    chaos.arm("serve.accept:every=1,times=3")
    tier = ServeTier(fleet, poll_s=0.02)
    tier.start()
    try:
        deadline = time.perf_counter() + 10.0
        while time.perf_counter() < deadline and tier.accept_faults < 3:
            time.sleep(0.02)
        assert tier.accept_faults == 3
        with urllib.request.urlopen(
            tier.address + "/healthz", timeout=10
        ) as r:
            doc = json.loads(r.read())
        assert doc["status"] == "serving"
    finally:
        tier.close()


# -- doctor -----------------------------------------------------------------


def _health(cause, channel="loader"):
    return {
        "t": 1.0, "kind": "health", "cause": cause, "channel": channel,
        "silence_seconds": 0.0, "threshold_seconds": 0.0,
        "detail": "", "channels": {},
    }


def _chaos_row(site):
    return {
        "t": 1.0, "kind": "chaos", "site": site, "hit": 1, "fires": 1,
        "detail": "seed=0",
    }


def test_doctor_blames_quarantine_budget_not_input_stall():
    from xflow_tpu.obs.doctor import diagnose

    rows = [
        _chaos_row("loader.parse_record"),
        _health("record_quarantined"),
        _health("quarantine_budget_exceeded"),
    ]
    findings = diagnose(rows)
    crit = [d for d in findings if d.severity == "crit"]
    assert any(d.code == "quarantine_budget_exceeded" for d in crit)
    assert any("NOT an input stall" in d.message for d in crit)
    # and no generic watchdog-trip misreading of the same rows
    assert not any(
        "watchdog tripped" in d.message for d in findings
    )


def test_doctor_ranks_absorbed_vs_unrevived_eviction():
    from xflow_tpu.obs.doctor import diagnose

    absorbed = diagnose([
        _chaos_row("serve.replica_score"),
        _health("replica_evicted", "serve"),
        _health("replica_revived", "serve"),
    ])
    d = next(d for d in absorbed if d.code == "replica_evicted")
    assert d.severity == "info" and "revived" in d.message
    assert any(d.code == "chaos_absorbed" for d in absorbed)

    stuck = diagnose([
        _chaos_row("serve.replica_score"),
        _health("replica_evicted", "serve"),
    ])
    d = next(d for d in stuck if d.code == "replica_evicted")
    assert d.severity == "warn" and "reduced capacity" in d.message
    assert any(d.code == "fault_storm" for d in stuck)


def test_doctor_flags_real_heals_without_chaos_rows():
    """Production faults (chaos disarmed, no `chaos` rows) must still
    produce a verdict: failing checkpoint saves and under-budget
    quarantines are warnings, not silence."""
    from xflow_tpu.obs.doctor import diagnose

    findings = diagnose([
        _health("checkpoint_save_failed", "train"),
        _health("record_quarantined"),
    ])
    codes = {d.code: d.severity for d in findings}
    assert codes.get("checkpoint_save_failed") == "warn"
    assert codes.get("record_quarantined") == "warn"
    # budget-exceeded escalates to the crit and subsumes the warn
    findings = diagnose([
        _health("record_quarantined"),
        _health("quarantine_budget_exceeded"),
    ])
    codes = {d.code: d.severity for d in findings}
    assert codes.get("quarantine_budget_exceeded") == "crit"
    assert "record_quarantined" not in codes
    # a fallback-only stream (silent training rewind) is NOT healthy
    findings = diagnose([_health("checkpoint_fallback", "train")])
    codes = {d.code: d.severity for d in findings}
    assert codes.get("checkpoint_fallback") == "warn"


def test_config_armed_schedule_dies_with_trainer(toy_dataset):
    """A chaos_spec-armed schedule's lifetime is its Trainer's: close()
    disarms it so later non-chaos Trainers in the same process never
    inherit injected faults.  Directly/env-armed registries survive."""
    t = Trainer(_cfg(toy_dataset, chaos_spec="loader.read_block:nth=999"))
    assert chaos.armed() is not None
    t.close()
    assert chaos.armed() is None
    reg = chaos.arm("x.y:nth=1")  # armed outside any trainer
    t2 = Trainer(_cfg(toy_dataset))
    t2.close()
    assert chaos.armed() is reg


def test_doctor_healthy_stream_has_no_chaos_findings():
    from xflow_tpu.obs.doctor import diagnose

    findings = diagnose([_health("recovered:io_retry")])
    assert not any(
        d.code in ("fault_storm", "chaos_absorbed") for d in findings
    )
    assert not any(d.severity in ("crit", "warn") for d in findings)


# -- tier-1 gate ------------------------------------------------------------


def test_check_chaos_script():
    """The chaos gate (scripts/check_chaos.py) passes — run as a
    subprocess exactly as CI would (tier-1 wiring, like
    check_store_smoke.py)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "check_chaos.py")],
        capture_output=True,
        text=True,
        timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout
