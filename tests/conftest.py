"""Test harness: emulate an 8-device pod on CPU.

The reference proves its whole distributed topology as plain processes
on one host (scripts/local.sh, SURVEY §4 item 2); the JAX equivalent is
8 virtual CPU devices via XLA_FLAGS, which every sharding test uses.
Must run before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Some environments (TPU plugins registered from sitecustomize) import
# jax before this conftest runs, making the env var too late; backend
# selection is still lazy, so force it through the config as well.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(scope="session")
def toy_dataset(tmp_path_factory):
    """Synthetic libffm dataset with learnable structure, regenerating the
    shape of the reference's bundled toy data (SURVEY §2 #19: shards
    ``prefix-%05d``, ~18 fields/sample, fid < 10^4, ``label\\tfgid:fid:val``
    lines)."""
    from tests.gen_data import generate_dataset

    root = tmp_path_factory.mktemp("toy")
    return generate_dataset(
        str(root),
        num_train_shards=3,
        lines_per_shard=200,
        num_fields=10,
        vocab_per_field=8,
        seed=7,
        scale=3.0,
    )
