"""Test harness: emulate an 8-device pod on CPU.

The reference proves its whole distributed topology as plain processes
on one host (scripts/local.sh, SURVEY §4 item 2); the JAX equivalent is
8 virtual CPU devices via XLA_FLAGS, which every sharding test uses.
Must run before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Some environments (TPU plugins registered from sitecustomize) import
# jax before this conftest runs, making the env var too late; backend
# selection is still lazy, so force it through the config as well.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

# -- multiprocess-CPU capability probe ------------------------------------
#
# tests/test_distributed.py needs REAL 2-process collectives on the CPU
# backend (gloo).  Some images ship a jaxlib whose CPU client cannot do
# cross-process computations at all ("Multiprocess computations aren't
# implemented on the CPU backend") — there the 6 distributed tests can
# never pass, and failing every tier-1 run teaches people to ignore
# red.  Probe the capability ONCE per session (two short-lived
# subprocesses running one allgather) and skip-mark the distributed
# tests with the probe's reason when it is absent.

_MP_CPU_PROBE: tuple[bool, str] | None = None


def _multiprocess_cpu_capable(timeout: float = 180.0) -> tuple[bool, str]:
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    code = (
        "import sys\n"
        "import numpy as np\n"
        "import jax\n"
        "jax.distributed.initialize("
        f"coordinator_address='localhost:{port}', "
        "num_processes=2, process_id=int(sys.argv[1]))\n"
        "from jax.experimental import multihost_utils\n"
        "out = multihost_utils.process_allgather(np.int32(1))\n"
        "assert int(np.asarray(out).sum()) == 2\n"
    )
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", code, str(i)],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE,
            text=True,
        )
        for i in range(2)
    ]
    errs: list[str] = []
    for p in procs:
        try:
            _, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            for q in procs:
                q.communicate()
            return False, "2-process CPU collective probe timed out"
        errs.append(err or "")
    if all(p.returncode == 0 for p in procs):
        return True, ""
    tail = next(
        (
            line.strip()
            for e in errs
            for line in reversed(e.strip().splitlines())
            if line.strip()
        ),
        "unknown failure",
    )
    return False, f"2-process CPU collectives unavailable: {tail[:160]}"


def pytest_collection_modifyitems(config, items):
    dist = [
        item
        for item in items
        if os.path.basename(str(item.fspath)) == "test_distributed.py"
    ]
    if not dist:
        return
    global _MP_CPU_PROBE
    if _MP_CPU_PROBE is None:
        _MP_CPU_PROBE = _multiprocess_cpu_capable()
    capable, reason = _MP_CPU_PROBE
    if capable:
        return
    marker = pytest.mark.skip(
        reason=f"multiprocess-CPU environment limitation: {reason}"
    )
    for item in dist:
        item.add_marker(marker)


@pytest.fixture(scope="session")
def toy_dataset(tmp_path_factory):
    """Synthetic libffm dataset with learnable structure, regenerating the
    shape of the reference's bundled toy data (SURVEY §2 #19: shards
    ``prefix-%05d``, ~18 fields/sample, fid < 10^4, ``label\\tfgid:fid:val``
    lines)."""
    from tests.gen_data import generate_dataset

    root = tmp_path_factory.mktemp("toy")
    return generate_dataset(
        str(root),
        num_train_shards=3,
        lines_per_shard=200,
        num_fields=10,
        vocab_per_field=8,
        seed=7,
        scale=3.0,
    )
