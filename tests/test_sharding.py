"""Multi-device correctness: the 8-virtual-device mesh (the moral
equivalent of the reference's scripts/local.sh multi-process proof,
SURVEY §4) must produce bit-identical training to a single device —
synchronous SPMD has no Hogwild nondeterminism to hide behind."""

import numpy as np
import jax
import pytest

from xflow_tpu.config import Config
from xflow_tpu.parallel.mesh import make_mesh, table_sharding
from xflow_tpu.trainer import Trainer


def cfg_for(ds, ndev, model="lr", **kw):
    base = dict(
        train_path=ds.train_prefix,
        test_path=ds.test_prefix,
        epochs=2,
        batch_size=64,
        table_size_log2=14,
        max_nnz=24,
        max_fields=20,
        num_devices=ndev,
    )
    base.update(kw)
    return Config(model=model, **base)


def table_host(trainer, name="w"):
    return np.asarray(jax.device_get(trainer.state["tables"][name]["param"]))


def test_eight_devices_available():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual CPU devices"


@pytest.mark.parametrize("model,table", [("lr", "w"), ("fm", "v"), ("mvm", "v")])
def test_sharded_matches_single_device(toy_dataset, model, table):
    t1 = Trainer(cfg_for(toy_dataset, 1, model))
    t1.train()
    t8 = Trainer(cfg_for(toy_dataset, 8, model))
    t8.train()
    w1 = table_host(t1, table)
    w8 = table_host(t8, table)
    np.testing.assert_allclose(w1, w8, rtol=1e-5, atol=1e-7)


def test_table_actually_sharded(toy_dataset):
    t8 = Trainer(cfg_for(toy_dataset, 8))
    param = t8.state["tables"]["w"]["param"]
    assert len(param.sharding.device_set) == 8
    shard_rows = {s.data.shape[0] for s in param.addressable_shards}
    assert shard_rows == {param.shape[0] // 8}


def test_eval_matches_across_meshes(toy_dataset):
    t1 = Trainer(cfg_for(toy_dataset, 1))
    t1.train()
    r1 = t1.evaluate()
    t8 = Trainer(cfg_for(toy_dataset, 8))
    t8.train()
    r8 = t8.evaluate()
    assert abs(r1["auc"] - r8["auc"]) < 1e-6
    assert abs(r1["logloss"] - r8["logloss"]) < 1e-6


def test_mesh_construction():
    mesh = make_mesh(4)
    assert mesh.devices.size == 4
    sh = table_sharding(mesh)
    assert sh.spec == jax.sharding.PartitionSpec("data", None)
