"""Host-side batch compaction (io/compact.py) and the dictionary wire
(Config.wire_dedup): compaction must round-trip loader batches
byte-exact, the native and numpy dedup kernels must agree, plane
capacities must bucket deterministically (compile_count stays flat),
and training/prediction over the dict wire must match the plain wire —
compression changes what crosses the link, never the math."""

import subprocess
import sys
import os

import numpy as np
import pytest
import jax

from xflow_tpu.config import Config
from xflow_tpu.io.batch import make_batch
from xflow_tpu.io.compact import (
    DICT_CAP,
    CompactBatch,
    compact_batch,
    dedup_select,
    plane_cap,
)

from tests.test_binary import batches_equal, make_loader

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _numpy_dedup(keys, cap):
    """Force the numpy fallback path regardless of the native build."""
    import unittest.mock as mock

    from xflow_tpu import native

    with mock.patch.object(native, "has_dict_encode", lambda: False):
        return dedup_select(keys, cap)


def _decode(keys, uniq, codes):
    """Per-element keys implied by a (uniq, codes) encoding."""
    m = codes != 0xFFFFFFFF
    got = keys.copy()
    if m.any():
        got[m] = uniq[codes[m].astype(np.int64)]
    return got, m


# -- kernel ----------------------------------------------------------------


@pytest.mark.parametrize("dist", ["random", "zipf"])
def test_dedup_select_native_numpy_parity(dist):
    """Same dictionary SET and same per-element tier on both kernel
    implementations (within-dictionary order is free), and both
    encodings decode back to the input keys."""
    from xflow_tpu import native

    rng = np.random.default_rng(3)
    if dist == "random":
        keys = rng.integers(0, 1 << 22, 40000).astype(np.int64)
    else:
        keys = (rng.zipf(1.3, 40000) - 1).astype(np.int64)
    for cap in (64, 1024, DICT_CAP):
        u_np, c_np = _numpy_dedup(keys, cap)
        assert len(u_np) <= cap
        d_np, m_np = _decode(keys, u_np, c_np)
        np.testing.assert_array_equal(d_np, keys)
        if not (native.available() and native.has_dict_encode()):
            continue
        u_nat, c_nat = native.native_dict_encode(keys, cap)
        assert set(u_nat.tolist()) == set(u_np.tolist())
        d_nat, m_nat = _decode(keys, u_nat, c_nat)
        np.testing.assert_array_equal(d_nat, keys)
        np.testing.assert_array_equal(m_nat, m_np)


def test_dedup_select_small_fits_whole_dictionary():
    keys = np.asarray([5, 5, 9, 5, 9, 7], np.int64)
    uniq, codes = _numpy_dedup(keys, DICT_CAP)
    assert sorted(uniq.tolist()) == [5, 7, 9]
    assert (codes != 0xFFFFFFFF).all()
    got, _ = _decode(keys, uniq, codes)
    np.testing.assert_array_equal(got, keys)


def test_dedup_select_threshold_caps_dictionary():
    """With more unique keys than cap, the dictionary keeps the
    most-duplicated ones (count >= threshold) and the tail codes as
    0xFFFFFFFF."""
    rng = np.random.default_rng(0)
    hot = np.repeat(np.arange(10, dtype=np.int64), 50)
    tail = rng.integers(1000, 1 << 30, 500).astype(np.int64)
    keys = np.concatenate([hot, tail])
    rng.shuffle(keys)
    uniq, codes = _numpy_dedup(keys, 16)
    assert set(np.arange(10).tolist()) <= set(uniq.tolist())
    assert len(uniq) <= 16
    got, covered = _decode(keys, uniq, codes)
    np.testing.assert_array_equal(got, keys)
    assert covered.sum() >= 500  # the hot head is covered


# -- capacities ------------------------------------------------------------


def test_plane_cap_bucketing():
    slots = 131072 * 16
    g = max(256, slots // 32)
    assert plane_cap(0, slots) == 0
    assert plane_cap(1, slots) == g
    assert plane_cap(g, slots) == g
    assert plane_cap(g + 1, slots) == 2 * g
    assert plane_cap(slots, slots) == slots
    assert plane_cap(slots - 1, slots) == slots  # never exceeds slots
    # nearby batch sizes share one bucket -> one compiled program
    assert plane_cap(g + 5, slots) == plane_cap(g + g // 2, slots)


# -- round trip ------------------------------------------------------------


@pytest.mark.parametrize("hot", [False, True])
def test_compact_roundtrip_loader_batches(toy_dataset, hot):
    """compact -> expand is byte-exact for every loader-produced batch,
    including the zero-padded partial tail batch."""
    src = toy_dataset.train_prefix + "-00000"
    kw = dict(hot_size=256, hot_nnz=6) if hot else {}
    if hot:
        rng = np.random.default_rng(3)
        kw["remap"] = rng.permutation(1 << 14).astype(np.int32)
    loader = make_loader(src, **kw)
    n = 0
    for batch, _ in loader.iter_batches():
        cb = compact_batch(batch, 1 << 14, 256 if hot else 0)
        batches_equal(batch, cb.expand())
        assert cb.num_real() == batch.num_real()
        np.testing.assert_array_equal(cb.labels, batch.labels)
        np.testing.assert_array_equal(cb.weights, batch.weights)
        n += 1
    assert n > 2


def test_compact_roundtrip_all_padding():
    """An all-padding batch (every key sentinel/masked) compacts to
    empty planes and expands back to zeros."""
    b, k = 8, 6
    z_i = np.zeros((b, k), np.int32)
    z_f = np.zeros((b, k), np.float32)
    batch = make_batch(
        z_i, z_i, z_f, z_f,
        np.zeros(b, np.float32), np.zeros(b, np.float32),
    )
    cb = compact_batch(batch, 1 << 14, 0)
    assert cb.n_cold == 0 and cb.n_dict == 0 and cb.num_real() == 0
    batches_equal(batch, cb.expand())


def test_compact_wire_is_smaller_and_fixed_point(toy_dataset):
    """The wire is smaller than the plain compact wire's planes, and
    compact(expand(cb)) reproduces cb's planes exactly (the packed-v2
    fixed point)."""
    from xflow_tpu.parallel.step import compact_wire_np

    src = toy_dataset.train_prefix + "-00000"
    loader = make_loader(src)
    batch, _ = next(iter(loader.iter_batches()))
    cb = compact_batch(batch, 1 << 14, 0)
    old = sum(
        v.nbytes for v in compact_wire_np(batch, ship_slots=True).values()
    )
    assert cb.wire_nbytes(ship_slots=True) < old
    cb2 = compact_batch(cb.expand(), 1 << 14, 0)
    for f in (
        "cu", "ci", "ct", "cf", "cc", "h8", "hx", "hxh", "hf", "hc",
        "lb", "wb", "cs", "hs",
    ):
        np.testing.assert_array_equal(
            getattr(cb, f), getattr(cb2, f), err_msg=f
        )


def test_packed_v2_mmap_vs_buffered_byte_equality(toy_dataset, tmp_path):
    """The packed-v2 reader's two paths — zero-copy mmap views of the
    shard file (the fan-out steady state) and the buffered fallback
    (unmmapable streams: no fileno) — must produce byte-identical
    planes, counts and record offsets.  The mmap path really is
    zero-copy: each plane's memory is backed by the mapping, not a
    per-record allocation."""
    import io as _io
    import mmap as _mmap

    from xflow_tpu.io import packed

    src = toy_dataset.train_prefix + "-00000"
    dst = str(tmp_path / "shard.pk2")
    packed.convert_shard(
        src, dst, fmt="v2", batch_size=32, max_nnz=24,
        table_size=1 << 14,
    )
    with open(dst, "rb") as f:
        via_mmap = list(packed.iter_compact_batches(f))
    with open(dst, "rb") as f:
        blob = f.read()
    # BytesIO has no usable fileno -> the reader falls back to read()
    via_buffer = list(packed.iter_compact_batches(_io.BytesIO(blob)))
    assert len(via_mmap) == len(via_buffer) > 1
    planes = (
        "cu", "ci", "ct", "cf", "cc", "h8", "hx", "hxh", "hf", "hc",
        "lb", "wb", "cs", "hs",
    )
    for (ma, oa, na), (mb, ob, nb) in zip(via_mmap, via_buffer):
        assert (oa, na) == (ob, nb)
        assert ma.n_real == mb.n_real and ma.n_cold == mb.n_cold
        for pl in planes:
            a, b = getattr(ma, pl), getattr(mb, pl)
            assert a.dtype == b.dtype
            np.testing.assert_array_equal(a, b, err_msg=pl)
    # zero-copy witness: an mmap-path plane's base buffer is the map
    def root_buffer(arr):
        while isinstance(getattr(arr, "base", None), np.ndarray):
            arr = arr.base
        return getattr(arr, "base", None)

    first = via_mmap[0][0]
    # hot-off shards synthesize default hot planes (from_planes) — the
    # zero-copy witness only applies to planes present in the record
    record_planes = ("cu", "ci", "ct", "cf", "cc", "lb", "wb", "cs")
    sized = [
        getattr(first, pl) for pl in record_planes
        if getattr(first, pl).size
    ]
    def is_map_backed(buf):
        return isinstance(buf, _mmap.mmap) or (
            isinstance(buf, memoryview)
            and isinstance(buf.obj, _mmap.mmap)
        )

    assert sized and all(
        is_map_backed(root_buffer(arr)) for arr in sized
    ), "mmap-path planes are not views of the mapping"
    # padded expansion equality too (the v1-contract surface)
    with open(dst, "rb") as f:
        exp_mmap = [b for b, _, _ in packed.iter_batches(f)]
    exp_buf = [b for b, _, _ in packed.iter_batches(_io.BytesIO(blob))]
    for a, b in zip(exp_mmap, exp_buf):
        batches_equal(a, b)


# -- validation ------------------------------------------------------------


def test_compact_rejects_value_batches():
    b = make_batch(
        np.zeros((2, 3), np.int32), np.zeros((2, 3), np.int32),
        np.asarray([[0.5, 1, 1], [1, 1, 1]], np.float32),
        np.ones((2, 3), np.float32),
        np.zeros(2, np.float32), np.ones(2, np.float32),
    )
    with pytest.raises(ValueError, match="binary features"):
        compact_batch(b, 1 << 14, 0)


def test_holey_rows_compact_semantically_but_not_strictly():
    """Rows with interior padding (mask holes) still ride the dict
    wire — entries re-compact leftward with their triplets intact
    (models are permutation-invariant over the feature axis) — but the
    packed-v2 writer's strict_layout contract refuses them, because
    byte-exact round-trip is impossible."""
    mask = np.asarray([[1, 0, 1]], np.float32)
    b = make_batch(
        np.asarray([[3, 0, 5]], np.int32),
        np.asarray([[1, 0, 2]], np.int32),
        mask.copy(), mask,
        np.zeros(1, np.float32), np.ones(1, np.float32),
    )
    eb = compact_batch(b, 1 << 14, 0).expand()
    np.testing.assert_array_equal(eb.keys, [[3, 5, 0]])
    np.testing.assert_array_equal(eb.slots, [[1, 2, 0]])
    np.testing.assert_array_equal(eb.mask, [[1, 1, 0]])
    with pytest.raises(ValueError, match="left-compacted"):
        compact_batch(b, 1 << 14, 0, strict_layout=True)


def test_compact_rejects_out_of_range_keys():
    mask = np.ones((1, 2), np.float32)
    b = make_batch(
        np.asarray([[3, 40000]], np.int32), np.zeros((1, 2), np.int32),
        mask.copy(), mask,
        np.zeros(1, np.float32), np.ones(1, np.float32),
    )
    with pytest.raises(ValueError, match="table_size"):
        compact_batch(b, 1 << 14, 0)


# -- wire parity on device -------------------------------------------------


def _train_once(cfg, batch):
    from xflow_tpu.models import make_model
    from xflow_tpu.optim import make_optimizer
    from xflow_tpu.parallel.mesh import make_mesh
    from xflow_tpu.parallel.step import TrainStep, init_state

    mesh = make_mesh(1)
    model, opt = make_model(cfg), make_optimizer(cfg)
    step = TrainStep(model, opt, cfg, mesh)
    state = init_state(model, opt, cfg, mesh)
    state, m = step.train(state, step.put_batch(batch))
    pctr = step.predict(state, step.put_batch(batch))
    return step, jax.device_get(state["tables"]), np.asarray(pctr)


@pytest.mark.parametrize("model", ["lr", "mvm"])
@pytest.mark.parametrize("cold_consolidate", [False, True])
def test_dict_wire_matches_plain_wire(model, cold_consolidate):
    """One train step + predict over the dict wire equals the plain
    compact wire to float tolerance, with and without the shipped
    consolidation plan (cold_consolidate arms the indexed scatter)."""
    rng = np.random.default_rng(11)
    b, k = 64, 24
    nnz = rng.integers(1, k, b)
    mask = (np.arange(k)[None, :] < nnz[:, None]).astype(np.float32)
    keys = np.where(
        mask > 0, rng.integers(0, 1 << 14, (b, k)), 0
    ).astype(np.int32)
    head = rng.integers(0, 64, (b, k)).astype(np.int32)
    keys = np.where((rng.random((b, k)) < 0.5) & (mask > 0), head, keys)
    slots = np.where(mask > 0, rng.integers(0, 8, (b, k)), 0).astype(
        np.int32
    )
    labels = (rng.random(b) < 0.4).astype(np.float32)
    weights = (np.arange(b) < 60).astype(np.float32)
    batch = make_batch(
        keys, slots, mask.copy(), mask, labels * weights, weights,
        1 << 8, 8,
    )
    kw = dict(
        model=model, batch_size=b, table_size_log2=14, max_nnz=16,
        max_fields=8, num_devices=1, hot_size_log2=8, hot_nnz=8,
        cold_consolidate=cold_consolidate,
    )
    step_off, tables_off, pctr_off = _train_once(
        Config(wire_dedup="off", **kw), batch
    )
    step_on, tables_on, pctr_on = _train_once(
        Config(wire_dedup="on", **kw), batch
    )
    assert not step_off.dict_wire and step_on.dict_wire
    assert step_on.wire_format == "dict"
    jax.tree.map(
        lambda a, c: np.testing.assert_allclose(
            a, c, rtol=1e-5, atol=1e-6
        ),
        tables_off,
        tables_on,
    )
    np.testing.assert_allclose(pctr_off, pctr_on, rtol=1e-5, atol=1e-6)


def test_dict_wire_eligibility_gates():
    common = dict(batch_size=64, table_size_log2=14, num_devices=1)
    from xflow_tpu.models import make_model
    from xflow_tpu.optim import make_optimizer
    from xflow_tpu.parallel.mesh import make_mesh
    from xflow_tpu.parallel.step import TrainStep

    def mk(**kw):
        cfg = Config(**common, **kw)
        return TrainStep(
            make_model(cfg), make_optimizer(cfg), cfg, make_mesh(1)
        )

    assert mk(model="lr").dict_wire
    assert mk(model="mvm").dict_wire
    # numeric mode carries values -> no compaction
    assert not mk(model="lr", hash_mode=False).dict_wire
    # u8 count planes bound the row widths
    assert not mk(model="lr", max_nnz=300).dict_wire
    # multi-device mesh: stream planes have no batch-axis sharding
    cfg = Config(
        model="lr", batch_size=64, table_size_log2=14, num_devices=2
    )
    step = TrainStep(
        make_model(cfg), make_optimizer(cfg), cfg, make_mesh(2)
    )
    assert not step.dict_wire
    with pytest.raises(ValueError, match="wire_dedup"):
        mk(model="lr", hash_mode=False, wire_dedup="on")


def test_serve_engine_pins_dict_wire_off(toy_dataset):
    """Serving must keep content-independent wire shapes (the
    one-compile-per-bucket guarantee), so the engine disables the
    dict wire regardless of eligibility."""
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.trainer import Trainer

    cfg = Config(
        model="lr", train_path=toy_dataset.train_prefix,
        batch_size=64, table_size_log2=14, max_nnz=24, num_devices=1,
        epochs=1,
    )
    t = Trainer(cfg)
    assert t.step.dict_wire  # the training feed does compact
    eng = PredictEngine(cfg, t.state, buckets=(1, 8))
    assert not eng.step.dict_wire
    eng.warm()
    n = eng.compile_count
    eng.predict(eng.featurize_raw([np.asarray([1, 2, 3])]))
    assert eng.compile_count == n
    t.close()


# -- tier-1 wiring ---------------------------------------------------------


def test_dedup_select_pathological_cap_truncates():
    """More than dict_cap keys EACH repeating > dict_cap times (so the
    count histogram can't separate them): selection truncates to
    dict_cap instead of overflowing the capped planes."""
    keys = np.repeat(np.arange(9, dtype=np.int64), 6)  # 9 keys x 6 > cap 4
    uniq, codes = _numpy_dedup(keys, 4)
    assert len(uniq) <= 4
    got, _ = _decode(keys, uniq, codes)
    np.testing.assert_array_equal(got, keys)


def test_engine_serves_wire_dedup_on_config_on_multi_device_mesh():
    """A wire_dedup='on' training config must still serve on a
    multi-device mesh: the engine overrides the step's wire, and the
    digest-locked artifact config keeps its identity."""
    from xflow_tpu.models import make_model
    from xflow_tpu.optim import make_optimizer
    from xflow_tpu.parallel.mesh import make_mesh
    from xflow_tpu.parallel.step import TrainStep, init_state
    from xflow_tpu.serve.engine import PredictEngine

    cfg = Config(
        model="lr", batch_size=64, table_size_log2=14, max_nnz=16,
        num_devices=1, wire_dedup="on",
    )
    mesh2 = make_mesh(2)
    state = init_state(
        make_model(cfg), make_optimizer(cfg), cfg, mesh2
    )
    eng = PredictEngine(
        cfg, state, mesh=mesh2, buckets=(2,), warm=False
    )
    assert not eng.step.dict_wire
    assert eng.cfg.wire_dedup == "on"  # artifact identity untouched
    assert eng.digest == cfg.digest()
    out = eng.predict(eng.featurize_raw([np.asarray([1, 2, 3])]))
    assert out.shape == (1,)


def test_check_wire_roundtrip_script():
    """Tier-1 wiring for scripts/check_wire_roundtrip.py (same pattern
    as check_metrics_schema/check_serve_smoke)."""
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "scripts", "check_wire_roundtrip.py"),
        ],
        capture_output=True,
        text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
