"""Prefetch lifecycle (io/loader.py::_PrefetchIter): the producer
thread must die on explicit close() — including when the consumer
abandons the iterator mid-shard — not whenever the GC notices, and
Trainer.close() must close every prefetch it spawned."""

import time

import numpy as np

from xflow_tpu.config import Config
from xflow_tpu.io.loader import ShardLoader, _PrefetchIter
from xflow_tpu.trainer import Trainer


def _wait_dead(it, timeout=5.0) -> bool:
    t0 = time.time()
    while time.time() - t0 < timeout:
        if not it.alive:
            return True
        time.sleep(0.01)
    return False


def test_prefetch_close_stops_abandoned_producer(toy_dataset):
    """Consumer takes ONE item and walks away; close() must stop the
    producer even while it is blocked on a full queue."""
    loader = ShardLoader(
        toy_dataset.train_prefix + "-00000",
        batch_size=16, max_nnz=24, table_size=1 << 14, block_mib=1,
    )
    it = loader.prefetch(depth=1)
    batch, _ = next(it)
    assert batch.num_real() == 16
    assert it.alive  # producer blocked on the depth-1 queue
    it.close()
    assert _wait_dead(it)
    # closed iterator is exhausted, not wedged
    assert list(it) == []


def test_prefetch_close_idempotent_and_context_manager(toy_dataset):
    loader = ShardLoader(
        toy_dataset.train_prefix + "-00000",
        batch_size=16, max_nnz=24, table_size=1 << 14, block_mib=1,
    )
    with loader.prefetch(depth=2) as it:
        next(it)
    assert _wait_dead(it)
    it.close()  # second close is a no-op

    # depth 0 degrades to a synchronous passthrough with the same
    # close() surface
    it0 = loader.prefetch(depth=0)
    next(it0)
    it0.close()
    assert list(it0) == []


def test_prefetch_exception_propagates(tmp_path):
    def boom():
        yield 1
        raise RuntimeError("producer exploded")

    it = _PrefetchIter(boom(), depth=2)
    assert next(it) == 1
    try:
        next(it)
        raise AssertionError("expected RuntimeError")
    except RuntimeError:
        pass
    assert _wait_dead(it)


def test_trainer_close_stops_live_prefetch(toy_dataset):
    """Abandon training mid-shard; Trainer.close() must reap the
    loader's producer thread."""
    cfg = Config(
        model="lr",
        train_path=toy_dataset.train_prefix,
        batch_size=16,
        table_size_log2=14,
        max_nnz=24,
        num_devices=1,
        prefetch_batches=2,
        epochs=1,
    )
    t = Trainer(cfg)
    it = t.iter_train_batches()
    next(it)  # the shard prefetch is now live
    live = list(t._live_prefetch)
    assert live and any(p.alive for p in live)
    t.close()
    for p in live:
        assert _wait_dead(p)
