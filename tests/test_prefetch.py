"""Prefetch lifecycle (io/loader.py::_PrefetchIter): the producer
thread must die on explicit close() — including when the consumer
abandons the iterator mid-shard — not whenever the GC notices, and
Trainer.close() must close every prefetch it spawned."""

import time

import numpy as np

from xflow_tpu.config import Config
from xflow_tpu.io.loader import ShardLoader, _PrefetchIter
from xflow_tpu.trainer import Trainer


def _wait_dead(it, timeout=5.0) -> bool:
    t0 = time.time()
    while time.time() - t0 < timeout:
        if not it.alive:
            return True
        time.sleep(0.01)
    return False


def test_prefetch_close_stops_abandoned_producer(toy_dataset):
    """Consumer takes ONE item and walks away; close() must stop the
    producer even while it is blocked on a full queue."""
    loader = ShardLoader(
        toy_dataset.train_prefix + "-00000",
        batch_size=16, max_nnz=24, table_size=1 << 14, block_mib=1,
    )
    it = loader.prefetch(depth=1)
    batch, _ = next(it)
    assert batch.num_real() == 16
    assert it.alive  # producer blocked on the depth-1 queue
    it.close()
    assert _wait_dead(it)
    # closed iterator is exhausted, not wedged
    assert list(it) == []


def test_prefetch_close_idempotent_and_context_manager(toy_dataset):
    loader = ShardLoader(
        toy_dataset.train_prefix + "-00000",
        batch_size=16, max_nnz=24, table_size=1 << 14, block_mib=1,
    )
    with loader.prefetch(depth=2) as it:
        next(it)
    assert _wait_dead(it)
    it.close()  # second close is a no-op

    # depth 0 degrades to a synchronous passthrough with the same
    # close() surface
    it0 = loader.prefetch(depth=0)
    next(it0)
    it0.close()
    assert list(it0) == []


def test_prefetch_exception_propagates(tmp_path):
    def boom():
        yield 1
        raise RuntimeError("producer exploded")

    it = _PrefetchIter(boom(), depth=2)
    assert next(it) == 1
    try:
        next(it)
        raise AssertionError("expected RuntimeError")
    except RuntimeError:
        pass
    assert _wait_dead(it)


def test_prefetch_leak_surfaced_on_join_timeout(tmp_path):
    """A producer wedged in parse/read (NOT on the queue) outlives the
    close() join: the leak must be SURFACED — warning, counter, and a
    ``health`` row `obs doctor` can rank — instead of silent
    (io/loader.py satellite, ISSUE 6)."""
    import json
    import threading
    import warnings

    from xflow_tpu.obs import Obs
    from xflow_tpu.obs.flight import FlightRecorder
    from xflow_tpu.utils.logging import MetricsLogger

    release = threading.Event()

    def wedged():
        yield 1
        release.wait()  # stuck mid-"parse", not on the queue
        yield 2

    path = str(tmp_path / "m.jsonl")
    logger = MetricsLogger(path)
    obs = Obs()
    obs.flight = FlightRecorder(metrics_logger=logger)
    it = _PrefetchIter(wedged(), depth=2, obs=obs)
    assert next(it) == 1
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        it.close(join_timeout=0.1)
    assert any(
        "outlived" in str(w.message) for w in caught
    ), [str(w.message) for w in caught]
    snap = obs.registry.snapshot()
    assert snap.counters.get("loader.leaked_threads") == 1
    logger.close()
    with open(path) as f:
        rows = [json.loads(line) for line in f if line.strip()]
    leak = [
        r for r in rows
        if r.get("kind") == "health"
        and r.get("cause") == "prefetch_thread_leak"
    ]
    assert len(leak) == 1
    assert leak[0]["channel"] == "loader"
    # schema-valid: obs validate must accept the leak row
    from xflow_tpu.obs.schema import validate_rows

    assert validate_rows(leak) == []
    # close() is idempotent: a second close (Trainer.close reaping
    # _live_prefetch after a direct close) must neither pay another
    # join_timeout nor double-report the leak
    import time as _time

    t0 = _time.monotonic()
    it.close(join_timeout=5.0)
    assert _time.monotonic() - t0 < 1.0
    assert obs.registry.snapshot().counters.get(
        "loader.leaked_threads"
    ) == 1
    # unwedge so the daemon producer exits before the test returns
    release.set()
    assert _wait_dead(it)


def test_prefetch_clean_close_does_not_warn(toy_dataset):
    """The normal close path stays silent: no leak warning, no
    counter, no health row."""
    import warnings

    loader = ShardLoader(
        toy_dataset.train_prefix + "-00000",
        batch_size=16, max_nnz=24, table_size=1 << 14, block_mib=1,
    )
    it = loader.prefetch(depth=1)
    next(it)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        it.close()
    assert not any(
        "outlived" in str(w.message) for w in caught
    ), [str(w.message) for w in caught]


def test_trainer_close_stops_live_prefetch(toy_dataset):
    """Abandon training mid-shard; Trainer.close() must reap the
    loader's producer thread."""
    cfg = Config(
        model="lr",
        train_path=toy_dataset.train_prefix,
        batch_size=16,
        table_size_log2=14,
        max_nnz=24,
        num_devices=1,
        prefetch_batches=2,
        epochs=1,
    )
    t = Trainer(cfg)
    it = t.iter_train_batches()
    next(it)  # the shard prefetch is now live
    live = list(t._live_prefetch)
    assert live and any(p.alive for p in live)
    t.close()
    for p in live:
        assert _wait_dead(p)
