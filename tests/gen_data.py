"""Deterministic synthetic libffm data with planted signal.

Regenerates the *format* of the reference's bundled toy data
(data/small_train-0000N, SURVEY §2 #19: ``label<TAB>fgid:fid:val`` with
space-separated feature tokens) but with a known generative model so
convergence tests can assert learnability: each (field, token) pair
carries a latent weight; the label is Bernoulli(sigmoid(sum of
weights)).  An LR/FM/MVM learner must reach AUC well above 0.5.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np


@dataclasses.dataclass
class ToyDataset:
    train_prefix: str
    test_prefix: str
    num_train_shards: int
    lines_per_shard: int
    num_fields: int


def generate_dataset(
    root: str,
    num_train_shards: int = 3,
    lines_per_shard: int = 200,
    num_fields: int = 18,
    vocab_per_field: int = 50,
    seed: int = 7,
    scale: float = 2.0,
) -> ToyDataset:
    rng = np.random.default_rng(seed)
    true_w = rng.normal(0.0, scale, size=(num_fields, vocab_per_field))
    os.makedirs(root, exist_ok=True)
    train_prefix = os.path.join(root, "toy_train")
    test_prefix = os.path.join(root, "toy_test")

    def write_shard(path: str, n_lines: int) -> None:
        lines = []
        for _ in range(n_lines):
            toks = rng.integers(0, vocab_per_field, size=num_fields)
            logit = float(true_w[np.arange(num_fields), toks].sum()) / np.sqrt(
                num_fields
            )
            y = int(rng.random() < 1.0 / (1.0 + np.exp(-logit)))
            feats = " ".join(
                # fid strings unique per field so hashing can't alias fields
                f"{f}:{f * vocab_per_field + t}:0.3651"
                for f, t in enumerate(toks)
            )
            lines.append(f"{y}\t{feats}\n")
        with open(path, "w") as fh:
            fh.writelines(lines)

    for s in range(num_train_shards):
        write_shard(f"{train_prefix}-{s:05d}", lines_per_shard)
    write_shard(f"{test_prefix}-00000", lines_per_shard)
    return ToyDataset(
        train_prefix=train_prefix,
        test_prefix=test_prefix,
        num_train_shards=num_train_shards,
        lines_per_shard=lines_per_shard,
        num_fields=num_fields,
    )
