"""Parallel sharded input fan-out (io/fanout.py; ISSUE 14 tentpole).

The pool's whole contract is "faster, otherwise invisible": N
concurrent shard streams must merge back into the serial reader's
exact batch sequence (bitwise — training is order-dependent), resume
cursors must keep working, failures must propagate, and close() must
reap every producer thread.  The tier-1 gate
(scripts/check_input_fanout.py) runs the packed-v2 corpus + sanitizer
acceptance; these tests cover the unit surface.
"""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from xflow_tpu.config import Config
from xflow_tpu.io.fanout import ShardStreamPool
from xflow_tpu.io.loader import ShardLoader
from xflow_tpu.trainer import Trainer, find_shards

BATCH_FIELDS = (
    "keys", "slots", "vals", "mask", "labels", "weights",
    "hot_keys", "hot_slots", "hot_vals", "hot_mask",
)


def _loader_factory(batch_size=32, max_nnz=24, table_log2=14):
    def make(path):
        return ShardLoader(
            path, batch_size=batch_size, max_nnz=max_nnz,
            table_size=1 << table_log2,
        )
    return make


def _batches_equal(a, b) -> bool:
    return all(
        np.array_equal(getattr(a, f), getattr(b, f)) for f in BATCH_FIELDS
    )


def _collect(shards, n, **kw):
    pool = ShardStreamPool(
        shards, _loader_factory(), num_streams=n, depth=2, **kw
    )
    try:
        return [(si, resume, b) for b, si, resume in pool]
    finally:
        pool.close()


@pytest.fixture(scope="module")
def shards(toy_dataset):
    return find_shards(toy_dataset.train_prefix)


def test_pool_matches_serial_bitwise(shards):
    """N=1, N=2 and N=4 pools all yield the serial loaders' exact
    (batch, shard, resume) sequence."""
    serial = []
    make = _loader_factory()
    for si, path in enumerate(shards):
        for batch, resume in make(path).iter_batches():
            serial.append((si, resume, batch))
    for n in (1, 2, 4):
        got = _collect(shards, n)
        assert len(got) == len(serial)
        for (sa, ra, ba), (sb, rb, bb) in zip(serial, got):
            assert (sa, ra) == (sb, rb)
            assert _batches_equal(ba, bb)


def test_pool_resume_cursor(shards):
    """A pool resumed at (start_shard, start_offset) yields exactly
    what the serial readers yield from the same cursor (resume
    granularity — bounded block replay — included)."""
    full = _collect(shards, 3)
    # resume from the second shard at the offset its second batch
    # reported (the trainer's checkpoint cursor shape)
    anchor = [i for i, (si, _, _) in enumerate(full) if si == 1][1]
    start_offset = full[anchor][1]
    make = _loader_factory()
    serial = []
    for si in range(1, len(shards)):
        offset = start_offset if si == 1 else 0
        for batch, resume in make(shards[si]).iter_batches(offset):
            serial.append((si, resume, batch))
    got = _collect(shards, 3, start_shard=1, start_offset=start_offset)
    assert len(got) == len(serial)
    for (sa, ra, ba), (sb, rb, bb) in zip(serial, got):
        assert (sa, ra) == (sb, rb)
        assert _batches_equal(ba, bb)


def test_pool_clamps_streams_and_validates(shards):
    pool = ShardStreamPool(
        shards[:2], _loader_factory(), num_streams=8, depth=2
    )
    try:
        assert pool.num_streams == 2  # never more streams than shards
    finally:
        pool.close()
    with pytest.raises(ValueError, match="num_streams"):
        ShardStreamPool(shards, _loader_factory(), num_streams=0)
    with pytest.raises(ValueError, match="depth"):
        ShardStreamPool(shards, _loader_factory(), num_streams=1, depth=0)


def test_pool_close_mid_iteration_reaps_threads(shards):
    before = {t.ident for t in threading.enumerate()}
    pool = ShardStreamPool(shards, _loader_factory(), num_streams=3, depth=2)
    it = iter(pool)
    next(it)  # streams are live
    assert pool.alive
    pool.close()
    deadline = time.time() + 10
    while time.time() < deadline and pool.alive:
        time.sleep(0.02)
    assert not pool.alive
    leaked = {
        t.ident for t in threading.enumerate() if t.is_alive()
    } - before
    assert not leaked, f"leaked stream threads: {leaked}"
    pool.close()  # idempotent


def test_pool_propagates_stream_exception(shards):
    """A loader failure inside one stream surfaces to the merging
    consumer (the quarantine-budget / I/O failure path)."""

    class Boom(RuntimeError):
        pass

    make = _loader_factory()

    def factory(path):
        loader = make(path)
        if path.endswith("-00001"):
            def bad_iter(*a, **k):
                raise Boom("stream reader died")
                yield  # pragma: no cover
            loader.iter_batches = bad_iter
        return loader

    pool = ShardStreamPool(shards, factory, num_streams=3, depth=2)
    try:
        with pytest.raises(Boom, match="stream reader died"):
            for _ in pool:
                pass
    finally:
        pool.close()


def test_pool_transform_runs_on_stream(shards):
    """The per-batch transform (TrainStep.precompact's seat) runs on
    the producer threads, not the consumer."""
    consumer = threading.get_ident()
    seen = []

    def tag(batch):
        seen.append(threading.get_ident())
        return batch

    out = _collect(shards, 2, transform=tag)
    assert out and seen
    assert consumer not in set(seen)


def test_pool_stream_stats_accounting(shards):
    pool = ShardStreamPool(shards, _loader_factory(), num_streams=2, depth=1)
    try:
        n = sum(b.num_real() for b, _, _ in pool)
    finally:
        pool.close()
    stats = pool.stream_stats()
    assert [s["stream"] for s in stats] == [0, 1]
    assert sum(s["shards"] for s in stats) == len(shards)
    assert sum(s["examples"] for s in stats) == n
    for s in stats:
        assert s["batches"] > 0
        assert s["seconds"] > 0
        assert s["examples_per_sec"] > 0
        assert s["stall_seconds"] >= 0


def test_pool_stall_seconds_under_slow_consumer(shards):
    """A consumer slower than the readers books backpressure stall on
    the streams — the signal that separates 'slow reader' from
    'saturated device' in the stream rows."""
    pool = ShardStreamPool(shards, _loader_factory(), num_streams=2, depth=1)
    try:
        for i, _ in enumerate(pool):
            if i < 4:
                time.sleep(0.12)
    finally:
        pool.close()
    assert sum(s["stall_seconds"] for s in pool.stream_stats()) > 0.1


# -- trainer integration ----------------------------------------------------


def _train_state(toy_dataset, tmp_path, streams, metrics=""):
    import jax

    cfg = Config(
        model="lr", train_path=toy_dataset.train_prefix, epochs=1,
        batch_size=32, table_size_log2=14, max_nnz=24, num_devices=1,
        input_streams=streams, metrics_out=metrics,
    )
    with Trainer(cfg) as t:
        t.train_epoch()
        return jax.device_get(t.state)


def test_trainer_fanout_bitwise_parity(toy_dataset, tmp_path):
    """input_streams=4 trains to the exact serial state and emits
    schema-valid per-stream rows plus the serial path's shard rows."""
    import jax.tree_util as tu

    from xflow_tpu.obs.schema import load_jsonl, validate_rows

    metrics = str(tmp_path / "fan.jsonl")
    s1 = _train_state(toy_dataset, tmp_path, streams=1)
    s4 = _train_state(toy_dataset, tmp_path, streams=4, metrics=metrics)
    for a, b in zip(tu.tree_leaves(s1), tu.tree_leaves(s4)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    rows = load_jsonl(metrics)
    assert validate_rows(rows) == []
    stream_rows = [r for r in rows if r.get("kind") == "stream"]
    shard_rows = [r for r in rows if r.get("kind") == "shard"]
    assert len(stream_rows) >= 2
    assert len(shard_rows) == 3  # toy corpus: one row per shard
    assert sum(r["shards"] for r in stream_rows) == 3
    assert all(r["examples_per_sec"] > 0 for r in stream_rows)


def test_trainer_fanout_preemption_reaps(toy_dataset, tmp_path):
    """Abandoning a fan-out epoch mid-stream (the preemption/crash
    shape) leaves no stream threads behind Trainer.close()."""
    before = {t.ident for t in threading.enumerate()}
    cfg = Config(
        model="lr", train_path=toy_dataset.train_prefix, epochs=1,
        batch_size=32, table_size_log2=14, max_nnz=24, num_devices=1,
        input_streams=3,
    )
    t = Trainer(cfg)
    it = t.iter_train_batches()
    next(it)
    t.close()
    deadline = time.time() + 10
    while time.time() < deadline:
        leaked = {
            th.ident for th in threading.enumerate() if th.is_alive()
        } - before
        if not leaked:
            break
        time.sleep(0.05)
    assert not leaked, f"fan-out streams leaked: {leaked}"


# -- config surface ---------------------------------------------------------


def test_config_validation():
    with pytest.raises(ValueError, match="input_streams must be >= 1"):
        Config(input_streams=0)
    with pytest.raises(ValueError, match="transfer_ahead_depth"):
        Config(transfer_ahead_depth=0)
    with pytest.raises(ValueError, match="ROADMAP item 2"):
        Config(
            input_streams=2, store_mode="tiered",
            table_size_log2=20, hot_capacity_log2=10,
        )
    # legacy manifests (pre-rename) keep loading
    cfg = Config.from_json(json.dumps({"transfer_ahead": 5}))
    assert cfg.transfer_ahead_depth == 5


# -- packed-v2 shard splitting ----------------------------------------------


def test_split_shard_v2(tmp_path, toy_dataset):
    """split_shard_v2 sub-shards stream the source's records
    byte-identically, in order, with correct per-shard totals."""
    from xflow_tpu.io import packed

    src = str(tmp_path / "whole.pk")
    packed.convert_shard(
        toy_dataset.train_prefix + "-00000", src, fmt="v2",
        batch_size=32, max_nnz=24, table_size=1 << 14,
    )
    parts = packed.split_shard_v2(src, str(tmp_path / "part"), 3)
    assert len(parts) == 3
    with open(src, "rb") as f:
        want = list(packed.iter_compact_batches(f))
    got = []
    total_examples = 0
    for p in parts:
        assert packed.is_packed_shard(p)
        total_examples += packed.shard_example_count(p)
        with open(p, "rb") as f:
            got.extend(cb for cb, _, _ in packed.iter_compact_batches(f))
    assert len(got) == len(want)
    assert total_examples == sum(cb.n_real for cb, _, _ in want)
    for (ca, _, _), cb in zip(want, got):
        for pl in (
            "cu", "ci", "ct", "cf", "cc", "lb", "wb", "cs",
        ):
            assert np.array_equal(getattr(ca, pl), getattr(cb, pl))
    with pytest.raises(ValueError, match="num_shards"):
        packed.split_shard_v2(src, str(tmp_path / "bad"), 0)


# -- obs surface ------------------------------------------------------------


def _stream_row(stream, eps, stall=0.0):
    return {
        "t": 1.0, "kind": "stream", "epoch": 0, "stream": stream,
        "shards": 2, "batches": 10, "examples": 1000,
        "seconds": 1.0, "read_seconds": 1000.0 / eps,
        "stall_seconds": stall, "examples_per_sec": eps,
    }


def test_doctor_stream_straggler(tmp_path, capsys):
    from xflow_tpu.obs.__main__ import main

    path = tmp_path / "streams.jsonl"
    rows = [
        _stream_row(0, 9000.0), _stream_row(1, 9500.0),
        _stream_row(2, 2000.0), _stream_row(3, 8800.0),
    ]
    path.write_text("".join(json.dumps(r) + "\n" for r in rows))
    rc = main(["doctor", str(path)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "stream_straggler" in out and "stream 2" in out


def test_doctor_balanced_streams_clean(tmp_path, capsys):
    from xflow_tpu.obs.__main__ import main

    path = tmp_path / "streams.jsonl"
    rows = [_stream_row(s, 9000.0 + 100 * s) for s in range(4)]
    path.write_text("".join(json.dumps(r) + "\n" for r in rows))
    rc = main(["doctor", str(path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "stream_skew" in out and "stream_straggler" not in out


def test_summarize_stream_spread_line(tmp_path, capsys):
    from xflow_tpu.obs.__main__ import main

    path = tmp_path / "streams.jsonl"
    rows = [_stream_row(0, 8000.0), _stream_row(1, 4000.0, stall=0.5)]
    path.write_text("".join(json.dumps(r) + "\n" for r in rows))
    assert main(["summarize", str(path)]) == 0
    out = capsys.readouterr().out
    assert "input streams: 2" in out
    assert "spread max/min = 2.00x" in out
    assert "backpressure stall 0.5s" in out


def _bench_artifact(path, value, e2e=None, degraded=False):
    row = {"metric": "m", "value": value, "backend": "cpu"}
    if e2e is not None:
        row["e2e_packed_examples_per_sec"] = e2e
    if degraded:
        row["degraded"] = True
    path.write_text(json.dumps({"parsed": row}))


def test_bench_regress_gates_e2e_packed(tmp_path, capsys):
    """check_bench_regress.py's second gate: e2e_packed compares
    against the best non-degraded prior that MEASURES it; a latest
    artifact that stopped measuring it fails --strict instead of
    silently ungating the metric."""
    import scripts.check_bench_regress as cbr

    _bench_artifact(tmp_path / "BENCH_r01.json", 100.0)  # no e2e metric
    _bench_artifact(tmp_path / "BENCH_r02.json", 90.0, e2e=5000.0)
    _bench_artifact(
        tmp_path / "BENCH_r03.json", 80.0, e2e=9999.0, degraded=True
    )
    _bench_artifact(tmp_path / "BENCH_r04.json", 85.0, e2e=5100.0)
    assert cbr.main(["--root", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    # r02 (not the degraded r03's absurd 9999) is the e2e bar
    assert "e2e_packed_examples_per_sec 5100 within" in out
    assert "BENCH_r02.json (5000)" in out

    # e2e regression: warn-only default, gates under --strict
    _bench_artifact(tmp_path / "BENCH_r04.json", 85.0, e2e=1000.0)
    assert cbr.main(["--root", str(tmp_path)]) == 0
    assert "input-path regression" in capsys.readouterr().err
    assert cbr.main(["--root", str(tmp_path), "--strict"]) == 1
    capsys.readouterr()

    # latest lost the metric entirely while priors measure it
    _bench_artifact(tmp_path / "BENCH_r04.json", 85.0)
    assert cbr.main(["--root", str(tmp_path), "--strict"]) == 1
    assert "missing metric" in capsys.readouterr().err


# -- tier-1 gate wiring -----------------------------------------------------


def test_check_input_fanout_script():
    """scripts/check_input_fanout.py: the packed-v2 corpus acceptance
    (bitwise N=4 vs serial, schema-valid stream rows, zero thread
    leaks, sanitizer-clean lock orders) exits 0 on the shipped tree."""
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(repo, "scripts", "check_input_fanout.py"),
        ],
        capture_output=True,
        text=True,
        timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK:" in proc.stdout
