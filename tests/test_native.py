"""Native C++ parser: bit/semantics parity with the Python parser over
structured, malformed, and fuzzed inputs (the native module replaces
the reference's C++ loader, load_data_from_disk.cc:103-210)."""

import numpy as np
import pytest

from xflow_tpu.io.hashing import murmur64
from xflow_tpu.io.libffm import parse_block
from xflow_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C++ toolchain to build native parser"
)

TABLE = 1 << 16


def assert_blocks_equal(a, b):
    np.testing.assert_array_equal(a.labels, b.labels)
    np.testing.assert_array_equal(a.row_ptr, b.row_ptr)
    np.testing.assert_array_equal(a.keys, b.keys)
    np.testing.assert_array_equal(a.slots, b.slots)
    np.testing.assert_array_equal(a.vals, b.vals)


@pytest.mark.parametrize("hash_mode", [True, False])
def test_parity_structured(hash_mode):
    data = (
        b"1\t0:123:0.5 2:456:1.0\n"
        b"0\t1:123:0.25\n"
        b"0.5 3:9:2.5 4:-7:1e-3\n"
        b"1e-8\t0:1:1\n"
        b"-3\t0:2:1\n"
        b"\n"
        b"2 5:77:0.125"  # no trailing newline
    )
    py = parse_block(data, TABLE, hash_mode)
    nat = native.native_parse_block(data, TABLE, hash_mode)
    assert_blocks_equal(py, nat)


def test_parity_malformed():
    data = (
        b"1\t0:1:1 garbage x:y:z:extra 2:3 :: a:b:c 1:tok:val trailing\n"
        b"notalabel\t0:1:1\n"
        b"nan\t0:1:1\n"
        b"inf\t0:1:1\n"
        b"0\t1:5:1\n"
        b"   \n"
        b"1\n"
    )
    for hash_mode in (True, False):
        py = parse_block(data, TABLE, hash_mode)
        nat = native.native_parse_block(data, TABLE, hash_mode)
        assert_blocks_equal(py, nat)


def test_parity_reference_format():
    # reference toy-data shape: label<TAB>fgid:fid:val with float vals
    rng = np.random.default_rng(0)
    lines = []
    for _ in range(300):
        feats = " ".join(
            f"{f}:{rng.integers(0, 10000)}:{rng.random():.4f}"
            for f in range(18)
        )
        lines.append(f"{rng.integers(0, 2)}\t{feats}\n")
    data = "".join(lines).encode()
    for hash_mode in (True, False):
        assert_blocks_equal(
            parse_block(data, TABLE, hash_mode),
            native.native_parse_block(data, TABLE, hash_mode),
        )


def test_parity_fuzz():
    # random token soup (underscore excluded: Python's int()/float() accept
    # "1_0" digit grouping, a documented non-goal for the native parser)
    rng = np.random.default_rng(1)
    alphabet = b"0123456789:.eE+- \tabcxyz\n"
    for trial in range(20):
        raw = bytes(
            alphabet[i] for i in rng.integers(0, len(alphabet), size=2000)
        )
        for hash_mode in (True, False):
            py = parse_block(raw, TABLE, hash_mode)
            nat = native.native_parse_block(raw, TABLE, hash_mode)
            assert_blocks_equal(py, nat)


def test_parity_extreme_tokens():
    """Edges found in review: 64+-byte numeric tokens, int64/int32
    overflow ids, double-rounding-sensitive float values."""
    long_label = b"0." + b"0" * 70 + b"1"  # > 64 chars, valid float
    data = (
        long_label + b"\t0:1:1\n"
        b"1\t0:99999999999999999999:1\n"  # fid > int64: token skipped
        b"1\t99999999999:5:1\n"  # fgid > int32: token skipped
        b"1\t-2147483648:5:1 2147483647:6:1\n"  # int32 bounds kept
        b"1\t0:7:7.038531e-26 0:8:1.1754944e-38\n"  # double-rounding probes
        b"1\t0:9:" + b"1" * 80 + b".5\n"  # long val token
    )
    for hash_mode in (True, False):
        py = parse_block(data, TABLE, hash_mode)
        nat = native.native_parse_block(data, TABLE, hash_mode)
        assert_blocks_equal(py, nat)
    # the overflow lines must keep their labels but drop the bad tokens
    py = parse_block(data, TABLE, hash_mode=False)
    assert py.num_samples == 6
    assert py.row_ptr[2] - py.row_ptr[1] == 0  # fid overflow dropped
    assert py.row_ptr[3] - py.row_ptr[2] == 0  # fgid overflow dropped
    assert py.row_ptr[4] - py.row_ptr[3] == 2  # int32 bounds kept


def test_native_murmur_matches_python():
    rng = np.random.default_rng(2)
    for n in list(range(0, 33)) + [100, 1000]:
        tok = bytes(rng.integers(0, 256, size=n).astype(np.uint8))
        assert native.native_murmur64(tok) == murmur64(tok)
        assert native.native_murmur64(tok, 42) == murmur64(tok, 42)


def test_hash_seed_parity():
    data = b"1\t0:sometoken:1\n"
    py = parse_block(data, TABLE, True, hash_seed=99)
    nat = native.native_parse_block(data, TABLE, True, hash_seed=99)
    assert_blocks_equal(py, nat)


def test_make_parse_fn_prefers_native(toy_dataset):
    from xflow_tpu.io.loader import ShardLoader, make_parse_fn

    fn = make_parse_fn(TABLE, True, 0, prefer_native=True)
    loader = ShardLoader(
        toy_dataset.train_prefix + "-00000",
        batch_size=32,
        max_nnz=16,
        table_size=TABLE,
        parse_fn=fn,
    )
    total = sum(b.num_real() for b, _ in loader.iter_batches())
    assert total == toy_dataset.lines_per_shard


def test_prefetch_matches_sync(toy_dataset):
    from xflow_tpu.io.loader import ShardLoader

    loader = ShardLoader(
        toy_dataset.train_prefix + "-00000",
        batch_size=32,
        max_nnz=16,
        table_size=TABLE,
    )
    sync = [(b.keys.copy(), r) for b, r in loader.iter_batches()]
    pre = [(b.keys.copy(), r) for b, r in loader.prefetch(3)]
    assert len(sync) == len(pre)
    for (ka, ra), (kb, rb) in zip(sync, pre):
        np.testing.assert_array_equal(ka, kb)
        assert ra == rb


def test_parallel_parse_matches_sequential(toy_dataset):
    from xflow_tpu.io.loader import ShardLoader

    loader = ShardLoader(
        toy_dataset.train_prefix + "-00000",
        batch_size=32,
        max_nnz=16,
        table_size=TABLE,
        block_mib=1,
    )
    seq = [(b.keys.copy(), b.labels.copy(), r) for b, r in loader.iter_batches()]
    par = [
        (b.keys.copy(), b.labels.copy(), r)
        for b, r in loader.iter_batches(parse_workers=4)
    ]
    assert len(seq) == len(par)
    for (ka, la, ra), (kb, lb, rb) in zip(seq, par):
        np.testing.assert_array_equal(ka, kb)
        np.testing.assert_array_equal(la, lb)
        assert ra == rb


def test_prefetch_propagates_errors():
    from xflow_tpu.io.loader import _prefetch_iter

    def boom():
        yield 1
        raise ValueError("boom")

    it = _prefetch_iter(boom(), depth=2)
    assert next(it) == 1
    with pytest.raises(ValueError, match="boom"):
        next(it)
