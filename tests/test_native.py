"""Native C++ parser: bit/semantics parity with the Python parser over
structured, malformed, and fuzzed inputs (the native module replaces
the reference's C++ loader, load_data_from_disk.cc:103-210)."""

import numpy as np
import pytest

from xflow_tpu.io.hashing import murmur64
from xflow_tpu.io.libffm import parse_block
from xflow_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C++ toolchain to build native parser"
)

TABLE = 1 << 16


def assert_blocks_equal(a, b):
    np.testing.assert_array_equal(a.labels, b.labels)
    np.testing.assert_array_equal(a.row_ptr, b.row_ptr)
    np.testing.assert_array_equal(a.keys, b.keys)
    np.testing.assert_array_equal(a.slots, b.slots)
    np.testing.assert_array_equal(a.vals, b.vals)


@pytest.mark.parametrize("hash_mode", [True, False])
def test_parity_structured(hash_mode):
    data = (
        b"1\t0:123:0.5 2:456:1.0\n"
        b"0\t1:123:0.25\n"
        b"0.5 3:9:2.5 4:-7:1e-3\n"
        b"1e-8\t0:1:1\n"
        b"-3\t0:2:1\n"
        b"\n"
        b"2 5:77:0.125"  # no trailing newline
    )
    py = parse_block(data, TABLE, hash_mode)
    nat = native.native_parse_block(data, TABLE, hash_mode)
    assert_blocks_equal(py, nat)


def test_parity_malformed():
    data = (
        b"1\t0:1:1 garbage x:y:z:extra 2:3 :: a:b:c 1:tok:val trailing\n"
        b"notalabel\t0:1:1\n"
        b"nan\t0:1:1\n"
        b"inf\t0:1:1\n"
        b"0\t1:5:1\n"
        b"   \n"
        b"1\n"
    )
    for hash_mode in (True, False):
        py = parse_block(data, TABLE, hash_mode)
        nat = native.native_parse_block(data, TABLE, hash_mode)
        assert_blocks_equal(py, nat)


def test_parity_reference_format():
    # reference toy-data shape: label<TAB>fgid:fid:val with float vals
    rng = np.random.default_rng(0)
    lines = []
    for _ in range(300):
        feats = " ".join(
            f"{f}:{rng.integers(0, 10000)}:{rng.random():.4f}"
            for f in range(18)
        )
        lines.append(f"{rng.integers(0, 2)}\t{feats}\n")
    data = "".join(lines).encode()
    for hash_mode in (True, False):
        assert_blocks_equal(
            parse_block(data, TABLE, hash_mode),
            native.native_parse_block(data, TABLE, hash_mode),
        )


def test_parity_fuzz():
    # random token soup (underscore excluded: Python's int()/float() accept
    # "1_0" digit grouping, a documented non-goal for the native parser)
    rng = np.random.default_rng(1)
    alphabet = b"0123456789:.eE+- \tabcxyz\n"
    for trial in range(20):
        raw = bytes(
            alphabet[i] for i in rng.integers(0, len(alphabet), size=2000)
        )
        for hash_mode in (True, False):
            py = parse_block(raw, TABLE, hash_mode)
            nat = native.native_parse_block(raw, TABLE, hash_mode)
            assert_blocks_equal(py, nat)


def test_parity_extreme_tokens():
    """Edges found in review: 64+-byte numeric tokens, int64/int32
    overflow ids, double-rounding-sensitive float values."""
    long_label = b"0." + b"0" * 70 + b"1"  # > 64 chars, valid float
    data = (
        long_label + b"\t0:1:1\n"
        b"1\t0:99999999999999999999:1\n"  # fid > int64: token skipped
        b"1\t99999999999:5:1\n"  # fgid > int32: token skipped
        b"1\t-2147483648:5:1 2147483647:6:1\n"  # int32 bounds kept
        b"1\t0:7:7.038531e-26 0:8:1.1754944e-38\n"  # double-rounding probes
        b"1\t0:9:" + b"1" * 80 + b".5\n"  # long val token
    )
    for hash_mode in (True, False):
        py = parse_block(data, TABLE, hash_mode)
        nat = native.native_parse_block(data, TABLE, hash_mode)
        assert_blocks_equal(py, nat)
    # the overflow lines must keep their labels but drop the bad tokens
    py = parse_block(data, TABLE, hash_mode=False)
    assert py.num_samples == 6
    assert py.row_ptr[2] - py.row_ptr[1] == 0  # fid overflow dropped
    assert py.row_ptr[3] - py.row_ptr[2] == 0  # fgid overflow dropped
    assert py.row_ptr[4] - py.row_ptr[3] == 2  # int32 bounds kept


def test_native_murmur_matches_python():
    rng = np.random.default_rng(2)
    for n in list(range(0, 33)) + [100, 1000]:
        tok = bytes(rng.integers(0, 256, size=n).astype(np.uint8))
        assert native.native_murmur64(tok) == murmur64(tok)
        assert native.native_murmur64(tok, 42) == murmur64(tok, 42)


def test_hash_seed_parity():
    data = b"1\t0:sometoken:1\n"
    py = parse_block(data, TABLE, True, hash_seed=99)
    nat = native.native_parse_block(data, TABLE, True, hash_seed=99)
    assert_blocks_equal(py, nat)


def test_make_parse_fn_prefers_native(toy_dataset):
    from xflow_tpu.io.loader import ShardLoader, make_parse_fn

    fn = make_parse_fn(TABLE, True, 0, prefer_native=True)
    loader = ShardLoader(
        toy_dataset.train_prefix + "-00000",
        batch_size=32,
        max_nnz=16,
        table_size=TABLE,
        parse_fn=fn,
    )
    total = sum(b.num_real() for b, _ in loader.iter_batches())
    assert total == toy_dataset.lines_per_shard


def test_prefetch_matches_sync(toy_dataset):
    from xflow_tpu.io.loader import ShardLoader

    loader = ShardLoader(
        toy_dataset.train_prefix + "-00000",
        batch_size=32,
        max_nnz=16,
        table_size=TABLE,
    )
    sync = [(b.keys.copy(), r) for b, r in loader.iter_batches()]
    pre = [(b.keys.copy(), r) for b, r in loader.prefetch(3)]
    assert len(sync) == len(pre)
    for (ka, ra), (kb, rb) in zip(sync, pre):
        np.testing.assert_array_equal(ka, kb)
        assert ra == rb


def test_parallel_parse_matches_sequential(toy_dataset):
    from xflow_tpu.io.loader import ShardLoader

    loader = ShardLoader(
        toy_dataset.train_prefix + "-00000",
        batch_size=32,
        max_nnz=16,
        table_size=TABLE,
        block_mib=1,
    )
    seq = [(b.keys.copy(), b.labels.copy(), r) for b, r in loader.iter_batches()]
    par = [
        (b.keys.copy(), b.labels.copy(), r)
        for b, r in loader.iter_batches(parse_workers=4)
    ]
    assert len(seq) == len(par)
    for (ka, la, ra), (kb, lb, rb) in zip(seq, par):
        np.testing.assert_array_equal(ka, kb)
        np.testing.assert_array_equal(la, lb)
        assert ra == rb


def test_prefetch_propagates_errors():
    from xflow_tpu.io.loader import _prefetch_iter

    def boom():
        yield 1
        raise ValueError("boom")

    it = _prefetch_iter(boom(), depth=2)
    assert next(it) == 1
    with pytest.raises(ValueError, match="boom"):
        next(it)


def _random_csr(rng, n_rows, max_nnz_per_row, table_size):
    from xflow_tpu.io.batch import ParsedBlock

    counts = rng.integers(0, max_nnz_per_row + 1, n_rows)
    row_ptr = np.zeros(n_rows + 1, np.int64)
    row_ptr[1:] = np.cumsum(counts)
    nnz = int(row_ptr[-1])
    return ParsedBlock(
        labels=rng.integers(0, 2, n_rows).astype(np.float32),
        row_ptr=row_ptr,
        keys=rng.integers(0, table_size, nnz).astype(np.int64),
        slots=rng.integers(0, 32, nnz).astype(np.int32),
        vals=rng.random(nnz).astype(np.float32),
    )


@pytest.mark.parametrize("hot", [False, True])
@pytest.mark.parametrize("use_remap", [False, True])
def test_native_pack_parity(hot, use_remap):
    """xf_pack_batch ≡ remap-then-pack_batch (padding, truncation, and
    hot/cold steering all bit-identical)."""
    if not native.available():
        pytest.skip("native library unavailable")
    from xflow_tpu.io.batch import pack_batch

    rng = np.random.default_rng(42)
    table_size = 512
    hot_size, hot_nnz = (64, 3) if hot else (0, 0)
    remap = None
    if use_remap:
        remap = rng.permutation(table_size).astype(np.int32)
    for trial in range(5):
        block = _random_csr(rng, 57, 12, table_size)
        ref_block = block
        if remap is not None:
            from xflow_tpu.io.batch import ParsedBlock

            ref_block = ParsedBlock(
                labels=block.labels, row_ptr=block.row_ptr,
                keys=remap[block.keys], slots=block.slots, vals=block.vals,
            )
        for start, end in [(0, 57), (0, 16), (40, 57), (5, 6)]:
            want = pack_batch(
                ref_block, start, end, 16 if end - start <= 16 else 64,
                6, hot_size, hot_nnz,
            )
            got = native.native_pack_batch(
                block, start, end, 16 if end - start <= 16 else 64,
                6, hot_size, hot_nnz, remap,
            )
            for f in (
                "keys", "slots", "vals", "mask", "labels", "weights",
                "hot_keys", "hot_slots", "hot_vals", "hot_mask",
            ):
                np.testing.assert_array_equal(
                    getattr(got, f), getattr(want, f), err_msg=f
                )


def test_native_key_range_guards():
    """Round-2 advisor finding: the native entry points are callable
    directly (bypassing Config's table_size_log2 <= 30 guard), and the
    pack path narrows int64 keys to int32 — both must reject rather
    than silently wrap."""
    from xflow_tpu.io.batch import ParsedBlock

    # parse: table_size beyond 2^31 would emit keys that can't survive
    # the downstream int32 batch cast (0 is valid: full keys, no mod)
    with pytest.raises(ValueError, match="table_size"):
        native.native_parse_block(b"1\t0:5:1\n", 1 << 32)
    with pytest.raises(ValueError, match="table_size"):
        native.native_parse_block(b"1\t0:5:1\n", -4)

    # pack: a raw key outside int32 (e.g. from a direct caller's own
    # CSR block) must raise, not wrap
    block = ParsedBlock(
        labels=np.asarray([1.0], np.float32),
        row_ptr=np.asarray([0, 1], np.int64),
        keys=np.asarray([1 << 33], np.int64),
        slots=np.asarray([0], np.int32),
        vals=np.asarray([1.0], np.float32),
    )
    with pytest.raises(ValueError, match="int32"):
        native.native_pack_batch(block, 0, 1, 4, 4)

    # boundary: INT32_MAX itself still packs
    block_ok = ParsedBlock(
        labels=np.asarray([1.0], np.float32),
        row_ptr=np.asarray([0, 1], np.int64),
        keys=np.asarray([(1 << 31) - 1], np.int64),
        slots=np.asarray([0], np.int32),
        vals=np.asarray([1.0], np.float32),
    )
    got = native.native_pack_batch(block_ok, 0, 1, 4, 4)
    assert got.keys[0, 0] == (1 << 31) - 1


def test_loader_full_batches_across_blocks(tmp_path):
    """Batches span text-block boundaries: only the final batch of a
    shard is partial, regardless of block size."""
    from xflow_tpu.io.loader import ShardLoader

    path = tmp_path / "shard"
    n = 1000
    with open(path, "w") as f:
        for i in range(n):
            f.write(f"{i % 2}\t0:f{i}:1 1:g{i % 7}:1\n")
    loader = ShardLoader(
        str(path), batch_size=64, max_nnz=4, table_size=1 << 16
    )
    loader.block_bytes = 512  # ~25 lines per block << batch_size
    out = list(loader.iter_batches())
    batches = [b for b, _ in out]
    offsets = [r for _, r in out]
    assert [b.num_real() for b in batches[:-1]] == [64] * (n // 64)
    assert batches[-1].num_real() == n % 64
    # labels survive the carry/concat path in order
    got = np.concatenate([b.labels[: b.num_real()] for b in batches])
    np.testing.assert_array_equal(got, np.arange(n) % 2)
    # resume offsets ADVANCE with consumption (a pinned offset would
    # replay the whole shard on resume) and land on line boundaries:
    # replaying from any batch's offset covers exactly the lines at or
    # after it — never the whole shard again
    assert offsets == sorted(offsets)
    assert offsets[-1] == path.stat().st_size
    import os

    for bi in (3, 7, len(out) - 2):
        with open(path, "rb") as f:
            f.seek(offsets[bi])
            lines_after = sum(1 for _ in f)
        consumed = 64 * (bi + 1)
        # replay window: everything not yet consumed, plus at most one
        # carry + one block of already-trained lines (block granularity)
        assert lines_after >= n - consumed
        assert lines_after <= n - consumed + 2 * 26
        replayed = sum(
            b.num_real() for b, _ in loader.iter_batches(offsets[bi])
        )
        assert replayed == lines_after


def test_parity_nonfinite_vals():
    """Numeric-mode values not finite in float32 (inf/nan literals, 1e39
    /1e999 overflow) are rejected by BOTH parsers identically, and no
    inf ever reaches the value arrays (round-1 weak point 8)."""
    data = (
        b"1\t0:1:1e999 1:2:-1e999 2:3:inf 3:4:-inf 4:5:nan 5:6:1e39\n"
        b"0\t0:7:0.5 1:8:-3.25 2:9:3.3e38\n"
        b"1\t0:10:1e-50 1:11:-0.0\n"
    )
    py = parse_block(data, 1 << 12, hash_mode=False)
    assert np.isfinite(py.vals).all()
    # line 1: every token rejected; line 2: all kept; line 3: subnormal
    # flushes fine
    assert list(np.diff(py.row_ptr)) == [0, 3, 2]
    if native.available():
        nat = native.native_parse_block(data, 1 << 12, hash_mode=False)
        assert_blocks_equal(py, nat)


def test_sanitizer_fuzz(tmp_path):
    """Build parser.cc + the fuzz driver with ASAN/UBSAN and run the
    fuzz corpus through parse + pack (hot and cold): any OOB access or
    UB aborts (round-1 VERDICT item 8)."""
    import shutil
    import subprocess

    from xflow_tpu.native.build import _DIR

    if shutil.which("g++") is None:
        pytest.skip("no g++")
    binary = tmp_path / "fuzz_driver"
    try:
        subprocess.run(
            [
                "g++", "-O1", "-g", "-std=c++17", "-Wall",
                "-fsanitize=address,undefined",
                "-fno-sanitize-recover=all",
                str(_DIR / "src" / "parser.cc"),
                str(_DIR / "src" / "fuzz_driver.cc"),
                "-o", str(binary),
            ],
            check=True, capture_output=True, text=True,
        )
    except subprocess.CalledProcessError as e:  # pragma: no cover
        pytest.skip(f"sanitizer build unavailable: {e.stderr[:200]}")

    rng = np.random.default_rng(0xF5)
    corpus = []
    # structured-ish lines, raw garbage, truncated utf-8, pathological
    # colon runs, huge tokens, empty file
    samples = [
        b"",
        b"1\t0:a:1 1:b:2\n0\t::::\n",
        b":" * 5000,
        b"1\t" + b"0:" + b"x" * 4096 + b":1\n",
        bytes(rng.integers(0, 256, 8192, dtype=np.uint8)),
        b"\n".join(
            b"%d\t%d:tok%d:%f" % (i % 2, i % 40, i * 7, i * 0.1)
            for i in range(500)
        ),
        b"1e999\t0:1:1e999 nan:2:3\n" * 50,
    ]
    for i, s in enumerate(samples):
        p = tmp_path / f"corpus{i}"
        p.write_bytes(s)
        corpus.append(str(p))
    r = subprocess.run(
        [str(binary), *corpus], capture_output=True, text=True, timeout=120
    )
    assert r.returncode == 0, f"sanitizer violation:\n{r.stderr[-2000:]}"
