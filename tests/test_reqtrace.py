"""Request-scoped tracing (ISSUE 16): context wire formats, head+tail
sampling, chain-filled phase decomposition, span-tree completeness
under concurrent mixed traffic, batch/digest integrity across a hot
swap, the flight-recorder heartbeat's oldest-trace detail, and the
tier-1 smoke gate (scripts/check_reqtrace_smoke.py)."""

import os
import re
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from xflow_tpu.config import Config
from xflow_tpu.obs.reqtrace import (
    PHASES,
    ReqTraceSink,
    TraceContext,
    format_header,
    head_keep,
    parse_header,
)

repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- context wire formats ----------------------------------------------------


def test_header_roundtrip():
    ctx = TraceContext(0xDEADBEEF12345678, 0x42, True)
    back = parse_header(format_header(ctx))
    assert back is not None
    assert back.trace_id == ctx.trace_id
    assert back.parent_span_id == ctx.parent_span_id
    assert back.sampled is True


@pytest.mark.parametrize("bad", [
    None, "", "nope", "12-34", "xyz-0-1", "12-34-5", "12-34-1-extra",
    "0000000000000000-0000000000000000-1",  # trace id 0 is reserved
])
def test_header_malformed_is_absent(bad):
    assert parse_header(bad) is None


def test_packed_wire_roundtrip_traced_and_plain():
    from xflow_tpu.serve.server import (
        decode_packed_request,
        decode_packed_request_traced,
        encode_packed_request,
    )

    row = (
        np.array([3, 5, 9], np.int64),
        np.array([0, 1, 2], np.int32),
        np.array([1.0, 1.0, 0.5], np.float32),
    )
    ctx = TraceContext(0x1122334455667788, 0x99, True)
    buf = encode_packed_request([row], trace=ctx)
    rows, back = decode_packed_request_traced(buf)
    assert back is not None and back.trace_id == ctx.trace_id
    assert back.parent_span_id == 0x99 and back.sampled is True
    np.testing.assert_array_equal(rows[0][0], row[0])
    np.testing.assert_array_equal(rows[0][1], row[1])
    # untraced XFS1 stays the pre-tracing format, trace is None
    plain = encode_packed_request([row])
    rows2, none = decode_packed_request_traced(plain)
    assert none is None
    np.testing.assert_array_equal(rows2[0][0], row[0])
    # the legacy single-return decoder still answers rows
    np.testing.assert_array_equal(decode_packed_request(buf)[0][0], row[0])


def test_packed_wire_rejects_bad_trace_triple():
    from xflow_tpu.serve.server import (
        PACKED_TRACE_MAGIC,
        decode_packed_request_traced,
        encode_packed_request,
    )

    with pytest.raises(ValueError, match="trace triple"):
        decode_packed_request_traced(PACKED_TRACE_MAGIC + b"\x00" * 8)
    buf = bytearray(encode_packed_request(
        [(np.array([1], np.int64), None, None)],
        trace=TraceContext(7),
    ))
    buf[4:12] = b"\x00" * 8  # trace id 0
    with pytest.raises(ValueError, match="trace triple"):
        decode_packed_request_traced(bytes(buf))


# -- sampling ----------------------------------------------------------------


def test_head_keep_deterministic_and_bounded():
    assert not head_keep(123, 0.0)
    assert head_keep(123, 1.0)
    verdicts = [head_keep(i, 0.5) for i in range(2000)]
    assert verdicts == [head_keep(i, 0.5) for i in range(2000)]
    frac = sum(verdicts) / len(verdicts)
    assert 0.4 < frac < 0.6  # splitmix64 is uniform enough at n=2000


def test_config_sample_validation():
    assert Config(obs_reqtrace_sample=0.0).obs_reqtrace_sample == 0.0
    with pytest.raises(ValueError, match="obs_reqtrace_sample"):
        Config(obs_reqtrace_sample=1.5)
    with pytest.raises(ValueError, match="obs_reqtrace_sample"):
        Config(obs_reqtrace_sample=-0.1)


# -- span records ------------------------------------------------------------


def test_complete_chain_fills_missing_stamps():
    sink = ReqTraceSink(sample=1.0)
    span = sink.start(None, "score")
    sink.complete(span, "shed", detail="queue_depth")
    [rec] = sink.flush()
    assert rec["status"] == "shed" and rec["keep"] == "shed"
    assert sorted(rec["phases"]) == sorted(PHASES)
    # nothing past arrival was reached: the whole life books as
    # admission_wait and the phase sum IS the e2e
    assert rec["phases"]["admission_wait"] == pytest.approx(
        rec["e2e"], abs=1e-9
    )
    assert sum(rec["phases"].values()) == pytest.approx(
        rec["e2e"], abs=1e-5
    )


def test_complete_full_stamps_partition_e2e():
    sink = ReqTraceSink(sample=1.0)
    span = sink.start(None, "score")
    t = time.perf_counter() - 0.050  # a request that arrived 50ms ago
    span.t_arrival = t
    span.t_enq = t + 0.010
    span.t_seal = t + 0.030
    span.t_deq = t + 0.031
    span.t_feat = t + 0.041
    sink.complete(span)
    [rec] = sink.flush()
    ph = rec["phases"]
    assert ph["admission_wait"] == pytest.approx(0.010, abs=1e-6)
    assert ph["coalesce_wait"] == pytest.approx(0.020, abs=1e-6)
    assert ph["swap_stall"] == pytest.approx(0.001, abs=1e-6)
    assert ph["featurize"] == pytest.approx(0.010, abs=1e-6)
    assert ph["device"] > 0.0  # t_feat -> completion, wall clock
    assert sum(ph.values()) == pytest.approx(rec["e2e"], abs=1e-5)


def _finished_span(sink, e2e_s, status="ok", stage="score", trace=None):
    span = sink.start(trace, stage)
    span.t_arrival = time.perf_counter() - e2e_s
    span.t_enq = span.t_seal = span.t_deq = span.t_feat = None
    sink.complete(span, status)
    return span


def test_flush_keeps_errors_sheds_and_slowest_at_sample_zero():
    sink = ReqTraceSink(sample=0.0, slow_k=2)
    for i in range(20):
        _finished_span(sink, 0.001 * (i + 1))
    _finished_span(sink, 0.0001, status="error")
    _finished_span(sink, 0.0001, status="shed")
    rows = sink.flush()
    keeps = sorted(r["keep"] for r in rows)
    assert keeps.count("slow") == 2
    assert keeps.count("error") == 1
    assert keeps.count("shed") == 1
    assert "head" not in keeps
    # the slow exemplars really are the window's slowest
    slow_e2e = sorted(
        r["e2e"] for r in rows if r["keep"] == "slow"
    )
    assert slow_e2e[0] >= 0.019


def test_flush_head_keeps_everything_at_sample_one():
    sink = ReqTraceSink(sample=1.0, slow_k=1)
    for i in range(10):
        _finished_span(sink, 0.001 * (i + 1))
    rows = sink.flush()
    assert len(rows) == 10
    assert all(r["keep"] in ("head", "slow") for r in rows)


def test_flush_promotes_whole_trace_trees():
    sink = ReqTraceSink(sample=0.0, slow_k=1)
    # one trace with two spans (a cascade: retrieval + ranking); only
    # the ranking span is slow enough to be a tail exemplar
    ctx = sink.mint()
    _finished_span(sink, 0.0001, stage="retrieval", trace=ctx)
    _finished_span(sink, 0.5, stage="ranking", trace=ctx)
    for _ in range(5):
        _finished_span(sink, 0.001)
    rows = sink.flush()
    mine = [r for r in rows if r["trace_id"] == f"{ctx.trace_id:016x}"]
    assert len(mine) == 2  # the fast sibling rode along...
    assert {r["keep"] for r in mine} == {"slow", "tree"}
    others = [r for r in rows if r["trace_id"] != f"{ctx.trace_id:016x}"]
    assert not others  # ...and unsampled fast singletons did not


def test_flush_keeps_only_referenced_batches():
    sink = ReqTraceSink(sample=0.0, slow_k=1)
    span = sink.start(None, "score")
    span.batch_id = sink.next_batch_id()
    sink.note_batch(span.batch_id, [span.trace_id], "digest-a", 8,
                    {"device": 0.001})
    sink.complete(span)
    orphan = sink.next_batch_id()
    sink.note_batch(orphan, [12345], "digest-a", 8, {"device": 0.001})
    rows = sink.flush()
    batches = [r for r in rows if r["span"] == "batch"]
    assert len(batches) == 1
    assert batches[0]["batch"] == f"b{span.batch_id}"
    assert batches[0]["keep"] == "batch"


def test_sink_capacity_drops_are_counted():
    sink = ReqTraceSink(sample=1.0, capacity=2)
    for _ in range(5):
        _finished_span(sink, 0.001)
    assert sink.dropped == 3
    assert len(sink.flush()) == 2


# -- live fleets: propagation under concurrency ------------------------------


def _live_engine(model_name, **over):
    from xflow_tpu.models import make_model
    from xflow_tpu.optim import make_optimizer
    from xflow_tpu.parallel.mesh import make_mesh
    from xflow_tpu.parallel.step import init_state
    from xflow_tpu.serve.engine import PredictEngine

    base = dict(
        model=model_name,
        table_size_log2=10,
        batch_size=8,
        max_nnz=8,
        max_fields=8,
        tower_split_field=4,
        tower_dim=4,
        num_devices=1,
    )
    base.update(over)
    cfg = Config(**base)
    mesh = make_mesh(1)
    model = make_model(cfg)
    state = init_state(model, make_optimizer(cfg), cfg, mesh)
    return PredictEngine(cfg, state, mesh=mesh, buckets=(4, 8))


def _item_index(n=6, dim=6, nnz=3, table_size=1024, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "count": n,
        "dim": dim,
        "item_index": rng.normal(size=(n, dim)).astype(np.float32),
        "item_ids": (10 + np.arange(n)).astype(np.int64),
        "item_keys": rng.integers(0, table_size, (n, nnz)).astype(np.int64),
        "item_slots": np.full((n, nnz), 5, np.int32),
        "item_vals": np.ones((n, nnz), np.float32),
        "item_nnz": np.full(n, nnz, np.int32),
    }


def _user_row(rng):
    return (
        rng.integers(0, 1024, 3).astype(np.int64),
        rng.integers(0, 4, 3).astype(np.int32),
        None,
    )


def test_concurrent_mixed_traffic_builds_complete_trees():
    """N threads of mixed single-row / top-k / cascade traffic: every
    response's trace id maps to exactly one complete span tree — one
    span for the flat kinds, 1 retrieval + k ranking spans for a
    cascade — and every ok span's batch reference resolves to a batch
    span that fans the trace id in with ONE digest."""
    from xflow_tpu.serve.cascade import CascadeEngine
    from xflow_tpu.serve.fleet import ReplicaFleet

    sink = ReqTraceSink(sample=1.0)
    retr_eng = _live_engine("two_tower")
    retr_eng.attach_item_index(_item_index(), topk_k=4)
    retrieval = ReplicaFleet(
        retr_eng, replicas=2, topk=True, deadline_budget_ms=5000.0,
        depth_budget=1024, reqtrace=sink,
    )
    retrieval.reqtrace_stage = "retrieval"
    ranking = ReplicaFleet(
        _live_engine("dcn"), replicas=2, deadline_budget_ms=5000.0,
        depth_budget=1024, reqtrace=sink,
    )
    ranking.reqtrace_stage = "ranking"
    K = 3
    cascade = CascadeEngine(retrieval, ranking, k=K)
    lock = threading.Lock()
    issued: list[tuple[str, str]] = []  # (kind, trace_id hex)
    fails: list[str] = []

    def worker(seed):
        rng = np.random.default_rng(seed)
        try:
            for i in range(6):
                ctx = sink.mint()
                tid = f"{ctx.trace_id:016x}"
                row = _user_row(rng)
                kind = ("score", "topk", "cascade")[i % 3]
                if kind == "score":
                    ranking.submit(*row, trace=ctx).result(timeout=60)
                elif kind == "topk":
                    retrieval.submit(*row, trace=ctx).result(timeout=60)
                else:
                    out = cascade.recommend(*row, trace=ctx)
                    assert len(out["items"]) == K
                with lock:
                    issued.append((kind, tid))
        except Exception as e:  # noqa: BLE001 - collected for the assert
            with lock:
                fails.append(f"worker {seed}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=worker, args=(s,)) for s in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not fails, fails
    rows = sink.flush()
    reqs = [r for r in rows if r["span"] == "request"]
    batches = {r["batch"]: r for r in rows if r["span"] == "batch"}
    by_trace: dict[str, list[dict]] = {}
    for r in reqs:
        by_trace.setdefault(r["trace_id"], []).append(r)
    for kind, tid in issued:
        tree = by_trace.get(tid)
        assert tree, f"{kind} trace {tid} emitted no spans"
        stages = sorted(s["stage"] for s in tree)
        if kind == "score":
            assert stages == ["ranking"], (tid, stages)
        elif kind == "topk":
            assert stages == ["retrieval"], (tid, stages)
        else:
            assert stages == ["ranking"] * K + ["retrieval"], (tid, stages)
        for s in tree:
            assert s["status"] == "ok"
            assert sorted(s["phases"]) == sorted(PHASES)
            assert sum(s["phases"].values()) == pytest.approx(
                s["e2e"], abs=1e-4
            )
            b = batches[s["batch"]]  # ok spans always reference one
            assert tid in b["trace_ids"]
            assert s["digest"] == b["digest"]
    # exactly one tree per issued trace — ids never bleed across kinds
    assert len(issued) == len({tid for _, tid in issued})
    retrieval.close()
    ranking.close()


def test_batch_spans_never_mix_digests_across_swap():
    """Under a forced hot swap with traffic in flight, every batch
    span carries ONE digest and every member request span agrees with
    its batch — a batch can never straddle a rollout swap."""
    from xflow_tpu.serve.fleet import ReplicaFleet

    sink = ReqTraceSink(sample=1.0)
    fleet = ReplicaFleet(
        _live_engine("lr"), replicas=1, deadline_budget_ms=5000.0,
        depth_budget=1024, reqtrace=sink,
    )
    other = _live_engine("lr", batch_size=16)  # different config digest
    assert other.digest != fleet.digest
    rng = np.random.default_rng(1)
    fails: list[str] = []

    def pound(seed):
        r = np.random.default_rng(seed)
        try:
            for _ in range(10):
                fleet.submit(*_user_row(r)).result(timeout=60)
        except Exception as e:  # noqa: BLE001
            fails.append(f"{type(e).__name__}: {e}")

    threads = [threading.Thread(target=pound, args=(s,)) for s in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.02)
    fleet.batchers[0].swap(other, force=True)
    for t in threads:
        t.join(timeout=120)
    assert not fails, fails
    fleet.submit(*_user_row(rng)).result(timeout=60)  # lands post-swap
    rows = sink.flush()
    batches = {r["batch"]: r for r in rows if r["span"] == "batch"}
    assert batches
    digests = set()
    for r in rows:
        if r["span"] != "request":
            continue
        b = batches[r["batch"]]
        assert r["digest"] == b["digest"], (r["trace_id"], r["digest"])
        digests.add(r["digest"])
    assert other.digest in digests  # the post-swap request scored there
    fleet.close()


def test_heartbeat_names_oldest_queued_trace():
    """The batcher's flight heartbeat carries the oldest in-flight
    trace id while a backlog exists — the detail the watchdog copies
    into serve_queue_stall health rows."""
    from xflow_tpu.serve.batcher import MicroBatcher

    class FlightSpy:
        def __init__(self):
            self.details = []

        def note_serve(self, detail="batch"):
            self.details.append(detail)

    sink = ReqTraceSink(sample=1.0)
    spy = FlightSpy()
    eng = _live_engine("lr")
    b = MicroBatcher(eng, max_wait_ms=0.0, max_batch=1, flight=spy)
    rng = np.random.default_rng(2)
    with b._swap_lock:  # stall the worker so a backlog builds
        futs = [
            b.submit(*_user_row(rng), trace=sink.start(None, "score"))
            for _ in range(3)
        ]
        time.sleep(0.05)
        assert b.oldest_trace() is not None
    for f in futs:
        f.result(timeout=60)
    b.close()
    traced = [d for d in spy.details
              if re.fullmatch(r"batch oldest_trace=[0-9a-f]{16}", d)]
    assert traced, spy.details
    assert "batch" in spy.details  # and the backlog-free form too


# -- tier-1 gate -------------------------------------------------------------


def test_check_reqtrace_smoke_script():
    """The CI lint (scripts/check_reqtrace_smoke.py) passes — run as a
    subprocess exactly as CI would (tier-1 wiring, like
    check_serve_smoke.py / check_cascade_smoke.py)."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join(repo, "scripts", "check_reqtrace_smoke.py")],
        capture_output=True,
        text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        timeout=600,
        cwd=repo,
    )
    assert proc.returncode == 0, (
        f"check_reqtrace_smoke failed:\nstdout:\n{proc.stdout}\n"
        f"stderr:\n{proc.stderr}"
    )
