"""Model forward/gradient math vs independent numpy oracles, including
the reference's FM forward/backward quirk (fm_worker.cc:82 vs :140-142)
and MVM's fixed consistent 1+sum form (checked against autodiff) —
plus the models/blocks.py refactor's no-regression contract: every
incumbent family's predict output bitwise-identical to a frozen copy
of the pre-refactor implementation (tests/_legacy_models.py) on a
fixed seeded batch, in dense, MXU-hot, and tiered store modes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests._legacy_models import legacy_model_for
from xflow_tpu.models.fm import FMModel
from xflow_tpu.models.lr import LRModel
from xflow_tpu.models.mvm import MVMModel

B, K, D, S = 4, 6, 5, 4


def random_batch(seed=0, binary=True):
    rng = np.random.default_rng(seed)
    mask = (rng.random((B, K)) < 0.8).astype(np.float32)
    return {
        "keys": jnp.asarray(rng.integers(0, 100, (B, K)), jnp.int32),
        "slots": jnp.asarray(rng.integers(0, S, (B, K)), jnp.int32),
        "vals": jnp.asarray(
            np.ones((B, K), np.float32)
            if binary
            else rng.normal(1, 0.3, (B, K)).astype(np.float32)
        ),
        "mask": jnp.asarray(mask),
        "labels": jnp.asarray(rng.integers(0, 2, B).astype(np.float32)),
        "weights": jnp.ones(B, jnp.float32),
    }


def test_lr_logit_oracle():
    model = LRModel()
    batch = random_batch(binary=False)
    w = jnp.asarray(np.random.default_rng(1).normal(size=(B, K, 1)), jnp.float32)
    got = np.asarray(model.logit({"w": w}, batch))
    x = np.asarray(batch["vals"]) * np.asarray(batch["mask"])
    want = (np.asarray(w)[..., 0] * x).sum(-1)
    np.testing.assert_allclose(got, want, rtol=1e-5)
    g = np.asarray(model.grad_logit({"w": w}, batch)["w"])
    np.testing.assert_allclose(g[..., 0], x, rtol=1e-6)


def test_fm_forward_has_no_half_factor():
    """logit = w·x + [(Σvx)² − Σ(vx)²] — no ½ (fm_worker.cc:82,86)."""
    model = FMModel(v_dim=D)
    batch = random_batch()
    rng = np.random.default_rng(2)
    w = jnp.asarray(rng.normal(size=(B, K, 1)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, K, D)), jnp.float32)
    got = np.asarray(model.logit({"w": w, "v": v}, batch))
    x = np.asarray(batch["mask"])  # vals are 1
    vx = np.asarray(v) * x[..., None]
    inter = (vx.sum(1) ** 2 - (vx**2).sum(1)).sum(-1)
    want = (np.asarray(w)[..., 0] * x).sum(-1) + inter
    np.testing.assert_allclose(got, want, rtol=2e-5)


def test_fm_gradient_is_half_scaled_reference_form():
    """grad_v = (Σ v x − v x)·x — the ½-scaled gradient the reference
    pushes (fm_worker.cc:140-142), which is NOT the autodiff gradient of
    the no-½ forward (would be twice this)."""
    model = FMModel(v_dim=D)
    batch = random_batch()
    rng = np.random.default_rng(3)
    w = jnp.asarray(rng.normal(size=(B, K, 1)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, K, D)), jnp.float32)
    rows = {"w": w, "v": v}
    g = model.grad_logit(rows, batch)
    x = np.asarray(batch["mask"])
    vx = np.asarray(v) * x[..., None]
    want_v = (vx.sum(1, keepdims=True) - vx) * x[..., None]
    np.testing.assert_allclose(np.asarray(g["v"]), want_v, rtol=2e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(g["w"])[..., 0], x, rtol=1e-6)

    # autodiff of the forward is exactly 2x on the interaction term
    auto = jax.grad(lambda vv: model.logit({"w": w, "v": vv}, batch).sum())(v)
    interaction_auto = np.asarray(auto) - 0.0  # w part not in v grad
    np.testing.assert_allclose(interaction_auto, 2.0 * want_v, rtol=2e-4, atol=1e-5)


def test_mvm_consistent_with_autodiff():
    """MVM uses the fixed 1+Σ form on both sides, so explicit grads must
    equal autodiff of the forward."""
    model = MVMModel(v_dim=D, max_fields=S)
    batch = random_batch(seed=5, binary=False)
    rng = np.random.default_rng(6)
    v = jnp.asarray(rng.normal(0, 0.5, size=(B, K, D)), jnp.float32)
    explicit = np.asarray(model.grad_logit({"v": v}, batch)["v"])
    auto = np.asarray(
        jax.grad(lambda vv: model.logit({"v": vv}, batch).sum())(v)
    )
    np.testing.assert_allclose(explicit, auto, rtol=1e-4, atol=1e-5)


def test_mvm_forward_oracle():
    model = MVMModel(v_dim=D, max_fields=S)
    batch = random_batch(seed=8, binary=False)
    rng = np.random.default_rng(9)
    v = np.asarray(rng.normal(0, 0.5, size=(B, K, D)), np.float32)
    got = np.asarray(model.logit({"v": jnp.asarray(v)}, batch))
    x = np.asarray(batch["vals"]) * np.asarray(batch["mask"])
    slots = np.asarray(batch["slots"])
    want = np.zeros(B)
    for b in range(B):
        total = 0.0
        for d in range(D):
            prod = 1.0
            for s in range(S):
                ssum = sum(
                    v[b, k, d] * x[b, k] for k in range(K) if slots[b, k] == s
                )
                prod *= 1.0 + ssum
            total += prod - 1.0  # centered form (models/mvm.py docstring)
        want[b] = total
    np.testing.assert_allclose(got, want, rtol=1e-4)


def test_mvm_ignores_out_of_range_fields():
    model = MVMModel(v_dim=D, max_fields=2)
    batch = random_batch(seed=10)
    batch["slots"] = jnp.full((B, K), 5, jnp.int32)  # all fields out of range
    v = jnp.asarray(np.random.default_rng(11).normal(size=(B, K, D)), jnp.float32)
    # every slot empty → product 1 per factor, centered to logit 0
    np.testing.assert_allclose(np.asarray(model.logit({"v": v}, batch)), 0.0)
    np.testing.assert_array_equal(
        np.asarray(model.grad_logit({"v": v}, batch)["v"]), 0.0
    )


# -- blocks refactor: bitwise no-regression vs the frozen legacy oracles ------
#
# The refactor's contract (docs/SERVING.md cascade PR): expressing the
# five incumbent families through models/blocks.py changes NOTHING —
# not "close", bitwise.  Each family runs the full TrainStep predict
# machinery twice on one fixed seeded batch and identical state: once
# with the refactored model, once with the frozen pre-refactor copy
# (tests/_legacy_models.py), and the pctr arrays must be equal bit for
# bit, in every parameter-residency mode the step supports.

_NR_FAMILIES = ("lr", "fm", "mvm", "ffm", "wide_deep")


def _nr_cfg(name, **over):
    from xflow_tpu.config import Config

    base = dict(
        model=name,
        table_size_log2=10,
        batch_size=8,
        max_nnz=6,
        max_fields=S,
        num_devices=1,
    )
    base.update(over)
    return Config(**base)


def _nr_batch(cfg, seed=11):
    from xflow_tpu.io.batch import make_batch

    rng = np.random.default_rng(seed)
    b, k = cfg.batch_size, cfg.max_nnz
    keys = rng.integers(0, cfg.table_size, (b, k)).astype(np.int32)
    slots = rng.integers(0, cfg.max_fields, (b, k)).astype(np.int32)
    vals = np.ones((b, k), np.float32)
    mask = (rng.random((b, k)) < 0.9).astype(np.float32)
    labels = rng.integers(0, 2, b).astype(np.float32)
    weights = np.ones(b, np.float32)
    return make_batch(
        keys, slots, vals, mask, labels, weights,
        cfg.hot_size, cfg.hot_nnz,
    )


def _nr_predict(model, cfg, batch, state=None):
    from xflow_tpu.optim import make_optimizer
    from xflow_tpu.parallel.mesh import make_mesh
    from xflow_tpu.parallel.step import TrainStep, init_state

    mesh = make_mesh(1)
    opt = make_optimizer(cfg)
    step = TrainStep(model, opt, cfg, mesh)
    if step.store is not None:
        state = step.store.init_device_state()
    elif state is None:
        state = init_state(model, opt, cfg, mesh)
    arrays = step.put_batch(batch, predict=True)
    return np.asarray(jax.device_get(step.predict(state, arrays))), state


@pytest.mark.parametrize("name", _NR_FAMILIES)
def test_blocks_refactor_bitwise_dense(name):
    from xflow_tpu.models import make_model

    cfg = _nr_cfg(name)
    batch = _nr_batch(cfg)
    got, state = _nr_predict(make_model(cfg), cfg, batch)
    want, _ = _nr_predict(legacy_model_for(cfg), cfg, batch, state=state)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("name", _NR_FAMILIES)
def test_blocks_refactor_bitwise_hot(name):
    """MXU-hot mode: frequency-head steering + the hot gather path
    (seg impl on CPU — gather-exact either way)."""
    from xflow_tpu.models import make_model

    cfg = _nr_cfg(name, hot_size_log2=6, hot_nnz=4)
    batch = _nr_batch(cfg)
    got, state = _nr_predict(make_model(cfg), cfg, batch)
    want, _ = _nr_predict(legacy_model_for(cfg), cfg, batch, state=state)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("name", _NR_FAMILIES)
def test_blocks_refactor_bitwise_tiered(name):
    """Tiered store mode: the hot+miss predict jit (store/hot.py) over
    a lazily materialized cold store — two independent TieredStores
    built from the same cfg/seed are deterministic, so the refactored
    and legacy models must still agree bitwise."""
    from xflow_tpu.models import make_model

    cfg = _nr_cfg(name, store_mode="tiered", hot_capacity_log2=5)
    batch = _nr_batch(cfg)
    got, _ = _nr_predict(make_model(cfg), cfg, batch)
    want, _ = _nr_predict(legacy_model_for(cfg), cfg, batch)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("name", ("lr", "fm", "mvm"))
def test_blocks_refactor_bitwise_grads(name):
    """Explicit-gradient families: grad_logit through blocks is
    bitwise the pre-refactor gradient (the FM reference-quirk ½-scaled
    form must survive the refactor exactly)."""
    from xflow_tpu.models import make_model

    cfg = _nr_cfg(name)
    new = make_model(cfg)
    old = legacy_model_for(cfg)
    rng = np.random.default_rng(5)
    batch = {
        "keys": jnp.asarray(rng.integers(0, 100, (B, K)), jnp.int32),
        "slots": jnp.asarray(rng.integers(0, S, (B, K)), jnp.int32),
        "vals": jnp.asarray(np.ones((B, K), np.float32)),
        "mask": jnp.asarray((rng.random((B, K)) < 0.8).astype(np.float32)),
        "labels": jnp.asarray(rng.integers(0, 2, B).astype(np.float32)),
        "weights": jnp.ones(B, jnp.float32),
    }
    rows = {
        spec.name: jnp.asarray(
            rng.normal(size=(B, K, spec.dim)).astype(np.float32)
        )
        for spec in new.tables()
    }
    g_new = new.grad_logit(rows, batch)
    g_old = old.grad_logit(rows, batch)
    assert set(g_new) == set(g_old)
    for t in g_new:
        np.testing.assert_array_equal(
            np.asarray(g_new[t]), np.asarray(g_old[t])
        )
