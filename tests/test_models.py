"""Model forward/gradient math vs independent numpy oracles, including
the reference's FM forward/backward quirk (fm_worker.cc:82 vs :140-142)
and MVM's fixed consistent 1+sum form (checked against autodiff)."""

import jax
import jax.numpy as jnp
import numpy as np

from xflow_tpu.models.fm import FMModel
from xflow_tpu.models.lr import LRModel
from xflow_tpu.models.mvm import MVMModel

B, K, D, S = 4, 6, 5, 4


def random_batch(seed=0, binary=True):
    rng = np.random.default_rng(seed)
    mask = (rng.random((B, K)) < 0.8).astype(np.float32)
    return {
        "keys": jnp.asarray(rng.integers(0, 100, (B, K)), jnp.int32),
        "slots": jnp.asarray(rng.integers(0, S, (B, K)), jnp.int32),
        "vals": jnp.asarray(
            np.ones((B, K), np.float32)
            if binary
            else rng.normal(1, 0.3, (B, K)).astype(np.float32)
        ),
        "mask": jnp.asarray(mask),
        "labels": jnp.asarray(rng.integers(0, 2, B).astype(np.float32)),
        "weights": jnp.ones(B, jnp.float32),
    }


def test_lr_logit_oracle():
    model = LRModel()
    batch = random_batch(binary=False)
    w = jnp.asarray(np.random.default_rng(1).normal(size=(B, K, 1)), jnp.float32)
    got = np.asarray(model.logit({"w": w}, batch))
    x = np.asarray(batch["vals"]) * np.asarray(batch["mask"])
    want = (np.asarray(w)[..., 0] * x).sum(-1)
    np.testing.assert_allclose(got, want, rtol=1e-5)
    g = np.asarray(model.grad_logit({"w": w}, batch)["w"])
    np.testing.assert_allclose(g[..., 0], x, rtol=1e-6)


def test_fm_forward_has_no_half_factor():
    """logit = w·x + [(Σvx)² − Σ(vx)²] — no ½ (fm_worker.cc:82,86)."""
    model = FMModel(v_dim=D)
    batch = random_batch()
    rng = np.random.default_rng(2)
    w = jnp.asarray(rng.normal(size=(B, K, 1)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, K, D)), jnp.float32)
    got = np.asarray(model.logit({"w": w, "v": v}, batch))
    x = np.asarray(batch["mask"])  # vals are 1
    vx = np.asarray(v) * x[..., None]
    inter = (vx.sum(1) ** 2 - (vx**2).sum(1)).sum(-1)
    want = (np.asarray(w)[..., 0] * x).sum(-1) + inter
    np.testing.assert_allclose(got, want, rtol=2e-5)


def test_fm_gradient_is_half_scaled_reference_form():
    """grad_v = (Σ v x − v x)·x — the ½-scaled gradient the reference
    pushes (fm_worker.cc:140-142), which is NOT the autodiff gradient of
    the no-½ forward (would be twice this)."""
    model = FMModel(v_dim=D)
    batch = random_batch()
    rng = np.random.default_rng(3)
    w = jnp.asarray(rng.normal(size=(B, K, 1)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, K, D)), jnp.float32)
    rows = {"w": w, "v": v}
    g = model.grad_logit(rows, batch)
    x = np.asarray(batch["mask"])
    vx = np.asarray(v) * x[..., None]
    want_v = (vx.sum(1, keepdims=True) - vx) * x[..., None]
    np.testing.assert_allclose(np.asarray(g["v"]), want_v, rtol=2e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(g["w"])[..., 0], x, rtol=1e-6)

    # autodiff of the forward is exactly 2x on the interaction term
    auto = jax.grad(lambda vv: model.logit({"w": w, "v": vv}, batch).sum())(v)
    interaction_auto = np.asarray(auto) - 0.0  # w part not in v grad
    np.testing.assert_allclose(interaction_auto, 2.0 * want_v, rtol=2e-4, atol=1e-5)


def test_mvm_consistent_with_autodiff():
    """MVM uses the fixed 1+Σ form on both sides, so explicit grads must
    equal autodiff of the forward."""
    model = MVMModel(v_dim=D, max_fields=S)
    batch = random_batch(seed=5, binary=False)
    rng = np.random.default_rng(6)
    v = jnp.asarray(rng.normal(0, 0.5, size=(B, K, D)), jnp.float32)
    explicit = np.asarray(model.grad_logit({"v": v}, batch)["v"])
    auto = np.asarray(
        jax.grad(lambda vv: model.logit({"v": vv}, batch).sum())(v)
    )
    np.testing.assert_allclose(explicit, auto, rtol=1e-4, atol=1e-5)


def test_mvm_forward_oracle():
    model = MVMModel(v_dim=D, max_fields=S)
    batch = random_batch(seed=8, binary=False)
    rng = np.random.default_rng(9)
    v = np.asarray(rng.normal(0, 0.5, size=(B, K, D)), np.float32)
    got = np.asarray(model.logit({"v": jnp.asarray(v)}, batch))
    x = np.asarray(batch["vals"]) * np.asarray(batch["mask"])
    slots = np.asarray(batch["slots"])
    want = np.zeros(B)
    for b in range(B):
        total = 0.0
        for d in range(D):
            prod = 1.0
            for s in range(S):
                ssum = sum(
                    v[b, k, d] * x[b, k] for k in range(K) if slots[b, k] == s
                )
                prod *= 1.0 + ssum
            total += prod - 1.0  # centered form (models/mvm.py docstring)
        want[b] = total
    np.testing.assert_allclose(got, want, rtol=1e-4)


def test_mvm_ignores_out_of_range_fields():
    model = MVMModel(v_dim=D, max_fields=2)
    batch = random_batch(seed=10)
    batch["slots"] = jnp.full((B, K), 5, jnp.int32)  # all fields out of range
    v = jnp.asarray(np.random.default_rng(11).normal(size=(B, K, D)), jnp.float32)
    # every slot empty → product 1 per factor, centered to logit 0
    np.testing.assert_allclose(np.asarray(model.logit({"v": v}, batch)), 0.0)
    np.testing.assert_array_equal(
        np.asarray(model.grad_logit({"v": v}, batch)["v"]), 0.0
    )
