"""Live telemetry plane (ISSUE 19): Prometheus-style exposition +
standalone exporter, rolling-window SLO alerting, host resource
telemetry, the streaming doctor (`obs live`), and the torn-final-line
tolerance of `obs merge` on still-appended files."""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from xflow_tpu.obs.export import (
    MetricsExporter,
    ResourceSampler,
    metric_name,
    parse_exposition,
    render_exposition,
    sample_resources,
)
from xflow_tpu.obs.live import (
    AlertEvaluator,
    AlertRule,
    LiveTailer,
    default_rules,
    run_live,
)
from xflow_tpu.obs.registry import MetricsRegistry
from xflow_tpu.obs.schema import (
    alert_row,
    load_jsonl_tolerant,
    resource_row,
    validate_rows,
)


def _header(run_id="r1", rank=0, t0=100.0):
    return {
        "t": 0.0, "kind": "run_start", "run_id": run_id,
        "time_unix": t0, "hostname": "h", "pid": 1,
        "config_digest": "x", "rank": rank, "num_hosts": 1,
        "model": "lr",
    }


# -- exposition -------------------------------------------------------------


def test_metric_name_sanitization():
    assert metric_name("serve.e2e.b8") == "xflow_serve_e2e_b8"
    assert metric_name("a-b c", prefix="") == "a_b_c"
    # a digit-leading name gets an underscore, per the exposition format
    assert metric_name("9lives", prefix="") == "_9lives"


def test_exposition_round_trips_registry_snapshot():
    """parse(render(snapshot)) recovers every counter, gauge, and
    histogram summary value — including the summary's companion _max
    gauge folded back into the summary, not misread as a gauge."""
    r = MetricsRegistry()
    r.counter_add("serve.requests", 7)
    r.counter_add("serve.shed_total", 2)
    r.gauge_set("loader.depth", 3.5)
    for v in (0.001, 0.01, 0.1, 1.0):
        r.observe("serve.queue_seconds", v)
    snap = r.snapshot(reset=False)
    parsed = parse_exposition(render_exposition(snap))
    assert parsed["counter"]["xflow_serve_requests"] == 7
    assert parsed["counter"]["xflow_serve_shed_total"] == 2
    assert parsed["gauge"]["xflow_loader_depth"] == 3.5
    s = parsed["summary"]["xflow_serve_queue_seconds"]
    h = snap.hists["serve.queue_seconds"]
    assert s["count"] == h["count"]
    assert s["0.5"] == h["p50"]
    assert s["0.99"] == h["p99"]
    assert s["max"] == h["max"]
    assert s["sum"] == pytest.approx(h["mean"] * h["count"])
    # and the _max line did NOT leak into the gauge family
    assert "xflow_serve_queue_seconds_max" not in parsed["gauge"]


def test_exposition_agrees_with_serve_stats_row():
    """The exposition and stats_row_from_snapshot are two views of ONE
    snapshot — the same registry read must produce agreeing numbers
    (what the check_live_obs gate scrapes over HTTP)."""
    from xflow_tpu.serve.batcher import stats_row_from_snapshot

    r = MetricsRegistry()
    r.counter_add("serve.requests", 10)
    r.counter_add("serve.batches", 4)
    for v in (0.002, 0.004, 0.008):
        r.observe("serve.queue_seconds", v)
        r.observe("serve.batch_size", 2.0)
    snap = r.snapshot(reset=False)
    row = stats_row_from_snapshot(snap)
    parsed = parse_exposition(render_exposition(snap))
    assert parsed["counter"]["xflow_serve_requests"] == row["requests"]
    assert parsed["counter"]["xflow_serve_batches"] == row["batches"]
    q = parsed["summary"]["xflow_serve_queue_seconds"]
    assert round(q["0.5"], 6) == row["queue_p50"]
    assert round(q["0.99"], 6) == row["queue_p99"]


def test_exposition_concurrent_scrape_lock_stress():
    """Writers hammer the registry while a render loop scrapes it,
    SANITIZER-ARMED: no exception, every scrape parses, and counters
    are monotonic across scrapes (a torn read would go backwards)."""
    from xflow_tpu.analysis import LockOrderSanitizer, static_lock_order

    r = MetricsRegistry()
    san = LockOrderSanitizer()
    san.instrument(r, "_lock", "MetricsRegistry._lock")
    stop = threading.Event()
    errors: list[BaseException] = []

    def writer():
        try:
            while not stop.is_set():
                r.counter_add("serve.requests")
                r.observe("serve.queue_seconds", 0.001)
                r.gauge_set("loader.depth", 1.0)
        except BaseException as e:  # pragma: no cover - failure path
            errors.append(e)

    threads = [threading.Thread(target=writer) for _ in range(4)]
    for t in threads:
        t.start()
    last = 0.0
    try:
        for _ in range(200):
            parsed = parse_exposition(
                render_exposition(r.snapshot(reset=False))
            )
            got = parsed["counter"].get("xflow_serve_requests", 0.0)
            assert got >= last, "counter went backwards: torn read"
            last = got
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
    assert not errors, errors
    assert last > 0
    # observed acquisition orders are consistent with the static XF007
    # lock-order graph (same cross-check as the batcher lock stress)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    static = static_lock_order([os.path.join(repo, "xflow_tpu")])
    assert san.contradictions(static) == []


# -- alert rules ------------------------------------------------------------


def test_alert_rule_value_semantics():
    rule = AlertRule(
        "err", "serve_shed", "errors", threshold=0.1, denom="admitted"
    )
    assert rule.value({"kind": "other", "errors": 1, "admitted": 1}) is None
    assert rule.value({"kind": "serve_shed", "admitted": 4}) is None
    assert rule.value(
        {"kind": "serve_shed", "errors": True, "admitted": 4}
    ) is None  # bools are not samples
    assert rule.value(
        {"kind": "serve_shed", "errors": 1, "admitted": 0}
    ) is None  # empty window: no denominator, no sample
    assert rule.value(
        {"kind": "serve_shed", "errors": 1, "admitted": 4}
    ) == 0.25
    plain = AlertRule("q", "serve_stats", "queue_p99", threshold=1.0)
    assert plain.value({"kind": "serve_stats", "queue_p99": 2.5}) == 2.5


def test_default_rules_unique_and_evaluator_rejects_duplicates():
    names = [r.name for r in default_rules()]
    assert len(set(names)) == len(names)
    with pytest.raises(ValueError, match="duplicate"):
        AlertEvaluator(rules=[
            AlertRule("a", "eval", "auc", 1.0),
            AlertRule("a", "eval", "auc", 2.0),
        ])


def test_burn_rate_needs_both_windows():
    """Multi-window semantics: a short spike over a healthy long
    window does NOT fire (the long mean gates it); a sustained breach
    fires; a clean short window resolves even while the long window
    still remembers the breach."""
    rule = AlertRule(
        "err", "serve_shed", "frac", threshold=0.1,
        short_s=60.0, long_s=300.0,
    )
    ev = AlertEvaluator(rules=[rule])
    t0 = 1_000.0
    # 4 healthy samples spread over the long window
    for i in range(4):
        assert ev.observe_rows(
            [{"kind": "serve_shed", "frac": 0.0, "time_unix": t0 + i * 50}]
        ) == []
    # one spike: short mean 1.0 > 0.1, but long mean 1/5 = 0.2... that
    # fires; use a diluted spike instead: long mean 0.4/5 = 0.08 < 0.1
    spike = ev.observe_rows(
        [{"kind": "serve_shed", "frac": 0.4, "time_unix": t0 + 200}]
    )
    assert spike == []  # long window vetoes the page
    # sustained breach: short AND long means cross the threshold
    fired = []
    for i in range(4):
        fired += ev.observe_rows([
            {"kind": "serve_shed", "frac": 0.4,
             "time_unix": t0 + 210 + i * 10}
        ])
    assert [(a["rule"], a["state"]) for a in fired] == [("err", "firing")]
    assert ev.summary()["firing"] == ["err"]
    # clean short window resolves (old breach still inside long_s)
    resolved = ev.observe_rows(
        [{"kind": "serve_shed", "frac": 0.0, "time_unix": t0 + 310}],
        now=t0 + 310,
    )
    assert [(a["rule"], a["state"]) for a in resolved] == [
        ("err", "resolved")
    ]
    assert ev.summary()["firing"] == []
    assert ev.summary()["fired_total"] == 1
    assert ev.summary()["resolved_total"] == 1


def test_alert_rows_land_in_metrics_stream_and_validate(tmp_path):
    from xflow_tpu.utils.logging import MetricsLogger

    out = tmp_path / "m.jsonl"
    logger = MetricsLogger(str(out), run_header=_header())
    ev = AlertEvaluator(metrics_logger=logger)
    t0 = 1_000.0
    ev.observe_rows(
        [{"kind": "serve_shed", "errors": 5, "admitted": 10,
          "time_unix": t0}], now=t0,
    )
    ev.observe_rows(
        [{"kind": "serve_shed", "errors": 0, "admitted": 10,
          "time_unix": t0 + 120}], now=t0 + 120,
    )
    logger.close()
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    assert validate_rows(rows) == []
    states = [
        (r["rule"], r["state"]) for r in rows if r["kind"] == "alert"
    ]
    assert states == [
        ("serve_error_frac", "firing"), ("serve_error_frac", "resolved"),
    ]


def test_doctor_consumes_alert_rows_as_evidence():
    from xflow_tpu.obs.doctor import diagnose

    base = alert_row(
        rule="serve_error_frac", state="firing", value=0.5,
        threshold=0.05, short_s=60, long_s=300, samples=3, detail="d",
    )
    firing = [_header(), dict(base, t=1.0, kind="alert")]
    codes = {(d.severity, d.code) for d in diagnose(firing)}
    assert ("warn", "alert_firing") in codes
    resolved = firing + [dict(
        alert_row(
            rule="serve_error_frac", state="resolved", value=0.0,
            threshold=0.05, short_s=60, long_s=300, samples=2,
            detail="d",
        ), t=2.0, kind="alert",
    )]
    codes = {(d.severity, d.code) for d in diagnose(resolved)}
    assert ("info", "alert_resolved") in codes
    assert ("warn", "alert_firing") not in codes


# -- torn-line tolerance (obs merge on a still-appended file) ---------------


def test_load_jsonl_tolerant_skips_torn_final_line(tmp_path):
    p = tmp_path / "m.jsonl"
    p.write_text(
        json.dumps(_header()) + "\n"
        + json.dumps({"t": 1.0, "kind": "eval", "auc": 0.5,
                      "logloss": 0.6, "examples": 10}) + "\n"
        + '{"t": 2.0, "kind": "ev'  # writer mid-append
    )
    rows, skipped = load_jsonl_tolerant(str(p))
    assert skipped == 1
    assert [r["kind"] for r in rows] == ["run_start", "eval"]
    # a torn MIDDLE line is corruption, not appending — still fatal
    bad = tmp_path / "bad.jsonl"
    bad.write_text(
        json.dumps(_header()) + "\n" + '{"torn\n'
        + json.dumps({"t": 1.0, "kind": "eval"}) + "\n"
    )
    with pytest.raises(ValueError, match="not valid JSON"):
        load_jsonl_tolerant(str(bad))


def test_merge_tolerates_still_appended_file(tmp_path):
    """Satellite regression pin: `obs merge` over a file whose final
    line is torn (still being appended) merges the complete rows and
    REPORTS the skip instead of failing."""
    from xflow_tpu.obs.doctor import merge_rows_tolerant

    a = tmp_path / "a.jsonl"
    a.write_text(
        json.dumps(_header(run_id="a", rank=0)) + "\n"
        + json.dumps({"t": 1.0, "kind": "eval", "auc": 0.5}) + "\n"
        + '{"t": 2.0, "kind"'
    )
    b = tmp_path / "b.jsonl"
    b.write_text(
        json.dumps(_header(run_id="b", rank=1, t0=100.5)) + "\n"
        + json.dumps({"t": 1.0, "kind": "eval", "auc": 0.6}) + "\n"
    )
    rows, skipped = merge_rows_tolerant([str(a), str(b)])
    assert skipped == 1
    assert len(rows) == 4
    assert all("time_unix" in r and "rank" in r for r in rows)
    # the CLI surface: exit 0 with the skip reported, rows on stdout
    proc = subprocess.run(
        [sys.executable, "-m", "xflow_tpu.obs", "merge",
         str(a), str(b)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert len(proc.stdout.splitlines()) == 4
    assert "1 torn final line(s) skipped" in proc.stderr


# -- live tailer / run_live -------------------------------------------------


def test_live_tailer_incremental_and_torn_tail(tmp_path):
    p = tmp_path / "m.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps(_header(t0=50.0)) + "\n")
        f.write(json.dumps({"t": 1.0, "kind": "eval", "auc": 0.5}) + "\n")
        f.write('{"t": 2.0, "kind": "ev')  # torn tail
    tailer = LiveTailer([str(p)])
    first = tailer.poll()
    assert [r["kind"] for r in first] == ["run_start", "eval"]
    assert first[1]["time_unix"] == 51.0  # t0 + t tagging, like merge
    assert tailer.skipped == 0
    assert tailer.poll() == []  # torn tail waits in the file
    with open(p, "a") as f:
        f.write('al", "auc": 0.6}\n')  # writer finishes the line
        f.write("garbage-not-json\n")  # a COMPLETE unparseable line
        f.write(json.dumps({"t": 3.0, "kind": "eval", "auc": 0.7}) + "\n")
    second = tailer.poll()
    assert [r.get("auc") for r in second] == [0.6, 0.7]
    assert tailer.skipped == 1  # counted, not fatal
    # a path that does not exist yet is tailed, not crashed on
    ghost = LiveTailer([str(tmp_path / "ghost.jsonl")])
    assert ghost.poll() == []


def test_run_live_once_matches_post_hoc_doctor(tmp_path):
    """The acceptance pin: on the same (finished or torn) file, `obs
    live --once` reaches the diagnosis codes and verdict `obs doctor`
    reaches post-hoc."""
    from xflow_tpu.obs.doctor import diagnose, merge_rows
    from xflow_tpu.obs.schema import health_row

    p = tmp_path / "sick.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps(_header()) + "\n")
        f.write(json.dumps(dict(health_row(
            cause="input_stall", channel="train",
            silence_seconds=45.0, threshold_seconds=30.0,
            detail="input_stall",
        ), t=5.0, kind="health")) + "\n")
        f.write('{"t": 9.0, "kind": "tr')  # still growing
    lines: list[str] = []
    rc = run_live([str(p)], once=True, out=lines.append)
    post = diagnose(merge_rows([str(p)]))
    live_codes = {
        l.split("] ", 1)[1].split(":", 1)[0]
        for l in lines
        if l.startswith("[") and not l.startswith("[ALERT]")
    }
    assert live_codes == {d.code for d in post}
    post_rc = (
        1 if any(d.severity in ("crit", "warn") for d in post) else 0
    )
    assert rc == post_rc == 1  # the stall IS a warn, both agree


def test_run_live_streams_alert_transitions(tmp_path):
    """run_live evaluates the SLO rules on rows as they appear: a bad
    window already in the file fires on the first poll and is printed
    as an [ALERT] line; exit code goes bad while it stays firing."""
    p = tmp_path / "m.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps(_header(t0=time.time())) + "\n")
        f.write(json.dumps({
            "t": 1.0, "kind": "serve_shed", "admitted": 10,
            "completed": 5, "shed_total": 0, "shed_frac": 0.0,
            "by_cause": {}, "errors": 5, "depth": 0,
            "queue_age_s": 0.0,
        }) + "\n")
    lines: list[str] = []
    rc = run_live([str(p)], once=True, out=lines.append)
    assert rc == 1
    assert any(
        l.startswith("[ALERT] serve_error_frac firing") for l in lines
    )
    assert any("firing now: ['serve_error_frac']" in l for l in lines)


def test_obs_live_cli(tmp_path):
    p = tmp_path / "m.jsonl"
    p.write_text(json.dumps(_header()) + "\n")
    proc = subprocess.run(
        [sys.executable, "-m", "xflow_tpu.obs", "live", str(p),
         "--once"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "obs live" in proc.stdout


# -- resource telemetry -----------------------------------------------------


def test_sample_resources_schema_valid():
    row = sample_resources()
    assert validate_rows([dict(row, t=0.0, kind="resource")]) == []
    assert row["rss_bytes"] > 0
    assert row["cpu_seconds"] > 0
    assert row["threads"] >= 1
    assert row["open_fds"] > 0


def test_resource_sampler_inline_sample_mirrors_gauges(tmp_path):
    from xflow_tpu.utils.logging import MetricsLogger

    out = tmp_path / "m.jsonl"
    logger = MetricsLogger(str(out), run_header=_header())
    reg = MetricsRegistry()
    sampler = ResourceSampler(metrics_logger=logger, registry=reg)
    body = sampler.sample()
    logger.close()
    gauges = reg.snapshot().gauges
    assert gauges["obs.resource.rss_bytes"] == float(body["rss_bytes"])
    assert gauges["obs.resource.threads"] == float(body["threads"])
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    assert validate_rows(rows) == []
    assert sum(1 for r in rows if r["kind"] == "resource") == 1


def test_resource_sampler_thread_lifecycle(tmp_path):
    from xflow_tpu.utils.logging import MetricsLogger

    with pytest.raises(ValueError, match="interval_s"):
        ResourceSampler(interval_s=0.0)
    out = tmp_path / "m.jsonl"
    logger = MetricsLogger(str(out), run_header=_header())
    reg = MetricsRegistry()
    sampler = ResourceSampler(
        metrics_logger=logger, registry=reg, interval_s=0.02
    ).start()
    time.sleep(0.1)
    sampler.close()
    sampler.close()  # idempotent
    logger.close()
    assert not any(
        t.name == "resource-sampler" for t in threading.enumerate()
    )
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    n = sum(1 for r in rows if r["kind"] == "resource")
    assert n >= 2  # the immediate first sample + the close() sample
    # the XF009 heartbeat gauge beat at least once mid-loop
    assert "obs.resource.beat_unix" in reg.snapshot().gauges


# -- standalone exporter ----------------------------------------------------


def test_metrics_exporter_serves_registry_and_reaps():
    reg = MetricsRegistry()
    reg.counter_add("train.steps", 42)
    with pytest.raises(ValueError, match="timeout_s"):
        MetricsExporter(reg, timeout_s=0.0)
    exporter = MetricsExporter(reg, port=0).start()
    try:
        url = f"{exporter.address}/metrics"
        with urllib.request.urlopen(url, timeout=10) as r:
            assert r.status == 200
            assert "text/plain" in r.headers["Content-Type"]
            text = r.read().decode()
        assert parse_exposition(text)["counter"][
            "xflow_train_steps"
        ] == 42
        # live: a counter bump shows on the NEXT scrape (no caching)
        reg.counter_add("train.steps", 1)
        with urllib.request.urlopen(url, timeout=10) as r:
            text = r.read().decode()
        assert parse_exposition(text)["counter"][
            "xflow_train_steps"
        ] == 43
        with urllib.request.urlopen(
            f"{exporter.address}/healthz", timeout=10
        ) as r:
            assert json.load(r)["status"] == "exporting"
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"{exporter.address}/nope", timeout=10
            )
    finally:
        exporter.close()
    assert not any(
        t.name == "metrics-exporter" for t in threading.enumerate()
    )


def test_trainer_reaps_exporter_and_sampler(toy_dataset, tmp_path):
    """Config.obs_export_port + obs_resource_every_s through the real
    Trainer: /metrics serves during the run, close() reaps both
    threads (XF006), and the resource rows land schema-valid."""
    from xflow_tpu.config import Config
    from xflow_tpu.trainer import Trainer

    out = tmp_path / "m.jsonl"
    cfg = Config(
        train_path=toy_dataset.train_prefix,
        test_path=toy_dataset.test_prefix,
        model="lr",
        epochs=1,
        batch_size=64,
        table_size_log2=14,
        max_nnz=24,
        num_devices=1,
        metrics_out=str(out),
        obs_export_port=0,  # off: picking a fixed port races CI boxes
        obs_resource_every_s=0.5,
    )
    t = Trainer(cfg)
    # attach an exporter the way Config.obs_export_port would, but on
    # an OS-assigned port (the config path needs a fixed one)
    from xflow_tpu.obs.export import MetricsExporter

    assert t._exporter is None
    t._exporter = MetricsExporter(t.obs.registry, port=0).start()
    t.train()
    with urllib.request.urlopen(
        f"{t._exporter.address}/metrics", timeout=10
    ) as r:
        assert r.status == 200
    t.close()
    assert not any(
        thr.name in ("resource-sampler", "metrics-exporter")
        for thr in threading.enumerate()
    )
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    assert validate_rows(rows) == []
    assert any(r["kind"] == "resource" for r in rows)


def test_config_validates_live_obs_knobs(toy_dataset):
    from xflow_tpu.config import Config

    base = dict(
        train_path=toy_dataset.train_prefix,
        test_path=toy_dataset.test_prefix,
        model="lr",
    )
    with pytest.raises(ValueError, match="obs_export_port"):
        Config(obs_export_port=70000, **base)
    with pytest.raises(ValueError, match="obs_resource_every_s"):
        Config(obs_resource_every_s=-1.0, **base)
    with pytest.raises(ValueError, match="metrics_out"):
        Config(obs_resource_every_s=5.0, **base)
    Config(obs_export_port=9100, **base)  # valid port, no exporter yet


# -- schema constructors ----------------------------------------------------


def test_alert_and_resource_constructors_schema_valid():
    rows = [
        dict(alert_row(
            rule="r", state="firing", value=1.234567891,
            threshold=0.05, short_s=60, long_s=300, samples=3,
            detail="d",
        ), t=0.0, kind="alert"),
        dict(resource_row(
            rss_bytes=1, cpu_seconds=2.5, threads=3, open_fds=4,
            gc_collections=5,
        ), t=0.0, kind="resource"),
    ]
    assert validate_rows(rows) == []
    assert rows[0]["value"] == round(1.234567891, 6)


def test_watchdog_state_surface():
    """Watchdog.state() — the /v1/stats enrichment — reports health,
    open incidents, and the last health row, all lock-guarded."""
    from xflow_tpu.obs.flight import FlightRecorder
    from xflow_tpu.obs.watchdog import Watchdog

    flight = FlightRecorder()
    wd = Watchdog(flight, input_s=0.01, device_s=10.0, serve_s=10.0)
    state = wd.state()
    assert state["healthy"] is True
    assert state["incidents"] == {}
    assert state["last"] is None
    flight.note_phase("input_stall")
    time.sleep(0.03)
    wd.check()  # trips input_stall (silence > input_s)
    state = wd.state()
    assert state["healthy"] is False
    assert state["incidents"]["train"]["cause"] == "input_stall"
    assert state["trip_count"] == 1
    assert state["last"]["cause"] == "input_stall"
    flight.note_phase("step")  # fresh beat -> recovery
    wd.check()
    state = wd.state()
    assert state["healthy"] is True
    assert state["last"]["cause"] == "recovered:input_stall"


# -- the tier-1 gate --------------------------------------------------------


def test_check_live_obs_script():
    """scripts/check_live_obs.py passes end to end — run as a
    subprocess exactly as CI would."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable,
         os.path.join(repo, "scripts", "check_live_obs.py")],
        capture_output=True,
        text=True,
        timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout
