"""Serving subsystem (ISSUE 2): artifact export/load, PredictEngine
shape-bucketed compilation, micro-batching, hot swap, CLI, and the
train → export → serve parity guarantee."""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import jax

from xflow_tpu.config import Config
from xflow_tpu.io.loader import ShardLoader
from xflow_tpu.trainer import Trainer


def _cfg(toy_dataset, **overrides):
    base = dict(
        train_path=toy_dataset.train_prefix,
        test_path=toy_dataset.test_prefix,
        model="lr",
        epochs=2,
        batch_size=64,
        table_size_log2=14,
        max_nnz=24,
        num_devices=1,
    )
    base.update(overrides)
    return Config(**base)


def _raw_batches(trainer, path):
    """Raw hash-key-space batches of the shard (no remap, no hot split)
    — what an external caller would build (io/batch.py)."""
    loader = ShardLoader(
        path,
        batch_size=trainer.cfg.batch_size,
        max_nnz=trainer.cfg.max_nnz,
        table_size=trainer.cfg.table_size,
        parse_fn=trainer._parse_fn(),
    )
    return [b for b, _ in loader.iter_batches()]


def _trainer_pctr(trainer, batch):
    """The pre-engine reference path: prepare + put + compiled predict."""
    return np.asarray(
        jax.device_get(
            trainer.step.predict(
                trainer.state,
                trainer.step.put_batch(trainer.prepare_batch(batch)),
            )
        )
    )


@pytest.fixture(scope="module")
def lr_served(toy_dataset, tmp_path_factory):
    """One trained lr model + its exported artifact, shared across
    tests (export is read-only from then on)."""
    from xflow_tpu.serve.artifact import export_artifact

    trainer = Trainer(_cfg(toy_dataset))
    trainer.train()
    art = str(tmp_path_factory.mktemp("serve") / "artifact")
    export_artifact(trainer, art)
    return {"trainer": trainer, "artifact": art}


def test_export_artifact_layout(lr_served):
    from xflow_tpu.serve.artifact import load_manifest

    art = lr_served["artifact"]
    manifest = load_manifest(art)
    assert manifest["model"] == "lr"
    assert manifest["config_digest"] == lr_served["trainer"].cfg.digest()
    assert "w.param" in manifest["arrays"]
    files = os.listdir(art)
    # params only: optimizer aux (FTRL n/z) never ships to serving
    assert not any(".n.r" in f or ".z.r" in f for f in files)
    assert any(f.startswith("w.param.r") for f in files)
    assert "remap.npy" not in files  # no hot table on this model


def test_engine_matches_trainer_and_eval_dump(lr_served, tmp_path):
    """Train → export → PredictEngine parity: engine pctr matches the
    trainer's compiled predict AND the evaluate() prediction dump to
    1e-6."""
    from xflow_tpu.serve.engine import PredictEngine

    trainer = lr_served["trainer"]
    engine = PredictEngine.load(
        lr_served["artifact"], buckets=(8, 64), warm=True
    )
    shard = trainer.cfg.test_path + "-00000"
    for batch in _raw_batches(trainer, shard):
        np.testing.assert_allclose(
            engine.predict(batch), _trainer_pctr(trainer, batch), atol=1e-6
        )
    # the evaluate() artifact (label\tpctr lines) as ground truth
    pred = tmp_path / "pred.txt"
    trainer.evaluate(pred_out=str(pred))
    want = np.asarray(
        [float(l.split("\t")[1]) for l in pred.read_text().splitlines()]
    )
    lines = open(shard).read().splitlines()
    got = engine.score_text(lines)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_engine_parity_hot_table(toy_dataset, tmp_path):
    """The hot-table remap folds into the artifact: an engine scoring
    RAW hash-space batches matches the trainer bit-for-bit though the
    table rows live in the permuted space."""
    from xflow_tpu.serve.artifact import export_artifact
    from xflow_tpu.serve.engine import PredictEngine

    trainer = Trainer(_cfg(
        toy_dataset, epochs=1,
        hot_size_log2=6, hot_nnz=8, freq_sample_mib=1,
    ))
    trainer.train()
    art = str(tmp_path / "hot_artifact")
    export_artifact(trainer, art)
    assert os.path.exists(os.path.join(art, "remap.npy"))
    engine = PredictEngine.load(art, buckets=(64,), warm=True)
    for batch in _raw_batches(trainer, trainer.cfg.test_path + "-00000"):
        np.testing.assert_allclose(
            engine.predict(batch), _trainer_pctr(trainer, batch), atol=1e-6
        )


def test_engine_needs_no_trainer_or_loader(lr_served, monkeypatch):
    """Acceptance: PredictEngine scores with ZERO Trainer/ShardLoader
    instantiation — both constructors are booby-trapped."""
    from xflow_tpu.serve.engine import PredictEngine

    def boom(*a, **kw):
        raise AssertionError("serving must not instantiate this")

    monkeypatch.setattr(Trainer, "__init__", boom)
    monkeypatch.setattr(ShardLoader, "__init__", boom)
    engine = PredictEngine.load(
        lr_served["artifact"], buckets=(8,), warm=True
    )
    rows = [np.asarray([3, 99, 2048]), np.asarray([7])]
    pctr = engine.predict(engine.featurize_raw(rows))
    assert pctr.shape == (2,)
    assert np.all((pctr > 0.0) & (pctr < 1.0))


def test_one_compile_per_bucket(lr_served):
    """Acceptance: exactly one compile per warmed bucket (the
    compile-count hook), and NO traffic mix adds more — arbitrary
    request sizes pad onto buckets, oversized batches chunk."""
    from xflow_tpu.serve.engine import PredictEngine

    engine = PredictEngine.load(
        lr_served["artifact"], buckets=(1, 8, 64), warm=True
    )
    assert engine.buckets == (1, 8, 64)
    assert engine.compile_count == 3
    rng = np.random.default_rng(0)
    table = engine.cfg.table_size
    for n in (1, 2, 3, 7, 8, 9, 40, 64, 65, 200):
        rows = [
            rng.integers(0, table, size=int(rng.integers(1, 10)))
            for _ in range(n)
        ]
        assert engine.predict(engine.featurize_raw(rows)).shape == (n,)
    assert engine.compile_count == 3, "a request size triggered a recompile"


def test_value_carrying_request_rejected_after_warm(lr_served):
    """Compact-wire invariants are validated on EVERY serving batch —
    warmup must not consume TrainStep's one-shot check and let a
    value-carrying request silently score with vals=1."""
    from xflow_tpu.serve.engine import PredictEngine

    engine = PredictEngine.load(
        lr_served["artifact"], buckets=(8,), warm=True
    )
    assert engine.step.compact_wire
    bad = (np.asarray([3, 5]), None, np.asarray([0.5, 2.0]))
    with pytest.raises(ValueError, match="compact wire"):
        engine.predict(engine.featurize_raw([bad]))


def test_engine_refuses_digest_mismatch(lr_served, tmp_path):
    from xflow_tpu.serve.artifact import MANIFEST
    from xflow_tpu.serve.engine import PredictEngine

    trainer = lr_served["trainer"]
    # caller expectation drifted from the exported config
    with pytest.raises(ValueError, match="refusing"):
        PredictEngine.load(
            lr_served["artifact"],
            config=trainer.cfg.replace(alpha=0.123),
            warm=False,
        )
    # matching expectation loads fine
    PredictEngine.load(
        lr_served["artifact"], config=trainer.cfg, buckets=(8,), warm=False
    )
    # tampered artifact: stored digest no longer matches embedded config
    import shutil

    bad = tmp_path / "tampered"
    shutil.copytree(lr_served["artifact"], bad)
    mpath = bad / MANIFEST
    manifest = json.loads(mpath.read_text())
    manifest["config_digest"] = "deadbeef0000"
    mpath.write_text(json.dumps(manifest))
    with pytest.raises(ValueError, match="corrupt or tampered"):
        PredictEngine.load(str(bad), warm=False)


def test_engine_multidevice_mesh(lr_served):
    """Artifact row-range shards assemble onto a different serving mesh
    (1-chip export → 8-device engine); buckets round up to
    mesh-divisible sizes and predictions are unchanged."""
    from xflow_tpu.serve.engine import PredictEngine

    e1 = PredictEngine.load(lr_served["artifact"], buckets=(8,), warm=False)
    e8 = PredictEngine.load(
        lr_served["artifact"], num_devices=8, buckets=(1, 8, 20), warm=False
    )
    assert e8.buckets == (8, 24)  # 1→8, 20→24 on the 8-device mesh
    rows = [np.asarray([5, 17, 4000]), np.asarray([9, 1]), np.asarray([2])]
    raw = e8.featurize_raw(rows)
    np.testing.assert_allclose(
        e8.predict(raw), e1.predict(raw), atol=1e-6
    )


def test_predict_batch_routes_through_buckets(toy_dataset):
    """Satellite: XFlow.predict_batch no longer recompiles per batch
    shape — distinct sizes share the engine's buckets, and the engine
    tracks the LIVE trainer state (scores reflect further training)."""
    from xflow_tpu.api import XFlow

    xf = XFlow(
        toy_dataset.train_prefix,
        toy_dataset.test_prefix,
        model="lr",
        epochs=1,
        batch_size=64,
        table_size_log2=14,
        max_nnz=24,
        num_devices=1,
    )
    xf.train()
    batches = _raw_batches(xf.trainer, xf.config.test_path + "-00000")
    full = batches[0]
    from xflow_tpu.serve.engine import _slice_rows

    for n in (1, 3, 17, 64):
        sub = _slice_rows(full, 0, n)
        np.testing.assert_allclose(
            xf.predict_batch(sub),
            _trainer_pctr(xf.trainer, sub),
            atol=1e-6,
        )
    engine = xf._engine
    compiles = engine.compile_count
    assert compiles <= len(engine.buckets)
    # more training; predict_batch must see the evolved weights with
    # no new compiles (same shapes/shardings through the AOT exes)
    before = xf.predict_batch(full)
    xf.trainer.train_epoch()
    after = xf.predict_batch(full)
    assert engine.compile_count == compiles
    assert not np.allclose(before, after)
    np.testing.assert_allclose(
        after, _trainer_pctr(xf.trainer, full), atol=1e-6
    )


def test_microbatcher_coalesces_and_accounts(lr_served, tmp_path):
    """Concurrent single-row submits coalesce into few device calls;
    values match direct engine scoring; the serve_stats row carries
    queue/featurize/device p50/p99 and passes the schema."""
    from xflow_tpu.obs.schema import load_jsonl, validate_rows
    from xflow_tpu.serve.batcher import MicroBatcher
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.utils.logging import MetricsLogger

    engine = PredictEngine.load(
        lr_served["artifact"], buckets=(8, 64), warm=True
    )
    out = tmp_path / "serve.jsonl"
    logger = MetricsLogger(out, run_header={
        "run_id": "t", "config_digest": engine.digest,
        "rank": 0, "num_hosts": 1,
    })
    rng = np.random.default_rng(1)
    rows = [
        rng.integers(0, engine.cfg.table_size, size=6) for _ in range(50)
    ]
    with MicroBatcher(
        engine, max_wait_ms=20.0, metrics_logger=logger
    ) as mb:
        futs = [mb.submit(r) for r in rows]
        got = np.asarray([f.result() for f in futs])
    stats = mb.close()  # idempotent: same final row, not re-logged
    assert mb.close() is stats
    with pytest.raises(RuntimeError, match="closed"):
        mb.submit(rows[0])
    logger.close()
    np.testing.assert_allclose(
        got, engine.predict(engine.featurize_raw(rows)), atol=1e-6
    )
    rows_jsonl = load_jsonl(str(out))
    assert validate_rows(rows_jsonl) == []
    srows = [r for r in rows_jsonl if r["kind"] == "serve_stats"]
    assert len(srows) == 1  # double close logs exactly one stats row
    srow = srows[0]
    assert srow["requests"] == 50
    assert 0 < srow["batches"] < 50  # coalescing happened
    for f in ("queue_p99", "featurize_p99", "device_p99"):
        assert srow[f] > 0.0
    assert srow["queue_p50"] <= srow["queue_p99"]
    assert stats["requests"] == srow["requests"]


def test_serve_watchdog_flags_backed_up_batcher(lr_served, tmp_path):
    """ISSUE 4: the serving tier heartbeats the flight recorder (engine
    per device call, batcher per coalesced batch), and a watchdog wired
    to ``batcher.pending`` classifies silence-with-backlog as
    serve_queue_stall — while a drained batcher's silence stays
    healthy."""
    import time

    from xflow_tpu.obs import Obs
    from xflow_tpu.obs.flight import FlightRecorder
    from xflow_tpu.obs.schema import load_jsonl, validate_rows
    from xflow_tpu.obs.watchdog import Watchdog
    from xflow_tpu.serve.batcher import MicroBatcher
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.utils.logging import MetricsLogger

    fl = FlightRecorder()
    engine = PredictEngine.load(
        lr_served["artifact"], buckets=(8,), warm=True, obs=Obs(flight=fl)
    )
    orig = engine.predict_prepared
    engine.predict_prepared = lambda b: (time.sleep(0.7), orig(b))[1]
    out = tmp_path / "serve.jsonl"
    logger = MetricsLogger(out)
    rng = np.random.default_rng(2)
    rows = [
        rng.integers(0, engine.cfg.table_size, size=6) for _ in range(3)
    ]
    with MicroBatcher(
        engine, max_wait_ms=0.0, max_batch=1, flight=fl
    ) as mb:
        wd = Watchdog(
            fl, input_s=60.0, device_s=60.0, serve_s=0.2,
            metrics_logger=logger,
        )
        wd.set_pending("serve", mb.pending)
        with wd:  # real monitor thread (poll = serve_s / 4)
            futs = [mb.submit(r) for r in rows]
            got = [f.result() for f in futs]
            # backlog existed: batch 2/3 queued behind the slowed
            # device call after batch 1's heartbeat — a trip fired
            assert wd.trip_count >= 1
            # drained now: silence with pending() False never trips
            before = wd.trip_count
            time.sleep(0.5)
            assert wd.trip_count == before
            assert not mb.pending()
    logger.close()
    assert len(got) == 3
    jrows = load_jsonl(str(out))
    assert validate_rows(jrows) == []
    causes = {r["cause"] for r in jrows if r["kind"] == "health"}
    assert "serve_queue_stall" in causes
    # the flight record saw both serve-side heartbeat sources; the
    # engine's beat names the bucket the call ran in
    details = {e["detail"] for e in fl.snapshot()["events"]}
    assert "batch" in details
    assert "execute:b8" in details  # bucket choice recorded


def test_microbatcher_hot_swap(toy_dataset, tmp_path):
    """Atomic mid-serve artifact swap: later requests score on the new
    weights, and a swap to a DIFFERENT config digest is refused."""
    from xflow_tpu.serve.artifact import export_artifact
    from xflow_tpu.serve.batcher import MicroBatcher
    from xflow_tpu.serve.engine import PredictEngine

    trainer = Trainer(_cfg(toy_dataset, epochs=1))
    trainer.train()
    art_a = str(tmp_path / "a")
    export_artifact(trainer, art_a)
    trainer.train_epoch()  # evolve the weights
    art_b = str(tmp_path / "b")
    export_artifact(trainer, art_b)

    ea = PredictEngine.load(art_a, buckets=(8,), warm=True)
    eb = PredictEngine.load(art_b, buckets=(8,), warm=True)
    # a row of keys the model actually trained on (arbitrary ids would
    # hit untouched zero rows: pctr 0.5 on both engines)
    first = _raw_batches(trainer, trainer.cfg.test_path + "-00000")[0]
    row = first.keys[0][first.mask[0] > 0]
    mb = MicroBatcher(ea, max_wait_ms=0.0)
    try:
        pa = mb.score(row)
        mb.swap(eb)
        pb = mb.score(row)
        assert pa == pytest.approx(float(ea.predict(ea.featurize_raw([row]))[0]))
        assert pb == pytest.approx(float(eb.predict(eb.featurize_raw([row]))[0]))
        assert pa != pb
        other = Trainer(_cfg(toy_dataset, epochs=1, alpha=0.9))
        art_c = str(tmp_path / "c")
        export_artifact(other, art_c)
        ec = PredictEngine.load(art_c, buckets=(8,), warm=False)
        with pytest.raises(ValueError, match="hot-swap refused"):
            mb.swap(ec)
        mb.swap(ec, force=True)  # explicit override works
    finally:
        stats = mb.close()
    assert stats["swaps"] == 2


def test_serve_cli_score_and_bench(lr_served, tmp_path, capsys):
    from xflow_tpu.obs.__main__ import main as obs_main
    from xflow_tpu.serve.__main__ import main as serve_main

    shard = lr_served["trainer"].cfg.test_path + "-00000"
    out = tmp_path / "scores.txt"
    assert serve_main([
        "score", lr_served["artifact"],
        "--input", shard, "--out", str(out), "--buckets", "8,64",
    ]) == 0
    scores = [float(l) for l in out.read_text().splitlines()]
    assert len(scores) == len(open(shard).read().splitlines())
    assert all(0.0 < s < 1.0 for s in scores)

    metrics = tmp_path / "bench.jsonl"
    assert serve_main([
        "bench", lr_served["artifact"],
        "--requests", "32", "--concurrency", "4",
        "--buckets", "8,64", "--max-wait-ms", "1",
        "--metrics-out", str(metrics),
    ]) == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    for f in (
        "e2e_p50", "e2e_p99", "queue_p99", "featurize_p99",
        "device_p99", "requests_per_sec",
    ):
        assert f in summary
    assert summary["requests"] == 32
    assert summary["compiles"] == 2
    # satellite: obs validate covers serve-mode metrics files
    assert obs_main(["validate", str(metrics)]) == 0
    kinds = [
        json.loads(l)["kind"] for l in metrics.read_text().splitlines()
    ]
    assert kinds == ["run_start", "serve_load", "serve_stats", "serve_bench"]


def test_train_cli_export_artifact(toy_dataset, tmp_path):
    """--export-artifact on the training CLI: the trained model lands
    as a loadable serving artifact."""
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.train import main as train_main

    art = tmp_path / "cli_artifact"
    assert train_main([
        "--train", toy_dataset.train_prefix,
        "--test", toy_dataset.test_prefix,
        "--model", "lr", "--epochs", "1", "--batch-size", "64",
        "--table-size-log2", "14", "--max-nnz", "24",
        "--num-devices", "1", "--skip-eval",
        "--export-artifact", str(art),
    ]) == 0
    engine = PredictEngine.load(str(art), buckets=(8,), warm=True)
    assert engine.compile_count == 1
    assert engine.predict(engine._empty_batch(3)).shape == (3,)


def test_check_serve_smoke_script():
    """The CI lint (scripts/check_serve_smoke.py) passes — run as a
    subprocess exactly as CI would (tier-1 wiring, like
    check_metrics_schema.py)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "check_serve_smoke.py")],
        capture_output=True,
        text=True,
        timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout
