"""Serving subsystem (ISSUE 2): artifact export/load, PredictEngine
shape-bucketed compilation, micro-batching, hot swap, CLI, and the
train → export → serve parity guarantee."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax

from xflow_tpu.config import Config
from xflow_tpu.io.loader import ShardLoader
from xflow_tpu.trainer import Trainer


def _cfg(toy_dataset, **overrides):
    base = dict(
        train_path=toy_dataset.train_prefix,
        test_path=toy_dataset.test_prefix,
        model="lr",
        epochs=2,
        batch_size=64,
        table_size_log2=14,
        max_nnz=24,
        num_devices=1,
    )
    base.update(overrides)
    return Config(**base)


def _raw_batches(trainer, path):
    """Raw hash-key-space batches of the shard (no remap, no hot split)
    — what an external caller would build (io/batch.py)."""
    loader = ShardLoader(
        path,
        batch_size=trainer.cfg.batch_size,
        max_nnz=trainer.cfg.max_nnz,
        table_size=trainer.cfg.table_size,
        parse_fn=trainer._parse_fn(),
    )
    return [b for b, _ in loader.iter_batches()]


def _trainer_pctr(trainer, batch):
    """The pre-engine reference path: prepare + put + compiled predict."""
    return np.asarray(
        jax.device_get(
            trainer.step.predict(
                trainer.state,
                trainer.step.put_batch(trainer.prepare_batch(batch)),
            )
        )
    )


@pytest.fixture(scope="module")
def lr_served(toy_dataset, tmp_path_factory):
    """One trained lr model + its exported artifact, shared across
    tests (export is read-only from then on)."""
    from xflow_tpu.serve.artifact import export_artifact

    trainer = Trainer(_cfg(toy_dataset))
    trainer.train()
    art = str(tmp_path_factory.mktemp("serve") / "artifact")
    export_artifact(trainer, art)
    return {"trainer": trainer, "artifact": art}


def test_export_artifact_layout(lr_served):
    from xflow_tpu.serve.artifact import load_manifest

    art = lr_served["artifact"]
    manifest = load_manifest(art)
    assert manifest["model"] == "lr"
    assert manifest["config_digest"] == lr_served["trainer"].cfg.digest()
    assert "w.param" in manifest["arrays"]
    files = os.listdir(art)
    # params only: optimizer aux (FTRL n/z) never ships to serving
    assert not any(".n.r" in f or ".z.r" in f for f in files)
    assert any(f.startswith("w.param.r") for f in files)
    assert "remap.npy" not in files  # no hot table on this model


def test_engine_matches_trainer_and_eval_dump(lr_served, tmp_path):
    """Train → export → PredictEngine parity: engine pctr matches the
    trainer's compiled predict AND the evaluate() prediction dump to
    1e-6."""
    from xflow_tpu.serve.engine import PredictEngine

    trainer = lr_served["trainer"]
    engine = PredictEngine.load(
        lr_served["artifact"], buckets=(8, 64), warm=True
    )
    shard = trainer.cfg.test_path + "-00000"
    for batch in _raw_batches(trainer, shard):
        np.testing.assert_allclose(
            engine.predict(batch), _trainer_pctr(trainer, batch), atol=1e-6
        )
    # the evaluate() artifact (label\tpctr lines) as ground truth
    pred = tmp_path / "pred.txt"
    trainer.evaluate(pred_out=str(pred))
    want = np.asarray(
        [float(l.split("\t")[1]) for l in pred.read_text().splitlines()]
    )
    lines = open(shard).read().splitlines()
    got = engine.score_text(lines)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_engine_parity_hot_table(toy_dataset, tmp_path):
    """The hot-table remap folds into the artifact: an engine scoring
    RAW hash-space batches matches the trainer bit-for-bit though the
    table rows live in the permuted space."""
    from xflow_tpu.serve.artifact import export_artifact
    from xflow_tpu.serve.engine import PredictEngine

    trainer = Trainer(_cfg(
        toy_dataset, epochs=1,
        hot_size_log2=6, hot_nnz=8, freq_sample_mib=1,
    ))
    trainer.train()
    art = str(tmp_path / "hot_artifact")
    export_artifact(trainer, art)
    assert os.path.exists(os.path.join(art, "remap.npy"))
    engine = PredictEngine.load(art, buckets=(64,), warm=True)
    for batch in _raw_batches(trainer, trainer.cfg.test_path + "-00000"):
        np.testing.assert_allclose(
            engine.predict(batch), _trainer_pctr(trainer, batch), atol=1e-6
        )


def test_engine_needs_no_trainer_or_loader(lr_served, monkeypatch):
    """Acceptance: PredictEngine scores with ZERO Trainer/ShardLoader
    instantiation — both constructors are booby-trapped."""
    from xflow_tpu.serve.engine import PredictEngine

    def boom(*a, **kw):
        raise AssertionError("serving must not instantiate this")

    monkeypatch.setattr(Trainer, "__init__", boom)
    monkeypatch.setattr(ShardLoader, "__init__", boom)
    engine = PredictEngine.load(
        lr_served["artifact"], buckets=(8,), warm=True
    )
    rows = [np.asarray([3, 99, 2048]), np.asarray([7])]
    pctr = engine.predict(engine.featurize_raw(rows))
    assert pctr.shape == (2,)
    assert np.all((pctr > 0.0) & (pctr < 1.0))


def test_one_compile_per_bucket(lr_served):
    """Acceptance: exactly one compile per warmed bucket (the
    compile-count hook), and NO traffic mix adds more — arbitrary
    request sizes pad onto buckets, oversized batches chunk."""
    from xflow_tpu.serve.engine import PredictEngine

    engine = PredictEngine.load(
        lr_served["artifact"], buckets=(1, 8, 64), warm=True
    )
    assert engine.buckets == (1, 8, 64)
    assert engine.compile_count == 3
    rng = np.random.default_rng(0)
    table = engine.cfg.table_size
    for n in (1, 2, 3, 7, 8, 9, 40, 64, 65, 200):
        rows = [
            rng.integers(0, table, size=int(rng.integers(1, 10)))
            for _ in range(n)
        ]
        assert engine.predict(engine.featurize_raw(rows)).shape == (n,)
    assert engine.compile_count == 3, "a request size triggered a recompile"


def test_value_carrying_request_rejected_after_warm(lr_served):
    """Compact-wire invariants are validated on EVERY serving batch —
    warmup must not consume TrainStep's one-shot check and let a
    value-carrying request silently score with vals=1."""
    from xflow_tpu.serve.engine import PredictEngine

    engine = PredictEngine.load(
        lr_served["artifact"], buckets=(8,), warm=True
    )
    assert engine.step.compact_wire
    bad = (np.asarray([3, 5]), None, np.asarray([0.5, 2.0]))
    with pytest.raises(ValueError, match="compact wire"):
        engine.predict(engine.featurize_raw([bad]))


def test_engine_refuses_digest_mismatch(lr_served, tmp_path):
    from xflow_tpu.serve.artifact import MANIFEST
    from xflow_tpu.serve.engine import PredictEngine

    trainer = lr_served["trainer"]
    # caller expectation drifted from the exported config
    with pytest.raises(ValueError, match="refusing"):
        PredictEngine.load(
            lr_served["artifact"],
            config=trainer.cfg.replace(alpha=0.123),
            warm=False,
        )
    # matching expectation loads fine
    PredictEngine.load(
        lr_served["artifact"], config=trainer.cfg, buckets=(8,), warm=False
    )
    # tampered artifact: stored digest no longer matches embedded config
    import shutil

    bad = tmp_path / "tampered"
    shutil.copytree(lr_served["artifact"], bad)
    mpath = bad / MANIFEST
    manifest = json.loads(mpath.read_text())
    manifest["config_digest"] = "deadbeef0000"
    mpath.write_text(json.dumps(manifest))
    with pytest.raises(ValueError, match="corrupt or tampered"):
        PredictEngine.load(str(bad), warm=False)


def test_engine_multidevice_mesh(lr_served):
    """Artifact row-range shards assemble onto a different serving mesh
    (1-chip export → 8-device engine); buckets round up to
    mesh-divisible sizes and predictions are unchanged."""
    from xflow_tpu.serve.engine import PredictEngine

    e1 = PredictEngine.load(lr_served["artifact"], buckets=(8,), warm=False)
    e8 = PredictEngine.load(
        lr_served["artifact"], num_devices=8, buckets=(1, 8, 20), warm=False
    )
    assert e8.buckets == (8, 24)  # 1→8, 20→24 on the 8-device mesh
    rows = [np.asarray([5, 17, 4000]), np.asarray([9, 1]), np.asarray([2])]
    raw = e8.featurize_raw(rows)
    np.testing.assert_allclose(
        e8.predict(raw), e1.predict(raw), atol=1e-6
    )


def test_predict_batch_routes_through_buckets(toy_dataset):
    """Satellite: XFlow.predict_batch no longer recompiles per batch
    shape — distinct sizes share the engine's buckets, and the engine
    tracks the LIVE trainer state (scores reflect further training)."""
    from xflow_tpu.api import XFlow

    xf = XFlow(
        toy_dataset.train_prefix,
        toy_dataset.test_prefix,
        model="lr",
        epochs=1,
        batch_size=64,
        table_size_log2=14,
        max_nnz=24,
        num_devices=1,
    )
    xf.train()
    batches = _raw_batches(xf.trainer, xf.config.test_path + "-00000")
    full = batches[0]
    from xflow_tpu.serve.engine import _slice_rows

    for n in (1, 3, 17, 64):
        sub = _slice_rows(full, 0, n)
        np.testing.assert_allclose(
            xf.predict_batch(sub),
            _trainer_pctr(xf.trainer, sub),
            atol=1e-6,
        )
    engine = xf._engine
    compiles = engine.compile_count
    assert compiles <= len(engine.buckets)
    # more training; predict_batch must see the evolved weights with
    # no new compiles (same shapes/shardings through the AOT exes)
    before = xf.predict_batch(full)
    xf.trainer.train_epoch()
    after = xf.predict_batch(full)
    assert engine.compile_count == compiles
    assert not np.allclose(before, after)
    np.testing.assert_allclose(
        after, _trainer_pctr(xf.trainer, full), atol=1e-6
    )


def test_microbatcher_coalesces_and_accounts(lr_served, tmp_path):
    """Concurrent single-row submits coalesce into few device calls;
    values match direct engine scoring; the serve_stats row carries
    queue/featurize/device p50/p99 and passes the schema."""
    from xflow_tpu.obs.schema import load_jsonl, validate_rows
    from xflow_tpu.serve.batcher import MicroBatcher
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.utils.logging import MetricsLogger

    engine = PredictEngine.load(
        lr_served["artifact"], buckets=(8, 64), warm=True
    )
    out = tmp_path / "serve.jsonl"
    logger = MetricsLogger(out, run_header={
        "run_id": "t", "config_digest": engine.digest,
        "rank": 0, "num_hosts": 1,
    })
    rng = np.random.default_rng(1)
    rows = [
        rng.integers(0, engine.cfg.table_size, size=6) for _ in range(50)
    ]
    with MicroBatcher(
        engine, max_wait_ms=20.0, metrics_logger=logger
    ) as mb:
        futs = [mb.submit(r) for r in rows]
        got = np.asarray([f.result() for f in futs])
    stats = mb.close()  # idempotent: same final row, not re-logged
    assert mb.close() is stats
    with pytest.raises(RuntimeError, match="closed"):
        mb.submit(rows[0])
    logger.close()
    np.testing.assert_allclose(
        got, engine.predict(engine.featurize_raw(rows)), atol=1e-6
    )
    rows_jsonl = load_jsonl(str(out))
    assert validate_rows(rows_jsonl) == []
    srows = [r for r in rows_jsonl if r["kind"] == "serve_stats"]
    assert len(srows) == 1  # double close logs exactly one stats row
    srow = srows[0]
    assert srow["requests"] == 50
    assert 0 < srow["batches"] < 50  # coalescing happened
    for f in ("queue_p99", "featurize_p99", "device_p99"):
        assert srow[f] > 0.0
    assert srow["queue_p50"] <= srow["queue_p99"]
    assert stats["requests"] == srow["requests"]


def test_serve_watchdog_flags_backed_up_batcher(lr_served, tmp_path):
    """ISSUE 4: the serving tier heartbeats the flight recorder (engine
    per device call, batcher per coalesced batch), and a watchdog wired
    to ``batcher.pending`` classifies silence-with-backlog as
    serve_queue_stall — while a drained batcher's silence stays
    healthy."""
    import time

    from xflow_tpu.obs import Obs
    from xflow_tpu.obs.flight import FlightRecorder
    from xflow_tpu.obs.schema import load_jsonl, validate_rows
    from xflow_tpu.obs.watchdog import Watchdog
    from xflow_tpu.serve.batcher import MicroBatcher
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.utils.logging import MetricsLogger

    fl = FlightRecorder()
    engine = PredictEngine.load(
        lr_served["artifact"], buckets=(8,), warm=True, obs=Obs(flight=fl)
    )
    orig = engine.predict_prepared
    engine.predict_prepared = lambda b: (time.sleep(0.7), orig(b))[1]
    out = tmp_path / "serve.jsonl"
    logger = MetricsLogger(out)
    rng = np.random.default_rng(2)
    rows = [
        rng.integers(0, engine.cfg.table_size, size=6) for _ in range(3)
    ]
    with MicroBatcher(
        engine, max_wait_ms=0.0, max_batch=1, flight=fl
    ) as mb:
        wd = Watchdog(
            fl, input_s=60.0, device_s=60.0, serve_s=0.2,
            metrics_logger=logger,
        )
        wd.set_pending("serve", mb.pending)
        with wd:  # real monitor thread (poll = serve_s / 4)
            futs = [mb.submit(r) for r in rows]
            got = [f.result() for f in futs]
            # backlog existed: batch 2/3 queued behind the slowed
            # device call after batch 1's heartbeat — a trip fired
            assert wd.trip_count >= 1
            # drained now: silence with pending() False never trips
            before = wd.trip_count
            time.sleep(0.5)
            assert wd.trip_count == before
            assert not mb.pending()
    logger.close()
    assert len(got) == 3
    jrows = load_jsonl(str(out))
    assert validate_rows(jrows) == []
    causes = {r["cause"] for r in jrows if r["kind"] == "health"}
    assert "serve_queue_stall" in causes
    # the flight record saw both serve-side heartbeat sources; the
    # engine's beat names the bucket the call ran in
    details = {e["detail"] for e in fl.snapshot()["events"]}
    assert "batch" in details
    assert "execute:b8" in details  # bucket choice recorded


def test_microbatcher_hot_swap(toy_dataset, tmp_path):
    """Atomic mid-serve artifact swap: later requests score on the new
    weights, and a swap to a DIFFERENT config digest is refused."""
    from xflow_tpu.serve.artifact import export_artifact
    from xflow_tpu.serve.batcher import MicroBatcher
    from xflow_tpu.serve.engine import PredictEngine

    trainer = Trainer(_cfg(toy_dataset, epochs=1))
    trainer.train()
    art_a = str(tmp_path / "a")
    export_artifact(trainer, art_a)
    trainer.train_epoch()  # evolve the weights
    art_b = str(tmp_path / "b")
    export_artifact(trainer, art_b)

    ea = PredictEngine.load(art_a, buckets=(8,), warm=True)
    eb = PredictEngine.load(art_b, buckets=(8,), warm=True)
    # a row of keys the model actually trained on (arbitrary ids would
    # hit untouched zero rows: pctr 0.5 on both engines)
    first = _raw_batches(trainer, trainer.cfg.test_path + "-00000")[0]
    row = first.keys[0][first.mask[0] > 0]
    mb = MicroBatcher(ea, max_wait_ms=0.0)
    try:
        pa = mb.score(row)
        mb.swap(eb)
        pb = mb.score(row)
        assert pa == pytest.approx(float(ea.predict(ea.featurize_raw([row]))[0]))
        assert pb == pytest.approx(float(eb.predict(eb.featurize_raw([row]))[0]))
        assert pa != pb
        other = Trainer(_cfg(toy_dataset, epochs=1, alpha=0.9))
        art_c = str(tmp_path / "c")
        export_artifact(other, art_c)
        ec = PredictEngine.load(art_c, buckets=(8,), warm=False)
        with pytest.raises(ValueError, match="hot-swap refused"):
            mb.swap(ec)
        mb.swap(ec, force=True)  # explicit override works
    finally:
        stats = mb.close()
    assert stats["swaps"] == 2


def test_serve_cli_score_and_bench(lr_served, tmp_path, capsys):
    from xflow_tpu.obs.__main__ import main as obs_main
    from xflow_tpu.serve.__main__ import main as serve_main

    shard = lr_served["trainer"].cfg.test_path + "-00000"
    out = tmp_path / "scores.txt"
    assert serve_main([
        "score", lr_served["artifact"],
        "--input", shard, "--out", str(out), "--buckets", "8,64",
    ]) == 0
    scores = [float(l) for l in out.read_text().splitlines()]
    assert len(scores) == len(open(shard).read().splitlines())
    assert all(0.0 < s < 1.0 for s in scores)

    metrics = tmp_path / "bench.jsonl"
    assert serve_main([
        "bench", lr_served["artifact"],
        "--requests", "32", "--concurrency", "4",
        "--buckets", "8,64", "--max-wait-ms", "1",
        "--metrics-out", str(metrics),
    ]) == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    for f in (
        "e2e_p50", "e2e_p99", "queue_p99", "featurize_p99",
        "device_p99", "requests_per_sec",
    ):
        assert f in summary
    assert summary["requests"] == 32
    assert summary["compiles"] == 2
    # satellite: obs validate covers serve-mode metrics files
    assert obs_main(["validate", str(metrics)]) == 0
    kinds = [
        json.loads(l)["kind"] for l in metrics.read_text().splitlines()
    ]
    assert kinds == ["run_start", "serve_load", "serve_stats", "serve_bench"]


def test_train_cli_export_artifact(toy_dataset, tmp_path):
    """--export-artifact on the training CLI: the trained model lands
    as a loadable serving artifact."""
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.train import main as train_main

    art = tmp_path / "cli_artifact"
    assert train_main([
        "--train", toy_dataset.train_prefix,
        "--test", toy_dataset.test_prefix,
        "--model", "lr", "--epochs", "1", "--batch-size", "64",
        "--table-size-log2", "14", "--max-nnz", "24",
        "--num-devices", "1", "--skip-eval",
        "--export-artifact", str(art),
    ]) == 0
    engine = PredictEngine.load(str(art), buckets=(8,), warm=True)
    assert engine.compile_count == 1
    assert engine.predict(engine._empty_batch(3)).shape == (3,)


def test_check_serve_smoke_script():
    """The CI lint (scripts/check_serve_smoke.py) passes — run as a
    subprocess exactly as CI would (tier-1 wiring, like
    check_metrics_schema.py)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "check_serve_smoke.py")],
        capture_output=True,
        text=True,
        timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout


# -- production tier (ISSUE 10): fleet, admission, rollout, HTTP, SLO -------


def _http_json(url, doc=None, method=None, timeout=30.0):
    """(status, parsed body) for a JSON request — 4xx/5xx included."""
    import urllib.error
    import urllib.request

    data = json.dumps(doc).encode() if doc is not None else None
    req = urllib.request.Request(
        url, data=data, method=method or ("POST" if doc is not None else "GET"),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode() or "{}"), dict(e.headers)


def _slowed(engine, delay_s):
    """Wrap the engine's device call in a sleep — the injected-latency
    regression used by admission/backpressure/SLO tests."""
    import time as _time

    orig = engine.predict_prepared
    engine.predict_prepared = lambda b: (_time.sleep(delay_s), orig(b))[1]
    return engine


def test_fleet_replicas_share_weights_and_compiles(lr_served):
    """ReplicaFleet fans ONE loaded artifact out to N clones: shared
    state dict, shared AOT executables (one compile set fleet-wide),
    per-replica batchers; routed scores match direct engine predict."""
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.serve.fleet import ReplicaFleet

    engine = PredictEngine.load(lr_served["artifact"], buckets=(8,), warm=True)
    fleet = ReplicaFleet(engine, replicas=3, max_wait_ms=1.0)
    try:
        assert fleet.replicas == 3
        assert fleet.engines[1].state is fleet.engines[0].state
        assert fleet.engines[2]._compiled is fleet.engines[0]._compiled
        assert fleet.engines[1].compile_count == 1  # shared, not 3x
        rng = np.random.default_rng(3)
        rows = [
            rng.integers(0, engine.cfg.table_size, size=6)
            for _ in range(30)
        ]
        futs = [fleet.submit(r) for r in rows]
        got = np.asarray([f.result(timeout=60) for f in futs])
        np.testing.assert_allclose(
            got, engine.predict(engine.featurize_raw(rows)), atol=1e-6
        )
        live = fleet.stats()
        assert live["replicas"] == 3
        assert live["shed"]["admitted"] == 30
        assert live["stats"]["requests"] == 30
        assert live["rollout"] is None
    finally:
        final = fleet.close()
    assert fleet.close() == final  # idempotent, same final rows
    with pytest.raises(RuntimeError, match="closed"):
        fleet.submit(rows[0])


def test_fleet_admission_sheds_typed_and_counts(lr_served, tmp_path):
    """Admission control: a backlog past the depth/deadline budget
    rejects with a TYPED ShedError (cause queue_depth/queue_age),
    counted per cause in the serve_shed row; admitted requests all
    still score."""
    from xflow_tpu.obs.schema import load_jsonl, validate_rows
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.serve.fleet import ReplicaFleet, ShedError
    from xflow_tpu.utils.logging import MetricsLogger

    engine = _slowed(
        PredictEngine.load(lr_served["artifact"], buckets=(8,), warm=True),
        0.2,
    )
    out = tmp_path / "shed.jsonl"
    logger = MetricsLogger(out, run_header={
        "run_id": "t", "config_digest": engine.digest,
        "rank": 0, "num_hosts": 1,
    })
    fleet = ReplicaFleet(
        engine, replicas=1, max_wait_ms=0.0,
        deadline_budget_ms=10.0, depth_budget=2,
        metrics_logger=logger,
    )
    rng = np.random.default_rng(4)
    row = rng.integers(0, engine.cfg.table_size, size=5)
    futs, sheds = [], []
    for _ in range(20):
        try:
            futs.append(fleet.submit(row))
        except ShedError as e:
            sheds.append(e)
    assert sheds, "a 0.2s device call never backed the queue up?"
    assert {e.cause for e in sheds} <= {"queue_depth", "queue_age"}
    assert all(e.depth >= 0 and e.queue_age_s >= 0 for e in sheds)
    got = [f.result(timeout=60) for f in futs]  # admitted all score
    assert len(got) == len(futs)
    final = fleet.close()
    logger.close()
    shed_row = final["shed"]
    assert shed_row["shed_total"] == len(sheds)
    assert sum(shed_row["by_cause"].values()) == len(sheds)
    assert shed_row["admitted"] == len(futs)
    assert final["stats"]["shed_total"] == len(sheds)  # satellite: stats()
    rows_jsonl = load_jsonl(str(out))
    assert validate_rows(rows_jsonl) == []
    kinds = [r["kind"] for r in rows_jsonl]
    assert "serve_shed" in kinds and "serve_stats" in kinds


def test_rollout_mid_traffic_never_mixes_artifacts(toy_dataset, tmp_path):
    """Tentpole acceptance: a staged rollout under concurrent live
    traffic never mixes two artifacts in one coalesced batch — every
    scored value matches exactly artifact A or artifact B, the stream
    converges on B after commit, and zero requests fail."""
    from xflow_tpu.obs.schema import load_jsonl, validate_rows
    from xflow_tpu.serve.artifact import export_artifact
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.serve.fleet import ReplicaFleet
    from xflow_tpu.utils.logging import MetricsLogger

    trainer = Trainer(_cfg(toy_dataset, epochs=1))
    trainer.train()
    art_a = str(tmp_path / "a")
    export_artifact(trainer, art_a)
    trainer.train_epoch()
    art_b = str(tmp_path / "b")
    export_artifact(trainer, art_b)

    ea = PredictEngine.load(art_a, buckets=(8,), warm=True)
    eb = PredictEngine.load(art_b, buckets=(8,), warm=True)
    first = _raw_batches(trainer, trainer.cfg.test_path + "-00000")[0]
    row = first.keys[0][first.mask[0] > 0]  # trained keys: pa != pb
    pa = float(ea.predict(ea.featurize_raw([row]))[0])
    pb = float(eb.predict(eb.featurize_raw([row]))[0])
    assert pa != pb

    out = tmp_path / "rollout.jsonl"
    logger = MetricsLogger(out, run_header={
        "run_id": "t", "config_digest": ea.digest,
        "rank": 0, "num_hosts": 1,
    })
    fleet = ReplicaFleet(
        ea, replicas=2, max_wait_ms=1.0, metrics_logger=logger
    )
    results: list[float] = []
    failures: list[BaseException] = []
    stop = threading.Event()

    def traffic():
        while not stop.is_set():
            try:
                results.append(fleet.score(row, timeout=60))
            except BaseException as e:  # noqa: BLE001 - recorded, asserted
                failures.append(e)
                return

    threads = [threading.Thread(target=traffic) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        state = fleet.begin_rollout(
            eb, canary_frac=0.5, min_canary_requests=8
        )
        assert state["canary_requests"] == 0
        deadline = time.perf_counter() + 30
        while time.perf_counter() < deadline:
            state = fleet.rollout_state()
            if state["healthy"]:
                break
            time.sleep(0.01)
        assert state["healthy"], f"canary never reached the gate: {state}"
        fleet.emit_stats()  # flushes the open-rollout 'canary' heartbeat
        health = fleet.commit_rollout()
        assert health["canary_errors"] == 0
        assert fleet.rollout_state() is None
        assert fleet.digest == eb.digest
        n_at_commit = len(results)
        while len(results) < n_at_commit + 8 and not failures:
            time.sleep(0.01)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        fleet.close()
        logger.close()
    assert not failures, failures
    # every value is EXACTLY one artifact's score — never a blend of
    # two engines inside one coalesced batch
    for p in results:
        assert p == pytest.approx(pa, abs=1e-6) or p == pytest.approx(
            pb, abs=1e-6
        ), f"scored {p}, which is neither artifact a ({pa}) nor b ({pb})"
    assert results[-1] == pytest.approx(pb, abs=1e-6)  # converged on B
    rows_jsonl = load_jsonl(str(out))
    assert validate_rows(rows_jsonl) == []
    events = [r["event"] for r in rows_jsonl if r["kind"] == "rollout"]
    assert events == ["begin", "canary", "commit"]


def test_rollout_digest_guard_and_health_gate(lr_served, toy_dataset, tmp_path):
    """Rollout discipline: a digest-mismatched candidate is refused
    BEFORE any traffic shifts; commit is refused until the canary
    health gate passes; abort restores the incumbent; double-open is
    an error."""
    from xflow_tpu.serve.artifact import export_artifact
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.serve.fleet import ReplicaFleet, RolloutError

    fleet = ReplicaFleet.load(lr_served["artifact"], replicas=2, buckets=(8,))
    try:
        other = Trainer(_cfg(toy_dataset, epochs=1, alpha=0.9))
        art_c = str(tmp_path / "c")
        export_artifact(other, art_c)
        ec = PredictEngine.load(art_c, buckets=(8,), warm=False)
        with pytest.raises(ValueError, match="redeploy"):
            fleet.begin_rollout(ec)
        assert fleet.rollout_state() is None  # no traffic ever shifted
        # same-digest artifact path (str → _load_candidate loads it)
        fleet.begin_rollout(
            lr_served["artifact"], canary_frac=0.25, min_canary_requests=5
        )
        with pytest.raises(RolloutError, match="already open"):
            fleet.begin_rollout(lr_served["artifact"])
        with pytest.raises(RolloutError, match="not healthy"):
            fleet.commit_rollout()  # 0 canary requests < gate
        health = fleet.abort_rollout(detail="test")
        assert health["canary_requests"] == 0
        assert fleet.rollout_state() is None
        with pytest.raises(RolloutError, match="no rollout open"):
            fleet.abort_rollout()
    finally:
        fleet.close()


def test_http_tier_endpoints_and_graceful_close(lr_served):
    """The HTTP front end: healthz/stats/score (JSON + packed wire)
    against a live 2-replica fleet; scores match direct engine
    predict; close() drains and is idempotent; the accept loop beats
    the flight recorder's http channel."""
    from xflow_tpu.obs.flight import FlightRecorder
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.serve.fleet import ReplicaFleet
    from xflow_tpu.serve.server import (
        ServeTier,
        decode_packed_response,
        encode_packed_request,
    )

    engine = PredictEngine.load(lr_served["artifact"], buckets=(8,), warm=True)
    fl = FlightRecorder()
    fleet = ReplicaFleet(engine, replicas=2, max_wait_ms=1.0, flight=fl)
    tier = ServeTier(fleet, port=0, flight=fl, poll_s=0.05).start()
    try:
        assert tier.running
        status, health, _ = _http_json(tier.address + "/healthz")
        assert status == 200
        assert health["status"] == "serving"
        assert health["digest"] == engine.digest
        assert health["replicas"] == 2

        rng = np.random.default_rng(5)
        rows = [
            rng.integers(0, engine.cfg.table_size, size=4) for _ in range(5)
        ]
        want = engine.predict(engine.featurize_raw(rows))
        status, doc, _ = _http_json(tier.address + "/v1/score", {
            "rows": [{"keys": [int(k) for k in r]} for r in rows],
        })
        assert status == 200
        np.testing.assert_allclose(doc["pctr"], want, atol=1e-5)
        assert doc["digest"] == engine.digest

        # packed-binary wire, same scoring path
        import urllib.request

        req = urllib.request.Request(
            tier.address + "/v1/score_packed",
            data=encode_packed_request([(r, None, None) for r in rows]),
            headers={"Content-Type": "application/octet-stream"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            packed = decode_packed_response(r.read())
        np.testing.assert_allclose(packed, want, atol=1e-6)

        status, stats, _ = _http_json(tier.address + "/v1/stats")
        assert status == 200
        assert stats["shed"]["admitted"] == 10  # 5 JSON + 5 packed
        status, _, _ = _http_json(tier.address + "/nope")
        assert status == 404
        status, err, _ = _http_json(tier.address + "/v1/score", {"bad": 1})
        assert status == 400
        # the accept loop heartbeats the http channel every poll
        assert fl.beat_age("http") is not None
    finally:
        final = tier.close()
    assert not tier.running
    assert tier.close() == final  # idempotent
    # stats() is non-destructive, so the final close-time flush still
    # owns the whole window
    assert final["shed"]["admitted"] == 10


def test_http_backpressure_typed_429(lr_served):
    """An admission-control shed surfaces as HTTP 429 with the typed
    cause + Retry-After header, while admitted requests still answer
    200 — clients can tell 'slow down' from 'broken'."""
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.serve.fleet import ReplicaFleet
    from xflow_tpu.serve.server import ServeTier

    engine = _slowed(
        PredictEngine.load(lr_served["artifact"], buckets=(8,), warm=True),
        0.3,
    )
    fleet = ReplicaFleet(
        engine, replicas=1, max_wait_ms=0.0,
        deadline_budget_ms=15.0, depth_budget=1,
    )
    tier = ServeTier(fleet, port=0, poll_s=0.05).start()
    statuses: list[tuple[int, dict, dict]] = []
    lock = threading.Lock()

    def hit():
        out = _http_json(
            tier.address + "/v1/score", {"keys": [1, 2, 3]}, timeout=60
        )
        with lock:
            statuses.append(out)

    try:
        threads = [threading.Thread(target=hit) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
    finally:
        tier.close()
    codes = sorted(s for s, _, _ in statuses)
    assert 200 in codes and 429 in codes, codes
    shed = next(doc for s, doc, _ in statuses if s == 429)
    assert shed["error"] == "backpressure"
    assert shed["cause"] in ("queue_depth", "queue_age")
    assert shed["retry_after_ms"] >= 1
    hdrs = next(h for s, _, h in statuses if s == 429)
    assert "Retry-After" in hdrs


def test_loadgen_slo_gate_healthy_and_regressed(lr_served, tmp_path):
    """Satellite (CI wiring): a healthy open-loop zipf loadgen run
    passes scripts/check_serve_slo.py; an injected latency regression
    (slow device + tight deadline budget → shed storm, fat p99) exits
    non-zero.  The serve_bench row and fleet windows all validate."""
    from xflow_tpu.obs.schema import load_jsonl, validate_rows
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.serve.fleet import ReplicaFleet
    from xflow_tpu.serve.loadgen import run_loadgen
    from xflow_tpu.utils.logging import MetricsLogger

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    gate = os.path.join(repo, "scripts", "check_serve_slo.py")

    def run(metrics_path, delay_s, deadline_ms, qps):
        engine = PredictEngine.load(
            lr_served["artifact"], buckets=(8, 64), warm=True
        )
        if delay_s:
            _slowed(engine, delay_s)
        logger = MetricsLogger(metrics_path, run_header={
            "run_id": "t", "config_digest": engine.digest,
            "rank": 0, "num_hosts": 1,
        })
        fleet = ReplicaFleet(
            engine, replicas=2, max_wait_ms=1.0,
            deadline_budget_ms=deadline_ms, metrics_logger=logger,
        )
        try:
            summary = run_loadgen(
                fleet, offered_qps=qps, duration_s=1.2, concurrency=4,
                nnz=6, seed=2, drain_timeout_s=30.0,
                metrics_logger=logger,
            )
        finally:
            fleet.close()
            logger.close()
        assert validate_rows(load_jsonl(str(metrics_path))) == []
        return summary

    healthy = tmp_path / "healthy.jsonl"
    summary = run(healthy, delay_s=0.0, deadline_ms=200.0, qps=100)
    assert summary["errors"] == 0
    assert summary["outstanding"] == 0
    assert summary["per_bucket"], "per-bucket e2e percentiles missing"
    assert summary["compiles"] == 2  # fleet-wide, shared executables
    proc = subprocess.run(
        [sys.executable, gate, str(healthy), "--max-shed-frac", "0.3"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout

    regressed = tmp_path / "regressed.jsonl"
    summary = run(regressed, delay_s=0.15, deadline_ms=20.0, qps=100)
    assert summary["shed_frac"] > 0.3, summary  # the storm happened
    proc = subprocess.run(
        [
            sys.executable, gate, str(regressed),
            "--max-shed-frac", "0.3", "--max-p99-ms", "100",
        ],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "FAIL" in proc.stdout + proc.stderr

    # a black-holed request (admitted, never resolved before the drain
    # timeout) is neither an error nor a shed — the outstanding gate
    # must refuse it by default
    rows = [json.loads(l) for l in open(healthy) if l.strip()]
    bench = next(r for r in rows if r.get("kind") == "serve_bench")
    bench["outstanding"] = 7
    blackhole = tmp_path / "blackhole.jsonl"
    blackhole.write_text(
        "\n".join(json.dumps(r) for r in rows) + "\n"
    )
    proc = subprocess.run(
        [sys.executable, gate, str(blackhole), "--max-shed-frac", "0.3"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "outstanding" in proc.stdout

    # a file with no serve_bench rows is a usage error, not a pass
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    proc = subprocess.run(
        [sys.executable, gate, str(empty)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 2

    # a closed-loop `bench` row (no offered_qps_actual) must be
    # refused as usage error, not gate-pass vacuously on defaults
    for r in rows:
        r.pop("offered_qps_actual", None)
    benchonly = tmp_path / "benchonly.jsonl"
    benchonly.write_text(
        "\n".join(json.dumps(r) for r in rows) + "\n"
    )
    proc = subprocess.run(
        [sys.executable, gate, str(benchonly)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "offered_qps_actual" in proc.stderr


def test_loadgen_failed_stripe_is_not_a_clean_run(lr_served, monkeypatch):
    """A worker whose row pre-generation dies must book its arrivals as
    failed requests (error_frac fails the gate), not silently vanish
    and leave a gate-passing summary over traffic never sent."""
    from xflow_tpu.serve import loadgen as lg
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.serve.fleet import ReplicaFleet

    with pytest.raises(ValueError, match="zipf_a"):
        lg.run_loadgen(object(), offered_qps=10, duration_s=1,
                       zipf_a=1.0, table_size=64)

    real = lg.zipf_rows
    calls = []

    def flaky(rng, n, **kw):
        calls.append(n)
        if len(calls) == 1:  # first stripe to generate dies
            raise MemoryError("synthetic generation failure")
        return real(rng, n, **kw)

    monkeypatch.setattr(lg, "zipf_rows", flaky)
    engine = PredictEngine.load(
        lr_served["artifact"], buckets=(8, 64), warm=True
    )
    fleet = ReplicaFleet(engine, replicas=1)
    try:
        summary = lg.run_loadgen(
            fleet, offered_qps=40, duration_s=0.5, concurrency=4,
            nnz=6, seed=3,
        )
    finally:
        fleet.close()
    # the dead stripe's share of offered traffic is booked as errors
    assert summary["errors"] >= 4, summary
    assert summary["outstanding"] == 0, summary
    assert summary["requests"] + summary["errors"] >= 20, summary


def test_http_target_honors_retry_after(monkeypatch):
    """ISSUE 11 satellite: a typed 429 is RETRIED after honoring
    Retry-After (capped exponential backoff) instead of booking an
    immediate shed — chaos runs measure recovery, not just rejection.
    Exhausted retries still surface as the typed ShedError."""
    import numpy as np

    from xflow_tpu.serve.fleet import ShedError
    from xflow_tpu.serve.loadgen import HttpTarget
    from xflow_tpu.serve.server import encode_packed_response

    shed_body = json.dumps({
        "error": "backpressure", "cause": "queue_depth",
        "depth": 9, "queue_age_ms": 1.0, "retry_after_ms": 5,
    }).encode()
    ok_body = encode_packed_response(np.asarray([0.25], np.float32))

    target = HttpTarget("http://127.0.0.1:1", max_retries=2)
    responses = [(429, shed_body, "0.001"), (429, shed_body, "0.001"),
                 (200, ok_body, "")]
    posts = []
    monkeypatch.setattr(
        target, "_post",
        lambda path, body, headers=None: (
            posts.append(path) or responses[len(posts) - 1]
        ),
    )
    fut = target.submit(np.asarray([1, 2, 3]))
    assert fut.result(0) == pytest.approx(0.25)
    assert len(posts) == 3  # two 429s retried, third attempt scored
    assert target.retried == 2

    # all-429: retries exhaust into the typed shed, counted per retry
    target2 = HttpTarget("http://127.0.0.1:1", max_retries=1)
    monkeypatch.setattr(
        target2, "_post",
        lambda path, body, headers=None: (429, shed_body, "0.001"),
    )
    with pytest.raises(ShedError) as ei:
        target2.submit(np.asarray([1]))
    assert ei.value.cause == "queue_depth"
    assert target2.retried == 1

    # the summary carries the retried count (serve_bench optional field)
    from xflow_tpu.obs.schema import OPTIONAL

    assert "retried" in OPTIONAL["serve_bench"]


def test_watchdog_http_channel_accept_stall():
    """The watchdog classifies http-channel silence (a wedged accept
    loop) as serve_accept_stall — independently of the serve channel —
    and only while the tier's pending probe says it should be alive."""
    import time as _time

    from xflow_tpu.obs.flight import FlightRecorder
    from xflow_tpu.obs.watchdog import Watchdog

    fl = FlightRecorder()
    wd = Watchdog(fl, input_s=60.0, device_s=60.0, serve_s=0.05)
    alive = {"running": True}
    wd.set_pending("http", lambda: alive["running"])
    fl.note_http("accept")
    _time.sleep(0.1)
    rows = wd.check()
    assert [r["cause"] for r in rows] == ["serve_accept_stall"]
    assert rows[0]["channel"] == "http"
    # a fresh beat recovers the incident with the stall duration
    fl.note_http("accept")
    rows = wd.check()
    assert [r["cause"] for r in rows] == ["recovered:serve_accept_stall"]
    # after close() the probe goes False: silence is a stopped server
    alive["running"] = False
    _time.sleep(0.1)
    assert wd.check() == []


def test_serve_cli_sigterm_graceful_drain(lr_served, tmp_path):
    """Satellite: `python -m xflow_tpu.serve serve` comes up, serves
    scoring traffic over HTTP, and drains gracefully on SIGTERM
    through the tier/fleet close() path — exit 0, final stats rows
    flushed and schema-valid."""
    import signal
    import urllib.request

    from xflow_tpu.obs.schema import load_jsonl, validate_rows

    metrics = tmp_path / "serve.jsonl"
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "xflow_tpu.serve", "serve",
            lr_served["artifact"], "--port", "0", "--replicas", "2",
            "--buckets", "8", "--canary-frac", "0.2",
            "--stats-every-s", "0.5", "--metrics-out", str(metrics),
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    try:
        line = proc.stdout.readline()
        hello = json.loads(line)
        assert hello["replicas"] == 2
        url = hello["serving"]
        with urllib.request.urlopen(url + "/healthz", timeout=30) as r:
            assert json.loads(r.read())["status"] == "serving"
        req = urllib.request.Request(
            url + "/v1/score",
            data=json.dumps({"keys": [3, 99, 2048]}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            pctr = json.loads(r.read())["pctr"]
        assert len(pctr) == 1 and 0.0 < pctr[0] < 1.0
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=120)
        assert proc.returncode == 0, out + err
        assert "drained" in out.splitlines()[-1]
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate(timeout=30)
    rows_jsonl = load_jsonl(str(metrics))
    assert validate_rows(rows_jsonl) == []
    kinds = [r["kind"] for r in rows_jsonl]
    assert "serve_load" in kinds
    assert "serve_stats" in kinds and "serve_shed" in kinds


def test_forced_redeploy_rollout_commits_and_stripes(
    lr_served, toy_dataset, tmp_path
):
    """A forced begin (different config digest — a redeploy) carries
    its force through commit: the non-canary replicas still run the
    OLD digest at commit time, so an unforced commit-side swap would
    raise mid-fleet (and, on the auto-commit path, unwind the accept
    loop).  Also pins interleaved canary striping: at canary_frac=0.5
    the canary sees every OTHER request, not a contiguous burst."""
    from xflow_tpu.serve.artifact import export_artifact
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.serve.fleet import ReplicaFleet

    fleet = ReplicaFleet.load(lr_served["artifact"], replicas=2, buckets=(8,))
    try:
        other = Trainer(_cfg(toy_dataset, epochs=1, alpha=0.9))
        art_c = str(tmp_path / "c")
        export_artifact(other, art_c)
        ec = PredictEngine.load(art_c, buckets=(8,), warm=False)
        assert ec.digest != fleet.digest
        fleet.begin_rollout(
            ec, canary_frac=0.5, min_canary_requests=4, force=True
        )
        for _ in range(8):
            fleet.score([3, 99, 2048])
        deadline = time.perf_counter() + 10
        while time.perf_counter() < deadline:
            n = fleet.rollout_state()["canary_requests"]
            if n >= 4:
                break
            time.sleep(0.01)
        # Bresenham striping: exactly every 2nd of 8 requests
        assert n == 4, n
        health = fleet.commit_rollout()  # no force arg: ro carries it
        assert health["canary_errors"] == 0
        assert fleet.digest == ec.digest
        for e in fleet.engines:
            assert e.digest == ec.digest
    finally:
        fleet.close()


def test_tier_close_without_start_is_bounded(lr_served):
    """close() on a tier whose accept loop never started must not
    block on the serve_forever shutdown handshake (the is-shut-down
    event only ever sets inside serve_forever) — the cleanup path for
    an exception between construction and start()."""
    from xflow_tpu.serve.fleet import ReplicaFleet
    from xflow_tpu.serve.server import ServeTier

    fleet = ReplicaFleet.load(lr_served["artifact"], replicas=1, buckets=(8,))
    tier = ServeTier(fleet, port=0)
    done: list[dict] = []
    t = threading.Thread(target=lambda: done.append(tier.close()))
    t.start()
    t.join(timeout=20)
    assert not t.is_alive(), "close() hung on a never-started tier"
    assert "shed" in done[0]
    with pytest.raises(RuntimeError, match="closed"):
        tier.start()


def test_http_malformed_score_bodies_are_400(lr_served):
    """Client-shaped garbage is a 400, not a 500: a JSON array body,
    non-object rows, and non-JSON all name the problem instead of
    surfacing an internal TypeError."""
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.serve.fleet import ReplicaFleet
    from xflow_tpu.serve.server import ServeTier

    engine = PredictEngine.load(lr_served["artifact"], buckets=(8,), warm=False)
    fleet = ReplicaFleet(engine, replicas=1)
    tier = ServeTier(fleet, port=0, poll_s=0.05).start()
    try:
        url = tier.address + "/v1/score"
        code, doc, _ = _http_json(url, [{"keys": [1, 2]}])
        assert code == 400 and "JSON object" in doc["error"]
        code, doc, _ = _http_json(url, {"rows": [[1, 2]]})
        assert code == 400 and "row" in doc["error"]
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            url, data=b"not json",
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                code = r.status
        except urllib.error.HTTPError as e:
            code = e.code
        assert code == 400
        # the tier still serves after the garbage
        code, doc, _ = _http_json(url, {"keys": [3, 99]})
        assert code == 200 and len(doc["pctr"]) == 1
    finally:
        tier.close()


def test_http_packed_wire_fuzz_corpus_is_400(lr_served):
    """Fuzz-regression corpus (analysis/wirefuzz.py mutation classes)
    pinned over LIVE HTTP: truncated XFS2 trace header, XFS1<->XFS2
    magic confusion, inflated nnz/row counts, unknown magic, and
    trailing bytes each answer a typed 400 — never a 500, never a
    hang — and the tier keeps serving afterwards."""
    import struct as _struct
    import urllib.error
    import urllib.request

    from xflow_tpu.obs.reqtrace import TraceContext
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.serve.fleet import ReplicaFleet
    from xflow_tpu.serve.server import (
        PACKED_MAGIC,
        PACKED_TRACE_MAGIC,
        ServeTier,
        encode_packed_request,
    )

    rows = [(np.asarray([3, 99], np.int64), None, None)]
    plain = encode_packed_request(rows)
    traced = encode_packed_request(
        rows, trace=TraceContext(0x1234_5678_9ABC_DEF0, 17, True)
    )
    corpus = {
        # XFS2 magic but the 17-byte trace triple is cut short
        "truncated_trace_header": PACKED_TRACE_MAGIC + traced[4:12],
        # traced body presented as XFS1: the trace triple's low u32
        # (0x9ABCDEF0) is read as an absurd nrows -> typed truncation
        "magic_confusion_xfs2_as_xfs1": PACKED_MAGIC + traced[4:],
        # untraced body presented as XFS2: row bytes parse as a trace
        # triple + garbage counts
        "magic_confusion_xfs1_as_xfs2": PACKED_TRACE_MAGIC + plain[4:],
        # row header claims 0xFFFF nnz with 8 payload bytes behind it
        "oversized_nnz": (
            PACKED_MAGIC + _struct.pack("<I", 1)
            + _struct.pack("<H", 0xFFFF) + b"\x00" * 8
        ),
        # nrows inflated past the single row actually shipped
        "oversized_nrows": (
            PACKED_MAGIC + _struct.pack("<I", 1 << 20) + plain[8:]
        ),
        "unknown_magic": b"XFQ9" + plain[4:],
        "trailing_bytes": plain + b"\x00",
        "empty_body": b"",
    }

    engine = PredictEngine.load(lr_served["artifact"], buckets=(8,), warm=False)
    fleet = ReplicaFleet(engine, replicas=1)
    tier = ServeTier(fleet, port=0, poll_s=0.05).start()
    try:
        url = tier.address + "/v1/score_packed"
        for name, blob in corpus.items():
            req = urllib.request.Request(
                url, data=blob,
                headers={"Content-Type": "application/octet-stream"},
            )
            try:
                with urllib.request.urlopen(req, timeout=30) as r:
                    code, body = r.status, r.read()
            except urllib.error.HTTPError as e:
                code, body = e.code, e.read()
            assert code == 400, (name, code, body)
            doc = json.loads(body.decode())
            # the 400 names the exception type (the typed-error
            # taxonomy the fuzzer enforces), not a stack trace
            assert doc["error"].split(":")[0] in (
                "ValueError", "KeyError", "error",  # struct.error
            ), (name, doc)
        # a pristine request still scores: the corpus poisoned nothing
        req = urllib.request.Request(
            url, data=plain,
            headers={"Content-Type": "application/octet-stream"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200
    finally:
        tier.close()


def test_route_striping_starves_no_replica_and_gates_ignore_stragglers(
    lr_served,
):
    """Two routing invariants under an open rollout: (1) the
    non-canary round-robin uses its own counter, so at canary_frac=0.5
    on a 3-replica fleet BOTH non-canary replicas receive traffic
    (a _seq-indexed round-robin stays phase-locked with the stripe and
    starves one); (2) canary health counts only completions routed by
    THIS rollout — a straggler carrying a resolved rollout's token
    never feeds the gate of the one that replaced it."""
    from concurrent.futures import Future

    from xflow_tpu.serve.fleet import ReplicaFleet

    fleet = ReplicaFleet.load(lr_served["artifact"], replicas=3, buckets=(8,))
    try:
        fleet.begin_rollout(
            lr_served["artifact"], canary_frac=0.5, min_canary_requests=4
        )
        routes = [fleet._route() for _ in range(24)]
        canary_hits = sum(1 for _, ro in routes if ro is not None)
        others_hit = {i for i, ro in routes if ro is None}
        assert canary_hits == 12, routes
        assert others_hit == {1, 2}, others_hit  # nobody starves
        ro_a = fleet._rollout
        fleet.abort_rollout(detail="test")
        fleet.begin_rollout(
            lr_served["artifact"], canary_frac=0.5, min_canary_requests=4
        )
        f: Future = Future()
        f.set_result(0.5)
        fleet._done(f, time.perf_counter(), ro_a, 0)  # straggler from A
        assert fleet.rollout_state()["canary_requests"] == 0
        fleet.abort_rollout(detail="test")
    finally:
        fleet.close()
