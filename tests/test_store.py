"""Hierarchical hot/cold parameter store (store/; docs/STORE.md):
config validation, cold-store semantics, tier-erased checkpoint
round-trip, zipf promotion convergence, the 2^28 acceptance geometry,
and the tier-1 smoke gate wiring."""

import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import jax

from xflow_tpu.config import Config
from xflow_tpu.trainer import Trainer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def cfg_for(ds, ndev=1, **kw):
    base = dict(
        train_path=ds.train_prefix,
        test_path=ds.test_prefix,
        model="fm",
        epochs=1,
        batch_size=64,
        table_size_log2=16,
        max_nnz=24,
        num_devices=ndev,
        store_mode="tiered",
        hot_capacity_log2=10,
    )
    base.update(kw)
    return Config(**base)


# -- config validation (satellite: actionable rejects) ---------------------


def test_hot_capacity_exceeding_table_rejected():
    with pytest.raises(ValueError, match="hot_capacity_log2"):
        Config(
            store_mode="tiered", table_size_log2=14, hot_capacity_log2=15
        )


def test_tiered_with_sequential_rejected():
    with pytest.raises(ValueError, match="sequential"):
        Config(
            store_mode="tiered",
            hot_capacity_log2=10,
            update_mode="sequential",
        )


def test_tiered_with_hot_table_rejected():
    with pytest.raises(ValueError, match="subsumes"):
        Config(
            store_mode="tiered", hot_capacity_log2=10, hot_size_log2=8
        )


def test_tiered_with_microbatch_rejected():
    with pytest.raises(ValueError, match="microbatch"):
        Config(store_mode="tiered", hot_capacity_log2=10, microbatch=4)


def test_unknown_store_mode_rejected():
    with pytest.raises(ValueError, match="store_mode"):
        Config(store_mode="paged")


def test_cli_store_flags():
    from xflow_tpu.train import build_parser, config_from_args

    args = build_parser().parse_args([
        "--train", "x", "--store-mode", "tiered",
        "--hot-capacity-log2", "11", "--store-promote-every", "4",
        "--table-size-log2", "16",
    ])
    cfg = config_from_args(args)
    assert cfg.store_mode == "tiered"
    assert cfg.hot_capacity_log2 == 11
    assert cfg.store_promote_every == 4


# -- cold store unit -------------------------------------------------------


def test_cold_store_lazy_init_deterministic_and_t_independent():
    from xflow_tpu.store.cold import row_init_values

    rows = np.asarray([0, 7, 123456789, (1 << 28) - 1], np.int64)
    a = row_init_values(3, "v", "param", rows, 10, "normal", 1e-2)
    b = row_init_values(3, "v", "param", rows, 10, "normal", 1e-2)
    assert np.array_equal(a, b)
    assert a.shape == (4, 10) and a.dtype == np.float32
    # distinct rows/tables draw distinct values; zeros kind is zeros
    c = row_init_values(3, "w", "param", rows, 10, "normal", 1e-2)
    assert not np.array_equal(a, c)
    assert not np.array_equal(a[0], a[1])
    z = row_init_values(3, "v", "n", rows, 10, "zeros", 0.0)
    assert not z.any()
    # scale is honored at the reference's 1e-2 magnitude
    assert 0.001 < np.abs(a).mean() < 0.02


def test_cold_store_fetch_write_take():
    from xflow_tpu.store.cold import ColdStore, ColdTableSpec

    store = ColdStore(
        {
            "w": ColdTableSpec(1, {"param": ("zeros", 0.0)}),
            "v": ColdTableSpec(4, {"param": ("normal", 1e-2)}),
        },
        seed=0,
    )
    keys = np.asarray([5, 9, 2], np.int64)
    lazy = store.fetch(keys)
    assert len(store) == 0  # fetch never inserts
    rows = {
        "w": {"param": np.ones((3, 1), np.float32)},
        "v": {"param": np.full((3, 4), 2.0, np.float32)},
    }
    store.write(keys, rows)
    assert len(store) == 3
    got = store.fetch(np.asarray([9, 2, 77], np.int64))
    assert np.array_equal(got["w"]["param"][:2], np.ones((2, 1)))
    # absent key 77 falls back to lazy init (v: deterministic normal)
    assert np.array_equal(
        got["v"]["param"][2], store.lazy_rows("v", "param", np.asarray([77]))[0]
    )
    taken = store.take(np.asarray([9], np.int64))
    assert float(taken["w"]["param"][0, 0]) == 1.0
    assert len(store) == 2
    # re-fetch of a taken key is lazy again
    refetch = store.fetch(np.asarray([9], np.int64))
    assert float(refetch["w"]["param"][0, 0]) == 0.0
    # the other rows survived the swap-with-last compaction
    left = store.fetch(keys)
    assert np.array_equal(
        left["v"]["param"][[0, 2]], np.full((2, 4), 2.0, np.float32)
    )
    assert np.array_equal(lazy["w"]["param"], np.zeros((3, 1)))


def test_table_spec_init_declarations_match_eager_init():
    """TableSpec carries the init distribution twice — the eager
    ``init`` lambda (dense mode) and the declarative
    init_kind/init_scale (the store's lazy per-row init).  Pin their
    agreement so an edit to one cannot silently diverge dense-mode and
    tiered-mode starting tables: zeros-kind tables must init to zeros,
    normal-kind tables to N(0,1)*init_scale (std within 20%)."""
    from xflow_tpu.models import make_model

    for name in ("lr", "fm", "mvm", "ffm", "wide_deep"):
        model = make_model(Config(model=name))
        for spec in model.tables():
            arr = np.asarray(
                spec.init(jax.random.PRNGKey(0), (4096, spec.dim))
            )
            if spec.init_kind == "zeros":
                assert not arr.any(), (name, spec.name)
                assert spec.init_scale == 0.0
            else:
                assert spec.init_kind == "normal", (name, spec.name)
                std = float(arr.std())
                assert (
                    0.8 * spec.init_scale < std < 1.2 * spec.init_scale
                ), (name, spec.name, std, spec.init_scale)


# -- end-to-end tiered training --------------------------------------------


def test_tiered_trains_and_emits_store_rows(toy_dataset, tmp_path):
    metrics = tmp_path / "m.jsonl"
    cfg = cfg_for(toy_dataset, epochs=2, metrics_out=str(metrics))
    with Trainer(cfg) as t:
        hist = t.train()
        assert len(hist) == 2
        assert hist[1]["train_logloss"] < hist[0]["train_logloss"]
        res = t.evaluate()
        assert res["auc"] > 0.6
    from xflow_tpu.obs.schema import load_jsonl, validate_rows

    rows = load_jsonl(str(metrics))
    assert validate_rows(rows) == []
    store_rows = [r for r in rows if r["kind"] == "store"]
    assert len(store_rows) == 2
    assert store_rows[0]["promotions"] > 0
    # warm epoch: the toy working set fits 2^10 slots entirely
    assert store_rows[1]["hot_hit_rate"] > 0.9
    assert 0.0 < store_rows[1]["hot_occupancy"] <= 1.0


def test_tiered_checkpoint_roundtrip_bitwise(toy_dataset, tmp_path):
    """Mid-run save with rows split across BOTH tiers -> restore ->
    bitwise-equal logical table including FTRL slots (the tier-erased
    fold contract, store/tiered.py)."""
    # capacity 2^5 = 32 slots << touched keys: rows MUST split
    cfg = cfg_for(
        toy_dataset,
        hot_capacity_log2=5,
        checkpoint_dir=str(tmp_path / "ck"),
        checkpoint_every_steps=3,
    )
    t1 = Trainer(cfg)
    t1.train()
    st1 = t1.step.store
    assert st1.hot.occupancy > 0, "nothing promoted"
    assert len(st1.cold) > 0, "nothing stayed cold — tiers not split"
    hot_keys = st1.hot.key_of[st1.hot.key_of >= 0]
    cold_keys = st1.cold._keys[: len(st1.cold)]
    probe = np.unique(np.concatenate([
        hot_keys[:40], cold_keys[:40],
        np.asarray([1, 2, 3, 60000], np.int64),  # incl. untouched
    ]))
    before = {
        tn: st1.logical_rows(t1.state, tn, probe) for tn in ("w", "v")
    }
    assert set(before["w"]) == {"param", "n", "z"}  # FTRL slots ride too
    t1.save(0, 0)

    t2 = Trainer(cfg)
    assert t2.restore() is not None
    st2 = t2.step.store
    # restore is all-cold; the logical table must not care
    assert st2.hot.occupancy == 0
    after = {
        tn: st2.logical_rows(t2.state, tn, probe) for tn in ("w", "v")
    }
    for tn in before:
        for an in before[tn]:
            assert np.array_equal(before[tn][an], after[tn][an]), (tn, an)
    # training continues from the restored table
    t2.train()
    t1.close()
    t2.close()


def test_same_instance_restore_resets_promoter(toy_dataset, tmp_path):
    """restore() on a LIVE trainer (rollback) must reset the promotion
    worker along with the maps it mirrors — a stale worker hot_view
    would filter the hottest keys out of every future promotion plan."""
    cfg = cfg_for(toy_dataset, checkpoint_dir=str(tmp_path / "ck"))
    with Trainer(cfg) as t:
        t.train()
        t.save(0, 0)
        store = t.step.store
        assert store.promoter is not None
        assert store.hot.occupancy > 0
        assert t.restore() is not None
        t.epoch = 0  # roll back: re-train the epoch from the ckpt
        # worker recreated fresh (lazily, on the next plan)
        assert store.promoter is None
        assert store.hot.occupancy == 0
        hist = t.train()  # rolls forward again: promotion must re-warm
        assert np.isfinite(hist[-1]["train_logloss"])
        assert store.hot.occupancy > 0


def test_dense_restore_of_tiered_checkpoint_refused(toy_dataset, tmp_path):
    ck = str(tmp_path / "ck")
    cfg = cfg_for(toy_dataset, checkpoint_dir=ck)
    with Trainer(cfg) as t:
        t.train()
        t.save(0, 0)
    dense_cfg = cfg.replace(store_mode="dense", hot_capacity_log2=18)
    logs = []
    t2 = Trainer(dense_cfg, log=logs.append)
    assert t2.restore() is None  # refused, starts fresh — with a reason
    assert any("tiered" in m for m in logs)
    t2.close()


def test_tiered_restore_of_dense_checkpoint_refused(toy_dataset, tmp_path):
    ck = str(tmp_path / "ck")
    dense_cfg = cfg_for(
        toy_dataset, store_mode="dense", hot_capacity_log2=18,
        checkpoint_dir=ck,
    )
    with Trainer(dense_cfg) as t:
        t.train()
        t.save(0, 0)
    cfg = cfg_for(toy_dataset, checkpoint_dir=ck)
    logs = []
    t2 = Trainer(cfg, log=logs.append)
    assert t2.restore() is None
    assert any("store" in m for m in logs)
    t2.close()


def test_tiered_multi_device_mesh(toy_dataset):
    """The hot tier row-shards over the mesh (parallel/mesh.py): a
    4-device run trains and the tier geometry divides."""
    cfg = cfg_for(toy_dataset, ndev=4, batch_size=64)
    with Trainer(cfg) as t:
        hist = t.train()
        assert np.isfinite(hist[0]["train_logloss"])


def test_fm_trains_tiered_at_2pow28(toy_dataset):
    """The acceptance geometry: fm (D>1) at table_size_log2=28 under
    store_mode='tiered' on the CPU mesh — impossible as a dense table
    (one [T, D] f32 buffer alone is 10 GiB); the tiered run bounds
    device state by hot capacity and host state by touched rows."""
    cfg = cfg_for(
        toy_dataset, table_size_log2=28, hot_capacity_log2=12, epochs=1
    )
    with Trainer(cfg) as t:
        hist = t.train()
        assert np.isfinite(hist[0]["train_logloss"])
        store = t.step.store
        # host cold rows are O(touched), nowhere near 2^28
        assert 0 < len(store.cold) + store.hot.occupancy < 1 << 20
        res = t.evaluate()
        assert 0.0 < res["logloss"] < 1.0


def test_zipf_promotion_reaches_hot_hit_rate(tmp_path):
    """Satellite: zipf traffic (the synth generator's distribution,
    scripts/gen_synth.py) at hot capacity 2^12 — after the warmup
    epoch the hot tier must serve > 0.9 of feature occurrences."""
    prefix = str(tmp_path / "zipf")
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "scripts", "gen_synth.py"),
            prefix, "8192", "--zipf-a", "2.0", "--seed", "11",
        ],
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    metrics = tmp_path / "m.jsonl"
    cfg = Config(
        train_path=prefix + ".train",
        model="lr",
        epochs=2,
        batch_size=256,
        table_size_log2=16,
        max_nnz=48,
        num_devices=1,
        store_mode="tiered",
        hot_capacity_log2=12,
        metrics_out=str(metrics),
    )
    with Trainer(cfg) as t:
        t.train()
    from xflow_tpu.obs.schema import load_jsonl

    store_rows = [
        r for r in load_jsonl(str(metrics)) if r["kind"] == "store"
    ]
    assert len(store_rows) == 2
    warm = store_rows[1]
    assert warm["hot_hit_rate"] > 0.9, store_rows
    assert warm["hot_occupancy"] > 0.0


def test_predict_batch_refused_tiered(toy_dataset):
    from xflow_tpu.api import XFlow

    xf = XFlow(
        train_path=toy_dataset.train_prefix,
        model="lr",
        epochs=1,
        batch_size=64,
        table_size_log2=16,
        max_nnz=24,
        num_devices=1,
        store_mode="tiered",
        hot_capacity_log2=10,
    )
    from xflow_tpu.io.batch import Batch

    b = Batch(
        keys=np.zeros((1, 4), np.int32),
        slots=np.zeros((1, 4), np.int32),
        vals=np.ones((1, 4), np.float32),
        mask=np.ones((1, 4), np.float32),
        labels=np.zeros(1, np.float32),
        weights=np.ones(1, np.float32),
    )
    with pytest.raises(ValueError, match="export_artifact"):
        xf.predict_batch(b)
    xf.trainer.close()


def test_promotion_worker_closes_without_leak():
    from xflow_tpu.store.promote import PromotionWorker

    before = {t.ident for t in threading.enumerate()}
    w = PromotionWorker(64)
    w.note(
        np.asarray([3, 5], np.int64),
        np.asarray([4, 1], np.int64),
        np.asarray([True, True]),
    )
    # the worker proposes promotion of the touched misses
    plan = None
    for _ in range(200):
        plan = w.poll_plan()
        if plan is not None:
            break
        import time

        time.sleep(0.01)
    assert plan is not None and set(plan["promote"]) == {3, 5}
    assert w.close()
    leftover = {
        t.ident for t in threading.enumerate()
    } - before
    assert not leftover


def test_store_thrash_doctor_diagnosis():
    """obs doctor gains the store-thrash cause: low warm hit rate +
    churn -> warn; the first (warmup) row is exempt."""
    from xflow_tpu.obs.doctor import diagnose

    def store_row(epoch, rate, promos, demos):
        return {
            "t": float(epoch), "kind": "store", "epoch": epoch,
            "hot_hit_rate": rate, "promotions": promos,
            "demotions": demos, "cold_fetch_seconds": 0.1,
            "hot_occupancy": 1.0,
        }

    header = {
        "t": 0.0, "kind": "run_start", "run_id": "x",
        "config_digest": "d", "rank": 0, "num_hosts": 1,
        "time_unix": 0.0,
    }
    sick = [header, store_row(0, 0.1, 500, 0),
            store_row(1, 0.3, 400, 400)]
    codes = {d.code for d in diagnose(sick)}
    assert "store_thrash" in codes
    # a SATURATED tier with zero churn (swap hysteresis) serving a
    # too-large working set is the same condition — occupancy fires it
    saturated = [header, store_row(0, 0.1, 500, 0),
                 store_row(1, 0.3, 0, 0)]
    codes = {d.code for d in diagnose(saturated)}
    assert "store_thrash" in codes
    # warmup-only miss storm is NOT thrash
    healthy = [header, store_row(0, 0.1, 500, 0),
               store_row(1, 0.97, 3, 3)]
    codes = {d.code for d in diagnose(healthy)}
    assert "store_thrash" not in codes


def test_check_store_smoke_script():
    """The CI lint (scripts/check_store_smoke.py) passes — run as a
    subprocess exactly as CI would (tier-1 wiring, like
    check_serve_smoke.py)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "check_store_smoke.py")],
        capture_output=True,
        text=True,
        timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout
