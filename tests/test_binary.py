"""Binary block cache (io/binary.py): the text parser and the cache
must be indistinguishable to everything downstream — identical batches,
identical resume offsets' continuation, table-size independence of one
cache file — plus the full-key (table_size=0) parse mode it builds on."""

import os

import numpy as np
import pytest

from xflow_tpu.io import binary
from xflow_tpu.io.libffm import parse_block
from xflow_tpu.io.loader import ShardLoader, make_parse_fn


def batches_equal(a, b):
    for f in (
        "keys", "slots", "vals", "mask", "labels", "weights",
        "hot_keys", "hot_slots", "hot_vals", "hot_mask",
    ):
        np.testing.assert_array_equal(
            getattr(a, f), getattr(b, f), err_msg=f
        )


def make_loader(path, table_size=1 << 14, **kw):
    args = dict(
        batch_size=64, max_nnz=24, table_size=table_size, block_mib=1
    )
    args.update(kw)
    return ShardLoader(path, **args)


@pytest.fixture(scope="module")
def converted(toy_dataset, tmp_path_factory):
    """First toy shard converted to the binary cache."""
    src = toy_dataset.train_prefix + "-00000"
    dst = str(tmp_path_factory.mktemp("bin") / "shard-00000")
    # ~2 KiB text blocks -> many records, so resume granularity is real
    meta = binary.convert_shard(src, dst, hash_mode=True, hash_seed=0,
                                block_mib=0.002)
    return src, dst, meta


def test_convert_header_totals(converted):
    src, dst, meta = converted
    assert binary.is_binary_shard(dst)
    assert not binary.is_binary_shard(src)
    assert meta["examples"] == 200
    assert meta["blocks"] >= 1
    assert binary.shard_example_count(dst) == 200
    # header survives the in-place rewrite (read back from disk)
    with open(dst, "rb") as f:
        reread, _ = binary.read_header(f)
    assert reread == meta


def test_binary_batches_match_text(converted):
    src, dst, _ = converted
    text = list(make_loader(src).iter_batches())
    bin_ = list(make_loader(dst).iter_batches())
    assert len(text) == len(bin_)
    for (tb, _), (bb, _) in zip(text, bin_):
        batches_equal(tb, bb)


def test_binary_batches_match_text_hot_remap(converted):
    """Hot steering + frequency remap apply identically on the cache."""
    src, dst, _ = converted
    rng = np.random.default_rng(5)
    t = 1 << 14
    remap = rng.permutation(t).astype(np.int32)
    kw = dict(remap=remap, hot_size=256, hot_nnz=6)
    text = list(make_loader(src, **kw).iter_batches())
    bin_ = list(make_loader(dst, **kw).iter_batches())
    assert len(text) == len(bin_)
    for (tb, _), (bb, _) in zip(text, bin_):
        batches_equal(tb, bb)


def test_binary_table_size_independent(converted):
    """ONE cache file serves any table size: keys stored full (64-bit)
    and reduced at load, bit-identical to parsing the text at that
    table size."""
    src, dst, _ = converted
    for log2 in (10, 18):
        text = list(make_loader(src, table_size=1 << log2).iter_batches())
        bin_ = list(make_loader(dst, table_size=1 << log2).iter_batches())
        for (tb, _), (bb, _) in zip(text, bin_):
            batches_equal(tb, bb)


def test_binary_resume_offsets(converted):
    """Resuming from a yielded offset re-covers every not-yet-consumed
    sample, with replay bounded by one record (the same block-
    granularity contract as the text loader)."""
    _, dst, meta = converted
    assert meta["blocks"] > 3  # resume granularity must be real
    loader = make_loader(dst, batch_size=1)  # per-sample streams
    full = list(loader.iter_batches())
    labels = [b.labels[0] for b, _ in full]
    consumed = 40
    _, resume = full[consumed - 1]
    tail = [
        b.labels[0]
        for b, _ in loader.iter_batches(start_offset=resume)
    ]
    # the resumed stream is a suffix of the full one ...
    assert len(tail) <= len(labels)
    np.testing.assert_array_equal(
        np.asarray(tail), np.asarray(labels[len(labels) - len(tail):])
    )
    # ... covering everything unconsumed, with bounded replay (at most
    # one ~2 KiB record of ~100 B lines)
    replay = len(tail) - (len(labels) - consumed)
    assert 0 <= replay <= 25


def test_binary_header_mismatch_rejected(converted, tmp_path):
    _, dst, _ = converted
    with pytest.raises(ValueError, match="seed"):
        list(make_loader(dst, hash_seed=99).iter_batches())
    with pytest.raises(ValueError, match="hash_mode"):
        list(make_loader(dst, hash_mode=False).iter_batches())


def test_convert_prefix_cli(toy_dataset, tmp_path):
    out = str(tmp_path / "bin")
    rc = binary.main(
        ["--train", toy_dataset.train_prefix, "--out", out, "--block-mib", "1"]
    )
    assert rc == 0
    shards = sorted(os.listdir(tmp_path))
    assert shards == ["bin-00000", "bin-00001", "bin-00002"]
    # the converted prefix trains end-to-end exactly like the text one
    from xflow_tpu.config import Config
    from xflow_tpu.trainer import Trainer

    base = dict(
        model="lr", epochs=2, batch_size=64, table_size_log2=14,
        max_nnz=24, num_devices=1, test_path=toy_dataset.test_prefix,
    )
    t_text = Trainer(Config(train_path=toy_dataset.train_prefix, **base))
    t_text.train()
    t_bin = Trainer(Config(train_path=out, **base))
    t_bin.train()
    import jax

    np.testing.assert_array_equal(
        np.asarray(jax.device_get(t_text.state["tables"]["w"]["param"])),
        np.asarray(jax.device_get(t_bin.state["tables"]["w"]["param"])),
    )


def test_full_key_parse_mode():
    """table_size=0 keeps full 64-bit keys; reducing them afterwards is
    bit-identical to parsing with the reduction."""
    data = b"1\t0:alpha:1 3:beta:1\n0\t2:gamma:1\n"
    full = parse_block(data, 0, hash_mode=True, hash_seed=0)
    t = 1 << 12
    reduced = parse_block(data, t, hash_mode=True, hash_seed=0)
    np.testing.assert_array_equal(
        binary.reduce_keys(full.keys, t, True), reduced.keys
    )
    # numeric mode, including negative fids
    data_n = b"1\t0:-7:0.5 1:123:1.5\n"
    full_n = parse_block(data_n, 0, hash_mode=False)
    red_n = parse_block(data_n, 64, hash_mode=False)
    assert full_n.keys.tolist() == [-7, 123]
    np.testing.assert_array_equal(
        binary.reduce_keys(full_n.keys, 64, False), red_n.keys
    )


def test_binary_misaligned_resume_rejected(converted):
    """A byte offset that is not a record boundary (e.g. a cursor saved
    against the TEXT version of the shard) must raise, not read garbage
    record sizes."""
    _, dst, _ = converted
    loader = make_loader(dst)
    good = list(loader.iter_batches())
    _, resume = good[0]
    with pytest.raises(ValueError, match="record boundary|shard end"):
        list(loader.iter_batches(start_offset=resume + 3))


def test_freq_count_rejects_packed(toy_dataset, tmp_path):
    """Packed caches hold post-remap keys — frequency counting must
    refuse them loudly instead of parsing binary bytes as text."""
    from xflow_tpu.io import freq, packed

    src = toy_dataset.train_prefix + "-00000"
    dst = str(tmp_path / "pk-00000")
    packed.convert_shard(
        src, dst, batch_size=64, max_nnz=24, table_size=1 << 14
    )
    with pytest.raises(ValueError, match="packed-batch cache"):
        freq.count_keys([dst], None, 1 << 14, 1 << 20)


def test_python_pack_rejects_wide_keys():
    """The pure-Python pack fallback must reject keys outside int32 just
    like the native path (parser.cc returns -2) — never silently wrap.
    Full 64-bit keys (table_size=0 parse) must be reduced first."""
    from xflow_tpu.io.batch import ParsedBlock, pack_batch

    block = ParsedBlock(
        labels=np.asarray([1.0], np.float32),
        row_ptr=np.asarray([0, 1], np.int64),
        keys=np.asarray([1 << 33], np.int64),
        slots=np.asarray([0], np.int32),
        vals=np.asarray([1.0], np.float32),
    )
    with pytest.raises(ValueError, match="int32"):
        pack_batch(block, 0, 1, 4, 4)
    neg = ParsedBlock(
        labels=np.asarray([1.0], np.float32),
        row_ptr=np.asarray([0, 1], np.int64),
        keys=np.asarray([-5], np.int64),
        slots=np.asarray([0], np.int32),
        vals=np.asarray([1.0], np.float32),
    )
    with pytest.raises(ValueError, match="int32"):
        pack_batch(neg, 0, 1, 4, 4)


def test_full_key_parse_native_parity():
    from xflow_tpu import native

    if not native.available():
        pytest.skip("native library unavailable")
    data = b"1\t0:alpha:1 3:beta:1\n0\t2:gamma:0.5\n"
    for hash_mode in (True, False):
        py = parse_block(data, 0, hash_mode)
        nat = native.native_parse_block(data, 0, hash_mode)
        np.testing.assert_array_equal(py.keys, nat.keys)
        np.testing.assert_array_equal(py.row_ptr, nat.row_ptr)
        np.testing.assert_array_equal(py.vals, nat.vals)
