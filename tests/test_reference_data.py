"""Pin training+eval on the reference's OWN bundled data — its de-facto
verification procedure (SURVEY §4: smoke run over data/small_train-*
through scripts/local.sh, eyeballing printed logloss/auc).  Round-1
VERDICT: "Reference-bundled data is never exercised by CI" — this makes
it permanent.
"""

import os

import numpy as np
import pytest

from xflow_tpu.config import Config
from xflow_tpu.trainer import Trainer

REF_DATA = "/root/reference/data"
TRAIN = os.path.join(REF_DATA, "small_train")
TEST = os.path.join(REF_DATA, "small_test")

needs_ref = pytest.mark.skipif(
    not os.path.exists(TRAIN + "-00000"), reason="reference data absent"
)


@needs_ref
def test_reference_data_parses_fully():
    """All 3x200 train lines and 200 test lines parse (libffm
    label<TAB>fgid:fid:val, 18 fields/sample, data/small_train-00000:1)."""
    from xflow_tpu.io.loader import ShardLoader, make_parse_fn
    from xflow_tpu.trainer import find_shards

    shards = find_shards(TRAIN)
    assert len(shards) == 3
    parse = make_parse_fn(1 << 16, True, 0)
    max_nnz = 40  # data lines carry 15..36 features (NOT a fixed 18)
    for path in shards + [TEST + "-00000"]:
        loader = ShardLoader(
            path, batch_size=64, max_nnz=max_nnz, table_size=1 << 16,
            parse_fn=parse,
        )
        total = sum(b.num_real() for b, _ in loader.iter_batches())
        assert total == 200
        # every feature token of every line survives parsing (none
        # dropped as malformed): expected count straight from the text
        expect = sum(
            min(len(line.split()) - 1, max_nnz)
            for line in open(path, "rb")
            if line.strip()
        )
        nnz = sum(
            int((b.mask.sum(axis=1) * (b.weights > 0)).sum())
            for b, _ in loader.iter_batches()
        )
        assert nnz == expect


@needs_ref
def test_reference_data_trains(tmp_path):
    """LR+FTRL on the reference's data with its default hyperparameters
    reaches finite, plausible metrics (independent 20-epoch anchor from
    round-1 review: logloss 0.5416, AUC 0.554) and writes the
    reference-granularity pred_<rank>_<block>.txt artifacts."""
    pred_dir = str(tmp_path / "preds")
    cfg = Config(
        model="lr",
        train_path=TRAIN,
        test_path=TEST,
        epochs=20,
        batch_size=128,
        table_size_log2=16,
        max_nnz=24,
        num_devices=1,
        pred_out=pred_dir,
        pred_style="per_block",
    )
    t = Trainer(cfg)
    history = t.train()
    assert history[-1]["examples"] == 600.0
    result = t.evaluate()
    assert np.isfinite(result["logloss"]) and np.isfinite(result["auc"])
    assert result["examples"] == 200
    # tp/fp are LABEL counts (reference base.h:101-108 prints positive/
    # negative totals) — fixed by the data, not by model thresholds
    assert result["tp"] == 46 and result["fp"] == 154
    # deterministic run: metrics pinned to the round-1 independent anchor
    assert abs(result["logloss"] - 0.5416) < 0.02
    assert result["auc"] > 0.52
    # reference artifact shape: pred_0_<block>.txt files totalling 200 lines
    files = sorted(os.listdir(pred_dir))
    assert files and all(f.startswith("pred_0_") for f in files)
    lines = sum(
        len(open(os.path.join(pred_dir, f)).readlines()) for f in files
    )
    assert lines == 200
