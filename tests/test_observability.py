"""Structured metrics JSONL + profiler trace hooks (SURVEY §5 gaps),
and the obs subsystem (ISSUE 1): span tracer, phase accounting,
pipeline-health metrics, schema, summarize/compare toolchain."""

import json
import os
import sys
import time

import pytest

from xflow_tpu.config import Config
from xflow_tpu.trainer import Trainer


def test_metrics_jsonl(toy_dataset, tmp_path):
    out = tmp_path / "metrics.jsonl"
    cfg = Config(
        train_path=toy_dataset.train_prefix,
        test_path=toy_dataset.test_prefix,
        model="lr",
        epochs=2,
        batch_size=64,
        table_size_log2=14,
        max_nnz=24,
        num_devices=1,
        metrics_out=str(out),
    )
    t = Trainer(cfg)
    t.train()
    t.evaluate()
    rows = [json.loads(line) for line in out.read_text().splitlines()]
    kinds = [r["kind"] for r in rows]
    assert kinds.count("train_epoch") == 2
    assert kinds.count("eval") == 1
    epoch_row = next(r for r in rows if r["kind"] == "train_epoch")
    for field in ("examples", "steps", "train_logloss", "examples_per_sec", "t"):
        assert field in epoch_row
    eval_row = next(r for r in rows if r["kind"] == "eval")
    assert 0.0 <= eval_row["auc"] <= 1.0


def test_eval_every_epochs(toy_dataset, tmp_path):
    """--eval-every N runs mid-training evals (convergence curves,
    VERDICT round 3 item 3); each eval record carries its epoch."""
    out = tmp_path / "metrics.jsonl"
    cfg = Config(
        train_path=toy_dataset.train_prefix,
        test_path=toy_dataset.test_prefix,
        model="lr",
        epochs=4,
        batch_size=64,
        table_size_log2=14,
        max_nnz=24,
        num_devices=1,
        metrics_out=str(out),
        eval_every_epochs=2,
    )
    t = Trainer(cfg)
    t.train()
    t.evaluate()  # the caller's final eval (train.py main does this)
    rows = [json.loads(line) for line in out.read_text().splitlines()]
    evals = [r for r in rows if r["kind"] == "eval"]
    # mid-run at epoch 2 (epoch 4 == cfg.epochs is left to the caller)
    # plus the final one
    assert [e["epoch"] for e in evals] == [2, 4]


def test_profile_trace_written(toy_dataset, tmp_path):
    prof = tmp_path / "prof"
    cfg = Config(
        train_path=toy_dataset.train_prefix,
        test_path=toy_dataset.test_prefix,
        model="lr",
        batch_size=64,
        table_size_log2=14,
        max_nnz=24,
        num_devices=1,
        epochs=3,
        profile_dir=str(prof),
        # larger than one epoch's step count: the trigger must carry
        # across epochs (global-step based, not per-epoch)
        profile_start_step=8,
        profile_steps=2,
    )
    t = Trainer(cfg)
    t.train()
    # jax writes plugins/profile/<ts>/*.pb under the trace dir
    produced = list(prof.rglob("*"))
    assert any(p.is_file() for p in produced), produced


# -- ISSUE 1: obs subsystem -------------------------------------------------


def _toy_cfg(toy_dataset, **overrides):
    base = dict(
        train_path=toy_dataset.train_prefix,
        test_path=toy_dataset.test_prefix,
        model="lr",
        epochs=2,
        batch_size=64,
        table_size_log2=14,
        max_nnz=24,
        num_devices=1,
    )
    base.update(overrides)
    return Config(**base)


def test_span_tracer_nesting_and_export(tmp_path):
    """Nested spans land as Chrome 'X' events whose intervals nest, and
    the export is loadable trace-event JSON."""
    from xflow_tpu.obs.trace import SpanTracer

    tr = SpanTracer(capacity=16, rank=3)
    with tr.span("outer", {"epoch": 1}):
        with tr.span("inner"):
            pass
        with tr.span("inner"):
            pass
    events = tr.events()
    assert [e["name"] for e in events] == ["inner", "inner", "outer"]
    outer = events[-1]
    for inner in events[:-1]:
        assert inner["ts"] >= outer["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    assert all(e["ph"] == "X" and e["pid"] == 3 for e in events)
    assert outer["args"]["epoch"] == 1

    path = tmp_path / "trace.json"
    tr.export_chrome(str(path))
    doc = json.loads(path.read_text())
    assert isinstance(doc["traceEvents"], list)
    for e in doc["traceEvents"]:
        for key in ("name", "ph", "ts", "dur", "pid", "tid"):
            assert key in e


def test_span_tracer_ring_buffer():
    from xflow_tpu.obs.trace import SpanTracer

    tr = SpanTracer(capacity=8)
    for i in range(20):
        with tr.span(f"s{i}"):
            pass
    events = tr.events()
    assert len(events) == 8  # only the newest capacity spans survive
    assert events[-1]["name"] == "s19"


def test_null_obs_is_shared_noop():
    """Disabled obs allocates nothing per step: phase()/span() return
    the one shared no-op object and the registry snapshot is empty."""
    from xflow_tpu.obs import NULL_OBS

    s1 = NULL_OBS.phase("a")
    s2 = NULL_OBS.phase("b")
    assert s1 is s2 is NULL_OBS.span("c")
    with s1:
        pass
    NULL_OBS.counter("x", 1.0)
    NULL_OBS.observe("y", 2.0)
    snap = NULL_OBS.registry.snapshot()
    assert snap.counters == {} and snap.hists == {}
    assert NULL_OBS.tracer.events() == []


def test_registry_percentiles_and_phases():
    from xflow_tpu.obs.registry import MetricsRegistry

    reg = MetricsRegistry()
    for v in range(1, 101):
        reg.observe("lat", float(v))
    reg.counter_add("phase.parse", 1.5)
    reg.counter_add("phase.parse", 0.5)
    reg.gauge_set("depth", 3.0)
    snap = reg.snapshot(reset=True)
    h = snap.hists["lat"]
    assert h["count"] == 100
    assert abs(h["p50"] - 50) <= 2 and abs(h["p99"] - 99) <= 2
    assert snap.phase_seconds() == {"parse": 2.0}
    assert snap.gauges["depth"] == 3.0
    assert reg.snapshot().counters == {}  # reset cleared it


def test_run_start_header_splits_runs(toy_dataset, tmp_path):
    """Append mode + run_start delimiter: two runs into one file never
    merge in summarize (ISSUE 1 satellite)."""
    from xflow_tpu.obs.summary import load_runs

    out = tmp_path / "m.jsonl"
    for epochs in (1, 1):
        with Trainer(_toy_cfg(toy_dataset, epochs=epochs, metrics_out=str(out))) as t:
            t.train()
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    headers = [r for r in rows if r["kind"] == "run_start"]
    assert len(headers) == 2
    for h in headers:
        for field in ("run_id", "config_digest", "rank", "num_hosts"):
            assert field in h
    runs = load_runs(str(out))
    assert len(runs) == 2
    assert all(len(r.epochs) == 1 for r in runs)


def test_epoch_phase_accounting(toy_dataset, tmp_path):
    """Main-thread phases are disjoint and account for most of the
    epoch wall-clock; overlapped worker phases are reported separately
    (the summarize >= 90% contract is checked run-level by
    scripts/check_metrics_schema.py)."""
    out = tmp_path / "m.jsonl"
    with Trainer(_toy_cfg(toy_dataset, metrics_out=str(out))) as t:
        t.train()
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    epochs = [r for r in rows if r["kind"] == "train_epoch"]
    assert len(epochs) == 2
    wall = sum(e["seconds"] for e in epochs)
    accounted = sum(sum(e["phases"].values()) for e in epochs)
    assert accounted <= wall * 1.01  # exclusive phases can't exceed wall
    assert accounted >= wall * 0.8, (accounted, wall, epochs)
    for e in epochs:
        assert "input_stall" in e["phases"] and "dispatch" in e["phases"]
        # single-host transfer-ahead: h2d rides the worker thread
        assert "h2d" in e["overlapped"]
        assert 0.0 <= e["input_stall_frac"] <= 1.0
        assert e["step_time_p50"] <= e["step_time_p99"]
        assert e["checkpoint_seconds"] == 0.0  # no checkpointing here


def test_stall_accounting_slow_loader(toy_dataset, tmp_path, monkeypatch):
    """An artificially slow input pipeline shows up as input_stall
    seconds, not as deflated mystery throughput."""
    # large enough that the injected stall dominates CPU-dispatch
    # wall-clock noise: at 0.02 the frac bound below flaked under
    # full-suite load (observed 0.16-0.18 vs the standalone ~0.3)
    delay = 0.05
    orig = Trainer.iter_train_batches

    def slow(self, *a, **kw):
        for item in orig(self, *a, **kw):
            time.sleep(delay)
            yield item

    monkeypatch.setattr(Trainer, "iter_train_batches", slow)
    out = tmp_path / "m.jsonl"
    with Trainer(
        _toy_cfg(toy_dataset, epochs=1, metrics_out=str(out))
    ) as t:
        t.train()
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    e = next(r for r in rows if r["kind"] == "train_epoch")
    # every batch was delayed on the path the main thread blocks on
    assert e["phases"]["input_stall"] >= e["steps"] * delay * 0.7
    # the frac bound is loose: the dict wire (Config.wire_dedup)
    # compiles a second shape bucket for partial tail batches, and a
    # loaded CI box inflates this toy run's dispatch wall-clock
    # relative to the injected stall (the absolute-seconds assertion
    # above is the real accounting check; 0.196 observed at a 0.2
    # bound under full-suite load — keep clear margin)
    assert e["input_stall_frac"] >= 0.15, e


def test_checkpoint_seconds_separated(toy_dataset, tmp_path):
    """Satellite: checkpoint-save time is reported as its own field and
    excluded from examples_per_sec instead of silently deflating it."""
    out = tmp_path / "m.jsonl"
    ckpt = tmp_path / "ckpt"
    with Trainer(_toy_cfg(
        toy_dataset, epochs=1, metrics_out=str(out),
        checkpoint_dir=str(ckpt), checkpoint_every_steps=3,
    )) as t:
        t.train()
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    e = next(r for r in rows if r["kind"] == "train_epoch")
    assert e["checkpoint_seconds"] > 0.0
    assert "checkpoint" in e["phases"]
    # throughput uses compute time: seconds minus checkpoint time
    # checkpoint_seconds is rounded in the record; allow that slack
    expect = e["examples"] / (e["seconds"] - e["checkpoint_seconds"])
    assert abs(e["examples_per_sec"] - expect) / expect < 1e-3


def test_schema_covers_all_emitted_kinds(toy_dataset, tmp_path):
    """Every emitted JSONL row carries its kind's required fields, and
    every kind the pipeline emits is in the schema."""
    from xflow_tpu.obs.schema import SCHEMA, validate_rows

    out = tmp_path / "m.jsonl"
    ckpt = tmp_path / "ckpt"
    with Trainer(_toy_cfg(
        toy_dataset, metrics_out=str(out),
        checkpoint_dir=str(ckpt), checkpoint_every_steps=4,
        eval_every_epochs=1,
    )) as t:
        t.train()
        t.evaluate()
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    assert validate_rows(rows) == []
    kinds = {r["kind"] for r in rows}
    assert kinds <= set(SCHEMA)
    assert {"run_start", "train_epoch", "eval", "shard"} <= kinds


def test_trainer_trace_export(toy_dataset, tmp_path):
    """Config.obs_trace_out: the trainer writes a loadable Chrome trace
    containing the span taxonomy's hot-path names."""
    trace = tmp_path / "trace.json"
    with Trainer(_toy_cfg(
        toy_dataset, epochs=1, obs_trace_out=str(trace)
    )) as t:
        t.train()
    doc = json.loads(trace.read_text())
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"train_epoch", "dispatch", "input_stall", "h2d"} <= names
    steps = [e["args"]["step"] for e in doc["traceEvents"] if "args" in e]
    assert steps and all(isinstance(s, int) for s in steps)


def test_summarize_and_compare_cli(toy_dataset, tmp_path, capsys):
    from xflow_tpu.obs.__main__ import main

    out = tmp_path / "m.jsonl"
    with Trainer(_toy_cfg(toy_dataset, metrics_out=str(out))) as t:
        t.train()
        t.evaluate()
    assert main(["summarize", str(out)]) == 0
    text = capsys.readouterr().out
    for token in ("phase", "accounted", "input_stall", "eval epoch"):
        assert token in text, text
    assert main(["compare", str(out), str(out)]) == 0
    text = capsys.readouterr().out
    assert "examples/sec" in text and "input_stall_frac" in text
    assert main(["validate", str(out)]) == 0


def test_metrics_logger_closes_on_exception(toy_dataset, tmp_path, monkeypatch):
    """Satellite: the logger is closed (rows flushed, file released) when
    training dies mid-run."""
    out = tmp_path / "m.jsonl"
    t = Trainer(_toy_cfg(toy_dataset, metrics_out=str(out)))

    def boom(*a, **kw):
        raise RuntimeError("loader died")

    monkeypatch.setattr(Trainer, "iter_train_batches", boom)
    with pytest.raises(RuntimeError, match="loader died"):
        t.train()
    assert t.metrics_logger.closed
    # late logs after close are swallowed, not crashes
    t.metrics_logger.log("eval", {})
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    assert [r["kind"] for r in rows] == ["run_start"]


def test_check_metrics_schema_script():
    """The CI lint (scripts/check_metrics_schema.py) passes on the toy
    pipeline — run as a subprocess exactly as CI would."""
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "check_metrics_schema.py")],
        capture_output=True,
        text=True,
        timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout


# -- ISSUE 4: flight recorder / watchdog / doctor ---------------------------


def test_histogram_alltime_max_survives_ring_overflow():
    """Satellite regression: `max` is an exact all-time aggregate (like
    count/sum/mean), `window_max` covers the retained ring.  The old
    code reported max(ring) as `max`, so a spike older than `capacity`
    observations silently vanished."""
    from xflow_tpu.obs.registry import Histogram

    h = Histogram(capacity=8)
    h.observe(100.0)  # the spike, soon evicted from the ring
    for v in range(20):
        h.observe(float(v))
    s = h.summary()
    assert s["count"] == 21
    assert s["max"] == 100.0  # all-time, despite eviction
    assert s["window_max"] == 19.0  # newest capacity=8 values: 12..19
    assert abs(s["mean"] - (100.0 + sum(range(20))) / 21) < 1e-9
    empty = Histogram(capacity=4).summary()
    assert empty["max"] == 0.0 and empty["window_max"] == 0.0


def test_run_start_carries_hostname_and_pid(tmp_path):
    """Satellite: every run_start row is stamped with hostname/pid by
    MetricsLogger itself, so every emitter (trainer, serve bench,
    smokes) gets host labels for `obs merge`/`doctor` for free."""
    import socket

    from xflow_tpu.obs.schema import validate_rows
    from xflow_tpu.utils.logging import MetricsLogger

    out = tmp_path / "m.jsonl"
    with MetricsLogger(str(out), run_header={
        "run_id": "x", "config_digest": "y", "rank": 0, "num_hosts": 1,
    }):
        pass
    row = json.loads(out.read_text().splitlines()[0])
    assert row["hostname"] == socket.gethostname()
    assert row["pid"] == os.getpid()
    assert validate_rows([row]) == []
    # the fields are OPTIONAL in the schema: pre-upgrade files (and
    # old headers in append-mode files that span the upgrade) still
    # validate, but a present field is still type-checked
    legacy = {k: v for k, v in row.items() if k not in ("hostname", "pid")}
    assert validate_rows([legacy]) == []
    bad = dict(row, pid="not-an-int")
    assert validate_rows([bad]) != []


def test_flight_recorder_dump_roundtrip(tmp_path):
    """The black box: notes ring-buffer, dump is atomic JSON carrying
    the active phase, thread stacks, and the last batch/checkpoint."""
    from xflow_tpu.obs.flight import FlightRecorder, load_dump

    fl = FlightRecorder(capacity=4)
    for i in range(10):
        fl.note_phase("input_stall", step=i)
    fl.note_phase("dispatch", step=10)
    fl.note_batch({"rows": 64, "cold_nnz": 24, "hot_nnz": 0, "shard": 1})
    fl.note_checkpoint(7)
    fl.note_loader("block")
    path = str(tmp_path / "flight.json")
    try:
        raise RuntimeError("boom")
    except RuntimeError as e:
        assert fl.dump(path, "exception", exc=e) == path
    doc = load_dump(path)
    assert doc["reason"] == "exception"
    assert doc["active_phase"] == "dispatch"
    assert doc["exception"]["type"] == "RuntimeError"
    rec = doc["record"]
    assert len(rec["events"]) == 4  # ring kept only the newest capacity
    assert rec["last_checkpoint_step"] == 7
    assert rec["last_batch"]["rows"] == 64
    assert {"train", "loader"} <= set(rec["channels"])
    assert any(t["stack"] for t in doc["threads"])
    # no leftover tmp file from the atomic write
    assert [p.name for p in tmp_path.iterdir()] == ["flight.json"]


def test_watchdog_classifies_silence_per_phase(tmp_path):
    """Unit classification: silence while in input_stall is input
    starvation (input threshold); while in dispatch/device_block it is
    a device hang (device threshold); 'idle' silence never trips."""
    from xflow_tpu.obs.flight import FlightRecorder
    from xflow_tpu.obs.watchdog import Watchdog

    fl = FlightRecorder()
    wd = Watchdog(fl, input_s=0.5, device_s=2.0, serve_s=1.0)
    fl.note_phase("input_stall", 1)
    now = time.perf_counter()
    assert wd.check(now + 0.1) == []  # within threshold
    rows = wd.check(now + 0.6)
    assert [r["cause"] for r in rows] == ["input_stall"]
    assert rows[0]["channel"] == "train"
    assert rows[0]["threshold_seconds"] == 0.5
    # recovery on the next beat
    fl.note_phase("dispatch", 2)
    now = time.perf_counter()
    rows = wd.check(now)
    assert [r["cause"] for r in rows] == ["recovered:input_stall"]
    # dispatch silence: device threshold, not the (tighter) input one
    assert wd.check(now + 1.0) == []
    rows = wd.check(now + 2.5)
    assert [r["cause"] for r in rows] == ["device_hang"]
    fl.note_phase("idle", 3)
    wd.check()  # recovery row for the device incident
    assert wd.check(time.perf_counter() + 999) == []  # idle never trips


def test_watchdog_serve_queue_stall_gated_on_pending(tmp_path):
    """Serve-channel silence only trips while work is pending; an idle
    batcher is healthy no matter how long it sits."""
    from xflow_tpu.obs.flight import FlightRecorder
    from xflow_tpu.obs.watchdog import Watchdog

    fl = FlightRecorder()
    wd = Watchdog(fl, input_s=1.0, device_s=1.0, serve_s=0.5)
    pending = [False]
    wd.set_pending("serve", lambda: pending[0])
    fl.note_serve("batch")
    now = time.perf_counter()
    assert wd.check(now + 10.0) == []  # silent but idle: healthy
    pending[0] = True
    rows = wd.check(now + 10.0)
    assert [r["cause"] for r in rows] == ["serve_queue_stall"]


def test_watchdog_escalates_to_flight_dump(tmp_path):
    """Trip → health row; persistence past 2x threshold → exactly one
    flight dump per incident, written where flight_out points."""
    from xflow_tpu.obs.flight import FlightRecorder, load_dump
    from xflow_tpu.obs.watchdog import Watchdog

    out = str(tmp_path / "flight.json")
    fl = FlightRecorder()
    wd = Watchdog(fl, input_s=0.5, device_s=2.0, serve_s=1.0, flight_out=out)
    fl.note_phase("input_stall", 5)
    now = time.perf_counter()
    wd.check(now + 0.6)  # trip
    assert not os.path.exists(out)  # not yet escalated
    wd.check(now + 1.1)  # past 2x threshold
    assert wd.dump_count == 1
    doc = load_dump(out)
    assert doc["reason"] == "watchdog"
    assert doc["active_phase"] == "input_stall"
    wd.check(now + 5.0)  # still silent: same incident, no second dump
    assert wd.dump_count == 1


def test_stalled_run_trips_watchdog_and_doctor_blames_input(
    toy_dataset, tmp_path, monkeypatch
):
    """ISSUE 4 acceptance: a deliberately stalled toy run (loader sleep
    injected) trips the watchdog within its threshold, lands a `health`
    row plus a flight dump, and `obs doctor` names input_stall as the
    dominant cause."""
    from xflow_tpu.obs.doctor import doctor
    from xflow_tpu.obs.flight import load_dump
    from xflow_tpu.obs.schema import validate_rows

    delay = 0.6
    orig = Trainer.iter_train_batches

    def slow(self, *a, **kw):
        for item in orig(self, *a, **kw):
            time.sleep(delay)
            yield item

    monkeypatch.setattr(Trainer, "iter_train_batches", slow)
    out = tmp_path / "m.jsonl"
    flight = tmp_path / "flight.json"
    with Trainer(_toy_cfg(
        toy_dataset,
        epochs=1,
        metrics_out=str(out),
        obs_flight_out=str(flight),
        obs_watchdog=True,
        obs_watchdog_input_s=0.2,  # delay > 2x threshold => escalation
        obs_watchdog_device_s=30.0,
    )) as t:
        t.train()
        assert t._watchdog.trip_count >= 1
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    assert validate_rows(rows) == []
    health = [r for r in rows if r["kind"] == "health"]
    trips = [r for r in health if r["cause"] == "input_stall"]
    assert trips, health
    # tripped within its threshold: the classified silence is of
    # threshold order, nowhere near the full injected delay
    assert trips[0]["silence_seconds"] < delay
    assert trips[0]["channel"] == "train"
    # the loader-channel context rode along (starvation forensics)
    assert "loader" in trips[0]["channels"]
    doc = load_dump(str(flight))
    assert doc["reason"] == "watchdog"
    assert doc["active_phase"] == "input_stall"
    assert any(r["kind"] == "flight_dump" for r in rows)
    text, rc = doctor(str(out), flight_path=str(flight))
    assert rc == 1
    # ranked diagnosis: the dominant (first) finding is the input stall
    first = next(l for l in text.splitlines() if l.strip().startswith("["))
    assert "input_stall" in first, text


def _epoch_row(epoch, rank=None, p50=0.002, p90=None, p99=None, stall=0.1):
    row = {
        "t": 1.0 + epoch, "kind": "train_epoch", "epoch": epoch,
        "examples": 640.0, "steps": 10, "train_logloss": 0.6,
        "examples_per_sec": 1000.0, "seconds": 1.0,
        "checkpoint_seconds": 0.0, "preempted": False,
        "phases": {"input_stall": stall, "dispatch": 1.0 - stall},
        "overlapped": {}, "input_stall_frac": stall,
        "step_time_p50": p50,
        "step_time_p90": p90 if p90 is not None else p50 * 1.1,
        "step_time_p99": p99 if p99 is not None else p50 * 1.2,
    }
    if rank is not None:
        row["rank"] = rank
    return row


def _run_header(rank, t0=1000.0):
    return {
        "t": 0.0, "kind": "run_start", "run_id": f"r{rank}",
        "config_digest": "abc", "rank": rank, "num_hosts": 2,
        "time_unix": t0, "hostname": f"host{rank}", "pid": 100 + rank,
    }


def test_obs_merge_ranks_and_aligns_time(tmp_path):
    """`obs merge`: per-host files combine into one rank-tagged stream
    whose rows carry absolute time (header time_unix + t) and sort by
    it; the merged file still validates."""
    from xflow_tpu.obs.__main__ import main
    from xflow_tpu.obs.schema import validate_rows

    a, b = tmp_path / "m-r0.jsonl", tmp_path / "m-r1.jsonl"
    a.write_text("\n".join(json.dumps(r) for r in [
        _run_header(0, t0=1000.0), _epoch_row(0), _epoch_row(1),
    ]) + "\n")
    b.write_text("\n".join(json.dumps(r) for r in [
        _run_header(1, t0=1000.5), _epoch_row(0), _epoch_row(1),
    ]) + "\n")
    merged = tmp_path / "merged.jsonl"
    assert main(["merge", str(a), str(b), "--out", str(merged)]) == 0
    rows = [json.loads(l) for l in merged.read_text().splitlines()]
    assert len(rows) == 6
    assert validate_rows(rows) == []
    assert all("rank" in r and "time_unix" in r for r in rows)
    times = [r["time_unix"] for r in rows]
    assert times == sorted(times)
    # rank-1 rows interleave by wall-clock, not file order
    assert [r["rank"] for r in rows] == [0, 1, 0, 1, 0, 1]


def test_doctor_flags_straggler_rank(tmp_path, capsys):
    """ISSUE 4 acceptance: a two-rank merged fixture where one rank's
    step times are ~2x the other's makes `doctor` call out the slow
    rank as a straggler."""
    from xflow_tpu.obs.__main__ import main

    a, b = tmp_path / "m-r0.jsonl", tmp_path / "m-r1.jsonl"
    a.write_text("\n".join(json.dumps(r) for r in [
        _run_header(0),
        _epoch_row(0, p50=0.002), _epoch_row(1, p50=0.002),
    ]) + "\n")
    b.write_text("\n".join(json.dumps(r) for r in [
        _run_header(1, t0=1000.2),
        _epoch_row(0, p50=0.004), _epoch_row(1, p50=0.0042),
    ]) + "\n")
    merged = tmp_path / "merged.jsonl"
    assert main(["merge", str(a), str(b), "--out", str(merged)]) == 0
    capsys.readouterr()
    rc = main(["doctor", str(merged)])
    text = capsys.readouterr().out
    assert rc == 1
    assert "straggler" in text and "rank 1" in text, text
    # balanced ranks stay clean
    b.write_text("\n".join(json.dumps(r) for r in [
        _run_header(1, t0=1000.2),
        _epoch_row(0, p50=0.0021), _epoch_row(1, p50=0.002),
    ]) + "\n")
    assert main(["merge", str(a), str(b), "--out", str(merged)]) == 0
    capsys.readouterr()
    assert main(["doctor", str(merged)]) == 0


def test_doctor_recompile_suspicion_and_degraded_bench(tmp_path, capsys):
    """Bimodal step times (p99 >> p50, p90 near p50) past epoch 0 read
    as recompile suspicion; a bench artifact with degraded: true is
    called out."""
    from xflow_tpu.obs.__main__ import main

    m = tmp_path / "m.jsonl"
    m.write_text("\n".join(json.dumps(r) for r in [
        _run_header(0),
        _epoch_row(0, p50=0.002),  # warmup epoch: exempt however it looks
        # p99 60ms vs p50 2ms: an unmistakable recompile-scale spike,
        # comfortably past the BIMODAL_MIN_EXCESS_S noise floor
        _epoch_row(1, p50=0.002, p90=0.0022, p99=0.06),
    ]) + "\n")
    bench = tmp_path / "BENCH_x.json"
    bench.write_text(json.dumps({
        "parsed": {
            "metric": "x_train_examples_per_sec", "value": 100.0,
            "degraded": True, "backend": "cpu",
            "last_good_artifact": "docs/artifacts/a.json",
        }
    }))
    rc = main(["doctor", str(m), "--bench", str(bench)])
    text = capsys.readouterr().out
    assert rc == 1
    assert "recompile_suspicion" in text, text
    assert "degraded_bench" in text, text
    # smooth step times + healthy bench: clean
    m.write_text("\n".join(json.dumps(r) for r in [
        _run_header(0), _epoch_row(0), _epoch_row(1),
    ]) + "\n")
    bench.write_text(json.dumps({"parsed": {
        "metric": "x", "value": 100.0, "degraded": False,
    }}))
    capsys.readouterr()
    assert main(["doctor", str(m), "--bench", str(bench)]) == 0


def test_doctor_warmup_exemption_survives_merge(tmp_path, capsys):
    """Regression: in a merged stream both hosts' run_start headers
    sort before every epoch row, so run membership must come from the
    merge's rank/run_id tags — EACH host's first (compile-spiky) epoch
    stays exempt from recompile suspicion, not just one."""
    from xflow_tpu.obs.__main__ import main

    spiky = dict(p50=0.002, p90=0.0022, p99=0.05)
    a, b = tmp_path / "m-r0.jsonl", tmp_path / "m-r1.jsonl"
    a.write_text("\n".join(json.dumps(r) for r in [
        _run_header(0), _epoch_row(0, **spiky), _epoch_row(1),
    ]) + "\n")
    b.write_text("\n".join(json.dumps(r) for r in [
        _run_header(1, t0=1000.2), _epoch_row(0, **spiky), _epoch_row(1),
    ]) + "\n")
    merged = tmp_path / "merged.jsonl"
    assert main(["merge", str(a), str(b), "--out", str(merged)]) == 0
    capsys.readouterr()
    rc = main(["doctor", str(merged)])
    text = capsys.readouterr().out
    assert "recompile_suspicion" not in text, text
    assert rc == 0


def test_compare_fail_on_regress(tmp_path, capsys):
    """Satellite: `obs compare --fail-on-regress FRAC` exits 3 when B
    fell more than FRAC below A — for bench artifacts and metrics
    files alike."""
    from xflow_tpu.obs.__main__ import main

    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps({"parsed": {"metric": "m", "value": 1000.0}}))
    b.write_text(json.dumps({"parsed": {"metric": "m", "value": 800.0}}))
    assert main(["compare", str(a), str(b)]) == 0  # no flag: report only
    capsys.readouterr()
    assert main([
        "compare", "--fail-on-regress", "0.1", str(a), str(b)
    ]) == 3
    err = capsys.readouterr().err
    assert "REGRESS" in err
    # within tolerance passes, and improvement always passes
    assert main([
        "compare", "--fail-on-regress", "0.25", str(a), str(b)
    ]) == 0
    capsys.readouterr()
    assert main([
        "compare", "--fail-on-regress", "0.1", str(b), str(a)
    ]) == 0


def test_check_doctor_smoke_script():
    """Tier-1 wiring for scripts/check_doctor_smoke.py: the toy
    pipeline with the watchdog armed stays trip-free and `obs doctor`
    reports clean."""
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "check_doctor_smoke.py")],
        capture_output=True,
        text=True,
        timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout


def test_check_bench_regress_script():
    """Tier-1 wiring for scripts/check_bench_regress.py: warn-only by
    default (degraded containers must not hard-fail CI), strict mode
    gates."""
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "check_bench_regress.py")],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "comparing latest" in proc.stdout or "SKIP" in proc.stdout


def _bench_artifact(path, value, degraded=False):
    row = {
        "metric": "e2e_packed_examples_per_sec",
        "value": value,
        "backend": "cpu" if degraded else "tpu",
    }
    if degraded:
        row["degraded"] = True
    with open(path, "w") as f:
        json.dump({"parsed": row}, f)


def test_bench_regress_degraded_baseline_skipped(tmp_path, capsys):
    """Baseline selection contract (BENCH_r05 is committed degraded):
    degraded rounds never become the bar — the best NON-degraded prior
    does — and the LATEST artifact is always the one under comparison,
    so a new bench (the store bench, r06+) lands against the right
    prior even when the round before it was a broken container."""
    import scripts.check_bench_regress as cbr

    # r01 good (the true bar), r02 degraded with an absurd value that
    # would fail any honest comparison, r03 = the latest under test
    _bench_artifact(tmp_path / "BENCH_r01.json", 100.0)
    _bench_artifact(tmp_path / "BENCH_r02.json", 99999.0, degraded=True)
    _bench_artifact(tmp_path / "BENCH_r03.json", 95.0)
    rc = cbr.main(["--root", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "BENCH_r02" not in out.split("comparing latest")[1].split(":")[0]
    assert "best prior" in out and "BENCH_r01.json" in out
    assert "BENCH_r03.json" in out.split("comparing latest")[1]

    # a real regression against the non-degraded bar: warn-only by
    # default, gating under --strict
    _bench_artifact(tmp_path / "BENCH_r03.json", 50.0)
    assert cbr.main(["--root", str(tmp_path)]) == 0
    err = capsys.readouterr().err
    assert "WARN" in err and "regression" in err
    assert cbr.main(["--root", str(tmp_path), "--strict"]) == 1

    # every prior degraded: fall back rather than skip silently
    _bench_artifact(tmp_path / "BENCH_r01.json", 100.0, degraded=True)
    _bench_artifact(tmp_path / "BENCH_r03.json", 99000.0)
    rc = cbr.main(["--root", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "every prior bench artifact is degraded" in out


def test_doctor_shed_storm_and_canary_stuck(tmp_path, capsys):
    """Serving-tier forensics (ISSUE 10): a serve_shed window where
    admission control rejected most traffic reads as shed_storm and is
    blamed on capacity — explicitly naming any serve_queue_stall trips
    as the same condition — and a rollout stream that ends on its
    open-rollout heartbeat reads as canary_stuck.  A resolved rollout
    and a quiet shed window stay clean."""
    from xflow_tpu.obs.__main__ import main

    def shed_row(frac, total):
        return {
            "t": 2.0, "kind": "serve_shed", "admitted": total * 2,
            "shed_total": total, "shed_frac": frac,
            "by_cause": {"queue_age": total}, "errors": 0,
            "depth": 12, "queue_age_s": 0.3,
        }

    def rollout_row(event):
        return {
            "t": 3.0, "kind": "rollout", "event": event,
            "from_digest": "aaa", "to_digest": "bbb",
            "canary_frac": 0.25, "canary_requests": 40,
            "canary_errors": 0, "detail": "",
        }

    stall = {
        "t": 1.0, "kind": "health", "cause": "serve_queue_stall",
        "channel": "serve", "silence_seconds": 2.0,
        "threshold_seconds": 0.5, "detail": "batch", "channels": {},
    }
    m = tmp_path / "storm.jsonl"
    m.write_text("\n".join(json.dumps(r) for r in [
        _run_header(0), stall, shed_row(0.8, 80),
        rollout_row("begin"), rollout_row("canary"),
    ]) + "\n")
    rc = main(["doctor", str(m)])
    text = capsys.readouterr().out
    assert rc == 1
    assert "shed_storm" in text and "80%" in text
    assert "same capacity condition" in text  # not misread as a queue bug
    assert "canary_stuck" in text and "'canary'" in text

    # resolved rollout + sub-threshold shedding: serving checks clean
    m.write_text("\n".join(json.dumps(r) for r in [
        _run_header(0), shed_row(0.02, 4),
        rollout_row("begin"), rollout_row("commit"),
    ]) + "\n")
    assert main(["doctor", str(m)]) == 0
    text = capsys.readouterr().out
    # finding-code form: the tmp dir name itself contains "shed_storm"
    assert "shed_storm:" not in text and "canary_stuck:" not in text
