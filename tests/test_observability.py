"""Structured metrics JSONL + profiler trace hooks (SURVEY §5 gaps),
and the obs subsystem (ISSUE 1): span tracer, phase accounting,
pipeline-health metrics, schema, summarize/compare toolchain."""

import json
import os
import sys
import time

import pytest

from xflow_tpu.config import Config
from xflow_tpu.trainer import Trainer


def test_metrics_jsonl(toy_dataset, tmp_path):
    out = tmp_path / "metrics.jsonl"
    cfg = Config(
        train_path=toy_dataset.train_prefix,
        test_path=toy_dataset.test_prefix,
        model="lr",
        epochs=2,
        batch_size=64,
        table_size_log2=14,
        max_nnz=24,
        num_devices=1,
        metrics_out=str(out),
    )
    t = Trainer(cfg)
    t.train()
    t.evaluate()
    rows = [json.loads(line) for line in out.read_text().splitlines()]
    kinds = [r["kind"] for r in rows]
    assert kinds.count("train_epoch") == 2
    assert kinds.count("eval") == 1
    epoch_row = next(r for r in rows if r["kind"] == "train_epoch")
    for field in ("examples", "steps", "train_logloss", "examples_per_sec", "t"):
        assert field in epoch_row
    eval_row = next(r for r in rows if r["kind"] == "eval")
    assert 0.0 <= eval_row["auc"] <= 1.0


def test_eval_every_epochs(toy_dataset, tmp_path):
    """--eval-every N runs mid-training evals (convergence curves,
    VERDICT round 3 item 3); each eval record carries its epoch."""
    out = tmp_path / "metrics.jsonl"
    cfg = Config(
        train_path=toy_dataset.train_prefix,
        test_path=toy_dataset.test_prefix,
        model="lr",
        epochs=4,
        batch_size=64,
        table_size_log2=14,
        max_nnz=24,
        num_devices=1,
        metrics_out=str(out),
        eval_every_epochs=2,
    )
    t = Trainer(cfg)
    t.train()
    t.evaluate()  # the caller's final eval (train.py main does this)
    rows = [json.loads(line) for line in out.read_text().splitlines()]
    evals = [r for r in rows if r["kind"] == "eval"]
    # mid-run at epoch 2 (epoch 4 == cfg.epochs is left to the caller)
    # plus the final one
    assert [e["epoch"] for e in evals] == [2, 4]


def test_profile_trace_written(toy_dataset, tmp_path):
    prof = tmp_path / "prof"
    cfg = Config(
        train_path=toy_dataset.train_prefix,
        test_path=toy_dataset.test_prefix,
        model="lr",
        batch_size=64,
        table_size_log2=14,
        max_nnz=24,
        num_devices=1,
        epochs=3,
        profile_dir=str(prof),
        # larger than one epoch's step count: the trigger must carry
        # across epochs (global-step based, not per-epoch)
        profile_start_step=8,
        profile_steps=2,
    )
    t = Trainer(cfg)
    t.train()
    # jax writes plugins/profile/<ts>/*.pb under the trace dir
    produced = list(prof.rglob("*"))
    assert any(p.is_file() for p in produced), produced


# -- ISSUE 1: obs subsystem -------------------------------------------------


def _toy_cfg(toy_dataset, **overrides):
    base = dict(
        train_path=toy_dataset.train_prefix,
        test_path=toy_dataset.test_prefix,
        model="lr",
        epochs=2,
        batch_size=64,
        table_size_log2=14,
        max_nnz=24,
        num_devices=1,
    )
    base.update(overrides)
    return Config(**base)


def test_span_tracer_nesting_and_export(tmp_path):
    """Nested spans land as Chrome 'X' events whose intervals nest, and
    the export is loadable trace-event JSON."""
    from xflow_tpu.obs.trace import SpanTracer

    tr = SpanTracer(capacity=16, rank=3)
    with tr.span("outer", {"epoch": 1}):
        with tr.span("inner"):
            pass
        with tr.span("inner"):
            pass
    events = tr.events()
    assert [e["name"] for e in events] == ["inner", "inner", "outer"]
    outer = events[-1]
    for inner in events[:-1]:
        assert inner["ts"] >= outer["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    assert all(e["ph"] == "X" and e["pid"] == 3 for e in events)
    assert outer["args"]["epoch"] == 1

    path = tmp_path / "trace.json"
    tr.export_chrome(str(path))
    doc = json.loads(path.read_text())
    assert isinstance(doc["traceEvents"], list)
    for e in doc["traceEvents"]:
        for key in ("name", "ph", "ts", "dur", "pid", "tid"):
            assert key in e


def test_span_tracer_ring_buffer():
    from xflow_tpu.obs.trace import SpanTracer

    tr = SpanTracer(capacity=8)
    for i in range(20):
        with tr.span(f"s{i}"):
            pass
    events = tr.events()
    assert len(events) == 8  # only the newest capacity spans survive
    assert events[-1]["name"] == "s19"


def test_null_obs_is_shared_noop():
    """Disabled obs allocates nothing per step: phase()/span() return
    the one shared no-op object and the registry snapshot is empty."""
    from xflow_tpu.obs import NULL_OBS

    s1 = NULL_OBS.phase("a")
    s2 = NULL_OBS.phase("b")
    assert s1 is s2 is NULL_OBS.span("c")
    with s1:
        pass
    NULL_OBS.counter("x", 1.0)
    NULL_OBS.observe("y", 2.0)
    snap = NULL_OBS.registry.snapshot()
    assert snap.counters == {} and snap.hists == {}
    assert NULL_OBS.tracer.events() == []


def test_registry_percentiles_and_phases():
    from xflow_tpu.obs.registry import MetricsRegistry

    reg = MetricsRegistry()
    for v in range(1, 101):
        reg.observe("lat", float(v))
    reg.counter_add("phase.parse", 1.5)
    reg.counter_add("phase.parse", 0.5)
    reg.gauge_set("depth", 3.0)
    snap = reg.snapshot(reset=True)
    h = snap.hists["lat"]
    assert h["count"] == 100
    assert abs(h["p50"] - 50) <= 2 and abs(h["p99"] - 99) <= 2
    assert snap.phase_seconds() == {"parse": 2.0}
    assert snap.gauges["depth"] == 3.0
    assert reg.snapshot().counters == {}  # reset cleared it


def test_run_start_header_splits_runs(toy_dataset, tmp_path):
    """Append mode + run_start delimiter: two runs into one file never
    merge in summarize (ISSUE 1 satellite)."""
    from xflow_tpu.obs.summary import load_runs

    out = tmp_path / "m.jsonl"
    for epochs in (1, 1):
        with Trainer(_toy_cfg(toy_dataset, epochs=epochs, metrics_out=str(out))) as t:
            t.train()
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    headers = [r for r in rows if r["kind"] == "run_start"]
    assert len(headers) == 2
    for h in headers:
        for field in ("run_id", "config_digest", "rank", "num_hosts"):
            assert field in h
    runs = load_runs(str(out))
    assert len(runs) == 2
    assert all(len(r.epochs) == 1 for r in runs)


def test_epoch_phase_accounting(toy_dataset, tmp_path):
    """Main-thread phases are disjoint and account for most of the
    epoch wall-clock; overlapped worker phases are reported separately
    (the summarize >= 90% contract is checked run-level by
    scripts/check_metrics_schema.py)."""
    out = tmp_path / "m.jsonl"
    with Trainer(_toy_cfg(toy_dataset, metrics_out=str(out))) as t:
        t.train()
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    epochs = [r for r in rows if r["kind"] == "train_epoch"]
    assert len(epochs) == 2
    wall = sum(e["seconds"] for e in epochs)
    accounted = sum(sum(e["phases"].values()) for e in epochs)
    assert accounted <= wall * 1.01  # exclusive phases can't exceed wall
    assert accounted >= wall * 0.8, (accounted, wall, epochs)
    for e in epochs:
        assert "input_stall" in e["phases"] and "dispatch" in e["phases"]
        # single-host transfer-ahead: h2d rides the worker thread
        assert "h2d" in e["overlapped"]
        assert 0.0 <= e["input_stall_frac"] <= 1.0
        assert e["step_time_p50"] <= e["step_time_p99"]
        assert e["checkpoint_seconds"] == 0.0  # no checkpointing here


def test_stall_accounting_slow_loader(toy_dataset, tmp_path, monkeypatch):
    """An artificially slow input pipeline shows up as input_stall
    seconds, not as deflated mystery throughput."""
    delay = 0.02
    orig = Trainer.iter_train_batches

    def slow(self, *a, **kw):
        for item in orig(self, *a, **kw):
            time.sleep(delay)
            yield item

    monkeypatch.setattr(Trainer, "iter_train_batches", slow)
    out = tmp_path / "m.jsonl"
    with Trainer(
        _toy_cfg(toy_dataset, epochs=1, metrics_out=str(out))
    ) as t:
        t.train()
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    e = next(r for r in rows if r["kind"] == "train_epoch")
    # every batch was delayed on the path the main thread blocks on
    assert e["phases"]["input_stall"] >= e["steps"] * delay * 0.7
    assert e["input_stall_frac"] >= 0.3, e


def test_checkpoint_seconds_separated(toy_dataset, tmp_path):
    """Satellite: checkpoint-save time is reported as its own field and
    excluded from examples_per_sec instead of silently deflating it."""
    out = tmp_path / "m.jsonl"
    ckpt = tmp_path / "ckpt"
    with Trainer(_toy_cfg(
        toy_dataset, epochs=1, metrics_out=str(out),
        checkpoint_dir=str(ckpt), checkpoint_every_steps=3,
    )) as t:
        t.train()
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    e = next(r for r in rows if r["kind"] == "train_epoch")
    assert e["checkpoint_seconds"] > 0.0
    assert "checkpoint" in e["phases"]
    # throughput uses compute time: seconds minus checkpoint time
    # checkpoint_seconds is rounded in the record; allow that slack
    expect = e["examples"] / (e["seconds"] - e["checkpoint_seconds"])
    assert abs(e["examples_per_sec"] - expect) / expect < 1e-3


def test_schema_covers_all_emitted_kinds(toy_dataset, tmp_path):
    """Every emitted JSONL row carries its kind's required fields, and
    every kind the pipeline emits is in the schema."""
    from xflow_tpu.obs.schema import SCHEMA, validate_rows

    out = tmp_path / "m.jsonl"
    ckpt = tmp_path / "ckpt"
    with Trainer(_toy_cfg(
        toy_dataset, metrics_out=str(out),
        checkpoint_dir=str(ckpt), checkpoint_every_steps=4,
        eval_every_epochs=1,
    )) as t:
        t.train()
        t.evaluate()
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    assert validate_rows(rows) == []
    kinds = {r["kind"] for r in rows}
    assert kinds <= set(SCHEMA)
    assert {"run_start", "train_epoch", "eval", "shard"} <= kinds


def test_trainer_trace_export(toy_dataset, tmp_path):
    """Config.obs_trace_out: the trainer writes a loadable Chrome trace
    containing the span taxonomy's hot-path names."""
    trace = tmp_path / "trace.json"
    with Trainer(_toy_cfg(
        toy_dataset, epochs=1, obs_trace_out=str(trace)
    )) as t:
        t.train()
    doc = json.loads(trace.read_text())
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"train_epoch", "dispatch", "input_stall", "h2d"} <= names
    steps = [e["args"]["step"] for e in doc["traceEvents"] if "args" in e]
    assert steps and all(isinstance(s, int) for s in steps)


def test_summarize_and_compare_cli(toy_dataset, tmp_path, capsys):
    from xflow_tpu.obs.__main__ import main

    out = tmp_path / "m.jsonl"
    with Trainer(_toy_cfg(toy_dataset, metrics_out=str(out))) as t:
        t.train()
        t.evaluate()
    assert main(["summarize", str(out)]) == 0
    text = capsys.readouterr().out
    for token in ("phase", "accounted", "input_stall", "eval epoch"):
        assert token in text, text
    assert main(["compare", str(out), str(out)]) == 0
    text = capsys.readouterr().out
    assert "examples/sec" in text and "input_stall_frac" in text
    assert main(["validate", str(out)]) == 0


def test_metrics_logger_closes_on_exception(toy_dataset, tmp_path, monkeypatch):
    """Satellite: the logger is closed (rows flushed, file released) when
    training dies mid-run."""
    out = tmp_path / "m.jsonl"
    t = Trainer(_toy_cfg(toy_dataset, metrics_out=str(out)))

    def boom(*a, **kw):
        raise RuntimeError("loader died")

    monkeypatch.setattr(Trainer, "iter_train_batches", boom)
    with pytest.raises(RuntimeError, match="loader died"):
        t.train()
    assert t.metrics_logger.closed
    # late logs after close are swallowed, not crashes
    t.metrics_logger.log("eval", {})
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    assert [r["kind"] for r in rows] == ["run_start"]


def test_check_metrics_schema_script():
    """The CI lint (scripts/check_metrics_schema.py) passes on the toy
    pipeline — run as a subprocess exactly as CI would."""
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "check_metrics_schema.py")],
        capture_output=True,
        text=True,
        timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout
