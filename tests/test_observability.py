"""Structured metrics JSONL + profiler trace hooks (SURVEY §5 gaps)."""

import json

from xflow_tpu.config import Config
from xflow_tpu.trainer import Trainer


def test_metrics_jsonl(toy_dataset, tmp_path):
    out = tmp_path / "metrics.jsonl"
    cfg = Config(
        train_path=toy_dataset.train_prefix,
        test_path=toy_dataset.test_prefix,
        model="lr",
        epochs=2,
        batch_size=64,
        table_size_log2=14,
        max_nnz=24,
        num_devices=1,
        metrics_out=str(out),
    )
    t = Trainer(cfg)
    t.train()
    t.evaluate()
    rows = [json.loads(line) for line in out.read_text().splitlines()]
    kinds = [r["kind"] for r in rows]
    assert kinds.count("train_epoch") == 2
    assert kinds.count("eval") == 1
    epoch_row = next(r for r in rows if r["kind"] == "train_epoch")
    for field in ("examples", "steps", "train_logloss", "examples_per_sec", "t"):
        assert field in epoch_row
    eval_row = next(r for r in rows if r["kind"] == "eval")
    assert 0.0 <= eval_row["auc"] <= 1.0


def test_eval_every_epochs(toy_dataset, tmp_path):
    """--eval-every N runs mid-training evals (convergence curves,
    VERDICT round 3 item 3); each eval record carries its epoch."""
    out = tmp_path / "metrics.jsonl"
    cfg = Config(
        train_path=toy_dataset.train_prefix,
        test_path=toy_dataset.test_prefix,
        model="lr",
        epochs=4,
        batch_size=64,
        table_size_log2=14,
        max_nnz=24,
        num_devices=1,
        metrics_out=str(out),
        eval_every_epochs=2,
    )
    t = Trainer(cfg)
    t.train()
    t.evaluate()  # the caller's final eval (train.py main does this)
    rows = [json.loads(line) for line in out.read_text().splitlines()]
    evals = [r for r in rows if r["kind"] == "eval"]
    # mid-run at epoch 2 (epoch 4 == cfg.epochs is left to the caller)
    # plus the final one
    assert [e["epoch"] for e in evals] == [2, 4]


def test_profile_trace_written(toy_dataset, tmp_path):
    prof = tmp_path / "prof"
    cfg = Config(
        train_path=toy_dataset.train_prefix,
        test_path=toy_dataset.test_prefix,
        model="lr",
        batch_size=64,
        table_size_log2=14,
        max_nnz=24,
        num_devices=1,
        epochs=3,
        profile_dir=str(prof),
        # larger than one epoch's step count: the trigger must carry
        # across epochs (global-step based, not per-epoch)
        profile_start_step=8,
        profile_steps=2,
    )
    t = Trainer(cfg)
    t.train()
    # jax writes plugins/profile/<ts>/*.pb under the trace dir
    produced = list(prof.rglob("*"))
    assert any(p.is_file() for p in produced), produced
