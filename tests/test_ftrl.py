"""FTRL-proximal golden tests: the jitted row update must reproduce the
reference recurrence (ftrl.h:58-74) computed independently in scalar
Python."""

import jax
import jax.numpy as jnp
import numpy as np

from xflow_tpu.optim.ftrl import FTRL
from xflow_tpu.optim.sgd import SGD

ALPHA, BETA, L1, L2 = 5e-2, 1.0, 5e-5, 10.0  # ftrl.h:17-20


def ftrl_scalar(w, n, z, g):
    """Direct transcription of the recurrence as documented in SURVEY §2
    component 3 (independent of the jax implementation).  Computed in
    float32 like the reference's C++ floats (ftrl.h:27-36)."""
    f = np.float32
    w, n, z, g = f(w), f(n), f(z), f(g)
    n_new = f(n + f(g * g))
    sigma = f(f(np.sqrt(n_new) - np.sqrt(n)) / f(ALPHA))
    z_new = f(f(z + g) - f(sigma * w))
    if abs(z_new) <= f(L1):
        w_new = f(0.0)
    else:
        sign = f(1.0) if z_new > 0 else (f(-1.0) if z_new < 0 else f(0.0))
        w_new = f(
            f(f(sign * f(L1)) - z_new)
            / f(f(f(f(BETA) + np.sqrt(n_new)) / f(ALPHA)) + f(L2))
        )
    return w_new, n_new, z_new


def test_ftrl_sequence_golden():
    opt = FTRL(alpha=ALPHA, beta=BETA, lambda1=L1, lambda2=L2)
    rng = np.random.default_rng(1)
    grads = rng.normal(0, 0.3, size=50)
    w = n = z = 0.0
    wj = jnp.zeros((1, 1))
    nj = jnp.zeros((1, 1))
    zj = jnp.zeros((1, 1))
    update = jax.jit(opt.update_rows)
    for g in grads:
        w, n, z = ftrl_scalar(w, n, z, float(g))
        out = update(
            {"param": wj, "n": nj, "z": zj}, jnp.full((1, 1), g, jnp.float32)
        )
        wj, nj, zj = out["param"], out["n"], out["z"]
        assert np.isclose(float(wj[0, 0]), w, rtol=1e-5, atol=1e-6), (w, wj)
        assert np.isclose(float(nj[0, 0]), n, rtol=1e-5)
        assert np.isclose(float(zj[0, 0]), z, rtol=1e-5, atol=1e-6)


def test_ftrl_l1_sparsity():
    # tiny accumulated |z| <= lambda1 must give exactly w = 0
    opt = FTRL(alpha=ALPHA, beta=BETA, lambda1=0.5, lambda2=L2)
    out = opt.update_rows(
        {
            "param": jnp.zeros((1, 1)),
            "n": jnp.zeros((1, 1)),
            "z": jnp.zeros((1, 1)),
        },
        jnp.full((1, 1), 0.1),
    )
    assert float(out["param"][0, 0]) == 0.0
    assert float(out["z"][0, 0]) != 0.0


def test_ftrl_zero_grad_is_idempotent():
    """g=0 (padding) must recompute the same w from (z, n) — the property
    the sparse-apply padding safety relies on (ops/sparse.py)."""
    opt = FTRL()
    rng = np.random.default_rng(2)
    rows = {
        "param": jnp.zeros((8, 3)),
        "n": jnp.asarray(np.abs(rng.normal(1, 1, (8, 3))), jnp.float32),
        "z": jnp.asarray(rng.normal(0, 1, (8, 3)), jnp.float32),
    }
    once = opt.update_rows(rows, jnp.zeros((8, 3)))
    twice = opt.update_rows(once, jnp.zeros((8, 3)))
    np.testing.assert_allclose(once["param"], twice["param"], rtol=1e-6)
    np.testing.assert_array_equal(once["n"], rows["n"])
    np.testing.assert_array_equal(once["z"], rows["z"])


def test_sgd_update():
    opt = SGD(lr=0.001)  # sgd.h:16
    out = opt.update_rows(
        {"param": jnp.ones((2, 1))}, jnp.asarray([[1.0], [-2.0]])
    )
    np.testing.assert_allclose(out["param"], [[1.0 - 0.001], [1.0 + 0.002]])
