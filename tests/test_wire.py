"""Compact wire format (Config.wire_mode): training and prediction must
be bit-identical to the full format — compaction only changes what
crosses the host->device link, never the math."""

import numpy as np
import pytest
import jax

from xflow_tpu.config import Config
from xflow_tpu.trainer import Trainer


def _tables(t):
    return jax.tree.map(
        lambda a: np.asarray(jax.device_get(a)), t.state["tables"]
    )


@pytest.mark.parametrize("model", ["lr", "fm"])
@pytest.mark.parametrize("hot", [False, True])
def test_compact_equals_full(toy_dataset, model, hot, tmp_path):
    base = dict(
        model=model,
        train_path=toy_dataset.train_prefix,
        test_path=toy_dataset.test_prefix,
        epochs=2,
        batch_size=64,
        table_size_log2=14,
        max_nnz=24,
        num_devices=1,
    )
    if hot:
        base.update(
            hot_size_log2=8, hot_nnz=8, freq_sample_mib=1,
            checkpoint_dir=str(tmp_path / f"{model}-ck"),
        )
    t_full = Trainer(Config(wire_mode="full", **base))
    assert not t_full.step.compact_wire
    t_full.train()
    r_full = t_full.evaluate()

    t_cmp = Trainer(Config(wire_mode="compact", **base))
    assert t_cmp.step.compact_wire
    t_cmp.train()
    r_cmp = t_cmp.evaluate()

    # not bit-exact: the two wire formats compile to different XLA
    # programs (mask*mask vs vals*mask fuses differently), so reduction
    # orders may differ at float32 epsilon scale — but nothing more
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7),
        _tables(t_full),
        _tables(t_cmp),
    )
    np.testing.assert_allclose(r_full["logloss"], r_cmp["logloss"], rtol=1e-5)
    np.testing.assert_allclose(r_full["auc"], r_cmp["auc"], rtol=1e-5)


def test_compact_rejected_for_slot_models(toy_dataset):
    with pytest.raises(ValueError, match="compact"):
        Trainer(
            Config(
                model="mvm",
                wire_mode="compact",
                train_path=toy_dataset.train_prefix,
                batch_size=64,
                table_size_log2=14,
                num_devices=1,
            )
        )


def test_auto_picks_compact_only_when_valid(toy_dataset):
    common = dict(
        train_path=toy_dataset.train_prefix,
        batch_size=64,
        table_size_log2=14,
        num_devices=1,
    )
    assert Trainer(Config(model="lr", **common)).step.compact_wire
    assert not Trainer(Config(model="mvm", **common)).step.compact_wire
    # numeric mode carries real values -> full wire even for lr
    assert not Trainer(
        Config(model="lr", hash_mode=False, **common)
    ).step.compact_wire


def test_compact_guards_value_batches():
    """User-built batches with fractional vals/weights must be refused,
    not silently binarized."""
    from xflow_tpu.io.batch import Batch
    from xflow_tpu.parallel.step import batch_to_compact

    b = Batch(
        keys=np.zeros((2, 3), np.int32),
        slots=np.zeros((2, 3), np.int32),
        vals=np.asarray([[0.5, 1, 1], [1, 1, 1]], np.float32),
        mask=np.ones((2, 3), np.float32),
        labels=np.zeros(2, np.float32),
        weights=np.ones(2, np.float32),
    )
    with pytest.raises(ValueError, match="binary features"):
        batch_to_compact(b)
    b2 = Batch(
        keys=np.zeros((2, 3), np.int32),
        slots=np.zeros((2, 3), np.int32),
        vals=np.ones((2, 3), np.float32),
        mask=np.ones((2, 3), np.float32),
        labels=np.zeros(2, np.float32),
        weights=np.asarray([1.0, 0.25], np.float32),
    )
    with pytest.raises(ValueError, match="0/1"):
        batch_to_compact(b2)
