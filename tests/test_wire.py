"""Compact wire format (Config.wire_mode): training and prediction must
be bit-identical to the full format — compaction only changes what
crosses the host->device link, never the math."""

import numpy as np
import pytest
import jax

from xflow_tpu.config import Config
from xflow_tpu.trainer import Trainer


def _tables(t):
    return jax.tree.map(
        lambda a: np.asarray(jax.device_get(a)), t.state["tables"]
    )


@pytest.mark.parametrize(
    "model", ["lr", "fm", "mvm", "ffm", "wide_deep"]
)
@pytest.mark.parametrize("hot", [False, True])
def test_compact_equals_full(toy_dataset, model, hot, tmp_path):
    base = dict(
        model=model,
        train_path=toy_dataset.train_prefix,
        test_path=toy_dataset.test_prefix,
        epochs=2,
        batch_size=64,
        table_size_log2=14,
        max_nnz=24,
        max_fields=12,
        num_devices=1,
        emb_dim=4,
        hidden_dim=8,
        ffm_v_dim=2,
    )
    if hot:
        base.update(
            hot_size_log2=8, hot_nnz=8, freq_sample_mib=1,
            checkpoint_dir=str(tmp_path / f"{model}-ck"),
        )
    t_full = Trainer(Config(wire_mode="full", **base))
    assert not t_full.step.compact_wire
    t_full.train()
    r_full = t_full.evaluate()

    t_cmp = Trainer(Config(wire_mode="compact", **base))
    assert t_cmp.step.compact_wire
    t_cmp.train()
    r_cmp = t_cmp.evaluate()

    # not bit-exact: the two wire formats compile to different XLA
    # programs (mask*mask vs vals*mask fuses differently), so reduction
    # orders may differ at float32 epsilon scale — but nothing more
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7),
        _tables(t_full),
        _tables(t_cmp),
    )
    np.testing.assert_allclose(r_full["logloss"], r_cmp["logloss"], rtol=1e-5)
    np.testing.assert_allclose(r_full["auc"], r_cmp["auc"], rtol=1e-5)


def test_compact_rejected_when_slots_exceed_u8(toy_dataset):
    """Slot-reading models need max_fields <= 255 for the u8 slots
    plane's clamp to stay inside the ignored range."""
    with pytest.raises(ValueError, match="max_fields"):
        Trainer(
            Config(
                model="mvm",
                wire_mode="compact",
                max_fields=300,
                train_path=toy_dataset.train_prefix,
                batch_size=64,
                table_size_log2=14,
                num_devices=1,
            )
        )


def test_auto_picks_compact_only_when_valid(toy_dataset):
    common = dict(
        train_path=toy_dataset.train_prefix,
        batch_size=64,
        table_size_log2=14,
        num_devices=1,
    )
    assert Trainer(Config(model="lr", **common)).step.compact_wire
    # slot-reading models ride compact too (u8 slots plane) ...
    assert Trainer(Config(model="mvm", **common)).step.compact_wire
    # ... unless their field space outgrows u8
    assert not Trainer(
        Config(model="mvm", max_fields=256, **common)
    ).step.compact_wire
    # numeric mode carries real values -> full wire even for lr
    assert not Trainer(
        Config(model="lr", hash_mode=False, **common)
    ).step.compact_wire


def test_u8_slot_clamp_matches_full_wire():
    """A slot beyond 255 clamps to 255 on the compact wire — still >=
    max_fields, so the model ignores it exactly as the full wire does
    (the lossless-clamp invariant compact_wire_np relies on)."""
    from xflow_tpu.io.batch import make_batch
    from xflow_tpu.models import make_model
    from xflow_tpu.optim import make_optimizer
    from xflow_tpu.parallel.mesh import make_mesh
    from xflow_tpu.parallel.step import TrainStep, init_state

    rng = np.random.default_rng(0)
    keys = rng.integers(0, 1 << 10, (8, 6)).astype(np.int32)
    slots = rng.integers(0, 8, (8, 6)).astype(np.int32)
    slots[0, 0] = 300  # out of u8 range AND >= max_fields
    slots[1, 1] = 200  # in u8 range but >= max_fields
    slots[2, 2] = -256  # negative: a plain u8 cast would wrap to 0
    slots[3, 3] = -250  # negative: would wrap to 6 (a live field)
    vals = np.ones((8, 6), np.float32)
    mask = np.ones((8, 6), np.float32)
    labels = (rng.uniform(size=8) < 0.5).astype(np.float32)
    weights = np.ones(8, np.float32)
    batch = make_batch(keys, slots, vals, mask, labels, weights)

    out = {}
    for wire in ("full", "compact"):
        cfg = Config(
            model="mvm", batch_size=8, table_size_log2=10, max_nnz=6,
            max_fields=8, num_devices=1, wire_mode=wire,
        )
        mesh = make_mesh(1)
        model, opt = make_model(cfg), make_optimizer(cfg)
        step = TrainStep(model, opt, cfg, mesh)
        state = init_state(model, opt, cfg, mesh)
        state, _ = step.train(state, step.put_batch(batch))
        out[wire] = np.asarray(
            jax.device_get(state["tables"]["v"]["param"])
        )
    np.testing.assert_allclose(
        out["full"], out["compact"], rtol=1e-5, atol=1e-7
    )


def test_compact_guards_value_batches():
    """User-built batches with fractional vals/weights must be refused,
    not silently binarized."""
    from xflow_tpu.io.batch import Batch
    from xflow_tpu.parallel.step import batch_to_compact

    b = Batch(
        keys=np.zeros((2, 3), np.int32),
        slots=np.zeros((2, 3), np.int32),
        vals=np.asarray([[0.5, 1, 1], [1, 1, 1]], np.float32),
        mask=np.ones((2, 3), np.float32),
        labels=np.zeros(2, np.float32),
        weights=np.ones(2, np.float32),
    )
    with pytest.raises(ValueError, match="binary features"):
        batch_to_compact(b)
    b2 = Batch(
        keys=np.zeros((2, 3), np.int32),
        slots=np.zeros((2, 3), np.int32),
        vals=np.ones((2, 3), np.float32),
        mask=np.ones((2, 3), np.float32),
        labels=np.zeros(2, np.float32),
        weights=np.asarray([1.0, 0.25], np.float32),
    )
    with pytest.raises(ValueError, match="0/1"):
        batch_to_compact(b2)


def test_hot_u16_plane_halves_and_roundtrips():
    """hot_u16 compact wire: the hot-keys plane ships as uint16
    (sentinel 0xFFFF — legal for H <= 2^15, ids can't reach it) at
    half the int32 plane's bytes, and _expand_wire reconstructs
    keys/mask/vals identically to the int32 plane."""
    import jax.numpy as jnp

    from xflow_tpu.io.batch import make_batch
    from xflow_tpu.models import make_model
    from xflow_tpu.optim import make_optimizer
    from xflow_tpu.parallel.mesh import make_mesh
    from xflow_tpu.parallel.step import TrainStep, compact_wire_np

    rng = np.random.default_rng(23)
    b, k = 32, 12
    keys = rng.integers(0, 1 << 12, (b, k)).astype(np.int32)
    keys[:, ::2] = rng.integers(0, 16, (b, 6)).astype(np.int32)  # hot
    slots = rng.integers(0, 8, (b, k)).astype(np.int32)
    mask = (rng.uniform(size=(b, k)) < 0.8).astype(np.float32)
    vals = mask.copy()  # hash mode: vals == 1 on real entries
    labels = (rng.uniform(size=b) < 0.4).astype(np.float32)
    weights = np.ones(b, np.float32)
    batch = make_batch(keys, slots, vals, mask, labels, weights, 1 << 8, 4)

    w16 = compact_wire_np(batch, hot_u16=True)
    w32 = compact_wire_np(batch, hot_u16=False)
    assert w16["hot_ckeys_u16"].dtype == np.uint16
    assert w16["hot_ckeys_u16"].nbytes * 2 == w32["hot_ckeys"].nbytes

    cfg = Config(
        model="lr", batch_size=b, table_size_log2=12, max_nnz=k,
        max_fields=8, num_devices=1, hot_size_log2=8, hot_nnz=4,
    )
    step = TrainStep(
        make_model(cfg), make_optimizer(cfg), cfg, make_mesh(1)
    )
    assert step._hot_u16
    e16 = step._expand_wire({k2: jnp.asarray(v) for k2, v in w16.items()})
    e32 = step._expand_wire({k2: jnp.asarray(v) for k2, v in w32.items()})
    for key in ("hot_keys", "hot_mask", "hot_vals"):
        np.testing.assert_array_equal(
            np.asarray(e16[key]), np.asarray(e32[key]), err_msg=key
        )


def test_hot_u16_disabled_above_sentinel_range():
    """hot_size_log2 = 16 would let a real id collide with the 0xFFFF
    sentinel, so the step must fall back to the int32 hot plane."""
    from xflow_tpu.models import make_model
    from xflow_tpu.optim import make_optimizer
    from xflow_tpu.parallel.mesh import make_mesh
    from xflow_tpu.parallel.step import TrainStep

    cfg = Config(
        model="lr", batch_size=32, table_size_log2=18, max_nnz=8,
        max_fields=8, num_devices=1, hot_size_log2=16, hot_nnz=4,
    )
    step = TrainStep(
        make_model(cfg), make_optimizer(cfg), cfg, make_mesh(1)
    )
    assert not step._hot_u16
