"""Transfer-ahead staging ring under failure (ISSUE 6 satellite).

trainer._transfer_ahead runs put_batch on ring workers; two failure
shapes must stay bounded:

* a worker RAISING mid-ring — the exception must propagate to the
  caller, and Trainer.close() must complete without deadlocking or
  stranding pending futures (the executor joins its in-flight
  put_batch calls, which are bounded host work);
* a ring ABANDONED mid-epoch (preemption break, consumer exception) —
  close() must shut the executor down explicitly instead of leaving
  its threads to the garbage collector (XF006, the _PrefetchIter leak
  class, executor edition).

Thread interleavings are shaken out with a lowered
``sys.setswitchinterval``, alongside the sanitizer-armed lock-stress
fixtures in tests/test_analysis.py.
"""

import sys
import threading
import time

import pytest

from xflow_tpu.config import Config
from xflow_tpu.trainer import Trainer


def _ring_threads() -> set[int]:
    return {
        th.ident
        for th in threading.enumerate()
        if th.name.startswith("ThreadPoolExecutor")
    }


def _wait_no_new_ring_threads(before: set[int], timeout: float = 15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        leaked = _ring_threads() - before
        if not leaked:
            return
        time.sleep(0.05)
    raise AssertionError(
        f"transfer-ahead executor threads leaked: {_ring_threads() - before}"
    )


@pytest.fixture
def trainer(toy_dataset):
    cfg = Config(
        model="lr",
        train_path=toy_dataset.train_prefix,
        batch_size=16,
        table_size_log2=14,
        max_nnz=24,
        num_devices=1,
        epochs=1,
        transfer_ahead_depth=2,
    )
    t = Trainer(cfg)
    yield t
    t.close()


def test_ring_depths_bitwise_equal_batch_streams(trainer):
    """Depths 1, 2 and 4 stage the identical (arrays, shard, resume)
    sequence — the deep ring reorders WORK, never batches, so training
    is bitwise-independent of Config.transfer_ahead_depth."""
    import jax
    import numpy as np

    def collect(depth):
        out = []
        stream = trainer._transfer_ahead(
            trainer.iter_train_batches(), depth=depth
        )
        trainer._live_transfer.add(stream)
        try:
            for arrays, si, resume in stream:
                out.append((si, resume, jax.device_get(arrays)))
        finally:
            trainer._live_transfer.discard(stream)
            stream.close()
        return out

    base = collect(1)
    assert len(base) > 3
    for depth in (2, 4):
        got = collect(depth)
        assert len(got) == len(base)
        for (sa, ra, aa), (sb, rb, ab) in zip(base, got):
            assert (sa, ra) == (sb, rb)
            assert sorted(aa) == sorted(ab)
            for k in aa:
                assert np.array_equal(
                    np.asarray(aa[k]), np.asarray(ab[k])
                ), k


def test_worker_exception_mid_ring_deep(toy_dataset):
    """A worker raising mid-ring at depth 4 (several in-flight futures
    on multiple workers) propagates, close() stays bounded, and no ring
    thread outlives it — the depth-2 contract holds at depth."""
    cfg = Config(
        model="lr",
        train_path=toy_dataset.train_prefix,
        batch_size=16,
        table_size_log2=14,
        max_nnz=24,
        num_devices=1,
        epochs=1,
        transfer_ahead_depth=4,
    )
    t = Trainer(cfg)
    before = _ring_threads()
    orig = t.step.put_batch
    calls = []

    def boom(batch):
        calls.append(1)
        if len(calls) == 5:
            raise RuntimeError("worker exploded mid-deep-ring")
        return orig(batch)

    t.step.put_batch = boom
    try:
        with pytest.raises(RuntimeError, match="mid-deep-ring"):
            t.train_epoch()
        t0 = time.time()
        t.close()
        assert time.time() - t0 < 30, "close() stalled after ring failure"
        _wait_no_new_ring_threads(before)
    finally:
        t.step.put_batch = orig
        t.close()


def test_ring_worker_scaling():
    """_ring_workers: 1 at depth 1, >= 2 once double buffering is
    possible, never more workers than ring slots."""
    from xflow_tpu.trainer import _ring_workers

    assert _ring_workers(1) == 1
    assert _ring_workers(2) == 2
    for depth in (2, 3, 4, 8):
        assert 2 <= _ring_workers(depth) <= depth


def test_worker_exception_mid_ring_no_deadlock(trainer):
    """put_batch raising on a ring worker: train_epoch surfaces the
    exception, close() returns promptly, no executor thread leaks, no
    pending future left stranded."""
    before = _ring_threads()
    orig = trainer.step.put_batch
    calls = []

    def boom(batch):
        calls.append(1)
        if len(calls) == 3:
            raise RuntimeError("worker exploded mid-ring")
        return orig(batch)

    trainer.step.put_batch = boom
    old_interval = sys.getswitchinterval()
    sys.setswitchinterval(1e-5)  # shake out interleavings
    try:
        with pytest.raises(RuntimeError, match="mid-ring"):
            trainer.train_epoch()
    finally:
        sys.setswitchinterval(old_interval)
    t0 = time.time()
    trainer.close()
    assert time.time() - t0 < 30, "close() stalled after ring failure"
    _wait_no_new_ring_threads(before)
    # a later epoch on the same trainer still works (state not wedged)
    trainer.step.put_batch = orig
    stats = trainer.train_epoch()
    assert stats["examples"] > 0


def test_abandoned_ring_reaped_by_close(trainer):
    """A suspended mid-epoch ring (the preemption-break shape) is shut
    down by Trainer.close(), not left to the GC."""
    before = _ring_threads()
    stream = trainer._transfer_ahead(trainer.iter_train_batches())
    trainer._live_transfer.add(stream)
    arrays, shard_idx, _ = next(stream)  # ring is live and primed
    assert shard_idx == 0
    assert _ring_threads() - before, "ring workers should be running"
    trainer.close()  # must reap WITHOUT consuming the stream
    _wait_no_new_ring_threads(before)
    # the generator was closed: resuming it is over immediately
    assert list(stream) == []


def test_epoch_end_reaps_ring_before_next_epoch(trainer):
    """The normal path: after train_epoch returns, no ring threads
    linger (the per-epoch executor is not left to the GC either)."""
    before = _ring_threads()
    stats = trainer.train_epoch()
    assert stats["examples"] > 0
    _wait_no_new_ring_threads(before)
