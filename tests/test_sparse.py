"""Consolidation (sort+segment-sum unique) vs a dense numpy oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xflow_tpu.ops.sparse import consolidate, gather_rows, scatter_rows

TABLE = 64


def oracle_sums(keys, grads, table):
    dense = np.zeros((table, grads.shape[1]), dtype=np.float64)
    for k, g in zip(keys, grads):
        if k < table:
            dense[k] += g
    return dense


def test_consolidate_matches_oracle():
    rng = np.random.default_rng(0)
    m, d = 256, 3
    keys = rng.integers(0, TABLE, size=m).astype(np.int32)
    # sprinkle sentinel padding
    keys[rng.random(m) < 0.2] = TABLE
    grads = rng.normal(size=(m, d)).astype(np.float32)
    grads[keys == TABLE] = 0.0

    ukeys, gsum = jax.jit(consolidate, static_argnums=2)(
        jnp.asarray(keys), jnp.asarray(grads), TABLE
    )
    ukeys, gsum = np.asarray(ukeys), np.asarray(gsum)

    dense = np.zeros((TABLE, d))
    for k, g in zip(ukeys, gsum):
        if k < TABLE:
            dense[k] += g
    np.testing.assert_allclose(dense, oracle_sums(keys, grads, TABLE), atol=1e-4)
    # real unique keys appear exactly once
    real = ukeys[ukeys < TABLE]
    assert len(real) == len(set(real.tolist()))
    assert set(real.tolist()) == set(keys[keys < TABLE].tolist())


def test_consolidate_all_padding():
    keys = jnp.full((16,), TABLE, jnp.int32)
    grads = jnp.zeros((16, 1))
    ukeys, gsum = consolidate(keys, grads, TABLE)
    assert np.all(np.asarray(ukeys) == TABLE)
    np.testing.assert_array_equal(np.asarray(gsum), 0.0)


def test_consolidate_single_unique_key():
    """Every real slot carries the same key: one live segment, all
    gradients summed into it, every other slot sentinel/zero."""
    m, d = 32, 2
    keys = np.full(m, 7, np.int32)
    keys[-4:] = TABLE  # a little padding
    grads = np.ones((m, d), np.float32)
    grads[-4:] = 0.0
    ukeys, gsum = consolidate(
        jnp.asarray(keys), jnp.asarray(grads), TABLE
    )
    ukeys, gsum = np.asarray(ukeys), np.asarray(gsum)
    real = ukeys < TABLE
    assert real.sum() == 1
    np.testing.assert_allclose(gsum[real][0], np.full(d, m - 4.0))
    np.testing.assert_array_equal(gsum[~real], 0.0)


@pytest.mark.parametrize("dist", ["random", "zipf"])
def test_host_compact_matches_device_consolidate(dist):
    """Parity between the host compaction kernel (io/compact.py
    dictionary + consolidate_indexed) and the device's sort-based
    consolidate: identical per-row gradient sums into a dense table,
    the dictionary tier collapsing its duplicates exactly like the
    argsort plan does."""
    rng = np.random.default_rng(9)
    m, d = 4096, 3
    if dist == "random":
        keys = rng.integers(0, TABLE, m).astype(np.int32)
    else:
        keys = np.minimum(rng.zipf(1.3, m) - 1, TABLE - 1).astype(np.int32)
    keys[rng.random(m) < 0.1] = TABLE  # padding sentinels
    grads = rng.normal(size=(m, d)).astype(np.float32)
    grads[keys == TABLE] = 0.0

    # device reference: sort + segment-sum consolidation
    ukeys, gsum = consolidate(jnp.asarray(keys), jnp.asarray(grads), TABLE)
    dense_dev = np.zeros((TABLE, d), np.float32)
    np.add.at(dense_dev, np.minimum(np.asarray(ukeys), TABLE - 1),
              np.where((np.asarray(ukeys) < TABLE)[:, None],
                       np.asarray(gsum), 0.0))

    # host plan: dictionary codes -> consolidate_indexed + tail scatter
    from xflow_tpu.io.compact import dedup_select
    from xflow_tpu.ops.sparse import consolidate_indexed

    real = keys < TABLE
    uniq, codes = dedup_select(keys[real].astype(np.int64), dict_cap=64)
    nd = len(uniq)
    uidx = np.full(m, nd, np.int32)  # dump slot: padding + tail
    covered = codes != 0xFFFFFFFF
    uidx[np.flatnonzero(real)[covered]] = codes[covered].astype(np.int32)
    gsum_dict = np.asarray(
        consolidate_indexed(jnp.asarray(grads), jnp.asarray(uidx), nd)
    )
    dense_host = np.zeros((TABLE, d), np.float32)
    np.add.at(dense_host, uniq.astype(np.int64), gsum_dict)
    tail = real & ~np.isin(
        np.arange(m), np.flatnonzero(real)[covered]
    )
    np.add.at(dense_host, keys[tail].astype(np.int64), grads[tail])

    np.testing.assert_allclose(dense_host, dense_dev, atol=1e-4)


def test_gather_scatter_sentinel_dropped():
    table = jnp.arange(TABLE, dtype=jnp.float32)[:, None]
    ukeys = jnp.asarray([3, TABLE, 5], jnp.int32)
    rows = gather_rows(table, ukeys)
    # sentinel gather clamps to last row
    np.testing.assert_allclose(np.asarray(rows)[:, 0], [3.0, TABLE - 1, 5.0])
    new = scatter_rows(table, ukeys, rows * 10.0)
    out = np.asarray(new)[:, 0]
    assert out[3] == 30.0 and out[5] == 50.0
    # last row untouched: sentinel write dropped
    assert out[TABLE - 1] == TABLE - 1
