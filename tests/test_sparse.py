"""Consolidation (sort+segment-sum unique) vs a dense numpy oracle."""

import jax
import jax.numpy as jnp
import numpy as np

from xflow_tpu.ops.sparse import consolidate, gather_rows, scatter_rows

TABLE = 64


def oracle_sums(keys, grads, table):
    dense = np.zeros((table, grads.shape[1]), dtype=np.float64)
    for k, g in zip(keys, grads):
        if k < table:
            dense[k] += g
    return dense


def test_consolidate_matches_oracle():
    rng = np.random.default_rng(0)
    m, d = 256, 3
    keys = rng.integers(0, TABLE, size=m).astype(np.int32)
    # sprinkle sentinel padding
    keys[rng.random(m) < 0.2] = TABLE
    grads = rng.normal(size=(m, d)).astype(np.float32)
    grads[keys == TABLE] = 0.0

    ukeys, gsum = jax.jit(consolidate, static_argnums=2)(
        jnp.asarray(keys), jnp.asarray(grads), TABLE
    )
    ukeys, gsum = np.asarray(ukeys), np.asarray(gsum)

    dense = np.zeros((TABLE, d))
    for k, g in zip(ukeys, gsum):
        if k < TABLE:
            dense[k] += g
    np.testing.assert_allclose(dense, oracle_sums(keys, grads, TABLE), atol=1e-4)
    # real unique keys appear exactly once
    real = ukeys[ukeys < TABLE]
    assert len(real) == len(set(real.tolist()))
    assert set(real.tolist()) == set(keys[keys < TABLE].tolist())


def test_consolidate_all_padding():
    keys = jnp.full((16,), TABLE, jnp.int32)
    grads = jnp.zeros((16, 1))
    ukeys, gsum = consolidate(keys, grads, TABLE)
    assert np.all(np.asarray(ukeys) == TABLE)
    np.testing.assert_array_equal(np.asarray(gsum), 0.0)


def test_gather_scatter_sentinel_dropped():
    table = jnp.arange(TABLE, dtype=jnp.float32)[:, None]
    ukeys = jnp.asarray([3, TABLE, 5], jnp.int32)
    rows = gather_rows(table, ukeys)
    # sentinel gather clamps to last row
    np.testing.assert_allclose(np.asarray(rows)[:, 0], [3.0, TABLE - 1, 5.0])
    new = scatter_rows(table, ukeys, rows * 10.0)
    out = np.asarray(new)[:, 0]
    assert out[3] == 30.0 and out[5] == 50.0
    # last row untouched: sentinel write dropped
    assert out[TABLE - 1] == TABLE - 1
