"""Retrieval→ranking cascade: registry, top-k engine mode, cascade
engine semantics, doctor diagnoses, and the tier-1 smoke gate
(scripts/check_cascade_smoke.py — trains both stages, serves the
cascade over HTTP, loadgens a zipf mix, checks parity/recompiles/
schema)."""

import os
import subprocess
import sys

import numpy as np
import pytest

from xflow_tpu.config import Config
from xflow_tpu.models import (
    ModelFamily,
    make_model,
    model_family,
    model_names,
    register_model,
)

repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- registry ----------------------------------------------------------------


def test_registry_names_cover_all_families():
    assert set(model_names()) == {
        "lr", "fm", "mvm", "ffm", "wide_deep", "two_tower", "dcn",
    }


def test_registry_unknown_model_actionable():
    with pytest.raises(ValueError, match="registered families"):
        Config(model="gbdt")
    with pytest.raises(ValueError, match="registered families"):
        model_family("gbdt")


def test_registry_refuses_duplicates():
    with pytest.raises(ValueError, match="already registered"):
        register_model(ModelFamily("lr", lambda cfg: None, "dup"))


def test_registry_retrieval_flag():
    assert model_family("two_tower").retrieval
    assert not model_family("dcn").retrieval
    assert not model_family("lr").retrieval


def test_two_tower_split_validation():
    with pytest.raises(ValueError, match="tower_split_field"):
        Config(model="two_tower", tower_split_field=0)
    with pytest.raises(ValueError, match="tower_split_field"):
        Config(model="two_tower", max_fields=8, tower_split_field=8)
    with pytest.raises(ValueError, match="cross_layers"):
        Config(model="dcn", cross_layers=0)


# -- engine top-k mode -------------------------------------------------------


def _live_engine(model_name, **over):
    from xflow_tpu.optim import make_optimizer
    from xflow_tpu.parallel.mesh import make_mesh
    from xflow_tpu.parallel.step import init_state
    from xflow_tpu.serve.engine import PredictEngine

    base = dict(
        model=model_name,
        table_size_log2=10,
        batch_size=8,
        max_nnz=8,
        max_fields=8,
        tower_split_field=4,
        tower_dim=4,
        num_devices=1,
    )
    base.update(over)
    cfg = Config(**base)
    mesh = make_mesh(1)
    model = make_model(cfg)
    state = init_state(model, make_optimizer(cfg), cfg, mesh)
    return PredictEngine(cfg, state, mesh=mesh, buckets=(4, 8))


def _toy_index(n=6, dim=6, nnz=3, table_size=1024, seed=0):
    # dim = tower_dim + 2: the bias-lane augmentation widens tower
    # outputs by [bias, 1] (models/two_tower.py docstring)
    rng = np.random.default_rng(seed)
    return {
        "count": n,
        "dim": dim,
        "item_index": rng.normal(size=(n, dim)).astype(np.float32),
        "item_ids": (10 + np.arange(n)).astype(np.int64),
        "item_keys": rng.integers(0, table_size, (n, nnz)).astype(np.int64),
        "item_slots": np.full((n, nnz), 5, np.int32),
        "item_vals": np.ones((n, nnz), np.float32),
        "item_nnz": np.full(n, nnz, np.int32),
    }


def test_topk_refused_without_index():
    eng = _live_engine("two_tower")
    with pytest.raises(ValueError, match="no item index"):
        eng.topk_prepared(eng._empty_batch(4))


def test_attach_index_refused_for_non_retrieval_model():
    eng = _live_engine("dcn")
    with pytest.raises(ValueError, match="retrieval=False"):
        eng.attach_item_index(_toy_index())


def test_topk_matches_full_scan_and_never_recompiles():
    eng = _live_engine("two_tower")
    eng.attach_item_index(_toy_index(), topk_k=4)
    eng.warm()
    warm = eng.compile_count
    rng = np.random.default_rng(1)
    rows = [
        (rng.integers(0, 1024, 5).astype(np.int64),
         np.arange(5, dtype=np.int32) % 4, None)
        for _ in range(3)
    ]
    from xflow_tpu.io.batch import pad_batch_rows

    prepared = pad_batch_rows(
        eng._prepare(eng.featurize_raw(rows)), eng.bucket_for(3)
    )
    ids, scores, u = eng.topk_prepared(prepared)
    ids, scores, u = ids[:3], scores[:3], u[:3]
    full = u @ eng.item_index["item_index"].T
    order = np.argsort(-full, axis=1, kind="stable")[:, :4]
    np.testing.assert_allclose(
        scores, np.take_along_axis(full, order, axis=1), atol=1e-6
    )
    np.testing.assert_array_equal(ids, eng.item_index["item_ids"][order])
    # mixed k and mixed sizes slice the ONE compiled width — the
    # no-recompile guarantee covers top-k traffic too
    for k in (1, 2, 4):
        eng.topk(eng.featurize_raw(rows[:2]), k=k)
    assert eng.compile_count == warm
    with pytest.raises(ValueError, match="topk_k"):
        eng.topk(eng.featurize_raw(rows[:1]), k=5)


def test_clone_shares_index_and_compiles():
    eng = _live_engine("two_tower")
    eng.attach_item_index(_toy_index(), topk_k=2)
    eng.warm()
    rep = eng.clone()
    assert rep.item_index is eng.item_index
    assert rep.topk_k == eng.topk_k
    assert rep._compiled is eng._compiled


def test_item_embeddings_requires_item_tower():
    eng = _live_engine("lr")
    with pytest.raises(ValueError, match="item tower"):
        eng.item_embeddings([(np.asarray([1, 2]), None, None)])


# -- cascade engine ----------------------------------------------------------


def _toy_cascade(k=2, topk_k=4, index=None):
    from xflow_tpu.serve.cascade import CascadeEngine
    from xflow_tpu.serve.fleet import ReplicaFleet

    reng = _live_engine("two_tower")
    reng.attach_item_index(
        _toy_index() if index is None else index, topk_k=topk_k
    )
    reng.warm()
    keng = _live_engine("dcn")
    keng.warm()
    retrieval = ReplicaFleet(reng, 2, topk=True, revive=False)
    ranking = ReplicaFleet(keng, 2, revive=False)
    return CascadeEngine(retrieval, ranking, k=k)


def test_cascade_requires_topk_retrieval_stage():
    from xflow_tpu.serve.cascade import CascadeEngine
    from xflow_tpu.serve.fleet import ReplicaFleet

    keng = _live_engine("dcn")
    plain = ReplicaFleet(keng, 1, revive=False)
    with pytest.raises(ValueError, match="top-k fleet"):
        CascadeEngine(plain, plain, k=1)
    plain.close()


def test_cascade_ranks_candidates_and_books_stats():
    casc = _toy_cascade(k=3)
    try:
        res = casc.recommend(
            np.asarray([3, 7, 11], np.int64),
            np.asarray([0, 1, 2], np.int32),
        )
        assert len(res["items"]) == 3
        assert res["pctr"] == sorted(res["pctr"], reverse=True)
        assert set(res["items"]) <= set(
            int(i) for i in casc.retrieval.engines[0].item_index["item_ids"]
        )
        row = casc.emit_stats()
        assert row["requests"] == 1 and row["errors"] == 0
        assert row["starved"] == 0 and row["k_returned_mean"] == 3.0
        assert row["e2e_p99"] >= row["rank_p50"] >= 0
        from xflow_tpu.obs.schema import validate_row

        assert validate_row(dict(row, t=0.0, kind="cascade")) == []
    finally:
        casc.close()


def test_cascade_starvation_counted_not_failed():
    """k beyond the compiled top-k width (a rollout can shrink the
    index under live traffic): served best-effort with fewer
    candidates, counted as starvation — never a failed request."""
    casc = _toy_cascade(k=2, topk_k=3)
    try:
        res = casc.recommend(
            np.asarray([5, 9], np.int64), np.asarray([0, 1], np.int32),
            k=5,
        )
        assert len(res["items"]) == 3  # index width, not the asked 5
        row = casc.emit_stats()
        assert row["starved"] == 1 and row["errors"] == 0
    finally:
        casc.close()


# -- doctor ------------------------------------------------------------------


def _cascade_row(**over):
    row = {
        "t": 1.0, "kind": "cascade", "requests": 10, "errors": 0,
        "shed_total": 0, "starved": 0, "k": 5, "k_returned_mean": 5.0,
        "retrieval_p50": 0.002, "retrieval_p99": 0.004,
        "rank_p50": 0.008, "rank_p99": 0.020,
        "e2e_p50": 0.011, "e2e_p99": 0.024,
    }
    row.update(over)
    return row


def test_doctor_cascade_starvation_and_attribution():
    from xflow_tpu.obs.doctor import diagnose

    finds = diagnose([_cascade_row(starved=3, k_returned_mean=3.2)])
    codes = {d.code: d.severity for d in finds}
    assert codes.get("candidate_starvation") == "warn"
    # per-stage p99 attribution blames the dominant stage by name
    attach = [d for d in finds if d.code == "cascade_stage_p99"]
    assert attach and "ranking" in attach[0].message


def test_doctor_cascade_clean_run_is_clean():
    from xflow_tpu.obs.doctor import diagnose

    finds = diagnose([_cascade_row()])
    assert all(
        d.severity not in ("crit", "warn") for d in finds
    ), [f"{d.code}: {d.message}" for d in finds]


def test_doctor_cascade_errors_warn():
    from xflow_tpu.obs.doctor import diagnose

    finds = diagnose([_cascade_row(errors=2)])
    assert any(
        d.code == "cascade_errors" and d.severity == "warn" for d in finds
    )


# -- tier-1 gate -------------------------------------------------------------


def test_check_cascade_smoke_script():
    """The CI lint (scripts/check_cascade_smoke.py) passes — run as a
    subprocess exactly as CI would (tier-1 wiring, like
    check_serve_smoke.py)."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join(repo, "scripts", "check_cascade_smoke.py")],
        capture_output=True,
        text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        timeout=600,
        cwd=repo,
    )
    assert proc.returncode == 0, (
        f"check_cascade_smoke failed:\nstdout:\n{proc.stdout}\n"
        f"stderr:\n{proc.stderr}"
    )


def test_topk_fleet_rollout_refuses_indexless_candidate(tmp_path):
    """A top-k fleet must refuse a candidate artifact with no item
    index at the rollout gate — per-request failures after the swap
    would evict every replica."""
    from xflow_tpu.serve.fleet import ReplicaFleet

    reng = _live_engine("two_tower")
    reng.attach_item_index(_toy_index(), topk_k=2)
    reng.warm()
    fleet = ReplicaFleet(reng, 1, topk=True, revive=False)
    try:
        bare = _live_engine("two_tower")  # same cfg digest, no index
        with pytest.raises(ValueError, match="no item index"):
            fleet.begin_rollout(bare)
    finally:
        fleet.close()
