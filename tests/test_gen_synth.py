"""The synthetic dataset generator (scripts/gen_synth.py) must plant ONE
logistic model across every shard of a dataset — round-4 regression:
the model seed was tied to the per-shard stream seed, giving each shard
its own hidden weights and the dataset as a whole no learnable signal
(test AUC ~0.49)."""

import numpy as np
import pytest

import scripts.gen_synth


@pytest.fixture(scope="module")
def gen():
    return scripts.gen_synth


def planted_auc(gen, path: str, seed: int) -> float:
    """AUC of the planted model's own logit, recomputed from the WRITTEN
    text — validates label/feature consistency end to end."""
    from xflow_tpu.io.libffm import parse_block
    from xflow_tpu.utils.metrics import auc_midrank

    w = gen.hidden_weights(seed)
    with open(path, "rb") as f:
        block = parse_block(f.read(), 0, hash_mode=False)
    gids = block.keys
    terms = w[gids // gen.VOCAB, gids % gen.VOCAB]
    sums = np.add.reduceat(terms, block.row_ptr[:-1])
    p = 1.0 / (1.0 + np.exp(-(sums - 1.0)))
    return auc_midrank(block.labels, p)


def test_one_model_across_shards(gen, tmp_path):
    prefix = str(tmp_path / "ds")
    gen.generate_dataset(
        prefix, num_train=12000, num_test=6000, train_shards=3, seed=7
    )
    # every shard — train AND test — scores high against the ONE
    # planted model (hidden_weights(seed)); the pre-fix behavior scored
    # ~0.5 on all but train shard 0
    for name in ("ds.train-00000", "ds.train-00001", "ds.train-00002",
                 "ds.test-00000"):
        auc = planted_auc(gen, str(tmp_path / name), seed=7)
        assert auc > 0.7, f"{name}: planted AUC {auc}"
    # distinct stream seeds: shards are not byte-identical
    a = (tmp_path / "ds.train-00000").read_bytes()[:4096]
    b = (tmp_path / "ds.train-00001").read_bytes()[:4096]
    assert a != b


def test_single_shard_bytes_stable(gen, tmp_path):
    """model_seed defaults to seed: single-shard bytes are identical to
    v1's, so numbers measured against regenerated single-shard data
    stay comparable across the GEN_VERSION bump.  (The bump itself
    still renames the bench cache file once — that regeneration
    reproduces these exact bytes.)"""
    p1 = str(tmp_path / "a.ffm")
    p2 = str(tmp_path / "b.ffm")
    gen.generate_shard(p1, 1000, seed=7)
    gen.generate_shard(p2, 1000, seed=7, model_seed=7)
    assert open(p1, "rb").read() == open(p2, "rb").read()
