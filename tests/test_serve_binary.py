"""Binary serve transport, QoS-classed admission, and the hot-key
score cache (ISSUE 20): XFB1 codec refusals, pipelined e2e scoring
parity, shed ordering under mixed-class overload (+ the extended
check_serve_slo.py gates), and cache correctness across rollouts."""

import json
import os
import socket
import struct
import subprocess
import sys
import time

import numpy as np
import pytest

from xflow_tpu.config import Config
from xflow_tpu.io.loader import ShardLoader
from xflow_tpu.trainer import Trainer


def _cfg(toy_dataset, **overrides):
    base = dict(
        train_path=toy_dataset.train_prefix,
        test_path=toy_dataset.test_prefix,
        model="lr",
        epochs=2,
        batch_size=64,
        table_size_log2=14,
        max_nnz=24,
        num_devices=1,
    )
    base.update(overrides)
    return Config(**base)


@pytest.fixture(scope="module")
def lr_served(toy_dataset, tmp_path_factory):
    """One trained lr model + exported artifact shared by the module
    (same shape as tests/test_serve.py's fixture)."""
    from xflow_tpu.serve.artifact import export_artifact

    trainer = Trainer(_cfg(toy_dataset))
    trainer.train()
    art = str(tmp_path_factory.mktemp("serve_bin") / "artifact")
    export_artifact(trainer, art)
    return {"trainer": trainer, "artifact": art}


def _slowed(engine, delay_s):
    import time as _time

    orig = engine.predict_prepared
    engine.predict_prepared = lambda b: (_time.sleep(delay_s), orig(b))[1]
    return engine


def _rows(cfg, n, nnz=6, seed=0):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(0, cfg.table_size, size=nnz) for _ in range(n)
    ]


def _trained_row(trainer, shard=None):
    """One row of TRAINED keys (an untrained random row scores the
    all-zero-weights 0.5 on every artifact — useless for telling two
    model versions apart)."""
    loader = ShardLoader(
        shard or trainer.cfg.test_path + "-00000",
        batch_size=trainer.cfg.batch_size,
        max_nnz=trainer.cfg.max_nnz,
        table_size=trainer.cfg.table_size,
        parse_fn=trainer._parse_fn(),
    )
    batch = next(b for b, _ in loader.iter_batches())
    return batch.keys[0][batch.mask[0] > 0]


# -- frame codec --------------------------------------------------------------


def test_xfb1_codec_roundtrip_and_typed_refusals():
    """The codec contract the wirefuzz target drives: encode→decode
    round-trips; truncation, trailing bytes, magic confusion, length
    inflation, and unknown QoS bytes all refuse with typed errors."""
    from xflow_tpu.serve.binary import (
        FRAME_MAGIC,
        MAX_FRAME_BYTES,
        STATUS_OK,
        decode_frame,
        decode_request_stream,
        decode_response_frame,
        encode_frame,
        encode_response_frame,
    )
    from xflow_tpu.serve.server import (
        decode_packed_response,
        encode_packed_request,
        encode_packed_response,
    )

    body = encode_packed_request([(np.asarray([3, 99, 2048]), None, None)])
    frame = encode_frame(7, "bidding", body)
    assert frame.startswith(FRAME_MAGIC)
    rid, qos, got = decode_frame(frame)
    assert (rid, qos, got) == (7, "bidding", body)

    # pipelined stream: every frame decodes, ids/classes preserved
    stream = (
        encode_frame(1, "normal", body)
        + encode_frame((1 << 64) - 1, "best_effort", body)
    )
    decoded = decode_request_stream(stream)
    assert [(r, q) for r, q, _, _ in decoded] == [
        (1, "normal"), ((1 << 64) - 1, "best_effort"),
    ]

    # response frame round-trip
    rbody = encode_packed_response([0.25, 0.5])
    rframe = encode_response_frame(9, STATUS_OK, rbody)
    rid, status, rgot = decode_response_frame(rframe)
    assert (rid, status) == (9, STATUS_OK)
    np.testing.assert_allclose(
        decode_packed_response(rgot), [0.25, 0.5], atol=1e-7
    )

    # truncation: every strict prefix refuses
    for cut in (1, 4, 7, 8, 12, len(frame) - 1):
        with pytest.raises(ValueError, match="truncat|magic|length"):
            decode_frame(frame[:cut])
    with pytest.raises(ValueError, match="truncated frame at offset"):
        decode_request_stream(stream[:-3])

    # trailing garbage after a complete frame
    with pytest.raises(ValueError, match="trailing"):
        decode_frame(frame + b"\x00")

    # magic confusion: an XFS1 body alone is not a frame
    with pytest.raises(ValueError, match="magic"):
        decode_frame(body)

    # length inflation refuses BEFORE buffering toward the claimed size
    inflated = bytearray(frame)
    struct.pack_into("<I", inflated, 4, MAX_FRAME_BYTES + 1)
    with pytest.raises(ValueError, match="length"):
        decode_frame(bytes(inflated))

    # unknown QoS byte (offset 16 = magic + len + u64 rid)
    bad_qos = bytearray(frame)
    bad_qos[16] = 9
    with pytest.raises(ValueError, match="QoS byte"):
        decode_frame(bytes(bad_qos))
    with pytest.raises(ValueError, match="QoS class"):
        encode_frame(1, "platinum", body)
    with pytest.raises(ValueError, match="u64"):
        encode_frame(1 << 64, "normal", body)
    with pytest.raises(ValueError, match="status"):
        encode_response_frame(1, 17, b"")


# -- binary tier e2e ----------------------------------------------------------


def _recv_response(sock, timeout=30.0):
    """Read exactly one response frame off a raw socket."""
    from xflow_tpu.serve.binary import decode_response_frame

    sock.settimeout(timeout)
    buf = b""
    while len(buf) < 8:
        buf += sock.recv(4096)
    (length,) = struct.unpack_from("<I", buf, 4)
    while len(buf) < 8 + length:
        buf += sock.recv(4096)
    return decode_response_frame(buf[:8 + length])


def test_binary_tier_pipelined_scores_match_engine(lr_served):
    """E2E over the wire: a pipelined BinaryTarget against a live
    BinaryTier scores bit-for-bit with direct engine predict; framed
    garbage gets a typed STATUS_ERROR on a SURVIVING connection;
    unframeable garbage drops the connection."""
    from xflow_tpu.serve.binary import (
        STATUS_ERROR,
        STATUS_OK,
        BinaryTier,
        encode_frame,
    )
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.serve.fleet import ReplicaFleet
    from xflow_tpu.serve.loadgen import BinaryTarget
    from xflow_tpu.serve.server import encode_packed_request

    engine = PredictEngine.load(
        lr_served["artifact"], buckets=(8, 64), warm=True
    )
    fleet = ReplicaFleet(engine, replicas=2, max_wait_ms=1.0)
    tier = BinaryTier(fleet, port=0, poll_s=0.02).start()
    rows = _rows(engine.cfg, 40, seed=5)
    try:
        with BinaryTarget(
            "127.0.0.1", tier.port, pipeline_depth=16
        ) as target:
            futs = [target.submit(r, qos="bidding") for r in rows]
            got = np.asarray([f.result(timeout=60) for f in futs])
        want = engine.predict(engine.featurize_raw(rows))
        np.testing.assert_allclose(got, want, atol=1e-6)
        live = fleet.stats()
        assert live["shed"]["by_class"]["bidding"]["admitted"] == 40
        assert "bidding" in live["qos"]

        # raw socket: framed-but-garbage body → typed STATUS_ERROR,
        # and the SAME connection still scores afterwards
        sock = socket.create_connection(("127.0.0.1", tier.port), 10)
        try:
            sock.sendall(encode_frame(50, "normal", b"not a request"))
            rid, status, body = _recv_response(sock)
            assert (rid, status) == (50, STATUS_ERROR)
            assert "error" in json.loads(body.decode())
            good = encode_packed_request([(rows[0], None, None)])
            sock.sendall(encode_frame(51, "normal", good))
            rid, status, body = _recv_response(sock)
            assert (rid, status) == (51, STATUS_OK)
            # unknown QoS byte with good framing: typed error frame
            bad = bytearray(encode_frame(52, "normal", good))
            bad[16] = 7
            sock.sendall(bytes(bad))
            rid, status, _ = _recv_response(sock)
            assert (rid, status) == (52, STATUS_ERROR)
            # unframeable garbage: the stream cannot resync — dropped
            sock.sendall(b"GET / HTTP/1.1\r\n\r\n")
            assert sock.recv(4096) == b""
        finally:
            sock.close()
    finally:
        tier.close()
        assert not tier.running
        fleet.close()  # the tier never closes the shared fleet


def test_binary_tier_shed_and_timeout_status(lr_served):
    """The wire's 429 and 504: an overloaded fleet answers
    STATUS_SHED (surfacing as a typed ShedError with its QoS class
    through BinaryTarget futures); a scoring future outliving
    score_timeout_s answers STATUS_TIMEOUT via the deadline sweep."""
    from xflow_tpu.serve.binary import BinaryTier
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.serve.fleet import ReplicaFleet, ShedError
    from xflow_tpu.serve.loadgen import BinaryTarget

    engine = _slowed(
        PredictEngine.load(lr_served["artifact"], buckets=(8,), warm=True),
        0.3,
    )
    fleet = ReplicaFleet(
        engine, replicas=1, max_wait_ms=0.0,
        deadline_budget_ms=15.0, depth_budget=2,
    )
    tier = BinaryTier(
        fleet, port=0, poll_s=0.02, score_timeout_s=0.1,
    ).start()
    row = _rows(engine.cfg, 1, seed=6)[0]
    try:
        with BinaryTarget(
            "127.0.0.1", tier.port, pipeline_depth=32, qos="best_effort"
        ) as target:
            futs = [target.submit(row) for _ in range(16)]
            sheds, timeouts, ok = [], 0, 0
            for f in futs:
                try:
                    f.result(timeout=60)
                    ok += 1
                except ShedError as e:
                    assert e.qos == "best_effort"
                    assert e.cause in ("queue_depth", "queue_age")
                    sheds.append(e)
                except TimeoutError:
                    timeouts += 1
            assert sheds, "a 0.3s device call never backed the queue up?"
            # with a 0.1s score budget over a 0.3s device call, every
            # admitted request times out on the wire
            assert timeouts >= 1
            assert ok + timeouts + len(sheds) == 16
    finally:
        tier.close()
        fleet.close()


# -- QoS ordering under overload + the extended SLO gate ----------------------


def test_qos_overload_ordering_and_slo_gate(lr_served, tmp_path):
    """Acceptance: under a mixed-class zipf overload the bidding shed
    fraction stays 0 while best_effort absorbs the shedding; the
    serve_bench row carries the per-class split and
    check_serve_slo.py --qos-ordering gates it (and refuses an
    inverted or classless row)."""
    from xflow_tpu.obs.schema import load_jsonl, validate_rows
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.serve.fleet import ReplicaFleet
    from xflow_tpu.serve.loadgen import run_loadgen
    from xflow_tpu.utils.logging import MetricsLogger

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    gate = os.path.join(repo, "scripts", "check_serve_slo.py")

    engine = _slowed(
        PredictEngine.load(lr_served["artifact"], buckets=(8, 64), warm=True),
        0.03,
    )
    metrics = tmp_path / "qos.jsonl"
    logger = MetricsLogger(metrics, run_header={
        "run_id": "t", "config_digest": engine.digest,
        "rank": 0, "num_hosts": 1,
    })
    # budgets make the ordering DEMONSTRABLE, not just configured:
    # bidding's (full) budget is far above anything a 1.2s run can
    # reach, best_effort's scaled copy sits under the slowed device
    # call, so pressure lands on best_effort only — the invariant the
    # gate and `obs doctor` qos_inversion both watch
    fleet = ReplicaFleet(
        engine, replicas=1, max_wait_ms=1.0,
        deadline_budget_ms=10_000.0, depth_budget=10_000,
        qos_normal_frac=0.5, qos_best_effort_frac=0.002,
        metrics_logger=logger,
    )
    try:
        summary = run_loadgen(
            fleet, offered_qps=300, duration_s=1.2, concurrency=4,
            nnz=6, seed=7, drain_timeout_s=60.0,
            metrics_logger=logger,
            qos_mix={"bidding": 0.2, "normal": 0.5, "best_effort": 0.3},
        )
    finally:
        fleet.close()
        logger.close()
    assert validate_rows(load_jsonl(str(metrics))) == []
    assert summary["errors"] == 0
    offered = summary["qos_offered"]
    shed = summary["qos_shed"]
    assert offered["bidding"] > 0 and offered["best_effort"] > 0
    assert shed.get("bidding", 0) == 0, summary
    assert shed.get("normal", 0) == 0, summary
    assert shed.get("best_effort", 0) > 0, (
        "the overload never pressured the best_effort budget"
    )

    proc = subprocess.run(
        [
            sys.executable, gate, str(metrics),
            "--qos-ordering", "--max-shed-frac", "0.9",
        ],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "qos_bidding_shed" in proc.stdout

    # an inverted row (bidding shed, best_effort clean) must FAIL
    rows = [json.loads(l) for l in open(metrics) if l.strip()]
    bench = next(r for r in rows if r.get("kind") == "serve_bench")
    bench["qos_shed"] = {"bidding": 3, "normal": 0, "best_effort": 0}
    inverted = tmp_path / "inverted.jsonl"
    inverted.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    proc = subprocess.run(
        [
            sys.executable, gate, str(inverted),
            "--qos-ordering", "--max-shed-frac", "0.9",
        ],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "qos_bidding_shed" in proc.stdout

    # a classless row cannot vacuously pass the ordering gate
    bench.pop("qos_shed")
    classless = tmp_path / "classless.jsonl"
    classless.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    proc = subprocess.run(
        [
            sys.executable, gate, str(classless),
            "--qos-ordering", "--max-shed-frac", "0.9",
        ],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "qos_shed" in proc.stderr


def test_compare_transports_gate_two_legs(lr_served, tmp_path):
    """Acceptance (CI wiring): one fleet serves both wires; an HTTP
    leg and a pipelined binary leg log transport-tagged serve_bench
    rows, and check_serve_slo.py --compare-transports requires the
    binary leg to beat HTTP on achieved QPS with a p99 no worse.  A
    file missing a leg is a usage error, not a pass."""
    from xflow_tpu.obs.schema import load_jsonl, validate_rows
    from xflow_tpu.serve.binary import BinaryTier
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.serve.fleet import ReplicaFleet
    from xflow_tpu.serve.loadgen import (
        BinaryTarget,
        HttpTarget,
        run_loadgen,
    )
    from xflow_tpu.serve.server import ServeTier
    from xflow_tpu.utils.logging import MetricsLogger

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    gate = os.path.join(repo, "scripts", "check_serve_slo.py")

    engine = PredictEngine.load(
        lr_served["artifact"], buckets=(8, 64), warm=True
    )
    metrics = tmp_path / "twoleg.jsonl"
    logger = MetricsLogger(metrics, run_header={
        "run_id": "t", "config_digest": engine.digest,
        "rank": 0, "num_hosts": 1,
    })
    fleet = ReplicaFleet(engine, replicas=2, max_wait_ms=1.0)
    tier = ServeTier(fleet, port=0, poll_s=0.05).start()
    btier = BinaryTier(fleet, port=0, poll_s=0.02).start()
    table = int(engine.cfg.table_size)
    # offer more than the synchronous-per-worker HTTP client can carry
    # so the legs separate: HTTP achieves its closed-loop ceiling,
    # the pipelined binary leg rides the open-loop schedule
    kw = dict(
        offered_qps=1200, duration_s=1.0, concurrency=4, nnz=6,
        seed=11, drain_timeout_s=60.0, table_size=table,
        metrics_logger=logger,
    )
    try:
        http = HttpTarget(tier.address, max_retries=0)
        http_sum = run_loadgen(http, **kw)
        with BinaryTarget(
            "127.0.0.1", btier.port, pipeline_depth=32
        ) as bt:
            bin_sum = run_loadgen(bt, **kw)
    finally:
        btier.close()
        tier.close()
        fleet.close()
        logger.close()
    assert validate_rows(load_jsonl(str(metrics))) == []
    assert http_sum["transport"] == "http"
    assert bin_sum["transport"] == "binary"
    assert bin_sum["errors"] == 0 and bin_sum["outstanding"] == 0
    assert bin_sum["achieved_qps"] > http_sum["achieved_qps"], (
        http_sum, bin_sum,
    )

    proc = subprocess.run(
        [
            sys.executable, gate, str(metrics),
            "--compare-transports", "--max-shed-frac", "0.5",
        ],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "transport_qps" in proc.stdout
    assert "transport_p99" in proc.stdout

    # one-leg file: usage error (exit 2), never a vacuous pass
    rows = [json.loads(l) for l in open(metrics) if l.strip()]
    solo = [
        r for r in rows
        if not (
            r.get("kind") == "serve_bench"
            and r.get("transport") == "http"
        )
    ]
    oneleg = tmp_path / "oneleg.jsonl"
    oneleg.write_text("\n".join(json.dumps(r) for r in solo) + "\n")
    proc = subprocess.run(
        [sys.executable, gate, str(oneleg), "--compare-transports"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "http" in proc.stderr


# -- score cache --------------------------------------------------------------


def test_scache_lru_bound_across_two_generations():
    """Unit contract: the LRU bound holds within a digest generation;
    a generation swap evicts wholesale and the straggler guard drops
    inserts carrying the previous digest."""
    from xflow_tpu.serve.scache import ScoreCache

    cache = ScoreCache(capacity=4)
    cache.set_current("gen-a")
    for i in range(10):
        assert cache.insert("gen-a", np.asarray([i]), None, None, i / 10)
    assert len(cache) == 4
    row = cache.stats_row(reset=False)
    assert row["cache_evictions"] == 6
    assert row["cache_bytes"] > 0

    evicted = cache.set_current("gen-b")
    assert evicted == 4 and len(cache) == 0
    # straggler insert under the OLD digest is dropped, not mis-keyed
    assert not cache.insert("gen-a", np.asarray([1]), None, None, 0.5)
    assert cache.lookup("gen-a", np.asarray([9]), None, None) is None
    for i in range(10):
        cache.insert("gen-b", np.asarray([i]), None, None, i / 10)
    assert len(cache) == 4
    assert cache.lookup("gen-b", np.asarray([9]), None, None) == 0.9
    row = cache.stats_row(reset=False)
    assert row["cache_inserts_dropped"] == 1
    assert row["cache_invalidations"] == 1  # the a→b swap (init pin aside)


def test_cache_hits_bitwise_and_rollout_commit(toy_dataset, tmp_path):
    """Acceptance: a cached score is BITWISE the engine's own score;
    across a staged rollout commit the cache never returns the old
    artifact's score — post-commit traffic matches the new engine
    exactly, and lookups are suspended while the rollout is open so
    the canary gate still sees traffic."""
    from xflow_tpu.serve.artifact import export_artifact
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.serve.fleet import ReplicaFleet
    from xflow_tpu.serve.scache import ScoreCache

    trainer = Trainer(_cfg(toy_dataset, epochs=1))
    trainer.train()
    art_a = str(tmp_path / "a")
    export_artifact(trainer, art_a)
    trainer.train_epoch()
    art_b = str(tmp_path / "b")
    export_artifact(trainer, art_b)

    ea = PredictEngine.load(art_a, buckets=(8,), warm=True)
    eb = PredictEngine.load(art_b, buckets=(8,), warm=True)
    row = _trained_row(trainer)
    pa = float(ea.predict(ea.featurize_raw([row]))[0])
    pb = float(eb.predict(eb.featurize_raw([row]))[0])
    assert pa != pb

    cache = ScoreCache(capacity=128)
    fleet = ReplicaFleet(ea, replicas=2, max_wait_ms=1.0, cache=cache)
    try:
        assert fleet.score(row, timeout=60) == pa  # miss → device
        assert fleet.score(row, timeout=60) == pa  # hit → cache
        stats = cache.stats_row(reset=False)
        assert stats["cache_hits"] == 1
        assert len(cache) >= 1

        fleet.begin_rollout(eb, canary_frac=0.5, min_canary_requests=6)
        # open rollout: lookups suspended — the canary stripe must see
        # live traffic or the health gate never accumulates
        deadline = time.perf_counter() + 30
        while time.perf_counter() < deadline:
            got = fleet.score(row, timeout=60)
            # scored by an ENGINE (canary or incumbent), never cached
            assert min(abs(got - pa), abs(got - pb)) < 1e-6
            state = fleet.rollout_state()
            if state["healthy"]:
                break
        assert fleet.rollout_state()["healthy"]
        hits_before = cache.stats_row(reset=False)["cache_hits"]
        fleet.commit_rollout()
        # committed swap evicted generation A atomically with the pin
        assert fleet.score(row, timeout=60) == pb  # miss on fresh gen
        assert fleet.score(row, timeout=60) == pb  # hit, new digest
        assert (
            cache.stats_row(reset=False)["cache_hits"] == hits_before + 1
        )
    finally:
        final = fleet.close()
        trainer.close()
    # the serve_stats window carries the cache fields
    assert "cache_hits" in final["stats"]


def test_cache_rollout_delta_refresh_bitwise(toy_dataset, tmp_path):
    """The zero-recompile delta refresh path: a cached score from the
    base servable is evicted by rollout_delta's commit, and post-
    commit scores match the delta-applied engine bitwise (the
    servable digest advanced even though the config digest did not)."""
    from xflow_tpu.serve.artifact import export_artifact
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.serve.fleet import ReplicaFleet
    from xflow_tpu.stream.delta import TouchedLedger, export_delta

    import jax

    trainer = Trainer(_cfg(toy_dataset, epochs=1))
    trainer.train()
    base = str(tmp_path / "base")
    export_artifact(trainer, base)
    base_step = int(jax.device_get(trainer.state["step"]))

    ledger = TouchedLedger()
    shard = trainer.cfg.train_path + "-00000"

    def feed(n):
        taken = 0
        while taken < n:
            for batch, _ in trainer._loader(shard).iter_batches():
                if taken >= n:
                    return
                ledger.mark(batch)
                taken += 1
                yield batch, None

    for _ in trainer.train_stream(feed(3)):
        pass
    delta = str(tmp_path / "delta")
    export_delta(trainer, delta, ledger, base_step)

    inc = PredictEngine.load(base, buckets=(8,), warm=True)
    ref = PredictEngine.load(base, buckets=(8,), warm=False).apply_delta(
        delta
    )
    # a row the DELTA actually touched (the stream fed this shard)
    row = _trained_row(trainer, shard=shard)
    p_base = float(inc.predict(inc.featurize_raw([row]))[0])
    p_delta = float(ref.predict(ref.featurize_raw([row]))[0])
    assert p_base != p_delta
    assert ref.servable_digest != inc.servable_digest

    fleet = ReplicaFleet.load(
        base, replicas=2, buckets=(8,), cache_capacity=64,
    )
    try:
        assert fleet.score(row, timeout=60) == p_base
        assert fleet.score(row, timeout=60) == p_base  # cached
        fleet.rollout_delta(delta, canary_frac=0.5, min_canary_requests=6)
        deadline = time.perf_counter() + 30
        while time.perf_counter() < deadline:
            fleet.score(row, timeout=60)
            if fleet.rollout_state()["healthy"]:
                break
        fleet.commit_rollout()
        assert fleet.servable == ref.servable_digest
        assert fleet.score(row, timeout=60) == p_delta  # fresh gen
        assert fleet.score(row, timeout=60) == p_delta  # cached hit
        assert fleet.cache.stats_row(reset=False)["cache_hits"] >= 2
    finally:
        fleet.close()
        trainer.close()


# -- observability: schema back-compat, doctor, summarize ---------------------


def test_serve_shed_by_class_schema_backcompat():
    """Pinned: by_class (serve_shed) and the cache_* fields
    (serve_stats) are additive-OPTIONAL — a pre-QoS metrics stream
    without them still validates, and typed violations still catch a
    wrong-typed by_class."""
    from xflow_tpu.obs.schema import validate_rows

    header = {
        "t": 0.0, "kind": "run_start", "run_id": "r0",
        "config_digest": "abc", "rank": 0, "num_hosts": 1,
        "time_unix": 1000.0, "hostname": "h", "pid": 1,
    }
    old_shed = {
        "t": 1.0, "kind": "serve_shed", "admitted": 10,
        "shed_total": 2, "shed_frac": 0.1667,
        "by_cause": {"queue_age": 2}, "errors": 0,
        "depth": 3, "queue_age_s": 0.05,
    }
    old_stats = {
        "t": 1.0, "kind": "serve_stats", "requests": 10, "batches": 2,
        "swaps": 0, "batch_fill_mean": 5.0, "queue_p50": 0.001,
        "queue_p99": 0.002, "featurize_p50": 0.001,
        "featurize_p99": 0.002, "device_p50": 0.001,
        "device_p99": 0.002,
    }
    assert validate_rows([header, old_shed, old_stats]) == []
    new_shed = dict(old_shed, by_class={
        c: {"admitted": 3, "shed": 0}
        for c in ("bidding", "normal", "best_effort")
    })
    new_stats = dict(
        old_stats, cache_hits=5, cache_misses=5, cache_hit_rate=0.5,
        cache_entries=5, cache_bytes=300, cache_evictions=0,
        cache_invalidations=0, cache_inserts_dropped=0,
    )
    assert validate_rows([header, new_shed, new_stats]) == []
    bad = dict(old_shed, by_class="bidding")
    assert any(
        "by_class" in v for v in validate_rows([header, bad])
    )


def test_doctor_qos_inversion_and_scache_thrash(tmp_path, capsys):
    """`obs doctor`: an inverted shed window (bidding shed while a
    traffic-carrying best_effort shed nothing) reads as
    qos_inversion; a post-warmup cache window stuck under a 10% hit
    rate reads as scache_thrash; healthy windows stay clean.  `obs
    summarize` prints the per-class shed and cache hit-rate lines."""
    from xflow_tpu.obs.__main__ import main

    header = {
        "t": 0.0, "kind": "run_start", "run_id": "r0",
        "config_digest": "abc", "rank": 0, "num_hosts": 1,
        "time_unix": 1000.0, "hostname": "h", "pid": 1,
    }

    def shed_row(bid_shed, be_shed, be_adm):
        return {
            "t": 2.0, "kind": "serve_shed", "admitted": 40,
            "shed_total": bid_shed + be_shed,
            "shed_frac": (bid_shed + be_shed) / 40,
            "by_cause": {"queue_age": bid_shed + be_shed}, "errors": 0,
            "depth": 3, "queue_age_s": 0.05,
            "by_class": {
                "bidding": {"admitted": 10, "shed": bid_shed},
                "normal": {"admitted": 20, "shed": 0},
                "best_effort": {"admitted": be_adm, "shed": be_shed},
            },
        }

    def stats_row(t, hits, misses):
        total = hits + misses
        return {
            "t": t, "kind": "serve_stats", "requests": total,
            "batches": 4, "swaps": 0, "batch_fill_mean": 8.0,
            "queue_p50": 0.001, "queue_p99": 0.002,
            "featurize_p50": 0.001, "featurize_p99": 0.002,
            "device_p50": 0.001, "device_p99": 0.002,
            "cache_hits": hits, "cache_misses": misses,
            "cache_hit_rate": hits / total if total else 0.0,
            "cache_entries": 64, "cache_bytes": 4096,
            "cache_evictions": 10, "cache_invalidations": 0,
            "cache_inserts_dropped": 0,
        }

    sick = tmp_path / "sick.jsonl"
    sick.write_text("\n".join(json.dumps(r) for r in [
        header,
        shed_row(bid_shed=4, be_shed=0, be_adm=10),
        stats_row(1.0, hits=0, misses=200),   # warmup window: exempt
        stats_row(2.0, hits=5, misses=195),   # post-warmup: thrash
    ]) + "\n")
    rc = main(["doctor", str(sick)])
    text = capsys.readouterr().out
    assert rc == 1
    assert "qos_inversion" in text
    assert "scache_thrash" in text

    healthy = tmp_path / "healthy.jsonl"
    healthy.write_text("\n".join(json.dumps(r) for r in [
        header,
        shed_row(bid_shed=0, be_shed=6, be_adm=4),
        stats_row(1.0, hits=0, misses=200),
        stats_row(2.0, hits=150, misses=50),
    ]) + "\n")
    assert main(["doctor", str(healthy)]) == 0
    text = capsys.readouterr().out
    assert "qos_inversion:" not in text
    assert "scache_thrash:" not in text

    # summarize: per-class shed + cache hit-rate lines
    assert main(["summarize", str(healthy)]) == 0
    text = capsys.readouterr().out
    assert "serve shed:" in text
    assert "best_effort" in text
    assert "score cache:" in text
    assert "hit rate" in text
