"""Unit tests for the two-level one-hot MXU hot-table path (ops/hot.py).

Correctness spec: hot_gather(W, k) == W[k] (zero row for k outside
[0, H)) and hot_scatter(k, g, H) == zeros([H, D]).at[k].add(g) (dropping
out-of-range keys) — i.e. exact drop/clip parity with the DMA path of
ops/sparse.py, up to summation order in the scatter.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xflow_tpu.ops.hot import hot_factors, hot_gather, hot_scatter


def dma_gather(w, keys):
    h = w.shape[0]
    rows = w[jnp.clip(keys, 0, h - 1)]
    return jnp.where((keys >= 0)[:, None] & (keys < h)[:, None], rows, 0.0)


def dma_scatter(keys, grads, h):
    return jnp.zeros((h, grads.shape[1]), jnp.float32).at[keys].add(
        grads, mode="drop"
    )


@pytest.mark.parametrize("h", [256, 4096, 8192])
def test_factors(h):
    h1, h2 = hot_factors(h)
    assert h1 * h2 == h
    assert h1 >= h2
    assert h1 & (h1 - 1) == 0 and h2 & (h2 - 1) == 0


def test_factors_rejects_non_pow2():
    with pytest.raises(ValueError):
        hot_factors(1000)


@pytest.mark.parametrize("h,d,m", [(256, 1, 1000), (1024, 10, 4097), (4096, 1, 300)])
def test_gather_matches_dma(h, d, m):
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(h, d)).astype(np.float32))
    # include out-of-range sentinel keys (the padding convention)
    keys = rng.integers(0, h + h // 4, size=m).astype(np.int32)
    got = hot_gather(w, jnp.asarray(keys))
    want = dma_gather(w, jnp.asarray(keys))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=0, atol=0)


@pytest.mark.parametrize("h,d,m", [(256, 1, 1000), (1024, 10, 4097), (4096, 1, 300)])
def test_scatter_matches_dma(h, d, m):
    rng = np.random.default_rng(1)
    # zipf-ish duplicates so real accumulation happens
    keys = (rng.zipf(1.3, size=m) - 1).clip(0, h + 10).astype(np.int32)
    grads = jnp.asarray(rng.normal(size=(m, d)).astype(np.float32))
    got = hot_scatter(jnp.asarray(keys), grads, h)
    want = dma_scatter(jnp.asarray(keys), grads, h)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )


def test_gather_f32_is_exact_selection():
    # one-hot selection in f32 must be bit-exact, not approximately equal
    rng = np.random.default_rng(2)
    w = jnp.asarray(rng.normal(size=(512, 3)).astype(np.float32) * 1e-4)
    keys = jnp.asarray(rng.integers(0, 512, size=700).astype(np.int32))
    got = np.asarray(hot_gather(w, keys))
    want = np.asarray(w)[np.asarray(keys)]
    assert (got == want).all()


def test_bf16_mode_close():
    rng = np.random.default_rng(3)
    w = jnp.asarray(rng.normal(size=(1024, 4)).astype(np.float32))
    keys = jnp.asarray(rng.integers(0, 1024, size=2000).astype(np.int32))
    got = np.asarray(hot_gather(w, keys, dtype=jnp.bfloat16))
    want = np.asarray(w)[np.asarray(keys)]
    np.testing.assert_allclose(got, want, rtol=1e-2, atol=1e-2)


def test_jit_and_grad_flow():
    # the ops must be jittable and differentiable (autodiff models route
    # gradients through hot_gather)
    w = jnp.ones((256, 2))
    keys = jnp.asarray(np.arange(100, dtype=np.int32))

    @jax.jit
    def f(w):
        return hot_gather(w, keys).sum()

    g = jax.grad(f)(w)
    assert float(g.sum()) == 200.0  # each of 100 keys contributes d=2 ones
