"""xflow_tpu.analysis: rule-engine fixtures (every rule fires on its
minimal repro and stays silent on the idiomatic pattern), pragma +
baseline round-trips, the CLI/JSON contract (incl. --changed-only), the
tier-1 gate scripts (check_analysis + check_concurrency), the
lock-stress runtime companion backing XF003, and the sanitizer-armed
lock-order cross-check backing XF007 (docs/ANALYSIS.md).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from xflow_tpu.analysis import (
    load_baseline,
    run_analysis,
    split_baselined,
    write_baseline,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def scan(tmp_path, files: dict[str, str], select=None):
    """Write a fixture tree and run the pass over it."""
    for rel, source in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(source)
    findings, suppressed = run_analysis([str(tmp_path)], select=select)
    return findings, suppressed


def rules_fired(findings):
    return {f.rule for f in findings}


# -- XF001: recompile hazards ---------------------------------------------


def test_xf001_jit_in_loop_fires(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": (
        "import jax\n"
        "def f(tables):\n"
        "    outs = []\n"
        "    for t in tables:\n"
        "        g = jax.jit(lambda x: x + 1)\n"
        "        outs.append(g(t))\n"
        "    return outs\n"
    )}, select=["XF001"])
    assert [f.rule for f in findings] == ["XF001"]
    assert findings[0].line == 5


def test_xf001_immediate_invoke_fires(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": (
        "import jax\n"
        "def f(x):\n"
        "    return jax.jit(lambda v: v * 2)(x)\n"
    )}, select=["XF001"])
    assert rules_fired(findings) == {"XF001"}


def test_xf001_scalar_literal_into_jitted_fires(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": (
        "import jax\n"
        "def impl(x, lr):\n"
        "    return x * lr\n"
        "step = jax.jit(impl)\n"
        "def run(x):\n"
        "    return step(x, 0.05)\n"
    )}, select=["XF001"])
    assert rules_fired(findings) == {"XF001"}
    assert "scalar literal" in findings[0].message


def test_xf001_shape_derived_into_jitted_fires(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": (
        "import jax\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self.predict = jax.jit(self._impl)\n"
        "    def _impl(self, x, n):\n"
        "        return x[:n]\n"
        "    def run(self, x):\n"
        "        return self.predict(x, x.shape[0] // 2)\n"
    )}, select=["XF001"])
    assert rules_fired(findings) == {"XF001"}
    assert ".shape-derived" in findings[0].message


def test_xf001_silent_on_idiomatic(tmp_path):
    # module-level binding, array args, static_argnums, and the AOT
    # .lower().compile() idiom (serve/engine.py) must all stay quiet
    findings, _ = scan(tmp_path, {"mod.py": (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def impl(x, y):\n"
        "    return x + y\n"
        "step = jax.jit(impl)\n"
        "sized = jax.jit(impl, static_argnums=1)\n"
        "def run(x):\n"
        "    exe = jax.jit(impl).lower(x, x).compile()\n"
        "    return step(x, jnp.asarray(x)), sized(x, 4), exe(x, x)\n"
    )}, select=["XF001"])
    assert findings == []


# -- XF002: hidden host syncs ---------------------------------------------


def test_xf002_float_in_jitted_function_fires(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": (
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return float(x.sum())\n"
    )}, select=["XF002"])
    assert rules_fired(findings) == {"XF002"}
    assert "float()" in findings[0].message


def test_xf002_numpy_in_traced_closure_fires(tmp_path):
    # helper reached through the traced call graph (jax.jit(self._impl)
    # seed -> self._helper closure), numpy materialization inside
    findings, _ = scan(tmp_path, {"mod.py": (
        "import jax\n"
        "import numpy as np\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self.train = jax.jit(self._impl)\n"
        "    def _impl(self, x):\n"
        "        return self._helper(x)\n"
        "    def _helper(self, x):\n"
        "        return np.asarray(x) + 1\n"
    )}, select=["XF002"])
    assert rules_fired(findings) == {"XF002"}
    assert "asarray" in findings[0].message


def test_xf002_scan_body_is_traced(tmp_path):
    # nested defs inside a traced fn (lax.scan bodies) are traced too
    findings, _ = scan(tmp_path, {"mod.py": (
        "import jax\n"
        "@jax.jit\n"
        "def step(xs):\n"
        "    def body(carry, x):\n"
        "        return carry + int(x.sum()), None\n"
        "    return jax.lax.scan(body, 0, xs)\n"
    )}, select=["XF002"])
    assert rules_fired(findings) == {"XF002"}


def test_xf002_host_code_is_silent(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": (
        "import numpy as np\n"
        "def host(rows):\n"
        "    return float(np.asarray(rows).sum())\n"
    )}, select=["XF002"])
    assert findings == []


def test_xf002_sync_outside_span_in_hot_module_fires(tmp_path):
    findings, _ = scan(tmp_path, {"serve/eng.py": (
        "import jax\n"
        "def fetch(garr):\n"
        "    return jax.device_get(garr)\n"
    )}, select=["XF002"])
    assert rules_fired(findings) == {"XF002"}
    assert "phase/span" in findings[0].message


def test_xf002_sync_inside_span_or_cold_module_is_silent(tmp_path):
    findings, _ = scan(tmp_path, {
        "serve/eng.py": (
            "import jax\n"
            "def fetch(obs, garr):\n"
            "    with obs.phase('device_block'):\n"
            "        return jax.device_get(garr)\n"
        ),
        # utils/ is not a hot-path module: export/checkpoint cold paths
        "utils/ck.py": (
            "import jax\n"
            "def fetch(garr):\n"
            "    return jax.device_get(garr)\n"
        ),
    }, select=["XF002"])
    assert findings == []


# -- XF003: lock discipline -----------------------------------------------

_XF003_POSITIVE = (
    "import threading\n"
    "class Shared:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "        self._state = {}\n"
    "        self._n = 0\n"
    "    def locked_add(self, k, v):\n"
    "        with self._lock:\n"
    "            self._state[k] = v\n"
    "            self._n += 1\n"
    "    def racy_reset(self):\n"
    "        self._n = 0\n"
)


def test_xf003_unlocked_write_fires(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": _XF003_POSITIVE},
                       select=["XF003"])
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "XF003" and f.line == 12 and "_n" in f.message


def test_xf003_subscript_store_counts_as_write(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": (
        "import threading\n"
        "class Shared:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._state = {}\n"
        "    def locked(self, k, v):\n"
        "        with self._lock:\n"
        "            self._state[k] = v\n"
        "    def racy(self, k, v):\n"
        "        self._state[k] = v\n"
    )}, select=["XF003"])
    assert len(findings) == 1 and "_state" in findings[0].message


def test_xf003_silent_when_disciplined(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": (
        "import threading\n"
        "class Shared:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._n = 0\n"          # __init__ writes are exempt
        "    def add(self):\n"
        "        with self._lock:\n"
        "            self._n += 1\n"
        "class NoLock:\n"               # lockless classes out of scope
        "    def set(self, v):\n"
        "        self.v = v\n"
    )}, select=["XF003"])
    assert findings == []


# -- XF004: schema drift --------------------------------------------------

_SCHEMA_FIXTURE = (
    "SCHEMA = {\n"
    "    'train_epoch': {'t': float},\n"
    "    'eval': {'t': float},\n"
    "}\n"
)


def test_xf004_undeclared_kind_fires(tmp_path):
    findings, _ = scan(tmp_path, {
        "obs/schema.py": _SCHEMA_FIXTURE,
        "serve/s.py": "def f(lg):\n    lg.log('bogus_kind', {'t': 1})\n",
    }, select=["XF004"])
    assert len(findings) == 1
    assert "bogus_kind" in findings[0].message
    assert findings[0].path == "serve/s.py"


def test_xf004_unused_kind_fires_on_whole_package_scan(tmp_path):
    findings, _ = scan(tmp_path, {
        "obs/schema.py": _SCHEMA_FIXTURE,
        # trainer.py present == whole-package scan sentinel
        "trainer.py": "def f(lg):\n    lg.log('train_epoch', {'t': 1})\n",
    }, select=["XF004"])
    assert len(findings) == 1
    assert "'eval'" in findings[0].message
    assert findings[0].path == "obs/schema.py"


def test_xf004_silent_on_subtree_scan_and_on_parity(tmp_path):
    # no trainer.py: the unused-kind direction must not misfire on a
    # subtree scan that legitimately emits only some kinds
    findings, _ = scan(tmp_path, {
        "obs/schema.py": _SCHEMA_FIXTURE,
        "serve/s.py": "def f(lg):\n    lg.log('eval', {'t': 1})\n",
    }, select=["XF004"])
    assert findings == []


# -- XF005: C-ABI parity --------------------------------------------------

_HEADER_OK = (
    "typedef void* XFHandle;\n"
    "XFHandle XFCreate(const char* p);\n"
    "void XFDestroy(XFHandle h);\n"
)
_CC_OK = (
    "// shims\n"
    "XFHandle XFCreate(const char* p) {\n"
    "  return call_impl(\"create\", 0);\n"
    "}\n"
    "void XFDestroy(XFHandle h) {}\n"
)
_CAPI_OK = "def create(p):\n    return p\n"


def _abi_tree(header, cc, capi):
    return {
        "native/include/xflow_tpu.h": header,
        "native/src/c_api.cc": cc,
        "capi_impl.py": capi,
    }


def test_xf005_parity_is_silent(tmp_path):
    findings, _ = scan(
        tmp_path, _abi_tree(_HEADER_OK, _CC_OK, _CAPI_OK), select=["XF005"]
    )
    assert findings == []


def test_xf005_missing_definition_fires(tmp_path):
    header = _HEADER_OK + "int XFTrain(XFHandle h);\n"
    findings, _ = scan(
        tmp_path, _abi_tree(header, _CC_OK, _CAPI_OK), select=["XF005"]
    )
    assert len(findings) == 1
    assert "XFTrain" in findings[0].message
    assert findings[0].path.endswith("xflow_tpu.h")


def test_xf005_orphan_definition_and_missing_impl_fire(tmp_path):
    cc = _CC_OK + (
        "int XFExtra(XFHandle h) {\n"
        "  return call_impl(\"missing_impl\", 0) ? 0 : -1;\n"
        "}\n"
    )
    findings, _ = scan(
        tmp_path, _abi_tree(_HEADER_OK, cc, _CAPI_OK), select=["XF005"]
    )
    messages = " | ".join(f.message for f in findings)
    assert "XFExtra" in messages          # defined but not declared
    assert "missing_impl" in messages     # call_impl target absent


def test_xf005_orphan_python_impl_fires(tmp_path):
    capi = _CAPI_OK + "def unused_public(x):\n    return x\n"
    findings, _ = scan(
        tmp_path, _abi_tree(_HEADER_OK, _CC_OK, capi), select=["XF005"]
    )
    assert len(findings) == 1
    assert "unused_public" in findings[0].message


def test_xf005_symbols_in_comments_ignored(tmp_path):
    header = "/* XFGhost(int) is not real */\n" + _HEADER_OK
    cc = "// XFPhantom() also not real\n" + _CC_OK
    findings, _ = scan(
        tmp_path, _abi_tree(header, cc, _CAPI_OK), select=["XF005"]
    )
    assert findings == []


# -- pragmas & baseline ---------------------------------------------------


def test_pragma_suppresses_on_line_and_from_preceding_comment(tmp_path):
    findings, suppressed = scan(tmp_path, {"mod.py": (
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    a = float(x.sum())  # xf: ignore[XF002]\n"
        "    # deliberate sync, see docs (xf: ignore[XF002])\n"
        "    b = float(x.max())\n"
        "    return a + b\n"
    )}, select=["XF002"])
    assert findings == []
    assert len(suppressed) == 2


def test_pragma_wrong_rule_does_not_suppress(tmp_path):
    findings, suppressed = scan(tmp_path, {"mod.py": (
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return float(x.sum())  # xf: ignore[XF001]\n"
    )}, select=["XF002"])
    assert len(findings) == 1 and suppressed == []


def test_file_pragma_suppresses_whole_file(tmp_path):
    findings, suppressed = scan(tmp_path, {"mod.py": (
        "# xf: ignore-file[XF003]\n" + _XF003_POSITIVE
    )}, select=["XF003"])
    assert findings == [] and len(suppressed) == 1


def test_pragma_in_docstring_or_string_does_not_register(tmp_path):
    # pragma syntax QUOTED in a docstring or string literal must not
    # suppress anything — only real # comments count (tokenize-based)
    findings, suppressed = scan(tmp_path, {"mod.py": (
        '"""Suppress with xf: ignore-file[XF003] pragmas."""\n'
        "SYNTAX = 'xf: ignore[XF003]'\n" + _XF003_POSITIVE
    )}, select=["XF003"])
    assert len(findings) == 1 and suppressed == []


def test_baseline_regeneration_preserves_justifications(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": _XF003_POSITIVE},
                       select=["XF003"])
    baseline = tmp_path / "baseline.json"
    write_baseline(str(baseline), findings)
    entries = load_baseline(str(baseline))
    entries[0]["justification"] = "legacy worker, rewrite scheduled"
    with open(baseline, "w") as f:
        json.dump({"findings": entries}, f)
    # regenerate: the hand-written field must survive
    write_baseline(str(baseline), findings,
                   previous=load_baseline(str(baseline)))
    kept = load_baseline(str(baseline))
    assert kept[0]["justification"] == "legacy worker, rewrite scheduled"


def test_batcher_failing_close_releases_concurrent_closers():
    """A first closer whose stats flush raises must not leave other
    closers blocked forever on the drain event (they fail fast)."""
    from xflow_tpu.serve.batcher import MicroBatcher

    batcher = MicroBatcher(_FakeEngine(), max_wait_ms=0.5)
    batcher.emit_stats = lambda: (_ for _ in ()).throw(
        RuntimeError("boom")
    )
    outcomes: list[BaseException] = []

    def first():
        try:
            batcher.close()
        except BaseException as e:
            outcomes.append(e)

    t = threading.Thread(target=first)
    t.start()
    t.join(timeout=30)
    assert not t.is_alive()
    with pytest.raises((AssertionError, RuntimeError)):
        batcher.close()  # must return/raise promptly, never hang
    assert len(outcomes) == 1 and isinstance(outcomes[0], RuntimeError)


def test_baseline_round_trip(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": _XF003_POSITIVE},
                       select=["XF003"])
    assert findings
    baseline = tmp_path / "baseline.json"
    write_baseline(str(baseline), findings)
    entries = load_baseline(str(baseline))
    new, grandfathered, stale = split_baselined(findings, entries)
    assert new == [] and len(grandfathered) == len(findings) and stale == []


def test_baseline_matching_survives_line_drift_and_reports_stale(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": _XF003_POSITIVE},
                       select=["XF003"])
    baseline = tmp_path / "baseline.json"
    write_baseline(str(baseline), findings)
    # shift every line: the finding moves but must still match
    (tmp_path / "mod.py").write_text("# prologue\n" + _XF003_POSITIVE)
    moved, _ = run_analysis([str(tmp_path)], select=["XF003"])
    new, grandfathered, stale = split_baselined(
        moved, load_baseline(str(baseline))
    )
    assert new == [] and len(grandfathered) == 1
    # fix the defect: the entry must surface as stale, not linger
    (tmp_path / "mod.py").write_text("x = 1\n")
    fixed, _ = run_analysis([str(tmp_path)], select=["XF003"])
    new, grandfathered, stale = split_baselined(
        fixed, load_baseline(str(baseline))
    )
    assert fixed == [] and len(stale) == 1


# -- CLI + tier-1 gate ----------------------------------------------------


def _run_cli(args, cwd):
    return subprocess.run(
        [sys.executable, "-m", "xflow_tpu.analysis", *args],
        capture_output=True,
        text=True,
        cwd=cwd,
        env={**os.environ, "PYTHONPATH": REPO},
    )


def test_cli_json_contract_and_exit_codes(tmp_path):
    (tmp_path / "mod.py").write_text(_XF003_POSITIVE)
    proc = _run_cli([str(tmp_path), "--format", "json"], cwd=str(tmp_path))
    assert proc.returncode == 1, proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["ok"] is False
    assert doc["counts"]["new"] == 1
    assert doc["counts"]["by_rule"] == {"XF003": 1}
    assert doc["findings"][0]["rule"] == "XF003"
    # write a baseline, rerun: grandfathered, exit 0
    proc = _run_cli(
        [str(tmp_path), "--write-baseline"], cwd=str(tmp_path)
    )
    assert proc.returncode == 0, proc.stderr
    proc = _run_cli([str(tmp_path), "--format", "json"], cwd=str(tmp_path))
    assert proc.returncode == 0, proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["ok"] is True and doc["counts"]["grandfathered"] == 1


def test_cli_nonzero_on_every_rule_repro(tmp_path):
    """One tree holding each rule's minimal repro: the CLI exits
    non-zero and the JSON by_rule counts show all five rule IDs."""
    files = {
        "a.py": (
            "import jax\n"
            "def f(ts):\n"
            "    for t in ts:\n"
            "        g = jax.jit(lambda x: x)\n"
        ),
        "b.py": (
            "import jax\n"
            "@jax.jit\n"
            "def step(x):\n"
            "    return float(x.sum())\n"
        ),
        "c.py": _XF003_POSITIVE,
        "obs/schema.py": _SCHEMA_FIXTURE,
        "trainer.py": "def f(lg):\n    lg.log('bogus', {'t': 1})\n",
        "native/include/xflow_tpu.h": _HEADER_OK
        + "int XFTrain(XFHandle h);\n",
        "native/src/c_api.cc": _CC_OK,
        "capi_impl.py": _CAPI_OK,
    }
    for rel, source in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(source)
    proc = _run_cli([str(tmp_path), "--format", "json"], cwd=str(tmp_path))
    assert proc.returncode == 1, proc.stderr
    doc = json.loads(proc.stdout)
    assert set(doc["counts"]["by_rule"]) >= {
        "XF001", "XF002", "XF003", "XF004", "XF005"
    }


def test_cli_select_unknown_rule_is_usage_error(tmp_path):
    (tmp_path / "mod.py").write_text("x = 1\n")
    proc = _run_cli([str(tmp_path), "--select", "XF999"], cwd=str(tmp_path))
    assert proc.returncode == 2


def test_repo_tree_is_clean():
    """The acceptance gate: the shipped tree passes its own analyzer
    (pragmas justified inline, baseline empty)."""
    proc = _run_cli(["xflow_tpu"], cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_check_analysis_script():
    """The CI gate script passes — run as a subprocess exactly as CI
    does (same pattern as check_metrics_schema/check_serve_smoke)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "check_analysis.py")],
        capture_output=True,
        text=True,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -- XF003's runtime companion: lock-stress -------------------------------


class _FakeEngine:
    """Minimal engine contract for MicroBatcher: echo scoring (pctr of
    a request == its single key value), no jax involved."""

    buckets = (1, 8, 64)
    digest = "fake0000"

    def featurize(self, rows):
        return [keys for keys, _, _ in rows]

    def predict_prepared(self, batch):
        return np.asarray([float(k[0]) for k in batch])


@pytest.mark.parametrize("n_threads", [8])
def test_lock_stress_microbatcher_no_lost_updates(n_threads):
    """Hammer MicroBatcher from >= 8 threads with a barrier start: every
    future resolves to ITS request's value (no crossed futures), the
    stats counters account for every request exactly once, and
    concurrent close() calls all return the same final row.

    The stress runs SANITIZER-ARMED (analysis/sanitizer.py): every
    lock acquisition order actually taken under contention is recorded
    and must be consistent with the static XF007 graph — the runtime
    half of the concurrency gate (docs/ANALYSIS.md)."""
    from xflow_tpu.analysis import LockOrderSanitizer, static_lock_order
    from xflow_tpu.serve.batcher import MicroBatcher

    per_thread = 50
    total = n_threads * per_thread
    batcher = MicroBatcher(_FakeEngine(), max_wait_ms=0.5)
    san = LockOrderSanitizer()
    san.instrument(batcher, "_submit_lock", "MicroBatcher._submit_lock")
    san.instrument(batcher, "_swap_lock", "MicroBatcher._swap_lock")
    san.instrument(
        batcher.registry, "_lock", "MetricsRegistry._lock"
    )
    barrier = threading.Barrier(n_threads)
    results: dict[int, list] = {}
    errors: list[BaseException] = []

    def worker(tid: int):
        try:
            barrier.wait()
            futs = [
                (v, batcher.submit(np.asarray([v])))
                for v in range(tid * per_thread, (tid + 1) * per_thread)
            ]
            results[tid] = [(v, f.result(timeout=30)) for v, f in futs]
        except BaseException as e:  # pragma: no cover - failure path
            errors.append(e)

    threads = [
        threading.Thread(target=worker, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    # no crossed or torn futures: each request got exactly its value
    for tid, pairs in results.items():
        for v, got in pairs:
            assert got == float(v)
    # concurrent close: all callers see the SAME final stats row
    closed: list[dict] = []
    close_barrier = threading.Barrier(n_threads)

    def closer():
        close_barrier.wait()
        closed.append(batcher.close())

    threads = [threading.Thread(target=closer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert len(closed) == n_threads
    assert all(c == closed[0] for c in closed)
    # no lost updates in the serve counters
    stats = closed[0]
    assert stats["requests"] == total
    assert 1 <= stats["batches"] <= total
    # the orders the stress ACTUALLY took must not contradict the
    # static XF007 lock graph (acceptance criterion, ISSUE 6)
    static = static_lock_order([os.path.join(REPO, "xflow_tpu")])
    assert san.contradictions(static) == []


# -- XF006: thread lifecycle ----------------------------------------------

_XF006_NO_JOIN = (
    "import threading\n"
    "class W:\n"
    "    def start(self):\n"
    "        self._t = threading.Thread(target=self._run)\n"
    "        self._t.start()\n"
    "    def _run(self):\n"
    "        pass\n"
)


def test_xf006_started_thread_without_join_fires(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": _XF006_NO_JOIN},
                       select=["XF006"])
    assert len(findings) == 1
    assert "no join" in findings[0].message


def test_xf006_join_without_timeout_fires(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": (
        _XF006_NO_JOIN
        + "    def close(self):\n"
        + "        self._t.join()\n"
    )}, select=["XF006"])
    assert len(findings) == 1
    assert "without a timeout" in findings[0].message


def test_xf006_fire_and_forget_local_thread_fires(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": (
        "import threading\n"
        "def fire(fn):\n"
        "    t = threading.Thread(target=fn)\n"
        "    t.start()\n"
    )}, select=["XF006"])
    assert len(findings) == 1
    assert "never" in findings[0].message


def test_xf006_executor_without_shutdown_fires(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": (
        "from concurrent.futures import ThreadPoolExecutor\n"
        "class P:\n"
        "    def __init__(self):\n"
        "        self._ex = ThreadPoolExecutor(2)\n"
    )}, select=["XF006"])
    assert len(findings) == 1
    assert "shutdown" in findings[0].message


def test_xf006_str_join_does_not_satisfy_thread_join(tmp_path):
    """Regression: ', '.join(parts) in close() is a STRING join — it
    must not pass for the started thread's shutdown join."""
    findings, _ = scan(tmp_path, {"mod.py": (
        _XF006_NO_JOIN
        + "    def close(self):\n"
        + "        return ', '.join(['a', 'b'])\n"
    )}, select=["XF006"])
    assert len(findings) == 1
    assert "no join" in findings[0].message


def test_xf006_silent_on_disciplined_shutdown(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": (
        "import threading\n"
        "from concurrent.futures import ThreadPoolExecutor\n"
        "class W:\n"
        "    def start(self):\n"
        "        self._t = threading.Thread(target=self._run)\n"
        "        self._t.start()\n"
        "        self._ex = ThreadPoolExecutor(2)\n"
        "    def _run(self):\n"
        "        pass\n"
        "    def close(self):\n"
        "        self._t.join(timeout=5.0)\n"
        "        self._ex.shutdown()\n"
        "def pooled(items, fn):\n"
        "    with ThreadPoolExecutor(4) as ex:\n"
        "        return [f.result(timeout=60)\n"
        "                for f in [ex.submit(fn, i) for i in items]]\n"
    )}, select=["XF006"])
    assert findings == []


# -- XF007: lock order -----------------------------------------------------


def test_xf007_lexical_lock_order_cycle_fires(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": (
        "import threading\n"
        "class AB:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "    def ab(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n"
        "    def ba(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n"
    )}, select=["XF007"])
    assert len(findings) == 1
    assert "lock-order cycle" in findings[0].message
    assert "AB._a" in findings[0].message and "AB._b" in findings[0].message


def test_xf007_multi_item_with_cycle_fires(tmp_path):
    """Regression: `with self._a, self._b:` acquires left-to-right —
    the a->b edge must come from the ACCUMULATING held set, so the
    reversed nested order elsewhere still closes the cycle."""
    findings, _ = scan(tmp_path, {"mod.py": (
        "import threading\n"
        "class AB:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "    def ab(self):\n"
        "        with self._a, self._b:\n"
        "            pass\n"
        "    def ba(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n"
    )}, select=["XF007"])
    assert len(findings) == 1
    assert "lock-order cycle" in findings[0].message


def test_xf007_interprocedural_cycle_through_calls_fires(tmp_path):
    # a() holds _a and calls a helper that takes _b; b() holds _b and
    # calls one that takes _a — no single function shows the cycle
    findings, _ = scan(tmp_path, {"mod.py": (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "    def a(self):\n"
        "        with self._a:\n"
        "            self._grab_b()\n"
        "    def _grab_b(self):\n"
        "        with self._b:\n"
        "            pass\n"
        "    def b(self):\n"
        "        with self._b:\n"
        "            self._grab_a()\n"
        "    def _grab_a(self):\n"
        "        with self._a:\n"
        "            pass\n"
    )}, select=["XF007"])
    assert len(findings) == 1
    assert "lock-order cycle" in findings[0].message


def test_xf007_self_deadlock_lock_fires_rlock_silent(tmp_path):
    src = (
        "import threading\n"
        "class {cls}:\n"
        "    def __init__(self):\n"
        "        self._m = threading.{ctor}()\n"
        "    def nest(self):\n"
        "        with self._m:\n"
        "            with self._m:\n"
        "                pass\n"
    )
    findings, _ = scan(tmp_path, {
        "plain.py": src.format(cls="SPlain", ctor="Lock"),
        "reent.py": src.format(cls="SReent", ctor="RLock"),
    }, select=["XF007"])
    # the non-reentrant Lock self-nest fires; the RLock one is legal
    assert len(findings) == 1
    assert findings[0].path == "plain.py"
    assert "re-acquired" in findings[0].message


def test_xf007_blocking_call_under_lock_fires(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": (
        "import threading\n"
        "import queue\n"
        "class Q:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._q = queue.Queue()\n"
        "    def bad(self):\n"
        "        with self._lock:\n"
        "            return self._q.get()\n"
        "    def ok(self, fut):\n"
        "        with self._lock:\n"
        "            a = self._q.get(timeout=1.0)\n"
        "        b = self._q.get()\n"
        "        return a, b, fut.result(timeout=5)\n"
    )}, select=["XF007"])
    assert len(findings) == 1
    assert ".get() without a timeout" in findings[0].message
    assert "Q._lock" in findings[0].message


def test_xf007_consistent_order_is_silent(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": (
        "import threading\n"
        "class AB:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "    def one(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n"
        "    def two(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n"
    )}, select=["XF007"])
    assert findings == []


def test_static_lock_order_exports_edges(tmp_path):
    from xflow_tpu.analysis import static_lock_order

    (tmp_path / "mod.py").write_text(
        "import threading\n"
        "class AB:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "    def ab(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n"
    )
    assert static_lock_order([str(tmp_path)]) == {"AB._a": ["AB._b"]}


# -- XF008: shared-state discipline ---------------------------------------

_XF008_POSITIVE = (
    "import threading\n"
    "class Shared:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "        self._t = threading.Thread(target=self._work)\n"
    "        self._latest = None\n"
    "    def _work(self):\n"
    "        self._latest = 1\n"
    "    def read(self):\n"
    "        return self._latest\n"
)


def test_xf008_unguarded_cross_context_state_fires(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": _XF008_POSITIVE},
                       select=["XF008"])
    # both the worker write and the main read are unguarded sites
    assert len(findings) == 2
    assert all("_latest" in f.message for f in findings)
    kinds = {("written" in f.message, "read" in f.message)
             for f in findings}
    assert len(kinds) == 2


def test_xf008_guarded_or_handed_off_is_silent(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": (
        "import threading\n"
        "import queue\n"
        "class Shared:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._t = threading.Thread(target=self._work)\n"
        "        self._latest = None\n"
        "        self._q = queue.Queue()\n"
        "        self._cfg = 42\n"
        "    def _work(self):\n"
        "        with self._lock:\n"
        "            self._latest = 1\n"
        "        self._q.put(self._cfg)\n"  # queue hand-off + init-only read
        "    def read(self):\n"
        "        with self._lock:\n"
        "            return self._latest\n"
    )}, select=["XF008"])
    assert findings == []


def test_xf008_single_context_state_is_silent(tmp_path):
    # written and read on the main side only: no cross-context race
    findings, _ = scan(tmp_path, {"mod.py": (
        "import threading\n"
        "class M:\n"
        "    def __init__(self):\n"
        "        self._t = threading.Thread(target=self._work)\n"
        "        self._n = 0\n"
        "    def _work(self):\n"
        "        pass\n"
        "    def bump(self):\n"
        "        self._n += 1\n"
    )}, select=["XF008"])
    assert findings == []


def test_context_classification_both_contexts(tmp_path):
    """A method both submitted to an executor AND plain-called is
    classified worker AND main (the TrainStep.put_batch shape)."""
    from xflow_tpu.analysis.core import PackageIndex
    from xflow_tpu.analysis.rules_concurrency import get_context

    (tmp_path / "mod.py").write_text(
        "from concurrent.futures import ThreadPoolExecutor\n"
        "class Step:\n"
        "    def put(self, x):\n"
        "        return x\n"
        "def ring(step, items):\n"
        "    with ThreadPoolExecutor(2) as ex:\n"
        "        futs = [ex.submit(step.put, i) for i in items]\n"
        "    return [f.result(timeout=5) for f in futs]\n"
        "def inline(step, x):\n"
        "    return step.put(x)\n"
    )
    ctx = get_context(PackageIndex([str(tmp_path)]))
    put = next(f for f in ctx.fns if f.qualname == "Step.put")
    assert put.is_worker and put.is_main


# -- XF009: heartbeat coverage --------------------------------------------

_XF009_TEMPLATE = (
    "import threading\n"
    "class Pump:\n"
    "    def __init__(self, flight):\n"
    "        self.flight = flight\n"
    "        self._stop = threading.Event()\n"
    "        self._t = threading.Thread(target=self._run)\n"
    "    def _run(self):\n"
    "        while not self._stop.is_set():\n"
    "            {body}\n"
    "    def beat(self):\n"
    "        self.flight.note_loader('tick')\n"
    "    def step(self):\n"
    "        pass\n"
)


def test_xf009_silent_worker_loop_in_hot_module_fires(tmp_path):
    findings, _ = scan(tmp_path, {
        "io/pump.py": _XF009_TEMPLATE.format(body="self.step()"),
    }, select=["XF009"])
    assert len(findings) == 1
    assert "heartbeat" in findings[0].message
    assert "_run" in findings[0].message


def test_xf009_heartbeat_through_call_closure_is_silent(tmp_path):
    findings, _ = scan(tmp_path, {
        "io/pump.py": _XF009_TEMPLATE.format(body="self.beat()"),
    }, select=["XF009"])
    assert findings == []


def test_xf009_heartbeat_in_defined_but_uncalled_lambda_fires(tmp_path):
    """Regression: a heartbeat referenced only inside a nested
    def/lambda the loop DEFINES (never calls) is not a beat — the
    scoped walk must not descend into it."""
    findings, _ = scan(tmp_path, {
        "io/pump.py": _XF009_TEMPLATE.format(
            body="cb = lambda: self.flight.note_loader('t')"
        ),
    }, select=["XF009"])
    assert len(findings) == 1
    assert "heartbeat" in findings[0].message


def test_xf009_bounded_loop_cold_module_main_context_silent(tmp_path):
    findings, _ = scan(tmp_path, {
        # bounded loop (comparison in the condition): not flagged
        "io/bounded.py": (
            "import threading\n"
            "class P:\n"
            "    def __init__(self):\n"
            "        self._t = threading.Thread(target=self._run)\n"
            "    def _run(self):\n"
            "        n = 0\n"
            "        while n < 10:\n"
            "            n += 1\n"
        ),
        # unbounded worker loop, but in a COLD module
        "utils/pump.py": _XF009_TEMPLATE.format(body="self.step()"),
        # unbounded loop in a hot module, but main-context
        "io/mainloop.py": (
            "def drain(q):\n"
            "    while True:\n"
            "        if q.empty():\n"
            "            return\n"
        ),
    }, select=["XF009"])
    assert findings == []


# -- XF015: swallowed worker exceptions -----------------------------------

_XF015_TEMPLATE = (
    "import threading\n"
    "class Pump:\n"
    "    def __init__(self, obs):\n"
    "        self.obs = obs\n"
    "        self._t = threading.Thread(target=self._run)\n"
    "    def _run(self):\n"
    "        try:\n"
    "            self.step()\n"
    "        {handler}\n"
    "    def step(self):\n"
    "        pass\n"
)


def test_xf015_silent_worker_swallow_fires(tmp_path):
    findings, _ = scan(tmp_path, {
        "pump.py": _XF015_TEMPLATE.format(
            handler="except Exception:\n            pass"
        ),
    }, select=["XF015"])
    assert len(findings) == 1
    assert "swallows" in findings[0].message
    assert "_run" in findings[0].message


def test_xf015_bare_except_fires_too(tmp_path):
    findings, _ = scan(tmp_path, {
        "pump.py": _XF015_TEMPLATE.format(
            handler="except:\n            return"
        ),
    }, select=["XF015"])
    assert len(findings) == 1


@pytest.mark.parametrize("handler", [
    # re-raise
    "except Exception:\n            raise",
    # propagate the exception object into a call (set_exception shape)
    "except Exception as e:\n            self.obs.put(e)",
    # loud reporting surface (health_row / counter / warn family)
    "except Exception:\n            self.obs.counter('pump.err')",
    # exception woven into a reported message
    "except Exception as e:\n"
    "            self.obs.record(f'died: {e}')",
])
def test_xf015_loud_handlers_are_silent(tmp_path, handler):
    findings, _ = scan(tmp_path, {
        "pump.py": _XF015_TEMPLATE.format(handler=handler),
    }, select=["XF015"])
    assert findings == []


def test_xf015_narrow_and_main_context_exempt(tmp_path):
    findings, _ = scan(tmp_path, {
        # narrow idiom (queue.Empty-style control flow): exempt
        "narrow.py": _XF015_TEMPLATE.format(
            handler="except ValueError:\n            pass"
        ),
        # same swallow, but main-context (no thread seeds it): exempt
        "mainctx.py": (
            "def drain(q):\n"
            "    try:\n"
            "        q.get()\n"
            "    except Exception:\n"
            "        pass\n"
        ),
    }, select=["XF015"])
    assert findings == []


def test_xf015_pragma_suppresses(tmp_path):
    findings, suppressed = scan(tmp_path, {
        "pump.py": _XF015_TEMPLATE.format(
            handler="except Exception:  # xf: ignore[XF015]\n"
            "            pass"
        ),
    }, select=["XF015"])
    assert findings == []
    assert [f.rule for f in suppressed] == ["XF015"]


def test_xf015_handler_in_nested_def_not_credited(tmp_path):
    """A reporting call inside a nested def the handler merely DEFINES
    does not make the swallow loud."""
    findings, _ = scan(tmp_path, {
        "pump.py": _XF015_TEMPLATE.format(
            handler="except Exception:\n"
            "            cb = lambda: self.obs.counter('x')"
        ),
    }, select=["XF015"])
    assert len(findings) == 1


# -- runtime sanitizer (analysis/sanitizer.py) ----------------------------


def test_sanitizer_records_orders_and_flags_contradictions():
    from xflow_tpu.analysis import LockOrderSanitizer

    san = LockOrderSanitizer()
    wa = san.wrap(threading.Lock(), "A")
    wb = san.wrap(threading.Lock(), "B")
    with wa:
        with wb:
            pass
    assert san.edges() == {"A": {"B"}}
    # consistent with a static graph that has (or implies) A -> B
    assert san.contradictions({"A": ["B"]}) == []
    assert san.contradictions({}) == []
    # the REVERSE observed order against static A -> B is a cycle the
    # static graph alone does not contain: a contradiction
    san2 = LockOrderSanitizer()
    wa2 = san2.wrap(threading.Lock(), "A")
    wb2 = san2.wrap(threading.Lock(), "B")
    with wb2:
        with wa2:
            pass
    out = san2.contradictions({"A": ["B"]})
    assert len(out) == 1 and "A" in out[0] and "B" in out[0]


def test_sanitizer_rlock_reentry_is_not_an_edge():
    from xflow_tpu.analysis import LockOrderSanitizer

    san = LockOrderSanitizer()
    w = san.wrap(threading.RLock(), "R")
    with w:
        with w:
            pass
    assert san.edges() == {}
    assert san.contradictions({}) == []


def test_sanitizer_arming_is_opt_in():
    from xflow_tpu.analysis.sanitizer import (
        _InstrumentedLock,
        armed,
        maybe_instrument,
    )

    assert not armed({})
    assert not armed({"XFLOW_LOCK_SANITIZER": "0"})
    assert armed({"XFLOW_LOCK_SANITIZER": "1"})

    class Holder:
        def __init__(self):
            self._lock = threading.Lock()

    h = Holder()
    # unarmed: no wrapper is created, the plain lock stays
    assert maybe_instrument(h, "_lock", environ={}) is None
    assert isinstance(h._lock, type(threading.Lock()))
    # armed via env: instrumented, and idempotent
    got = maybe_instrument(
        h, "_lock", environ={"XFLOW_LOCK_SANITIZER": "1"}
    )
    assert isinstance(got, _InstrumentedLock)
    assert got.name == "Holder._lock"
    again = maybe_instrument(
        h, "_lock", environ={"XFLOW_LOCK_SANITIZER": "1"}
    )
    assert again is got


def test_check_concurrency_script():
    """The static+runtime concurrency gate passes on the shipped tree —
    run exactly as CI does (same pattern as check_analysis)."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "scripts", "check_concurrency.py")],
        capture_output=True,
        text=True,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_changed_only_scopes_to_git_diff(tmp_path):
    """--changed-only reports findings only for files changed vs HEAD
    (the fast pre-commit mode); a committed violation elsewhere in the
    tree no longer fails the scoped run."""
    def git(*args):
        proc = subprocess.run(
            ["git", *args], cwd=str(tmp_path),
            capture_output=True, text=True,
        )
        assert proc.returncode == 0, proc.stderr
        return proc

    git("init", "-q", ".")
    git("config", "user.email", "t@t")
    git("config", "user.name", "t")
    (tmp_path / "committed.py").write_text(_XF003_POSITIVE)
    git("add", "committed.py")
    git("commit", "-qm", "seed")
    proc = _run_cli(
        [str(tmp_path), "--select", "XF003", "--changed-only",
         "--format", "json"],
        cwd=str(tmp_path),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(proc.stdout)["counts"]["new"] == 0
    # an UNTRACKED file with a violation is in scope
    (tmp_path / "fresh.py").write_text(_XF003_POSITIVE)
    proc = _run_cli(
        [str(tmp_path), "--select", "XF003", "--changed-only",
         "--format", "json"],
        cwd=str(tmp_path),
    )
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["counts"]["new"] == 1
    assert doc["findings"][0]["path"] == "fresh.py"


def test_cli_changed_only_from_subdirectory_sees_untracked(tmp_path):
    """Regression: `git ls-files --others` prints paths relative to
    its cwd — run from a SUBDIRECTORY, an untracked violation there
    must still be in scope (the listing runs from the repo root)."""
    def git(*args):
        proc = subprocess.run(
            ["git", *args], cwd=str(tmp_path),
            capture_output=True, text=True,
        )
        assert proc.returncode == 0, proc.stderr
        return proc

    git("init", "-q", ".")
    git("config", "user.email", "t@t")
    git("config", "user.name", "t")
    (tmp_path / "root.py").write_text("x = 1\n")
    git("add", "root.py")
    git("commit", "-qm", "seed")
    sub = tmp_path / "sub"
    sub.mkdir()
    (sub / "fresh.py").write_text(_XF003_POSITIVE)
    proc = _run_cli(
        [".", "--select", "XF003", "--changed-only", "--format", "json"],
        cwd=str(sub),
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["counts"]["new"] == 1
    assert doc["findings"][0]["path"] == "fresh.py"


def test_cli_changed_only_outside_git_is_usage_error(tmp_path):
    (tmp_path / "mod.py").write_text("x = 1\n")
    proc = _run_cli(
        [str(tmp_path), "--changed-only"], cwd=str(tmp_path)
    )
    assert proc.returncode == 2
    assert "git" in proc.stderr


def test_cli_changed_only_baseline_interactions(tmp_path):
    """Regression: a scoped run must not misreport baseline entries of
    UNCHANGED files as stale (their findings were filtered, not fixed),
    and --changed-only --write-baseline is refused (a scoped write
    would truncate the committed baseline)."""
    def git(*args):
        proc = subprocess.run(
            ["git", *args], cwd=str(tmp_path),
            capture_output=True, text=True,
        )
        assert proc.returncode == 0, proc.stderr
        return proc

    git("init", "-q", ".")
    git("config", "user.email", "t@t")
    git("config", "user.name", "t")
    (tmp_path / "legacy.py").write_text(_XF003_POSITIVE)
    # baseline grandfathers the committed legacy finding
    proc = _run_cli([".", "--write-baseline"], cwd=str(tmp_path))
    assert proc.returncode == 0, proc.stderr
    git("add", "-A")
    git("commit", "-qm", "seed with baseline")
    # touch an UNRELATED file: the legacy entry must NOT surface stale
    (tmp_path / "other.py").write_text("x = 1\n")
    proc = _run_cli(
        [".", "--changed-only", "--format", "json"], cwd=str(tmp_path)
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["counts"]["new"] == 0
    assert doc["counts"]["stale_baseline"] == 0, doc
    # the scoped-write footgun is refused outright
    proc = _run_cli(
        [".", "--changed-only", "--write-baseline"], cwd=str(tmp_path)
    )
    assert proc.returncode == 2
    assert "write-baseline" in proc.stderr


def test_lock_stress_metrics_registry_exact_counts():
    """8 threads, barrier start, fixed per-thread work: counters sum
    exactly, histogram count is exact (no torn Histogram state), and a
    racing snapshot(reset=True) never double-counts or drops."""
    from xflow_tpu.obs.registry import MetricsRegistry

    n_threads, adds, observes = 8, 2000, 500
    reg = MetricsRegistry()
    barrier = threading.Barrier(n_threads)

    def worker():
        barrier.wait()
        for _ in range(adds):
            reg.counter_add("stress.c", 1.0)
        for i in range(observes):
            reg.observe("stress.h", float(i))

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    snap = reg.snapshot()
    assert snap.counters["stress.c"] == n_threads * adds
    assert snap.hists["stress.h"]["count"] == n_threads * observes


def test_metrics_logger_concurrent_log_no_torn_lines(tmp_path):
    """8 threads log concurrently into one MetricsLogger (the
    trainer-thread + batcher-thread sharing pattern): every line parses
    as JSON, nothing interleaves, close() races are safe."""
    from xflow_tpu.utils.logging import MetricsLogger

    path = str(tmp_path / "m.jsonl")
    logger = MetricsLogger(path)
    n_threads, per_thread = 8, 200
    barrier = threading.Barrier(n_threads)

    def worker(tid):
        barrier.wait()
        for i in range(per_thread):
            logger.log("stress", {"tid": tid, "i": i, "pad": "x" * 64})

    threads = [
        threading.Thread(target=worker, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    logger.close()
    logger.log("stress", {"late": True})  # after close: dropped, no raise
    with open(path) as f:
        rows = [json.loads(line) for line in f if line.strip()]
    assert len(rows) == n_threads * per_thread
    seen = {(r["tid"], r["i"]) for r in rows}
    assert len(seen) == n_threads * per_thread


# -- XF016-XF020: wire-protocol & failure-domain rules (ISSUE 18) ----------


def test_xf016_pack_without_unpack_fires(tmp_path):
    findings, _ = scan(tmp_path, {"wire.py": (
        "import struct\n"
        "def emit(n):\n"
        "    return struct.pack('<I', n)\n"
    )}, select=["XF016"])
    assert [f.rule for f in findings] == ["XF016"]
    assert "never unpacked" in findings[0].message


def test_xf016_unpack_without_pack_fires(tmp_path):
    findings, _ = scan(tmp_path, {"wire.py": (
        "import struct\n"
        "def read(buf):\n"
        "    return struct.unpack('<I', buf)\n"
    )}, select=["XF016"])
    assert [f.rule for f in findings] == ["XF016"]
    assert "never packed" in findings[0].message


def test_xf016_cross_module_parity_is_silent(tmp_path):
    # encoder and decoder in DIFFERENT files: parity is tree-wide
    findings, _ = scan(tmp_path, {
        "enc.py": (
            "import struct\n"
            "def emit(n):\n"
            "    return struct.pack('<I', n)\n"
        ),
        "dec.py": (
            "import struct\n"
            "def read(buf):\n"
            "    return struct.unpack('<I', buf)\n"
        ),
    }, select=["XF016"])
    assert findings == []


def test_xf016_struct_object_binding_counts(tmp_path):
    # a Struct-bound NAME.pack/.unpack pairs up like the module calls
    findings, _ = scan(tmp_path, {"wire.py": (
        "import struct\n"
        "HDR = struct.Struct('<QQ')\n"
        "def emit(a, b):\n"
        "    return HDR.pack(a, b)\n"
        "def read(buf):\n"
        "    return HDR.unpack(buf)\n"
    )}, select=["XF016"])
    assert findings == []


def test_xf016_registry_drift_and_unregistered_module(tmp_path):
    src = {
        "wire.py": (
            "import struct\n"
            "MAGIC = b'TT01'\n"
            "def emit(n):\n"
            "    return struct.pack('<I', n)\n"
            "def read(buf):\n"
            "    return struct.unpack('<I', buf)\n"
        ),
    }
    # no registry file next to the root: the registry half is unarmed
    findings, _ = scan(tmp_path, src, select=["XF016"])
    assert findings == []
    # registry present and matching: silent
    (tmp_path / "protocol-registry.json").write_text(json.dumps({
        "modules": {"wire.py": {
            "magics": {"MAGIC": b"TT01".hex()},
            "versions": {},
            "formats": ["<I"],
        }},
    }))
    findings, _ = run_analysis([str(tmp_path)], select=["XF016"])
    assert findings == []
    # registry present but the magic drifted: fires
    (tmp_path / "protocol-registry.json").write_text(json.dumps({
        "modules": {"wire.py": {
            "magics": {"MAGIC": b"TT99".hex()},
            "versions": {},
            "formats": ["<I"],
        }},
    }))
    findings, _ = run_analysis([str(tmp_path)], select=["XF016"])
    assert [f.rule for f in findings] == ["XF016"]
    assert "drifted" in findings[0].message and "magics" in findings[0].message
    # unregistered wire module: fires
    (tmp_path / "protocol-registry.json").write_text(
        json.dumps({"modules": {}})
    )
    findings, _ = run_analysis([str(tmp_path)], select=["XF016"])
    assert any("not registered" in f.message for f in findings)


def test_xf017_unbounded_result_in_serve_domain_fires(tmp_path):
    findings, _ = scan(tmp_path, {"serve/front.py": (
        "def score(fut):\n"
        "    return fut.result()\n"
    )}, select=["XF017"])
    assert [f.rule for f in findings] == ["XF017"]
    assert findings[0].line == 2


def test_xf017_timeout_and_out_of_domain_are_silent(tmp_path):
    findings, _ = scan(tmp_path, {
        # same domain, bounded: silent
        "serve/front.py": (
            "def score(fut):\n"
            "    return fut.result(timeout=5.0)\n"
        ),
        # unbounded but OUTSIDE serve/stream/store: not this rule's
        # domain (the training loop may legitimately block)
        "ops/math.py": (
            "def gather(fut):\n"
            "    return fut.result()\n"
        ),
    }, select=["XF017"])
    assert findings == []


def test_xf017_http_ctor_without_timeout_fires(tmp_path):
    findings, _ = scan(tmp_path, {"serve/client.py": (
        "import http.client\n"
        "def dial(host):\n"
        "    return http.client.HTTPConnection(host)\n"
        "def dial_bounded(host):\n"
        "    return http.client.HTTPConnection(host, timeout=10.0)\n"
    )}, select=["XF017"])
    assert [f.rule for f in findings] == ["XF017"]
    assert findings[0].line == 3


def test_xf017_bare_queue_get_fires_dict_get_silent(tmp_path):
    findings, _ = scan(tmp_path, {"stream/pump.py": (
        "def drain(q, d):\n"
        "    x = q.get()\n"
        "    y = d.get('k', 0)\n"  # dict.get carries args: not blocking
        "    return x, y\n"
    )}, select=["XF017"])
    assert [f.rule for f in findings] == ["XF017"]
    assert findings[0].line == 2


def test_xf018_uncovered_io_fires_and_failpoint_covers(tmp_path):
    findings, _ = scan(tmp_path, {"io/reader.py": (
        "def read_raw(path):\n"
        "    with open(path, 'rb') as f:\n"
        "        return f.read()\n"
    )}, select=["XF018"])
    assert [f.rule for f in findings] == ["XF018"]
    assert findings[0].line == 2  # anchored at the I/O call, not the def
    # a failpoint in the function itself covers it (fresh tree: scan
    # roots accumulate files otherwise)
    findings, _ = scan(tmp_path / "covered", {"io/covered.py": (
        "from xflow_tpu.chaos import failpoint\n"
        "def read_raw(path):\n"
        "    failpoint('reader.read')\n"
        "    with open(path, 'rb') as f:\n"
        "        return f.read()\n"
    )}, select=["XF018"])
    assert findings == []


def test_xf018_transitive_caller_coverage(tmp_path):
    # the failpoint sits in the CALLER: the callee's boundary is on an
    # injected path, so it is covered
    findings, _ = scan(tmp_path, {"io/stack.py": (
        "from xflow_tpu.chaos import failpoint\n"
        "def _raw(path):\n"
        "    with open(path, 'rb') as f:\n"
        "        return f.read()\n"
        "def fetch(path):\n"
        "    failpoint('stack.fetch')\n"
        "    return _raw(path)\n"
    )}, select=["XF018"])
    assert findings == []


def test_xf018_outside_chaos_domain_silent(tmp_path):
    findings, _ = scan(tmp_path, {"obs/dump.py": (
        "def write(path, s):\n"
        "    with open(path, 'w') as f:\n"
        "        f.write(s)\n"
    )}, select=["XF018"])
    assert findings == []


def test_xf019_wall_clock_into_digest_fires(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": (
        "import hashlib\n"
        "import time\n"
        "def stamp():\n"
        "    h = hashlib.sha256()\n"
        "    t = time.time()\n"
        "    h.update(str(t).encode())\n"
        "    return h.hexdigest()\n"
    )}, select=["XF019"])
    assert [f.rule for f in findings] == ["XF019"]
    assert "wall-clock/random" in findings[0].message


def test_xf019_taint_through_assignment_chain(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": (
        "import hashlib\n"
        "import uuid\n"
        "def tag(payload):\n"
        "    nonce = uuid.uuid4()\n"
        "    salted = payload + str(nonce)\n"
        "    return hashlib.sha256(salted.encode()).hexdigest()\n"
    )}, select=["XF019"])
    assert [f.rule for f in findings] == ["XF019"]


def test_xf019_deterministic_digest_silent(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": (
        "import hashlib\n"
        "import time\n"
        "def digest(payload):\n"
        "    t0 = time.perf_counter()\n"  # timed, but never fed in
        "    h = hashlib.sha256(payload)\n"
        "    _ = time.perf_counter() - t0\n"
        "    return h.hexdigest()\n"
    )}, select=["XF019"])
    assert findings == []


def test_xf020_native_order_fires_explicit_silent(tmp_path):
    findings, _ = scan(tmp_path, {"wire.py": (
        "import struct\n"
        "def emit(n, m, k):\n"
        "    a = struct.pack('I', n)\n"   # native order+size: fires
        "    b = struct.pack('=I', m)\n"  # native order: fires
        "    c = struct.pack('<I', k)\n"  # explicit: silent
        "    return a + b + c\n"
    )}, select=["XF020"])
    assert [f.rule for f in findings] == ["XF020", "XF020"]
    lines = sorted(f.line for f in findings)
    assert lines == [3, 4]


def test_protocol_rules_pragma_suppression(tmp_path):
    findings, suppressed = scan(tmp_path, {"serve/front.py": (
        "def score(fut):\n"
        "    # sentinel-drain: producer closes the queue (xf: ignore[XF017])\n"
        "    return fut.result()\n"
    )}, select=["XF017"])
    assert findings == []
    assert [f.rule for f in suppressed] == ["XF017"]


# -- wirefuzz: the runtime companion (analysis/wirefuzz.py) ----------------


def test_wirefuzz_deterministic_and_clean():
    """Same seed -> byte-identical mutation stream (the gate's
    reproducibility contract) and the shipped decoders refuse every
    mutant with a typed error."""
    from xflow_tpu.analysis.wirefuzz import run_wirefuzz

    a = run_wirefuzz(seed=5, rounds=25)
    b = run_wirefuzz(seed=5, rounds=25)
    assert a["mutation_digest"] == b["mutation_digest"]
    assert a["ok"] and b["ok"], (a, b)
    assert set(a["targets"]) == {
        "xfs1", "xfs2", "xfb1", "packed_v2", "binary_csr",
        "delta_manifest",
    }
    for name, t in a["targets"].items():
        c = t["counts"]
        assert c["untyped"] == 0 and c["slow"] == 0, (name, t)
        assert c["typed"] + c["accepted"] + c["accepted_mismatch"] == 25
    # a different seed explores a different mutation stream
    c = run_wirefuzz(seed=6, rounds=25)
    assert c["mutation_digest"] != a["mutation_digest"]


def test_wirefuzz_flags_untyped_and_hang(tmp_path):
    """The fuzzer itself is honest: a decoder that raises an UNTYPED
    error (or sleeps past the case budget) is a failure, not a pass."""
    from xflow_tpu.analysis import wirefuzz
    from xflow_tpu.analysis.wirefuzz import (
        FuzzTarget,
        SplitMix64,
        fuzz_target,
    )
    import hashlib

    def bad_decode(buf):
        if buf != b"GOOD":
            raise OverflowError("boom")  # not in TYPED_ERRORS

    t = FuzzTarget("bad", b"GOOD", bad_decode)
    report = fuzz_target(t, SplitMix64(1), 10, hashlib.sha256())
    assert not report["ok"]
    assert report["counts"]["untyped"] > 0
    assert any("OverflowError" in f["detail"] for f in report["failures"])
    assert wirefuzz.TYPED_ERRORS == (ValueError, KeyError, __import__("struct").error)


def test_check_protocol_script():
    """The wire-protocol gate (XF016-XF020 static + seeded decoder
    fuzz) passes on the shipped tree — run exactly as CI does."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "scripts", "check_protocol.py")],
        capture_output=True,
        text=True,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "typed refusals only" in proc.stdout
