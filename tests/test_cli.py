"""CLI + library API end-to-end on the synthetic toy data (the
reference's launch surface: main.cc argv + run_ps_local.sh)."""

import numpy as np

from xflow_tpu.api import XFlow
from xflow_tpu.train import build_parser, config_from_args, main


def test_cli_flags_to_config():
    args = build_parser().parse_args(
        [
            "--train", "/tmp/tr", "--test", "/tmp/te",
            "--model", "1",  # numeric alias per main.cc:27-45
            "--epochs", "3", "--optimizer", "sgd", "--batch-size", "32",
            "--table-size-log2", "12", "--alpha", "0.1", "--no-hash",
        ]
    )
    cfg = config_from_args(args)
    assert cfg.model == "fm"
    assert cfg.epochs == 3
    assert cfg.optimizer == "sgd"
    assert cfg.alpha == 0.1
    assert cfg.hash_mode is False
    assert cfg.table_size == 1 << 12


def test_cli_end_to_end(toy_dataset, tmp_path, capsys):
    rc = main(
        [
            "--train", toy_dataset.train_prefix,
            "--test", toy_dataset.test_prefix,
            "--model", "lr", "--epochs", "2", "--batch-size", "64",
            "--table-size-log2", "14", "--max-nnz", "24",
            "--num-devices", "1",
            "--pred-out", str(tmp_path / "pred.txt"),
        ]
    )
    assert rc == 0
    lines = (tmp_path / "pred.txt").read_text().strip().splitlines()
    assert len(lines) == toy_dataset.lines_per_shard
    label, pctr = lines[0].split("\t")
    assert label in ("0", "1")
    assert 0.0 <= float(pctr) <= 1.0


def test_cli_requires_train():
    assert main(["--model", "lr"]) == 2


def test_library_api(toy_dataset, tmp_path):
    xf = XFlow(
        toy_dataset.train_prefix,
        toy_dataset.test_prefix,
        model="lr",
        epochs=2,
        batch_size=64,
        table_size_log2=14,
        max_nnz=24,
        num_devices=1,
        checkpoint_dir=str(tmp_path),
    )
    xf.train()
    result = xf.evaluate()
    assert np.isfinite(result["logloss"])
    assert xf.save() is not None
    xf2 = XFlow(
        toy_dataset.train_prefix,
        toy_dataset.test_prefix,
        model="lr",
        epochs=2,
        batch_size=64,
        table_size_log2=14,
        max_nnz=24,
        num_devices=1,
        checkpoint_dir=str(tmp_path),
    )
    assert xf2.restore() is not None
