"""XF010–XF014 memory/sharding rules + the shapeflow symbolic
shape/dtype dataflow under them (docs/ANALYSIS.md): per-rule
positive/negative fixtures, symbolic-propagation units (call-edge and
Config-cap resolution, reshape(-1), scan carries), the
memory-budget.json round-trip incl. stale-entry failure, the
narrow_keys_i32 choke point, and the repo-tree-clean + tier-1 gate
acceptance — following the tests/test_analysis.py pattern.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from xflow_tpu.analysis import run_analysis
from xflow_tpu.analysis.core import PackageIndex

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MEM_RULES = ["XF010", "XF011", "XF012", "XF013", "XF014"]


def scan(tmp_path, files: dict[str, str], select=None):
    for rel, source in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(source)
    findings, suppressed = run_analysis([str(tmp_path)], select=select)
    return findings, suppressed


def flows(tmp_path, files: dict[str, str]):
    """The shapeflow transient map for a fixture tree."""
    from xflow_tpu.analysis.rules_memory import memory_context
    from xflow_tpu.analysis.shapeflow import shape_str

    for rel, source in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(source)
    mem = memory_context(PackageIndex([str(tmp_path)]))
    return {
        key: [(t.sf.rel, t.line, shape_str(t.shape), t.kind) for t in ts]
        for key, ts in mem.flows.items()
    }


# -- shapeflow units -------------------------------------------------------


def test_shapeflow_config_caps_and_state_seeds(tmp_path):
    """cfg.table_size resolves to the T symbol and the state pytree
    seed makes tables [T, D] — the foundation every rule stands on."""
    out = flows(tmp_path, {"mod.py": (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def step(state, batch, cfg):\n"
        "    t = state['tables']['w']['param']\n"
        "    g = jnp.zeros_like(t)\n"
        "    oh = jax.nn.one_hot(batch['slots'], cfg.max_fields)\n"
        "    return g, oh\n"
    )})
    shapes = {s for _, _, s, _ in out["mod.py::step"]}
    assert "[T, D]" in shapes


def test_shapeflow_interprocedural_call_edge(tmp_path):
    """Shapes flow through an in-package call edge: the callee's
    allocation is sized from the CALLER's arguments (Config cap +
    table row width)."""
    out = flows(tmp_path, {"mod.py": (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def helper(t, n):\n"
        "    return jnp.zeros((n, t.shape[1]))\n"
        "@jax.jit\n"
        "def step(state, cfg):\n"
        "    t = state['tables']['w']['param']\n"
        "    return helper(t, cfg.batch_size)\n"
    )})
    shapes = {s for _, _, s, _ in out["mod.py::step"]}
    assert "[B, D]" in shapes


def test_shapeflow_reshape_minus_one(tmp_path):
    out = flows(tmp_path, {"mod.py": (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def step(batch):\n"
        "    flat = batch['keys'].reshape(-1)\n"
        "    return jnp.zeros((flat.shape[0], 3))\n"
    )})
    shapes = {s for _, _, s, _ in out["mod.py::step"]}
    assert "[(B*K), 3]" in shapes


def test_shapeflow_scan_carry(tmp_path):
    """lax.scan bodies are analyzed with carry bound from the init —
    the _train_sequential shape (tables ride the carry)."""
    out = flows(tmp_path, {"mod.py": (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def step(state, batch):\n"
        "    def body(carry, x):\n"
        "        tabs, acc = carry\n"
        "        g = {n: jnp.zeros_like(t['param'])\n"
        "             for n, t in tabs.items()}\n"
        "        return (tabs, acc), None\n"
        "    return jax.lax.scan(body, (state['tables'], 0),\n"
        "                        batch['keys'])\n"
    )})
    shapes = {s for _, _, s, _ in out["mod.py::step"]}
    assert "[T, D]" in shapes


def test_shapeflow_same_line_allocs_both_counted(tmp_path):
    """Two distinct same-shape allocations on ONE source line must both
    count toward the XF014 upper bound (dedup is per column, not per
    line)."""
    out = flows(tmp_path, {"mod.py": (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def step(state):\n"
        "    t = state['tables']['w']['param']\n"
        "    a, b = jnp.zeros_like(t), jnp.zeros_like(t)\n"
        "    return a, b\n"
    )})
    table_allocs = [e for e in out["mod.py::step"] if e[2] == "[T, D]"]
    assert len(table_allocs) == 2


def test_shapeflow_gather_records_transient(tmp_path):
    out = flows(tmp_path, {"mod.py": (
        "import jax\n"
        "@jax.jit\n"
        "def step(state, batch):\n"
        "    return state['tables']['w']['param'][batch['keys']]\n"
    )})
    entries = out["mod.py::step"]
    assert ("mod.py", 4, "[B, K, D]", "gather") in entries


# -- XF010: full-table transients ------------------------------------------

_XF010_POSITIVE = (
    "import jax\n"
    "import jax.numpy as jnp\n"
    "@jax.jit\n"
    "def step(state, batch):\n"
    "    return {n: jnp.zeros_like(t['param'])\n"
    "            for n, t in state['tables'].items()}\n"
)


def test_xf010_zeros_like_table_in_jit_fires(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": _XF010_POSITIVE},
                       select=["XF010"])
    assert len(findings) == 1
    assert findings[0].rule == "XF010"
    assert "full-table" in findings[0].message
    assert "[T, D]" in findings[0].message


def test_xf010_one_hot_into_t_dim_fires(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": (
        "import jax\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self.train = jax.jit(self._impl)\n"
        "    def _impl(self, batch):\n"
        "        return jax.nn.one_hot(batch['keys'],\n"
        "                              self.cfg.table_size)\n"
    )}, select=["XF010"])
    assert len(findings) == 1
    assert "one-hot" in findings[0].message


def test_xf010_silent_on_head_scale_and_host_code(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def step(state, batch, cfg):\n"
        "    heads = {n: t['param'][:cfg.hot_size]\n"
        "             for n, t in state['tables'].items()}\n"
        "    g = {n: jnp.zeros_like(h) for n, h in heads.items()}\n"
        "    oh = jax.nn.one_hot(batch['slots'], cfg.max_fields)\n"
        "    return g, oh\n"
        "def host_init(state):\n"  # not jitted: allocation is fine
        "    return {n: jnp.zeros_like(t['param'])\n"
        "            for n, t in state['tables'].items()}\n"
    )}, select=["XF010"])
    assert findings == []


def test_xf010_pragma_suppresses(tmp_path):
    findings, suppressed = scan(tmp_path, {"mod.py": (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def step(state, batch):\n"
        "    # dense-mode design buffer (xf: ignore[XF010])\n"
        "    return {n: jnp.zeros_like(t['param'])\n"
        "            for n, t in state['tables'].items()}\n"
    )}, select=["XF010"])
    assert findings == [] and len(suppressed) == 1


# -- XF011: dtype discipline -----------------------------------------------


def test_xf011_adhoc_key_astype_fires(tmp_path):
    findings, _ = scan(tmp_path, {"io/pack.py": (
        "import numpy as np\n"
        "def pack(keys):\n"
        "    return keys.astype(np.int32)\n"
    )}, select=["XF011"])
    assert len(findings) == 1
    assert "narrow_keys_i32" in findings[0].message


def test_xf011_np_int32_coercion_of_keys_fires(tmp_path):
    findings, _ = scan(tmp_path, {"io/pack.py": (
        "import numpy as np\n"
        "def pack(batch):\n"
        "    return np.int32(batch.hot_keys)\n"
    )}, select=["XF011"])
    assert len(findings) == 1
    assert "np.int32" in findings[0].message


def test_xf011_silent_on_helper_and_non_keys(tmp_path):
    findings, _ = scan(tmp_path, {"io/pack.py": (
        "import numpy as np\n"
        "def narrow_keys_i32(keys):\n"  # THE choke point itself
        "    return keys.astype(np.int32)\n"
        "def counts(rows):\n"  # not a key plane
        "    return rows.astype(np.int32)\n"
        "def widen(keys):\n"  # widening is fine
        "    return keys.astype(np.int64)\n"
        "def sentinel():\n"  # constant coercion is fine
        "    return np.int32(-1)\n"
    )}, select=["XF011"])
    assert findings == []


def test_xf011_float64_in_traced_fires_host_silent(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": (
        "import jax\n"
        "import numpy as np\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return jnp.zeros((4,), dtype=np.float64)\n"
        "def host(x):\n"
        "    return np.zeros((4,), dtype=np.float64)\n"
    )}, select=["XF011"])
    assert len(findings) == 1
    assert findings[0].line == 6
    assert "float64" in findings[0].message


# -- XF012: sharding coverage ----------------------------------------------

_MESH_FIXTURE = 'DATA_AXIS = "data"\n'


def test_xf012_unsharded_device_put_in_hot_module_fires(tmp_path):
    findings, _ = scan(tmp_path, {
        "parallel/mesh.py": _MESH_FIXTURE,
        "parallel/put.py": (
            "import jax\n"
            "def stage(x):\n"
            "    return jax.device_put(x)\n"
        ),
    }, select=["XF012"])
    assert len(findings) == 1
    assert "without a sharding" in findings[0].message


def test_xf012_sharded_put_and_cold_module_silent(tmp_path):
    findings, _ = scan(tmp_path, {
        "parallel/mesh.py": _MESH_FIXTURE,
        "parallel/put.py": (
            "import jax\n"
            "from parallel.mesh import table_sharding\n"
            "def stage(x, mesh):\n"
            "    return jax.device_put(x, table_sharding(mesh))\n"
        ),
        "utils/ck.py": (  # cold module: restore-path puts are exempt
            "import jax\n"
            "def restore(x):\n"
            "    return jax.device_put(x)\n"
        ),
    }, select=["XF012"])
    assert findings == []


def test_xf012_adhoc_namedsharding_fires_mesh_module_silent(tmp_path):
    findings, _ = scan(tmp_path, {
        "parallel/mesh.py": (
            "from jax.sharding import Mesh, NamedSharding, "
            "PartitionSpec as P\n"
            'DATA_AXIS = "data"\n'
            "def table_sharding(mesh):\n"
            "    return NamedSharding(mesh, P(DATA_AXIS, None))\n"
        ),
        "serve/eng.py": (
            "from jax.sharding import NamedSharding, PartitionSpec\n"
            "def layout(mesh):\n"
            "    return NamedSharding(mesh, PartitionSpec('data'))\n"
        ),
    }, select=["XF012"])
    assert len(findings) == 1
    assert findings[0].path == "serve/eng.py"
    assert "outside parallel/mesh.py" in findings[0].message


def test_xf012_unknown_collective_axis_fires_declared_silent(tmp_path):
    findings, _ = scan(tmp_path, {
        "parallel/mesh.py": _MESH_FIXTURE,
        "parallel/coll.py": (
            "import jax\n"
            "def both(x):\n"
            "    good = jax.lax.psum(x, 'data')\n"
            "    bad = jax.lax.psum(x, 'model')\n"
            "    return good, bad\n"
        ),
    }, select=["XF012"])
    assert len(findings) == 1
    assert findings[0].line == 4
    assert "'model'" in findings[0].message


# -- XF013: donation safety ------------------------------------------------

_XF013_CLASS = (
    "import jax\n"
    "class S:\n"
    "    def __init__(self):\n"
    "        self.train = jax.jit(self._impl, donate_argnums=0)\n"
    "    def _impl(self, state, b):\n"
    "        return state\n"
)


def test_xf013_read_after_donation_fires(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": (
        _XF013_CLASS
        + "    def run(self, state, b):\n"
        + "        out = self.train(state, b)\n"
        + "        return out, state['step']\n"
    )}, select=["XF013"])
    assert len(findings) == 1
    assert "donated" in findings[0].message
    assert findings[0].line == 9


def test_xf013_rebind_idiom_is_silent(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": (
        _XF013_CLASS
        + "    def run(self, state, b):\n"
        + "        state = self.train(state, b)\n"
        + "        return state\n"
    )}, select=["XF013"])
    assert findings == []


def test_xf013_cross_file_receiver_call_fires(tmp_path):
    """The real call sites of a donate-bound jit live OUTSIDE the
    binding's file and go through arbitrary receivers
    (step.train(...)) — matched by attribute name package-wide."""
    findings, _ = scan(tmp_path, {
        "step.py": _XF013_CLASS,
        "trainer.py": (
            "def run(step, state, b):\n"
            "    out = step.train(state, b)\n"
            "    return out, state\n"
        ),
    }, select=["XF013"])
    assert len(findings) == 1
    assert findings[0].path == "trainer.py"
    assert "donated" in findings[0].message


def test_xf013_undonated_jit_is_silent(tmp_path):
    findings, _ = scan(tmp_path, {"mod.py": (
        "import jax\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self.predict = jax.jit(self._impl)\n"
        "    def _impl(self, state, b):\n"
        "        return state\n"
        "    def run(self, state, b):\n"
        "        out = self.predict(state, b)\n"
        "        return out, state\n"
    )}, select=["XF013"])
    assert findings == []


# -- XF014: transient budget -----------------------------------------------

_XF014_MOD = (
    "import jax\n"
    "import jax.numpy as jnp\n"
    "@jax.jit\n"
    "def step(state, batch):\n"
    "    # fixture design buffer (xf: ignore[XF010])\n"
    "    return {n: jnp.zeros_like(t['param'])\n"
    "            for n, t in state['tables'].items()}\n"
)

_GEOMETRY = {
    "T": 1 << 20, "B": 64, "K": 8, "Kh": 4, "H": 256, "S": 4,
    "families": {"lr": 1, "fm": 10},
}


def _budget_tree(budgets: dict) -> dict[str, str]:
    return {
        "mod.py": _XF014_MOD,
        "memory-budget.json": json.dumps(
            {"geometry": _GEOMETRY, "budgets": budgets}
        ),
    }


def test_xf014_within_budget_is_silent(tmp_path):
    # [T=2^20, D] f32: lr 4 MiB, fm 40 MiB
    findings, _ = scan(tmp_path, _budget_tree(
        {"mod.py::step": {"lr": 5 << 20, "fm": 41 << 20}}
    ), select=["XF014"])
    assert findings == []


def test_xf014_over_budget_fires_with_largest_site(tmp_path):
    findings, _ = scan(tmp_path, _budget_tree(
        {"mod.py::step": {"lr": 1 << 20, "fm": 41 << 20}}
    ), select=["XF014"])
    assert len(findings) == 1
    f = findings[0]
    assert "exceeds the committed budget" in f.message
    assert "'lr'" in f.message and "[T, D]" in f.message


def test_xf014_missing_entry_and_family_fire(tmp_path):
    findings, _ = scan(tmp_path, _budget_tree({}), select=["XF014"])
    assert len(findings) == 1
    assert "no memory-budget.json entry" in findings[0].message

    findings, _ = scan(tmp_path, _budget_tree(
        {"mod.py::step": {"lr": 5 << 20}}  # fm missing
    ), select=["XF014"])
    assert len(findings) == 1
    assert "no budget for model family 'fm'" in findings[0].message


def test_xf014_stale_entry_fails(tmp_path):
    """A budget entry matching no live jit must fail the run — it
    would silently grandfather a future regression under its key."""
    findings, _ = scan(tmp_path, _budget_tree({
        "mod.py::step": {"lr": 5 << 20, "fm": 41 << 20},
        "gone.py::old_step": {"lr": 1},
    }), select=["XF014"])
    assert len(findings) == 1
    assert "stale budget entry" in findings[0].message
    assert "gone.py::old_step" in findings[0].message


def test_xf014_stale_family_fires_comment_exempt(tmp_path):
    """A numeric budget value for a family the geometry no longer
    declares must fail (it would silently re-arm if the name ever
    returned); non-numeric fields (comments) are carried, not stale."""
    findings, _ = scan(tmp_path, _budget_tree({
        "mod.py::step": {
            "lr": 5 << 20, "fm": 41 << 20, "gone": 1,
            "comment": "per-entry note",
        },
    }), select=["XF014"])
    assert len(findings) == 1
    assert "stale budget family 'gone'" in findings[0].message


def test_xf014_no_budget_file_in_scope_is_silent(tmp_path):
    # fixture scans without a budget don't fire; the committed repo
    # file is enforced by scripts/check_memory.py instead
    findings, _ = scan(tmp_path, {"mod.py": _XF014_MOD},
                       select=["XF014"])
    assert findings == []


def test_budget_round_trip_validation(tmp_path):
    from xflow_tpu.analysis import load_budget

    path = tmp_path / "memory-budget.json"
    path.write_text(json.dumps({"geometry": _GEOMETRY, "budgets": {}}))
    doc = load_budget(str(path))
    assert doc["geometry"]["families"] == _GEOMETRY["families"]
    path.write_text(json.dumps({"budgets": {}}))
    with pytest.raises(ValueError, match="geometry"):
        load_budget(str(path))
    path.write_text(json.dumps({"geometry": {}, "budgets": {}}))
    with pytest.raises(ValueError, match="families"):
        load_budget(str(path))


# -- narrow_keys_i32 (the XF011 choke point) -------------------------------


def test_narrow_keys_i32_contract():
    from xflow_tpu.io.batch import narrow_keys_i32

    a = np.arange(8, dtype=np.int32)
    assert narrow_keys_i32(a) is a  # int32 passes through untouched
    wide = np.array([0, 2**20], dtype=np.int64)
    out = narrow_keys_i32(wide)
    assert out.dtype == np.int32 and out.tolist() == [0, 2**20]
    u64 = np.array([1, 5], dtype=np.uint64)
    assert narrow_keys_i32(u64).dtype == np.int32
    with pytest.raises(ValueError, match="never wrap"):
        narrow_keys_i32(np.array([2**40], dtype=np.uint64))
    with pytest.raises(ValueError, match="never wrap"):
        narrow_keys_i32(np.array([-(2**33)], dtype=np.int64))


def test_compact_wire_sentinel_ignores_masked_garbage():
    """Masked lanes may carry unreduced 64-bit garbage (external
    batches pad however they like) — only LIVE keys owe the int32
    range contract.  The sentinel coding zeroes masked lanes in the
    wide dtype BEFORE narrowing, then applies -1 in int32 space."""
    from xflow_tpu.io.batch import Batch
    from xflow_tpu.parallel.step import compact_wire_np

    def mk(mask):
        return Batch(
            keys=np.array([[1, 2**40]], dtype=np.int64),
            slots=np.zeros((1, 2), np.int32),
            vals=mask.copy(),
            mask=mask,
            labels=np.ones(1, np.float32),
            weights=np.ones(1, np.float32),
        )

    wire = compact_wire_np(mk(np.array([[1.0, 0.0]], np.float32)))
    assert wire["ckeys"].dtype == np.int32
    assert wire["ckeys"].tolist() == [[1, -1]]
    # the same garbage in a LIVE lane still rejects (never wraps)
    with pytest.raises(ValueError, match="never wrap"):
        compact_wire_np(mk(np.ones((1, 2), np.float32)))


# -- acceptance: repo tree, estimates, CLI wiring, tier-1 gate -------------


def _run_cli(args, cwd):
    return subprocess.run(
        [sys.executable, "-m", "xflow_tpu.analysis", *args],
        capture_output=True,
        text=True,
        cwd=cwd,
        env={**os.environ, "PYTHONPATH": REPO},
    )


def test_repo_tree_is_clean_under_memory_rules():
    """The ISSUE 7 acceptance gate: the shipped tree passes XF010–XF014
    (justified pragmas + committed budget only)."""
    proc = _run_cli(
        ["xflow_tpu", "--select", ",".join(MEM_RULES)], cwd=REPO
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_repo_estimates_cover_every_family_within_budget():
    """XF014 reports a per-jit transient estimate at T=2^28 for every
    model family, and the justified step.py window-end path is within
    the committed budget."""
    from xflow_tpu.analysis import estimate_transients, load_budget

    doc = load_budget(os.path.join(REPO, "memory-budget.json"))
    assert doc["geometry"]["T"] == 1 << 28
    est = estimate_transients(
        PackageIndex([os.path.join(REPO, "xflow_tpu")]), doc
    )
    train_key = "parallel/step.py::TrainStep._train_impl"
    assert train_key in est
    # the budget geometry must cover exactly the REGISTERED families
    # (models/__init__.py): a new family registers once and the memory
    # gate covers it, or this asserts
    from xflow_tpu.models import model_names

    families = set(doc["geometry"]["families"])
    assert families == set(model_names())
    # jits that are in-place scatters of donated state have NO sized
    # transients by design — a zero estimate is the correct answer
    # there, not a shapeflow bail-out (store/hot.py::_fill_impl writes
    # PROMOTE_CAP rows with .at[].set into the donated tier); the
    # serving engine's retrieval legs' dominant transient ([B, N]
    # scores over the runtime-sized item index) is unsized by the
    # static flow, so zero is legitimate there too
    zero_ok = {
        "store/hot.py::HotTier._fill_impl",
        "serve/engine.py::PredictEngine._topk_impl",
        "serve/engine.py::PredictEngine._item_embed_impl",
    }
    for key, fams in est.items():
        assert set(fams) == families
        for family, e in fams.items():
            budget = doc["budgets"][key][family]
            floor = 0 if key in zero_ok else 1
            assert floor <= e["bytes"] <= budget, (
                key, family, e["bytes"],
            )
    # the window-end [T, D] path is among the sized sites
    sites = est[train_key]["fm"]["sites"]
    assert any(
        s["shape"] == "[T, D]" and s["path"].endswith("parallel/step.py")
        for s in sites
    )
    # and the flagship-D scaling is visible: fm >> lr
    assert (
        est[train_key]["fm"]["bytes"] > 5 * est[train_key]["lr"]["bytes"]
    )


def test_new_rules_in_list_rules_and_select():
    proc = _run_cli(["--list-rules"], cwd=REPO)
    assert proc.returncode == 0
    for rule in MEM_RULES:
        assert rule in proc.stdout
    proc = _run_cli(["xflow_tpu", "--select", "XF010"], cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_memory_rules_ride_changed_only(tmp_path):
    """The pre-commit path (PR 6's --changed-only) scopes XF010 findings
    to changed files like every other rule."""
    def git(*args):
        proc = subprocess.run(
            ["git", *args], cwd=str(tmp_path),
            capture_output=True, text=True,
        )
        assert proc.returncode == 0, proc.stderr
        return proc

    git("init", "-q", ".")
    git("config", "user.email", "t@t")
    git("config", "user.name", "t")
    (tmp_path / "clean.py").write_text("x = 1\n")
    git("add", "clean.py")
    git("commit", "-qm", "seed")
    (tmp_path / "fresh.py").write_text(_XF010_POSITIVE)
    proc = _run_cli(
        [str(tmp_path), "--select", "XF010", "--changed-only",
         "--format", "json"],
        cwd=str(tmp_path),
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["counts"]["new"] == 1
    assert doc["findings"][0]["path"] == "fresh.py"
    assert doc["findings"][0]["rule"] == "XF010"


def test_check_memory_script():
    """The tier-1 gate passes on the shipped tree — run exactly as CI
    does (same pattern as check_analysis/check_concurrency)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "check_memory.py")],
        capture_output=True,
        text=True,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # the report prints an estimate line per jit per family
    assert "TrainStep._train_impl [lr]" in proc.stdout
    assert "TrainStep._train_impl [wide_deep]" in proc.stdout
    assert "budget" in proc.stdout
