"""MurmurHash64A correctness: canonical C vectors + scalar/vectorized parity."""

import numpy as np

from xflow_tpu.io.hashing import murmur64, murmur64_batch

# Golden values computed with Austin Appleby's canonical C MurmurHash64A.
CANONICAL = {
    b"": 0,
    b"a": 510903276987443985,
    b"abc": 11297775770902552315,
    b"1234567": 12582702356558746626,
    b"12345678": 8471103573108904450,
    b"123456789": 5293780161301791536,
    b"hello world, murmur": 9380668716882518948,
    b"8672": 6327032894063803160,
    b"0.3651": 14821329774425605409,
}


def test_scalar_matches_canonical():
    for data, want in CANONICAL.items():
        assert murmur64(data) == want


def test_seed():
    # canonical MurmurHash64A("abc", seed=42)
    assert murmur64(b"abc", seed=42) == 13453544136074613394


def test_batch_matches_scalar():
    rng = np.random.default_rng(0)
    tokens = [
        bytes(rng.integers(0, 256, size=int(n)).astype(np.uint8))
        for n in rng.integers(0, 40, size=500)
    ]
    got = murmur64_batch(tokens)
    want = np.array([murmur64(t) for t in tokens], dtype=np.uint64)
    np.testing.assert_array_equal(got, want)


def test_str_and_bytes_agree():
    assert murmur64("8672") == murmur64(b"8672")
