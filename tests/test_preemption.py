"""Graceful preemption: SIGTERM during training checkpoints (weights +
optimizer state + data cursor) and exits cleanly; --resume continues.

The reference's only recovery story is ``pkill -9`` and a full restart
(scripts/stop.sh:1, SURVEY §5 failure-detection row); this is the
capability gap filled.  Crash forensics (ISSUE 4) ride the same exit
paths: an exception or preemption mid-epoch must leave a fully-flushed
schema-valid metrics file AND a parseable flight dump naming the phase
that was active.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest


@pytest.fixture(scope="module")
def big_dataset(tmp_path_factory):
    from tests.gen_data import generate_dataset

    root = tmp_path_factory.mktemp("preempt")
    return generate_dataset(
        str(root),
        num_train_shards=2,
        lines_per_shard=2000,
        num_fields=10,
        vocab_per_field=32,
        seed=3,
    )


def test_sigterm_checkpoints_and_resume_completes(big_dataset, tmp_path):
    ck = tmp_path / "ck"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
    )
    cmd = [
        sys.executable, "-m", "xflow_tpu.train",
        "--model", "lr",
        "--train", big_dataset.train_prefix,
        "--test", big_dataset.test_prefix,
        "--epochs", "500",  # far more than fits before the signal
        "--batch-size", "64",
        "--table-size-log2", "14",
        "--max-nnz", "16",
        "--num-devices", "1",
        "--checkpoint-dir", str(ck),
        "--checkpoint-every-steps", "5",
        "--platform", "cpu",  # env alone is overridden by TPU plugins
    ]
    proc = subprocess.Popen(
        cmd, env=env, stderr=subprocess.PIPE, text=True, cwd=os.getcwd()
    )
    # wait until training demonstrably progresses (first checkpoint lands)
    deadline = time.time() + 180
    while time.time() < deadline and not (ck / "LATEST").exists():
        if proc.poll() is not None:
            pytest.fail(f"trainer exited early: {proc.communicate()[1]}")
        time.sleep(0.5)
    assert (ck / "LATEST").exists(), "no checkpoint appeared within deadline"

    proc.send_signal(signal.SIGTERM)
    try:
        _, err = proc.communicate(timeout=120)
    except subprocess.TimeoutExpired:
        proc.kill()
        pytest.fail("trainer did not exit after SIGTERM")
    assert proc.returncode == 0, err
    assert "preempted: checkpoint saved" in err

    # resume: must pick up the cursor and run to completion (small epoch
    # count now) without error
    resume_cmd = [c for c in cmd]
    resume_cmd[resume_cmd.index("--epochs") + 1] = "1"
    resume_cmd.append("--resume")
    out = subprocess.run(
        resume_cmd, env=env, stderr=subprocess.PIPE, text=True,
        cwd=os.getcwd(), timeout=300,
    )
    assert out.returncode == 0, out.stderr
    assert "resumed at" in out.stderr
    assert "auc" in out.stderr  # evaluation ran after completed training


def test_midepoch_crash_flushes_metrics_and_flight_dump(
    big_dataset, tmp_path, monkeypatch
):
    """ISSUE 4 satellite: an exception raised mid-epoch still yields
    (a) a schema-valid, fully-flushed metrics file — including the
    flight_dump pointer row — and (b) a parseable flight dump naming
    the phase that was active when the run died."""
    from xflow_tpu.config import Config
    from xflow_tpu.obs.flight import load_dump
    from xflow_tpu.obs.schema import validate_rows
    from xflow_tpu.trainer import Trainer

    out = tmp_path / "m.jsonl"
    flight = tmp_path / "flight.json"
    cfg = Config(
        train_path=big_dataset.train_prefix,
        model="lr",
        epochs=3,
        batch_size=64,
        table_size_log2=14,
        max_nnz=16,
        num_devices=1,
        metrics_out=str(out),
        obs_flight_out=str(flight),
    )
    orig = Trainer.iter_train_batches

    def dies_midway(self, *a, **kw):
        for i, item in enumerate(orig(self, *a, **kw)):
            if i == 3:
                raise RuntimeError("shard went away mid-epoch")
            yield item

    monkeypatch.setattr(Trainer, "iter_train_batches", dies_midway)
    t = Trainer(cfg)
    with pytest.raises(RuntimeError, match="mid-epoch"):
        t.train()
    # (a) the metrics file is flushed, closed, and schema-valid
    assert t.metrics_logger.closed
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    assert validate_rows(rows) == []
    dump_rows = [r for r in rows if r["kind"] == "flight_dump"]
    assert len(dump_rows) == 1
    assert dump_rows[0]["reason"] == "exception"
    assert dump_rows[0]["path"] == str(flight)
    # (b) the flight dump parses and names the active phase (the crash
    # surfaced while the loop was pulling from the input iterator)
    doc = load_dump(str(flight))
    assert doc["reason"] == "exception"
    assert doc["active_phase"] == "input_stall"
    assert dump_rows[0]["active_phase"] == "input_stall"
    assert doc["exception"]["type"] == "RuntimeError"
    assert "mid-epoch" in doc["exception"]["message"]
    assert doc["record"]["last_batch"] is not None  # batches were in flight
    assert any(t_["stack"] for t_ in doc["threads"])
    # a second close() must not write a second dump row
    t.close()
    rows2 = [json.loads(l) for l in out.read_text().splitlines()]
    assert rows2 == rows


def test_preemption_mid_checkpoint_resume_auto_roundtrip(
    big_dataset, tmp_path
):
    """ISSUE 11 satellite: a run killed MID-CHECKPOINT (the
    ckpt.finalize failpoint fires between manifest write and rename —
    the worst preemption moment) leaves the previous complete
    generation restorable, and `--resume auto` picks it and runs to
    completion with a schema-valid metrics stream."""
    from xflow_tpu import chaos
    from xflow_tpu.config import Config
    from xflow_tpu.obs.schema import validate_rows
    from xflow_tpu.trainer import Trainer
    from xflow_tpu.utils.checkpoint import latest_complete

    ck = tmp_path / "ck"
    metrics = tmp_path / "m.jsonl"
    cfg = Config(
        train_path=big_dataset.train_prefix,
        model="lr",
        epochs=1,
        batch_size=64,
        table_size_log2=14,
        max_nnz=16,
        num_devices=1,
        checkpoint_dir=str(ck),
        checkpoint_every_steps=5,
        metrics_out=str(metrics),
    )
    # the 3rd mid-epoch save dies mid-commit: two complete generations
    # exist by then, so the fallback has something to restore
    chaos.arm("ckpt.finalize:nth=3")
    t1 = Trainer(cfg)
    try:
        with pytest.raises(chaos.ChaosError):
            t1.train()
    finally:
        t1.close()
        chaos.disarm()
    survivor = latest_complete(str(ck))
    assert survivor is not None

    t2 = Trainer(cfg)
    try:
        cursor = t2.restore(auto=True)
        assert cursor is not None
        # mid-shard cursor: the save recorded a real resume offset
        assert {"shard", "offset"} <= set(cursor["cursors"][0])
        history = t2.train()
        assert history and not history[-1].get("preempted")
    finally:
        t2.close()
    rows = [json.loads(l) for l in metrics.read_text().splitlines()]
    assert validate_rows(rows) == []
    causes = [r["cause"] for r in rows if r["kind"] == "health"]
    assert "checkpoint_save_failed" in causes
    assert [r["site"] for r in rows if r["kind"] == "chaos"] == [
        "ckpt.finalize"
    ]
