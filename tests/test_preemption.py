"""Graceful preemption: SIGTERM during training checkpoints (weights +
optimizer state + data cursor) and exits cleanly; --resume continues.

The reference's only recovery story is ``pkill -9`` and a full restart
(scripts/stop.sh:1, SURVEY §5 failure-detection row); this is the
capability gap filled.
"""

import os
import signal
import subprocess
import sys
import time

import pytest


@pytest.fixture(scope="module")
def big_dataset(tmp_path_factory):
    from tests.gen_data import generate_dataset

    root = tmp_path_factory.mktemp("preempt")
    return generate_dataset(
        str(root),
        num_train_shards=2,
        lines_per_shard=2000,
        num_fields=10,
        vocab_per_field=32,
        seed=3,
    )


def test_sigterm_checkpoints_and_resume_completes(big_dataset, tmp_path):
    ck = tmp_path / "ck"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
    )
    cmd = [
        sys.executable, "-m", "xflow_tpu.train",
        "--model", "lr",
        "--train", big_dataset.train_prefix,
        "--test", big_dataset.test_prefix,
        "--epochs", "500",  # far more than fits before the signal
        "--batch-size", "64",
        "--table-size-log2", "14",
        "--max-nnz", "16",
        "--num-devices", "1",
        "--checkpoint-dir", str(ck),
        "--checkpoint-every-steps", "5",
        "--platform", "cpu",  # env alone is overridden by TPU plugins
    ]
    proc = subprocess.Popen(
        cmd, env=env, stderr=subprocess.PIPE, text=True, cwd=os.getcwd()
    )
    # wait until training demonstrably progresses (first checkpoint lands)
    deadline = time.time() + 180
    while time.time() < deadline and not (ck / "LATEST").exists():
        if proc.poll() is not None:
            pytest.fail(f"trainer exited early: {proc.communicate()[1]}")
        time.sleep(0.5)
    assert (ck / "LATEST").exists(), "no checkpoint appeared within deadline"

    proc.send_signal(signal.SIGTERM)
    try:
        _, err = proc.communicate(timeout=120)
    except subprocess.TimeoutExpired:
        proc.kill()
        pytest.fail("trainer did not exit after SIGTERM")
    assert proc.returncode == 0, err
    assert "preempted: checkpoint saved" in err

    # resume: must pick up the cursor and run to completion (small epoch
    # count now) without error
    resume_cmd = [c for c in cmd]
    resume_cmd[resume_cmd.index("--epochs") + 1] = "1"
    resume_cmd.append("--resume")
    out = subprocess.run(
        resume_cmd, env=env, stderr=subprocess.PIPE, text=True,
        cwd=os.getcwd(), timeout=300,
    )
    assert out.returncode == 0, out.stderr
    assert "resumed at" in out.stderr
    assert "auc" in out.stderr  # evaluation ran after completed training
