"""Hot-table path end-to-end: steering, frequency remap, and the
invariant that hot-enabled training is numerically the same model as
DMA-only training (the remap is a permutation of row placement and the
f32 MXU gather is exact — docs/PERF.md)."""

import numpy as np
import pytest

from xflow_tpu.config import Config
from xflow_tpu.io.batch import split_hot
from xflow_tpu.io import freq
from xflow_tpu.trainer import Trainer


# -- unit: steering ---------------------------------------------------------


def test_split_hot_steering():
    # row 0: 3 hot (capacity 2 -> one spills cold), 1 cold
    # row 1: all cold;  row 2: padding-only tail
    keys = np.array([[1, 2, 3, 50], [60, 70, 80, 90], [5, 0, 0, 0]], np.int32)
    slots = np.arange(12, dtype=np.int32).reshape(3, 4)
    vals = np.ones((3, 4), np.float32) * 2.0
    mask = np.array(
        [[1, 1, 1, 1], [1, 1, 1, 1], [1, 0, 0, 0]], np.float32
    )
    out = split_hot(keys, slots, vals, mask, hot_size=10, hot_nnz=2)
    np.testing.assert_array_equal(out["hot_keys"], [[1, 2], [0, 0], [5, 0]])
    np.testing.assert_array_equal(out["hot_mask"], [[1, 1], [0, 0], [1, 0]])
    # cold section: row 0 gets the spilled hot key 3 plus 50
    np.testing.assert_array_equal(
        out["keys"], [[3, 50], [60, 70], [0, 0]]
    )
    np.testing.assert_array_equal(out["mask"], [[1, 1], [1, 1], [0, 0]])
    # slots travel with their entries
    np.testing.assert_array_equal(out["hot_slots"], [[0, 1], [0, 0], [8, 0]])
    np.testing.assert_array_equal(out["slots"], [[2, 3], [4, 5], [0, 0]])
    # cold truncation: row 1 had 4 cold entries but capacity 2
    assert out["keys"].shape == (3, 2)


def test_split_hot_no_entry_lost_when_capacity_suffices():
    # each row: 3 hot keys (< 30), 3 cold keys (>= 30), 2 pad entries;
    # capacities kh=4, kc=8-4=4 suffice, so no entry may be dropped
    rng = np.random.default_rng(0)
    hot_part = rng.integers(0, 30, (16, 3))
    cold_part = rng.integers(30, 100, (16, 3))
    pad = np.zeros((16, 2), dtype=np.int64)
    keys = np.concatenate([hot_part, cold_part, pad], axis=1).astype(np.int32)
    mask = np.concatenate(
        [np.ones((16, 6)), np.zeros((16, 2))], axis=1
    ).astype(np.float32)
    vals = rng.random((16, 8)).astype(np.float32) * mask
    slots = rng.integers(0, 5, (16, 8)).astype(np.int32)
    out = split_hot(keys, slots, vals, mask, hot_size=30, hot_nnz=4)
    total_in = int(mask.sum())
    total_out = int(out["hot_mask"].sum() + out["mask"].sum())
    assert total_in == total_out
    # multiset of (key, val) pairs preserved
    def pairs(k, v, m):
        sel = m > 0
        return sorted(zip(k[sel].tolist(), v[sel].tolist()))

    got = sorted(
        pairs(out["hot_keys"], out["hot_vals"], out["hot_mask"])
        + pairs(out["keys"], out["vals"], out["mask"])
    )
    assert got == pairs(keys, vals * mask, mask)


# -- unit: frequency remap --------------------------------------------------


def test_build_remap_is_permutation_capturing_head():
    rng = np.random.default_rng(1)
    t = 1 << 12
    # zipfian occurrences
    occ = (rng.zipf(1.2, size=200_000) - 1) % t
    counts = np.bincount(occ, minlength=t).astype(np.int64)
    h = 256
    remap = freq.build_remap(counts, h)
    assert sorted(remap.tolist()) == list(range(t))  # bijection
    # the H most frequent keys all land in [0, H)
    top = np.argsort(counts)[::-1][:h]
    assert (remap[top] < h).all()
    assert freq.hot_mass(counts, remap, h) > 0.5  # zipf head dominates


def test_count_keys_samples_front(tmp_path):
    p = tmp_path / "f-00000"
    lines = [f"1\t0:{i % 7}:1.0\n" for i in range(1000)]
    p.write_text("".join(lines))
    from xflow_tpu.io.loader import make_parse_fn

    parse_fn = make_parse_fn(1 << 12, True, 0, prefer_native=False)
    counts = freq.count_keys([str(p)], parse_fn, 1 << 12, sample_bytes=10**9)
    assert counts.sum() == 1000
    assert (counts > 0).sum() == 7


# -- end-to-end: hot == cold ------------------------------------------------


@pytest.fixture(scope="module")
def zipfy_dataset(tmp_path_factory):
    # wider vocab than the session toy set so hot(256) is a strict subset
    from tests.gen_data import generate_dataset

    root = tmp_path_factory.mktemp("zipfy")
    return generate_dataset(
        str(root),
        num_train_shards=2,
        lines_per_shard=300,
        num_fields=10,
        vocab_per_field=64,
        seed=11,
        scale=3.0,
    )


def _cfg(ds, **kw):
    base = dict(
        train_path=ds.train_prefix,
        test_path=ds.test_prefix,
        epochs=4,
        batch_size=64,
        table_size_log2=14,
        max_nnz=16,
        max_fields=12,
        num_devices=1,
    )
    base.update(kw)
    return Config(**base)


@pytest.mark.parametrize("model", ["lr", "fm", "ffm"])
def test_hot_training_matches_dma_training(zipfy_dataset, model, tmp_path):
    # ffm exercises the mixed per-table hot route (TableSpec.hot):
    # w rides the MXU one-hot path, v keeps DMA for hot occurrences
    cold = Trainer(_cfg(zipfy_dataset, model=model))
    cold.train()
    cold_out = tmp_path / "cold_pred.txt"
    cold_res = cold.evaluate(pred_out=str(cold_out))

    hot = Trainer(
        _cfg(
            zipfy_dataset,
            model=model,
            hot_size_log2=8,
            hot_nnz=8,
            freq_sample_mib=1,
        )
    )
    assert hot.remap is not None
    hot_out = tmp_path / "hot_pred.txt"
    hot.train()
    hot_res = hot.evaluate(pred_out=str(hot_out))

    # same logical model: per-example predictions equal up to float
    # summation order
    cold_p = np.loadtxt(cold_out, usecols=1)
    hot_p = np.loadtxt(hot_out, usecols=1)
    np.testing.assert_allclose(hot_p, cold_p, rtol=2e-3, atol=2e-4)
    assert abs(hot_res["auc"] - cold_res["auc"]) < 1e-3


def test_hot_remap_persists_and_resumes(zipfy_dataset, tmp_path):
    ckdir = tmp_path / "ck"
    cfg = _cfg(
        zipfy_dataset,
        model="lr",
        epochs=2,
        hot_size_log2=8,
        hot_nnz=8,
        checkpoint_dir=str(ckdir),
    )
    t1 = Trainer(cfg)
    t1.train()
    r1 = t1.evaluate()
    assert (ckdir / "remap.npy").exists()

    # a fresh trainer must load the SAME remap (not recount) and restore
    t2 = Trainer(cfg.replace(epochs=2))
    np.testing.assert_array_equal(t1.remap, t2.remap)
    assert t2.restore() is not None
    r2 = t2.evaluate()
    assert abs(r1["logloss"] - r2["logloss"]) < 1e-6


def test_hot_multidevice_sharded_step(zipfy_dataset):
    # full hot train step over the 8-virtual-device CPU mesh: validates
    # that the MXU-path one-hot matmuls and the [0:H) dense add compile
    # and psum correctly under pjit row-sharding
    trainer = Trainer(
        _cfg(
            zipfy_dataset,
            model="fm",
            epochs=1,
            num_devices=0,  # all 8 virtual devices
            hot_size_log2=8,
            hot_nnz=8,
        )
    )
    trainer.train()
    res = trainer.evaluate()
    assert 0.0 < res["auc"] <= 1.0


def test_prepare_batch_applies_remap_for_external_batches(zipfy_dataset):
    # XFlow.predict_batch path: a user-built Batch carries raw hash-space
    # keys; prepare_batch must remap + re-steer so predictions match the
    # internal (loader-prepared) pipeline exactly
    import jax

    from xflow_tpu.io.loader import ShardLoader

    cfg = _cfg(
        zipfy_dataset, model="lr", epochs=2,
        hot_size_log2=8, hot_nnz=8, freq_sample_mib=1,
    )
    tr = Trainer(cfg)
    tr.train()
    path = zipfy_dataset.test_prefix + "-00000"
    raw_loader = ShardLoader(
        path, batch_size=cfg.batch_size, max_nnz=cfg.max_nnz,
        table_size=cfg.table_size, parse_fn=tr._parse_fn(),
    )
    int_loader = tr._loader(path)
    n = 0
    for (rb, _), (ib, _) in zip(
        raw_loader.iter_batches(), int_loader.iter_batches()
    ):
        p_ext = jax.device_get(
            tr.step.predict(tr.state, tr.step.put_batch(tr.prepare_batch(rb)))
        )
        p_int = jax.device_get(
            tr.step.predict(tr.state, tr.step.put_batch(ib))
        )
        np.testing.assert_allclose(p_ext, p_int, rtol=1e-5, atol=1e-6)
        n += 1
    assert n > 0


def test_hot_toggle_across_checkpoint_dir_is_rejected(zipfy_dataset, tmp_path):
    # checkpointed table rows live in one key space; silently flipping
    # the hot remap on or off across runs must be refused
    ck_hot = tmp_path / "ck_hot"
    cfg_hot = _cfg(
        zipfy_dataset, model="lr", epochs=1,
        hot_size_log2=8, hot_nnz=8, checkpoint_dir=str(ck_hot),
    )
    Trainer(cfg_hot).train()
    with pytest.raises(ValueError, match="hot table"):
        Trainer(cfg_hot.replace(hot_size_log2=0))

    ck_cold = tmp_path / "ck_cold"
    cfg_cold = _cfg(
        zipfy_dataset, model="lr", epochs=1, checkpoint_dir=str(ck_cold)
    )
    Trainer(cfg_cold).train()
    with pytest.raises(ValueError, match="WITHOUT"):
        Trainer(cfg_cold.replace(hot_size_log2=8, hot_nnz=8))


def test_hot_requires_dense_mode():
    with pytest.raises(ValueError):
        Config(hot_size_log2=8, update_mode="sparse")
