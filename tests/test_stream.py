"""Continuous-training subsystem (xflow_tpu/stream/; ISSUE 12,
docs/CONTINUOUS.md): streaming ingestion, incremental delta export,
SLO-gated hot-swap.

Covers: the durable ingestion cursor's atomic flush + resume contract,
the follower's tail-safety (never observes tmp/partial shards) and
chaos-poll healing, delta-export round-trips (full export vs
base+deltas bitwise-identical on dense AND tiered stores, FTRL slots
excluded, digest-chain mismatch refused actionably), the delta-size
acceptance bar, the packed-writer mid-write-kill regression, the
doctor's servable_stale rankings, and the tier-1 streaming gate
(scripts/check_continuous.py)."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from xflow_tpu.config import Config  # noqa: E402
from xflow_tpu.io import packed  # noqa: E402
from xflow_tpu.stream.delta import (  # noqa: E402
    TouchedLedger,
    apply_delta,
    delta_nbytes,
    export_delta,
)
from xflow_tpu.stream.follower import (  # noqa: E402
    IngestCursor,
    ShardFollower,
)
from xflow_tpu.trainer import Trainer  # noqa: E402


def _cfg(ds, **kw):
    base = dict(
        train_path=ds.train_prefix,
        model="lr",
        epochs=1,
        batch_size=64,
        table_size_log2=16,
        max_nnz=24,
        num_devices=1,
        parse_workers=1,
    )
    base.update(kw)
    return Config(**base)


def _pack_shard(ds, i, out_dir, name=None, table_log2=16):
    os.makedirs(out_dir, exist_ok=True)
    dst = os.path.join(out_dir, name or f"shard-{i:05d}.pk")
    packed.convert_shard(
        f"{ds.train_prefix}-{i:05d}",
        dst,
        batch_size=64,
        max_nnz=24,
        table_size=1 << table_log2,
        hash_mode=True,
        hash_seed=0,
        fmt="v2",
    )
    return dst


def _train_steps(trainer, ledger, n, shard=None):
    """Drive ``n`` steps through Trainer.train_stream from one shard's
    loader, marking the ledger per batch (the driver's hook)."""
    src = shard or f"{trainer.cfg.train_path}-00000"

    def feed():
        taken = 0
        while taken < n:  # loop the shard until n steps are fed
            for batch, _ in trainer._loader(src).iter_batches():
                if taken >= n:
                    return
                if ledger is not None:
                    ledger.mark(batch)
                taken += 1
                yield batch, None

    for _ in trainer.train_stream(feed()):
        pass


def _engine_tables(engine):
    import jax

    return {
        t: np.asarray(jax.device_get(d["param"]))
        for t, d in engine.state["tables"].items()
    }


# -- ingestion cursor -------------------------------------------------------


def test_cursor_flush_atomic_roundtrip(tmp_path):
    path = str(tmp_path / "cursor.json")
    c = IngestCursor(path)
    c.note("shard-00000.pk", 4096)
    c.flush()
    c.mark_done("shard-00000.pk")
    c.note("shard-00001.pk", 128)
    c.flush()
    # atomic: no tmp residue, and a reload sees exactly the flushed state
    assert not [n for n in os.listdir(tmp_path) if ".tmp" in n]
    c2 = IngestCursor(path)
    assert c2.done == {"shard-00000.pk"}
    assert c2.current == "shard-00001.pk" and c2.offset == 128
    # idempotent: a clean cursor's flush is a no-op (mtime stable)
    before = os.path.getmtime(path)
    time.sleep(0.01)
    c2.flush()
    assert os.path.getmtime(path) == before


def test_trainer_close_flushes_cursor(toy_dataset, tmp_path):
    """Satellite: Trainer.close() flushes the registered ingestion
    cursor through the atomic tmp+os.replace path, so a graceful
    preemption loses at most the in-flight shard (at-least-once)."""
    cfg = _cfg(toy_dataset)
    path = str(tmp_path / "cursor.json")
    with Trainer(cfg) as trainer:
        c = IngestCursor(path)
        trainer.register_stream_cursor(c)
        c.note("shard-00002.pk", 777)  # dirty, never explicitly flushed
    c2 = IngestCursor(path)
    assert c2.current == "shard-00002.pk" and c2.offset == 777


# -- follower ---------------------------------------------------------------


def test_follower_tails_and_skips_tmp(toy_dataset, tmp_path):
    stream = str(tmp_path / "stream")
    _pack_shard(toy_dataset, 0, stream)
    # writer scratch + foreign junk must never reach the trainer
    with open(os.path.join(stream, "shard-00001.pk.tmp.123"), "wb") as f:
        f.write(b"garbage half-written shard")
    cfg = _cfg(toy_dataset)
    trainer = Trainer(cfg)
    cursor = IngestCursor(str(tmp_path / "cursor.json"))
    appended = []

    def stop():
        # append a second shard after the first is consumed; stop once
        # both are done
        if cursor.done and not appended:
            appended.append(_pack_shard(toy_dataset, 1, stream))
        return len(cursor.done) >= 2

    fol = ShardFollower(
        stream, trainer._loader, cursor,
        poll_interval_s=0.05, stop=stop,
    )
    seen = [meta.shard for _, meta in fol.batches()]
    trainer.close()
    assert "shard-00000.pk" in seen
    assert "shard-00001.pk" in seen  # tail picked up the appended file
    assert not any(".tmp" in s for s in seen)
    assert cursor.done == {"shard-00000.pk", "shard-00001.pk"}
    # ingest order is stable and stamped
    metas = seen  # names only; timestamps checked via cursor state
    assert metas == sorted(metas)


def test_follower_resume_skips_done_shards(toy_dataset, tmp_path):
    stream = str(tmp_path / "stream")
    _pack_shard(toy_dataset, 0, stream)
    _pack_shard(toy_dataset, 1, stream)
    cfg = _cfg(toy_dataset)
    trainer = Trainer(cfg)
    cpath = str(tmp_path / "cursor.json")
    c1 = IngestCursor(cpath)
    fol = ShardFollower(
        stream, trainer._loader, c1,
        poll_interval_s=0.05, idle_stop_s=0.2,
    )
    n_first = sum(1 for _ in fol.batches())
    assert n_first > 0 and c1.done == {
        "shard-00000.pk", "shard-00001.pk"
    }
    # a restarted follower on the durable cursor re-trains NOTHING
    c2 = IngestCursor(cpath)
    fol2 = ShardFollower(
        stream, trainer._loader, c2,
        poll_interval_s=0.05, idle_stop_s=0.2,
    )
    assert sum(1 for _ in fol2.batches()) == 0
    # ... and a third shard appended later streams alone (no replay)
    _pack_shard(toy_dataset, 2, stream)
    c3 = IngestCursor(cpath)
    fol3 = ShardFollower(
        stream, trainer._loader, c3,
        poll_interval_s=0.05, idle_stop_s=0.2,
    )
    shards = {meta.shard for _, meta in fol3.batches()}
    trainer.close()
    assert shards == {"shard-00002.pk"}


def test_follower_poll_fault_heals(toy_dataset, tmp_path):
    """The stream.poll failpoint: an injected transient listing fault
    heals through the bounded retry — the stream is complete and
    identical to the fault-free run."""
    from xflow_tpu import chaos

    stream = str(tmp_path / "stream")
    _pack_shard(toy_dataset, 0, stream)
    cfg = _cfg(toy_dataset)
    trainer = Trainer(cfg)
    try:
        reg = chaos.arm("seed=1;stream.poll:nth=1")
        cursor = IngestCursor(str(tmp_path / "cursor.json"))
        fol = ShardFollower(
            stream, trainer._loader, cursor,
            poll_interval_s=0.05, idle_stop_s=0.2,
        )
        n = sum(1 for _ in fol.batches())
        assert reg.fired().get("stream.poll") == 1
        assert n > 0 and cursor.done == {"shard-00000.pk"}
    finally:
        chaos.disarm()
        trainer.close()


# -- packed-writer tail safety (satellite) ----------------------------------


def test_packed_midwrite_kill_leaves_no_readable_partial(
    toy_dataset, tmp_path
):
    """Kill a packed-v2 writer mid-write (SIGKILL — no cleanup runs):
    the destination name must not exist, the only residue is a
    ``.tmp``-infixed scratch file, and neither the format sniffer nor
    the follower's listing can mistake it for a shard."""
    dst = str(tmp_path / "stream" / "shard-00000.pk")
    script = f"""
import os, signal, sys, time
sys.path.insert(0, {REPO!r})
import numpy as np
from xflow_tpu.io import packed
from xflow_tpu.io.loader import ShardLoader

loader = ShardLoader(
    {toy_dataset.train_prefix + "-00000"!r}, batch_size=64, max_nnz=24,
    table_size=1 << 16,
)

def batches():
    for i, (b, _) in enumerate(loader.iter_batches()):
        if i == 1:
            sys.stdout.write("MID\\n"); sys.stdout.flush()
            os.kill(os.getpid(), signal.SIGKILL)
        yield b

meta = dict(batch_size=64, cold_nnz=24, hot_nnz=0, hot_size=0,
            table_size=1 << 16, hash_mode=True, hash_seed=0,
            remap_sha256=None)
packed.write_shard_v2({dst!r}, meta, batches())
"""
    proc = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == -signal.SIGKILL, proc.stderr
    assert "MID" in proc.stdout  # it died mid-write, not before
    stream_dir = os.path.dirname(dst)
    assert not os.path.exists(dst)
    residue = os.listdir(stream_dir)
    assert residue and all(".tmp" in n for n in residue)
    for n in residue:
        assert not packed.is_packed_shard(os.path.join(stream_dir, n))
    # the follower's discovery never surfaces the residue
    cursor = IngestCursor(str(tmp_path / "cursor.json"))
    fol = ShardFollower(
        stream_dir, lambda p: None, cursor, poll_interval_s=0.05,
    )
    assert fol.pending_shards() == []


# -- delta export round-trips -----------------------------------------------


def _roundtrip(ds, tmp_path, cfg, shard=None):
    """Train → base → train more (x2) → full vs base+delta1+delta2."""
    from xflow_tpu.serve.artifact import export_artifact
    from xflow_tpu.serve.engine import PredictEngine

    trainer = Trainer(cfg)
    try:
        ledger = TouchedLedger()
        _train_steps(trainer, None, 4, shard)
        base_dir = str(tmp_path / "base")
        export_artifact(trainer, base_dir)
        base_step = 4
        _train_steps(trainer, ledger, 3, shard)
        d1 = str(tmp_path / "delta1")
        m1 = export_delta(trainer, d1, ledger, base_step)
        ledger.reset()
        _train_steps(trainer, ledger, 2, shard)
        d2 = str(tmp_path / "delta2")
        m2 = export_delta(trainer, d2, ledger, m1["step"])
        full_dir = str(tmp_path / "full")
        export_artifact(trainer, full_dir)
    finally:
        trainer.close()
    # FTRL slot state never ships: param-plane files only
    for d in (d1, d2):
        names = os.listdir(d)
        assert not [n for n in names if ".n." in n or ".z." in n]
        assert any(n.endswith(".param.npy") for n in names)
    eng = PredictEngine.load(base_dir, warm=False)
    eng = apply_delta(eng, d1)
    assert eng.servable_digest == m1["delta_digest"]
    eng = apply_delta(eng, d2)
    assert eng.servable_digest == m2["delta_digest"]
    ref = PredictEngine.load(full_dir, warm=False)
    assert eng.servable_digest == ref.servable_digest
    got, want = _engine_tables(eng), _engine_tables(ref)
    assert set(got) == set(want)
    for t in want:
        assert np.array_equal(got[t], want[t]), (
            f"table {t}: base+deltas diverged from the full export"
        )
    import jax

    for dname, arr in ref.state["dense"].items():
        assert np.array_equal(
            np.asarray(jax.device_get(eng.state["dense"][dname])),
            np.asarray(jax.device_get(arr)),
        )
    return eng, ref


def test_delta_roundtrip_dense_bitwise(toy_dataset, tmp_path):
    _roundtrip(toy_dataset, tmp_path, _cfg(toy_dataset))


def test_delta_roundtrip_dense_hot_table(toy_dataset, tmp_path):
    """Hot-table (MXU head) geometry: hot-section ids are table rows,
    so the ledger must cover them too."""
    cfg = _cfg(
        toy_dataset,
        hot_size_log2=8,
        hot_nnz=8,
        freq_sample_mib=1,
    )
    _roundtrip(toy_dataset, tmp_path, cfg)


def test_delta_roundtrip_tiered_bitwise(toy_dataset, tmp_path):
    """Tiered store: delta rows read through the two-tier logical view
    (hot tier + cold store + lazy init), still bitwise-identical to a
    full export."""
    cfg = _cfg(
        toy_dataset,
        model="fm",
        store_mode="tiered",
        hot_capacity_log2=10,
        v_dim=4,
    )
    _roundtrip(toy_dataset, tmp_path, cfg)


def test_delta_chain_mismatch_refused(toy_dataset, tmp_path):
    """Out-of-order application fails loudly with the fix in the
    message — never silently skews weights."""
    from xflow_tpu.serve.artifact import export_artifact
    from xflow_tpu.serve.engine import PredictEngine

    cfg = _cfg(toy_dataset)
    trainer = Trainer(cfg)
    try:
        ledger = TouchedLedger()
        _train_steps(trainer, None, 2)
        base_dir = str(tmp_path / "base")
        export_artifact(trainer, base_dir)
        _train_steps(trainer, ledger, 2)
        d1 = str(tmp_path / "delta1")
        export_delta(trainer, d1, ledger, 2)
        ledger.reset()
        _train_steps(trainer, ledger, 2)
        d2 = str(tmp_path / "delta2")
        export_delta(trainer, d2, ledger, 4)
    finally:
        trainer.close()
    eng = PredictEngine.load(base_dir, warm=False)
    with pytest.raises(ValueError) as ei:
        apply_delta(eng, d2)  # skipped delta1 — chain broken
    msg = str(ei.value)
    assert "digest-chain mismatch" in msg
    assert "intervening deltas" in msg  # actionable: what to do
    # the chain applies cleanly in order
    eng = apply_delta(eng, d1)
    eng = apply_delta(eng, d2)
    # ... and a delta never applies twice
    with pytest.raises(ValueError, match="digest-chain mismatch"):
        apply_delta(eng, d1)


def test_delta_bytes_incremental_at_2e22(toy_dataset, tmp_path):
    """Acceptance: for a run touching <10% of rows between exports,
    delta bytes < 25% of a full export at table_size_log2 >= 22."""
    from xflow_tpu.serve.artifact import export_artifact

    cfg = _cfg(toy_dataset, table_size_log2=22)
    trainer = Trainer(cfg)
    try:
        ledger = TouchedLedger()
        _train_steps(trainer, None, 2)
        _train_steps(trainer, ledger, 2)
        touched_frac = len(ledger) / cfg.table_size
        assert touched_frac < 0.10  # the premise of the bar
        d = str(tmp_path / "delta")
        export_delta(trainer, d, ledger, 2)
        full = str(tmp_path / "full")
        export_artifact(trainer, full)
    finally:
        trainer.close()
    ratio = delta_nbytes(d) / delta_nbytes(full)
    assert ratio < 0.25, (
        f"delta is {ratio:.1%} of a full export — not incremental"
    )


def test_driver_checkpoint_restart_consistent(toy_dataset, tmp_path):
    """With --checkpoint-dir, a restarted driver restores the model
    AND rewinds the ingestion cursor to the checkpoint's embedded
    snapshot: shards trained after the checkpoint REPLAY on the
    restored weights (at-least-once) — a restart can never train new
    shards on fresh weights while the cursor skips the old ones."""
    import jax

    from xflow_tpu.stream.driver import StreamDriver

    stream = str(tmp_path / "stream")
    _pack_shard(toy_dataset, 0, stream)
    _pack_shard(toy_dataset, 1, stream)
    work = str(tmp_path / "work")
    cfg = _cfg(toy_dataset, checkpoint_dir=str(tmp_path / "ck"))
    kw = dict(
        replicas=1, export_every_steps=3, min_canary_requests=2,
        canary_frac=1.0, idle_stop_s=0.4, poll_interval_s=0.05,
        rollout_timeout_s=30.0, buckets=(1, 8),
    )
    d1 = StreamDriver(cfg, stream, work, **kw)
    s1 = d1.run()
    assert s1["exports"] >= 1 and s1["shards_ingested"] == 2
    # run 1 finished the stream: its BOUNDARY cursor marks both done,
    # but the checkpoint embedded the snapshot at its export step
    d2 = StreamDriver(cfg, stream, work, resume="auto", **kw)
    try:
        restored_step = int(jax.device_get(d2.trainer.state["step"]))
        assert restored_step > 0 and restored_step % 3 == 0
        # cursor rewound to the checkpoint: the stream AFTER the
        # checkpoint is pending again, not skipped
        assert not (
            d2.cursor.done == {"shard-00000.pk", "shard-00001.pk"}
            and d2.cursor.current is None
        )
        pending = d2.follower.pending_shards()
        assert pending, "rewound cursor left nothing to replay"
    finally:
        d2.close()
    # a fresh-model restart against a populated cursor warns loudly
    logs: list[str] = []
    d3 = StreamDriver(
        _cfg(toy_dataset), stream, work, log=logs.append, **kw
    )
    d3.close()
    assert any("MODEL starts fresh" in s for s in logs)


# -- doctor: servable_stale -------------------------------------------------


def _header():
    return {
        "t": 0.0, "kind": "run_start", "run_id": "r1",
        "config_digest": "cfg0", "rank": 0, "num_hosts": 1,
        "time_unix": 1000.0,
    }


def _fresh_row(event, age, slo=30.0, step=10):
    return {
        "t": 1.0, "kind": "freshness", "event": event,
        "newest_event_age_s": age, "slo_s": slo, "servable": "s1",
        "export_kind": "delta", "step": step, "rows": 10,
        "delta_bytes": 100, "deltas_since_base": 1,
    }


def _rollout_row(event):
    return {
        "t": 2.0, "kind": "rollout", "event": event,
        "from_digest": "aaa", "to_digest": "aaa",
        "canary_frac": 0.25, "canary_requests": 10,
        "canary_errors": 0, "detail": "",
    }


def _doctor(tmp_path, rows):
    from xflow_tpu.obs.doctor import diagnose, format_diagnosis

    findings = diagnose(rows)
    return findings, format_diagnosis("x", rows, findings)


def test_doctor_servable_stale_over_slo(tmp_path):
    rows = [
        _header(),
        _rollout_row("begin"), _rollout_row("commit"),
        _fresh_row("commit", 5.0),
        _fresh_row("commit", 95.0),  # last row is over the 30s SLO
    ]
    findings, text = _doctor(tmp_path, rows)
    stale = [f for f in findings if f.code == "servable_stale"]
    assert stale and stale[0].severity == "warn"
    assert "over the 30s SLO" in text

    # healthy stream: no servable_stale, diagnosis clean
    rows[-1] = _fresh_row("commit", 3.0)
    findings, text = _doctor(tmp_path, rows)
    assert not [f for f in findings if f.code == "servable_stale"]
    assert "clean" in text


def test_doctor_servable_stale_repeated_aborts(tmp_path):
    rows = [
        _header(),
        _rollout_row("begin"), _rollout_row("commit"),
        _fresh_row("commit", 2.0),
        _rollout_row("begin"), _rollout_row("abort"),
        _fresh_row("abort", 10.0),
        _rollout_row("begin"), _rollout_row("abort"),
        _fresh_row("abort", 20.0),
    ]
    findings, text = _doctor(tmp_path, rows)
    stale = [f for f in findings if f.code == "servable_stale"]
    assert stale and "repeatedly aborting" in text
    # one commit resets the abort streak
    rows += [_rollout_row("commit"), _fresh_row("commit", 2.0)]
    findings, _ = _doctor(tmp_path, rows)
    assert not [
        f for f in findings
        if f.code == "servable_stale"
        and "aborting" in f.message
    ]


def test_doctor_servable_stale_begin_without_commit(tmp_path):
    """The begin-with-no-commit case: a stream run that cut and
    canaried exports but never shipped one is stale AND canary-stuck,
    never clean."""
    rows = [
        _header(),
        _fresh_row("export", 1.0),
        _rollout_row("begin"), _rollout_row("canary"),
    ]
    findings, text = _doctor(tmp_path, rows)
    codes = {f.code for f in findings if f.severity == "warn"}
    assert "servable_stale" in codes
    assert "canary_stuck" in codes
    assert "never committed" in text


# -- tier-1 gate ------------------------------------------------------------


def test_check_continuous_script():
    """The continuous-training gate (scripts/check_continuous.py)
    passes — run as a subprocess exactly as CI would (tier-1 wiring,
    like check_chaos.py)."""
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "scripts", "check_continuous.py"),
        ],
        capture_output=True,
        text=True,
        timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout
