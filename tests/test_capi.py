"""C ABI embed library: a real C program trains and evaluates through
libxflow_tpu.so (the live counterpart of the reference's dead c_api,
c_api.h:26-41)."""

import os
import shutil
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("python3-config") is None,
    reason="native toolchain not available",
)

DRIVER = textwrap.dedent(
    """
    #include <stdio.h>
    #include "xflow_tpu.h"

    int main(int argc, char** argv) {
      if (argc < 4) return 10;
      XFHandle h = XFCreate(argv[1], argv[2], argv[3]);
      if (!h) { fprintf(stderr, "create: %s\\n", XFLastError()); return 1; }
      if (XFStartTrain(h)) {
        fprintf(stderr, "train: %s\\n", XFLastError());
        return 2;
      }
      double ll = -1.0, auc = -1.0;
      if (XFEvaluate(h, &ll, &auc)) {
        fprintf(stderr, "eval: %s\\n", XFLastError());
        return 3;
      }
      printf("logloss=%.6f auc=%.6f\\n", ll, auc);
      XFDestroy(h);
      return 0;
    }
    """
)


def test_c_driver_trains_and_evaluates(toy_dataset, tmp_path):
    from xflow_tpu.native.build import CAPI_LIB, build_capi, _DIR

    build_capi()
    assert CAPI_LIB.exists()

    src = tmp_path / "driver.c"
    src.write_text(DRIVER)
    exe = tmp_path / "driver"
    subprocess.run(
        [
            "g++", "-o", str(exe), str(src),
            f"-I{_DIR / 'include'}",
            str(CAPI_LIB),
            f"-Wl,-rpath,{CAPI_LIB.parent}",
        ],
        check=True,
        capture_output=True,
        text=True,
    )

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(
        os.environ,
        PYTHONPATH=repo_root,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
    )
    cfg = (
        '{"model": "lr", "epochs": 4, "batch_size": 64, '
        '"table_size_log2": 14, "max_nnz": 24, "num_devices": 1}'
    )
    out = subprocess.run(
        [str(exe), toy_dataset.train_prefix, toy_dataset.test_prefix, cfg],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert "logloss=" in out.stdout and "auc=" in out.stdout
    auc = float(out.stdout.split("auc=")[1].split()[0])
    assert 0.0 < auc <= 1.0


def test_c_driver_reports_errors(tmp_path):
    # bad config JSON must surface through XFLastError, not crash
    from xflow_tpu.native.build import CAPI_LIB, build_capi, _DIR

    build_capi()
    src = tmp_path / "driver.c"
    src.write_text(DRIVER)
    exe = tmp_path / "driver"
    subprocess.run(
        [
            "g++", "-o", str(exe), str(src),
            f"-I{_DIR / 'include'}",
            str(CAPI_LIB),
            f"-Wl,-rpath,{CAPI_LIB.parent}",
        ],
        check=True,
        capture_output=True,
        text=True,
    )
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo_root, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [str(exe), "/nonexistent", "/nonexistent", '{"model": "nope"}'],
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert out.returncode == 1
    assert "nope" in out.stderr  # Config's unknown-model ValueError text
