"""Parser behavior vs the reference loader spec
(load_minibatch_hash_data_fread, load_data_from_disk.cc:103-210)."""

import io

import numpy as np

from xflow_tpu.io.batch import pack_batch
from xflow_tpu.io.hashing import murmur64
from xflow_tpu.io.libffm import BlockReader, parse_block
from xflow_tpu.io.loader import ShardLoader, shard_path

TABLE = 1 << 12


def test_basic_parse_hash_mode():
    data = b"1\t0:123:0.5 2:abc:1.0\n0\t1:123:0.25\n"
    blk = parse_block(data, TABLE, hash_mode=True)
    assert blk.num_samples == 2
    np.testing.assert_array_equal(blk.labels, [1.0, 0.0])
    np.testing.assert_array_equal(blk.row_ptr, [0, 2, 3])
    np.testing.assert_array_equal(blk.slots, [0, 2, 1])
    # hash mode: fid token hashed as string, value discarded (binary)
    assert blk.keys[0] == murmur64(b"123") % TABLE
    assert blk.keys[1] == murmur64(b"abc") % TABLE
    np.testing.assert_array_equal(blk.vals, [1.0, 1.0, 1.0])
    # same token in different fields hashes identically (reference hashes
    # the fid token only, load_data_from_disk.cc:151)
    assert blk.keys[0] == blk.keys[2]


def test_label_binarization():
    # y > 1e-7 → 1 (load_data_from_disk.cc:131-134)
    data = b"0.5\t0:1:1\n1e-8\t0:1:1\n-3\t0:1:1\n2\t0:1:1\n"
    blk = parse_block(data, TABLE)
    np.testing.assert_array_equal(blk.labels, [1.0, 0.0, 0.0, 1.0])


def test_numeric_mode_keeps_values():
    data = b"1 3:77:0.25 4:9:2.0\n"
    blk = parse_block(data, TABLE, hash_mode=False)
    np.testing.assert_array_equal(blk.keys, [77, 9])
    np.testing.assert_allclose(blk.vals, [0.25, 2.0])


def test_malformed_tokens_skipped():
    data = b"1\t0:1:1 garbage x:y 2:3\nnotalabel\t0:1:1\n0\t1:5:1\n"
    blk = parse_block(data, TABLE)
    assert blk.num_samples == 2  # "notalabel" line dropped
    np.testing.assert_array_equal(blk.row_ptr, [0, 1, 2])


def test_block_reader_partial_line_carry():
    lines = [f"{i % 2}\t0:{i}:1.0\n".encode() for i in range(100)]
    raw = b"".join(lines)
    # Tiny blocks force mid-line splits; carry must reassemble every line.
    reader = BlockReader(io.BytesIO(raw), block_bytes=7)
    out = b"".join(reader)
    assert out == raw
    # every yielded chunk ends on a line boundary
    reader2 = BlockReader(io.BytesIO(raw), block_bytes=13)
    for chunk in reader2:
        assert chunk.endswith(b"\n")


def test_block_reader_no_trailing_newline():
    raw = b"1\t0:1:1\n0\t0:2:1"
    chunks = list(BlockReader(io.BytesIO(raw), block_bytes=4))
    assert b"".join(chunks) == raw


def test_pack_batch_padding_and_truncation():
    data = b"1\t0:1:1 1:2:1 2:3:1\n0\t0:4:1\n"
    blk = parse_block(data, TABLE)
    b = pack_batch(blk, 0, 2, batch_size=4, max_nnz=2)
    assert b.keys.shape == (4, 2)
    # sample 0 truncated to 2 features
    np.testing.assert_array_equal(b.mask[0], [1.0, 1.0])
    np.testing.assert_array_equal(b.mask[1], [1.0, 0.0])
    np.testing.assert_array_equal(b.weights, [1.0, 1.0, 0.0, 0.0])
    np.testing.assert_array_equal(b.labels[:2], [1.0, 0.0])


def test_shard_path():
    assert shard_path("/x/data", 3) == "/x/data-00003"  # lr_worker.cc:210


def test_loader_roundtrip(tmp_path):
    path = tmp_path / "shard"
    n = 137
    with open(path, "w") as f:
        for i in range(n):
            f.write(f"{i % 2}\t0:{i}:1.0 1:tok{i}:0.5\n")
    loader = ShardLoader(
        str(path), batch_size=16, max_nnz=4, table_size=TABLE, block_mib=1
    )
    total = 0
    for batch, resume in loader.iter_batches():
        total += batch.num_real()
    assert total == n
    assert resume == path.stat().st_size


def test_loader_resume_cursor(tmp_path):
    path = tmp_path / "shard"
    with open(path, "w") as f:
        for i in range(64):
            f.write(f"1\t0:{i}:1.0\n")
    loader = ShardLoader(
        str(path), batch_size=8, max_nnz=2, table_size=TABLE, block_mib=1
    )
    batches = list(loader.iter_batches())
    # resuming from a yielded offset replays exactly the lines at/after it
    _, resume = batches[3]
    with open(path, "rb") as f:
        f.seek(resume)
        lines_after = sum(1 for _ in f)
    replayed = sum(b.num_real() for b, _ in loader.iter_batches(resume))
    assert replayed == lines_after
    # resume at EOF yields nothing
    assert list(loader.iter_batches(batches[-1][1])) == []
