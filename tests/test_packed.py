"""Packed-batch cache (io/packed.py): stored batches must be
bit-identical to what the text loader assembles at the same config, the
geometry validation must refuse mismatched caches, and training from a
packed prefix must reproduce training from text exactly."""

import os

import numpy as np
import pytest

from xflow_tpu.io import packed
from xflow_tpu.io.loader import ShardLoader

from tests.test_binary import batches_equal, make_loader

T = 1 << 14


@pytest.fixture(scope="module")
def packed_shard(toy_dataset, tmp_path_factory):
    src = toy_dataset.train_prefix + "-00000"
    dst = str(tmp_path_factory.mktemp("pk") / "shard-00000")
    meta = packed.convert_shard(
        src, dst, batch_size=64, max_nnz=24, table_size=T, block_mib=0.002
    )
    return src, dst, meta


def test_packed_matches_text(packed_shard):
    src, dst, meta = packed_shard
    assert packed.is_packed_shard(dst)
    assert meta["examples"] == 200
    assert packed.shard_example_count(dst) == 200
    text = list(make_loader(src).iter_batches())
    pk = list(make_loader(dst).iter_batches())
    assert len(text) == len(pk) == meta["batches"]
    for (tb, _), (pb, _) in zip(text, pk):
        batches_equal(tb, pb)


def test_packed_hot_remap(toy_dataset, tmp_path):
    """Hot geometry + remap bake into the cache; loading with the same
    remap matches text, with a different remap refuses."""
    src = toy_dataset.train_prefix + "-00000"
    dst = str(tmp_path / "hot-00000")
    rng = np.random.default_rng(3)
    remap = rng.permutation(T).astype(np.int32)
    packed.convert_shard(
        src, dst, batch_size=64, max_nnz=24, table_size=T,
        hot_size=256, hot_nnz=6, remap=remap, block_mib=0.002,
    )
    kw = dict(remap=remap, hot_size=256, hot_nnz=6)
    text = list(make_loader(src, **kw).iter_batches())
    pk = list(make_loader(dst, **kw).iter_batches())
    for (tb, _), (pb, _) in zip(text, pk):
        batches_equal(tb, pb)
    other = rng.permutation(T).astype(np.int32)
    with pytest.raises(ValueError, match="remap_sha256"):
        list(make_loader(dst, remap=other, hot_size=256, hot_nnz=6).iter_batches())


def test_packed_geometry_mismatch_rejected(packed_shard):
    _, dst, _ = packed_shard
    with pytest.raises(ValueError, match="batch_size"):
        list(make_loader(dst, batch_size=32).iter_batches())
    with pytest.raises(ValueError, match="cold_nnz"):
        list(make_loader(dst, max_nnz=16).iter_batches())
    with pytest.raises(ValueError, match="table_size"):
        list(make_loader(dst, table_size=1 << 12).iter_batches())
    with pytest.raises(ValueError, match="seed"):
        list(make_loader(dst, hash_seed=9).iter_batches())


def test_packed_resume_exact(packed_shard):
    """Packed resume offsets are exact (record-aligned): no replay at
    all, unlike the block-granularity text/CSR caches."""
    _, dst, _ = packed_shard
    loader = make_loader(dst)
    full = list(loader.iter_batches())
    assert len(full) > 2
    _, resume = full[0]
    tail = list(loader.iter_batches(start_offset=resume))
    assert len(tail) == len(full) - 1
    for (fb, fo), (tb, to) in zip(full[1:], tail):
        batches_equal(fb, tb)
        assert fo == to


def test_packed_stale_resume_cursor_rejected(packed_shard):
    """A resume offset past EOF (checkpoint cursor against a cache
    rebuilt shorter) fails with a clear message — like the CSR cache's
    'past the shard end' — instead of silently dropping the shard
    remainder or claiming a truncated record."""
    _, dst, _ = packed_shard
    loader = make_loader(dst)
    full = list(loader.iter_batches())
    rec_size = full[1][1] - full[0][1]  # record-aligned stride
    with pytest.raises(ValueError, match="past the packed shard end"):
        list(loader.iter_batches(start_offset=full[-1][1] + rec_size))


def test_packed_cli_and_training_parity(toy_dataset, tmp_path):
    out = str(tmp_path / "pk")
    rc = packed.main([
        "--train", toy_dataset.train_prefix, "--out", out,
        "--batch-size", "64", "--max-nnz", "24",
        "--table-size-log2", "14", "--block-mib", "0.01",
    ])
    assert rc == 0
    assert sorted(os.listdir(tmp_path)) == ["pk-00000", "pk-00001", "pk-00002"]

    from xflow_tpu.config import Config
    from xflow_tpu.trainer import Trainer
    import jax

    base = dict(
        model="lr", epochs=2, batch_size=64, table_size_log2=14,
        max_nnz=24, num_devices=1, test_path=toy_dataset.test_prefix,
    )
    t_text = Trainer(Config(train_path=toy_dataset.train_prefix, **base))
    t_text.train()
    t_pk = Trainer(Config(train_path=out, **base))
    t_pk.train()
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(t_text.state["tables"]["w"]["param"])),
        np.asarray(jax.device_get(t_pk.state["tables"]["w"]["param"])),
    )
