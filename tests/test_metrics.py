"""Sigmoid clamp, rank-sum AUC (reference algorithm), logloss."""

import numpy as np
import jax.numpy as jnp

from xflow_tpu.utils.metrics import (
    AucAccumulator,
    auc_rank_sum,
    logloss,
    sigmoid_ref,
)


def test_sigmoid_clamps():
    # base.h:54-63: x<-30 → 1e-6, x>30 → 1.0
    x = jnp.asarray([-31.0, -30.0, 0.0, 30.0, 31.0])
    p = np.asarray(sigmoid_ref(x))
    assert p[0] == 1e-6
    assert p[4] == 1.0
    np.testing.assert_allclose(p[2], 0.5)
    assert 0.0 < p[1] < 1e-12 or p[1] > 0  # plain sigmoid at -30
    np.testing.assert_allclose(p[3], 1.0 / (1.0 + np.exp(-30.0)), rtol=1e-6)


def test_auc_perfect_and_random():
    labels = np.array([1, 1, 0, 0])
    assert auc_rank_sum(labels, np.array([0.9, 0.8, 0.2, 0.1])) == 1.0
    assert auc_rank_sum(labels, np.array([0.1, 0.2, 0.8, 0.9])) == 0.0
    # one class only → NaN (reference prints tp_n only, base.h:102-104)
    assert np.isnan(auc_rank_sum(np.ones(4), np.random.rand(4)))


def test_auc_matches_pairwise_oracle():
    rng = np.random.default_rng(0)
    labels = (rng.random(200) < 0.3).astype(int)
    pctr = rng.random(200)
    got = auc_rank_sum(labels, pctr)
    pos = pctr[labels == 1]
    neg = pctr[labels == 0]
    # reference counts a positive above a negative; sort-desc walk counts
    # strictly-greater pairs plus ties ordered positive-first by stable sort.
    wins = (pos[:, None] > neg[None, :]).sum()
    assert abs(got - wins / (len(pos) * len(neg))) < 1e-6


def test_logloss_natural_log():
    labels = jnp.asarray([1.0, 0.0])
    pctr = jnp.asarray([0.8, 0.2])
    want = -(np.log(0.8) + np.log(0.8)) / 2
    np.testing.assert_allclose(float(logloss(labels, pctr)), want, rtol=1e-6)


def test_logloss_weighted_and_clamped():
    labels = jnp.asarray([1.0, 0.0, 1.0])
    pctr = jnp.asarray([1.0, 0.5, 0.5])  # exact 1.0 must not produce inf
    w = jnp.asarray([1.0, 1.0, 0.0])
    val = float(logloss(labels, pctr, w))
    assert np.isfinite(val)
    np.testing.assert_allclose(val, -np.log(0.5) / 2, rtol=1e-3)


def test_accumulator_streams():
    acc = AucAccumulator()
    acc.add(np.array([1, 0]), np.array([0.9, 0.1]))
    acc.add(np.array([1, 0, 1]), np.array([0.8, 0.2, 0.7]), np.array([1, 1, 0]))
    assert acc.count() == 4
    ll, auc = acc.compute()
    assert auc == 1.0
    assert np.isfinite(ll)
