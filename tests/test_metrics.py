"""Sigmoid clamp, rank-sum AUC (reference algorithm), logloss."""

import numpy as np
import jax.numpy as jnp

from xflow_tpu.utils.metrics import (
    AucAccumulator,
    auc_rank_sum,
    logloss,
    sigmoid_ref,
)


def test_sigmoid_clamps():
    # base.h:54-63: x<-30 → 1e-6, x>30 → 1.0
    x = jnp.asarray([-31.0, -30.0, 0.0, 30.0, 31.0])
    p = np.asarray(sigmoid_ref(x))
    assert p[0] == 1e-6
    assert p[4] == 1.0
    np.testing.assert_allclose(p[2], 0.5)
    assert 0.0 < p[1] < 1e-12 or p[1] > 0  # plain sigmoid at -30
    np.testing.assert_allclose(p[3], 1.0 / (1.0 + np.exp(-30.0)), rtol=1e-6)


def test_auc_perfect_and_random():
    labels = np.array([1, 1, 0, 0])
    assert auc_rank_sum(labels, np.array([0.9, 0.8, 0.2, 0.1])) == 1.0
    assert auc_rank_sum(labels, np.array([0.1, 0.2, 0.8, 0.9])) == 0.0
    # one class only → NaN (reference prints tp_n only, base.h:102-104)
    assert np.isnan(auc_rank_sum(np.ones(4), np.random.rand(4)))


def test_auc_matches_pairwise_oracle():
    rng = np.random.default_rng(0)
    labels = (rng.random(200) < 0.3).astype(int)
    pctr = rng.random(200)
    got = auc_rank_sum(labels, pctr)
    pos = pctr[labels == 1]
    neg = pctr[labels == 0]
    # reference counts a positive above a negative; sort-desc walk counts
    # strictly-greater pairs plus ties ordered positive-first by stable sort.
    wins = (pos[:, None] > neg[None, :]).sum()
    assert abs(got - wins / (len(pos) * len(neg))) < 1e-6


def test_logloss_natural_log():
    labels = jnp.asarray([1.0, 0.0])
    pctr = jnp.asarray([0.8, 0.2])
    want = -(np.log(0.8) + np.log(0.8)) / 2
    np.testing.assert_allclose(float(logloss(labels, pctr)), want, rtol=1e-6)


def test_logloss_weighted_and_clamped():
    labels = jnp.asarray([1.0, 0.0, 1.0])
    pctr = jnp.asarray([1.0, 0.5, 0.5])  # exact 1.0 must not produce inf
    w = jnp.asarray([1.0, 1.0, 0.0])
    val = float(logloss(labels, pctr, w))
    assert np.isfinite(val)
    np.testing.assert_allclose(val, -np.log(0.5) / 2, rtol=1e-3)


def test_accumulator_streams():
    acc = AucAccumulator()
    acc.add(np.array([1, 0]), np.array([0.9, 0.1]))
    acc.add(np.array([1, 0, 1]), np.array([0.8, 0.2, 0.7]), np.array([1, 1, 0]))
    assert acc.count() == 4
    ll, auc = acc.compute()
    assert auc == 1.0
    assert np.isfinite(ll)


def test_hist_auc_matches_exact():
    """HistAuc (multi-host streaming path) ≈ pairwise rank-sum AUC;
    logloss is exact (it sums — no quantization)."""
    from xflow_tpu.utils.metrics import AucAccumulator, HistAuc

    rng = np.random.default_rng(3)
    labels = (rng.random(20000) < 0.3).astype(np.float32)
    pctr = np.clip(
        rng.beta(2, 5, 20000) + labels * 0.1, 0, 1
    ).astype(np.float32)
    acc, hist = AucAccumulator(), HistAuc()
    for s in range(0, 20000, 4096):  # streaming in chunks
        acc.add(labels[s : s + 4096], pctr[s : s + 4096])
        hist.add(labels[s : s + 4096], pctr[s : s + 4096])
    ll_a, auc_a = acc.compute()
    ll_h, auc_h = hist.compute()
    # pairwise path accumulates in float32, histogram in float64
    assert abs(ll_a - ll_h) < 1e-6
    assert abs(auc_a - auc_h) < 1e-4
    # mergeable state: two half-streams summed == one stream
    h1, h2 = HistAuc(), HistAuc()
    h1.add(labels[:10000], pctr[:10000])
    h2.add(labels[10000:], pctr[10000:])
    merged = HistAuc.from_state(
        {
            k: np.asarray(h1.state()[k]) + np.asarray(h2.state()[k])
            for k in h1.state()
        }
    )
    np.testing.assert_allclose(merged.compute(), hist.compute(), rtol=1e-12)


def test_auc_tie_semantics_bounds():
    """Tie-heavy golden test (VERDICT round 1, tightened round 4).  The
    reference's AUC under tied pctrs depends on std::sort's arbitrary
    permutation (base.h:89-106: each negative counts positives EARLIER
    in sort order, so within a tied group the area can be anything
    between 0 and p_g*n_g extra).  Contract: the reference-parity
    ``auc_rank_sum`` lands inside that achievable [min, max] envelope,
    while BOTH reporting paths — exact (auc_midrank, used by
    AucAccumulator) and histogram (HistAuc) — sit exactly at the
    midpoint (midrank), independent of host count."""
    from xflow_tpu.utils.metrics import (
        AucAccumulator,
        HistAuc,
        auc_midrank,
        auc_rank_sum,
    )

    rng = np.random.default_rng(11)
    # 5 distinct pctr levels, 400 samples each -> massive tie groups
    levels = np.asarray([0.1, 0.3, 0.5, 0.7, 0.9], np.float32)
    pctr = np.repeat(levels, 400)
    labels = (rng.random(2000) < np.repeat(levels, 400)).astype(np.float32)
    perm = rng.permutation(2000)
    pctr, labels = pctr[perm], labels[perm]

    # reference envelope: fixed cross-group area +/- within-group freedom
    fixed = 0.0
    slack = 0.0
    p_total = labels.sum()
    n_total = len(labels) - p_total
    for lv in levels:
        g = pctr == lv
        p_g = labels[g].sum()
        n_g = g.sum() - p_g
        p_above = labels[pctr > lv].sum()
        fixed += n_g * p_above
        slack += p_g * n_g
    lo = fixed / (p_total * n_total)
    hi = (fixed + slack) / (p_total * n_total)

    got = auc_rank_sum(labels, pctr)
    assert lo - 1e-12 <= got <= hi + 1e-12
    # both reporting paths: exactly the midrank midpoint
    np.testing.assert_allclose(
        auc_midrank(labels, pctr), (lo + hi) / 2, rtol=1e-12
    )
    acc = AucAccumulator()
    acc.add(labels, pctr)
    _, auc_acc = acc.compute()
    np.testing.assert_allclose(auc_acc, (lo + hi) / 2, rtol=1e-12)
    hist = HistAuc()
    hist.add(labels, pctr)
    _, auc_h = hist.compute()
    np.testing.assert_allclose(auc_h, (lo + hi) / 2, rtol=1e-12)
    # single-host (exact midrank) ≡ multi-host (histogram midrank)
    np.testing.assert_allclose(auc_acc, auc_h, rtol=1e-12)
