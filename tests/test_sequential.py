"""update_mode='sequential' is step-for-step the same training as a
sequence of dense steps of batch_size/microbatch examples (the scan
carries the tables; gradients divide by the slice's real count) —
the property that lets one device dispatch compose with the proven
small-batch FTRL convergence (config.update_mode docstring)."""

import numpy as np
import jax
import pytest

from xflow_tpu.config import Config
from xflow_tpu.io.batch import make_batch
from xflow_tpu.models import make_model
from xflow_tpu.optim import make_optimizer
from xflow_tpu.parallel.mesh import make_mesh
from xflow_tpu.parallel.step import TrainStep, init_state

B, M, K = 64, 4, 12  # superbatch, slice count, padded nnz


def rand_batch(rng, b, hot_size=0, hot_nnz=0, table=1 << 12, fields=8):
    keys = rng.integers(0, table, (b, K)).astype(np.int32)
    slots = rng.integers(0, fields, (b, K)).astype(np.int32)
    vals = rng.uniform(0.5, 1.5, (b, K)).astype(np.float32)
    mask = (rng.uniform(size=(b, K)) < 0.8).astype(np.float32)
    labels = (rng.uniform(size=b) < 0.4).astype(np.float32)
    weights = np.ones(b, np.float32)
    weights[-3:] = 0.0  # pad examples in the last slices
    return keys, slots, vals, mask, labels, weights


def slice_rows(arrs, j, m):
    """Interleaved slice j (example i -> slice i % m), matching
    parallel.step._interleaved_slices."""
    return tuple(a[j::m] for a in arrs)


def build(model, cfg):
    mesh = make_mesh(cfg.num_devices)
    mdl = make_model(cfg)
    opt = make_optimizer(cfg)
    step = TrainStep(mdl, opt, cfg, mesh)
    return step, init_state(mdl, opt, cfg, mesh)


def base_cfg(model, **kw):
    d = dict(
        model=model,
        batch_size=B,
        table_size_log2=12,
        max_nnz=K,
        max_fields=8,
        num_devices=1,
        wire_mode="full",
        emb_dim=4,
        hidden_dim=8,
        ffm_v_dim=2,
    )
    d.update(kw)
    return Config(**d)


@pytest.mark.parametrize(
    "model,kw",
    [
        ("lr", {}),
        ("fm", {}),
        ("mvm", {}),
        ("ffm", {}),
        ("wide_deep", {}),
        ("lr", {"hot_size_log2": 8, "hot_nnz": 6}),
        ("lr", {"optimizer": "sgd"}),
    ],
)
def test_sequential_equals_dense_sequence(model, kw):
    rng = np.random.default_rng(7)
    raw = rand_batch(rng, B)
    hot_size = (1 << kw["hot_size_log2"]) if kw.get("hot_size_log2") else 0
    hot_nnz = kw.get("hot_nnz", 0)

    seq_cfg = base_cfg(
        model, update_mode="sequential", microbatch=M, **kw
    )
    sstep, sstate = build(model, seq_cfg)
    sbatch = make_batch(*raw, hot_size, hot_nnz)
    sstate, smetrics = sstep.train(sstate, sstep.put_batch(sbatch))

    dense_cfg = base_cfg(
        model, update_mode="dense", batch_size=B // M, **kw
    )
    dstep, dstate = build(model, dense_cfg)
    nll, cnt = 0.0, 0.0
    for j in range(M):
        db = make_batch(*slice_rows(raw, j, M), hot_size, hot_nnz)
        dstate, dm = dstep.train(dstate, dstep.put_batch(db))
        c = float(jax.device_get(dm["count"]))
        nll += float(jax.device_get(dm["logloss"])) * c
        cnt += c

    for name in dstate["tables"]:
        for part in dstate["tables"][name]:
            np.testing.assert_allclose(
                np.asarray(jax.device_get(sstate["tables"][name][part])),
                np.asarray(jax.device_get(dstate["tables"][name][part])),
                rtol=1e-5,
                atol=1e-7,
                err_msg=f"{model}:{name}/{part}",
            )
    for key in dstate["dense"]:
        np.testing.assert_allclose(
            np.asarray(jax.device_get(sstate["dense"][key])),
            np.asarray(jax.device_get(dstate["dense"][key])),
            rtol=1e-5,
            atol=1e-6,
            err_msg=f"{model}:dense/{key}",
        )
    # dispatch-window metrics == weighted mean over the dense sequence
    assert float(jax.device_get(smetrics["count"])) == cnt
    np.testing.assert_allclose(
        float(jax.device_get(smetrics["logloss"])),
        nll / cnt,
        rtol=1e-5,
    )


def test_sequential_empty_slice_is_noop():
    """A slice of all-padding examples (weights 0 — multi-host step
    alignment feeds these) must leave the carried tables untouched."""
    rng = np.random.default_rng(3)
    keys, slots, vals, mask, labels, weights = rand_batch(rng, B)
    weights = weights.copy()
    weights[1::M] = 0.0  # slice 1 entirely padding
    mask[1::M] = 0.0

    cfg = base_cfg("lr", update_mode="sequential", microbatch=M)
    step, state = build("lr", cfg)
    batch = make_batch(keys, slots, vals, mask, labels, weights)
    state, _ = step.train(state, step.put_batch(batch))

    dcfg = base_cfg("lr", update_mode="dense", batch_size=B // M)
    dstep, dstate = build("lr", dcfg)
    for j in [0, 2, 3]:  # skip the empty slice entirely
        db = make_batch(
            *slice_rows((keys, slots, vals, mask, labels, weights), j, M)
        )
        dstate, _ = dstep.train(dstate, dstep.put_batch(db))
    np.testing.assert_allclose(
        np.asarray(jax.device_get(state["tables"]["w"]["param"])),
        np.asarray(jax.device_get(dstate["tables"]["w"]["param"])),
        rtol=1e-5,
        atol=1e-7,
    )


def test_sequential_sharded_matches_single():
    rng = np.random.default_rng(11)
    raw = rand_batch(rng, B)
    out = {}
    for ndev in (1, 8):
        cfg = base_cfg(
            "lr", update_mode="sequential", microbatch=M, num_devices=ndev
        )
        step, state = build("lr", cfg)
        state, _ = step.train(state, step.put_batch(make_batch(*raw)))
        out[ndev] = np.asarray(
            jax.device_get(state["tables"]["w"]["param"])
        )
    np.testing.assert_allclose(out[1], out[8], rtol=1e-5, atol=1e-7)


@pytest.mark.parametrize("model", ["lr", "fm", "wide_deep"])
def test_sequential_sparse_inner_equals_dense_inner(model):
    """config.sequential_inner='sparse' (touched-rows-only per slice —
    the north-star-table form) is the same training as the dense
    inner."""
    rng = np.random.default_rng(13)
    raw = rand_batch(rng, B)
    out = {}
    for inner in ("dense", "sparse"):
        cfg = base_cfg(
            model,
            update_mode="sequential",
            microbatch=M,
            sequential_inner=inner,
        )
        step, state = build(model, cfg)
        state, _ = step.train(state, step.put_batch(make_batch(*raw)))
        out[inner] = jax.device_get(state)
    for name in out["dense"]["tables"]:
        for part in out["dense"]["tables"][name]:
            np.testing.assert_allclose(
                np.asarray(out["sparse"]["tables"][name][part]),
                np.asarray(out["dense"]["tables"][name][part]),
                rtol=1e-5,
                atol=1e-7,
                err_msg=f"{model}:{name}/{part}",
            )
    for key in out["dense"]["dense"]:
        np.testing.assert_allclose(
            np.asarray(out["sparse"]["dense"][key]),
            np.asarray(out["dense"]["dense"][key]),
            rtol=1e-5,
            atol=1e-6,
        )


@pytest.mark.parametrize("model", ["lr", "fm", "ffm"])
def test_sequential_sparse_inner_hybrid_hot(model):
    """sparse inner + hot table (the hybrid, step.py::_sparse_update):
    cold keys keep the touched-rows path, the hot section gets a dense
    [H, D] head update, and hot rows that ALSO arrive through the cold
    planes (split_hot overflow spill) are folded into the hot buffer so
    every row sees exactly one summed-gradient update — the same
    training as the dense inner."""
    rng = np.random.default_rng(17)
    keys, slots, vals, mask, labels, weights = rand_batch(rng, B)
    # force heavy hot-head traffic incl. per-row overflow: half the
    # columns draw from hot rows [0, 16), so rows carry more hot keys
    # than hot_nnz=4 and the excess spills into the cold planes with
    # row ids < H — the exactly-once case the hybrid must fold in
    keys[:, ::2] = rng.integers(0, 16, (B, (K + 1) // 2)).astype(np.int32)
    raw = (keys, slots, vals, mask, labels, weights)
    hot_size, hot_nnz = 1 << 8, 4
    out = {}
    for inner in ("dense", "sparse"):
        cfg = base_cfg(
            model,
            update_mode="sequential",
            microbatch=M,
            sequential_inner=inner,
            hot_size_log2=8,
            hot_nnz=hot_nnz,
        )
        step, state = build(model, cfg)
        state, _ = step.train(
            state, step.put_batch(make_batch(*raw, hot_size, hot_nnz))
        )
        out[inner] = jax.device_get(state)
    for name in out["dense"]["tables"]:
        for part in out["dense"]["tables"][name]:
            np.testing.assert_allclose(
                np.asarray(out["sparse"]["tables"][name][part]),
                np.asarray(out["dense"]["tables"][name][part]),
                rtol=1e-5,
                atol=1e-7,
                err_msg=f"{model}:{name}/{part}",
            )


@pytest.mark.parametrize("model", ["lr", "fm", "wide_deep"])
def test_sequential_hot_inner_all_hot_equals_dense_inner(model):
    """sequential_inner='hot' with NO cold traffic (every key < H,
    hot_nnz >= per-row key count, so split_hot sends everything to the
    hot planes) is bit-for-bit true sequential training: the per-slice
    hot-head update IS the whole update, and the window-end cold pass
    runs on an all-zero gradient buffer (idempotent)."""
    rng = np.random.default_rng(19)
    keys, slots, vals, mask, labels, weights = rand_batch(rng, B)
    keys = rng.integers(0, 1 << 8, (B, K)).astype(np.int32)
    raw = (keys, slots, vals, mask, labels, weights)
    hot_size, hot_nnz = 1 << 8, K
    out = {}
    for inner in ("dense", "hot"):
        cfg = base_cfg(
            model,
            update_mode="sequential",
            microbatch=M,
            sequential_inner=inner,
            hot_size_log2=8,
            hot_nnz=hot_nnz,
        )
        step, state = build(model, cfg)
        state, metrics = step.train(
            state, step.put_batch(make_batch(*raw, hot_size, hot_nnz))
        )
        out[inner] = (jax.device_get(state), jax.device_get(metrics))
    for name in out["dense"][0]["tables"]:
        for part in out["dense"][0]["tables"][name]:
            np.testing.assert_allclose(
                np.asarray(out["hot"][0]["tables"][name][part]),
                np.asarray(out["dense"][0]["tables"][name][part]),
                rtol=1e-5,
                atol=1e-7,
                err_msg=f"{model}:{name}/{part}",
            )
    for key in out["dense"][0]["dense"]:
        np.testing.assert_allclose(
            np.asarray(out["hot"][0]["dense"][key]),
            np.asarray(out["dense"][0]["dense"][key]),
            rtol=1e-5,
            atol=1e-6,
        )
    np.testing.assert_allclose(
        float(out["hot"][1]["logloss"]),
        float(out["dense"][1]["logloss"]),
        rtol=1e-5,
    )


@pytest.mark.parametrize("model", ["lr", "fm"])
def test_sequential_hot_inner_singleton_cold_equals_dense_inner(model):
    """Hot-fine/cold-coarse's two divergences from true sequential —
    window-stale cold forward values and summed-gradient cold updates —
    both vanish when every cold key occurs exactly ONCE in the dispatch
    window (its pre-gathered value equals the live value at its slice,
    and a one-occurrence sum is the one gradient).  With unique cold
    keys and spill-free hot traffic, the hot inner must reproduce the
    dense inner exactly.  This pins the window-end pass: grads
    un-interleave to batch order, land post-writeback, exactly once."""
    rng = np.random.default_rng(23)
    keys, slots, vals, mask, labels, weights = rand_batch(rng, B)
    nhot = (K + 1) // 2
    # even columns: hot rows [0, 256) with capacity hot_nnz = nhot (no
    # spill); odd columns: globally unique cold keys >= H
    keys[:, ::2] = rng.integers(0, 1 << 8, (B, nhot)).astype(np.int32)
    ncold = K - nhot
    uniq = (1 << 8) + np.arange(B * ncold, dtype=np.int32)
    keys[:, 1::2] = rng.permutation(uniq).reshape(B, ncold)
    raw = (keys, slots, vals, mask, labels, weights)
    hot_size, hot_nnz = 1 << 8, nhot
    out = {}
    for inner in ("dense", "hot"):
        cfg = base_cfg(
            model,
            update_mode="sequential",
            microbatch=M,
            sequential_inner=inner,
            hot_size_log2=8,
            hot_nnz=hot_nnz,
        )
        step, state = build(model, cfg)
        state, _ = step.train(
            state, step.put_batch(make_batch(*raw, hot_size, hot_nnz))
        )
        out[inner] = jax.device_get(state)
    for name in out["dense"]["tables"]:
        for part in out["dense"]["tables"][name]:
            np.testing.assert_allclose(
                np.asarray(out["hot"]["tables"][name][part]),
                np.asarray(out["dense"]["tables"][name][part]),
                rtol=1e-5,
                atol=1e-7,
                err_msg=f"{model}:{name}/{part}",
            )


def test_sequential_hot_inner_consolidate_matches_plain():
    """cold_consolidate under the hot inner routes the window-end
    scatter through consolidate_plan/apply — same result as the plain
    scatter-add on duplicate-heavy cold traffic."""
    rng = np.random.default_rng(37)
    keys, slots, vals, mask, labels, weights = rand_batch(rng, B)
    # duplicate-heavy cold keys: draw from a tiny cold range >= H
    keys[:, 1::2] = (
        (1 << 8) + rng.integers(0, 32, (B, K // 2))
    ).astype(np.int32)
    raw = (keys, slots, vals, mask, labels, weights)
    out = {}
    for consolidate in (False, True):
        cfg = base_cfg(
            "lr",
            update_mode="sequential",
            microbatch=M,
            sequential_inner="hot",
            hot_size_log2=8,
            hot_nnz=6,
            cold_consolidate=consolidate,
        )
        step, state = build("lr", cfg)
        state, _ = step.train(
            state, step.put_batch(make_batch(*raw, 1 << 8, 6))
        )
        out[consolidate] = np.asarray(
            jax.device_get(state["tables"]["w"]["param"])
        )
    np.testing.assert_allclose(out[False], out[True], rtol=1e-5, atol=1e-7)


def test_sequential_hot_inner_sharded_matches_single():
    rng = np.random.default_rng(29)
    keys, slots, vals, mask, labels, weights = rand_batch(rng, B)
    keys[:, ::2] = rng.integers(0, 1 << 8, (B, (K + 1) // 2)).astype(
        np.int32
    )
    raw = (keys, slots, vals, mask, labels, weights)
    out = {}
    for ndev in (1, 8):
        cfg = base_cfg(
            "lr",
            update_mode="sequential",
            microbatch=M,
            sequential_inner="hot",
            hot_size_log2=8,
            hot_nnz=4,
            num_devices=ndev,
        )
        step, state = build("lr", cfg)
        state, _ = step.train(
            state, step.put_batch(make_batch(*raw, 1 << 8, 4))
        )
        out[ndev] = np.asarray(
            jax.device_get(state["tables"]["w"]["param"])
        )
    np.testing.assert_allclose(out[1], out[8], rtol=1e-5, atol=1e-7)


def test_sequential_hot_inner_spill_trains():
    """With per-row hot overflow spilling into the cold planes (keys
    < H arriving cold), the hot inner defers those grads to the
    window-end pass — approximate vs true sequential by design
    (docstring), but every update must land exactly once and training
    must make progress.  Train a few windows on a learnable batch and
    check the loss moves down and all state stays finite."""
    rng = np.random.default_rng(31)
    keys, slots, vals, mask, labels, weights = rand_batch(rng, B)
    # heavy hot traffic (8 of 12 columns) against hot_nnz=4 capacity —
    # guaranteed spill — and labels correlated with one hot key so
    # there is signal to learn
    keys[:, :8] = rng.integers(0, 16, (B, 8)).astype(np.int32)
    labels = (keys[:, 0] < 8).astype(np.float32)
    raw = (keys, slots, vals, mask, labels, weights)
    cfg = base_cfg(
        "lr",
        update_mode="sequential",
        microbatch=M,
        sequential_inner="hot",
        hot_size_log2=8,
        hot_nnz=4,
    )
    step, state = build("lr", cfg)
    batch = step.put_batch(make_batch(*raw, 1 << 8, 4))
    losses = []
    for _ in range(15):
        state, metrics = step.train(state, batch)
        losses.append(float(jax.device_get(metrics["logloss"])))
    assert losses[-1] < losses[0] - 0.03, losses
    for name, table in state["tables"].items():
        for part, arr in table.items():
            assert np.isfinite(np.asarray(jax.device_get(arr))).all(), (
                name,
                part,
            )


@pytest.mark.parametrize("model", ["lr", "fm"])
def test_hot_windowend_sparse_matches_dense(model):
    """Config.hot_windowend='sparse' routes the window-end cold-tail
    pass through the consolidated touched-rows update (ops/sparse.py)
    instead of a [T, D] buffer + full-table optimizer pass — the
    T=2^28 form (analysis rules XF010/XF014).  Same training on
    duplicate-heavy cold traffic WITH hot-overflow spill (cold-plane
    keys < H landing on the written-back head, exactly once)."""
    rng = np.random.default_rng(41)
    keys, slots, vals, mask, labels, weights = rand_batch(rng, B)
    # heavy hot traffic with spill (8 of 12 columns vs hot_nnz=4) AND
    # duplicate-heavy cold keys >= H
    keys[:, :8] = rng.integers(0, 16, (B, 8)).astype(np.int32)
    keys[:, 8:] = (
        (1 << 8) + rng.integers(0, 32, (B, K - 8))
    ).astype(np.int32)
    raw = (keys, slots, vals, mask, labels, weights)
    out = {}
    for windowend in ("dense", "sparse"):
        cfg = base_cfg(
            model,
            update_mode="sequential",
            microbatch=M,
            sequential_inner="hot",
            hot_size_log2=8,
            hot_nnz=4,
            hot_windowend=windowend,
        )
        step, state = build(model, cfg)
        assert step._windowend == windowend
        state, _ = step.train(
            state, step.put_batch(make_batch(*raw, 1 << 8, 4))
        )
        out[windowend] = jax.device_get(state)
    for name in out["dense"]["tables"]:
        for part in out["dense"]["tables"][name]:
            np.testing.assert_allclose(
                np.asarray(out["sparse"]["tables"][name][part]),
                np.asarray(out["dense"]["tables"][name][part]),
                rtol=1e-5,
                atol=1e-7,
                err_msg=f"{model}:{name}/{part}",
            )


def test_hot_windowend_auto_routes_by_table_size():
    """auto = dense below 2^24 (full-table pass is noise there),
    sparse from 2^24 up (the [T, D] transient is the hazard)."""
    small = base_cfg(
        "lr", update_mode="sequential", microbatch=M,
        sequential_inner="hot", hot_size_log2=8, hot_nnz=4,
    )
    step, _ = build("lr", small)
    assert step._windowend == "dense"
    big = small.replace(table_size_log2=24)
    mesh = make_mesh(big.num_devices)
    big_step = TrainStep(
        make_model(big), make_optimizer(big), big, mesh
    )
    assert big_step._windowend == "sparse"


def test_hot_inner_requires_hot_table():
    with pytest.raises(ValueError, match="hot"):
        base_cfg("lr", update_mode="sequential", sequential_inner="hot")


def test_hot_inner_rejects_mxu_opted_out_tables():
    """ffm opts its wide v table out of the MXU hot path
    (TableSpec.hot=False) — the hot inner carries every table's head
    in the scan, so TrainStep must refuse the combination up front."""
    cfg = base_cfg(
        "ffm",
        update_mode="sequential",
        microbatch=M,
        sequential_inner="hot",
        hot_size_log2=8,
        hot_nnz=4,
    )
    with pytest.raises(ValueError, match="opts table"):
        build("ffm", cfg)


def test_mxu_opted_out_inner_hot_legal_outside_sequential():
    """ADVICE round-5 low #2 regression: the hot-inner/opt-out check
    only applies when the hot inner RUNS (update_mode='sequential').
    ffm + dense mode + sequential_inner='hot' is a legal Config (the
    inner is an unused knob there) and must build and train."""
    rng = np.random.default_rng(43)
    raw = rand_batch(rng, B)
    cfg = base_cfg(
        "ffm",
        update_mode="dense",
        sequential_inner="hot",
        hot_size_log2=8,
        hot_nnz=4,
    )
    step, state = build("ffm", cfg)  # used to raise at build
    state, metrics = step.train(
        state, step.put_batch(make_batch(*raw, 1 << 8, 4))
    )
    assert np.isfinite(float(jax.device_get(metrics["logloss"])))


@pytest.mark.parametrize(
    "inner,hot",
    [("dense", False), ("sparse", False), ("sparse", True), ("hot", True)],
)
def test_sequential_microbatch_one_is_dense(inner, hot):
    """microbatch=1 degenerates to a single whole-batch update — via
    the dense pass or, with sequential_inner='sparse', the
    touched-rows-only path (which must not silently fall through to a
    full-table pass at north-star table sizes).  The hot-on case pins
    the degenerate path of the hybrid inner."""
    rng = np.random.default_rng(5)
    raw = rand_batch(rng, B)
    hot_kw = {"hot_size_log2": 8, "hot_nnz": 4} if hot else {}
    hot_args = (1 << 8, 4) if hot else ()
    states = {}
    for mode in ("sequential", "dense"):
        cfg = base_cfg(
            "lr", update_mode=mode, sequential_inner=inner, **hot_kw
        )
        step, state = build("lr", cfg)
        state, _ = step.train(
            state, step.put_batch(make_batch(*raw, *hot_args))
        )
        states[mode] = np.asarray(
            jax.device_get(state["tables"]["w"]["param"])
        )
    np.testing.assert_allclose(
        states["sequential"], states["dense"], rtol=1e-5, atol=1e-7
    )
