"""update_mode='sequential' is step-for-step the same training as a
sequence of dense steps of batch_size/microbatch examples (the scan
carries the tables; gradients divide by the slice's real count) —
the property that lets one device dispatch compose with the proven
small-batch FTRL convergence (config.update_mode docstring)."""

import numpy as np
import jax
import pytest

from xflow_tpu.config import Config
from xflow_tpu.io.batch import make_batch
from xflow_tpu.models import make_model
from xflow_tpu.optim import make_optimizer
from xflow_tpu.parallel.mesh import make_mesh
from xflow_tpu.parallel.step import TrainStep, init_state

B, M, K = 64, 4, 12  # superbatch, slice count, padded nnz


def rand_batch(rng, b, hot_size=0, hot_nnz=0, table=1 << 12, fields=8):
    keys = rng.integers(0, table, (b, K)).astype(np.int32)
    slots = rng.integers(0, fields, (b, K)).astype(np.int32)
    vals = rng.uniform(0.5, 1.5, (b, K)).astype(np.float32)
    mask = (rng.uniform(size=(b, K)) < 0.8).astype(np.float32)
    labels = (rng.uniform(size=b) < 0.4).astype(np.float32)
    weights = np.ones(b, np.float32)
    weights[-3:] = 0.0  # pad examples in the last slices
    return keys, slots, vals, mask, labels, weights


def slice_rows(arrs, j, m):
    """Interleaved slice j (example i -> slice i % m), matching
    parallel.step._interleaved_slices."""
    return tuple(a[j::m] for a in arrs)


def build(model, cfg):
    mesh = make_mesh(cfg.num_devices)
    mdl = make_model(cfg)
    opt = make_optimizer(cfg)
    step = TrainStep(mdl, opt, cfg, mesh)
    return step, init_state(mdl, opt, cfg, mesh)


def base_cfg(model, **kw):
    d = dict(
        model=model,
        batch_size=B,
        table_size_log2=12,
        max_nnz=K,
        max_fields=8,
        num_devices=1,
        wire_mode="full",
        emb_dim=4,
        hidden_dim=8,
        ffm_v_dim=2,
    )
    d.update(kw)
    return Config(**d)


@pytest.mark.parametrize(
    "model,kw",
    [
        ("lr", {}),
        ("fm", {}),
        ("mvm", {}),
        ("ffm", {}),
        ("wide_deep", {}),
        ("lr", {"hot_size_log2": 8, "hot_nnz": 6}),
        ("lr", {"optimizer": "sgd"}),
    ],
)
def test_sequential_equals_dense_sequence(model, kw):
    rng = np.random.default_rng(7)
    raw = rand_batch(rng, B)
    hot_size = (1 << kw["hot_size_log2"]) if kw.get("hot_size_log2") else 0
    hot_nnz = kw.get("hot_nnz", 0)

    seq_cfg = base_cfg(
        model, update_mode="sequential", microbatch=M, **kw
    )
    sstep, sstate = build(model, seq_cfg)
    sbatch = make_batch(*raw, hot_size, hot_nnz)
    sstate, smetrics = sstep.train(sstate, sstep.put_batch(sbatch))

    dense_cfg = base_cfg(
        model, update_mode="dense", batch_size=B // M, **kw
    )
    dstep, dstate = build(model, dense_cfg)
    nll, cnt = 0.0, 0.0
    for j in range(M):
        db = make_batch(*slice_rows(raw, j, M), hot_size, hot_nnz)
        dstate, dm = dstep.train(dstate, dstep.put_batch(db))
        c = float(jax.device_get(dm["count"]))
        nll += float(jax.device_get(dm["logloss"])) * c
        cnt += c

    for name in dstate["tables"]:
        for part in dstate["tables"][name]:
            np.testing.assert_allclose(
                np.asarray(jax.device_get(sstate["tables"][name][part])),
                np.asarray(jax.device_get(dstate["tables"][name][part])),
                rtol=1e-5,
                atol=1e-7,
                err_msg=f"{model}:{name}/{part}",
            )
    for key in dstate["dense"]:
        np.testing.assert_allclose(
            np.asarray(jax.device_get(sstate["dense"][key])),
            np.asarray(jax.device_get(dstate["dense"][key])),
            rtol=1e-5,
            atol=1e-6,
            err_msg=f"{model}:dense/{key}",
        )
    # dispatch-window metrics == weighted mean over the dense sequence
    assert float(jax.device_get(smetrics["count"])) == cnt
    np.testing.assert_allclose(
        float(jax.device_get(smetrics["logloss"])),
        nll / cnt,
        rtol=1e-5,
    )


def test_sequential_empty_slice_is_noop():
    """A slice of all-padding examples (weights 0 — multi-host step
    alignment feeds these) must leave the carried tables untouched."""
    rng = np.random.default_rng(3)
    keys, slots, vals, mask, labels, weights = rand_batch(rng, B)
    weights = weights.copy()
    weights[1::M] = 0.0  # slice 1 entirely padding
    mask[1::M] = 0.0

    cfg = base_cfg("lr", update_mode="sequential", microbatch=M)
    step, state = build("lr", cfg)
    batch = make_batch(keys, slots, vals, mask, labels, weights)
    state, _ = step.train(state, step.put_batch(batch))

    dcfg = base_cfg("lr", update_mode="dense", batch_size=B // M)
    dstep, dstate = build("lr", dcfg)
    for j in [0, 2, 3]:  # skip the empty slice entirely
        db = make_batch(
            *slice_rows((keys, slots, vals, mask, labels, weights), j, M)
        )
        dstate, _ = dstep.train(dstate, dstep.put_batch(db))
    np.testing.assert_allclose(
        np.asarray(jax.device_get(state["tables"]["w"]["param"])),
        np.asarray(jax.device_get(dstate["tables"]["w"]["param"])),
        rtol=1e-5,
        atol=1e-7,
    )


def test_sequential_sharded_matches_single():
    rng = np.random.default_rng(11)
    raw = rand_batch(rng, B)
    out = {}
    for ndev in (1, 8):
        cfg = base_cfg(
            "lr", update_mode="sequential", microbatch=M, num_devices=ndev
        )
        step, state = build("lr", cfg)
        state, _ = step.train(state, step.put_batch(make_batch(*raw)))
        out[ndev] = np.asarray(
            jax.device_get(state["tables"]["w"]["param"])
        )
    np.testing.assert_allclose(out[1], out[8], rtol=1e-5, atol=1e-7)


@pytest.mark.parametrize("model", ["lr", "fm", "wide_deep"])
def test_sequential_sparse_inner_equals_dense_inner(model):
    """config.sequential_inner='sparse' (touched-rows-only per slice —
    the north-star-table form) is the same training as the dense
    inner."""
    rng = np.random.default_rng(13)
    raw = rand_batch(rng, B)
    out = {}
    for inner in ("dense", "sparse"):
        cfg = base_cfg(
            model,
            update_mode="sequential",
            microbatch=M,
            sequential_inner=inner,
        )
        step, state = build(model, cfg)
        state, _ = step.train(state, step.put_batch(make_batch(*raw)))
        out[inner] = jax.device_get(state)
    for name in out["dense"]["tables"]:
        for part in out["dense"]["tables"][name]:
            np.testing.assert_allclose(
                np.asarray(out["sparse"]["tables"][name][part]),
                np.asarray(out["dense"]["tables"][name][part]),
                rtol=1e-5,
                atol=1e-7,
                err_msg=f"{model}:{name}/{part}",
            )
    for key in out["dense"]["dense"]:
        np.testing.assert_allclose(
            np.asarray(out["sparse"]["dense"][key]),
            np.asarray(out["dense"]["dense"][key]),
            rtol=1e-5,
            atol=1e-6,
        )


@pytest.mark.parametrize("model", ["lr", "fm"])
def test_sequential_sparse_inner_hybrid_hot(model):
    """sparse inner + hot table (the hybrid, step.py::_sparse_update):
    cold keys keep the touched-rows path, the hot section gets a dense
    [H, D] head update, and hot rows that ALSO arrive through the cold
    planes (split_hot overflow spill) are folded into the hot buffer so
    every row sees exactly one summed-gradient update — the same
    training as the dense inner."""
    rng = np.random.default_rng(17)
    keys, slots, vals, mask, labels, weights = rand_batch(rng, B)
    # force heavy hot-head traffic incl. per-row overflow: half the
    # columns draw from hot rows [0, 16), so rows carry more hot keys
    # than hot_nnz=4 and the excess spills into the cold planes with
    # row ids < H — the exactly-once case the hybrid must fold in
    keys[:, ::2] = rng.integers(0, 16, (B, (K + 1) // 2)).astype(np.int32)
    raw = (keys, slots, vals, mask, labels, weights)
    hot_size, hot_nnz = 1 << 8, 4
    out = {}
    for inner in ("dense", "sparse"):
        cfg = base_cfg(
            model,
            update_mode="sequential",
            microbatch=M,
            sequential_inner=inner,
            hot_size_log2=8,
            hot_nnz=hot_nnz,
        )
        step, state = build(model, cfg)
        state, _ = step.train(
            state, step.put_batch(make_batch(*raw, hot_size, hot_nnz))
        )
        out[inner] = jax.device_get(state)
    for name in out["dense"]["tables"]:
        for part in out["dense"]["tables"][name]:
            np.testing.assert_allclose(
                np.asarray(out["sparse"]["tables"][name][part]),
                np.asarray(out["dense"]["tables"][name][part]),
                rtol=1e-5,
                atol=1e-7,
                err_msg=f"{model}:{name}/{part}",
            )


@pytest.mark.parametrize(
    "inner,hot",
    [("dense", False), ("sparse", False), ("sparse", True)],
)
def test_sequential_microbatch_one_is_dense(inner, hot):
    """microbatch=1 degenerates to a single whole-batch update — via
    the dense pass or, with sequential_inner='sparse', the
    touched-rows-only path (which must not silently fall through to a
    full-table pass at north-star table sizes).  The hot-on case pins
    the degenerate path of the hybrid inner."""
    rng = np.random.default_rng(5)
    raw = rand_batch(rng, B)
    hot_kw = {"hot_size_log2": 8, "hot_nnz": 4} if hot else {}
    hot_args = (1 << 8, 4) if hot else ()
    states = {}
    for mode in ("sequential", "dense"):
        cfg = base_cfg(
            "lr", update_mode=mode, sequential_inner=inner, **hot_kw
        )
        step, state = build("lr", cfg)
        state, _ = step.train(
            state, step.put_batch(make_batch(*raw, *hot_args))
        )
        states[mode] = np.asarray(
            jax.device_get(state["tables"]["w"]["param"])
        )
    np.testing.assert_allclose(
        states["sequential"], states["dense"], rtol=1e-5, atol=1e-7
    )
