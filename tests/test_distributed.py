"""True multi-process distributed training on one machine.

The reference proves its whole distributed topology (scheduler + servers
+ workers) as plain local processes (scripts/local.sh, SURVEY §4 item
2).  The equivalent here: two OS processes, `jax.distributed.initialize`
over a localhost coordinator, gloo CPU collectives, each host reading
its own shard subset — the exact `scripts/run_dist.sh` path.

Three train shards across two hosts makes the split UNEQUAL (host 0
gets shards 0 and 2, host 1 gets shard 1), exercising the SPMD
step-count agreement (`Trainer._synced_batches`): host 1 must feed
zero-weight padding batches while host 0 finishes its second shard, or
the pjit collectives deadlock.
"""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _launch_pair(cmd, env, timeout=600, fail_msg="distributed run deadlocked"):
    """Spawn both ranks of a 2-process job, wait with a deadlock
    timeout (kill all on expiry), return (procs, stderr_texts)."""
    procs = [
        subprocess.Popen(
            cmd + ["--process-id", str(pid)],
            env=env, stderr=subprocess.PIPE, text=True, cwd=os.getcwd(),
        )
        for pid in range(2)
    ]
    errs = []
    for p in procs:
        try:
            _, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail(fail_msg)
        errs.append(err)
    return procs, errs


@pytest.mark.parametrize("hot", [False, "dense", "hot"])
def test_two_process_training(toy_dataset, tmp_path, hot):
    port = _free_port()
    env_base = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
    )
    cmd = [
        sys.executable, "-m", "xflow_tpu.train",
        "--model", "lr",
        "--train", toy_dataset.train_prefix,  # 3 shards -> unequal split
        "--test", toy_dataset.test_prefix,
        "--epochs", "3",
        "--batch-size", "64",
        "--table-size-log2", "14",
        "--max-nnz", "24",
        "--num-devices", "2",
        "--platform", "cpu",  # env alone is overridden by TPU plugins
        "--coordinator", f"localhost:{port}",
        "--num-processes", "2",
    ]
    if hot:
        # compose the hot-table MXU path AND the sequential per-slice
        # update scan with real 2-process collectives — with the dense
        # inner and with the hot-fine/cold-coarse inner (scan-carried
        # head + window-end writeback under GSPMD) (the accumulate
        # scan's sharding is covered by
        # test_dense_sharded_matches_single on the 8-device mesh)
        cmd += ["--hot-size-log2", "8", "--hot-nnz", "8",
                "--freq-sample-mib", "1", "--microbatch", "2",
                "--update-mode", "sequential",
                "--sequential-inner", hot]
    else:
        # cover the multi-host checkpoint path (collective allgather
        # save, rank-0 writes) in one of the parametrizations
        cmd += ["--checkpoint-dir", str(tmp_path / "ck")]

    def run_pair(extra):
        return _launch_pair(
            cmd + extra, env_base,
            fail_msg="distributed training deadlocked (collective mismatch?)",
        )

    procs, errs = run_pair([])
    assert procs[0].returncode == 0, errs[0]
    assert procs[1].returncode == 0, errs[1]
    # rank-0 reports the global eval (allgathered across hosts)
    assert "auc" in errs[0]
    # all 200 test examples counted exactly once despite padding batches
    assert "tp = " in errs[0]

    if not hot:
        assert (tmp_path / "ck" / "LATEST").exists()
        # multi-host restore: sharded tables rebuilt from the rank-0 files
        procs, errs = run_pair(["--resume"])
        assert procs[0].returncode == 0, errs[0]
        assert procs[1].returncode == 0, errs[1]
        assert "resumed at" in errs[0]


def test_two_process_training_packed_shards(toy_dataset, tmp_path):
    """Multi-host training over PACKED-cache shards (io/packed.py): the
    format sniffing, geometry validation, and per-host shard walk must
    compose with the SPMD step-count voting exactly like text shards
    (3 packed shards over 2 hosts = unequal split)."""
    from xflow_tpu.io import packed

    out = str(tmp_path / "pk")
    for i in range(3):
        packed.convert_shard(
            toy_dataset.train_prefix + f"-{i:05d}",
            f"{out}-{i:05d}",
            batch_size=64,
            max_nnz=24,
            table_size=1 << 14,
        )
    port = _free_port()
    env_base = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
    )
    cmd = [
        sys.executable, "-m", "xflow_tpu.train",
        "--model", "lr",
        "--train", out,
        "--test", toy_dataset.test_prefix,
        "--epochs", "3",
        "--batch-size", "64",
        "--table-size-log2", "14",
        "--max-nnz", "24",
        "--num-devices", "2",
        "--platform", "cpu",
        "--coordinator", f"localhost:{port}",
        "--num-processes", "2",
    ]
    procs, errs = _launch_pair(
        cmd, env_base,
        fail_msg="packed-shard distributed training deadlocked",
    )
    assert procs[0].returncode == 0, errs[0]
    assert procs[1].returncode == 0, errs[1]
    assert "auc" in errs[0]
    assert "tp = " in errs[0]


def test_two_process_ckpt_mkdir_failure_raises_not_hangs(toy_dataset, tmp_path):
    """Round-2 advisor finding: an exception on process 0 BEFORE the
    post-mkdir synchronization point (e.g. os.makedirs failing) used to
    send process 0 into _all_ok's allgather while process 1 sat in a
    bare sync_global_devices — mismatched collectives, multi-host hang.
    With the mkdir outcome itself voted through _all_ok, both processes
    must now exit nonzero promptly instead of deadlocking."""
    port = _free_port()
    env_base = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
    )
    blocker = tmp_path / "blocker"
    blocker.write_text("regular file: makedirs(blocker/ck) must fail")
    cmd = [
        sys.executable, "-m", "xflow_tpu.train",
        "--model", "lr",
        "--train", toy_dataset.train_prefix,
        "--test", toy_dataset.test_prefix,
        "--epochs", "1",
        "--batch-size", "64",
        "--table-size-log2", "14",
        "--max-nnz", "24",
        "--num-devices", "2",
        "--platform", "cpu",
        "--coordinator", f"localhost:{port}",
        "--num-processes", "2",
        "--checkpoint-dir", str(blocker / "ck"),
        "--skip-eval",
    ]
    procs, errs = _launch_pair(
        cmd, env_base, timeout=300,
        fail_msg="checkpoint mkdir failure deadlocked the job (pre-barrier "
        "exception not voted through _all_ok?)",
    )
    assert procs[0].returncode != 0, "process 0 should fail on mkdir"
    assert procs[1].returncode != 0, "process 1 should learn of the failure"
    assert "NotADirectoryError" in errs[0] or "FileExistsError" in errs[0]
    assert "checkpoint mkdir failed on process 0" in errs[1]


def test_two_process_midepoch_cursor_resume(toy_dataset, tmp_path):
    """Mid-epoch checkpoints record EVERY host's (shard, offset) cursor
    and each host resumes from its own — the round-1 advisor finding:
    rank 0's byte offset must not be applied to other hosts' different
    shard subsets."""
    import json

    port = _free_port()
    env_base = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
    )
    ck = tmp_path / "ck"
    cmd = [
        sys.executable, "-m", "xflow_tpu.train",
        "--model", "lr",
        "--train", toy_dataset.train_prefix,  # 3 shards -> unequal split
        "--test", toy_dataset.test_prefix,
        "--epochs", "1",
        "--batch-size", "32",
        "--block-mib", "1",
        "--table-size-log2", "14",
        "--max-nnz", "24",
        "--num-devices", "2",
        "--platform", "cpu",
        "--coordinator", f"localhost:{port}",
        "--num-processes", "2",
        "--checkpoint-dir", str(ck),
        "--checkpoint-every-steps", "2",
        "--skip-eval",
    ]

    def run_pair(extra, port):
        cmd2 = list(cmd)
        cmd2[cmd2.index("--coordinator") + 1] = f"localhost:{port}"
        procs, errs = _launch_pair(cmd2 + extra, env_base)
        assert procs[0].returncode == 0, errs[0]
        assert procs[1].returncode == 0, errs[1]
        return errs

    run_pair([], port)
    # every checkpoint (intermediate + final) carries both hosts' cursors
    import glob as _glob

    ckpts = sorted(_glob.glob(str(ck / "ckpt-*")))
    assert len(ckpts) >= 2  # at least one mid-epoch + the final
    manifests = [
        json.load(open(os.path.join(c, "manifest.json"))) for c in ckpts
    ]
    for m in manifests:
        assert m["cursor"]["num_hosts"] == 2
        assert len(m["cursor"]["cursors"]) == 2
    # host 0 owns shards {0,2}, host 1 owns {1}: once host 0 crosses into
    # its second local shard (or host 1 finishes first), the two hosts'
    # cursors MUST diverge in some mid-epoch checkpoint — rank 0's cursor
    # alone could not describe both (the round-1 advisor bug)
    assert any(
        m["cursor"]["cursors"][0] != m["cursor"]["cursors"][1]
        for m in manifests[:-1]
    )

    # resume from the mid-epoch checkpoint: point LATEST at it
    with open(ck / "LATEST", "w") as f:
        f.write(os.path.basename(ckpts[0]))
    errs = run_pair(["--resume"], _free_port())
    assert "resumed at" in errs[0]
