"""True multi-process distributed training on one machine.

The reference proves its whole distributed topology (scheduler + servers
+ workers) as plain local processes (scripts/local.sh, SURVEY §4 item
2).  The equivalent here: two OS processes, `jax.distributed.initialize`
over a localhost coordinator, gloo CPU collectives, each host reading
its own shard subset — the exact `scripts/run_dist.sh` path.

Three train shards across two hosts makes the split UNEQUAL (host 0
gets shards 0 and 2, host 1 gets shard 1), exercising the SPMD
step-count agreement (`Trainer._synced_batches`): host 1 must feed
zero-weight padding batches while host 0 finishes its second shard, or
the pjit collectives deadlock.
"""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.parametrize("hot", [False, True])
def test_two_process_training(toy_dataset, tmp_path, hot):
    port = _free_port()
    env_base = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
    )
    cmd = [
        sys.executable, "-m", "xflow_tpu.train",
        "--model", "lr",
        "--train", toy_dataset.train_prefix,  # 3 shards -> unequal split
        "--test", toy_dataset.test_prefix,
        "--epochs", "3",
        "--batch-size", "64",
        "--table-size-log2", "14",
        "--max-nnz", "24",
        "--num-devices", "2",
        "--platform", "cpu",  # env alone is overridden by TPU plugins
        "--coordinator", f"localhost:{port}",
        "--num-processes", "2",
    ]
    if hot:
        cmd += ["--hot-size-log2", "8", "--hot-nnz", "8",
                "--freq-sample-mib", "1"]
    else:
        # cover the multi-host checkpoint path (collective allgather
        # save, rank-0 writes) in one of the parametrizations
        cmd += ["--checkpoint-dir", str(tmp_path / "ck")]

    def run_pair(extra):
        procs = [
            subprocess.Popen(
                cmd + extra + ["--process-id", str(pid)],
                env=env_base,
                stderr=subprocess.PIPE,
                text=True,
                cwd=os.getcwd(),
            )
            for pid in range(2)
        ]
        errs = []
        for p in procs:
            try:
                _, err = p.communicate(timeout=600)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                pytest.fail(
                    "distributed training deadlocked (collective mismatch?)"
                )
            errs.append(err)
        return procs, errs

    procs, errs = run_pair([])
    assert procs[0].returncode == 0, errs[0]
    assert procs[1].returncode == 0, errs[1]
    # rank-0 reports the global eval (allgathered across hosts)
    assert "auc" in errs[0]
    # all 200 test examples counted exactly once despite padding batches
    assert "tp = " in errs[0]

    if not hot:
        assert (tmp_path / "ck" / "LATEST").exists()
        # multi-host restore: sharded tables rebuilt from the rank-0 files
        procs, errs = run_pair(["--resume"])
        assert procs[0].returncode == 0, errs[0]
        assert procs[1].returncode == 0, errs[1]
        assert "resumed at" in errs[0]
