"""FFM and Wide&Deep (capability extensions beyond the reference zoo):
forward oracles, autodiff training, convergence, sharding equivalence,
checkpoint roundtrip with dense params."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from xflow_tpu.config import Config
from xflow_tpu.models.ffm import FFMModel
from xflow_tpu.models.wide_deep import WideDeepModel
from xflow_tpu.trainer import Trainer

B, K, F, D = 3, 5, 4, 2


def random_batch(seed=0):
    rng = np.random.default_rng(seed)
    mask = (rng.random((B, K)) < 0.85).astype(np.float32)
    return {
        "keys": jnp.asarray(rng.integers(0, 50, (B, K)), jnp.int32),
        "slots": jnp.asarray(rng.integers(0, F + 1, (B, K)), jnp.int32),
        "vals": jnp.asarray(rng.normal(1, 0.2, (B, K)).astype(np.float32)),
        "mask": jnp.asarray(mask),
        "labels": jnp.asarray(rng.integers(0, 2, B).astype(np.float32)),
        "weights": jnp.ones(B, jnp.float32),
    }


def test_ffm_logit_oracle():
    model = FFMModel(v_dim=D, max_fields=F)
    batch = random_batch()
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.normal(size=(B, K, 1)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, K, F * D)), jnp.float32)
    got = np.asarray(model.logit({"w": w, "v": v}, batch))

    x = np.asarray(batch["vals"]) * np.asarray(batch["mask"])
    slots = np.asarray(batch["slots"])
    mask = np.asarray(batch["mask"])
    v4 = np.asarray(v).reshape(B, K, F, D)
    want = (np.asarray(w)[..., 0] * x).sum(-1)
    for b in range(B):
        for i in range(K):
            for j in range(i + 1, K):
                if mask[b, i] == 0 or mask[b, j] == 0:
                    continue
                fi, fj = slots[b, i], slots[b, j]
                if fi >= F or fj >= F:
                    continue
                want[b] += (
                    np.dot(v4[b, i, fj], v4[b, j, fi]) * x[b, i] * x[b, j]
                )
    np.testing.assert_allclose(got, want, rtol=1e-4)


def test_wide_deep_logit_shapes_and_grad():
    model = WideDeepModel(emb_dim=D, hidden=8, max_fields=F)
    batch = random_batch(2)
    rng_np = np.random.default_rng(3)
    w = jnp.asarray(rng_np.normal(size=(B, K, 1)), jnp.float32)
    emb = jnp.asarray(rng_np.normal(size=(B, K, D)), jnp.float32)
    dense = model.dense_init(jax.random.PRNGKey(0))
    logit = model.logit({"w": w, "emb": emb}, batch, dense)
    assert logit.shape == (B,)
    # gradient flows to dense params and to embeddings
    g = jax.grad(
        lambda d, e: jnp.sum(model.logit({"w": w, "emb": e}, batch, d))
    , argnums=(0, 1))(dense, emb)
    assert float(jnp.abs(g[0]["w1"]).sum()) > 0
    assert float(jnp.abs(g[1]).sum()) > 0


def make_cfg(ds, model, **kw):
    base = dict(
        train_path=ds.train_prefix,
        test_path=ds.test_prefix,
        epochs=12,
        batch_size=64,
        table_size_log2=14,
        max_nnz=24,
        max_fields=12,
        num_devices=1,
        model=model,
    )
    base.update(kw)
    return Config(**base)


def test_ffm_learns(toy_dataset):
    trainer = Trainer(make_cfg(toy_dataset, "ffm"))
    trainer.train()
    result = trainer.evaluate()
    assert result["auc"] > 0.68, result


def test_wide_deep_learns(toy_dataset):
    trainer = Trainer(make_cfg(toy_dataset, "wide_deep", sgd_lr=0.05))
    trainer.train()
    result = trainer.evaluate()
    assert result["auc"] > 0.68, result


@pytest.mark.parametrize("model", ["ffm", "wide_deep"])
def test_sharded_matches_single_device(toy_dataset, model):
    t1 = Trainer(make_cfg(toy_dataset, model, epochs=2))
    t1.train()
    t8 = Trainer(make_cfg(toy_dataset, model, epochs=2, num_devices=8))
    t8.train()
    for name in t1.state["tables"]:
        np.testing.assert_allclose(
            np.asarray(jax.device_get(t1.state["tables"][name]["param"])),
            np.asarray(jax.device_get(t8.state["tables"][name]["param"])),
            rtol=1e-5,
            atol=1e-6,
            err_msg=name,
        )
    # replicated dense params must match too (catches per-shard grads
    # that were never reduced across the mesh)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(jax.device_get(a)),
            np.asarray(jax.device_get(b)),
            rtol=1e-5,
            atol=1e-6,
        ),
        t1.state["dense"],
        t8.state["dense"],
    )


def test_wide_deep_checkpoint_roundtrip(toy_dataset, tmp_path):
    cfg = make_cfg(
        toy_dataset, "wide_deep", epochs=2, checkpoint_dir=str(tmp_path)
    )
    t = Trainer(cfg)
    t.train()
    before = jax.device_get(t.state["dense"])
    t2 = Trainer(cfg)
    assert t2.restore() is not None
    after = jax.device_get(t2.state["dense"])
    jax.tree.map(np.testing.assert_array_equal, before, after)


def test_ffm_aggregated_matches_pairwise():
    """The O(B*F^2*D) field-aggregated logit == the naive O(K^2) pairwise
    definition, including invalid fields, padding, duplicate fields,
    and values != 1."""
    import jax
    import jax.numpy as jnp

    from xflow_tpu.models.ffm import FFMModel

    rng = np.random.default_rng(5)
    b, k, f, d, t = 17, 13, 6, 4, 256
    model = FFMModel(v_dim=d, max_fields=f)
    w = rng.normal(0, 1, (t, 1)).astype(np.float32)
    v = rng.normal(0, 0.3, (t, f * d)).astype(np.float32)
    keys = rng.integers(0, t, (b, k)).astype(np.int32)
    batch = {
        "keys": jnp.asarray(keys),
        # includes out-of-range and negative fields, and duplicates
        "slots": jnp.asarray(
            rng.integers(-2, f + 3, (b, k)).astype(np.int32)
        ),
        "vals": jnp.asarray(rng.normal(0, 1, (b, k)).astype(np.float32)),
        "mask": jnp.asarray(
            (rng.random((b, k)) < 0.7).astype(np.float32)
        ),
        "labels": jnp.zeros(b, jnp.float32),
        "weights": jnp.ones(b, jnp.float32),
    }
    rows = {"w": jnp.asarray(w)[keys], "v": jnp.asarray(v)[keys]}
    fast = np.asarray(model.logit(rows, batch))
    slow = np.asarray(model.logit_pairwise(rows, batch))
    np.testing.assert_allclose(fast, slow, rtol=2e-4, atol=2e-5)
