"""Dense and sparse update paths must produce identical training states
— same consolidation semantics, different execution strategies
(config.update_mode docstring)."""

import numpy as np
import jax
import pytest

from xflow_tpu.config import Config
from xflow_tpu.trainer import Trainer


def cfg_for(ds, mode, model="lr", **kw):
    base = dict(
        train_path=ds.train_prefix,
        test_path=ds.test_prefix,
        epochs=2,
        batch_size=64,
        table_size_log2=14,
        max_nnz=24,
        max_fields=12,
        num_devices=1,
        update_mode=mode,
    )
    base.update(kw)
    return Config(model=model, **base)


@pytest.mark.parametrize(
    "model,table",
    [("lr", "w"), ("fm", "v"), ("mvm", "v"), ("wide_deep", "emb")],
)
def test_dense_equals_sparse(toy_dataset, model, table):
    kw = {"emb_dim": 4, "hidden_dim": 8} if model == "wide_deep" else {}
    td = Trainer(cfg_for(toy_dataset, "dense", model, **kw))
    td.train()
    ts = Trainer(cfg_for(toy_dataset, "sparse", model, **kw))
    ts.train()
    for name in td.state["tables"]:
        for part in td.state["tables"][name]:
            a = np.asarray(jax.device_get(td.state["tables"][name][part]))
            b = np.asarray(jax.device_get(ts.state["tables"][name][part]))
            np.testing.assert_allclose(
                a, b, rtol=1e-5, atol=1e-7, err_msg=f"{name}/{part}"
            )
    # dense (MLP) params must train in BOTH modes — a refactor once
    # dropped grad_dense on the sparse path and only the tables moved
    if td.state["dense"]:
        init_dense = Trainer(
            cfg_for(toy_dataset, "dense", model, **kw)
        ).state["dense"]
        for key in td.state["dense"]:
            a = np.asarray(jax.device_get(td.state["dense"][key]))
            b = np.asarray(jax.device_get(ts.state["dense"][key]))
            np.testing.assert_allclose(
                a, b, rtol=1e-5, atol=1e-6, err_msg=f"dense/{key}"
            )
            assert not np.allclose(
                a, np.asarray(jax.device_get(init_dense[key]))
            ) or a.size <= 1, f"dense/{key} never updated"


def test_dense_equals_sparse_sgd(toy_dataset):
    td = Trainer(cfg_for(toy_dataset, "dense", optimizer="sgd"))
    td.train()
    ts = Trainer(cfg_for(toy_dataset, "sparse", optimizer="sgd"))
    ts.train()
    a = np.asarray(jax.device_get(td.state["tables"]["w"]["param"]))
    b = np.asarray(jax.device_get(ts.state["tables"]["w"]["param"]))
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)


@pytest.mark.parametrize(
    "model,kw",
    [
        ("lr", {}),
        ("fm", {}),
        ("ffm", {"ffm_v_dim": 2}),
        ("wide_deep", {"emb_dim": 4, "hidden_dim": 8}),
        # hot table + microbatch compose: hot sections split per slice
        ("lr", {"hot_size_log2": 8, "hot_nnz": 8}),
        # mixed per-table hot (TableSpec.hot): ffm's w rides the MXU,
        # v keeps plain DMA for its hot-plane occurrences
        ("ffm", {"ffm_v_dim": 2, "hot_size_log2": 8, "hot_nnz": 8}),
    ],
)
def test_microbatch_equals_full_batch(toy_dataset, model, kw):
    """Gradient accumulation (Config.microbatch) is the same optimizer
    step as the single-pass dense path — grads are pre-divided by the
    full batch's real count, accumulated, then applied once."""
    t1 = Trainer(cfg_for(toy_dataset, "dense", model, **kw))
    t1.train()
    t4 = Trainer(cfg_for(toy_dataset, "dense", model, microbatch=4, **kw))
    t4.train()
    for name in t1.state["tables"]:
        for part in t1.state["tables"][name]:
            np.testing.assert_allclose(
                np.asarray(jax.device_get(t1.state["tables"][name][part])),
                np.asarray(jax.device_get(t4.state["tables"][name][part])),
                rtol=1e-5,
                atol=1e-7,
                err_msg=f"{model}:{name}/{part}",
            )
    for key in t1.state["dense"]:
        np.testing.assert_allclose(
            np.asarray(jax.device_get(t1.state["dense"][key])),
            np.asarray(jax.device_get(t4.state["dense"][key])),
            rtol=1e-5,
            atol=1e-6,
            err_msg=f"{model}:dense/{key}",
        )


@pytest.mark.parametrize(
    "model,kw",
    [
        ("fm", {}),
        ("mvm", {}),
        ("fm", {"hot_size_log2": 8, "hot_nnz": 8}),
        ("wide_deep", {"emb_dim": 4, "hidden_dim": 8}),
        ("lr", {"update_mode": "sequential", "microbatch": 4}),
    ],
)
def test_cold_consolidate_equals_plain(toy_dataset, model, kw, tmp_path):
    """Config.cold_consolidate merges duplicate cold keys before the
    scatter-add — purely an execution-strategy change, same gradients
    (a [M] scatter of per-occurrence grads vs a [U] scatter of
    segment-summed grads over the same keys)."""
    kw = dict(kw)  # parametrize dicts are shared across invocations
    mode = kw.pop("update_mode", "dense")
    if kw.get("hot_size_log2"):
        kw.update(freq_sample_mib=1, checkpoint_dir=str(tmp_path / "ck"))
    t_plain = Trainer(cfg_for(toy_dataset, mode, model, **kw))
    t_plain.train()
    t_cons = Trainer(
        cfg_for(toy_dataset, mode, model, cold_consolidate=True, **kw)
    )
    t_cons.train()
    for name in t_plain.state["tables"]:
        for part in t_plain.state["tables"][name]:
            np.testing.assert_allclose(
                np.asarray(
                    jax.device_get(t_plain.state["tables"][name][part])
                ),
                np.asarray(
                    jax.device_get(t_cons.state["tables"][name][part])
                ),
                rtol=1e-5,
                atol=1e-7,
                err_msg=f"{model}:{name}/{part}",
            )


@pytest.mark.parametrize("mb", [1, 4])
def test_dense_sharded_matches_single(toy_dataset, mb):
    t1 = Trainer(cfg_for(toy_dataset, "dense", num_devices=1))
    t1.train()
    t8 = Trainer(cfg_for(toy_dataset, "dense", num_devices=8, microbatch=mb))
    t8.train()
    np.testing.assert_allclose(
        np.asarray(jax.device_get(t1.state["tables"]["w"]["param"])),
        np.asarray(jax.device_get(t8.state["tables"]["w"]["param"])),
        rtol=1e-5,
        atol=1e-7,
    )
