"""End-to-end training: the minimum slice of SURVEY §7 stage 1 — all
three models must learn the planted signal in the synthetic libffm data
(reference de-facto verification: toy-data smoke run, SURVEY §4)."""

import numpy as np
import pytest

from xflow_tpu.config import Config
from xflow_tpu.trainer import Trainer


def make_cfg(ds, **kw):
    base = dict(
        train_path=ds.train_prefix,
        test_path=ds.test_prefix,
        epochs=12,
        batch_size=64,
        table_size_log2=14,
        max_nnz=24,
        max_fields=12,
        num_devices=1,
    )
    base.update(kw)
    return Config(**base)


@pytest.mark.parametrize("optimizer", ["ftrl", "sgd"])
def test_lr_learns(toy_dataset, optimizer):
    extra = {}
    if optimizer == "sgd":
        extra = dict(sgd_lr=0.05)
    trainer = Trainer(make_cfg(toy_dataset, model="lr", optimizer=optimizer, **extra))
    history = trainer.train()
    result = trainer.evaluate()
    assert history[-1]["train_logloss"] < history[0]["train_logloss"]
    assert result["auc"] > 0.7, result
    assert result["examples"] == toy_dataset.lines_per_shard


def test_fm_learns(toy_dataset):
    trainer = Trainer(make_cfg(toy_dataset, model="fm"))
    trainer.train()
    result = trainer.evaluate()
    assert result["auc"] > 0.68, result


def test_mvm_learns(toy_dataset):
    trainer = Trainer(make_cfg(toy_dataset, model="mvm", epochs=15))
    trainer.train()
    result = trainer.evaluate()
    assert result["auc"] > 0.65, result


def test_ftrl_induces_sparsity(toy_dataset):
    """L1 must leave most of the never/rarely-touched table at exactly 0."""
    trainer = Trainer(make_cfg(toy_dataset, model="lr", epochs=2))
    trainer.train()
    import jax

    w = np.asarray(jax.device_get(trainer.state["tables"]["w"]["param"]))
    assert (w == 0.0).mean() > 0.9


def test_train_deterministic(toy_dataset):
    cfg = make_cfg(toy_dataset, model="lr", epochs=2)
    import jax

    t1 = Trainer(cfg)
    t1.train()
    t2 = Trainer(cfg)
    t2.train()
    w1 = np.asarray(jax.device_get(t1.state["tables"]["w"]["param"]))
    w2 = np.asarray(jax.device_get(t2.state["tables"]["w"]["param"]))
    np.testing.assert_array_equal(w1, w2)
