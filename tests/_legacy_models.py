"""FROZEN pre-refactor model implementations — the bitwise oracles for
the models/blocks.py refactor (tests/test_models.py no-regression
pins).

These are verbatim copies of the five incumbent families' forward (and
explicit gradient) math as they stood BEFORE the logits were expressed
through models/blocks.py.  They exist so the refactor's
bitwise-unchanged contract is testable forever: a TrainStep built
around a legacy model and one built around the refactored model must
produce np.array_equal pctr on the same state and batch, in dense,
MXU-hot, and tiered store modes.

DO NOT "clean up" or re-route these through blocks — drifting the
oracle toward the implementation is exactly the failure mode this file
exists to prevent.  TableSpecs mirror the live models so init_state
produces identical tables for either side.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from xflow_tpu.models.base import AutodiffModel, BatchArrays, TableSpec


class LegacyLRModel:
    name = "lr"
    uses_slots = False

    def tables(self) -> list[TableSpec]:
        return [TableSpec("w", 1, lambda rng, shape: jnp.zeros(shape, jnp.float32))]

    def logit(self, rows: dict[str, jax.Array], batch: BatchArrays) -> jax.Array:
        x = batch["vals"] * batch["mask"]  # [B, K]
        return jnp.sum(rows["w"][..., 0] * x, axis=-1)

    def grad_logit(
        self, rows: dict[str, jax.Array], batch: BatchArrays
    ) -> dict[str, jax.Array]:
        x = batch["vals"] * batch["mask"]
        return {"w": x[..., None]}


@dataclasses.dataclass(frozen=True)
class LegacyFMModel:
    v_dim: int = 10
    v_init_scale: float = 1e-2
    name: str = "fm"
    uses_slots = False

    def tables(self) -> list[TableSpec]:
        return [
            TableSpec("w", 1, lambda rng, shape: jnp.zeros(shape, jnp.float32)),
            TableSpec(
                "v",
                self.v_dim,
                lambda rng, shape: (
                    jax.random.normal(rng, shape, jnp.float32) * self.v_init_scale
                ),
                init_kind="normal",
                init_scale=self.v_init_scale,
            ),
        ]

    def _interaction_pieces(
        self, rows: dict[str, jax.Array], batch: BatchArrays
    ) -> tuple[jax.Array, jax.Array]:
        x = (batch["vals"] * batch["mask"])[..., None]  # [B, K, 1]
        vx = rows["v"] * x  # [B, K, D]
        sum_vx = jnp.sum(vx, axis=1)  # [B, D]
        sum_vx2 = jnp.sum(vx * vx, axis=1)  # [B, D]
        return sum_vx, sum_vx2

    def logit(self, rows: dict[str, jax.Array], batch: BatchArrays) -> jax.Array:
        x = batch["vals"] * batch["mask"]
        linear = jnp.sum(rows["w"][..., 0] * x, axis=-1)
        sum_vx, sum_vx2 = self._interaction_pieces(rows, batch)
        return linear + jnp.sum(sum_vx * sum_vx - sum_vx2, axis=-1)

    def grad_logit(
        self, rows: dict[str, jax.Array], batch: BatchArrays
    ) -> dict[str, jax.Array]:
        x = batch["vals"] * batch["mask"]  # [B, K]
        sum_vx, _ = self._interaction_pieces(rows, batch)
        vx = rows["v"] * x[..., None]
        grad_v = (sum_vx[:, None, :] - vx) * x[..., None]
        return {"w": x[..., None], "v": grad_v}


_GUARD_EPS = 1e-12


@dataclasses.dataclass(frozen=True)
class LegacyMVMModel:
    v_dim: int = 10
    v_init_scale: float = 1e-2
    max_fields: int = 32
    name: str = "mvm"

    def tables(self) -> list[TableSpec]:
        return [
            TableSpec(
                "v",
                self.v_dim,
                lambda rng, shape: (
                    jax.random.normal(rng, shape, jnp.float32) * self.v_init_scale
                ),
                init_kind="normal",
                init_scale=self.v_init_scale,
            )
        ]

    def _slot_terms(
        self, rows: dict[str, jax.Array], batch: BatchArrays
    ) -> tuple[jax.Array, jax.Array]:
        x = batch["vals"] * batch["mask"]  # [B, K]
        onehot = jax.nn.one_hot(
            batch["slots"], self.max_fields, dtype=x.dtype
        )  # [B, K, S]
        vx = rows["v"] * x[..., None]  # [B, K, D]
        slotsum = jnp.einsum("bks,bkd->bsd", onehot, vx)  # [B, S, D]
        one_plus = 1.0 + slotsum
        prod = jnp.prod(one_plus, axis=1)  # [B, D]
        return one_plus, prod

    def logit(self, rows: dict[str, jax.Array], batch: BatchArrays) -> jax.Array:
        _, prod = self._slot_terms(rows, batch)
        return jnp.sum(prod - 1.0, axis=-1)

    def grad_logit(
        self, rows: dict[str, jax.Array], batch: BatchArrays
    ) -> dict[str, jax.Array]:
        x = batch["vals"] * batch["mask"]  # [B, K]
        one_plus, prod = self._slot_terms(rows, batch)
        slot_idx = jnp.clip(batch["slots"], 0, self.max_fields - 1)  # [B, K]
        own = jnp.take_along_axis(
            one_plus,
            slot_idx[:, :, None],
            axis=1,
        )  # [B, K, D]
        safe = jnp.where(jnp.abs(own) < _GUARD_EPS, 1.0, own)
        grad_v = jnp.where(
            jnp.abs(own) < _GUARD_EPS,
            0.0,
            prod[:, None, :] / safe,
        ) * x[..., None]
        valid = (
            (batch["slots"] >= 0) & (batch["slots"] < self.max_fields)
        )[..., None]
        return {"v": jnp.where(valid, grad_v, 0.0)}


@dataclasses.dataclass(frozen=True)
class LegacyFFMModel(AutodiffModel):
    v_dim: int = 4
    max_fields: int = 32
    v_init_scale: float = 1e-2
    name: str = "ffm"

    def tables(self) -> list[TableSpec]:
        return [
            TableSpec("w", 1, lambda rng, shape: jnp.zeros(shape, jnp.float32)),
            TableSpec(
                "v",
                self.max_fields * self.v_dim,
                lambda rng, shape: (
                    jax.random.normal(rng, shape, jnp.float32) * self.v_init_scale
                ),
                hot=False,
                init_kind="normal",
                init_scale=self.v_init_scale,
            ),
        ]

    def logit(
        self,
        rows: dict[str, jax.Array],
        batch: BatchArrays,
        dense: dict | None = None,
    ) -> jax.Array:
        b, k = batch["keys"].shape
        f, d = self.max_fields, self.v_dim
        x = batch["vals"] * batch["mask"]  # [B, K]
        linear = jnp.sum(rows["w"][..., 0] * x, axis=-1)

        valid = (
            (batch["slots"] >= 0) & (batch["slots"] < f) & (batch["mask"] > 0)
        )
        x_eff = jnp.where(valid, x, 0.0)
        slot = jnp.clip(batch["slots"], 0, f - 1)  # [B, K]
        onehot = (
            (slot[:, :, None] == jnp.arange(f)[None, None, :])
            & valid[:, :, None]
        ).astype(rows["v"].dtype)  # [B, K, F]

        vx = rows["v"] * x_eff[:, :, None]  # [B, K, E]
        s = jnp.einsum("bkf,bke->bfe", onehot, vx)  # [B, F, E]

        s4 = s.reshape(b, f, f, d)
        cross = jnp.sum(
            s4 * jnp.transpose(s4, (0, 2, 1, 3)), axis=(1, 2, 3)
        )
        eslot = (jnp.arange(f * d) // d).astype(slot.dtype)  # [E]
        emask = eslot[None, None, :] == slot[:, :, None]  # [B, K, E]
        diag = jnp.sum(jnp.where(emask, vx * vx, 0.0), axis=(1, 2))
        return linear + 0.5 * (cross - diag)


@dataclasses.dataclass(frozen=True)
class LegacyWideDeepModel(AutodiffModel):
    emb_dim: int = 8
    hidden: int = 64
    max_fields: int = 32
    v_init_scale: float = 1e-2
    name: str = "wide_deep"

    def tables(self) -> list[TableSpec]:
        return [
            TableSpec("w", 1, lambda rng, shape: jnp.zeros(shape, jnp.float32)),
            TableSpec(
                "emb",
                self.emb_dim,
                lambda rng, shape: (
                    jax.random.normal(rng, shape, jnp.float32) * self.v_init_scale
                ),
                init_kind="normal",
                init_scale=self.v_init_scale,
            ),
        ]

    def dense_init(self, rng: jax.Array) -> dict:
        k1, k2 = jax.random.split(rng)
        in_dim = self.max_fields * self.emb_dim
        return {
            "w1": jax.random.normal(k1, (in_dim, self.hidden), jnp.float32)
            * jnp.sqrt(2.0 / in_dim),
            "b1": jnp.zeros((self.hidden,), jnp.float32),
            "w2": jax.random.normal(k2, (self.hidden, 1), jnp.float32)
            * jnp.sqrt(1.0 / self.hidden),
            "b2": jnp.zeros((1,), jnp.float32),
        }

    def logit(
        self,
        rows: dict[str, jax.Array],
        batch: BatchArrays,
        dense: dict | None = None,
    ) -> jax.Array:
        assert dense is not None, "wide_deep requires dense MLP params"
        x = batch["vals"] * batch["mask"]  # [B, K]
        wide = jnp.sum(rows["w"][..., 0] * x, axis=-1)

        onehot = jax.nn.one_hot(
            batch["slots"], self.max_fields, dtype=x.dtype
        )  # [B, K, F]
        embx = rows["emb"] * x[..., None]  # [B, K, E]
        field_emb = jnp.einsum("bkf,bke->bfe", onehot, embx)  # [B, F, E]
        h = field_emb.reshape(field_emb.shape[0], -1)  # [B, F*E]
        h = jax.nn.relu(h @ dense["w1"] + dense["b1"])
        deep = (h @ dense["w2"] + dense["b2"])[:, 0]
        return wide + deep


def legacy_model_for(cfg):
    """Legacy twin of models.make_model(cfg) for the five incumbent
    families (the blocks refactor's no-regression scope)."""
    if cfg.model == "lr":
        return LegacyLRModel()
    if cfg.model == "fm":
        return LegacyFMModel(v_dim=cfg.v_dim, v_init_scale=cfg.v_init_scale)
    if cfg.model == "mvm":
        return LegacyMVMModel(
            v_dim=cfg.v_dim,
            v_init_scale=cfg.v_init_scale,
            max_fields=cfg.max_fields,
        )
    if cfg.model == "ffm":
        return LegacyFFMModel(
            v_dim=cfg.ffm_v_dim,
            max_fields=cfg.max_fields,
            v_init_scale=cfg.v_init_scale,
        )
    if cfg.model == "wide_deep":
        return LegacyWideDeepModel(
            emb_dim=cfg.emb_dim,
            hidden=cfg.hidden_dim,
            max_fields=cfg.max_fields,
            v_init_scale=cfg.v_init_scale,
        )
    raise ValueError(f"no legacy oracle for {cfg.model!r}")
