"""Benchmark harness: steady-state LR+FTRL training throughput.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "examples/sec", "vs_baseline": N}

Baseline: the reference publishes no numbers (BASELINE.md), so
``vs_baseline`` is measured against a CPU proxy — the same sparse
LR+FTRL step compiled for this host's CPU backend, standing in for the
reference's CPU-cluster workers.  The north-star comparison (8-worker
ps-lite on Criteo) needs that cluster; this proxy is documented in
BASELINE.md terms: value = accelerator examples/sec, vs_baseline =
accelerator/CPU-host throughput ratio.

Shapes model Criteo-style CTR: 39 features/sample padded to 40,
batch 131072 (throughput saturates there on v5e: measured 0.97M ex/s at
B=16k, 1.34M at 64k, 1.40M at 128k, 1.26M at 256k), 2^24-row hashed
table.  The step is slice-count-bound: XLA TPU gather/scatter cost
~8-10ns per gathered/scattered slice regardless of slice width or table
size (measured on v5e), so B*nnz slices set the floor; see
docs/PERF.md for the full measurement log.
"""

from __future__ import annotations

import json
import time

import numpy as np


def build(platform_devices, cfg):
    from xflow_tpu.models import make_model
    from xflow_tpu.optim import make_optimizer
    from xflow_tpu.parallel.mesh import make_mesh
    from xflow_tpu.parallel.step import TrainStep, init_state

    mesh = make_mesh(1, devices=platform_devices[:1])
    model = make_model(cfg)
    opt = make_optimizer(cfg)
    step = TrainStep(model, opt, cfg, mesh)
    state = init_state(model, opt, cfg, mesh)
    return step, state


def make_batches(cfg, num, seed=0):
    from xflow_tpu.io.batch import make_batch

    rng = np.random.default_rng(seed)
    b = cfg.batch_size
    k = cfg.max_nnz + (cfg.hot_nnz if cfg.hot_size else 0)
    batches = []
    for _ in range(num):
        # ~39 real features/sample, Criteo-style; zipf-ish key reuse (30%
        # of occurrences drawn from a 1000-key head) so consolidation and
        # the hot table see realistic duplicate densities
        nnz = 39
        mask = np.zeros((b, k), np.float32)
        mask[:, :nnz] = 1.0
        keys = rng.integers(0, cfg.table_size, (b, k)).astype(np.int32)
        head = rng.integers(0, 1000, (b, k)).astype(np.int32)
        use_head = rng.random((b, k)) < 0.3
        keys = np.where(use_head, head, keys)
        slots = np.broadcast_to(np.arange(k, dtype=np.int32), (b, k)).copy()
        vals = np.ones((b, k), np.float32)
        labels = rng.integers(0, 2, b).astype(np.float32)
        weights = np.ones(b, np.float32)
        # head keys already live in [0, 1000) ⊂ [0, hot_size) — the
        # identity remap is what io/freq.py would compute here
        batches.append(
            make_batch(
                keys, slots, vals, mask, labels, weights,
                cfg.hot_size, cfg.hot_nnz,
            )
        )
    return batches


def run(step, state, batches, iters, warmup=3):
    import jax

    device_batches = [step.put_batch(b) for b in batches]
    def sync(st):
        # device_get forces real completion; block_until_ready has been
        # observed returning early on tunneled PJRT platforms
        jax.device_get(st["tables"]["w"]["param"][:1, 0])

    for i in range(warmup):
        state, m = step.train(state, device_batches[i % len(device_batches)])
    sync(state)
    t0 = time.perf_counter()
    for i in range(iters):
        state, m = step.train(state, device_batches[i % len(device_batches)])
    sync(state)
    dt = time.perf_counter() - t0
    return state, iters * batches[0].batch_size / dt


def main() -> None:
    import jax

    from xflow_tpu.config import Config

    # Flagship config: hot table on (docs/PERF.md "The win") — the 1000-key
    # head (30% of occurrences) rides the MXU path; cold capacity 32 +
    # hot capacity 16 covers the 39-feature rows (cold overflow truncation
    # < 0.5% of entries at this head rate).
    cfg = Config(
        model="lr",
        optimizer="ftrl",
        table_size_log2=24,
        batch_size=131072,
        max_nnz=32,
        hot_size_log2=12,
        hot_nnz=16,
        num_devices=1,
    )
    accel = [d for d in jax.devices() if d.platform != "cpu"]
    cpu = jax.devices("cpu")

    batches = make_batches(cfg, 4)
    if accel:
        step, state = build(accel, cfg)
        _, accel_eps = run(step, state, batches, iters=20)
    else:
        step, state = build(cpu, cfg)
        _, accel_eps = run(step, state, batches, iters=6)

    # CPU proxy baseline, smaller table/iters to keep runtime bounded.
    # The proxy runs ITS best config (no hot table — one-hot matmuls are
    # an MXU trick, slow on CPU; scatter-add DMA is the CPU-fast path),
    # so vs_baseline compares best-vs-best.
    cpu_cfg = cfg.replace(
        table_size_log2=22, batch_size=16384, max_nnz=40, hot_size_log2=0
    )
    cpu_step, cpu_state = build(cpu, cpu_cfg)
    cpu_batches = make_batches(cpu_cfg, 4)
    _, cpu_eps = run(cpu_step, cpu_state, cpu_batches, iters=8, warmup=2)

    print(
        json.dumps(
            {
                "metric": "lr_ftrl_train_examples_per_sec",
                "value": round(accel_eps, 1),
                "unit": "examples/sec",
                "vs_baseline": round(accel_eps / cpu_eps, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
