"""Benchmark harness: steady-state LR+FTRL training throughput.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "examples/sec",
     "vs_baseline": N, "backend": ..., ...}

Robustness (round-2 fix): the accelerator is probed in a SUBPROCESS with
a timeout before this process imports jax — a wedged device tunnel hangs
clients forever inside PJRT client init, and an accelerator plugin that
fails to initialize raises from a bare ``jax.devices()``.  Neither may
take the bench down: on probe failure the bench pins JAX_PLATFORMS=cpu
and still emits its JSON line (with ``"backend": "cpu"``).  Every other
failure path is also caught; the bench always prints a parseable line
and exits 0.

Baseline: the reference publishes no numbers (BASELINE.md), so
``vs_baseline`` is measured against a CPU proxy — the same sparse
LR+FTRL step compiled for this host's CPU backend, standing in for the
reference's CPU-cluster workers.  value = accelerator examples/sec,
vs_baseline = accelerator/CPU-host throughput ratio.

Shapes model Criteo-style CTR: 39 features/sample, batch 131072
(throughput saturates there on v5e), 2^24-row hashed table.  The step is
slice-count-bound: XLA TPU gather/scatter costs ~8-10ns per slice
regardless of slice width or table size (measured on v5e), so B*nnz
slices set the floor; see docs/PERF.md for the measurement log.

Secondary metrics in the same JSON line:
  - ``hot_truncated_frac``: measured fraction of real feature entries
    dropped by hot/cold steering at the flagship config (claimed <0.5%).
  - ``e2e_examples_per_sec`` / ``parse_mb_per_sec``: end-to-end
    text->parse->pack->device->train throughput over a generated zipf
    libffm dataset, exercising the real ShardLoader + native parser
    (the reference's whole bottleneck was host IO — SURVEY §7c).
  - ``input_stall_frac`` / ``e2e_phase_seconds``: per-phase attribution
    of the e2e loop (input stall vs h2d vs dispatch vs device block) —
    the same accounting the trainer emits per epoch (xflow_tpu/obs,
    docs/OBSERVABILITY.md), so a degraded e2e number names its
    bottleneck instead of just shipping ``degraded: true``.
  - ``e2e_packed_examples_per_sec`` / ``packed_read_examples_per_sec``:
    the steady-state path — text parsed ONCE into the packed-batch
    cache (io/packed.py), epochs 2..N stream device-ready batches over
    the compact wire (Config.wire_mode) with transfer-ahead.  The
    read rate is the host-side feed capacity; the e2e rate is bounded
    by this environment's tunneled host<->TPU link (docs/PERF.md).
"""

from __future__ import annotations

import glob
import json
import os
import subprocess
import sys
import time

import numpy as np

PROBE_TIMEOUT = float(os.environ.get("XFLOW_BENCH_PROBE_TIMEOUT", "240"))


def probe_accelerator(timeout: float = PROBE_TIMEOUT) -> str | None:
    """Name of the non-CPU platform, or None if absent/broken/hung.

    Runs in a subprocess so a wedged tunnel (client hangs forever in
    PJRT client creation) or a crashing plugin cannot take down the
    bench process.  Killing the probe on timeout is safe: a client that
    never finished initializing holds no device lease.
    """
    code = (
        "import jax\n"
        "ds = [d for d in jax.devices() if d.platform != 'cpu']\n"
        "print('PLATFORM=' + (ds[0].platform if ds else ''))\n"
    )
    try:
        proc = subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
        )
    except OSError:
        return None
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        # A healthy client enumerates devices well inside the timeout; a
        # probe still stuck here means the tunnel is already unhealthy.
        # Prefer SIGTERM + grace over SIGKILL so a client that *can*
        # still clean up releases any partially acquired lease.
        proc.terminate()
        try:
            proc.communicate(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()
        return None
    if proc.returncode != 0:
        return None
    for line in (out or "").splitlines():
        if line.startswith("PLATFORM="):
            return line[len("PLATFORM=") :] or None
    return None


def build(platform_devices, cfg):
    from xflow_tpu.models import make_model
    from xflow_tpu.optim import make_optimizer
    from xflow_tpu.parallel.mesh import make_mesh
    from xflow_tpu.parallel.step import TrainStep, init_state

    mesh = make_mesh(1, devices=platform_devices[:1])
    model = make_model(cfg)
    opt = make_optimizer(cfg)
    step = TrainStep(model, opt, cfg, mesh)
    state = init_state(model, opt, cfg, mesh)
    return step, state


def make_batches(cfg, num, seed=0):
    """Synthetic device batches + the measured hot-truncation fraction."""
    from xflow_tpu.io.batch import make_batch

    rng = np.random.default_rng(seed)
    b = cfg.batch_size
    k = cfg.max_nnz + (cfg.hot_nnz if cfg.hot_size else 0)
    batches = []
    entries_in = 0
    entries_kept = 0
    for _ in range(num):
        # ~39 real features/sample, Criteo-style; zipf-ish key reuse (30%
        # of occurrences drawn from a 1000-key head) so consolidation and
        # the hot table see realistic duplicate densities
        nnz = 39
        mask = np.zeros((b, k), np.float32)
        mask[:, :nnz] = 1.0
        keys = rng.integers(0, cfg.table_size, (b, k)).astype(np.int32)
        head = rng.integers(0, 1000, (b, k)).astype(np.int32)
        use_head = rng.random((b, k)) < 0.3
        keys = np.where(use_head, head, keys)
        slots = np.broadcast_to(np.arange(k, dtype=np.int32), (b, k)).copy()
        vals = np.ones((b, k), np.float32)
        labels = rng.integers(0, 2, b).astype(np.float32)
        weights = np.ones(b, np.float32)
        # head keys already live in [0, 1000) ⊂ [0, hot_size) — the
        # identity remap is what io/freq.py would compute here
        batch = make_batch(
            keys, slots, vals, mask, labels, weights,
            cfg.hot_size, cfg.hot_nnz,
        )
        entries_in += int(mask.sum())
        entries_kept += int(batch.mask.sum() + batch.hot_mask.sum())
        batches.append(batch)
    truncated_frac = (entries_in - entries_kept) / max(entries_in, 1)
    return batches, truncated_frac


def prepare_real_data(cfg, n_examples: int):
    """Shared real-data setup: zipf text shard (cached), CSR binary
    cache (cached), frequency counts + hot remap at cfg's geometry.
    Returns (data_path, csr_path, remap, hot_mass|None)."""
    from xflow_tpu.io import binary, freq

    data_path = ensure_synth_data(
        os.path.join(
            os.environ.get("XFLOW_BENCH_CACHE", "/tmp/xflow_bench"),
            f"zipf-{n_examples}.ffm",
        ),
        n_examples,
    )
    csr = data_path + ".xfbc"
    if not os.path.exists(csr):
        binary.convert_shard(data_path, csr, block_mib=8)
    remap = None
    mass = None
    if cfg.hot_size:
        counts = cached_counts(csr, cfg.table_size_log2)
        remap = freq.build_remap(counts, cfg.hot_size)
        mass = freq.hot_mass(counts, remap, cfg.hot_size)
    return data_path, csr, remap, mass


def cached_counts(csr: str, table_size_log2: int):
    """Key-frequency counts over the CSR cache, memoized on disk —
    bench_models.py runs each model in a fresh subprocess and the
    counting pass (~1 min on a 1-core host) must not repeat per model."""
    from xflow_tpu.io import freq

    cache = f"{csr}.counts-t{table_size_log2}.npy"
    # stale if the CSR cache was regenerated after the counts were taken
    if os.path.exists(cache) and (
        os.path.getmtime(cache) >= os.path.getmtime(csr)
    ):
        return np.load(cache)
    counts = freq.count_keys([csr], None, 1 << table_size_log2, 64 << 20)
    tmp = f"{cache}.tmp.{os.getpid()}.npy"
    np.save(tmp, counts)
    os.replace(tmp, cache)
    return counts


def real_batches(cfg, csr_path: str, remap, num: int):
    """Production-loader batches off the CSR cache — the device bench
    measures the step on REAL zipf-distributed keys (synthetic uniform
    keys understate hot-table coverage; the measured head mass is
    ~0.71-0.85, not the old synthetic 30%)."""
    from xflow_tpu.io.loader import ShardLoader

    loader = ShardLoader(
        csr_path,
        batch_size=cfg.batch_size,
        max_nnz=cfg.max_nnz,
        table_size=cfg.table_size,
        hash_seed=cfg.seed,
        remap=remap,
        hot_size=cfg.hot_size,
        hot_nnz=cfg.hot_nnz if cfg.hot_size else 0,
    )
    batches = []
    kept = 0.0
    real = 0
    for batch, _ in loader.iter_batches():
        if batch.num_real() < cfg.batch_size:
            break  # partial tail batch would inflate run()'s eps
        kept += float(batch.mask.sum() + batch.hot_mask.sum())
        real += batch.num_real()
        batches.append(batch)
        if len(batches) == num:
            break
    if len(batches) < num:
        raise ValueError(
            f"{csr_path}: only {len(batches)} full batches of "
            f"{cfg.batch_size} available, need {num}"
        )
    truncated = 1.0 - kept / (real * 39.0)  # generator: 39 features/row
    return batches, truncated


def run(step, state, batches, iters, warmup=3):
    import jax

    device_batches = [step.put_batch(b) for b in batches]

    def sync(st):
        # device_get forces real completion; block_until_ready has been
        # observed returning early on tunneled PJRT platforms
        first = next(iter(st["tables"].values()))
        jax.device_get(first["param"][:1, 0])

    for i in range(warmup):
        state, m = step.train(state, device_batches[i % len(device_batches)])
    sync(state)
    t0 = time.perf_counter()
    for i in range(iters):
        state, m = step.train(state, device_batches[i % len(device_batches)])
    sync(state)
    dt = time.perf_counter() - t0
    return state, iters * batches[0].batch_size / dt


def bench_e2e(devices, cfg, data_path: str, result: dict, remap=None) -> None:
    """End-to-end: text shard -> BlockReader -> (native) parser -> pack ->
    put_batch -> fused train step, via the production ShardLoader
    prefetch path.  Fills e2e_* fields of ``result`` in place.
    ``remap`` (from prepare_real_data at the same cfg) skips the
    frequency-count setup when the caller already has one."""
    import jax

    from xflow_tpu.io.loader import ShardLoader, make_parse_fn
    from xflow_tpu.native import available as native_available

    step, state = build(devices, cfg)
    parse_fn = make_parse_fn(cfg.table_size, True, cfg.seed)
    if remap is not None and len(remap) != cfg.table_size:
        remap = None  # caller's remap was built for a different table
    if cfg.hot_size and remap is None:
        # production hot-table path: measure key frequencies on a sample
        # and permute the head into rows [0, H) (io/freq.py), exactly as
        # trainer._init_remap does; setup cost is outside the timed loop
        # (one-time, like compilation)
        from xflow_tpu.io import freq

        counts = freq.count_keys(
            [data_path], parse_fn, cfg.table_size, 32 << 20, 8 << 20
        )
        remap = freq.build_remap(counts, cfg.hot_size)
        result["hot_mass"] = round(
            freq.hot_mass(counts, remap, cfg.hot_size), 4
        )
    loader = ShardLoader(
        data_path,
        batch_size=cfg.batch_size,
        max_nnz=cfg.max_nnz,
        table_size=cfg.table_size,
        block_mib=8,
        parse_fn=parse_fn,
        remap=remap,
        hot_size=cfg.hot_size,
        hot_nnz=cfg.hot_nnz,
    )
    workers = max(1, min(6, (os.cpu_count() or 1) - 1))
    nbytes = os.path.getsize(data_path)
    examples = 0
    # Per-phase attribution of the e2e loop (ISSUE 1): input_stall is
    # time blocked on the prefetch iterator (parse+pack hide behind
    # it), h2d the inline put_batch, dispatch the async train call;
    # device_block the final drain.  input_stall_frac says whether the
    # gap between `value` (pure compute) and e2e_examples_per_sec is
    # the host pipeline or the device path.
    phase = {"input_stall": 0.0, "h2d": 0.0, "dispatch": 0.0}
    it = loader.prefetch(depth=2, parse_workers=workers)
    t0 = time.perf_counter()
    while True:
        t = time.perf_counter()
        try:
            batch, _ = next(it)
        except StopIteration:
            break
        phase["input_stall"] += time.perf_counter() - t
        t = time.perf_counter()
        arrays = step.put_batch(batch)
        phase["h2d"] += time.perf_counter() - t
        t = time.perf_counter()
        state, _ = step.train(state, arrays)
        phase["dispatch"] += time.perf_counter() - t
        examples += batch.num_real()
    t = time.perf_counter()
    jax.device_get(state["tables"]["w"]["param"][:1, 0])
    phase["device_block"] = time.perf_counter() - t
    dt = time.perf_counter() - t0
    result["e2e_examples_per_sec"] = round(examples / dt, 1)
    result["e2e_mb_per_sec"] = round(nbytes / dt / 2**20, 1)
    result["e2e_examples"] = examples
    result["input_stall_frac"] = round(phase["input_stall"] / dt, 4)
    result["e2e_phase_seconds"] = {
        k: round(v, 3) for k, v in phase.items()
    }
    result["native_parser"] = bool(native_available())

    # host-only parse+pack rate (no device work): isolates the host
    # pipeline the e2e number is bound by on low-core hosts
    t0 = time.perf_counter()
    parsed = 0
    for batch, _ in loader.prefetch(depth=2, parse_workers=workers):
        parsed += batch.num_real()
    dt = time.perf_counter() - t0
    result["parse_mb_per_sec"] = round(nbytes / dt / 2**20, 1)
    result["parse_examples_per_sec"] = round(parsed / dt, 1)

    # -- packed-batch cache path (io/packed.py): the steady-state story.
    # Text parses ONCE into device-ready batches; epochs 2..N stream
    # them at memory speed.  Cached on disk keyed by config + remap;
    # the v2 cache stores PRE-COMPACTED records (io/compact.py), so the
    # steady-state feed pays zero per-batch compaction or wire packing.
    from xflow_tpu.io import packed as packed_mod

    digest = (packed_mod.remap_digest(remap) or "none")[:12]
    pk_path = (
        f"{data_path}.pk2-b{cfg.batch_size}-k{cfg.max_nnz}"
        f"-t{cfg.table_size_log2}-h{cfg.hot_size_log2}.{cfg.hot_nnz}"
        f"-s{cfg.seed}-r{digest}"
    )
    if not os.path.exists(pk_path):
        t0 = time.perf_counter()
        packed_mod.convert_shard(
            data_path,
            pk_path,
            batch_size=cfg.batch_size,
            max_nnz=cfg.max_nnz,
            table_size=cfg.table_size,
            hot_size=cfg.hot_size,
            hot_nnz=cfg.hot_nnz if cfg.hot_size else 0,
            hash_mode=True,
            hash_seed=cfg.seed,
            block_mib=8,
            remap=remap,
            parse_fn=parse_fn,
        )
        result["packed_build_secs"] = round(time.perf_counter() - t0, 1)
    pk_loader = ShardLoader(
        pk_path,
        batch_size=cfg.batch_size,
        max_nnz=cfg.max_nnz,
        table_size=cfg.table_size,
        hash_seed=cfg.seed,
        remap=remap,
        hot_size=cfg.hot_size,
        hot_nnz=cfg.hot_nnz if cfg.hot_size else 0,
        emit_compact=step.dict_wire,
    )
    result["wire_format"] = step.wire_format
    # host-only read rate (epoch-2+ feed capacity, no device).  Records
    # are mmap-backed views; to keep the metric honest this loop runs
    # the numpy half of put_batch — by construction exactly the
    # per-batch work the training feed performs
    # (parallel/step.py::host_wire_np).
    t0 = time.perf_counter()
    n = 0
    for batch, _ in pk_loader.iter_batches():
        step.host_wire_np(batch)
        n += batch.num_real()
    dt = time.perf_counter() - t0
    result["packed_read_examples_per_sec"] = round(n / dt, 1)
    # e2e with the input fan-out + staging ring (the trainer's
    # production structure: io/fanout.py ShardStreamPool feeding
    # trainer._transfer_ahead's ring): the packed corpus splits into
    # XFLOW_BENCH_STREAMS contiguous sub-shards (split_shard_v2 — raw
    # record copy) so N reader streams pre-read/compact ahead while the
    # ring stages XFLOW_BENCH_RING_DEPTH batches of h2d.  The first
    # timed pass on the tunneled link warms slowly (and compiles the
    # full- and tail-batch shape buckets), so run two and report the
    # steady-state (second) pass — that IS the epoch regime.  The
    # second pass must hit the executable cache only: e2e_recompiles
    # counts programs compiled DURING it (acceptance: 0 — the dict
    # wire's plane_cap bucketing keeps steady shapes on one program,
    # and the fan-out's serial-order merge feeds the identical batch
    # sequence).
    from concurrent.futures import ThreadPoolExecutor

    from xflow_tpu.io.fanout import ShardStreamPool
    from xflow_tpu.trainer import _ring_workers

    n_streams = int(os.environ.get("XFLOW_BENCH_STREAMS", "4"))
    ring_depth = int(os.environ.get("XFLOW_BENCH_RING_DEPTH", "4"))
    fan_prefix = f"{pk_path}.fan{n_streams}"
    # a hard-killed prior split can leave `.tmp.<pid>` residue next to
    # the real sub-shards — the tail-safety convention says any name
    # with a .tmp infix is never a shard
    fan_paths = sorted(
        p for p in glob.glob(glob.escape(fan_prefix) + "-*")
        if ".tmp." not in os.path.basename(p)
    )
    if not fan_paths:
        fan_paths = packed_mod.split_shard_v2(
            pk_path, fan_prefix, n_streams
        )
    result["input_streams"] = n_streams
    result["transfer_ahead_depth"] = ring_depth

    def fan_loader(path):
        return ShardLoader(
            path,
            batch_size=cfg.batch_size,
            max_nnz=cfg.max_nnz,
            table_size=cfg.table_size,
            hash_seed=cfg.seed,
            remap=remap,
            hot_size=cfg.hot_size,
            hot_nnz=cfg.hot_nnz if cfg.hot_size else 0,
            emit_compact=step.dict_wire,
        )

    def train_cache_size():
        try:
            return int(step.train._cache_size())
        except Exception:
            return -1

    best = 0.0
    best_link = 0.0
    wire_bytes_per_batch = None
    compaction_ratio = None
    for pass_i in range(2):
        cache_before = train_cache_size()
        t0 = time.perf_counter()
        n = 0
        sent = 0
        pending = []
        pool = ShardStreamPool(
            fan_paths, fan_loader, num_streams=n_streams, depth=2,
            transform=step.precompact,
        )
        try:
            with ThreadPoolExecutor(_ring_workers(ring_depth)) as ex:
                for batch, _, _ in pool:
                    sent += 1
                    if wire_bytes_per_batch is None:
                        # what actually crosses the link per dispatch
                        # (the bytes x link-MB/s reconciliation,
                        # VERDICT r4 #6)
                        wire, cb = step.host_wire_np(batch)
                        wire_bytes_per_batch = sum(
                            v.nbytes for v in wire.values()
                        )
                        if cb is not None and cb.n_dict:
                            compaction_ratio = round(
                                cb.n_cold / max(cb.cold_touched, 1), 3
                            )
                    pending.append(
                        (ex.submit(step.put_batch, batch), batch.num_real())
                    )
                    if len(pending) > ring_depth:
                        fut, cnt = pending.pop(0)
                        state, _ = step.train(state, fut.result())
                        n += cnt
                for fut, cnt in pending:
                    state, _ = step.train(state, fut.result())
                    n += cnt
        finally:
            pool.close()
        jax.device_get(state["tables"]["w"]["param"][:1, 0])
        dt = time.perf_counter() - t0
        if pass_i == 1:
            delta = train_cache_size() - cache_before
            result["e2e_recompiles"] = (
                delta if cache_before >= 0 else None
            )
        eps = n / dt
        if eps > best:
            best = eps
            # actual bytes shipped per second this pass (every
            # dispatched batch ships the same bucketed wire, so
            # count batches, not real examples — a real-example
            # scaling would read low by the tail-batch pad
            # fraction)
            if wire_bytes_per_batch:
                best_link = sent * wire_bytes_per_batch / dt
    result["e2e_packed_examples_per_sec"] = round(best, 1)
    if compaction_ratio is not None:
        result["compaction_ratio"] = compaction_ratio
    if wire_bytes_per_batch:
        result["wire_bytes_per_batch"] = wire_bytes_per_batch
        result["wire_bytes_per_example"] = round(
            wire_bytes_per_batch / cfg.batch_size, 1
        )
        # implied link rate IF the link were the only cost.  Compare
        # against the measured 150-250 MB/s tunnel to check the
        # "bounded by the link, not the code" claim.
        result["e2e_implied_link_mb_per_sec"] = round(
            best_link / 2**20, 1
        )


def ensure_synth_data(path: str, num_examples: int, seed: int = 7) -> str:
    """Generate (once, cached) a zipf-feature libffm shard for the e2e
    bench; format matches the reference's bundled data
    (/root/reference/data/small_train-00000:1 ``label<TAB>fgid:fid:val``).

    The cache key (filename) embeds the generator version+params so a
    stale shard from older generator settings is never reused; the temp
    name is pid-unique so concurrent benches can't interleave writes.
    """
    import scripts.gen_synth as gen

    base, ext = os.path.splitext(path)
    key = f"g{gen.GEN_VERSION}-s{seed}-f{gen.FIELDS}-v{gen.VOCAB}"
    path = f"{base}-{key}{ext}"
    if not os.path.exists(path):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        gen.generate_shard(tmp, num_examples, seed=seed)
        os.replace(tmp, path)
    return path


def main() -> None:
    force_cpu = os.environ.get("XFLOW_BENCH_CPU") == "1"
    backend = None if force_cpu else probe_accelerator()

    import jax

    if backend is None:
        # Pin the platform via jax.config, not the env var: site hooks
        # may have imported jax (freezing JAX_PLATFORMS) before this
        # process's main() runs, and an accelerator plugin would then
        # initialize — and possibly hang — on any devices() call.
        jax.config.update("jax_platforms", "cpu")

    from xflow_tpu.config import Config

    result: dict = {
        "metric": "lr_ftrl_train_examples_per_sec",
        "value": 0.0,
        "unit": "examples/sec",
        "vs_baseline": 0.0,
        "backend": backend or "cpu",
    }

    # Flagship config (docs/PERF.md sweep, round 4): hot head H=2^12
    # captures 71% of real zipf occurrence mass; hot capacity 32 rides
    # the MXU, cold capacity 16 catches the rest on the DMA path — the
    # step is cold-slice-bound, so shrinking the cold section is the
    # whole game.  Truncation at this geometry is measured and reported
    # as hot_truncated_frac (~0.1%).
    cfg = Config(
        model="lr",
        optimizer="ftrl",
        table_size_log2=24,
        batch_size=131072,
        max_nnz=16,
        hot_size_log2=12,
        hot_nnz=32,
        num_devices=1,
        # cold_consolidate stays OFF: the dict wire ships the cold
        # head's consolidation plan for free (no device argsort), but
        # for LR's scalar (D=1) scatters even the free plan loses to
        # the direct scatter-add (measured +15% step time on CPU) —
        # consolidation pays for multi-lane tables (fm/mvm/ffm), see
        # docs/PERF.md "Wire format and compaction"
    )
    try:
        accel = [d for d in jax.devices() if d.platform != "cpu"]
    except RuntimeError as e:
        result["accel_error"] = f"{type(e).__name__}: {e}"
        result["backend"] = "cpu"
        accel = []
    try:
        cpu = jax.devices("cpu")
    except RuntimeError:
        cpu = []

    # Real zipf-distributed batches off the CSR cache (production
    # loader + measured remap) — synthetic uniform keys understate the
    # head mass the hot table exists for.  Any failure falls back to
    # the old synthetic batches so the bench always reports.
    n_examples = int(
        os.environ.get(
            "XFLOW_BENCH_E2E_EXAMPLES", "2000000" if accel else "200000"
        )
    )
    data_path = csr = remap = None
    try:
        if n_examples <= 0:
            raise ValueError("XFLOW_BENCH_E2E_EXAMPLES=0: real data off")
        data_path, csr, remap, hot_mass = prepare_real_data(cfg, n_examples)
        nb = max(1, min(4, n_examples // cfg.batch_size))
        batches, truncated_frac = real_batches(cfg, csr, remap, nb)
        result["batch_source"] = "zipf-cache"
        if hot_mass is not None:
            result["hot_mass"] = round(hot_mass, 4)
    except Exception as e:
        result["real_data_error"] = f"{type(e).__name__}: {e}"
        result["batch_source"] = "synthetic"
        # the CPU proxy must use the SAME batch source as the accel leg
        # (best-vs-best on one dataset), so drop the cache wholesale
        csr = remap = None
        batches, truncated_frac = make_batches(cfg, 4)
    result["hot_truncated_frac"] = round(truncated_frac, 6)

    accel_eps = None
    if accel:
        try:
            step, state = build(accel, cfg)
            _, accel_eps = run(step, state, batches, iters=20)
        except Exception as e:  # fall back to CPU-only reporting
            result["accel_error"] = f"{type(e).__name__}: {e}"
            result["backend"] = "cpu"
            accel_eps = None

    # CPU proxy baseline, smaller table/iters to keep runtime bounded.
    # The proxy runs ITS best config (no hot table — one-hot matmuls are
    # an MXU trick, slow on CPU; scatter-add DMA is the CPU-fast path)
    # on the same real data, so vs_baseline compares best-vs-best.
    cpu_eps = None
    if cpu:
        try:
            cpu_cfg = cfg.replace(
                table_size_log2=22, batch_size=16384, max_nnz=40,
                hot_size_log2=0,
            )
            cpu_step, cpu_state = build(cpu, cpu_cfg)
            if csr is not None:
                cpu_batches, _ = real_batches(cpu_cfg, csr, None, 4)
            else:
                cpu_batches, _ = make_batches(cpu_cfg, 4)
            _, cpu_eps = run(cpu_step, cpu_state, cpu_batches, iters=8, warmup=2)
        except Exception as e:
            result["cpu_error"] = f"{type(e).__name__}: {e}"

    if accel_eps is not None:
        result["value"] = round(accel_eps, 1)
        if cpu_eps:
            result["vs_baseline"] = round(accel_eps / cpu_eps, 3)
    elif cpu_eps is not None:
        result["value"] = round(cpu_eps, 1)
        result["vs_baseline"] = 1.0
    if cpu_eps is not None:
        result["cpu_examples_per_sec"] = round(cpu_eps, 1)

    # -- end-to-end pipeline metric (text -> trained table) ----------------
    try:
        e2e_devices = accel if accel_eps is not None else cpu
        if accel_eps is None:
            # degraded environment (no/broken accelerator): don't run
            # the 2M-example e2e on CPU — shrink to the old CPU default
            n_examples = int(
                os.environ.get("XFLOW_BENCH_E2E_EXAMPLES", "200000")
            )
            data_path = None
        if n_examples > 0 and e2e_devices:
            if data_path is None:
                data_path = ensure_synth_data(
                    os.path.join(
                        os.environ.get("XFLOW_BENCH_CACHE", "/tmp/xflow_bench"),
                        f"zipf-{n_examples}.ffm",
                    ),
                    n_examples,
                )
            e2e_cfg = cfg if accel_eps is not None else cfg.replace(
                table_size_log2=22, batch_size=16384
            )
            bench_e2e(
                e2e_devices, e2e_cfg, data_path, result, remap=remap
            )
    except Exception as e:
        result["e2e_error"] = f"{type(e).__name__}: {e}"

    _finalize_artifact(result, force_cpu, accel_eps)
    print(json.dumps(result))


def _finalize_artifact(result: dict, force_cpu: bool, accel_eps) -> None:
    """Outage-proof the artifact of record (round-4 lesson: the TPU
    tunnel died mid-round and BENCH_r04.json silently became a CPU
    self-comparison at vs_baseline 1.0).

    - An accelerator was EXPECTED (not XFLOW_BENCH_CPU=1) but the run
      landed on CPU: mark ``degraded: true`` and null out vs_baseline —
      a CPU-vs-CPU ratio is not the metric — and point at the newest
      committed last-good TPU artifact so downstream readers compare
      against a real number instead of concluding a regression.
    - A successful accelerator run: persist the full JSON under
      docs/artifacts/bench_tpu_*.json, so the last-good number is
      always a citable artifact rather than prose.
    """
    art_dir = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "docs", "artifacts"
    )
    if not force_cpu and accel_eps is None:
        result["degraded"] = True
        result["vs_baseline"] = None
        try:
            import glob as _glob

            good = sorted(
                _glob.glob(os.path.join(art_dir, "bench_tpu_*.json"))
            )
            if good:
                result["last_good_artifact"] = os.path.join(
                    "docs", "artifacts", os.path.basename(good[-1])
                )
            else:
                # no per-run artifact yet: fall back to the newest
                # committed round artifact that ran on an accelerator
                repo = os.path.dirname(os.path.abspath(__file__))
                for rnd in sorted(
                    _glob.glob(os.path.join(repo, "BENCH_r*.json")),
                    reverse=True,
                ):
                    # driver wrapper: the extracted bench object lives
                    # in "parsed"; fall back to scanning "tail" for
                    # pre-"parsed" wrappers (guard json.loads per line —
                    # a truncated second brace-line must not discard an
                    # already-found valid metric object)
                    try:
                        with open(rnd) as f:
                            wrapper = json.load(f)
                    except (OSError, ValueError):
                        continue
                    prev = wrapper.get("parsed")
                    if not isinstance(prev, dict):
                        prev = None
                        for line in str(wrapper.get("tail", "")).splitlines():
                            line = line.strip()
                            if line.startswith("{") and "metric" in line:
                                try:
                                    prev = json.loads(line)
                                except ValueError:
                                    continue
                    if prev and prev.get("backend") not in (
                        None, "cpu", "unknown",
                    ):
                        result["last_good_artifact"] = os.path.basename(
                            rnd
                        )
                        break
        except OSError:
            pass
    elif accel_eps is not None:
        try:
            os.makedirs(art_dir, exist_ok=True)
            name = "bench_tpu_{}.json".format(
                time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
            )
            with open(os.path.join(art_dir, name), "w") as f:
                json.dump(result, f, indent=2, sort_keys=True)
                f.write("\n")
            result["artifact"] = os.path.join("docs", "artifacts", name)
        except OSError as e:
            result["artifact_error"] = f"{type(e).__name__}: {e}"


if __name__ == "__main__":
    try:
        main()
    except BaseException as e:  # never exit nonzero without the JSON line
        print(
            json.dumps(
                {
                    "metric": "lr_ftrl_train_examples_per_sec",
                    "value": 0.0,
                    "unit": "examples/sec",
                    "vs_baseline": None,
                    "backend": "unknown",
                    "degraded": True,
                    "error": f"{type(e).__name__}: {e}",
                }
            )
        )
        sys.exit(0)
