"""Host-resident cold row store — the 2^28 tail that never fits HBM.

Storage model: one shared key→slot index over packed per-table arrays
(``[rows_stored, D]`` numpy, amortized-doubling growth).  A key is
present for ALL of a model's tables or none — a logical table row moves
between tiers as a unit, optimizer slots included.  Rows that were
never written materialize on fetch from the per-row deterministic init
(``row_init_values``), which is the whole reason a 2^28-row table costs
O(touched rows) host memory instead of 10+ GiB per table: the zipf tail
is mostly untouched, and an untouched row's value is a pure function of
(seed, table, array, row index) — computable per-row, independent of T,
bit-stable across save/restore (the checkpoint round-trip's
"bitwise-equal logical table" guarantee rides on this).

This is deliberately the reference's own storage semantics: its server
tables are unordered_maps materializing entries on first touch with
zeros (w/n/z) or N(0,1)*scale (v) — ftrl.h:84,113-120 — not dense
arrays.  The dense [T, D] device table was the TPU adaptation; the cold
store walks it back for the tail while store/hot.py keeps the head
dense where the MXU wants it.
"""

from __future__ import annotations

import dataclasses

import numpy as np

_GOLD = np.uint64(0x9E3779B97F4A7C15)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer over uint64 arrays (wrapping
    arithmetic is the point — numpy array uint64 ops wrap silently)."""
    x = x + _GOLD
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


def _tag64(s: str) -> np.uint64:
    """FNV-1a of a table/array tag, so 'w.param' and 'v.param' draw
    independent streams for the same row index.  Python-int arithmetic
    masked to 64 bits — numpy uint64 SCALARS warn on overflow (arrays
    wrap silently, which _splitmix64 relies on)."""
    h = 0xCBF29CE484222325
    for b in s.encode():
        h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return np.uint64(h)


def row_init_values(
    seed: int,
    table: str,
    arr: str,
    rows: np.ndarray,
    dim: int,
    init_kind: str = "zeros",
    init_scale: float = 0.0,
) -> np.ndarray:
    """Initial value of logical rows ``rows`` of ``table``'s ``arr``
    plane: float32 [len(rows), dim], deterministic in (seed, table,
    arr, row, col) and independent of the table size — the lazy
    counterpart of TableSpec.init (models/base.py).  "normal" is
    Box-Muller over two splitmix64 streams; optimizer aux planes are
    always zeros (FTRL n/z start at 0, ftrl.h:113-120)."""
    m = len(rows)
    if init_kind != "normal" or init_scale == 0.0:
        return np.zeros((m, dim), np.float32)
    seed_mix = np.uint64(
        (int(seed) * 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    )
    base = _splitmix64(
        rows.astype(np.uint64) ^ _tag64(f"{table}.{arr}") ^ seed_mix
    )
    e = _splitmix64(
        base[:, None] + np.arange(1, dim + 1, dtype=np.uint64)[None, :]
    )
    # u1 in (0, 1] (the +1 keeps log finite), u2 in [0, 1)
    u1 = ((e >> np.uint64(11)).astype(np.float64) + 1.0) * 2.0**-53
    u2 = (_splitmix64(e) >> np.uint64(11)).astype(np.float64) * 2.0**-53
    z = np.sqrt(-2.0 * np.log(u1)) * np.cos(2.0 * np.pi * u2)
    return (z * init_scale).astype(np.float32)


@dataclasses.dataclass(frozen=True)
class ColdTableSpec:
    """Per-table layout the store needs: row width plus the init
    distribution of every array plane ({arr_name: (kind, scale)})."""

    dim: int
    arrays: dict  # {arr_name: (init_kind, init_scale)}


class ColdStore:
    """Packed host rows + key index.  Main-thread only by design: the
    trainer's plan/write-back/promotion-apply path is strictly
    sequential (store/tiered.py), and the async promotion worker talks
    queues, never this object."""

    _INITIAL_CAP = 1024

    def __init__(self, tables: dict[str, ColdTableSpec], seed: int = 0):
        self.tables = tables
        self.seed = seed
        self._index: dict[int, int] = {}
        self._cap = self._INITIAL_CAP
        self._n = 0
        self._keys = np.full(self._cap, -1, np.int64)
        self._data: dict[str, dict[str, np.ndarray]] = {
            t: {
                a: np.zeros((self._cap, spec.dim), np.float32)
                for a in spec.arrays
            }
            for t, spec in tables.items()
        }

    # -- capacity ----------------------------------------------------------

    def __len__(self) -> int:
        return self._n

    def nbytes(self) -> int:
        """Host bytes the packed value arrays occupy (capacity, not
        just live rows) — the number behind docs/STORE.md's budget
        math."""
        return sum(
            arr.nbytes for arrs in self._data.values()
            for arr in arrs.values()
        )

    def _grow(self, need: int) -> None:
        if self._n + need <= self._cap:
            return
        new_cap = self._cap
        while new_cap < self._n + need:
            new_cap *= 2
        keys = np.full(new_cap, -1, np.int64)
        keys[: self._n] = self._keys[: self._n]
        self._keys = keys
        for t, arrs in self._data.items():
            for a, arr in arrs.items():
                grown = np.zeros((new_cap, arr.shape[1]), np.float32)
                grown[: self._n] = arr[: self._n]
                arrs[a] = grown
        self._cap = new_cap

    def _slots_of(self, keys: np.ndarray) -> np.ndarray:
        # per-key dict resolution, but through tolist()+map (native
        # ints, C-level loop) — ~3x over a python generator.  Unlike
        # the hot map (store/hot.py::lookup, sorted-snapshot), this
        # index mutates on EVERY write-back, so a rebuild-per-step
        # snapshot would cost O(rows log rows) each step at scale;
        # lookups here cover only miss/write keys (small after
        # warmup).  A log-structured sorted index (append tail +
        # amortized merge) is the follow-up if cold-start profiles
        # ever dominate (docs/STORE.md).
        idx = self._index
        return np.asarray(
            [s if (s := idx.get(k)) is not None else -1
             for k in keys.tolist()],
            dtype=np.int64,
        ) if len(keys) else np.empty(0, np.int64)

    # -- fetch / write / take ----------------------------------------------

    def lazy_rows(self, table: str, arr: str, keys: np.ndarray) -> np.ndarray:
        spec = self.tables[table]
        kind, scale = spec.arrays[arr]
        return row_init_values(
            self.seed, table, arr, keys, spec.dim, kind, scale
        )

    def fetch(
        self, keys: np.ndarray, planes: tuple[str, ...] | None = None
    ) -> dict[str, dict[str, np.ndarray]]:
        """Rows for ``keys`` across every table: stored values where
        present, lazy init ONLY for the absent subset (the Box-Muller
        draw is real host work on the serialized per-step path — don't
        compute it for rows about to be overwritten).  ``planes``
        restricts which array planes are materialized (predict fetches
        pass ("param",) — optimizer slots never score).  Read-only —
        predict-path fetches never grow the store."""
        slots = self._slots_of(keys)
        present = slots >= 0
        absent = ~present
        any_present = bool(present.any())
        any_absent = bool(absent.any())
        out: dict[str, dict[str, np.ndarray]] = {}
        for t, arrs in self._data.items():
            out[t] = {}
            for a, arr in arrs.items():
                if planes is not None and a not in planes:
                    continue
                rows = np.zeros((len(keys), arr.shape[1]), np.float32)
                if any_absent:
                    rows[absent] = self.lazy_rows(t, a, keys[absent])
                if any_present:
                    rows[present] = arr[slots[present]]
                out[t][a] = rows
        return out

    def write(
        self, keys: np.ndarray, rows: dict[str, dict[str, np.ndarray]]
    ) -> None:
        """Upsert rows for ``keys`` (every table/array plane together —
        the write-back of one step's miss block)."""
        slots = self._slots_of(keys)
        absent = slots < 0
        n_new = int(absent.sum())
        if n_new:
            self._grow(n_new)
            new_slots = np.arange(self._n, self._n + n_new, dtype=np.int64)
            slots[absent] = new_slots
            self._keys[new_slots] = keys[absent]
            # bulk insert (C-level dict.update over native ints)
            self._index.update(
                zip(keys[absent].tolist(), new_slots.tolist())
            )
            self._n += n_new
        for t, arrs in rows.items():
            data = self._data[t]
            for a, block in arrs.items():
                data[a][slots] = block
        return None

    def delete(self, keys: np.ndarray) -> None:
        """Remove ``keys`` (promotion: the row now lives in the hot
        tier).  Swap-with-last keeps the arrays packed."""
        for k in keys:
            k = int(k)
            slot = self._index.pop(k, None)
            if slot is None:
                continue
            last = self._n - 1
            if slot != last:
                moved = int(self._keys[last])
                self._keys[slot] = moved
                self._index[moved] = slot
                for arrs in self._data.values():
                    for arr in arrs.values():
                        arr[slot] = arr[last]
            self._keys[last] = -1
            self._n = last

    def take(self, keys: np.ndarray) -> dict[str, dict[str, np.ndarray]]:
        """fetch + delete: the promotion path (rows move to the hot
        tier).  Keys never written back (e.g. only ever touched by a
        read-only predict plan) still yield their lazy-init rows."""
        rows = self.fetch(keys)
        self.delete(keys)
        return rows

    # -- bulk (checkpoint fold / restore) ----------------------------------

    def keys_view(self) -> np.ndarray:
        """View of the live keys, packed order (checkpoint fold)."""
        return self._keys[: self._n]

    def export_array(self, table: str, arr: str) -> tuple[np.ndarray, np.ndarray]:
        """(keys, rows) VIEWS of one plane's live rows — the fold paths
        (store/tiered.py) gather through these per chunk instead of
        copying the whole touched set."""
        return (
            self._keys[: self._n],
            self._data[table][arr][: self._n],
        )

    def load_rows(
        self, keys: np.ndarray, data: dict[str, dict[str, np.ndarray]]
    ) -> None:
        """Replace the whole store with ``keys``/``data`` (restore)."""
        n = len(keys)
        self._cap = max(self._INITIAL_CAP, n)
        self._n = n
        self._keys = np.full(self._cap, -1, np.int64)
        self._keys[:n] = keys
        self._index = {int(k): i for i, k in enumerate(keys)}
        self._data = {}
        for t, spec in self.tables.items():
            self._data[t] = {}
            for a in spec.arrays:
                arr = np.zeros((self._cap, spec.dim), np.float32)
                if n:
                    arr[:n] = data[t][a]
                self._data[t][a] = arr
