"""HBM-resident hot tier: bounded device rows + the hot+miss step.

The device never sees a table key under store_mode='tiered'.  The host
resolves every batch key through the key→slot map (PR 5's dedup kernel
supplies the per-batch unique set); occurrences ship as ``refs`` into a
combined row space::

    [0, Hc)        the hot tier        (param + optimizer slots,
                                        row-sharded over the mesh)
    [Hc, Hc+Mc)    this batch's misses (cold rows fetched by the host,
                                        shipped with the batch)
    Hc+Mc          the drop sentinel   (padding)

and the jitted step concatenates the two blocks, gathers, computes the
model's gradients (the ONE forward/backward, parallel/step.py::
grads_from_rows), and applies the optimizer over the combined tier —
dense elementwise (g=0 rows idempotent, the dense-mode argument) or
touched-rows-only (ops/sparse.py) per Config.update_mode.  The updated
miss block returns to the host for write-back (store/tiered.py).

Every transient here is [B, K, D] or [Hc+Mc, D] shaped — hot capacity
and batch geometry, never T.  That is the property memory-budget.json
pins at the north-star T=2^28 (analysis rules XF010/XF014, shapeflow
symbols Hc/M), and what makes FM/MVM/FFM trainable at full feature
scale where only LR's D=1 table used to fit.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from xflow_tpu.config import Config
from xflow_tpu.models.base import Model
from xflow_tpu.ops.sparse import (
    consolidate_apply,
    consolidate_plan,
    gather_rows,
    scatter_rows,
)
from xflow_tpu.optim.base import Optimizer
from xflow_tpu.parallel.mesh import batch_sharding, replicated, table_sharding
from xflow_tpu.parallel.step import apply_dense_sgd, grads_from_rows
from xflow_tpu.utils.metrics import logloss, sigmoid_ref

# Fixed promotion/demotion transfer width: fill/read always move this
# many row slots (sentinel-padded), so the tier-maintenance jits
# compile exactly once (XF001 discipline; shapeflow symbol P).
PROMOTE_CAP = 1024


class HotTier:
    """Bounded device rows + key→slot map + the tiered jits."""

    def __init__(self, model: Model, optimizer: Optimizer, cfg: Config, mesh):
        self.model = model
        self.optimizer = optimizer
        self.cfg = cfg
        self.mesh = mesh
        self.capacity = cfg.hot_capacity
        ndev = mesh.devices.size
        if self.capacity % ndev:
            raise ValueError(
                f"hot capacity {self.capacity} not divisible by the "
                f"mesh's {ndev} devices — pick hot_capacity_log2 >= "
                "log2(devices)"
            )
        if cfg.update_mode not in ("dense", "sparse"):
            raise ValueError(
                "tiered store supports update_mode 'dense' or 'sparse' "
                f"(got {cfg.update_mode!r})"
            )
        self._update = cfg.update_mode
        # optimizer aux plane names (FTRL: n/z; SGD: none), discovered
        # once from a 1-row probe
        self._aux_names = tuple(
            sorted(optimizer.init_aux(jnp.zeros((1, 1), jnp.float32)))
        )
        # key→slot remap: key_of[-1 = free] is the inverse, _free a
        # stack of unassigned slots.  Main-thread only (the promotion
        # worker proposes over queues; application is between steps).
        self.key_of = np.full(self.capacity, -1, np.int64)
        self.slot_of: dict[int, int] = {}
        self._free: list[int] = list(range(self.capacity - 1, -1, -1))
        # vectorized lookup snapshot (key-sorted occupied slots),
        # rebuilt lazily: the map only mutates in maintain() (between
        # steps), while lookup() runs per batch over every unique key
        # — a per-key Python dict walk there would put O(uniques) of
        # interpreter time on the serial critical path
        self._lookup_keys = np.empty(0, np.int64)
        self._lookup_slots = np.empty(0, np.int64)
        self._lookup_dirty = False
        self.train = jax.jit(self._train_impl, donate_argnums=0)
        self.predict = jax.jit(self._predict_impl)
        self.fill = jax.jit(self._fill_impl, donate_argnums=0)
        self.read = jax.jit(self._read_impl)

    # -- device state -------------------------------------------------------

    def init_device_state(self) -> dict:
        """Fresh [Hc, D] tier per table array (rows are garbage until a
        slot is assigned and filled — the maps gate every read), plus
        replicated dense params seeded exactly like the dense-mode
        init_state (parallel/step.py) so model quality is
        layout-independent."""
        sharding = table_sharding(self.mesh)
        tables: dict[str, dict[str, jax.Array]] = {}
        for spec in self.model.tables():
            zero = np.zeros((self.capacity, spec.dim), np.float32)
            entry = {"param": jax.device_put(zero, sharding)}
            for aux in self._aux_names:
                entry[aux] = jax.device_put(zero.copy(), sharding)
            tables[spec.name] = entry
        dense = {}
        if hasattr(self.model, "dense_init"):
            rng = jax.random.PRNGKey(self.cfg.seed)
            dense = jax.tree.map(
                lambda a: jax.device_put(a, replicated(self.mesh)),
                self.model.dense_init(jax.random.fold_in(rng, 1000)),
            )
        return {
            "tables": tables,
            "dense": dense,
            "step": jnp.zeros((), jnp.int32),
        }

    def batch_shardings(self):
        return batch_sharding(self.mesh), replicated(self.mesh)

    # -- key→slot map -------------------------------------------------------

    def lookup(self, keys: np.ndarray) -> np.ndarray:
        """Slot per key (-1 = miss), vectorized: binary search over the
        key-sorted occupancy snapshot."""
        if self._lookup_dirty:
            occ = np.flatnonzero(self.key_of >= 0)
            hkeys = self.key_of[occ]
            order = np.argsort(hkeys)
            self._lookup_keys = hkeys[order]
            self._lookup_slots = occ[order]
            self._lookup_dirty = False
        if not len(self._lookup_keys) or not len(keys):
            return np.full(len(keys), -1, np.int64)
        pos = np.searchsorted(self._lookup_keys, keys)
        pos = np.minimum(pos, len(self._lookup_keys) - 1)
        hit = self._lookup_keys[pos] == keys
        return np.where(hit, self._lookup_slots[pos], -1)

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def occupancy(self) -> int:
        return self.capacity - len(self._free)

    def assign(self, keys) -> np.ndarray:
        """Pop a free slot per key (caller guarantees capacity and that
        no key is already hot)."""
        slots = np.empty(len(keys), np.int64)
        for i, k in enumerate(keys):
            k = int(k)
            s = self._free.pop()
            self.slot_of[k] = s
            self.key_of[s] = k
            slots[i] = s
        self._lookup_dirty = True
        return slots

    def release(self, keys) -> np.ndarray:
        """Free the slots of ``keys`` (demotion — rows must already be
        flushed to the cold store)."""
        slots = np.empty(len(keys), np.int64)
        for i, k in enumerate(keys):
            k = int(k)
            s = self.slot_of.pop(k)
            self.key_of[s] = -1
            self._free.append(s)
            slots[i] = s
        self._lookup_dirty = True
        return slots

    def reset_maps(self) -> None:
        """Empty the tier (restore: every row re-enters through the
        cold store and promotes again)."""
        self.key_of.fill(-1)
        self.slot_of.clear()
        self._free = list(range(self.capacity - 1, -1, -1))
        self._lookup_keys = np.empty(0, np.int64)
        self._lookup_slots = np.empty(0, np.int64)
        self._lookup_dirty = False

    # -- compiled bodies ----------------------------------------------------

    def _combined(self, tables: dict, miss: dict) -> dict:
        """Per table: {arr: [Hc+Mc, D]} — hot tier with this batch's
        miss block appended (the whole addressable row space of one
        step)."""
        return {
            name: {
                arr: jnp.concatenate([t[arr], miss[name][arr]])
                for arr in t
            }
            for name, t in tables.items()
        }

    def _train_impl(self, tstate: dict, tbatch: dict):
        """One tiered train step: gather over [refs], the shared
        forward/backward, optimizer over the combined hot+miss tier,
        split back.  Returns (new_state, miss_out, metrics)."""
        tables = tstate["tables"]
        dense = tstate["dense"]
        combined = self._combined(tables, tbatch["miss"])
        batch = {
            "keys": tbatch["refs"],
            "slots": tbatch["slots"],
            "vals": tbatch["vals"],
            "mask": tbatch["mask"],
            "labels": tbatch["labels"],
            "weights": tbatch["weights"],
        }
        num_real = jnp.maximum(jnp.sum(batch["weights"]), 1.0)
        rows = {
            name: c["param"][batch["keys"]] for name, c in combined.items()
        }
        pctr, occ_grads, grad_dense = grads_from_rows(
            self.model, rows, dense, batch, num_real
        )
        # drop sentinel = one past the combined rows (same convention
        # as ops/sparse.py / step.py's _cold_keys_eff, in ref space)
        c = next(iter(combined.values()))["param"].shape[0]
        refs_eff = jnp.where(
            batch["mask"] > 0, batch["keys"], jnp.int32(c)
        ).reshape(-1)
        plan = (
            consolidate_plan(refs_eff, c)
            if self._update == "sparse"
            else None
        )
        new_combined = {}
        for name, ctab in combined.items():
            d = ctab["param"].shape[-1]
            occ = occ_grads[name].reshape(-1, d)
            if plan is not None:
                # touched-rows-only: consolidate per unique ref, then
                # gather/update/scatter (the sparse update mode's form,
                # ops/sparse.py — O(batch nnz) work)
                order, seg, ukeys = plan
                gsum = consolidate_apply(occ, order, seg)
                state_rows = {
                    k: gather_rows(a, ukeys) for k, a in ctab.items()
                }
                new_rows = self.optimizer.update_rows(state_rows, gsum)
                new_combined[name] = {
                    k: scatter_rows(ctab[k], ukeys, new_rows[k])
                    for k in ctab
                }
            else:
                # dense over the combined tier: scatter-add + ONE
                # elementwise pass over [Hc+Mc, D] — hot-capacity
                # scale, the dense mode's semantics without its [T, D]
                # buffer (g=0 rows idempotent, optim docstrings)
                gbuf = jnp.zeros_like(ctab["param"])
                gbuf = gbuf.at[refs_eff].add(occ, mode="drop")
                new_combined[name] = self.optimizer.update_rows(
                    ctab, gbuf
                )
        new_tables = {
            name: {k: a[: self.capacity] for k, a in ct.items()}
            for name, ct in new_combined.items()
        }
        miss_out = {
            name: {k: a[self.capacity :] for k, a in ct.items()}
            for name, ct in new_combined.items()
        }
        new_dense = apply_dense_sgd(dense, grad_dense, self.cfg.sgd_lr)
        metrics = {
            "logloss": logloss(
                batch["labels"], pctr, batch["weights"]
            ),
            "count": jnp.sum(batch["weights"]),
        }
        new_state = {
            "tables": new_tables,
            "dense": new_dense,
            "step": tstate["step"] + 1,
        }
        return new_state, miss_out, metrics

    def _predict_impl(self, tstate: dict, tbatch: dict) -> jax.Array:
        """pctr over the combined tier (misses fetched read-only by the
        planner — no write-back; the predict wire ships ONLY the param
        plane per miss block, since optimizer slots never score)."""
        batch = {
            "keys": tbatch["refs"],
            "slots": tbatch["slots"],
            "vals": tbatch["vals"],
            "mask": tbatch["mask"],
            "labels": tbatch["labels"],
            "weights": tbatch["weights"],
        }
        miss = tbatch["miss"]
        rows = {
            name: jnp.concatenate([t["param"], miss[name]["param"]])[
                batch["keys"]
            ]
            for name, t in tstate["tables"].items()
        }
        if getattr(self.model, "autodiff", False):
            logit = self.model.logit(rows, batch, tstate["dense"])
        else:
            logit = self.model.logit(rows, batch)
        return sigmoid_ref(logit)

    def _fill_impl(self, tstate: dict, slots: jax.Array, fill_rows: dict):
        """Write PROMOTE_CAP rows into the tier at ``slots`` (sentinel
        = capacity → dropped): promotion and restore warm-fill."""
        new_tables = {
            name: {
                arr: scatter_rows(t[arr], slots, fill_rows[name][arr])
                for arr in t
            }
            for name, t in tstate["tables"].items()
        }
        return {
            "tables": new_tables,
            "dense": tstate["dense"],
            "step": tstate["step"],
        }

    def _read_impl(self, tstate: dict, slots: jax.Array) -> dict:
        """Gather PROMOTE_CAP rows at ``slots``: demotion transfers.
        Pad slots (sentinel = capacity) CLAMP to the last hot row
        (gather mode='clip', ops/sparse.py) — callers MUST trim to the
        real count before consuming."""
        return {
            name: {arr: gather_rows(t[arr], slots) for arr in t}
            for name, t in tstate["tables"].items()
        }
