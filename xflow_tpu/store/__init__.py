"""Hierarchical hot/cold parameter store (ISSUE 9; docs/STORE.md).

ROADMAP item 2's blocker was residency: a dense [T, D] table is ~10 GiB
per FM table at the north-star hashed 2^28 geometry — it does not fit
one device, and PR 7's XF010/XF014 gates exist precisely to keep jitted
code from ever materializing at that scale.  This package is the other
half of the answer, mirroring the hierarchical parameter-server design
for massive ads models (arXiv:2003.05622) with the cross-replica
sharded update discipline of arXiv:2004.13336:

* ``cold.py`` — a host-resident row store: touched rows packed dense,
  addressed by hashed key, untouched rows materialized lazily from the
  per-row init (TableSpec.init_kind — the reference's own lazy
  server-side init, ftrl.h:113-120).  Serialized in the
  utils/checkpoint.py row-range shard format.
* ``hot.py`` — the HBM-resident hot tier: ``2^hot_capacity_log2`` rows
  per table (param + optimizer slots), row-sharded over the mesh
  (parallel/mesh.py), plus the host-side key→slot remap and the jitted
  hot+miss step whose every transient scales with hot capacity, never
  T (memory-budget.json entries prove it at T=2^28).
* ``promote.py`` — the async promotion/demotion worker: scores per-
  batch touch counts off the critical path, proposes plans over
  queues; the trainer applies them between steps so in-flight batches
  never see a moving key→slot map.
* ``tiered.py`` — the orchestrator threading the three through
  TrainStep.put_batch (miss cold-fetch), dispatch (miss write-back),
  checkpoint/export (both tiers folded into one logical table), and
  the ``store`` obs row.
"""

from xflow_tpu.store.cold import ColdStore, row_init_values
from xflow_tpu.store.hot import HotTier
from xflow_tpu.store.promote import PromotionWorker
from xflow_tpu.store.tiered import BatchPlan, TieredStore

__all__ = [
    "BatchPlan",
    "ColdStore",
    "HotTier",
    "PromotionWorker",
    "TieredStore",
    "row_init_values",
]
