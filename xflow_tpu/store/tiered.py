"""TieredStore — the orchestrator threading cold/hot/promote through
the train step, checkpoints, and serving export.

Dataflow per train step (strictly sequential on the main thread — the
trainer pins the transfer-ahead ring off under store_mode='tiered' so
the cold store has read-your-writes semantics; the seq discipline is
documented in docs/STORE.md, and the async-PS relaxation that would
re-enable the ring is future work):

    put_batch        complete previous write-back → dedup keys (PR 5
                     kernel) → hot-map lookup → cold-fetch misses →
                     ship refs + miss blocks, arm the plan
    dispatch_train   take the plan → hot+miss jit → defer (plan,
                     miss_out) as the pending write-back
    maintain         complete write-back → apply the promotion
                     worker's plan (demote: device read → cold write;
                     promote: cold take → device fill) between steps

Checkpoints FOLD both tiers into one tier-erased logical table: sorted
touched keys + packed rows in the utils/checkpoint.py row-range shard
format (``store.<table>.<arr>.r<start>-<stop>.npy``).  Restore loads
everything cold and lets promotion re-warm — the logical table
(touched rows exact, untouched rows re-derived from the deterministic
per-row init) is bitwise identical regardless of how rows were split
across tiers at save time.  Artifact export materializes the full
logical [T, D] param table in bounded chunks, so PredictEngine loads a
tiered model with zero serving changes.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import time
from collections import deque

import numpy as np

import jax
import jax.numpy as jnp

from xflow_tpu.chaos import ChaosError, emit_health, failpoint, retry_call
from xflow_tpu.config import Config
from xflow_tpu.io.compact import dedup_select, plane_cap
from xflow_tpu.obs import NULL_OBS
from xflow_tpu.store.cold import ColdStore, ColdTableSpec
from xflow_tpu.store.hot import PROMOTE_CAP, HotTier
from xflow_tpu.store.promote import PromotionWorker
from xflow_tpu.utils.checkpoint import (
    MANIFEST,
    IncompatibleCheckpoint,
    RangeReader,
    _write_latest,
    gc_checkpoints,
)

# rows per checkpoint/export range file — bounds peak memory of the
# fold at 2^28 (a chunk is CHUNK_ROWS * D * 4 B, ~40 MiB at FM's D=10)
CHUNK_ROWS = 1 << 20


@dataclasses.dataclass
class BatchPlan:
    """Host-side half of one staged batch: which unique keys missed the
    hot tier, the cold rows that were shipped for them, and the touch
    note for the promotion worker (posted only when the plan is TAKEN
    by a train dispatch — predict/eval traffic must not steer tier
    placement, or a between-epochs eval over a differently-distributed
    test set would churn the training run's hot tier)."""

    miss_keys: np.ndarray  # int64 [n_miss]
    miss_rows: dict  # {table: {arr: np.float32 [mc, D]}} (padded)
    miss_nbytes: int
    touch: tuple  # (uniq, counts, miss) for PromotionWorker.note
    param_only: bool  # predict plan: param plane shipped alone


class TieredStore:
    def __init__(self, model, optimizer, cfg: Config, mesh):
        self.model = model
        self.optimizer = optimizer
        self.cfg = cfg
        self.mesh = mesh
        self.hot = HotTier(model, optimizer, cfg, mesh)
        self.cold = ColdStore(
            {
                spec.name: ColdTableSpec(
                    dim=spec.dim,
                    arrays={
                        "param": (spec.init_kind, spec.init_scale),
                        **{a: ("zeros", 0.0) for a in self.hot._aux_names},
                    },
                )
                for spec in model.tables()
            },
            seed=cfg.seed,
        )
        # default health/counter sink for paths with no per-call obs
        # (checkpoint/export/close flushes) — Trainer rebinds it to the
        # live bundle so heals there are as loud as maintain()'s
        self.obs = NULL_OBS
        self.promoter: PromotionWorker | None = None
        # staged plans keyed by the IDENTITY of the device-array dict
        # they were built with (put_batch returns it; dispatch passes
        # it back), so a dispatch can never pair one batch's arrays
        # with another batch's plan — the ring keeps a strong ref to
        # the arrays object, which both prevents id() reuse and bounds
        # how many staged-but-never-dispatched (predict-path) batches
        # stay alive
        self._staged: deque = deque(maxlen=2)
        self._pending: tuple[BatchPlan, dict] | None = None
        # promotion-worker self-healing (docs/ROBUSTNESS.md): a dead
        # worker is restarted exactly ONCE; a second death leaves the
        # store running with placement frozen (new keys stay all-miss
        # — correct, just cold) rather than thrashing restarts
        self._promoter_restarts = 0
        self._promoter_dead = False

    # -- per-batch planning -------------------------------------------------

    def _ensure_promoter(self, obs) -> None:
        if self.promoter is None:
            self.promoter = PromotionWorker(self.hot.capacity, obs=obs)

    def plan_batch(self, batch, obs=NULL_OBS, param_only: bool = False):
        """Resolve one Batch through the tier map: returns (wire, plan)
        where wire holds the numpy planes to ship (refs replace keys;
        the model-facing planes pass through) and plan the host half.
        Read-only with respect to the store — the write-back happens at
        complete_pending() with the step's miss output.  ``param_only``
        (predict/eval): fetch and ship only the param plane per miss —
        optimizer slots never score, and this path is serial."""
        self._ensure_promoter(obs)
        if batch.hot_nnz:
            raise ValueError(
                "tiered store batches must not carry MXU hot planes "
                "(config validation enforces hot_size_log2=0)"
            )
        b, k = batch.keys.shape
        mask = batch.mask.reshape(-1) > 0
        flat = batch.keys.reshape(-1).astype(np.int64)
        live = flat[mask]
        if len(live):
            # PR 5's dedup kernel with an uncapped dictionary: every
            # unique key gets a code, codes index the unique list
            uniq, codes = dedup_select(live, dict_cap=len(live))
            codes = codes.astype(np.int64)
        else:
            uniq = np.zeros(0, np.int64)
            codes = np.zeros(0, np.int64)
        slots = self.hot.lookup(uniq)
        miss = slots < 0
        miss_keys = uniq[miss]
        n_miss = len(miss_keys)
        # granule-bucketed miss capacity (io/compact.py::plane_cap):
        # steady-state batches share one compiled program per bucket
        mc = plane_cap(n_miss, b * k)
        miss_pos = np.cumsum(miss) - 1
        ref_of_u = np.where(miss, self.hot.capacity + miss_pos, slots)
        refs = np.zeros(b * k, np.int64)
        if len(live):
            refs[mask] = ref_of_u[codes]
        refs2d = refs.reshape(b, k).astype(np.int32)
        t0 = time.perf_counter()

        def fetch():
            # chaos site: transient cold-store read — bounded retry
            # heals it with zero data loss (the fetch is idempotent)
            failpoint("store.cold_fetch")
            return self.cold.fetch(
                miss_keys, planes=("param",) if param_only else None
            )

        fetched = retry_call(
            fetch,
            attempts=self.cfg.io_retries,
            backoff_s=self.cfg.io_retry_backoff_s,
            channel="store",
            site="cold_fetch",
            obs=obs,
        )
        obs.counter(
            "store.cold_fetch_seconds", time.perf_counter() - t0
        )
        miss_rows: dict = {}
        miss_nbytes = 0
        for tname, arrs in fetched.items():
            miss_rows[tname] = {}
            for aname, rows in arrs.items():
                block = np.zeros((mc, rows.shape[1]), np.float32)
                block[:n_miss] = rows
                miss_rows[tname][aname] = block
                miss_nbytes += block.nbytes
        counts = np.bincount(codes, minlength=len(uniq)).astype(np.int64)
        hit_occ = int(counts[~miss].sum())
        miss_occ = int(counts[miss].sum())
        obs.counter("store.hit_occ", hit_occ)
        obs.counter("store.miss_occ", miss_occ)
        obs.counter("store.miss_rows", n_miss)
        wire = {
            "refs": refs2d,
            "slots": batch.slots,
            "vals": batch.vals,
            "mask": batch.mask,
            "labels": batch.labels,
            "weights": batch.weights,
        }
        return wire, BatchPlan(
            miss_keys=miss_keys,
            miss_rows=miss_rows,
            miss_nbytes=miss_nbytes,
            touch=(uniq, counts, miss),
            param_only=param_only,
        )

    # -- staging / write-back ----------------------------------------------

    def stage(self, arrays: dict, plan: BatchPlan) -> None:
        """Arm ``plan`` for the dispatch of exactly ``arrays`` (predict
        paths stage and never take — their entries age out of the
        identity ring)."""
        self._staged.append((arrays, plan))

    def take_staged(self, arrays: dict) -> BatchPlan:
        for i, (staged_arrays, plan) in enumerate(self._staged):
            if staged_arrays is arrays:
                del self._staged[i]
                if plan.param_only:
                    raise RuntimeError(
                        "dispatch_train on a predict-staged batch — "
                        "its miss blocks carry no optimizer slots; "
                        "stage train batches with put_batch(batch) "
                        "(predict=False)"
                    )
                if self.promoter is not None:
                    # taking a plan means this batch TRAINS: only now
                    # does its touch profile steer promotion
                    # (BatchPlan.touch rationale)
                    self.promoter.note(*plan.touch)
                return plan
        raise RuntimeError(
            "dispatch_train received arrays put_batch did not stage "
            "(or staged too long ago) — under store_mode='tiered' "
            "every dispatch must consume a put_batch result from the "
            "same step"
        )

    def defer_complete(self, plan: BatchPlan, miss_out: dict) -> None:
        self.complete_pending()  # invariant: at most one pending
        self._pending = (plan, miss_out)

    def complete_pending(self, obs=None) -> None:
        """Flush the deferred write-back: fetch the step's updated miss
        rows and upsert them into the cold store.  Called before every
        plan (read-your-writes), before maintenance, checkpoint save,
        export, and close.  The upsert is idempotent, so a transient
        failure (``store.writeback`` failpoint) retries safely —
        loudly on every call path (no-obs callers fall back to the
        store's own bundle)."""
        if obs is None:
            obs = self.obs
        if self._pending is None:
            return
        plan, miss_out = self._pending
        self._pending = None
        n = len(plan.miss_keys)
        if not n:
            return
        host = jax.device_get(miss_out)

        def write():
            failpoint("store.writeback")
            self.cold.write(plan.miss_keys, {
                tname: {
                    aname: np.asarray(block)[:n]
                    for aname, block in arrs.items()
                }
                for tname, arrs in host.items()
            })

        retry_call(
            write,
            attempts=self.cfg.io_retries,
            backoff_s=self.cfg.io_retry_backoff_s,
            channel="store",
            site="writeback",
            obs=obs,
        )

    # -- tier maintenance ---------------------------------------------------

    def maintain(self, state: dict, obs=NULL_OBS) -> dict:
        """Between-steps application point: flush the write-back, check
        the promotion worker's pulse, then apply its plan (if any).
        Returns the (possibly rebound) device state."""
        self.complete_pending(obs=obs)
        if self.promoter is None:
            return state
        if not self.promoter.alive() and not self._promoter_dead:
            self._heal_promoter(obs)
        if self._promoter_dead:
            return state
        plan = self.promoter.poll_plan()
        if plan is None:
            return state
        evict = [k for k in plan.get("evict", []) if k in self.hot.slot_of]
        promote = [
            k for k in plan.get("promote", [])
            if k not in self.hot.slot_of
        ]
        demoted: list[int] = []
        for chunk in _chunks(evict, PROMOTE_CAP):
            state = self._demote(state, chunk)
            demoted.extend(chunk)
        promote = promote[: self.hot.free_count]
        promoted: list[int] = []
        for chunk in _chunks(promote, PROMOTE_CAP):
            state = self._promote(state, chunk)
            promoted.extend(chunk)
        if promoted or demoted:
            obs.counter("store.promotions", len(promoted))
            obs.counter("store.demotions", len(demoted))
            self.promoter.ack(promoted, demoted)
        return state

    def _heal_promoter(self, obs) -> None:
        """The promotion worker died (the watchdog's ``store`` channel
        sees the silence; this is the sequential-path restart point).
        Restart ONCE — the fresh worker's empty hot_view self-corrects
        through maintain's slot_of filters + acks.  A second death
        leaves placement frozen: the store stays correct (hot hits
        keep hitting, new keys ride the miss path) with no more tier
        movement — degraded, loud, never corrupt."""
        crash = self.promoter.crashed
        self.promoter.close()  # dead thread: the join returns at once
        if self._promoter_restarts == 0:
            self._promoter_restarts += 1
            obs.counter("store.promote_restarts")
            emit_health(
                obs,
                cause="store_promote_restarted",
                channel="store",
                detail=f"promotion worker died "
                f"({type(crash).__name__ if crash else 'no exception'}"
                f"{f': {crash}' if crash else ''}) — restarted once",
            )
            self.promoter = PromotionWorker(self.hot.capacity, obs=obs)
        else:
            self._promoter_dead = True
            emit_health(
                obs,
                cause="store_promote_dead",
                channel="store",
                detail="promotion worker died again after its one "
                "restart — tier placement frozen (all-miss for new "
                "keys); training continues correctly",
            )

    def _pad_slots(self, slots: np.ndarray) -> jax.Array:
        out = np.full(PROMOTE_CAP, self.hot.capacity, np.int32)
        out[: len(slots)] = slots
        return jnp.asarray(out)

    def _demote(self, state: dict, keys: list[int]) -> dict:
        """Flush ``keys``' rows (param + optimizer slots) from the hot
        tier back to the cold store and free their slots."""
        karr = np.asarray(keys, np.int64)
        slots = np.asarray(
            [self.hot.slot_of[int(k)] for k in keys], np.int64
        )
        rows_dev = self.hot.read(state, self._pad_slots(slots))
        host = jax.device_get(rows_dev)
        self.cold.write(karr, {
            tname: {
                aname: np.asarray(block)[: len(keys)]
                for aname, block in arrs.items()
            }
            for tname, arrs in host.items()
        })
        self.hot.release(karr)
        return state

    def _promote(self, state: dict, keys: list[int]) -> dict:
        """Move ``keys``' rows from the cold store into freshly
        assigned hot slots (one fixed-width device fill)."""
        karr = np.asarray(keys, np.int64)
        rows = self.cold.take(karr)
        slots = self.hot.assign(karr)
        fill_rows = {
            tname: {
                aname: jnp.asarray(_pad_rows(block, PROMOTE_CAP))
                for aname, block in arrs.items()
            }
            for tname, arrs in rows.items()
        }
        state = self.hot.fill(state, self._pad_slots(slots), fill_rows)
        return state

    def occupancy_frac(self) -> float:
        return self.hot.occupancy / self.hot.capacity

    def close(self) -> None:
        """Flush the write-back (best-effort — on a crash path the
        device may be the thing that died) and reap the promotion
        worker (bounded join; a leak surfaces as a health row)."""
        try:
            self.complete_pending()
        except Exception:  # noqa: BLE001 - crash-path cleanup
            self._pending = None
        if self.promoter is not None:
            self.promoter.close()

    # -- device state -------------------------------------------------------

    def init_device_state(self) -> dict:
        return self.hot.init_device_state()

    # -- logical-table views ------------------------------------------------

    def logical_rows(self, state: dict, table: str, keys: np.ndarray) -> dict:
        """{arr: [m, D]} — the logical table rows for ``keys``
        regardless of tier: hot slots read from the device, the rest
        from the cold store (stored or lazy-init).  Test/debug surface
        behind the checkpoint round-trip's bitwise guarantee."""
        self.complete_pending()
        out = self.cold.fetch(keys)
        slots = self.hot.lookup(keys)
        sel = slots >= 0
        if sel.any():
            host = jax.device_get(state["tables"][table])
            for aname, arr in host.items():
                out[table][aname][sel] = np.asarray(arr)[slots[sel]]
        return out[table]

    @staticmethod
    def _gather_fold(
        idx: np.ndarray,
        ncold: int,
        cold_rows: np.ndarray,
        hot_rows: np.ndarray,
    ) -> np.ndarray:
        """Rows for merged-index positions ``idx`` of the two-tier key
        space (cold keys first, hot keys appended — idx < ncold gathers
        the cold view, the rest offset into the hot host copy).  The
        ONE split-gather shared by the checkpoint fold and the export
        fold so the subtle index arithmetic cannot drift between
        them."""
        csel = idx < ncold
        block = np.empty((len(idx), cold_rows.shape[1]), np.float32)
        block[csel] = cold_rows[idx[csel]]
        block[~csel] = hot_rows[idx[~csel] - ncold]
        return block

    def iter_logical_param_shards(
        self, state: dict, table: str, chunk: int = CHUNK_ROWS
    ):
        """(start, stop, rows) blocks of the FULL logical [T, D] param
        table — lazy init overlaid with both tiers' live rows.  Peak
        extra memory is O(chunk) row data + O(touched keys) int64
        index (the sort below); touched ROWS are gathered per chunk
        from the stores' own arrays, never copied wholesale — at an FM
        north-star export that is the difference between ~1.6 GB of
        index and a >4 GB second copy of every touched row.
        serve/artifact.py writes these as the standard row-range shard
        files, so a tiered model exports to an artifact PredictEngine
        loads unchanged."""
        self.complete_pending()
        host_param = np.asarray(
            jax.device_get(state["tables"][table]["param"])
        )
        occupied = np.flatnonzero(self.hot.key_of >= 0)
        hkeys = self.hot.key_of[occupied]
        hrows = host_param[occupied]
        ckeys, crows = self.cold.export_array(table, "param")  # views
        ncold = len(ckeys)
        mkeys = np.concatenate([ckeys, hkeys])
        order = np.argsort(mkeys)
        skeys = mkeys[order]
        t = self.cfg.table_size
        for start in range(0, t, chunk):
            stop = min(start + chunk, t)
            block = self.cold.lazy_rows(
                table, "param", np.arange(start, stop, dtype=np.int64)
            )
            lo, hi = np.searchsorted(skeys, (start, stop))
            idx = order[lo:hi]
            at = skeys[lo:hi] - start
            block[at] = self._gather_fold(idx, ncold, crows, hrows)
            yield start, stop, block

    # -- checkpoint (tier-erased fold) --------------------------------------

    def save_checkpoint(
        self,
        directory: str,
        state: dict,
        cursor: dict,
        config_json: str | None = None,
        keep: int = 0,
    ) -> str:
        """Tiered checkpoint: manifest format 2 plus a ``store``
        section; touched rows from BOTH tiers in the row-range shard
        format over the PACKED key-sorted space, written chunk by
        chunk through a sort INDEX (no [T, D] materialization and no
        second copy of the touched rows — peak extra memory is
        O(CHUNK_ROWS) row data + O(touched keys) int64 index);
        single-process by construction (TrainStep refuses tiered
        multi-host)."""
        self.complete_pending()
        step = int(jax.device_get(state["step"]))
        final = os.path.join(directory, f"ckpt-{step:010d}")
        tmp = os.path.join(directory, f".tmp-ckpt-{step:010d}")
        os.makedirs(directory, exist_ok=True)
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        # same chaos site as the dense path: a fire leaves only a
        # .tmp-ckpt-* (cleaned by the next save); the previous
        # committed generation stays the newest complete one
        failpoint("ckpt.write_shard")
        host = jax.device_get(state["tables"])
        occupied = np.flatnonzero(self.hot.key_of >= 0)
        hkeys = self.hot.key_of[occupied]
        ckeys = self.cold.keys_view()
        ncold = len(ckeys)
        all_keys = np.concatenate([ckeys, hkeys])
        order = np.argsort(all_keys)
        n = len(order)
        np.save(os.path.join(tmp, "store.keys.npy"), all_keys[order])
        arrays_meta: dict = {}
        for tname, spec in self.cold.tables.items():
            for aname in spec.arrays:
                key = f"store.{tname}.{aname}"
                _, cold_rows = self.cold.export_array(tname, aname)
                hot_rows = np.asarray(host[tname][aname])[occupied]
                arrays_meta[key] = {
                    "shape": [n, spec.dim],
                    "dtype": "float32",
                }
                for start in range(0, n, CHUNK_ROWS):
                    stop = min(start + CHUNK_ROWS, n)
                    block = self._gather_fold(
                        order[start:stop], ncold, cold_rows, hot_rows
                    )
                    np.save(
                        os.path.join(
                            tmp, f"{key}.r{start:012d}-{stop:012d}.npy"
                        ),
                        block,
                    )
        for dname in sorted(state.get("dense", {})):
            np.save(
                os.path.join(tmp, f"dense.{dname}.npy"),
                np.asarray(jax.device_get(state["dense"][dname])),
            )
        manifest = {
            "format": 2,
            "step": step,
            "arrays": arrays_meta,
            "dense": sorted(state.get("dense", {})),
            "cursor": cursor,
            "config": config_json,
            "store": {
                "rows": n,
                "table_size": self.cfg.table_size,
                "hot_capacity": self.hot.capacity,
                "hot_occupancy": self.hot.occupancy,
            },
        }
        with open(os.path.join(tmp, MANIFEST), "w") as f:
            json.dump(manifest, f, indent=2)
        failpoint("ckpt.finalize")  # kill mid-commit (manifest-last)
        if os.path.exists(final):
            shutil.rmtree(final)
        os.rename(tmp, final)
        _write_latest(directory, os.path.basename(final))
        if keep > 0:
            gc_checkpoints(directory, keep)
        return final

    def load_checkpoint(self, path: str, state: dict):
        """Restore: repopulate the cold store with the folded rows,
        reset the hot tier (promotion re-warms it), rebuild device
        state.  Returns (state, cursor)."""
        from xflow_tpu.utils.checkpoint import is_complete

        failpoint("ckpt.restore")
        if not is_complete(path):
            raise IncompatibleCheckpoint(
                f"checkpoint {path} has no {MANIFEST} — incomplete or "
                "externally corrupted generation"
            )
        with open(os.path.join(path, MANIFEST)) as f:
            manifest = json.load(f)
        store_meta = manifest.get("store")
        if manifest.get("format") != 2 or store_meta is None:
            raise IncompatibleCheckpoint(
                f"checkpoint {path} was not written by "
                "store_mode='tiered' (no store section) — restore it "
                "with the store mode it was trained under"
            )
        if int(store_meta["table_size"]) != self.cfg.table_size:
            raise ValueError(
                f"checkpoint {path} table_size "
                f"{store_meta['table_size']} != configured "
                f"{self.cfg.table_size} — table_size_log2 changed "
                "between runs?"
            )
        n = int(store_meta["rows"])
        keys = (
            np.load(os.path.join(path, "store.keys.npy"))
            if n
            else np.zeros(0, np.int64)
        )
        data: dict[str, dict[str, np.ndarray]] = {}
        for tname, spec in self.cold.tables.items():
            data[tname] = {}
            for aname in spec.arrays:
                key = f"store.{tname}.{aname}"
                meta = manifest["arrays"].get(key)
                if meta is None:
                    raise ValueError(
                        f"checkpoint {path} missing array {key}"
                    )
                if n:
                    reader = RangeReader(
                        path, key, tuple(meta["shape"]),
                        np.dtype(meta["dtype"]),
                    )
                    data[tname][aname] = reader.read((slice(0, n),))
                else:
                    data[tname][aname] = np.zeros(
                        (0, spec.dim), np.float32
                    )
        self._staged.clear()
        self._pending = None
        if self.promoter is not None:
            # the worker mirrors the tier (hot_view, decayed scores);
            # restoring under it would leave keys it still believes hot
            # permanently un-promotable — recreate it fresh alongside
            # the maps it mirrors
            self.promoter.close()
            self.promoter = None
        self._promoter_restarts = 0  # restored run: fresh heal budget
        self._promoter_dead = False
        self.cold.load_rows(keys, data)
        self.hot.reset_maps()
        new_state = self.init_device_state()
        for dname, arr in new_state.get("dense", {}).items():
            fname = os.path.join(path, f"dense.{dname}.npy")
            if not os.path.exists(fname):
                raise ValueError(
                    f"checkpoint {path} missing dense array {dname}"
                )
            host = np.load(fname)
            if host.shape != arr.shape:
                raise ValueError(
                    f"checkpoint dense {dname} shape {host.shape} != "
                    f"{arr.shape}"
                )
            new_state["dense"][dname] = jax.device_put(
                host, arr.sharding
            )
        new_state["step"] = jnp.asarray(manifest["step"], jnp.int32)
        return new_state, manifest["cursor"]


def _chunks(items: list, size: int):
    for i in range(0, len(items), size):
        yield items[i : i + size]


def _pad_rows(block: np.ndarray, cap: int) -> np.ndarray:
    out = np.zeros((cap, block.shape[1]), np.float32)
    out[: len(block)] = block
    return out
