"""Async promotion/demotion worker — tier placement off the hot loop.

The worker never touches the store: the planner posts per-batch touch
counts (the dedup kernel's unique keys + occurrence counts — free, the
host computed them for the refs plane anyway) into a bounded queue; the
worker folds them into a decayed score table and proposes plans
(promote these misses / evict those cold hot rows); the trainer applies
a plan BETWEEN steps (store/tiered.py::maintain) so an in-flight batch
never sees a moving key→slot map, then acks what actually happened so
the worker's view of the tier converges.  Queues are the only shared
state (XF008 by construction: no lock to get wrong), the loop
heartbeats the flight recorder (the XF009 discipline — a silent
promoter with misses flowing is a diagnosable stall, not a mystery),
and close() joins with a timeout, surfacing a leak as a ``health`` row
exactly like the loader's prefetch reaper (XF006).

Policy: promote any touched miss while free slots exist (zipf traffic
front-loads the head, so first-touch filling is near-optimal); once
full, swap in candidates whose decayed score clears the coldest hot
rows by a margin (hysteresis — a tie must not churn).  Scores halve
every DECAY_EVERY batches so yesterday's head can age out.
"""

from __future__ import annotations

import heapq
import queue
import threading

import numpy as np

from xflow_tpu.chaos import failpoint
from xflow_tpu.obs import NULL_OBS

POLL_S = 0.05
DECAY_EVERY = 512
DECAY = 0.5
SCORE_FLOOR = 0.25  # decayed-out entries are dropped
# Hard score-table bound: when the dict outgrows this, decay+prune runs
# IMMEDIATELY instead of waiting for the DECAY_EVERY cadence.  A
# once-touched tail key survives at most two decays (1.0 -> 0.5 ->
# 0.25-pruned), so resident entries are bounded by ~2-3 trigger
# intervals of unique inflow — without this, a 2^28 zipf run's
# singleton tail would accumulate for a whole decay window (millions
# of dict entries, GBs of host RAM competing with the cold store).
SCORES_MAX_FACTOR = 8  # * capacity, floored at 65536
MAX_SWAPS = 256  # evict/promote pairs per plan
SWAP_EVERY = 8  # scan the hot set for cold rows every N notes
SWAP_MARGIN = 2.0  # candidate must beat the evictee by this factor


class PromotionWorker:
    def __init__(self, capacity: int, obs=NULL_OBS):
        self.capacity = capacity
        self._obs = obs
        self._touch_q: queue.Queue = queue.Queue(maxsize=256)
        self._plan_q: queue.Queue = queue.Queue(maxsize=2)
        self._ack_q: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        # set when _run dies on an exception (the store.promote_worker
        # failpoint, or a real bug): TieredStore.maintain polls
        # alive() every step and restarts the worker ONCE with a
        # health row — placement degrades, correctness never does
        self.crashed: BaseException | None = None
        self._thread = threading.Thread(
            target=self._run, name="store-promote", daemon=True
        )
        self._thread.start()

    # -- main-thread surface ------------------------------------------------

    def note(
        self, keys: np.ndarray, counts: np.ndarray, miss: np.ndarray
    ) -> None:
        """Post one batch's (unique keys, occurrence counts, miss mask).
        Dropped (with a counter) when the worker lags — placement is
        advisory, the training step is not."""
        try:
            self._touch_q.put_nowait((keys, counts, miss))
        except queue.Full:
            self._obs.counter("store.touch_dropped")

    def poll_plan(self) -> dict | None:
        try:
            return self._plan_q.get_nowait()
        except queue.Empty:
            return None

    def ack(self, promoted: list[int], demoted: list[int]) -> None:
        """Report what maintain() actually applied, so the worker's
        hot-set view converges on the authoritative maps."""
        self._ack_q.put((promoted, demoted))

    def close(self) -> bool:
        """Stop + bounded join; returns True when the thread exited.
        A leak is surfaced exactly like the loader's (io/loader.py):
        counter + schema-valid ``health`` row through the flight
        recorder's logger."""
        self._stop.set()
        self._thread.join(timeout=5.0)
        leaked = self._thread.is_alive()
        if leaked:
            self._obs.counter("store.promote_thread_leak")
            flight = self._obs.flight
            if flight is not None and flight.metrics_logger is not None:
                from xflow_tpu.obs.schema import health_row

                flight.metrics_logger.log("health", health_row(
                    cause="store_promote_leak",
                    channel="store",
                    silence_seconds=5.0,
                    threshold_seconds=5.0,
                    detail="promotion worker did not exit within the "
                    "join timeout",
                ))
        return not leaked

    def alive(self) -> bool:
        """The worker thread is still running.  False + ``crashed``
        set = it died on an exception; False + clean = it exited via
        close().  maintain() (store/tiered.py) polls this between
        steps — the watchdog's ``store`` channel independently sees
        the silence, but the restart decision is taken on the strictly
        sequential maintain path so it can never race a live plan."""
        return self._thread.is_alive()

    # -- worker -------------------------------------------------------------

    def _beat(self, detail: str) -> None:
        flight = self._obs.flight
        if flight is not None:
            flight.note_store(detail)

    def _run(self) -> None:
        try:
            self._run_inner()
        except BaseException as e:
            # worker death is a FACT to surface, not a crash to spread:
            # record it (maintain's alive() poll restarts once + emits
            # the health row) and exit — the store keeps training
            # correctly with placement frozen (all-miss for new keys)
            self.crashed = e
            self._obs.counter("store.promote_crash")

    def _run_inner(self) -> None:
        scores: dict[int, float] = {}
        hot_view: set[int] = set()
        scores_max = max(SCORES_MAX_FACTOR * self.capacity, 65536)
        notes = 0
        while not self._stop.is_set():
            while True:
                try:
                    promoted, demoted = self._ack_q.get_nowait()
                except queue.Empty:
                    break
                hot_view.update(promoted)
                hot_view.difference_update(demoted)
            try:
                keys, counts, miss = self._touch_q.get(timeout=POLL_S)
            except queue.Empty:
                self._beat("idle")
                continue
            # chaos site: a fire kills THIS thread (caught by _run's
            # death recorder) — the self-healing under test is the
            # maintain()-side detect-and-restart-once
            failpoint("store.promote_worker")
            self._beat("note")
            notes += 1
            miss_keys: list[int] = []
            for k, c, m in zip(
                keys.tolist(), counts.tolist(), miss.tolist()
            ):
                scores[k] = scores.get(k, 0.0) + float(c)
                if m and k not in hot_view:
                    miss_keys.append(k)
            if notes % DECAY_EVERY == 0 or len(scores) > scores_max:
                scores = {
                    k: v * DECAY
                    for k, v in scores.items()
                    if v * DECAY >= SCORE_FLOOR
                }
            plan = self._build_plan(scores, hot_view, miss_keys, notes)
            if plan is not None:
                try:
                    self._plan_q.put_nowait(plan)
                except queue.Full:
                    pass  # maintain() hasn't drained the last one yet

    def _build_plan(
        self,
        scores: dict[int, float],
        hot_view: set[int],
        miss_keys: list[int],
        notes: int,
    ) -> dict | None:
        if not miss_keys:
            return None
        cand = sorted(miss_keys, key=lambda k: -scores.get(k, 0.0))
        free = max(0, self.capacity - len(hot_view))
        promote = cand[:free]
        evict: list[int] = []
        rest = cand[free : free + MAX_SWAPS]
        if rest and hot_view and notes % SWAP_EVERY == 0:
            coldest = heapq.nsmallest(
                len(rest), hot_view, key=lambda k: scores.get(k, 0.0)
            )
            for k, old in zip(rest, coldest):
                if scores.get(k, 0.0) > SWAP_MARGIN * scores.get(old, 0.0):
                    promote.append(k)
                    evict.append(old)
        if not promote:
            return None
        # NOT applied to hot_view here: only maintain()'s ack mutates
        # the view, so a dropped/truncated plan self-corrects (the next
        # plan re-proposes; maintain skips keys already placed)
        return {"promote": promote, "evict": evict}
