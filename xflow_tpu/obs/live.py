"""Rolling-window SLO alerting + streaming doctor (ISSUE 19).

Post-hoc diagnosis (`obs doctor`) answers "what went wrong" after a
run dies; operating a fleet needs "what is going wrong" while it can
still be fixed.  This module adds both halves:

* ``AlertRule`` / ``AlertEvaluator`` — a declarative rolling-window
  SLO evaluator with **multi-window burn-rate** semantics: a rule
  fires only when its metric breaches the threshold over BOTH a short
  window (the problem is happening *now* — fast resolve once it
  stops) and a long window (it is *sustained* — one bad tick never
  pages).  Transitions emit ``alert`` JSONL rows (firing/resolved,
  obs/schema.py) that `obs doctor` consumes as first-class evidence
  and ``GET /v1/stats`` summarizes.  The committed default rules
  cover error fraction, shed fraction, queue p99, freshness age, and
  input-stall fraction.

* ``LiveTailer`` / ``run_live`` — `python -m xflow_tpu.obs live`:
  incremental tailing of growing (multi-host, rank-tagged) metrics
  files — torn tail fragments wait in the file, torn complete lines
  are counted and skipped, never fatal — feeding the full doctor
  check suite plus the alert rules continuously, printing each
  finding the moment the evidence supports it.  On a finished file it
  reaches exactly the diagnosis `obs doctor` reaches post-hoc
  (scripts/check_live_obs.py pins this).

docs/OBSERVABILITY.md "Operating a live fleet" documents the rule
grammar and the burn-rate math.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass

from xflow_tpu.obs.schema import alert_row


@dataclass(frozen=True)
class AlertRule:
    """One declarative SLO rule: sample ``field`` (optionally divided
    by ``denom``) from every row of ``kind``; fire when the mean over
    both windows exceeds ``threshold``."""

    name: str
    kind: str
    field: str
    threshold: float
    denom: str = ""
    short_s: float = 60.0
    long_s: float = 300.0
    min_samples: int = 1
    description: str = ""

    def value(self, row: dict) -> float | None:
        """The rule's sample from one row (None = row not sampled)."""
        if row.get("kind") != self.kind:
            return None
        v = row.get(self.field)
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            return None
        if self.denom:
            d = row.get(self.denom)
            if isinstance(d, bool) or not isinstance(d, (int, float)):
                return None
            if d <= 0:
                return None
            return float(v) / float(d)
        return float(v)


def default_rules(
    short_s: float = 60.0, long_s: float = 300.0
) -> tuple[AlertRule, ...]:
    """The committed rule set (thresholds are operating bars, not CI
    bars: a healthy tier under load stays silent on all five)."""
    return (
        AlertRule(
            "serve_error_frac", "serve_shed", "errors",
            threshold=0.05, denom="admitted",
            short_s=short_s, long_s=long_s,
            description="scoring errors per admitted request",
        ),
        AlertRule(
            "serve_shed_frac", "serve_shed", "shed_frac",
            threshold=0.5,
            short_s=short_s, long_s=long_s,
            description="admission-control shed fraction (a storm, "
            "not policy shedding)",
        ),
        AlertRule(
            "serve_queue_p99", "serve_stats", "queue_p99",
            threshold=1.0,
            short_s=short_s, long_s=long_s,
            description="p99 coalescing-queue wait in seconds",
        ),
        AlertRule(
            "freshness_age", "freshness", "newest_event_age_s",
            threshold=1.0, denom="slo_s",
            short_s=short_s, long_s=long_s,
            description="event-to-servable age as a fraction of the "
            "freshness SLO",
        ),
        AlertRule(
            "train_stall_frac", "train_epoch", "input_stall_frac",
            threshold=0.9,
            short_s=short_s, long_s=long_s,
            description="epoch wall fraction spent stalled on input",
        ),
    )


def _mean(samples: list[float]) -> float:
    return sum(samples) / len(samples)


class AlertEvaluator:
    """Feed rows in, get ``alert`` transitions out.

    Samples are timestamped from the row's ``time_unix`` tag when
    present (merged/tailed multi-host streams evaluate in LOG time, so
    live and post-hoc reach the same verdicts) and from the caller's
    ``now`` otherwise (in-process serve ticks).  When a metrics logger
    is attached, every transition is also emitted as an ``alert``
    JSONL row.  All state is lock-guarded: the serve CLI evaluates on
    its stats tick while HTTP handler threads read ``summary()``."""

    def __init__(self, rules=None, metrics_logger=None):
        self.rules: tuple[AlertRule, ...] = (
            tuple(rules) if rules is not None else default_rules()
        )
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names: {names}")
        self.metrics_logger = metrics_logger
        self._lock = threading.Lock()
        self._samples: dict[str, deque] = {
            r.name: deque() for r in self.rules
        }
        self._firing: dict[str, dict] = {}
        self._fired_total = 0
        self._resolved_total = 0
        self._last: dict | None = None

    def observe_rows(self, rows, now: float | None = None) -> list[dict]:
        """Ingest rows, evaluate every rule, return (and log) the
        ``alert`` rows for any state transitions."""
        if now is None:
            stamps = [
                r.get("time_unix") for r in rows
                if isinstance(r.get("time_unix"), (int, float))
            ]
            now = max(stamps) if stamps else time.time()
        with self._lock:
            for row in rows:
                ts = row.get("time_unix")
                if isinstance(ts, bool) or not isinstance(
                    ts, (int, float)
                ):
                    ts = now
                for rule in self.rules:
                    v = rule.value(row)
                    if v is not None:
                        self._samples[rule.name].append((float(ts), v))
            transitions = self._evaluate_locked(now)
        if self.metrics_logger is not None:
            for body in transitions:
                self.metrics_logger.log("alert", body)
        # callers without a logger (obs live) still need kind-tagged
        # rows to feed diagnose()
        return [dict(b, kind="alert", t=0.0) for b in transitions]

    def _evaluate_locked(self, now: float) -> list[dict]:
        out: list[dict] = []
        for rule in self.rules:
            samples = self._samples[rule.name]
            while samples and samples[0][0] < now - rule.long_s:
                samples.popleft()
            short = [
                v for ts, v in samples if ts >= now - rule.short_s
            ]
            if len(short) < rule.min_samples:
                continue  # no short-window evidence either way
            short_mean = _mean(short)
            long_mean = _mean([v for _, v in samples])
            firing = rule.name in self._firing
            if not firing and (
                short_mean > rule.threshold
                and long_mean > rule.threshold
            ):
                body = alert_row(
                    rule=rule.name, state="firing",
                    value=short_mean, threshold=rule.threshold,
                    short_s=rule.short_s, long_s=rule.long_s,
                    samples=len(short),
                    detail=(
                        f"{rule.kind}.{rule.field} short-window mean "
                        f"{short_mean:.4f} and long-window mean "
                        f"{long_mean:.4f} both over "
                        f"{rule.threshold} — {rule.description}"
                    ),
                )
                self._firing[rule.name] = body
                self._fired_total += 1
                self._last = body
                out.append(body)
            elif firing and short_mean <= rule.threshold:
                body = alert_row(
                    rule=rule.name, state="resolved",
                    value=short_mean, threshold=rule.threshold,
                    short_s=rule.short_s, long_s=rule.long_s,
                    samples=len(short),
                    detail=(
                        f"{rule.kind}.{rule.field} short-window mean "
                        f"{short_mean:.4f} back under "
                        f"{rule.threshold}"
                    ),
                )
                del self._firing[rule.name]
                self._resolved_total += 1
                self._last = body
                out.append(body)
        return out

    def summary(self) -> dict:
        """JSON-ready state for ``GET /v1/stats``: which rules are
        firing right now plus lifetime transition counts."""
        with self._lock:
            return {
                "firing": sorted(self._firing),
                "fired_total": self._fired_total,
                "resolved_total": self._resolved_total,
                "last": dict(self._last) if self._last else None,
            }


# -- incremental tailing ----------------------------------------------------


class _FileCursor:
    __slots__ = ("offset", "rank", "run_id", "t0")

    def __init__(self):
        self.offset = 0
        self.rank = 0
        self.run_id = ""
        self.t0 = 0.0


class LiveTailer:
    """Incremental, rank-tagging reader over growing metrics files.

    Each ``poll()`` consumes only the bytes appended since the last
    one, up to the final newline — a torn tail fragment simply stays
    in the file until the writer finishes the line.  A COMPLETE line
    that fails to parse (a crashed writer's garbage) is counted in
    ``skipped`` and skipped: a live monitor must outlive the thing it
    monitors.  Rows are tagged with rank / run_id / time_unix exactly
    like ``doctor.merge_rows``, so downstream checks see the same
    stream either way."""

    def __init__(self, paths):
        self.paths = [os.fspath(p) for p in paths]
        self.skipped = 0
        self._cursors = {p: _FileCursor() for p in self.paths}

    def poll(self) -> list[dict]:
        """Newly completed rows across every file, time-sorted."""
        out: list[dict] = []
        for path in self.paths:
            cur = self._cursors[path]
            try:
                with open(path, "rb") as f:
                    f.seek(cur.offset)
                    chunk = f.read()
            except OSError:
                continue  # not created yet / rotated away: keep tailing
            end = chunk.rfind(b"\n")
            if end < 0:
                continue  # torn tail only — wait for the newline
            cur.offset += end + 1
            for raw in chunk[: end + 1].split(b"\n"):
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    row = json.loads(raw)
                except ValueError:
                    self.skipped += 1
                    continue
                if row.get("kind") == "run_start":
                    cur.rank = int(row.get("rank", 0))
                    cur.run_id = str(row.get("run_id", ""))
                    cur.t0 = float(row.get("time_unix", 0.0))
                tagged = dict(row)
                tagged.setdefault("rank", cur.rank)
                tagged.setdefault("run_id", cur.run_id)
                tagged.setdefault(
                    "time_unix",
                    round(cur.t0 + float(row.get("t", 0.0)), 3),
                )
                out.append(tagged)
        out.sort(key=lambda r: r.get("time_unix", 0.0))
        return out


def run_live(
    paths,
    out=print,
    interval_s: float = 2.0,
    max_seconds: float = 0.0,
    once: bool = False,
    rules=None,
    sleep=time.sleep,
) -> int:
    """The `obs live` engine: tail ``paths``, run the alert rules and
    the full doctor suite over everything seen so far, and print each
    finding / alert transition once, the moment it appears.  Runs
    until ``max_seconds`` (0 = until interrupted) or a single pass
    with ``once``.  Exit code matches `obs doctor`: 1 when anything
    at warn or above fired, else 0."""
    from xflow_tpu.obs.doctor import diagnose

    tailer = LiveTailer(paths)
    evaluator = AlertEvaluator(rules=rules)
    rows: list[dict] = []
    reported: set[tuple] = set()
    seen_skipped = 0
    bad = False
    deadline = time.monotonic() + (
        max_seconds if max_seconds > 0 else float("inf")
    )
    try:
        while time.monotonic() < deadline:
            new = tailer.poll()
            if new:
                alerts = evaluator.observe_rows(new)
                rows.extend(new)
                rows.extend(alerts)
                for a in alerts:
                    out(
                        f"[ALERT] {a['rule']} {a['state']}: "
                        f"value {a['value']} vs threshold "
                        f"{a['threshold']} ({a['detail']})"
                    )
                findings = diagnose(rows)
                for d in findings:
                    key = (d.severity, d.code, d.message)
                    if key in reported:
                        continue
                    reported.add(key)
                    if d.severity in ("crit", "warn"):
                        bad = True
                    out(
                        f"[{d.severity.upper():4s}] {d.code}: "
                        f"{d.message}"
                    )
            if tailer.skipped > seen_skipped:
                out(
                    f"(skipped {tailer.skipped - seen_skipped} "
                    "unparseable line(s) — still-growing file)"
                )
                seen_skipped = tailer.skipped
            if once:
                break
            sleep(interval_s)
    except KeyboardInterrupt:
        pass
    summary = evaluator.summary()
    out(
        f"obs live — {len(rows)} row(s) observed, "
        f"{summary['fired_total']} alert(s) fired, "
        f"{summary['resolved_total']} resolved, "
        f"firing now: {summary['firing'] or 'none'}"
    )
    if summary["firing"]:
        bad = True
    return 1 if bad else 0
