"""CLI: ``python -m xflow_tpu.obs <summarize|validate|compare> ...``

    summarize run.jsonl      phase/throughput/percentile tables per run
    compare   a.jsonl b.jsonl  side-by-side diff of the last run in each
    validate  run.jsonl      strict schema check (exit 1 on violations)

Pure host-side file processing — never imports jax, so it runs
anywhere (including hosts with no accelerator runtime).
"""

from __future__ import annotations

import argparse
import sys

from xflow_tpu.obs.schema import load_jsonl, validate_rows
from xflow_tpu.obs.summary import compare, summarize


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m xflow_tpu.obs",
        description="metrics JSONL toolchain (docs/OBSERVABILITY.md)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    ps = sub.add_parser("summarize", help="per-run phase/throughput tables")
    ps.add_argument("path")
    pv = sub.add_parser("validate", help="strict schema check")
    pv.add_argument("path")
    pc = sub.add_parser("compare", help="diff the last run of two files")
    pc.add_argument("path_a")
    pc.add_argument("path_b")
    args = p.parse_args(argv)

    if args.cmd == "summarize":
        print(summarize(args.path))
        return 0
    if args.cmd == "validate":
        errors = validate_rows(load_jsonl(args.path))
        for e in errors:
            print(e, file=sys.stderr)
        print(f"{args.path}: {'FAIL' if errors else 'OK'} "
              f"({len(errors)} violation(s))")
        return 1 if errors else 0
    if args.cmd == "compare":
        try:
            print(compare(args.path_a, args.path_b))
        except ValueError as e:  # empty/headerless file: diagnose, not crash
            print(f"error: {e}", file=sys.stderr)
            return 1
        return 0
    return 2  # unreachable (subparsers required)


if __name__ == "__main__":
    sys.exit(main())
