"""CLI: ``python -m xflow_tpu.obs <summarize|validate|compare|merge|doctor>``

    summarize run.jsonl       phase/throughput/percentile tables per run
    compare   a b             side-by-side diff: metrics JSONL files
                              (last run each) or bench artifacts
                              (BENCH_r*.json); --fail-on-regress FRAC
                              exits 3 when B's throughput fell more
                              than FRAC below A's
    validate  run.jsonl       strict schema check (exit 1 on violations)
    merge     a.jsonl b.jsonl combine per-host metrics files into one
                              rank-tagged, time-aligned stream
                              (--out FILE, default stdout)
    doctor    run.jsonl       ranked diagnosis of a sick (or healthy)
                              run: stall causes, stragglers, recompile
                              suspicion (--flight DUMP, --bench JSON);
                              exit 0 only when clean
    live      run.jsonl ...   streaming doctor: tail growing metrics
                              files, run the doctor checks plus the
                              SLO alert rules continuously
                              (--interval-s, --max-seconds, --once)

Pure host-side file processing — never imports jax, so it runs
anywhere (including hosts with no accelerator runtime).
Docs: docs/OBSERVABILITY.md ("Diagnosing a sick run",
"Operating a live fleet").
"""

from __future__ import annotations

import argparse
import sys

from xflow_tpu.obs.schema import load_jsonl, validate_rows
from xflow_tpu.obs.summary import check_regress, compare, summarize


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m xflow_tpu.obs",
        description="metrics JSONL toolchain (docs/OBSERVABILITY.md)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    ps = sub.add_parser("summarize", help="per-run phase/throughput tables")
    ps.add_argument("path")
    pv = sub.add_parser("validate", help="strict schema check")
    pv.add_argument("path")
    pc = sub.add_parser(
        "compare", help="diff two metrics files or bench artifacts"
    )
    pc.add_argument("path_a")
    pc.add_argument("path_b")
    pc.add_argument(
        "--fail-on-regress",
        type=float,
        default=None,
        metavar="FRAC",
        help="exit 3 when B's throughput is more than FRAC (e.g. 0.05) "
        "below A's — the scripts/check_bench_regress.py gate",
    )
    pm = sub.add_parser(
        "merge", help="combine per-host metrics files into one stream"
    )
    pm.add_argument("paths", nargs="+")
    pm.add_argument("--out", default="", help="output file (default stdout)")
    pd = sub.add_parser("doctor", help="ranked diagnosis of a run")
    pd.add_argument("path", help="metrics JSONL (single-host or merged)")
    pd.add_argument(
        "--flight", default="", help="flight dump (Config.obs_flight_out)"
    )
    pd.add_argument(
        "--bench", default="", help="bench artifact (BENCH_r*.json)"
    )
    pl = sub.add_parser(
        "live", help="streaming doctor over growing metrics files"
    )
    pl.add_argument(
        "paths", nargs="+",
        help="metrics JSONL file(s), possibly still being written "
        "(one per host)",
    )
    pl.add_argument(
        "--interval-s", type=float, default=2.0,
        help="poll cadence (default 2s)",
    )
    pl.add_argument(
        "--max-seconds", type=float, default=0.0,
        help="stop after this long (default 0 = until Ctrl-C)",
    )
    pl.add_argument(
        "--once", action="store_true",
        help="single pass over what exists now, then exit — a "
        "file-tolerant `doctor` for still-growing files",
    )
    args = p.parse_args(argv)

    if args.cmd == "summarize":
        print(summarize(args.path))
        return 0
    if args.cmd == "validate":
        errors = validate_rows(load_jsonl(args.path))
        for e in errors:
            print(e, file=sys.stderr)
        print(f"{args.path}: {'FAIL' if errors else 'OK'} "
              f"({len(errors)} violation(s))")
        return 1 if errors else 0
    if args.cmd == "compare":
        try:
            print(compare(args.path_a, args.path_b))
            if args.fail_on_regress is not None:
                verdict = check_regress(
                    args.path_a, args.path_b, args.fail_on_regress
                )
                if verdict is not None:
                    print(verdict, file=sys.stderr)
                    return 3
        except ValueError as e:  # empty/headerless file: diagnose, not crash
            print(f"error: {e}", file=sys.stderr)
            return 1
        return 0
    if args.cmd == "merge":
        from xflow_tpu.obs.doctor import merge_rows_tolerant, write_jsonl

        try:
            rows, skipped = merge_rows_tolerant(args.paths)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        torn = (
            f", {skipped} torn final line(s) skipped (still-appended "
            "file)" if skipped else ""
        )
        if args.out:
            with open(args.out, "w") as f:
                write_jsonl(rows, f)
            print(
                f"{args.out}: {len(rows)} rows merged from "
                f"{len(args.paths)} file(s){torn}",
                file=sys.stderr,
            )
        else:
            write_jsonl(rows, sys.stdout)
            if skipped:
                print(
                    f"{skipped} torn final line(s) skipped "
                    "(still-appended file)",
                    file=sys.stderr,
                )
        return 0
    if args.cmd == "live":
        from xflow_tpu.obs.live import run_live

        return run_live(
            args.paths,
            interval_s=args.interval_s,
            max_seconds=args.max_seconds,
            once=args.once,
        )
    if args.cmd == "doctor":
        from xflow_tpu.obs.doctor import doctor

        try:
            text, rc = doctor(
                args.path,
                flight_path=args.flight or None,
                bench_path=args.bench or None,
            )
        except (ValueError, OSError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        print(text)
        return rc
    return 2  # unreachable (subparsers required)


if __name__ == "__main__":
    sys.exit(main())
