"""Turn metrics JSONL files into human-readable throughput / stall /
percentile tables (the ``python -m xflow_tpu.obs`` toolchain).

A metrics file may hold several runs appended back to back; each run
starts with its ``run_start`` header row (utils/logging.MetricsLogger),
so runs are never silently merged.  Rows before the first header (files
written by pre-schema versions) form one anonymous run.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from xflow_tpu.obs.schema import load_jsonl, validate_rows


@dataclass
class Run:
    header: dict | None = None
    rows: list = field(default_factory=list)

    def kind(self, kind: str) -> list[dict]:
        return [r for r in self.rows if r.get("kind") == kind]

    @property
    def epochs(self) -> list[dict]:
        return self.kind("train_epoch")

    @property
    def evals(self) -> list[dict]:
        return self.kind("eval")

    @property
    def shards(self) -> list[dict]:
        return self.kind("shard")

    def label(self) -> str:
        if not self.header:
            return "(no run_start header — pre-schema file?)"
        h = self.header
        host = ""
        if h.get("hostname"):
            host = f"  host {h['hostname']}:{h.get('pid', '?')}"
        return (
            f"run {h.get('run_id', '?')}  config {h.get('config_digest', '?')}"
            f"  rank {h.get('rank', '?')}/{h.get('num_hosts', '?')} hosts"
            f"{host}"
        )

    def wall_seconds(self) -> float:
        return sum(e.get("seconds", 0.0) for e in self.epochs)

    def phase_totals(self) -> tuple[dict[str, float], dict[str, float]]:
        """(exclusive main-thread phases, overlapped worker phases)
        summed over the run's epochs."""
        phases: dict[str, float] = {}
        overlapped: dict[str, float] = {}
        for e in self.epochs:
            for k, v in (e.get("phases") or {}).items():
                phases[k] = phases.get(k, 0.0) + float(v)
            for k, v in (e.get("overlapped") or {}).items():
                overlapped[k] = overlapped.get(k, 0.0) + float(v)
        return phases, overlapped

    def throughput(self) -> float:
        """Overall examples/sec over compute time (checkpoint saves
        excluded, matching train_epoch.examples_per_sec semantics)."""
        ex = sum(e.get("examples", 0.0) for e in self.epochs)
        dt = sum(
            max(e.get("seconds", 0.0) - e.get("checkpoint_seconds", 0.0), 0.0)
            for e in self.epochs
        )
        return ex / dt if dt > 0 else 0.0

    def stall_frac(self) -> float:
        wall = self.wall_seconds()
        stall = self.phase_totals()[0].get("input_stall", 0.0)
        return stall / wall if wall > 0 else 0.0


def split_runs(rows: list[dict]) -> list[Run]:
    runs: list[Run] = []
    for row in rows:
        if row.get("kind") == "run_start" or not runs:
            if row.get("kind") == "run_start":
                runs.append(Run(header=row))
                continue
            runs.append(Run())
        runs[-1].rows.append(row)
    return runs


def load_runs(path: str) -> list[Run]:
    return split_runs(load_jsonl(path))


def _fmt_row(cols: list, widths: list[int]) -> str:
    return "  ".join(str(c).rjust(w) for c, w in zip(cols, widths))


def format_run(run: Run) -> str:
    out = [run.label()]
    epochs = run.epochs
    if epochs:
        widths = [5, 10, 11, 10, 7, 8, 8, 7]
        out.append(_fmt_row(
            ["epoch", "examples", "ex/s", "logloss", "stall%",
             "p50ms", "p99ms", "ckpt_s"],
            widths,
        ))
        for e in epochs:
            out.append(_fmt_row(
                [
                    e.get("epoch", "?"),
                    int(e.get("examples", 0)),
                    f"{e.get('examples_per_sec', 0.0):.0f}",
                    f"{e.get('train_logloss', float('nan')):.6f}",
                    f"{100 * e.get('input_stall_frac', 0.0):.1f}",
                    f"{1e3 * e.get('step_time_p50', 0.0):.2f}",
                    f"{1e3 * e.get('step_time_p99', 0.0):.2f}",
                    f"{e.get('checkpoint_seconds', 0.0):.2f}",
                ],
                widths,
            ))
        phases, overlapped = run.phase_totals()
        wall = run.wall_seconds()
        if phases and wall > 0:
            out.append("")
            out.append(_fmt_row(["phase", "seconds", "% wall"], [16, 9, 7]))
            accounted = 0.0
            for name, secs in sorted(
                phases.items(), key=lambda kv: -kv[1]
            ):
                accounted += secs
                out.append(_fmt_row(
                    [name, f"{secs:.3f}", f"{100 * secs / wall:.1f}"],
                    [16, 9, 7],
                ))
            out.append(_fmt_row(
                ["accounted", f"{accounted:.3f}",
                 f"{100 * accounted / wall:.1f}"],
                [16, 9, 7],
            ))
            if overlapped:
                items = ", ".join(
                    f"{k} {v:.3f}s"
                    for k, v in sorted(overlapped.items(), key=lambda kv: -kv[1])
                )
                out.append(f"overlapped (worker threads, not additive): {items}")
    for ev in run.evals:
        out.append(
            f"eval epoch {ev.get('epoch', '?')}: "
            f"logloss={ev.get('logloss', float('nan')):.6f} "
            f"auc={ev.get('auc', float('nan')):.6f} "
            f"examples={ev.get('examples', 0)}"
        )
    wire = run.kind("wire")
    if wire:
        last = wire[-1]
        out.append(
            f"wire: format={last.get('format', '?')} "
            f"{last.get('wire_bytes_per_example', 0.0):.1f} B/example, "
            f"compaction {last.get('compaction_ratio', 1.0):.2f}x "
            "(cold occurrences per table touch; docs/PERF.md "
            "\"Wire format and compaction\")"
        )
    sheds = run.kind("serve_shed")
    if sheds:
        total_shed = sum(int(r.get("shed_total", 0)) for r in sheds)
        total_adm = sum(int(r.get("admitted", 0)) for r in sheds)
        line = (
            f"serve shed: {total_shed} shed vs {total_adm} admitted "
            f"across {len(sheds)} window(s)"
        )
        agg: dict[str, dict[str, int]] = {}
        for r in sheds:
            for c, d in (r.get("by_class") or {}).items():
                a = agg.setdefault(c, {"admitted": 0, "shed": 0})
                a["admitted"] += int(d.get("admitted", 0))
                a["shed"] += int(d.get("shed", 0))
        if agg:
            # protection order, best-protected first (fleet.QOS_CLASSES)
            order = ["bidding", "normal", "best_effort"]
            line += "; per class shed/offered: " + ", ".join(
                f"{c} {agg[c]['shed']}/"
                f"{agg[c]['admitted'] + agg[c]['shed']}"
                for c in order + sorted(set(agg) - set(order))
                if c in agg
            )
        out.append(line)
    cstats = [r for r in run.kind("serve_stats") if "cache_hits" in r]
    if cstats:
        hits = sum(int(r.get("cache_hits", 0)) for r in cstats)
        misses = sum(int(r.get("cache_misses", 0)) for r in cstats)
        inval = sum(
            int(r.get("cache_invalidations", 0)) for r in cstats
        )
        last = cstats[-1]
        rate = hits / (hits + misses) if (hits + misses) else 0.0
        out.append(
            f"score cache: hit rate {rate:.2f} "
            f"({hits} hit(s) / {misses} miss(es)), "
            f"{last.get('cache_entries', 0)} entries "
            f"({float(last.get('cache_bytes', 0)) / 2**20:.2f} MiB), "
            f"{inval} invalidation(s) "
            "(docs/SERVING.md \"Binary transport and QoS\")"
        )
    fresh = run.kind("freshness")
    if fresh:
        commits = sorted(
            float(r.get("newest_event_age_s", 0.0))
            for r in fresh if r.get("event") == "commit"
        )
        aborts = sum(1 for r in fresh if r.get("event") == "abort")
        last = fresh[-1]
        line = (
            f"freshness: {len(commits)} commit(s), {aborts} abort(s)"
        )
        if commits:
            p50 = commits[len(commits) // 2]
            p99 = commits[min(len(commits) - 1,
                              int(0.99 * len(commits)))]
            line += (
                f", newest-event-age p50/p99 = {p50:.1f}/{p99:.1f}s "
                f"(SLO {float(last.get('slo_s', 0.0)):.0f}s)"
            )
        line += (
            f"; last: {last.get('event')} {last.get('export_kind')} "
            f"step {last.get('step')} "
            f"({last.get('delta_bytes', 0)} B, "
            f"{last.get('rows', 0)} row(s))"
        )
        out.append(line)
    alerts = run.kind("alert")
    if alerts:
        state: dict[str, str] = {}
        for a in alerts:
            state[str(a.get("rule", "?"))] = str(a.get("state", "?"))
        open_rules = sorted(r for r, s in state.items() if s == "firing")
        fired = sum(1 for a in alerts if a.get("state") == "firing")
        resolved = sum(1 for a in alerts if a.get("state") == "resolved")
        last = alerts[-1]
        out.append(
            f"alerts: {fired} fired, {resolved} resolved; "
            f"firing at end: {', '.join(open_rules) or 'none'}; "
            f"last: {last.get('rule')} {last.get('state')} "
            f"(value {last.get('value')} vs threshold "
            f"{last.get('threshold')}; docs/OBSERVABILITY.md "
            "\"Operating a live fleet\")"
        )
    res = run.kind("resource")
    if res:
        last = res[-1]
        peak_rss = max(int(r.get("rss_bytes", 0)) for r in res)
        out.append(
            f"resources: {len(res)} sample(s), rss last/peak = "
            f"{float(last.get('rss_bytes', 0)) / 2**20:.1f}/"
            f"{peak_rss / 2**20:.1f} MiB, "
            f"cpu {float(last.get('cpu_seconds', 0.0)):.1f}s, "
            f"{last.get('threads', 0)} thread(s), "
            f"{last.get('open_fds', 0)} open fd(s), "
            f"{last.get('gc_collections', 0)} gc collection(s)"
        )
    traces = [
        r for r in run.kind("reqtrace")
        if r.get("span") == "request"
        and isinstance(r.get("phases"), dict) and "e2e" in r
    ]
    if traces:
        def _pct(vals: list[float], q: float) -> float:
            s = sorted(vals)
            return s[min(len(s) - 1, int(q * len(s)))]
        e2e = [float(r["e2e"]) for r in traces]
        names = sorted({p for r in traces for p in r["phases"]})
        decomp = "  ".join(
            f"{p} {1e3 * _pct(vs, 0.5):.1f}/{1e3 * _pct(vs, 0.99):.1f}"
            for p in names
            for vs in [[float(r["phases"].get(p, 0.0)) for r in traces]]
        )
        kept = {}
        for r in traces:
            kept[r.get("keep", "?")] = kept.get(r.get("keep", "?"), 0) + 1
        out.append(
            f"reqtrace: {len(traces)} request span(s) "
            f"({', '.join(f'{k}={v}' for k, v in sorted(kept.items()))}), "
            f"e2e p50/p99 = {1e3 * _pct(e2e, 0.5):.1f}/"
            f"{1e3 * _pct(e2e, 0.99):.1f}ms; per-phase p50/p99 ms: "
            f"{decomp} (docs/OBSERVABILITY.md \"Tracing a request\")"
        )
    shards = run.shards
    if shards:
        rates = [s.get("examples_per_sec", 0.0) for s in shards]
        out.append(
            f"shards: {len(shards)} finished, loader throughput "
            f"min/mean/max = {min(rates):.0f}/"
            f"{sum(rates) / len(rates):.0f}/{max(rates):.0f} ex/s"
        )
    streams = run.kind("stream")
    if streams:
        # per-stream mean across epochs, so one cold epoch doesn't
        # read as a straggling stream; zero-rate rows (a stream that
        # never finished a shard — preempted epoch) are excluded like
        # doctor._check_streams does, instead of exploding the ratio
        per: dict[int, list[float]] = {}
        stall = 0.0
        for s in streams:
            eps = float(s.get("examples_per_sec", 0.0))
            if eps > 0:
                per.setdefault(int(s.get("stream", 0)), []).append(eps)
            stall += float(s.get("stall_seconds", 0.0))
        if per:
            means = [sum(v) / len(v) for v in per.values()]
            lo, hi = min(means), max(means)
            out.append(
                f"input streams: {len(per)} (fan-out, io/fanout.py), "
                f"throughput min/mean/max = {lo:.0f}/"
                f"{sum(means) / len(means):.0f}/{hi:.0f} ex/s, "
                f"spread max/min = {hi / lo:.2f}x, "
                f"backpressure stall {stall:.1f}s total"
            )
    mem = run.kind("device_mem")
    if mem:
        last = mem[-1].get("devices") or []
        used = [
            d.get("bytes_in_use") for d in last
            if isinstance(d, dict) and d.get("bytes_in_use") is not None
        ]
        if used:
            out.append(
                f"device memory (last epoch): "
                f"{sum(used) / 2**20:.1f} MiB in use across "
                f"{len(used)} device(s)"
            )
    return "\n".join(out)


def summarize(path: str) -> str:
    rows = load_jsonl(path)
    runs = split_runs(rows)
    parts = [f"{path}: {len(rows)} rows, {len(runs)} run(s)"]
    errors = validate_rows(rows)
    if errors:
        parts.append(
            f"WARNING: {len(errors)} schema violation(s), first: {errors[0]}"
        )
    for i, run in enumerate(runs):
        parts.append("")
        parts.append(f"-- run {i + 1} of {len(runs)} --")
        parts.append(format_run(run))
    return "\n".join(parts)


def load_bench_result(path: str) -> dict | None:
    """The result row of a committed bench artifact (BENCH_r*.json:
    one JSON object whose ``parsed`` field holds the metric row), or
    None when the file isn't one — `compare` uses this to accept bench
    artifacts next to metrics JSONL."""
    import json

    try:
        with open(path) as f:
            doc = json.load(f)
    except ValueError:
        return None  # multi-line JSONL etc. — not a bench artifact
    if not isinstance(doc, dict):
        return None
    row = doc.get("parsed", doc)
    if isinstance(row, dict) and "value" in row and "metric" in row:
        return row
    return None


def throughput_of(path: str) -> tuple[float, str]:
    """(examples/sec, source label) for either file format: a bench
    artifact's parsed metric value, or the LAST run's throughput in a
    metrics JSONL file."""
    bench = load_bench_result(path)
    if bench is not None:
        label = bench.get("metric", "bench")
        if bench.get("degraded"):
            label += " [degraded]"
        return float(bench["value"]), str(label)
    run = _last_run(path)
    return run.throughput(), "examples/sec (last run)"


def check_regress(path_a: str, path_b: str, frac: float) -> str | None:
    """Regression verdict comparing B (candidate) against A (baseline):
    an error string when B's throughput fell more than ``frac`` below
    A's, else None.  ``frac`` is a fraction (0.05 = fail on a >5%
    drop)."""
    a, label_a = throughput_of(path_a)
    b, label_b = throughput_of(path_b)
    if a <= 0:
        return None  # no baseline signal — nothing to gate on
    drop = (a - b) / a
    if drop > frac:
        return (
            f"REGRESS: {path_b} ({label_b}) = {b:.0f} is "
            f"{100 * drop:.1f}% below {path_a} ({label_a}) = {a:.0f} "
            f"(--fail-on-regress {frac})"
        )
    return None


def _last_run(path: str) -> Run:
    runs = load_runs(path)
    if not runs:
        raise ValueError(f"{path}: no metrics rows to compare")
    return runs[-1]


def compare(path_a: str, path_b: str) -> str:
    """Side-by-side comparison of the LAST run in each file.  Bench
    artifacts (BENCH_r*.json) compare on their parsed metric row."""
    ba, bb = load_bench_result(path_a), load_bench_result(path_b)
    if ba is not None and bb is not None:
        widths = [34, 14, 14, 8]
        out = [
            f"A: {path_a}  ({ba.get('metric', '?')}"
            f"{' [degraded]' if ba.get('degraded') else ''})",
            f"B: {path_b}  ({bb.get('metric', '?')}"
            f"{' [degraded]' if bb.get('degraded') else ''})",
            "",
            _fmt_row(["metric", "A", "B", "delta"], widths),
        ]
        keys = [
            k for k in ba
            if isinstance(ba.get(k), (int, float))
            and isinstance(bb.get(k), (int, float))
            and not isinstance(ba[k], bool)
            and not isinstance(bb[k], bool)
        ]
        for k in keys:
            a, b = float(ba[k]), float(bb[k])
            d = f"{100.0 * (b - a) / a:+.1f}%" if a else "n/a"
            out.append(_fmt_row([k, f"{a:g}", f"{b:g}", d], widths))
        return "\n".join(out)
    ra = _last_run(path_a)
    rb = _last_run(path_b)
    out = [f"A: {path_a}  ({ra.label()})", f"B: {path_b}  ({rb.label()})", ""]

    def delta(a: float, b: float) -> str:
        if a == 0:
            return "n/a"
        return f"{100.0 * (b - a) / a:+.1f}%"

    widths = [22, 12, 12, 8]
    out.append(_fmt_row(["metric", "A", "B", "delta"], widths))
    tp_a, tp_b = ra.throughput(), rb.throughput()
    out.append(_fmt_row(
        ["examples/sec", f"{tp_a:.0f}", f"{tp_b:.0f}", delta(tp_a, tp_b)],
        widths,
    ))
    st_a, st_b = ra.stall_frac(), rb.stall_frac()
    out.append(_fmt_row(
        ["input_stall_frac", f"{st_a:.3f}", f"{st_b:.3f}",
         delta(st_a, st_b)],
        widths,
    ))
    wall_a, wall_b = ra.wall_seconds(), rb.wall_seconds()
    out.append(_fmt_row(
        ["wall seconds", f"{wall_a:.2f}", f"{wall_b:.2f}",
         delta(wall_a, wall_b)],
        widths,
    ))
    pa, _ = ra.phase_totals()
    pb, _ = rb.phase_totals()
    for name in sorted(set(pa) | set(pb)):
        a, b = pa.get(name, 0.0), pb.get(name, 0.0)
        out.append(_fmt_row(
            [f"phase.{name} (s)", f"{a:.3f}", f"{b:.3f}", delta(a, b)],
            widths,
        ))
    ll = [
        (r.evals[-1] if r.evals else None) for r in (ra, rb)
    ]
    if ll[0] and ll[1]:
        out.append(_fmt_row(
            ["eval auc", f"{ll[0].get('auc', 0.0):.6f}",
             f"{ll[1].get('auc', 0.0):.6f}",
             delta(ll[0].get("auc", 0.0), ll[1].get("auc", 0.0))],
            widths,
        ))
    return "\n".join(out)
